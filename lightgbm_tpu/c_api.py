"""LGBM_*-shaped stable API surface — handle-based, mirrors
include/LightGBM/c_api.h:37-719.

The reference's C API is the ABI every binding goes through; here the same
function names/shapes operate on an in-process handle registry so code (and
tests) written against the C API style — dataset from file/mat, push fields,
booster create/update/eval/predict, model save/load — ports over directly
(tests/c_api_test/test.py is the model).  Arguments that were raw C pointers
take numpy arrays.
"""
from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .utils.config import key_alias_transform
from .utils.log import LightGBMError

_handles: Dict[int, Any] = {}
_next_handle = itertools.count(1)


def _register(obj) -> int:
    h = next(_next_handle)
    _handles[h] = obj
    return h


def _get(handle: int):
    if handle not in _handles:
        raise LightGBMError("Invalid handle %s" % handle)
    obj = _handles[handle]
    if isinstance(obj, tuple) and len(obj) == 2 and obj[0] == "pushing":
        raise LightGBMError(
            "Dataset %s is still streaming: %d of %d declared rows pushed "
            "(LGBM_DatasetPushRows)" % (
                handle, sum(len(r) for r in obj[1]["rows"]),
                obj[1]["num_total_row"]))
    return obj


def _get_pushing(handle: int):
    if handle not in _handles:
        raise LightGBMError("Invalid handle %s" % handle)
    return _handles[handle]


def _csr_to_dense(indptr, indices, data, num_col: int) -> np.ndarray:
    """Small-chunk densify (streaming push-rows only; bulk creation goes
    through the sparse path, io/sparse.py)."""
    indptr = np.asarray(indptr, dtype=np.int64)
    n = len(indptr) - 1
    mat = np.zeros((n, num_col), dtype=np.float64)
    rows = np.repeat(np.arange(n), np.diff(indptr))
    mat[rows, np.asarray(indices, dtype=np.int64)] = \
        np.asarray(data, dtype=np.float64)
    return mat


def _csc_to_dense(colptr, indices, data, num_row: int) -> np.ndarray:
    colptr = np.asarray(colptr, dtype=np.int64)
    num_col = len(colptr) - 1
    mat = np.zeros((num_row, num_col), dtype=np.float64)
    cols = np.repeat(np.arange(num_col), np.diff(colptr))
    mat[np.asarray(indices, dtype=np.int64), cols] = \
        np.asarray(data, dtype=np.float64)
    return mat


def _parse_params(parameters: str) -> dict:
    out = {}
    for tok in (parameters or "").split():
        if "=" in tok:
            k, _, v = tok.partition("=")
            out[k] = v
    return out


# ---------------------------------------------------------------- datasets

def LGBM_DatasetCreateFromFile(filename: str, parameters: str = "",
                               reference: Optional[int] = None) -> int:
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(filename, params=params, reference=ref)
    ds.construct()
    return _register(ds)


def LGBM_DatasetCreateFromMat(data, parameters: str = "",
                              reference: Optional[int] = None,
                              label=None) -> int:
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(np.asarray(data, dtype=np.float64), label=label,
                 params=params, reference=ref, free_raw_data=False)
    ds.construct()
    return _register(ds)


def LGBM_DatasetCreateFromCSR(indptr, indices, data, num_col: int,
                              parameters: str = "",
                              reference: Optional[int] = None) -> int:
    """Sparse create stays sparse (sparse_bin.hpp:68 analog): rows are
    re-sorted to CSC and binned per column — no N x F densification."""
    from .io.sparse import csr_to_csc
    sp = csr_to_csc(indptr, indices, data, num_col)
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(sp, params=params, reference=ref, free_raw_data=False)
    ds.construct()
    return _register(ds)


def LGBM_DatasetCreateFromCSC(colptr, indices, data, num_row: int,
                              parameters: str = "",
                              reference: Optional[int] = None) -> int:
    from .io.sparse import csc_arrays
    sp = csc_arrays(colptr, indices, data, num_row)
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(sp, params=params, reference=ref, free_raw_data=False)
    ds.construct()
    return _register(ds)


def LGBM_DatasetSetField(handle: int, field_name: str, data) -> int:
    _get(handle).set_field(field_name, data)
    return 0


def LGBM_DatasetGetField(handle: int, field_name: str):
    return _get(handle).get_field(field_name)


def LGBM_DatasetGetNumData(handle: int) -> int:
    return _get(handle).num_data()


def LGBM_DatasetGetNumFeature(handle: int) -> int:
    return _get(handle).num_feature()


def LGBM_DatasetSaveBinary(handle: int, filename: str) -> int:
    _get(handle).save_binary(filename)
    return 0


def LGBM_DatasetFree(handle: int) -> int:
    _handles.pop(handle, None)
    # drop pinned GetField pointers for this dataset (C ABI bridge)
    for key in [k for k in _field_cache if k[0] == handle]:
        _field_cache.pop(key, None)
    return 0


# ---------------------------------------------------------------- boosters

def LGBM_BoosterCreate(train_data: int, parameters: str = "") -> int:
    params = _parse_params(parameters)
    bst = Booster(params=params, train_set=_get(train_data))
    return _register(bst)


def LGBM_BoosterCreateFromModelfile(filename: str) -> int:
    return _register(Booster(model_file=filename))


def LGBM_BoosterLoadModelFromString(model_str: str) -> int:
    return _register(Booster(model_str=model_str))


def LGBM_BoosterAddValidData(handle: int, valid_data: int) -> int:
    bst = _get(handle)
    bst.add_valid(_get(valid_data), "valid_%d" % len(bst.name_valid_sets))
    return 0


def LGBM_BoosterUpdateOneIter(handle: int) -> int:
    """Returns 1 when training should stop (c_api.cpp:149 semantics)."""
    return int(_get(handle).update())


def LGBM_BoosterUpdateOneIterCustom(handle: int, grad, hess) -> int:
    bst = _get(handle)
    return int(bst._gbdt.train_one_iter(np.asarray(grad, np.float32),
                                        np.asarray(hess, np.float32), False))


def LGBM_BoosterRollbackOneIter(handle: int) -> int:
    _get(handle).rollback_one_iter()
    return 0


def LGBM_BoosterGetCurrentIteration(handle: int) -> int:
    return _get(handle).current_iteration()


def LGBM_BoosterGetEval(handle: int, data_idx: int) -> List[float]:
    return _get(handle)._gbdt.get_eval_at(data_idx)


def LGBM_BoosterGetEvalNames(handle: int) -> List[str]:
    return _get(handle)._gbdt.eval_names(0)


def LGBM_BoosterGetNumClasses(handle: int) -> int:
    return _get(handle)._gbdt.num_class


def LGBM_BoosterPredictForMat(handle: int, data, predict_type: int = 0,
                              num_iteration: int = -1):
    """predict_type: 0 normal, 1 raw score, 2 leaf index (c_api.h).

    The serving entry: per-request latency and batch-size land in the
    metrics registry via Booster.predict (lightgbm_tpu/obs/metrics.py;
    the CSR/CSC variants route through here per dense chunk).  Scrape
    via LGBM_MetricsScrape.
    """
    bst = _get(handle)
    return bst.predict(np.asarray(data, dtype=np.float64),
                       num_iteration=num_iteration,
                       raw_score=predict_type == 1,
                       pred_leaf=predict_type == 2)


def LGBM_MetricsScrape(fmt: str = "prometheus") -> str:
    """Process-global metrics registry export: 'prometheus' textfile
    format or 'json'.  Not part of the reference C API — the hook a
    serving wrapper exposes on its /metrics endpoint."""
    from .obs.metrics import REGISTRY
    if fmt == "prometheus":
        return REGISTRY.to_prometheus()
    if fmt == "json":
        return REGISTRY.to_json()
    raise LightGBMError("LGBM_MetricsScrape: unknown format %r "
                        "(expected prometheus/json)" % (fmt,))


def LGBM_BoosterPredictForFile(handle: int, data_filename: str,
                               data_has_header: bool, result_filename: str,
                               predict_type: int = 0,
                               num_iteration: int = -1) -> int:
    bst = _get(handle)
    out = bst.predict(data_filename, data_has_header=data_has_header,
                      num_iteration=num_iteration,
                      raw_score=predict_type == 1,
                      pred_leaf=predict_type == 2)
    out = np.asarray(out)
    with open(result_filename, "w") as f:
        if out.ndim == 1:
            for v in out:
                f.write("%.9g\n" % v)
        else:
            for row in out:
                f.write("\t".join("%.9g" % v for v in row) + "\n")
    return 0


def LGBM_BoosterSaveModel(handle: int, num_iteration: int, filename: str) -> int:
    _get(handle).save_model(filename, num_iteration=num_iteration)
    return 0


def LGBM_BoosterSaveModelToString(handle: int, num_iteration: int = -1) -> str:
    return _get(handle).model_to_string(num_iteration=num_iteration)


def LGBM_BoosterDumpModel(handle: int, num_iteration: int = -1) -> str:
    import json
    return json.dumps(_get(handle).dump_model(num_iteration=num_iteration))


def LGBM_BoosterGetLeafValue(handle: int, tree_idx: int, leaf_idx: int) -> float:
    gbdt = _get(handle)._gbdt
    gbdt._materialize()
    return float(gbdt.models[tree_idx].leaf_value[leaf_idx])


def LGBM_BoosterSetLeafValue(handle: int, tree_idx: int, leaf_idx: int,
                             val: float) -> int:
    gbdt = _get(handle)._gbdt
    gbdt._materialize()
    gbdt.models[tree_idx].set_leaf_value(leaf_idx, val)
    return 0


def LGBM_BoosterFeatureImportance(handle: int, num_iteration: int = -1):
    return _get(handle)._gbdt.feature_importance()


def LGBM_BoosterFree(handle: int) -> int:
    _handles.pop(handle, None)
    return 0


# ------------------------------------------------- error handling (c_api.h)

_last_error: List[str] = [""]


def LGBM_SetLastError(msg: str) -> None:
    _last_error[0] = str(msg)


def LGBM_GetLastError() -> str:
    return _last_error[0]


def LGBM_APIHandleException(ex) -> int:
    """Reference macro API_END catch-all (c_api.cpp): record and return -1."""
    LGBM_SetLastError(str(ex))
    return -1


# --------------------------------------------- remaining dataset functions

def LGBM_DatasetCreateByReference(reference: int, num_total_row: int) -> int:
    """Empty dataset aligned to a reference for row streaming
    (c_api.h LGBM_DatasetCreateByReference + PushRows protocol)."""
    ds = {"reference": _get(reference), "num_total_row": int(num_total_row),
          "rows": []}     # list of (start_row, chunk) — any push order
    return _register(("pushing", ds))


def LGBM_DatasetPushRows(handle: int, data, start_row: int = -1) -> int:
    """Chunks may arrive in any order (multi-threaded producers push with
    explicit start_row, c_api.h:120-140); start_row=-1 appends after the
    last pushed row."""
    obj = _get_pushing(handle)
    if not (isinstance(obj, tuple) and obj[0] == "pushing"):
        raise LightGBMError("Dataset was not created for row pushing")
    _, ds = obj
    chunk = np.asarray(data, dtype=np.float64)
    if start_row is None or start_row < 0:
        start_row = sum(len(c) for _, c in ds["rows"])
    start_row = int(start_row)
    n_total = ds["num_total_row"]
    if start_row + len(chunk) > n_total:
        raise LightGBMError(
            "PushRows chunk [%d, %d) exceeds num_total_row=%d"
            % (start_row, start_row + len(chunk), n_total))
    # finalize only when every row is covered exactly once — a duplicate
    # start_row must not trigger premature finalization with zero-filled
    # holes, nor silently overwrite previously pushed rows
    covered = ds.setdefault("covered", np.zeros(n_total, dtype=bool))
    if covered[start_row:start_row + len(chunk)].any():
        raise LightGBMError(
            "PushRows chunk [%d, %d) overlaps previously pushed rows"
            % (start_row, start_row + len(chunk)))
    covered[start_row:start_row + len(chunk)] = True
    ds["rows"].append((start_row, chunk))
    if covered.all():
        _finish_push(handle, ds)
    return 0


def LGBM_DatasetPushRowsByCSR(handle: int, indptr, indices, data,
                              num_col: int, start_row: int = -1) -> int:
    return LGBM_DatasetPushRows(handle,
                                _csr_to_dense(indptr, indices, data, num_col),
                                start_row)


def _finish_push(handle: int, ds: dict) -> None:
    n = ds["num_total_row"]
    f = ds["rows"][0][1].shape[1]
    mat = np.zeros((n, f), dtype=np.float64)
    for start, chunk in ds["rows"]:
        end = min(start + len(chunk), n)
        mat[start:end] = chunk[:end - start]
    out = Dataset(mat, reference=ds["reference"], free_raw_data=False)
    out.construct()
    _handles[handle] = out


def LGBM_DatasetCreateFromSampledColumn(sample_data, sample_indices,
                                        num_col: int, num_per_col,
                                        num_sample_row: int,
                                        num_total_row: int,
                                        parameters: str = "") -> int:
    """Sampled-column creation (c_api.h:78-101): bin mappers from column
    samples, rows streamed afterwards via LGBM_DatasetPushRows."""
    mat = np.zeros((num_sample_row, num_col), dtype=np.float64)
    for c in range(num_col):
        vals = np.asarray(sample_data[c], dtype=np.float64)
        idx = np.asarray(sample_indices[c], dtype=np.int64)
        mat[idx[:len(vals)], c] = vals
    params = _parse_params(parameters)
    ref = Dataset(mat, params=params, free_raw_data=False)
    ref.construct()
    ds = {"reference": ref, "num_total_row": int(num_total_row), "rows": []}
    return _register(("pushing", ds))


def LGBM_DatasetGetSubset(handle: int, used_row_indices,
                          parameters: str = "") -> int:
    sub = _get(handle).subset(np.asarray(used_row_indices, dtype=np.int64),
                              params=_parse_params(parameters))
    sub.construct()
    return _register(sub)


def LGBM_DatasetSetFeatureNames(handle: int, feature_names: List[str]) -> int:
    ds = _get(handle)
    ds.set_feature_name(list(feature_names))
    return 0


def LGBM_DatasetGetFeatureNames(handle: int) -> List[str]:
    ds = _get(handle)
    ds.construct()
    return list(ds._handle.feature_names)


# --------------------------------------------- remaining booster functions

def LGBM_BoosterMerge(handle: int, other_handle: int) -> int:
    """Merge other's trees into handle (c_api.cpp Booster::MergeFrom)."""
    a = _get(handle)._gbdt
    b = _get(other_handle)._gbdt
    a._materialize()
    b._materialize()
    a.merge_from(b)
    return 0


def LGBM_BoosterResetParameter(handle: int, parameters: str) -> int:
    bst = _get(handle)
    bst.reset_parameter(key_alias_transform(_parse_params(parameters)))
    return 0


def LGBM_BoosterResetTrainingData(handle: int, train_data: int) -> int:
    bst = _get(handle)
    bst.set_train_data(_get(train_data))
    return 0


def LGBM_BoosterGetNumFeature(handle: int) -> int:
    return int(_get(handle)._gbdt.max_feature_idx + 1)


def LGBM_BoosterGetEvalCounts(handle: int) -> int:
    return len(LGBM_BoosterGetEvalNames(handle))


def LGBM_BoosterCalcNumPredict(handle: int, num_row: int,
                               predict_type: int = 0,
                               num_iteration: int = -1) -> int:
    gbdt = _get(handle)._gbdt
    k = gbdt.num_tree_per_iteration
    if predict_type == 2:    # leaf index: one per tree
        total = len(gbdt.models) // max(k, 1)
        n_iter = min(num_iteration, total) if num_iteration > 0 else total
        return num_row * k * n_iter
    return num_row * k


def LGBM_BoosterGetNumPredict(handle: int, data_idx: int) -> int:
    gbdt = _get(handle)._gbdt
    if data_idx == 0:
        n = gbdt.num_data
    else:
        n = gbdt.valid_data[data_idx - 1].num_data
    return n * gbdt.num_tree_per_iteration


def LGBM_BoosterGetPredict(handle: int, data_idx: int):
    """Raw scores of train (0) or valid set (1..) — c_api GetPredict."""
    gbdt = _get(handle)._gbdt
    if data_idx == 0:
        return np.asarray(gbdt.train_score).reshape(-1).copy()
    return np.asarray(gbdt.valid_score_host(data_idx - 1)).reshape(-1).copy()


def LGBM_BoosterGetFeatureNames(handle: int) -> List[str]:
    return list(_get(handle).feature_name())


def LGBM_BoosterPredictForCSR(handle: int, indptr, indices, data,
                              num_col: int, predict_type: int = 0,
                              num_iteration: int = -1):
    from .io.sparse import csr_to_csc, iter_dense_row_chunks
    sp = csr_to_csc(indptr, indices, data, num_col)
    outs = [LGBM_BoosterPredictForMat(handle, block, predict_type,
                                      num_iteration)
            for _, block in iter_dense_row_chunks(sp)]
    return np.concatenate(outs) if outs else np.zeros(0, dtype=np.float64)


def LGBM_BoosterPredictForCSC(handle: int, colptr, indices, data,
                              num_row: int, predict_type: int = 0,
                              num_iteration: int = -1):
    from .io.sparse import csc_arrays, iter_dense_row_chunks
    sp = csc_arrays(colptr, indices, data, num_row)
    outs = [LGBM_BoosterPredictForMat(handle, block, predict_type,
                                      num_iteration)
            for _, block in iter_dense_row_chunks(sp)]
    return np.concatenate(outs) if outs else np.zeros(0, dtype=np.float64)


# ------------------------------------------------------------- C ABI bridge
# Buffer-based adapters for the native shared library
# (cpp/src/capi_bridge.cpp).  The .so embeds CPython and forwards each
# exported LGBM_* symbol here, passing raw caller memory as memoryviews —
# these shims give them numpy form with the C_API_DTYPE_* codes of
# include/LightGBM/c_api.h:16-22.

_DTYPE_BY_CODE = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}


def _np_from_buffer(mv, count, dtype_code, copy=True):
    # COPY by default: the caller's C buffer is only valid for the
    # duration of the call, but datasets/metadata retain arrays
    # (free_raw_data=False, Metadata.set_label) — a view would dangle
    # after the C side frees it.  Pure prediction paths pass copy=False
    # (nothing retains the matrix past the synchronous call).
    arr = np.frombuffer(mv, dtype=_DTYPE_BY_CODE[int(dtype_code)],
                        count=int(count))
    return arr.copy() if copy else arr


def _abi_dataset_from_file(filename, parameters, ref_handle):
    return LGBM_DatasetCreateFromFile(filename, parameters,
                                      ref_handle or None)


def _abi_dataset_from_mat(mv, nrow, ncol, dtype_code, is_row_major,
                          parameters, ref_handle):
    mat = _np_from_buffer(mv, nrow * ncol, dtype_code)
    mat = (mat.reshape(nrow, ncol) if is_row_major
           else mat.reshape(ncol, nrow).T)
    return LGBM_DatasetCreateFromMat(mat, parameters, ref_handle or None)


def _abi_dataset_from_csr(mv_indptr, n_indptr, indptr_code, mv_indices,
                          mv_data, nnz, data_code, num_col, parameters,
                          ref_handle):
    indptr = _np_from_buffer(mv_indptr, n_indptr, indptr_code)
    indices = _np_from_buffer(mv_indices, nnz, 2)
    data = _np_from_buffer(mv_data, nnz, data_code)
    return LGBM_DatasetCreateFromCSR(indptr, indices, data, num_col,
                                     parameters, ref_handle or None)


def _abi_dataset_from_csc(mv_colptr, n_colptr, colptr_code, mv_indices,
                          mv_data, nnz, data_code, num_row, parameters,
                          ref_handle):
    colptr = _np_from_buffer(mv_colptr, n_colptr, colptr_code)
    indices = _np_from_buffer(mv_indices, nnz, 2)
    data = _np_from_buffer(mv_data, nnz, data_code)
    return LGBM_DatasetCreateFromCSC(colptr, indices, data, num_row,
                                     parameters, ref_handle or None)


def _abi_dataset_set_field(handle, field_name, mv, count, dtype_code):
    return LGBM_DatasetSetField(handle, field_name,
                                _np_from_buffer(mv, count, dtype_code))


def _abi_booster_get_eval(handle, data_idx):
    return np.asarray(LGBM_BoosterGetEval(handle, data_idx),
                      dtype=np.float64)


def _abi_booster_predict_mat(handle, mv, nrow, ncol, dtype_code,
                             is_row_major, predict_type, num_iteration):
    mat = _np_from_buffer(mv, nrow * ncol, dtype_code, copy=False)
    mat = (mat.reshape(nrow, ncol) if is_row_major
           else mat.reshape(ncol, nrow).T)
    out = LGBM_BoosterPredictForMat(handle, mat, predict_type,
                                    num_iteration)
    return np.ascontiguousarray(np.asarray(out, dtype=np.float64)
                                .reshape(-1))


def _abi_booster_predict_csr(handle, mv_indptr, n_indptr, indptr_code,
                             mv_indices, mv_data, nnz, data_code, num_col,
                             predict_type, num_iteration):
    indptr = _np_from_buffer(mv_indptr, n_indptr, indptr_code, copy=False)
    indices = _np_from_buffer(mv_indices, nnz, 2, copy=False)
    data = _np_from_buffer(mv_data, nnz, data_code, copy=False)
    out = LGBM_BoosterPredictForCSR(handle, indptr, indices, data, num_col,
                                    predict_type, num_iteration)
    return np.ascontiguousarray(np.asarray(out, dtype=np.float64)
                                .reshape(-1))


def _abi_booster_save_model_string(handle, num_iteration):
    return LGBM_BoosterSaveModelToString(handle, num_iteration)


def _abi_booster_dump_model(handle, num_iteration):
    return LGBM_BoosterDumpModel(handle, num_iteration)


def _abi_dataset_push_rows(handle, mv, nrow, ncol, dtype_code, start_row):
    mat = _np_from_buffer(mv, nrow * ncol, dtype_code).reshape(nrow, ncol)
    return LGBM_DatasetPushRows(handle, mat, start_row)


def _abi_dataset_push_rows_csr(handle, mv_indptr, n_indptr, indptr_code,
                               mv_indices, mv_data, nnz, data_code,
                               num_col, start_row):
    indptr = _np_from_buffer(mv_indptr, n_indptr, indptr_code)
    indices = _np_from_buffer(mv_indices, nnz, 2)
    data = _np_from_buffer(mv_data, nnz, data_code)
    return LGBM_DatasetPushRowsByCSR(handle, indptr, indices, data,
                                     num_col, start_row)


def _abi_dataset_from_sampled(cols, idxs, num_col, num_sample_row,
                              num_total_row, parameters):
    """cols/idxs: per-column memoryviews (f64 values / i32 row indices),
    sized by the C caller from num_per_col."""
    sd = [np.frombuffer(c, dtype=np.float64).copy() for c in cols]
    si = [np.frombuffer(i, dtype=np.int32).copy() for i in idxs]
    return LGBM_DatasetCreateFromSampledColumn(
        sd, si, num_col, [len(x) for x in sd], num_sample_row,
        num_total_row, parameters)


def _abi_dataset_get_subset(handle, mv_indices, count, parameters):
    idx = _np_from_buffer(mv_indices, count, 2)
    return LGBM_DatasetGetSubset(handle, idx, parameters)


# GetField hands out INTERNAL pointers (c_api.h:286-290 semantics); the
# arrays are pinned here so the address outlives the call — freed with the
# dataset (LGBM_DatasetFree clears the registry entry the cache keys on).
_field_cache: dict = {}
_FIELD_CODE = {np.dtype(np.float32): 0, np.dtype(np.float64): 1,
               np.dtype(np.int32): 2, np.dtype(np.int64): 3}


def _abi_dataset_get_field(handle, field_name):
    """-> (addr, length, dtype_code); addr valid until the next GetField
    of the same field or DatasetFree.  Missing fields are an ERROR, as in
    the reference (success never yields a NULL pointer)."""
    arr = LGBM_DatasetGetField(handle, field_name)
    if arr is None:
        raise LightGBMError("Field %s not found" % field_name)
    arr = np.ascontiguousarray(arr)
    if arr.dtype not in _FIELD_CODE:
        arr = np.ascontiguousarray(arr, dtype=np.float64)
    _field_cache[(handle, field_name)] = arr
    return (int(arr.ctypes.data), int(arr.size), _FIELD_CODE[arr.dtype])


def _abi_booster_train_size(handle):
    """grad/hess element count for UpdateOneIterCustom."""
    gbdt = _get(handle)._gbdt
    return int(gbdt.num_data * max(gbdt.num_tree_per_iteration, 1))


def _abi_booster_update_custom(handle, mv_grad, mv_hess, n):
    grad = _np_from_buffer(mv_grad, n, 0, copy=False)
    hess = _np_from_buffer(mv_hess, n, 0, copy=False)
    return LGBM_BoosterUpdateOneIterCustom(handle, grad, hess)


def _abi_booster_get_predict(handle, data_idx):
    return np.asarray(LGBM_BoosterGetPredict(handle, data_idx),
                      dtype=np.float64)


def _abi_booster_predict_csc(handle, mv_colptr, n_colptr, colptr_code,
                             mv_indices, mv_data, nnz, data_code, num_row,
                             predict_type, num_iteration):
    colptr = _np_from_buffer(mv_colptr, n_colptr, colptr_code, copy=False)
    indices = _np_from_buffer(mv_indices, nnz, 2, copy=False)
    data = _np_from_buffer(mv_data, nnz, data_code, copy=False)
    out = LGBM_BoosterPredictForCSC(handle, colptr, indices, data, num_row,
                                    predict_type, num_iteration)
    return np.ascontiguousarray(np.asarray(out, dtype=np.float64)
                                .reshape(-1))
