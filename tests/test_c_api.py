"""C-API-surface smoke test — mirrors tests/c_api_test/test.py flow."""
import numpy as np
import pytest

from lightgbm_tpu import c_api


def make_data(seed=0, n=600, f=6):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
    return X, y


def test_dataset_roundtrip(tmp_path):
    X, y = make_data()
    h = c_api.LGBM_DatasetCreateFromMat(X, "max_bin=63", label=y)
    assert c_api.LGBM_DatasetGetNumData(h) == 600
    assert c_api.LGBM_DatasetGetNumFeature(h) == 6
    c_api.LGBM_DatasetSetField(h, "weight", np.ones(600))
    w = c_api.LGBM_DatasetGetField(h, "weight")
    assert len(w) == 600
    path = str(tmp_path / "ds.bin.npz")
    c_api.LGBM_DatasetSaveBinary(h, path)
    h2 = c_api.LGBM_DatasetCreateFromFile(path)
    assert c_api.LGBM_DatasetGetNumData(h2) == 600
    c_api.LGBM_DatasetFree(h)
    c_api.LGBM_DatasetFree(h2)


def test_csr_csc():
    X, y = make_data(n=100)
    from scipy import sparse as sp
    csr = sp.csr_matrix(X)
    h = c_api.LGBM_DatasetCreateFromCSR(csr.indptr, csr.indices, csr.data,
                                        X.shape[1])
    assert c_api.LGBM_DatasetGetNumData(h) == 100
    csc = sp.csc_matrix(X)
    h2 = c_api.LGBM_DatasetCreateFromCSC(csc.indptr, csc.indices, csc.data,
                                         X.shape[0])
    assert c_api.LGBM_DatasetGetNumData(h2) == 100


def test_booster_train_eval_predict(tmp_path):
    X, y = make_data()
    Xv, yv = make_data(seed=1)
    train = c_api.LGBM_DatasetCreateFromMat(
        X, "objective=binary metric=binary_logloss verbose=-1", label=y)
    valid = c_api.LGBM_DatasetCreateFromMat(
        Xv, "objective=binary verbose=-1", label=yv,
        reference=train)
    bst = c_api.LGBM_BoosterCreate(
        train, "objective=binary metric=binary_logloss verbose=-1")
    c_api.LGBM_BoosterAddValidData(bst, valid)
    for i in range(20):
        stop = c_api.LGBM_BoosterUpdateOneIter(bst)
        assert stop == 0
    assert c_api.LGBM_BoosterGetCurrentIteration(bst) == 20
    ev = c_api.LGBM_BoosterGetEval(bst, 1)
    assert len(ev) == 1 and ev[0] < 0.4
    pred = c_api.LGBM_BoosterPredictForMat(bst, Xv)
    assert ((pred > 0.5) == (yv > 0)).mean() > 0.9
    # model save/load parity
    path = str(tmp_path / "model.txt")
    c_api.LGBM_BoosterSaveModel(bst, -1, path)
    bst2 = c_api.LGBM_BoosterCreateFromModelfile(path)
    pred2 = c_api.LGBM_BoosterPredictForMat(bst2, Xv)
    np.testing.assert_allclose(pred, pred2, rtol=1e-14)
    # leaf get/set
    v = c_api.LGBM_BoosterGetLeafValue(bst, 1, 0)
    c_api.LGBM_BoosterSetLeafValue(bst, 1, 0, v * 2)
    assert c_api.LGBM_BoosterGetLeafValue(bst, 1, 0) == pytest.approx(v * 2)


def test_custom_update():
    X, y = make_data()
    train = c_api.LGBM_DatasetCreateFromMat(X, "verbose=-1", label=y)
    bst = c_api.LGBM_BoosterCreate(train, "objective=none verbose=-1 num_leaves=15")
    p = np.zeros(len(y))
    for _ in range(10):
        prob = 1.0 / (1.0 + np.exp(-p))
        c_api.LGBM_BoosterUpdateOneIterCustom(bst, prob - y, prob * (1 - prob))
        p = c_api.LGBM_BoosterPredictForMat(bst, X, predict_type=1)
    acc = ((p > 0) == (y > 0)).mean()
    assert acc > 0.9


def test_push_rows_and_sampled_column():
    """Streaming dataset creation (LGBM_DatasetCreateFromSampledColumn +
    PushRows, c_api.h:78-140)."""
    X, y = make_data(seed=1)
    n, f = X.shape
    sample_idx = np.arange(0, n, 3)
    sample_data = [X[sample_idx, c] for c in range(f)]
    sample_indices = [np.arange(len(sample_idx)) for _ in range(f)]
    h = c_api.LGBM_DatasetCreateFromSampledColumn(
        sample_data, sample_indices, f, [len(sample_idx)] * f,
        len(sample_idx), n, "max_bin=63 verbose=-1")
    for start in range(0, n, 200):
        c_api.LGBM_DatasetPushRows(h, X[start:start + 200], start)
    assert c_api.LGBM_DatasetGetNumData(h) == n
    c_api.LGBM_DatasetSetField(h, "label", y)
    bst = c_api.LGBM_BoosterCreate(h, "objective=binary verbose=-1 num_leaves=15")
    for _ in range(10):
        c_api.LGBM_BoosterUpdateOneIter(bst)
    pred = c_api.LGBM_BoosterPredictForMat(bst, X)
    assert ((pred > 0.5) == (y > 0)).mean() > 0.9


def test_subset_feature_names_and_error():
    X, y = make_data(seed=2)
    h = c_api.LGBM_DatasetCreateFromMat(X, "verbose=-1", label=y)
    c_api.LGBM_DatasetSetFeatureNames(h, ["f%d" % i for i in range(6)])
    assert c_api.LGBM_DatasetGetFeatureNames(h)[0] == "f0"
    sub = c_api.LGBM_DatasetGetSubset(h, np.arange(100))
    assert c_api.LGBM_DatasetGetNumData(sub) == 100
    c_api.LGBM_SetLastError("boom")
    assert c_api.LGBM_GetLastError() == "boom"
    assert c_api.LGBM_APIHandleException(ValueError("x")) == -1
    assert c_api.LGBM_GetLastError() == "x"


def test_booster_aux_functions():
    X, y = make_data(seed=3)
    train = c_api.LGBM_DatasetCreateFromMat(X, "verbose=-1", label=y)
    bst = c_api.LGBM_BoosterCreate(
        train, "objective=binary verbose=-1 num_leaves=15 metric=auc")
    for _ in range(5):
        c_api.LGBM_BoosterUpdateOneIter(bst)
    assert c_api.LGBM_BoosterGetNumFeature(bst) == 6
    assert c_api.LGBM_BoosterGetEvalCounts(bst) == 1
    assert c_api.LGBM_BoosterGetNumPredict(bst, 0) == len(y)
    raw = c_api.LGBM_BoosterGetPredict(bst, 0)
    assert raw.shape == (len(y),)
    names = c_api.LGBM_BoosterGetFeatureNames(bst)
    assert len(names) == 6
    assert c_api.LGBM_BoosterCalcNumPredict(bst, 10, 0) == 10
    assert c_api.LGBM_BoosterCalcNumPredict(bst, 10, 2) == 50
    # CSR predict
    indptr, indices, data = [0], [], []
    for r in range(20):
        for ci in range(6):
            if X[r, ci] != 0:
                indices.append(ci); data.append(X[r, ci])
        indptr.append(len(indices))
    p_csr = c_api.LGBM_BoosterPredictForCSR(bst, indptr, indices, data, 6)
    p_mat = c_api.LGBM_BoosterPredictForMat(bst, X[:20])
    np.testing.assert_allclose(p_csr, p_mat, rtol=1e-12)
    # reset_parameter takes effect on shrinkage
    c_api.LGBM_BoosterResetParameter(bst, "learning_rate=0.5")
    # merge two boosters
    bst2 = c_api.LGBM_BoosterCreate(train, "objective=binary verbose=-1 num_leaves=7")
    c_api.LGBM_BoosterUpdateOneIter(bst2)
    n_before = c_api.LGBM_BoosterGetCurrentIteration(bst)
    c_api.LGBM_BoosterMerge(bst, bst2)
    assert c_api.LGBM_BoosterGetCurrentIteration(bst) == n_before + 1
