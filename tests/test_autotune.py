"""Measured kernel autotuner (ops/autotune.py).

* off-mode parity: with tpu_autotune=off (the CPU-CI default) the
  selected cells are bit-identical to the legacy hand-tuned heuristics
  across the benchmark shape buckets — the tuner must be a pure
  superset of today's behaviour.
* measured selection is deterministic under the injectable bench/timer
  hooks (the SloEngine fake-clock pattern), round-trips through the
  on-disk cache (a warm cache performs ZERO probe waves), and a cache
  schema-rev bump invalidates every old entry.
* off-TPU measure mode is a documented no-op falling back to the prior.
* the former 18-30 MB band prior is GONE: its root cause (the wave
  kernels' row-tile planner ignoring the VMEM-resident accumulator
  block) is fixed in ops/pallas_wave.py::_tile_plan, and the
  `tile_plan_vmem_report` regressions here pin the planner to the
  measured cells the band used to bend — including the yahoo W64
  misfire the (18,30) bounds could never encode.
* a cache file written at another CACHE_SCHEMA_REV is dropped whole:
  `load_cache` returns an empty cache, the next measure run re-probes,
  and the rewritten file carries the current rev (no stale-rev entry
  can be re-merged by `store_cache`).
"""
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops import autotune
from lightgbm_tpu.ops.autotune import (Cell, Pins, ShapeBucket, decide,
                                       enumerate_cells, measure_cells,
                                       prior_hist_mode, resolve_wave_order,
                                       resolve_wave_width, row_bucket)
from lightgbm_tpu.utils.config import Config


@pytest.fixture(autouse=True)
def _clean_hooks():
    autotune.clear_probe_hooks()
    yield
    autotune.clear_probe_hooks()


def _cfg(num_leaves, **kw):
    kw.setdefault("verbose", -1)
    kw["num_leaves"] = num_leaves
    return Config(kw)


# --------------------------------------------------------------- off parity

# the benchmark shape buckets (tools/BENCH_SUITE.md) and the cells the
# legacy inline heuristics picked for them on TPU; tpu_autotune=off must
# reproduce these exactly (ncols, bin_pad, num_leaves, mode, width).
# The widths are the RAW ladder values: the band-escape bend that used
# to push epsilon to 32 and bosch to 64 is gone — its root cause lives
# in the tile planner now (test_tile_plan_* below), so the band shapes
# run their natural widths.
LEGACY_TABLE = [
    ("flagship", 28, 256, 255, "pallas_t", 32),   # narrow-F
    ("epsilon", 2000, 64, 63, "pallas_t", 16),    # ex-band: W16 stays 16
    ("msltr", 136, 256, 255, "pallas_t", 32),     # 13.4MB block
    ("expo_cat", 40, 64, 31, "pallas_ct", 8),     # 40*64=2560: ct bound
    ("bosch", 968, 64, 255, "pallas_t", 32),      # ex-band: W32 stays 32
    ("bosch_widepad", 968, 256, 255, "onehot", None),  # 95MB > VMEM gate
]


@pytest.mark.parametrize("name,ncols,bin_pad,leaves,mode,width",
                         LEGACY_TABLE)
def test_off_mode_matches_legacy_heuristics(name, ncols, bin_pad, leaves,
                                            mode, width):
    cfg = _cfg(leaves)
    got_mode = prior_hist_mode(cfg, ncols, bin_pad, leaves, None,
                               on_tpu=True)
    assert got_mode == mode, name
    if width is not None:
        w = resolve_wave_width(cfg, leaves, resolve_wave_order(cfg))
        assert w == width, name


def test_off_mode_decide_is_identity():
    """tpu_autotune=off returns the prior cell untouched — no cache
    read, no probes — while still recording the decision."""
    prior = Cell("pallas_t", 32, True, False)
    d = decide(_cfg(255), ShapeBucket(28, 256, 255, 1 << 20), prior,
               Pins(), eligible=True)
    assert d.cell == prior and d.source == "off" and not d.probes
    evs = [ev for ev, _ in d.events]
    assert evs == ["autotune_decision"]


def test_ineligible_decide_keeps_prior(tmp_path):
    cfg = _cfg(31, tpu_autotune="measure",
               tpu_autotune_cache=str(tmp_path / "c.json"))
    prior = Cell("onehot", 1, True, False)
    d = decide(cfg, ShapeBucket(28, 256, 31, 4096), prior, Pins(),
               eligible=False)
    assert d.cell == prior and d.source == "ineligible" and not d.probes


# ----------------------------------------------------------- measured path

def _bench(cell, bucket):
    """Deterministic synthetic cost: wider faster, bf16 beats hilo, ct
    pays a tax, compaction a small win."""
    s = 1.0 / max(1, cell.wave_width)
    if cell.hist_hilo:
        s += 0.1
    if cell.hist_mode == "pallas_ct":
        s += 0.5
    if cell.compact:
        s -= 0.01
    return s


def test_measure_mode_deterministic_winner(tmp_path):
    autotune.install_probe_hooks(bench=_bench)
    cfg = _cfg(15, tpu_autotune="measure",
               tpu_autotune_cache=str(tmp_path / "c.json"))
    prior = Cell("pallas_t", 8, True, False)
    d = decide(cfg, ShapeBucket(8, 64, 15, 2048), prior, Pins(),
               eligible=True)
    assert d.source == "measured" and not d.cache_hit
    # bf16 at the prior width wins under the synthetic costs (which are
    # fused-agnostic, so the fused arm ties the prior and loses the tie)
    assert d.cell == Cell("pallas_t", 8, False, False)
    assert len(d.probes) == 6 and d.margin > 0 and d.overhead_s > 0
    probe_evs = [f for ev, f in d.events if ev == "autotune_probe"]
    assert len(probe_evs) == 6
    assert all(f["s_per_wave"] == _bench(Cell.from_dict(f["cell"]), None)
               for f in probe_evs)


def test_cache_round_trip_skips_probing(tmp_path):
    autotune.install_probe_hooks(bench=_bench)
    cache = str(tmp_path / "c.json")
    cfg = _cfg(15, tpu_autotune="measure", tpu_autotune_cache=cache)
    prior = Cell("pallas_t", 8, True, False)
    bucket = ShapeBucket(8, 64, 15, 2048)
    d1 = decide(cfg, bucket, prior, Pins(), eligible=True)
    assert d1.source == "measured"
    with open(cache) as f:
        blob = json.load(f)
    assert blob["version"] == autotune.CACHE_SCHEMA_REV
    assert autotune.cache_key(autotune._device_kind(), bucket) \
        in blob["entries"]
    # warm cache: zero probe waves, same winner
    d2 = decide(cfg, bucket, prior, Pins(), eligible=True)
    assert d2.source == "cache" and d2.cache_hit and not d2.probes
    assert d2.cell == d1.cell
    assert [ev for ev, _ in d2.events] == ["autotune_decision"]
    # a different bucket is a different key -> probes again
    d3 = decide(cfg, ShapeBucket(8, 64, 15, 4096), prior, Pins(),
                eligible=True)
    assert d3.source == "measured"


def test_cache_invalidated_by_schema_rev_bump(tmp_path, monkeypatch):
    autotune.install_probe_hooks(bench=_bench)
    cfg = _cfg(15, tpu_autotune="measure",
               tpu_autotune_cache=str(tmp_path / "c.json"))
    prior = Cell("pallas_t", 8, True, False)
    bucket = ShapeBucket(8, 64, 15, 2048)
    assert decide(cfg, bucket, prior, Pins(),
                  eligible=True).source == "measured"
    assert decide(cfg, bucket, prior, Pins(),
                  eligible=True).source == "cache"
    monkeypatch.setattr(autotune, "CACHE_SCHEMA_REV",
                        autotune.CACHE_SCHEMA_REV + 1)
    d = decide(cfg, bucket, prior, Pins(), eligible=True)
    assert d.source == "measured" and not d.cache_hit


def test_cached_winner_respects_pins(tmp_path):
    """A cache entry tuned without pins must not override a pinned
    dimension on reuse."""
    autotune.install_probe_hooks(bench=_bench)
    cfg = _cfg(15, tpu_autotune="measure",
               tpu_autotune_cache=str(tmp_path / "c.json"))
    bucket = ShapeBucket(8, 64, 15, 2048)
    d1 = decide(cfg, bucket, Cell("pallas_t", 8, True, False), Pins(),
                eligible=True)
    assert d1.cell.wave_width == 8  # cached winner: W=8 bf16
    # now the same bucket with width pinned at 4: the cached cell's
    # width must be replaced by the prior's
    prior = Cell("pallas_t", 4, True, False)
    d2 = decide(cfg, bucket, prior, Pins(width=True), eligible=True)
    assert d2.source == "cache" and d2.cell.wave_width == 4


def test_corrupt_cache_is_empty_cache(tmp_path):
    autotune.install_probe_hooks(bench=_bench)
    cache = tmp_path / "c.json"
    cache.write_text("{not json")
    cfg = _cfg(15, tpu_autotune="measure", tpu_autotune_cache=str(cache))
    d = decide(cfg, ShapeBucket(8, 64, 15, 2048),
               Cell("pallas_t", 8, True, False), Pins(), eligible=True)
    assert d.source == "measured"   # re-probed, did not raise


def test_stale_rev_cache_file_dropped_whole(tmp_path):
    """Satellite regression (v11): a cache file written at an older
    CACHE_SCHEMA_REV is an EMPTY cache — `load_cache` must not return
    its entries, decide() must re-probe, and the rewritten file must
    carry the current rev with the stale entries gone (store_cache
    merges through load_cache, so returning stale entries would
    resurrect them under a fresh version stamp forever)."""
    autotune.install_probe_hooks(bench=_bench)
    cache = tmp_path / "c.json"
    bucket = ShapeBucket(8, 64, 15, 2048)
    stale_key = autotune.cache_key(autotune._device_kind(), bucket)
    # a plausible rev-1 file: pre-`fused` cell dicts under rev-1 keys
    cache.write_text(json.dumps({
        "version": 1,
        "entries": {
            stale_key.replace("|v%d|" % autotune.CACHE_SCHEMA_REV,
                              "|v1|"): {
                "cell": {"hist_mode": "pallas_ct", "wave_width": 64,
                         "hist_hilo": False, "compact": True},
                "s_per_wave": 1e-9, "waves": 3},
        }}))
    assert autotune.load_cache(str(cache)) == {}
    cfg = _cfg(15, tpu_autotune="measure", tpu_autotune_cache=str(cache))
    prior = Cell("pallas_t", 8, True, False)
    d = decide(cfg, bucket, prior, Pins(), eligible=True)
    assert d.source == "measured" and not d.cache_hit
    with open(cache) as f:
        blob = json.load(f)
    assert blob["version"] == autotune.CACHE_SCHEMA_REV
    assert list(blob["entries"]) == [stale_key]
    assert blob["entries"][stale_key]["cell"] == d.cell.as_dict()
    assert "fused" in blob["entries"][stale_key]["cell"]
    # and the fresh file is an ordinary warm cache
    d2 = decide(cfg, bucket, prior, Pins(), eligible=True)
    assert d2.source == "cache" and d2.cell == d.cell


def test_force_mode_ignores_cache(tmp_path):
    autotune.install_probe_hooks(bench=_bench)
    cache = str(tmp_path / "c.json")
    bucket = ShapeBucket(8, 64, 15, 2048)
    prior = Cell("pallas_t", 8, True, False)
    decide(_cfg(15, tpu_autotune="measure", tpu_autotune_cache=cache),
           bucket, prior, Pins(), eligible=True)
    d = decide(_cfg(15, tpu_autotune="force", tpu_autotune_cache=cache),
               bucket, prior, Pins(), eligible=True)
    assert d.source == "measured" and d.probes


def test_measure_off_tpu_is_noop(tmp_path):
    """No TPU, no injected hooks: measure mode falls back to the prior
    with zero probes (CPU CI must not pay wave compiles)."""
    cfg = _cfg(15, tpu_autotune="measure",
               tpu_autotune_cache=str(tmp_path / "c.json"))
    prior = Cell("pallas_t", 8, True, False)
    d = decide(cfg, ShapeBucket(8, 64, 15, 2048), prior, Pins(),
               eligible=True, probe=lambda cell: (lambda: None))
    assert d.cell == prior and d.source == "prior" and not d.probes
    assert not os.path.exists(str(tmp_path / "c.json"))


def test_measure_cells_injectable_timer():
    """With a fake clock the measured s/wave is exact: the timer ticks
    once before and once after the timed loop."""
    ticks = [0.0]

    def timer():
        ticks[0] += 1.0
        return ticks[0]

    autotune.install_probe_hooks(timer=timer)
    cells = [Cell("pallas_t", 8, True, False),
             Cell("pallas_t", 16, True, False)]
    events = []
    out = measure_cells(cells, ShapeBucket(8, 64, 15, 2048),
                        lambda cell: (lambda: None), waves=4,
                        events=events)
    assert [(c, s) for c, s in out] == [(cells[0], 0.25),
                                        (cells[1], 0.25)]
    assert len(events) == 2 and all(e[0] == "autotune_probe"
                                    for e in events)


def test_failed_probe_drops_candidate_not_training():
    autotune.install_probe_hooks(force=True)

    def probe(cell):
        if cell.wave_width == 16:
            raise RuntimeError("mosaic says no")
        return lambda: None

    events = []
    out = measure_cells([Cell("pallas_t", 8, True, False),
                         Cell("pallas_t", 16, True, False)],
                        ShapeBucket(8, 64, 15, 2048), probe, 1, events)
    assert [c.wave_width for c, _ in out] == [8]


# ------------------------------------------------------------- enumeration

def test_enumerate_cells_respects_pins_and_gates():
    bucket = ShapeBucket(8, 64, 15, 2048)
    prior = Cell("pallas_t", 8, True, False)
    cells = enumerate_cells(prior, bucket, Pins())
    assert cells[0] == prior and len(cells) <= autotune.MAX_CELLS
    widths = {c.wave_width for c in cells}
    assert {4, 8, 16} <= widths
    # the staged/fused flip (rev 2) is a candidate when unpinned ...
    assert any(c.fused for c in cells)
    # ... and fully pinned (all five dimensions) only the prior survives
    assert enumerate_cells(
        prior, bucket, Pins(True, True, True, True, True)) == [prior]
    # pinning fused alone removes exactly the fused arm
    assert all(not c.fused
               for c in enumerate_cells(prior, bucket, Pins(fused=True)))
    # non-wave kernels have no neighbours
    assert enumerate_cells(Cell("onehot", 1, True, False), bucket,
                           Pins()) == [Cell("onehot", 1, True, False)]
    # VMEM hard gate: a W*2 neighbour whose block exceeds the budget is
    # not enumerated (bosch-wide: W64 at 968x256 would be 190 MB)
    wide = ShapeBucket(968, 256, 255, 1 << 20)
    big = enumerate_cells(Cell("pallas_t", 32, True, False), wide, Pins())
    assert all(c.wave_width <= 32 for c in big)
    # ct cells are only candidates where ct may run (serial execution)
    no_ct = enumerate_cells(prior, bucket, Pins(), ct_allowed=False)
    assert all(c.hist_mode == "pallas_t" for c in no_ct)


def test_ct_beyond_promotion_bound_is_a_candidate():
    """The 2560 ct bound is a PRIOR, not a hard gate: measure mode
    probes the ct arm on shapes the heuristic would never promote."""
    bucket = ShapeBucket(136, 256, 255, 1 << 20)   # 34816 >> 2560
    cells = enumerate_cells(Cell("pallas_t", 32, True, False), bucket,
                            Pins())
    assert any(c.hist_mode == "pallas_ct" for c in cells)


def test_row_bucket_powers_of_two():
    assert row_bucket(1) == 1
    assert row_bucket(1000) == 1024
    assert row_bucket(1024) == 1024
    assert row_bucket(1025) == 2048


# -------------------------------------------------- band prior post-mortem

# The 18-30 MB HIST_BLOCK_BAND and its band_adjusted_width escape were
# deleted: the degeneracy was never a property of the block SIZE but of
# the row-tile planner sizing transients against a fixed 16 MB budget
# that ignored the VMEM-resident accumulator, so mid-size blocks
# oversubscribed Mosaic's ~52 MB overlap window (while huge blocks were
# rescued by the chunked-RMW schedule at ~44 MB resident).  The fix
# lives in ops/pallas_wave.py::_tile_plan; tile_plan_vmem_report is the
# minimal reproduction and these tests keep it fixed.

def test_band_prior_is_gone():
    assert not hasattr(autotune, "HIST_BLOCK_BAND")
    assert not hasattr(autotune, "band_adjusted_width")


def test_tile_plan_fixes_the_ex_band_cells():
    """epsilon W16 and bosch W32 — the two measured in-band cells the
    escape used to bend to wider widths — are pathological under the
    legacy plan and schedulable under the accumulator-aware one."""
    from lightgbm_tpu.ops.pallas_wave import tile_plan_vmem_report
    for fc, bp, k in [(2000, 64, 16), (968, 64, 32)]:
        rep = tile_plan_vmem_report(1 << 20, fc, bp, k)
        assert rep["pathological_old"], (fc, k)
        assert not rep["pathological_new"], (fc, k)
        assert rep["c_new"] < rep["c_old"]
        assert rep["live_new"] <= rep["overlap_window"]


def test_tile_plan_catches_the_band_misfire():
    """yahoo-shaped W64 (700 cols, 64-pad): 32.8 MB resident sits OVER
    the old band's 30 MB upper edge, so the escape declared it clear —
    yet resident + 36 MB of transients blows the overlap window and the
    cell measured 3.2x slow.  The live-set bound flags and fixes it;
    the (18,30) size band never could."""
    from lightgbm_tpu.ops.pallas_wave import tile_plan_vmem_report
    rep = tile_plan_vmem_report(1 << 20, 700, 64, 64)
    assert rep["resident_bytes"] > 30 << 20     # outside the old band
    assert not rep["chunked_rmw"]               # below the chunked rescue
    assert rep["pathological_old"]
    assert not rep["pathological_new"]


def test_tile_plan_leaves_healthy_cells_alone():
    """Shapes that were never degenerate keep their full row tile: the
    flagship (tiny resident block) and bosch W64 (45 MB resident, the
    chunked-RMW schedule overlaps regardless of live set)."""
    from lightgbm_tpu.ops.pallas_wave import tile_plan_vmem_report
    flag = tile_plan_vmem_report(1 << 20, 28, 256, 32)
    assert flag["c_new"] == flag["c_old"] == 8192
    assert not flag["pathological_old"]
    bosch64 = tile_plan_vmem_report(1 << 20, 968, 64, 64)
    assert bosch64["chunked_rmw"]
    assert bosch64["c_new"] == bosch64["c_old"]
    assert not bosch64["pathological_new"]


# ------------------------------------------------------------ integration

def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_train_measure_then_cache_hit(tmp_path):
    """End-to-end through lgb.train on CPU via the bench hook: first
    run probes and persists, second run is a cache hit with zero probe
    waves — the decision/probe events land on the timeline through the
    learner's pending-events queue."""
    rng = np.random.default_rng(3)
    X = rng.standard_normal((800, 6)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    autotune.install_probe_hooks(bench=_bench)

    def run(tag):
        ev_path = str(tmp_path / ("%s.jsonl" % tag))
        p = {"objective": "binary", "num_leaves": 15, "verbose": -1,
             "min_data_in_leaf": 5, "tpu_growth": "wave",
             "tpu_histogram_mode": "pallas_t", "tpu_autotune": "measure",
             "tpu_autotune_cache": str(tmp_path / "cache.json"),
             "obs_events_path": ev_path}
        lgb.train(p, lgb.Dataset(X, label=y, params=p),
                  num_boost_round=2)
        evs = _events(ev_path)
        return ([e for e in evs if e.get("ev") == "autotune_decision"],
                [e for e in evs if e.get("ev") == "autotune_probe"])

    d1, p1 = run("run1")
    assert len(d1) == 1 and d1[0]["source"] == "measured" and p1
    d2, p2 = run("run2")
    assert len(d2) == 1 and d2[0]["source"] == "cache" and not p2
    assert d2[0]["cache_hit"] and d2[0]["cell"] == d1[0]["cell"]


def test_train_off_mode_single_decision(tmp_path):
    """Default params: exactly one decision event, mode off, zero
    probes — the bench.py --dry contract."""
    rng = np.random.default_rng(4)
    X = rng.standard_normal((500, 5)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    ev_path = str(tmp_path / "off.jsonl")
    p = {"objective": "binary", "num_leaves": 7, "verbose": -1,
         "min_data_in_leaf": 5, "obs_events_path": ev_path}
    lgb.train(p, lgb.Dataset(X, label=y, params=p), num_boost_round=2)
    evs = _events(ev_path)
    decs = [e for e in evs if e.get("ev") == "autotune_decision"]
    assert len(decs) == 1
    assert decs[0]["mode"] == "off" and decs[0]["source"] == "off"
    assert not [e for e in evs if e.get("ev") == "autotune_probe"]
