"""Hang watchdog + flight recorder for distributed runs.

The failure mode XLA gives a pod for free is a collective that never
returns: one rank dies or stalls, every other rank parks inside the
barrier, and the job burns its reservation in silence — no exception,
no log line, no timeline record past the last flush.  The watchdog is
the forensics for exactly that death.

``Watchdog`` is a daemon thread armed by the observer around blocking
regions (host collectives in ``parallel/comm.py``) and re-armed by
per-iteration progress (``iter_begin``/``iter_end``).  When no progress
lands within ``obs_watchdog_secs`` it dumps a **flight record** next to
the rank's timeline shard (``<events_path>.flight.json``):

* the ring buffer of the last N events (``RingBuffer`` in events.py) —
  what this rank was doing right before it stopped;
* the armed label — which collective/iteration hung, with its ``seq``;
* every Python thread's stack via ``sys._current_frames`` (the
  ``faulthandler``-style view, but structured);
* live per-device memory stats and the current metrics-registry
  snapshot;
* any registered flight-context providers (``obs.add_flight_provider``)
  — the serve scheduler reports queue depth, queued rows and pending
  route kinds, so a wedged serve runner's dump shows what was stuck
  behind it.

The serve worker thread arms the same watchdog around every runner call
(serve/scheduler.py), so a microbatch that never returns — a hung device
call, a deadlocked host predictor — dumps the same flight record a hung
collective would.

The same dump fires on SIGTERM (the scheduler killing the job) and on
``obs_health=fatal`` aborts, so "the run died" always leaves a black
box.  The watchdog only observes — it never kills the run itself; the
simulated-rank barrier timeout (comm.py) and the cluster scheduler stay
in charge of actually reaping a hung job.
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time

from ..utils.log import Log


def _thread_stacks():
    """{thread label: [frame lines]} for every live Python thread.

    Delegates to the one shared ``sys._current_frames`` walker in
    obs/prof.py — flight records, incident evidence and the sampling
    profiler must agree on thread labeling, so there is exactly one
    capture path."""
    from .prof import capture_thread_stacks
    return capture_thread_stacks()


def dump_flight_record(obs, reason, label=None, extra=None):
    """Write ``<events_path>.flight.json`` for ``obs`` and return the
    path (None when the observer has no events path to anchor it to).
    Best-effort everywhere: forensics must never raise into the run."""
    path = getattr(obs, "flight_path", "")
    record = {
        "reason": str(reason),
        "label": label if label is not None else getattr(
            getattr(obs, "_watchdog", None), "label", None),
        "t": time.time(),
        "run": getattr(obs, "run_id", None),
        "rank": getattr(obs, "rank", 0),
        "world_size": getattr(obs, "world_size", 1),
        "pid": os.getpid(),
        "events": obs.ring_snapshot(),
        "threads": _thread_stacks(),
    }
    # a live scrape plane (obs/live.py) outlives the hang that dumped
    # this record — point the operator reading the dump at it
    live = getattr(obs, "live_url", "")
    if live:
        record["live_url"] = live
    if extra:
        record["extra"] = dict(extra)
    # live-context providers (serve/scheduler.py: queue depth, queued
    # rows, pending route kinds) — what the subsystem was holding when
    # the run wedged, which the event ring alone cannot show
    try:
        ctx = obs.flight_context()
    except AttributeError:
        ctx = {}
    except Exception as e:
        ctx = {"error": repr(e)}
    if ctx:
        record["context"] = ctx
    try:
        from .memory import device_memory_stats
        record["devices"] = device_memory_stats()
    except Exception as e:
        record["devices"] = [{"error": repr(e)}]
    try:
        from .metrics import REGISTRY
        record["metrics"] = REGISTRY.snapshot()
    except Exception as e:
        record["metrics"] = {"error": repr(e)}
    if not path:
        return None
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            json.dump(record, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError as e:
        Log.warning("obs: flight record %s failed: %s", path, e)
        return None
    obs._flight_dumped = True
    Log.warning("obs: flight record (%s) -> %s", reason, path)
    return path


class Watchdog:
    """Per-observer hang detector.

    ``arm(label)`` starts (or restarts) the countdown with a new label;
    ``pet(label)`` is the progress heartbeat that restarts it.  The
    daemon thread fires at most once per armed window: it dumps the
    flight record and emits a ``health`` event with
    ``check="watchdog"``, then waits for fresh progress before it can
    fire again — a genuinely hung rank dumps exactly one record.
    """

    def __init__(self, obs, timeout_s):
        self._obs = obs
        self.timeout_s = float(timeout_s)
        self.label = None
        self.fired = 0
        self._deadline = None          # None = disarmed
        self._fired_this_window = False
        self._near_signaled = False    # one near-expiry per armed window
        self._wake = threading.Event()
        self._stop = False
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, name="lgbm-obs-watchdog", daemon=True)
        _install_sigterm_hook()

    def start(self):
        self._thread.start()

    # ------------------------------------------------------------ arming
    def arm(self, label):
        with self._lock:
            self.label = str(label)
            self._deadline = time.monotonic() + self.timeout_s
            self._fired_this_window = False
            self._near_signaled = False
        self._wake.set()

    def pet(self, label=None):
        with self._lock:
            if label is not None:
                self.label = str(label)
            self._deadline = time.monotonic() + self.timeout_s
            self._fired_this_window = False
            self._near_signaled = False
        self._wake.set()

    def stop(self):
        with self._lock:
            self._stop = True
            self._deadline = None
        self._wake.set()

    # ------------------------------------------------------------- loop
    def _loop(self):
        poll = max(0.02, min(0.25, self.timeout_s / 4.0))
        while True:
            self._wake.wait(timeout=poll)
            self._wake.clear()
            with self._lock:
                if self._stop:
                    return
                now = time.monotonic()
                expired = (self._deadline is not None
                           and not self._fired_this_window
                           and now >= self._deadline)
                # near-expiry at 75% of the window: an incident-engine
                # early warning — evidence captured while the rank is
                # merely SLOW still shows what it was stuck on when it
                # finally hangs
                near = (not expired and self._deadline is not None
                        and not self._fired_this_window
                        and not self._near_signaled
                        and now >= self._deadline - 0.25 * self.timeout_s)
                label = self.label
                remaining = (self._deadline - now
                             if self._deadline is not None else 0.0)
                if expired:
                    self._fired_this_window = True
                    self.fired += 1
                if near:
                    self._near_signaled = True
            if near:
                try:
                    self._obs.incident_signal(
                        "watchdog_near_expiry",
                        {"timeout_s": self.timeout_s, "label": label,
                         "remaining_s": round(max(0.0, remaining), 3)})
                except Exception:
                    pass
            if expired:
                self._fire(label)

    def _fire(self, label):
        obs = self._obs
        Log.warning("obs: watchdog expired after %.1fs without progress "
                    "(rank %d, last armed: %s)", self.timeout_s,
                    getattr(obs, "rank", 0), label)
        path = dump_flight_record(obs, "watchdog timeout", label=label)
        try:
            obs.event("health", check="watchdog", status="warn",
                      it=getattr(obs, "_iters", -1),
                      detail={"timeout_s": self.timeout_s,
                              "label": label,
                              "flight_record": path or ""})
            obs.flush()
        except Exception:
            pass


# -- SIGTERM hook ---------------------------------------------------------
# one per process, installed lazily by the first watchdog-enabled
# observer; dumps a flight record for EVERY live observer, then defers
# to the previous handler so the process still dies as asked
_SIGTERM_INSTALLED = False


def _install_sigterm_hook():
    global _SIGTERM_INSTALLED
    if _SIGTERM_INSTALLED:
        return
    if threading.current_thread() is not threading.main_thread():
        return                  # signal.signal only works there
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            from .events import live_observers
            for obs in live_observers():
                if getattr(obs, "_watchdog", None) is not None:
                    try:
                        dump_flight_record(obs, "SIGTERM")
                    except Exception:
                        pass
            if callable(prev):
                prev(signum, frame)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_term)
        _SIGTERM_INSTALLED = True
    except (ValueError, OSError):
        pass
