"""Wave-histogram Pallas kernels vs the XLA oracle (interpret mode, CPU).

Covers both operand layouts (v1 row-major, v2 transposed) and the 4-bit
packed input path of each.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.pack import pack4_host
from lightgbm_tpu.ops.pallas_wave import (wave_histogram_pallas,
                                          wave_histogram_pallas_t,
                                          wave_histogram_reference)


def _data(n=3000, f=7, b=14, k=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.integers(0, b, size=(n, f), dtype=np.uint8)
    leaf_id = rng.integers(0, 2 * k, size=n).astype(np.int32)
    w3 = rng.normal(size=(n, 3)).astype(np.float32)
    cid = np.array([0, 2, 4, -1, 7], dtype=np.int32)[:k]
    return X, leaf_id, w3, cid, b


@pytest.mark.parametrize("layout", ["v1", "v2"])
def test_kernel_matches_oracle(layout):
    X, leaf_id, w3, cid, b = _data()
    want = np.array(wave_histogram_reference(
        jnp.asarray(X), jnp.asarray(leaf_id), jnp.asarray(w3),
        jnp.asarray(cid), b))
    want[np.asarray(cid) < 0] = 0.0
    if layout == "v1":
        got = wave_histogram_pallas(
            jnp.asarray(X), jnp.asarray(leaf_id), jnp.asarray(w3),
            jnp.asarray(cid), b, interpret=True)
    else:
        got = wave_histogram_pallas_t(
            jnp.asarray(X.T), jnp.asarray(leaf_id), jnp.asarray(w3),
            jnp.asarray(cid), b, interpret=True)
    np.testing.assert_allclose(np.asarray(got), want, rtol=5e-4, atol=5e-4)


def test_pallas_t_data_parallel_constructs():
    """tree_learner=data + pallas_t must reach the mesh wave branch (the
    base constructor's exact-engine fallback maps pallas_t to onehot
    instead of crashing) and train."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(2)
    X = rng.normal(size=(1600, 6))
    y = (X[:, 0] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "tree_learner": "data", "tpu_histogram_mode": "pallas_t"}
    bst = lgb.train(params, lgb.Dataset(X, label=y, params=params),
                    num_boost_round=2)
    assert bst.predict(X).shape == (1600,)


def test_pallas_t_mode_plumbing():
    """tpu_histogram_mode=pallas_t resolves to wave growth and trains
    (falling back to the einsum path off-TPU); exact growth rejects it."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.log import LightGBMError

    rng = np.random.default_rng(1)
    X = rng.normal(size=(1200, 6))
    y = (X[:, 0] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "tpu_histogram_mode": "pallas_t"}
    bst = lgb.train(params, lgb.Dataset(X, label=y, params=params),
                    num_boost_round=3)
    assert bst._gbdt.learner.growth == "wave"
    p = bst.predict(X)
    assert p.shape == (1200,)

    bad = dict(params, tpu_growth="exact")
    with pytest.raises(LightGBMError):
        lgb.train(bad, lgb.Dataset(X, label=y, params=bad),
                  num_boost_round=1)


@pytest.mark.parametrize("layout", ["v1", "v2"])
def test_kernel_packed_matches_oracle(layout):
    X, leaf_id, w3, cid, b = _data(f=9, b=15, seed=3)
    want = np.array(wave_histogram_reference(
        jnp.asarray(X), jnp.asarray(leaf_id), jnp.asarray(w3),
        jnp.asarray(cid), b))
    want[np.asarray(cid) < 0] = 0.0
    packed = pack4_host(X)
    if layout == "v1":
        got = wave_histogram_pallas(
            jnp.asarray(packed), jnp.asarray(leaf_id), jnp.asarray(w3),
            jnp.asarray(cid), b, interpret=True, logical_cols=X.shape[1])
    else:
        got = wave_histogram_pallas_t(
            jnp.asarray(packed.T), jnp.asarray(leaf_id), jnp.asarray(w3),
            jnp.asarray(cid), b, interpret=True, logical_cols=X.shape[1])
    np.testing.assert_allclose(np.asarray(got), want, rtol=5e-4, atol=5e-4)
