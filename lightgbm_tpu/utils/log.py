"""Leveled logger mirroring the reference's static ``Log`` class.

Reference: include/LightGBM/utils/log.h:38 (Debug/Info/Warning/Fatal with a
``verbosity`` mapping in src/io/config.cpp).  ``Log.fatal`` raises instead of
aborting the process so library users can catch errors.
"""
from __future__ import annotations

import sys


class LightGBMError(Exception):
    """Error raised by lightgbm_tpu routines (mirrors ``Log::Fatal``)."""


class Log:
    # verbosity semantics match the reference: <0 fatal-only, 0 +warning,
    # 1 +info (default), >1 +debug   (src/io/config.cpp verbosity mapping)
    _level = 1
    _stream = None          # None -> sys.stderr (resolved at write time)

    @classmethod
    def reset_level(cls, verbosity: int) -> None:
        cls._level = verbosity

    @classmethod
    def set_stream(cls, stream):
        """Redirect log output to ``stream`` (None restores stderr).
        Returns the previous stream so callers/tests can restore it."""
        prev = cls._stream
        cls._stream = stream
        return prev

    @classmethod
    def debug(cls, msg: str, *args) -> None:
        if cls._level > 1:
            cls._write("Debug", msg, args)

    @classmethod
    def info(cls, msg: str, *args) -> None:
        if cls._level >= 1:
            cls._write("Info", msg, args)

    @classmethod
    def warning(cls, msg: str, *args) -> None:
        if cls._level >= 0:
            cls._write("Warning", msg, args)

    @classmethod
    def fatal(cls, msg: str, *args) -> None:
        text = (msg % args) if args else msg
        raise LightGBMError(text)

    @classmethod
    def _write(cls, level: str, msg: str, args) -> None:
        text = (msg % args) if args else msg
        stream = cls._stream if cls._stream is not None else sys.stderr
        stream.write("[LightGBM-TPU] [%s] %s\n" % (level, text))
        stream.flush()
