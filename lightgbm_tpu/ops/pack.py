"""4-bit bin packing — two bin columns per byte on device.

The reference halves histogram memory traffic for `max_bin<=15` by storing
two 4-bit bins per byte (src/io/dense_nbits_bin.hpp:37); SURVEY §7 step 8
names int4 packing as the TPU analog.  Here the packed matrix IS the
device-resident store: HBM for the bin matrix halves, and the growth
engines unpack per row-chunk inside their scans (a shift+mask the compiler
fuses into the chunk's consumers), so the full-size matrix never
materializes in HBM.

Layout is SPLIT-HALF, not interleaved: packed column ``j`` carries logical
column ``j`` in its LOW nibble and logical column ``j + Fh`` in its HIGH
nibble (``Fh = ceil(F/2)``).  Unpacking is then a lane-contiguous
``concat([x & 15, x >> 4])[:, :F]`` — no strided lane shuffles, which TPU
vector units (and Mosaic) handle poorly.  With odd ``F`` the last high
nibble is zero padding and is dropped by the slice.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp


def can_pack4(num_bins_per_col) -> bool:
    """True when every device column's bin count fits a nibble."""
    arr = np.asarray(num_bins_per_col)
    return arr.size > 0 and int(arr.max()) <= 16


def pack4_host(binned: np.ndarray) -> np.ndarray:
    """(N, F) uint8 bins (< 16) -> (N, ceil(F/2)) packed uint8."""
    n, f = binned.shape
    fh = (f + 1) // 2
    lo = binned[:, :fh].astype(np.uint8)
    hi = np.zeros((n, fh), dtype=np.uint8)
    hi[:, : f - fh] = binned[:, fh:]
    return (lo | (hi << 4)).astype(np.uint8)


def unpack4(xc, logical_cols: int):
    """Packed (C, Fh) uint8 chunk -> (C, logical_cols) bins.

    Pure bitwise + concat; called inside the growth engines' chunk scans so
    XLA fuses it into the chunk's one-hot/compare consumers.
    """
    x = xc.astype(jnp.int32)
    return jnp.concatenate([x & 15, x >> 4], axis=-1)[..., :logical_cols]
