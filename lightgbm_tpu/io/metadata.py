"""Metadata: labels, weights, query boundaries, init scores + side files.

Parity target: include/LightGBM/dataset.h:36-248 and src/io/metadata.cpp.
Side files ``<data>.weight``, ``<data>.query``, ``<data>.init`` are read when
present, exactly like ``Metadata::Init(data_filename, ...)``; query id lists
are converted to boundary arrays; query weights are auto-derived from data
weights (sum per query) as in metadata.cpp.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..utils.log import Log


class Metadata:
    def __init__(self, num_data: int = 0):
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None
        self.weights: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None
        self.query_weights: Optional[np.ndarray] = None
        self.init_score: Optional[np.ndarray] = None

    # ------------------------------------------------------------ side files
    def init_from_file(self, data_filename: str) -> None:
        """Load .weight/.query/.init side files if they exist
        (metadata.cpp LoadWeights/LoadQueryBoundaries/LoadInitialScore)."""
        wf = data_filename + ".weight"
        qf = data_filename + ".query"
        sf = data_filename + ".init"
        if os.path.exists(wf):
            self.set_weights(np.loadtxt(wf, dtype=np.float64, ndmin=1))
            Log.info("Loading weights...")
        if os.path.exists(qf):
            counts = np.loadtxt(qf, dtype=np.int64, ndmin=1)
            self.set_query_counts(counts)
            Log.info("Loading query boundaries...")
        if os.path.exists(sf):
            init = np.loadtxt(sf, dtype=np.float64, ndmin=1)
            self.init_score = init.reshape(-1)
            Log.info("Loading initial scores...")

    # --------------------------------------------------------------- setters
    def set_label(self, label) -> None:
        label = np.asarray(label, dtype=np.float32).reshape(-1)
        if self.num_data and len(label) != self.num_data:
            Log.fatal("Length of label is not same with #data")
        self.label = label
        if not self.num_data:
            self.num_data = len(label)

    def set_weights(self, weights) -> None:
        if weights is None:
            self.weights = None
            self.query_weights = None
            return
        weights = np.asarray(weights, dtype=np.float32).reshape(-1)
        if self.num_data and len(weights) != self.num_data:
            Log.fatal("Length of weights is not same with #data")
        self.weights = weights
        self._update_query_weights()

    def set_query_counts(self, counts) -> None:
        """Per-query data counts -> boundary array (metadata.cpp semantics)."""
        counts = np.asarray(counts, dtype=np.int64).reshape(-1)
        boundaries = np.zeros(len(counts) + 1, dtype=np.int64)
        np.cumsum(counts, out=boundaries[1:])
        if self.num_data and boundaries[-1] != self.num_data:
            Log.fatal("Sum of query counts is not same with #data")
        self.query_boundaries = boundaries
        self._update_query_weights()

    def set_query_id(self, qid) -> None:
        """Raw per-row query ids -> boundaries (requires grouped rows)."""
        qid = np.asarray(qid).reshape(-1)
        change = np.nonzero(np.diff(qid))[0] + 1
        boundaries = np.concatenate([[0], change, [len(qid)]])
        self.query_boundaries = boundaries.astype(np.int64)
        self._update_query_weights()

    def set_init_score(self, init_score) -> None:
        if init_score is None:
            self.init_score = None
            return
        self.init_score = np.asarray(init_score, dtype=np.float64).reshape(-1)

    def set_field(self, name: str, data) -> None:
        if name == "label":
            self.set_label(data)
        elif name == "weight":
            self.set_weights(data)
        elif name == "group" or name == "query":
            self.set_query_counts(data)
        elif name == "init_score":
            self.set_init_score(data)
        else:
            Log.fatal("Unknown field name: %s", name)

    def get_field(self, name: str):
        if name == "label":
            return self.label
        if name == "weight":
            return self.weights
        if name == "group" or name == "query":
            return self.query_boundaries
        if name == "init_score":
            return self.init_score
        Log.fatal("Unknown field name: %s", name)

    def _update_query_weights(self) -> None:
        """Sum data weights per query (metadata.cpp query_weights_ calc)."""
        if self.weights is None or self.query_boundaries is None:
            self.query_weights = None
            return
        nq = len(self.query_boundaries) - 1
        qw = np.add.reduceat(self.weights, self.query_boundaries[:-1])
        counts = np.diff(self.query_boundaries)
        qw = np.where(counts > 0, qw / np.maximum(counts, 1), 0.0)
        self.query_weights = qw.astype(np.float32)
        assert len(self.query_weights) == nq

    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1

    def subset(self, indices: np.ndarray) -> "Metadata":
        """Row subset copy used by bagging (metadata.cpp Init(fullset, used_indices))."""
        out = Metadata(len(indices))
        if self.label is not None:
            out.label = self.label[indices]
        if self.weights is not None:
            out.weights = self.weights[indices]
        if self.init_score is not None:
            ns = len(self.init_score) // max(self.num_data, 1)
            parts = [self.init_score[k * self.num_data:(k + 1) * self.num_data][indices]
                     for k in range(ns)]
            out.init_score = np.concatenate(parts) if parts else None
        # queries are not subsettable row-wise; ranking doesn't bag rows
        return out
