# CMAKE generated file: DO NOT EDIT!
# Timestamp file for compiler generated dependencies management for lgbm_tpu_native.
