"""Tree learner: host facade over the device-resident grower (ops/grow.py).

Replaces SerialTreeLearner (serial_tree_learner.cpp) with a single jitted
XLA program per tree; the host only samples feature_fraction masks, feeds
gradients, and materializes the finished tree.  `train_device` returns the
device pytree without any host sync — the GBDT loop uses it to keep the
whole boosting iteration on-device; `train` additionally materializes a
models.Tree (real-valued thresholds resolved in float64 via the BinMappers).

Bagging/GOSS enter via `row_mult`; data-parallel runs wrap the same grow
program in shard_map (parallel/mesh.py).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..io.dataset import TrainingData
from ..models.tree import Tree
from ..obs import NULL_OBSERVER
from ..utils.config import Config
from ..utils.random import Random
from .grow import (BundleArrays, TreeArrays, default_row_capacities,
                   make_grow_fn)
from .split_finder import FeatureMeta, SplitParams
from ..utils.log import Log

# auto histogram-cache budget when histogram_pool_size is unset (-1): the
# reference's default is unlimited, but an Epsilon-shaped cache
# (L=255,F=2000,B=255 ~ 1.5GB) per booster is an HBM hazard on shared
# chips, so above this we fall back to recompute instead of subtraction
_AUTO_HIST_CACHE_MB = 2048.0


def hist_cache_enabled(config: Config, num_leaves: int, num_cols: int,
                       num_bins: int, dtype_bytes: int) -> bool:
    """HistogramPool policy (feature_histogram.hpp:398-565): cache per-leaf
    histograms (enabling larger-child-by-subtraction) only while the
    (L, F, B, 3) cache fits the histogram_pool_size budget; otherwise
    recompute both children and warn with the number."""
    need_mb = (num_leaves * max(num_cols, 1) * max(num_bins, 2) * 3
               * dtype_bytes) / 1e6
    budget = float(config.histogram_pool_size)
    if budget <= 0:
        budget = _AUTO_HIST_CACHE_MB
    if need_mb <= budget:
        return True
    Log.warning(
        "Histogram cache would need %.0f MB (num_leaves=%d x %d columns x "
        "%d bins x 3 x %dB) > histogram_pool_size budget %.0f MB; disabling "
        "the per-leaf histogram cache (children are recomputed instead of "
        "obtained by subtraction).", need_mb, num_leaves, num_cols,
        num_bins, dtype_bytes, budget)
    return False


def build_bundle_arrays(train_data: TrainingData):
    """(BundleArrays, group_bins) for the device grower, or (None, 0) when
    the dataset has no EFB layout."""
    bund = train_data.bundle
    if bund is None:
        return None, 0
    num_bin = np.asarray(train_data.num_bin_arr, np.int64)
    default = np.asarray(train_data.default_bin_arr, np.int64)
    B = int(num_bin.max())
    Bg = int(bund.num_group_bins.max())
    b = np.arange(B)[None, :]
    gb = bund.bin_off[:, None] + b - bund.bin_adj[:, None]
    valid = (b < num_bin[:, None]) & (b != default[:, None])
    flat_idx = bund.group_of[:, None].astype(np.int64) * Bg + gb
    flat_idx = np.clip(flat_idx, 0, len(bund.num_group_bins) * Bg - 1)
    arrays = BundleArrays(
        group_of=jnp.asarray(bund.group_of, jnp.int32),
        bin_off=jnp.asarray(bund.bin_off, jnp.int32),
        bin_adj=jnp.asarray(bund.bin_adj, jnp.int32),
        bin_span=jnp.asarray(bund.bin_span, jnp.int32),
        gather_idx=jnp.asarray(flat_idx, jnp.int32),
        valid_mask=jnp.asarray(valid),
    )
    return arrays, Bg


# kernel-selection policy now lives in ops/autotune.py (the measured
# autotuner's PRIOR); re-exported here because tests and downstream
# code import the resolvers from the learner module.  (The former
# HIST_BLOCK_BAND / band_adjusted_width escape prior is gone: the
# 18-30 MB degeneracy was root-caused to the tile planner's live-set
# overshoot and fixed in ops/pallas_wave.py _tile_plan — post-mortem
# in docs/FusedIteration.md.)
from .autotune import (_order_sensitive, resolve_wave_order,
                       resolve_wave_width)


def build_split_params(config: Config) -> SplitParams:
    return SplitParams(
        lambda_l1=float(config.lambda_l1),
        lambda_l2=float(config.lambda_l2),
        min_gain_to_split=float(config.min_gain_to_split),
        min_data_in_leaf=float(config.min_data_in_leaf),
        min_sum_hessian_in_leaf=float(config.min_sum_hessian_in_leaf),
        use_missing=bool(config.use_missing),
    )


def paged_device_matrix(train_data, row_pad: int = 0):
    """Device bin matrix paged shard-by-shard from a binned-format mmap
    reader (io/binned_format.py): the host never materializes the full
    (N, G) matrix, so peak host RSS stays O(shard) for out-of-core
    datasets.  Returns None when the dataset is not reader-backed —
    callers fall back to the one-shot host upload."""
    reader = getattr(train_data, "_binned_reader", None)
    if reader is None or reader.num_columns == 0 or reader.num_data == 0:
        return None
    # iter_rows restricts paging to the reader's row_range — on a
    # rank-sharded open (io/dataset.py from_binned(comm=...)) this rank
    # uploads only its own rows and never maps a foreign shard
    parts = [jnp.asarray(np.ascontiguousarray(view))
             for _, view in reader.iter_rows()]
    if row_pad:
        parts.append(jnp.zeros((int(row_pad), reader.num_columns),
                               parts[0].dtype))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)


class SerialTreeLearner:
    # run observer (lightgbm_tpu/obs); a class-level NULL default keeps
    # every constructor untouched and the disabled path allocation-free
    _obs = NULL_OBSERVER

    def __init__(self, config: Config, train_data: TrainingData,
                 psum_axis: Optional[str] = None, device_data=None,
                 device_row_pad: int = 0, device_packed_cols: int = 0,
                 device_sparse_col_cap: int = 0):
        """device_data: pre-uploaded (and possibly row-padded) bin matrix,
        or a SparseDeviceStore (with device_sparse_col_cap set);
        device_row_pad says how many trailing pad rows it carries so
        row_mult/_ones stay aligned (reset_config's no-reupload reuse);
        device_packed_cols: the logical column count when device_data is
        4-bit packed (0 = unpacked)."""
        self.config = config
        self.train_data = train_data
        # schema events produced during construction (band escapes,
        # autotune probes/decision) — the observer is attached AFTER
        # construction (gbdt.py _reset_observer), so they queue here
        # and set_observer flushes them right after the run header
        self._pending_events = []
        self.num_leaves = config.num_leaves
        self.dtype = jnp.float64 if config.tpu_use_dp else jnp.float32
        self.num_bins = int(train_data.num_bin_arr.max()) if train_data.num_features else 2
        if train_data.num_features == 0:
            # every feature was trivial ("no meaningful features" warned at
            # load): feed ONE constant dummy column so the engines still
            # produce the boost-from-average stump, as the reference does —
            # the mesh learners already synthesize exactly this column
            self.meta = FeatureMeta(
                num_bin=jnp.asarray([2], jnp.int32),
                default_bin=jnp.asarray([0], jnp.int32),
                is_categorical=jnp.asarray([False]),
            )
        else:
            self.meta = FeatureMeta(
                num_bin=jnp.asarray(train_data.num_bin_arr),
                default_bin=jnp.asarray(train_data.default_bin_arr),
                is_categorical=jnp.asarray(train_data.is_categorical_arr),
            )
        self.params = build_split_params(config)
        from .wave import WAVE_ONLY_MODES, _bin_pad
        hist_mode = config.tpu_histogram_mode
        if hist_mode not in (("auto", "onehot", "scatter", "pallas")
                             + WAVE_ONLY_MODES):
            Log.fatal("Unknown tpu_histogram_mode %s (expected auto/onehot/"
                      "scatter/pallas/pallas_t/pallas_ct)", hist_mode)
        self.bundle_arrays, self.group_bins = build_bundle_arrays(train_data)
        ncols = (len(train_data.bundle.num_group_bins)
                 if train_data.bundle is not None
                 else max(train_data.num_features, 1))
        nbins = self.group_bins if train_data.bundle is not None \
            else self.num_bins
        if hist_mode == "auto":
            # the measured-heuristic PRIOR (ops/autotune.py
            # prior_hist_mode, with the chip-session provenance in its
            # docstring): pallas_ct / pallas_t where the wave engine
            # will run with VMEM headroom, onehot on TPU otherwise,
            # scatter on CPU.  In measure/force autotune modes the
            # decide() block below may override this with a probed
            # winner for the shape bucket.
            from .autotune import prior_hist_mode
            hist_mode = prior_hist_mode(config, ncols, _bin_pad(nbins),
                                        self.num_leaves, psum_axis)
        self.hist_mode = hist_mode
        self.cache_hists = hist_cache_enabled(
            config, self.num_leaves, ncols, nbins,
            8 if config.tpu_use_dp else 4)
        # growth schedule: 'wave' batches the top-W pending splits per
        # sweep so the histogram work rides the MXU (ops/wave.py); 'exact'
        # is the per-split leaf-wise order of the reference (ops/grow.py).
        # auto -> wave on TPU.  NOTE: W (tpu_wave_width, default -1 = auto
        # via resolve_wave_width) approximates the leaf-wise ORDER (same
        # greedy frontier, batched; quality parity in tests/test_wave.py)
        # — set tpu_wave_width=1 for the reference's exact split sequence.
        growth = config.tpu_growth
        if growth not in ("auto", "exact", "wave"):
            Log.fatal("Unknown tpu_growth %s (expected auto/exact/wave)",
                      growth)
        if growth == "auto":
            # 'pallas' is the exact engine's per-leaf kernel; the
            # WAVE_ONLY_MODES kernels exist only as wave kernels
            if hist_mode in WAVE_ONLY_MODES:
                growth = "wave"
            else:
                growth = ("wave" if jax.default_backend() == "tpu"
                          and hist_mode != "pallas" else "exact")
        if growth == "exact" and hist_mode in WAVE_ONLY_MODES:
            Log.fatal("tpu_histogram_mode=%s requires tpu_growth=wave "
                      "(this kernel is wave-only)" % hist_mode)
        # ---- sparse device store (SparseBin/OrderedSparseBin analog,
        # ops/sparse_store.py): histograms from nonzero entries only, one
        # segment_sum over nnz per leaf instead of an O(N*F) dense pass.
        # Serial exact engine only; the wave engine keeps the dense store.
        from ..utils.config import _FALSE_SET, _TRUE_SET
        from .sparse_store import SparseDeviceStore as _SpStore
        serial_learner = str(config.tree_learner) in ("serial",)
        # gate on the engine actually running, not the tree_learner
        # string: a 'data'/'voting' config falling back to the serial
        # engine on one device still gets the sparse store.  The
        # feature-parallel subclass is the exception — it calls this
        # ctor with psum_axis=None but a pre-sharded dense device_data.
        from .sparse_mxu import ChunkedSparseStore as _ChStore
        true_serial = (psum_axis is None
                       and (device_data is None
                            or isinstance(device_data,
                                          (_SpStore, _ChStore))))
        # the data-parallel learner shards the coordinate store by row
        # blocks itself (parallel/mesh.py); feature/voting keep dense
        dp_learner = (psum_axis is not None
                      and str(config.tree_learner)
                      in ("data", "data_parallel"))
        sparse_on = bool(config.tpu_sparse)
        if sparse_on and not (true_serial or dp_learner):
            Log.warning("tpu_sparse=true ignored: the sparse device store "
                        "supports the serial and data-parallel learners "
                        "only")
            sparse_on = False
        sparse_kernel = bool(config.tpu_sparse_kernel)
        if sparse_kernel and not sparse_on:
            Log.warning("tpu_sparse_kernel=true has no effect without "
                        "tpu_sparse=true")
            sparse_kernel = False
        if sparse_on:
            if hist_mode.startswith("pallas"):
                Log.fatal("tpu_sparse=true is incompatible with "
                          "tpu_histogram_mode=%s (the pallas kernels are "
                          "dense-only)", hist_mode)
            if sparse_kernel and dp_learner:
                Log.warning("tpu_sparse_kernel=true ignored under the "
                            "data-parallel learner (the mesh sparse grow "
                            "shards the coordinate store)")
                sparse_kernel = False
            if sparse_kernel:
                # entry-chunk MXU store (ops/sparse_mxu.py) — wave-only:
                # the whole design amortizes one O(nnz) pass over W
                # splits and feeds the MXU per chunk
                if str(config.tpu_growth) == "exact":
                    Log.fatal("tpu_sparse_kernel=true requires wave "
                              "growth (tpu_growth=exact scans per leaf)")
                growth = "wave"
                hist_mode = "sparse_mxu"
            else:
                # both engines take the coordinate store: exact scans
                # nonzeros per split, wave amortizes the O(nnz) pass
                # over W splits but pays W split-column
                # materializations — measured SLOWER on the CPU mesh
                # (BENCH_NOTES.md) and unproven on chip, so auto growth
                # stays exact; an explicit tpu_growth=wave is honored
                if str(config.tpu_growth) == "auto":
                    growth = "exact"
                hist_mode = "sparse"
            self.hist_mode = hist_mode
        self.sparse_on = sparse_on
        self.sparse_col_cap = 0
        self.growth = growth
        # wave width only matters (and is only validated) under wave
        # growth — an exact-growth config with a leftover garbage
        # tpu_wave_width must keep training (ADVICE r2).
        self.wave_order = (resolve_wave_order(config)
                           if growth == "wave" else "batched")
        self.wave_width = (resolve_wave_width(config, self.num_leaves,
                                              self.wave_order)
                           if growth == "wave" else 1)
        # NOTE (PR 11): auto widths are no longer bent away from the
        # 18-30 MB accumulator-block band.  The band was a lossy proxy
        # for the tile planner's live-set overshoot of Mosaic's overlap
        # window; ops/pallas_wave.py _tile_plan now budgets the row tile
        # against the resident accumulator directly, so in-band widths
        # are no longer pathological (tile_plan_vmem_report is the
        # probe; regression-pinned in tests/test_pallas_wave.py and
        # tests/test_fused_iter.py, post-mortem in
        # docs/FusedIteration.md).  Old timelines may still carry
        # wave_band_escape events; the schema keeps accepting them.
        if bool(config.tpu_wave_compact):
            from .wave import pallas_wave_active as _pwa2
            if not (growth == "wave"
                    and self.hist_mode in ("pallas_ct", "pallas_t")
                    and _pwa2(self.hist_mode, self.dtype)):
                # explicit opt-ins must not be dropped silently (same
                # policy as tpu_sparse / tpu_bin_pack); the kernel gate
                # (_pwa2) also covers non-TPU backends and f64
                Log.warning("tpu_wave_compact=true ignored: requires "
                            "wave growth with a transposed Pallas wave "
                            "kernel (pallas_ct/pallas_t) on TPU with "
                            "f32 accumulation (resolved growth=%s, "
                            "histogram mode=%s, backend=%s)",
                            growth, self.hist_mode,
                            jax.default_backend())
        hp = str(config.tpu_hist_precision).strip().lower()
        if hp not in ("auto", "hilo", "bf16"):
            Log.fatal("Unknown tpu_hist_precision %s (expected auto/"
                      "hilo/bf16)", config.tpu_hist_precision)
        if hp == "auto":
            # the round-5 bf16 promotion PRIOR (ops/autotune.py
            # prior_hist_hilo carries the measured provenance); scoped
            # to serial wave execution like the pallas_ct promotion
            from .autotune import prior_hist_hilo
            self.hist_hilo = prior_hist_hilo(growth, psum_axis,
                                             self.hist_mode, self.dtype)
        else:
            self.hist_hilo = hp != "bf16"
        # resolved compaction flag — a plain config passthrough today,
        # but an autotune-tunable dimension, so it lives on the learner
        # (the wave jit below reads THIS, never the raw config)
        self.wave_compact = bool(config.tpu_wave_compact)
        lk = str(config.tpu_wave_lookup).strip().lower()
        # validate unconditionally (like tpu_histogram_mode): a typo'd
        # value must not be silently ignored just because growth resolved
        # to exact (ADVICE r3); it is APPLIED only under wave growth
        if lk not in ("auto", "onehot", "compact", "gather"):
            Log.fatal("Unknown tpu_wave_lookup %s (expected auto/"
                      "onehot/compact/gather)", config.tpu_wave_lookup)
        if growth == "wave":
            # auto -> compact on TPU (measured on v5e at 1Mx28/255
            # leaves/W=32: 7.12 it/s vs onehot-lookup's 6.34 on the XLA
            # engine — the (C, L) leaf one-hot was ~L/W of pure traffic);
            # onehot elsewhere (CPU layouts don't pay the lane padding)
            if lk == "auto":
                self.wave_lookup = ("compact"
                                    if jax.default_backend() == "tpu"
                                    else "onehot")
            else:
                self.wave_lookup = lk
            # the "no effect" warning must only fire when the fused
            # kernel will ACTUALLY run — off-TPU those modes fall back
            # to the XLA partition scan where the lookup does apply
            # (ADVICE r3); the sparse pass owns its lookup everywhere
            from .wave import pallas_wave_active
            fused_runs = (hist_mode == "pallas_ct"
                          and pallas_wave_active(hist_mode, self.dtype))
            if lk != "auto" and (fused_runs or sparse_on):
                Log.warning("tpu_wave_lookup=%s has no effect under %s "
                            "(the fused kernels / sparse pass own their "
                            "own lookup)", lk,
                            "tpu_sparse" if sparse_on
                            else "tpu_histogram_mode=%s" % hist_mode)
        else:
            self.wave_lookup = "onehot"
        # 4-bit packing (dense_nbits_bin.hpp:37 analog, ops/pack.py): when
        # every device column fits a nibble, store TWO columns per byte in
        # HBM; the growth engines unpack per chunk/column in-scan, so the
        # bin matrix's HBM footprint and read traffic halve.  Supported by
        # the wave engine (the TPU default) and by exact growth under the
        # onehot/scatter kernels; the pallas kernels and mesh learners
        # keep byte bins.
        from .pack import can_pack4
        bins_per_col = (train_data.bundle.num_group_bins
                        if train_data.bundle is not None
                        else train_data.num_bin_arr)
        pack_cfg = str(config.tpu_bin_pack).strip().lower()
        if pack_cfg not in _TRUE_SET | _FALSE_SET | {"auto"}:
            Log.fatal("tpu_bin_pack: value %s cannot be parsed as "
                      "auto/bool", config.tpu_bin_pack)
        pack_forced = pack_cfg in _TRUE_SET
        pack_growth_ok = (growth == "wave"
                          or (growth == "exact"
                              and hist_mode in ("onehot", "scatter")))
        # mesh learners keep byte bins: data/voting arrive with psum_axis
        # set, but the feature-parallel subclass calls this base ctor with
        # psum_axis=None and a pre-sharded device matrix — gate on the
        # tree_learner config (serial_learner above), not just the axis
        self.packed_cols = 0
        if ((pack_forced or pack_cfg == "auto") and pack_growth_ok
                and not sparse_on
                and psum_axis is None and serial_learner
                and can_pack4(bins_per_col)):
            self.packed_cols = ncols
        elif pack_forced:
            reasons = []
            if sparse_on:
                reasons.append("the dense device store (tpu_sparse keeps "
                               "coordinates, there are no bin bytes to "
                               "pack)")
            elif not pack_growth_ok:
                reasons.append("wave growth or exact growth with the "
                               "onehot/scatter histogram kernels")
            if psum_axis is not None or not serial_learner:
                reasons.append("the serial (single-shard) learner")
            if not can_pack4(bins_per_col):
                reasons.append("at most 16 bins per column (max_bin<=15 "
                               "plus the reserved zero/missing bin)")
            Log.warning("tpu_bin_pack=true ignored: packing requires %s",
                        " and ".join(reasons))
        if int(config.tpu_wave_chunk) <= 0:
            Log.fatal("tpu_wave_chunk must be positive, got %s",
                      config.tpu_wave_chunk)
        elif growth == "wave" and int(config.tpu_wave_chunk) < 256:
            Log.warning("tpu_wave_chunk=%d is below the engine minimum; "
                        "the wave sweep uses 256-row chunks instead",
                        int(config.tpu_wave_chunk))
        # ---- device upload (row-padded to a quantum so nearby dataset
        # sizes land on the same compiled shape; pad rows carry zero
        # row_mult and change nothing)
        self._row_pad = device_row_pad
        if sparse_on and psum_axis is not None:
            # data-parallel: the mesh learner replaces X with its
            # row-block coordinate stores after this ctor; keep the
            # dense device_data meanwhile
            self.X = device_data
        elif sparse_on:
            from .sparse_mxu import ChunkedSparseStore, build_chunked_store
            from .sparse_store import (SparseDeviceStore,
                                       build_sparse_store,
                                       column_fill_bins)
            self._row_pad = 0
            want_store = (ChunkedSparseStore if sparse_kernel
                          else SparseDeviceStore)
            if (isinstance(device_data, want_store)
                    and device_sparse_col_cap > 0):
                # reset_config reuse: same train_data -> same store
                self.X = device_data
                self.sparse_col_cap = device_sparse_col_cap
                if sparse_kernel:
                    nc, e = (int(s) for s in device_data.ent_bin.shape)
                    self.sparse_device_bytes = 4 * (
                        2 * nc * e + nc
                        + 2 * int(device_data.fill.shape[0]) + 1)
                else:
                    self.sparse_device_bytes = 4 * (
                        3 * int(device_data.nz_row.shape[0])
                        + 2 * int(device_data.fill.shape[0]) + 1)
            else:
                nbins_dev = (self.group_bins
                             if train_data.bundle is not None
                             else self.num_bins)
                binned = train_data.binned
                if binned.shape[1] == 0:    # dummy column (see meta above)
                    binned = np.zeros((train_data.num_data, 1), np.uint8)
                    fill = np.zeros(1, np.int64)
                else:
                    fill = column_fill_bins(train_data.num_bin_arr,
                                            train_data.default_bin_arr,
                                            train_data.bundle)
                if sparse_kernel:
                    # auto_uniform: low-skew stores widen the entry
                    # chunk so each column is ONE MXU dot (sparse_mxu)
                    def build(b, fl, nb):
                        return build_chunked_store(b, fl, nb,
                                                   auto_uniform=True)
                else:
                    build = build_sparse_store
                self.X, self.sparse_col_cap, self.sparse_device_bytes = \
                    build(binned, fill, nbins_dev)
        elif (device_data is not None
                and device_packed_cols == self.packed_cols):
            self.X = device_data
        else:
            from .pack import pack4_host
            self._row_pad = (-train_data.num_data) % 1024
            X = None
            if not self.packed_cols:
                # out-of-core datasets page shard-by-shard to the device —
                # no padded full-size host copy is ever built
                X = paged_device_matrix(train_data, self._row_pad)
            if X is not None:
                self.X = X
            else:
                binned = train_data.binned
                if binned.shape[1] == 0:    # dummy column (see meta above)
                    binned = np.zeros((train_data.num_data, 1), np.uint8)
                n = binned.shape[0]
                self._row_pad = (-n) % 1024
                if self._row_pad:
                    binned = np.concatenate(
                        [binned, np.zeros((self._row_pad, binned.shape[1]),
                                          binned.dtype)])
                if self.packed_cols:
                    binned = pack4_host(binned)
                self.X = jnp.asarray(binned)
        if self._row_pad:
            self._ones = jnp.concatenate(
                [jnp.ones(train_data.num_data, self.dtype),
                 jnp.zeros(self._row_pad, self.dtype)])
        else:
            self._ones = jnp.ones(train_data.num_data, self.dtype)
        self._full_mask = jnp.ones(max(train_data.num_features, 1), dtype=bool)
        # CPU-interpret Pallas execution (tests / CI parity runs): a
        # forced wave-kernel mode off-TPU normally falls back to the XLA
        # wave path; tpu_pallas_interpret=true runs the ACTUAL Pallas
        # kernels through the interpreter instead, so fused-vs-staged
        # bit-identity and the tile-plan regressions are CPU-testable
        # end-to-end (tests/test_fused_iter.py, CI bench-smoke).  On TPU
        # the flag is meaningless — the compiled kernels run.
        self.pallas_interpret = bool(config.tpu_pallas_interpret)
        if self.pallas_interpret and jax.default_backend() == "tpu":
            Log.warning("tpu_pallas_interpret=true ignored on TPU (the "
                        "compiled Pallas kernels run)")
            self.pallas_interpret = False
        # ---- measured kernel autotune (ops/autotune.py).  Everything
        # resolved above — hist_mode, wave_width, hist_hilo,
        # wave_compact — is the heuristic PRIOR; under
        # tpu_autotune=measure/force on a real device, decide() probes
        # the 3-5 candidate cells for this shape bucket on the uploaded
        # bin matrix and the measured winner overrides the prior (the
        # winner is cached on disk, so one probe cost per shape bucket
        # per device kind).  Under off (the default) decide() only
        # records the prior decision on the timeline.
        from . import autotune as _at
        at_shape = _at.ShapeBucket(int(ncols), int(_bin_pad(nbins)),
                                   int(self.num_leaves),
                                   _at.row_bucket(train_data.num_data))
        at_prior = _at.Cell(self.hist_mode, int(self.wave_width),
                            bool(self.hist_hilo), self.wave_compact,
                            fused=False)
        at_pins = _at.Pins(
            # pins = explicit user choices + quality gates, never tuned
            kernel=str(config.tpu_histogram_mode) != "auto",
            width=(int(config.tpu_wave_width) > 0
                   or (_order_sensitive(config)
                       and self.wave_order != "exact")),
            precision=hp != "auto",
            compact="tpu_wave_compact" in config.raw,
            # an explicit tpu_fused_iter=on/off is a user decision the
            # tuner must not second-guess; auto leaves the staged/fused
            # flip a measured dimension (rev-2 cells)
            fused=str(config.tpu_fused_iter).strip().lower() != "auto")
        at_eligible = (growth == "wave" and psum_axis is None
                       and not sparse_on and self.dtype == jnp.float32
                       and self.hist_mode in WAVE_ONLY_MODES)
        at_probe = (self._make_autotune_probe(config)
                    if at_eligible else None)
        dec = _at.decide(config, at_shape, at_prior, at_pins,
                         at_eligible, probe=at_probe,
                         ct_allowed=psum_axis is None)
        self.autotune_mode, self.autotune_source = dec.mode, dec.source
        self._pending_events.extend(dec.events)
        # measured staged-vs-fused verdict for this shape bucket; the
        # booster's tpu_fused_iter=auto resolution consults it
        # (models/gbdt.py _resolve_fused_iter)
        self.fused_autotune = bool(dec.cell.fused)
        if dec.cell != at_prior:
            self.hist_mode = hist_mode = dec.cell.hist_mode
            self.wave_width = int(dec.cell.wave_width)
            self.hist_hilo = bool(dec.cell.hist_hilo)
            self.wave_compact = bool(dec.cell.compact)
        # Ordered-partition growth (grow.py): per-split cost is O(parent
        # segment) for the partition and O(child segment * F) for the
        # histogram — the reference's DataPartition + ordered-iteration
        # economics (data_partition.hpp:94-147, dense_bin.hpp:66-98) — so
        # the capacity-tier ladder pays at every shape.  Pallas histogram
        # kernels take the full-N mask form and keep the legacy path.
        self.row_capacities = (
            default_row_capacities(train_data.num_data + self._row_pad)
            if hist_mode not in ("pallas", "sparse",
                                 "sparse_mxu") + WAVE_ONLY_MODES
            else ())
        # distributed learners (psum_axis set) own their grow construction
        # in parallel/mesh.py — including the wave-vs-voting choice
        if growth == "wave" and psum_axis is None:
            from .wave import make_wave_jit
            core = make_wave_jit(
                self.num_leaves, self.num_bins, self.params,
                config.max_depth, self.wave_width, self.dtype, None,
                self.bundle_arrays is not None, self.group_bins,
                self.cache_hists, hist_mode,
                int(config.tpu_wave_chunk), self.packed_cols,
                self.sparse_col_cap, self.wave_order == "exact",
                self.wave_lookup, self.hist_hilo,
                self.wave_compact, self.pallas_interpret)
            meta, bund = self.meta, self.bundle_arrays
            # the transposed kernel's (F, N) matrix: materialized ONCE per
            # booster (X never changes across trees), not per dispatch;
            # the shared predicate keeps this in lockstep with the engine
            # gate so no dead (F, N) copy is pinned when the kernel won't
            # run
            from .wave import transposed_wave_active
            xt = (jnp.transpose(self.X)
                  if transposed_wave_active(hist_mode, self.dtype)
                  else None)

            def _grow(X, g, h, rm, m, _core=core, _meta=meta,
                      _bund=bund, _xt=xt):
                return _core(X, g, h, rm, m, _meta, _bund, Xt=_xt)

            # AOT hook for obs compile attribution: the wrapper itself is
            # not jitted, so expose the core's lowering over the observed
            # call args (obs/compile.py analyze_compiled)
            _grow._aot_lower = (
                lambda X, g, h, rm, m, _core=core, _meta=meta,
                _bund=bund, _xt=xt:
                _core.lower(X, g, h, rm, m, _meta, _bund, Xt=_xt))
            self._grow = _grow
        elif psum_axis is None:
            # cached jitted core: a second booster/fold with the same
            # static config reuses the compiled executable (meta/bundle
            # are call-time args, ops/grow.py make_grow_jit)
            from .grow import make_grow_jit
            core = make_grow_jit(self.num_leaves, self.num_bins,
                                 self.params, config.max_depth, hist_mode,
                                 self.dtype, None, None, 0, 1,
                                 self.bundle_arrays is not None,
                                 self.group_bins, self.row_capacities,
                                 self.cache_hists, 15, self.packed_cols,
                                 self.sparse_col_cap)
            meta, bund = self.meta, self.bundle_arrays

            def _grow(X, g, h, rm, m, _core=core, _meta=meta, _bund=bund):
                return _core(X, g, h, rm, m, _meta, _bund)

            _grow._aot_lower = (
                lambda X, g, h, rm, m, _core=core, _meta=meta, _bund=bund:
                _core.lower(X, g, h, rm, m, _meta, _bund))
            self._grow = _grow
        elif sparse_on:
            # the data-parallel mesh subclass owns the sparse grow (it
            # has the col_cap and the sharded store); a base fallback
            # with col_cap=0 would silently misroute every partition
            self._grow = None
        else:
            # the distributed base fallback is the exact engine; the
            # wave-only pallas_t kernel maps to onehot here — mesh
            # subclasses that run the wave schedule install their own
            # pallas_t-capable grow right after this constructor
            base_mode = ("onehot" if hist_mode in WAVE_ONLY_MODES
                         else hist_mode)
            self._grow = make_grow_fn(self.num_leaves, self.num_bins,
                                      self.meta, self.params,
                                      config.max_depth, hist_mode=base_mode,
                                      hist_dtype=self.dtype,
                                      psum_axis=psum_axis,
                                      bundle=self.bundle_arrays,
                                      group_bins=self.group_bins,
                                      row_capacities=self.row_capacities,
                                      cache_hists=self.cache_hists)
        # feature_fraction RNG persists across trees
        # (serial_tree_learner.cpp:40-96 Init + :257-275 BeforeTrain)
        self._feature_rng = Random(config.feature_fraction_seed)

    # --------------------------------------------------------- autotuning
    def _make_autotune_probe(self, config):
        """Probe factory for ops/autotune.py: builds a candidate cell's
        wave core STANDALONE — same statics as the production core
        below except the cell's tuned dimensions — against the real
        uploaded bin matrix with synthetic deterministic gradients, and
        returns a nullary run closure the tuner times.  make_wave_jit
        is lru-cached, so the winning cell's probe compile is reused by
        the production core.

        ``cell.fused`` flips the probe between the two iteration
        dataflows the booster can submit (models/gbdt.py): the staged
        chain times gradients / grow / score-update as separate
        dispatches (host glue between them included in what the timer
        sees), the fused chain times the whole step as ONE jitted entry
        — the exact shape ops/fused_iter.py compiles — so the
        staged-vs-fused flip is genuinely measured, not guessed."""
        from .wave import make_wave_jit, transposed_wave_active
        from .partition import score_update_impl
        from ..obs.timers import fence

        def probe(cell):
            core = make_wave_jit(
                self.num_leaves, self.num_bins, self.params,
                config.max_depth, int(cell.wave_width), self.dtype,
                None, self.bundle_arrays is not None, self.group_bins,
                self.cache_hists, cell.hist_mode,
                int(config.tpu_wave_chunk), self.packed_cols,
                self.sparse_col_cap, self.wave_order == "exact",
                self.wave_lookup, bool(cell.hist_hilo),
                bool(cell.compact), self.pallas_interpret)
            xt = (jnp.transpose(self.X)
                  if transposed_wave_active(cell.hist_mode, self.dtype)
                  else None)
            n = int(self._ones.shape[0])
            rm, mask = self._ones, self._full_mask
            meta, bund = self.meta, self.bundle_arrays
            # deterministic, real-shaped iteration state: an L2-style
            # in-graph gradient from the running score against a
            # sign-varying target, so splits have gain and the wave
            # actually sweeps
            tgt = jnp.asarray(np.linspace(-1.0, 1.0, n), self.dtype)
            score0 = jnp.zeros((n,), self.dtype)
            scale = jnp.asarray(0.1, self.dtype)

            def _grad(score):
                return score - tgt, jnp.full((n,), 0.25, self.dtype)

            if cell.fused:
                def _step(score):
                    g, h = _grad(score)
                    tree, leaf_id = core(self.X, g, h, rm, mask, meta,
                                         bund, Xt=xt)
                    return score_update_impl(score, leaf_id,
                                             tree.leaf_value, scale)

                step = jax.jit(_step)

                def run():
                    # measurement-scoped sync: the tuner needs the wall
                    # time of the finished program.  Production
                    # iterations never block mid-tree (bench.py --dry
                    # asserts a zero fence-count delta); every probe
                    # sync goes through obs/timers.fence so that audit
                    # has a single counted choke point.
                    fence(step(score0))
            else:
                grad_fn = jax.jit(_grad)
                upd = jax.jit(score_update_impl)

                def run():
                    # the staged chain the booster submits: three
                    # separate dispatches with the host glue between
                    # them inside the timed window
                    g, h = grad_fn(score0)
                    tree, leaf_id = core(self.X, g, h, rm, mask, meta,
                                         bund, Xt=xt)
                    fence(upd(score0, leaf_id, tree.leaf_value, scale))

            return run

        return probe

    # -------------------------------------------------------- observability
    def set_observer(self, obs) -> None:
        self._obs = obs
        pend = getattr(self, "_pending_events", None)
        if pend and getattr(obs, "enabled", False):
            # construction-time events (band escapes, autotune
            # probes/decision) recorded now that the run header exists
            for ev, fields in pend:
                obs.event(ev, **fields)
            del pend[:]

    def obs_info(self) -> dict:
        """Static run-header context: which engines/knobs this learner
        resolved to (the 'auto' params post-resolution)."""
        return {
            "learner": type(self).__name__,
            "growth": getattr(self, "growth", ""),
            "hist_mode": getattr(self, "hist_mode", ""),
            "wave_width": int(getattr(self, "wave_width", 0) or 0),
            "wave_order": getattr(self, "wave_order", ""),
            "wave_lookup": getattr(self, "wave_lookup", ""),
            "hist_hilo": bool(getattr(self, "hist_hilo", True)),
            "wave_compact": bool(getattr(self, "wave_compact", False)),
            "autotune_mode": getattr(self, "autotune_mode", "off"),
            "autotune_source": getattr(self, "autotune_source", ""),
            "fused": bool(getattr(self, "fused_autotune", False)),
            "pallas_interpret": bool(getattr(self, "pallas_interpret",
                                             False)),
            "packed_cols": int(getattr(self, "packed_cols", 0) or 0),
            "num_leaves": int(self.num_leaves),
            "num_bins": int(self.num_bins),
            "dtype": jnp.dtype(self.dtype).name,
            "cache_hists": bool(getattr(self, "cache_hists", False)),
        }

    # ------------------------------------------------------------ internals
    def sample_feature_mask(self):
        f = self.train_data.num_features
        if self.config.feature_fraction >= 1.0 or f == 0:
            return self._full_mask
        used_cnt = int(f * self.config.feature_fraction)
        idx = self._feature_rng.sample(f, used_cnt)
        mask = np.zeros(f, dtype=bool)
        mask[idx] = True
        return jnp.asarray(mask)

    # ----------------------------------------------------------------- train
    def train_device(self, grad, hess, row_mult=None,
                     feature_mask=None) -> Tuple[TreeArrays, jnp.ndarray]:
        """Grow one tree fully on device; no host synchronization."""
        if row_mult is None:
            row_mult = self._ones
        else:
            row_mult = jnp.asarray(row_mult, self.dtype)
            if self._row_pad:
                row_mult = jnp.concatenate(
                    [row_mult, jnp.zeros(self._row_pad, self.dtype)])
        if feature_mask is None:
            feature_mask = self.sample_feature_mask()
        grad = jnp.asarray(grad, self.dtype)
        hess = jnp.asarray(hess, self.dtype)
        if self._row_pad:
            grad = jnp.concatenate(
                [grad, jnp.zeros(self._row_pad, self.dtype)])
            hess = jnp.concatenate(
                [hess, jnp.zeros(self._row_pad, self.dtype)])
        obs = self._obs
        args = (self.X, grad, hess, row_mult, feature_mask)
        obs.entry_args("tree_grow", self._grow, args,
                       names=("X", "grad", "hess", "row_mult",
                              "feature_mask"))
        t0 = obs.entry_start()
        tree, leaf_id = self._grow(*args)
        obs.entry_end("tree_grow", t0, (tree, leaf_id))
        if self._row_pad:
            leaf_id = leaf_id[:self.train_data.num_data]
        return tree, leaf_id

    def train(self, grad, hess, row_mult=None) -> Tuple[Tree, jnp.ndarray]:
        dev_tree, leaf_id = self.train_device(grad, hess, row_mult)
        tree = self.materialize(dev_tree)
        return tree, leaf_id

    def materialize(self, dev_tree: TreeArrays) -> Tree:
        from ..obs.timers import fenced_get
        return materialize_tree(fenced_get(dev_tree), self.train_data,
                                self.num_leaves)

    # ------------------------------------------------------------ DART refit
    def fit_by_existing_tree(self, tree: Tree, grad, hess) -> Tree:
        """Refit leaf outputs of an existing structure on new gradients
        (SerialTreeLearner::FitByExistingTree, serial_tree_learner.cpp:225-250).
        """
        leaves = self._leaf_index_binned(tree)
        grad = np.asarray(grad, dtype=np.float64)
        hess = np.asarray(hess, dtype=np.float64)
        l1, l2 = self.config.lambda_l1, self.config.lambda_l2
        for leaf in range(tree.num_leaves):
            m = leaves == leaf
            sum_g = grad[m].sum()
            sum_h = hess[m].sum()
            reg = max(abs(sum_g) - l1, 0.0)
            out = -np.sign(sum_g) * reg / (sum_h + l2 + 1e-15)
            tree.set_leaf_value(leaf, out)
        return tree

    def _leaf_index_binned(self, tree: Tree) -> np.ndarray:
        binned = self.train_data.binned
        n = binned.shape[0]
        if tree.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        bund = self.train_data.bundle
        node = np.zeros(n, dtype=np.int32)
        active = node >= 0
        while active.any():
            idx = np.nonzero(active)[0]
            nd = node[idx]
            f = tree.split_feature_inner[nd]
            if bund is None:
                b = binned[idx, f].astype(np.int64)
            else:
                v = binned[idx, bund.group_of[f]].astype(np.int64)
                off = bund.bin_off[f]
                in_range = (v >= off) & (v < off + bund.bin_span[f])
                b = np.where(in_range, v - off + bund.bin_adj[f],
                             self.train_data.default_bin_arr[f])
            th = tree.threshold_in_bin[nd]
            is_cat = tree.decision_type[nd] == 1
            go_left = np.where(is_cat, b == th, b <= th)
            is_def = b == tree.zero_bin[nd]
            dbz = tree.default_bin_for_zero[nd]
            def_left = np.where(is_cat, dbz == th, dbz <= th)
            go_left = np.where(is_def, def_left, go_left)
            node[idx] = np.where(go_left, tree.left_child[nd], tree.right_child[nd])
            active = node >= 0
        return (~node).astype(np.int32)


def materialize_tree(host_tree: TreeArrays, train_data: TrainingData,
                     max_leaves: int) -> Tree:
    """Device tree arrays -> models.Tree with real-valued thresholds.

    Real thresholds and default values are resolved host-side in float64
    (Dataset::RealThreshold, dataset.h:457-462) so the text model format
    keeps full precision.
    """
    nl = int(host_tree.num_leaves)
    tree = Tree(max(max_leaves, 2))
    tree.num_leaves = nl
    if nl <= 1:
        return tree
    ni = nl - 1
    tree.split_feature_inner[:ni] = host_tree.split_feature[:ni]
    tree.threshold_in_bin[:ni] = host_tree.threshold_bin[:ni]
    tree.default_bin_for_zero[:ni] = host_tree.default_bin_for_zero[:ni]
    tree.zero_bin[:ni] = host_tree.default_bin[:ni]
    tree.decision_type[:ni] = host_tree.is_cat[:ni].astype(np.int8)
    tree.has_categorical = bool(host_tree.is_cat[:ni].any())
    tree.left_child[:ni] = host_tree.left_child[:ni]
    tree.right_child[:ni] = host_tree.right_child[:ni]
    tree.split_gain[:ni] = host_tree.split_gain[:ni]
    tree.internal_value[:ni] = host_tree.internal_value[:ni]
    tree.internal_count[:ni] = host_tree.internal_count[:ni]
    tree.leaf_parent[:nl] = host_tree.leaf_parent[:nl]
    tree.leaf_value[:nl] = host_tree.leaf_value[:nl]
    tree.leaf_count[:nl] = host_tree.leaf_count[:nl]
    tree.leaf_depth[:nl] = host_tree.leaf_depth[:nl]
    tree.second_gain[:ni] = host_tree.second_gain[:ni]
    from ..utils.common import avoid_inf
    for i in range(ni):
        inner_f = int(host_tree.split_feature[i])
        mapper = train_data.feature_bin_mapper(inner_f)
        tree.split_feature[i] = train_data.real_feature_index(inner_f)
        # runner-up candidate resolved to the real feature index (the
        # split-audit margin surface; -1 = no competitor)
        sf_inner = int(host_tree.second_feature[i])
        tree.second_feature[i] = (train_data.real_feature_index(sf_inner)
                                  if sf_inner >= 0 else -1)
        tree.threshold[i] = avoid_inf(
            mapper.bin_to_value(int(host_tree.threshold_bin[i])))
        dbz = int(host_tree.default_bin_for_zero[i])
        if dbz != mapper.default_bin:
            # AvoidInf as in Tree::Split (tree.cpp:75)
            tree.default_value[i] = avoid_inf(mapper.bin_to_value(dbz))
        else:
            tree.default_value[i] = 0.0
    return tree
