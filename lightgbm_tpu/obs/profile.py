"""Programmatic jax.profiler trace windows over configured iterations.

``obs_trace_iters=a:b`` (python-range semantics: start at iteration
``a``, stop after iteration ``b-1``) plus ``obs_trace_dir`` captures a
perfetto trace of exactly the steady-state iterations — no bespoke
profiling script per investigation.  The start/stop calls go through
module-level ``_start_trace``/``_stop_trace`` wrappers so tests can
monkeypatch them and exercise the window logic without a real profiler.
"""
from __future__ import annotations

from ..utils.log import Log


def parse_trace_iters(spec):
    """'a:b' -> (a, b) with 0 <= a < b; '' -> None.  Fatal on malformed
    input — a silently dropped trace window wastes an on-chip run."""
    spec = str(spec or "").strip()
    if not spec:
        return None
    parts = spec.split(":")
    if len(parts) == 2:
        try:
            start, stop = int(parts[0]), int(parts[1])
        except ValueError:
            start = stop = -1
        if 0 <= start < stop:
            return (start, stop)
    Log.fatal("Bad obs_trace_iters %r (expected 'a:b' with 0 <= a < b, "
              "e.g. '10:13')", spec)


def _start_trace(trace_dir):
    import jax
    jax.profiler.start_trace(trace_dir)


def _stop_trace():
    import jax
    jax.profiler.stop_trace()


class TraceWindow:
    """Opens the profiler at iteration ``start`` and closes it after
    iteration ``stop - 1``; one window per run."""

    def __init__(self, iters_spec, trace_dir):
        self.window = parse_trace_iters(iters_spec)
        self.trace_dir = str(trace_dir or "")
        self.active = False
        self.done = False

    def maybe_start(self, it, obs):
        if (self.window is None or self.active or self.done
                or it < self.window[0]):
            return
        try:
            _start_trace(self.trace_dir)
        except Exception as exc:        # profiler busy / unsupported
            Log.warning("obs: could not start profiler trace: %s", exc)
            self.done = True
            return
        self.active = True
        obs.event("trace_window", action="start", dir=self.trace_dir, it=it)

    def maybe_stop(self, it, obs):
        if not self.active or it + 1 < self.window[1]:
            return
        self._stop(obs, it)

    def force_stop(self, obs):
        """Close a window left open at run end (early stop inside it)."""
        if self.active:
            self._stop(obs, -1)

    def _stop(self, obs, it):
        try:
            _stop_trace()
        except Exception as exc:
            Log.warning("obs: could not stop profiler trace: %s", exc)
        self.active = False
        self.done = True
        obs.event("trace_window", action="stop", dir=self.trace_dir, it=it)
