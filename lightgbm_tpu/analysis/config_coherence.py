"""Pass 4 — config coherence: code, registry and docs agree on params.

Three surfaces must agree: attribute reads on ``Config`` objects in the
code, the registry in ``utils/config.py`` (``Config._FIELDS`` /
``PARAMETER_SET`` / ``ALIAS_TABLE``), and the generated
``docs/Parameters.md``.  The registry is the single source of truth;
this pass makes the other two provably consistent with it, so a param
misspelling (silent ``AttributeError`` at train time) or a stale doc is
a lint failure, not a review nit.

* ``config-unknown-read``  — ``config.<name>`` where ``<name>`` is not a
  registered field or a real attribute of the Config class
* ``config-unknown-key``   — a string key into ``.raw`` that is neither
  canonical nor an alias
* ``config-registry``      — internal registry drift: alias targeting an
  unregistered key, a _FIELDS entry missing from PARAMETER_SET
* ``params-doc-stale``     — docs/Parameters.md differs from a fresh
  ``tools/gen_params_doc.py`` render

Receivers recognized as Config objects: names/attributes whose last
component is ``config`` or ``cfg`` (the repo's uniform convention), plus
anything annotated ``: Config``.
"""
from __future__ import annotations

import ast
import os
from typing import List, Set

from .core import Finding, SourceModule, dotted_name, str_const

PASS_NAME = "config"

RULES = {
    "config-unknown-read":
        "attribute read on a Config object that no registered field or "
        "class attribute provides",
    "config-unknown-key":
        "string key into Config.raw that is neither a canonical "
        "parameter nor an alias",
    "config-registry":
        "utils/config.py registry is internally inconsistent",
    "params-doc-stale":
        "docs/Parameters.md does not match a fresh "
        "tools/gen_params_doc.py render",
}

_RECEIVER_SUFFIXES = ("config", "cfg")
# a dotted receiver rooted at an external module is that module's own
# config object (jax.config.update(...)), not the repo Config
_FOREIGN_ROOTS = {"jax", "jnp", "lax", "np", "numpy", "scipy"}


def _registry():
    from ..utils import config as C
    return C


def _known_attrs() -> Set[str]:
    C = _registry()
    known = set(C.Config._FIELDS)
    # real API of the class (methods, properties, class attrs) and the
    # instance attrs __init__ materializes beyond _FIELDS
    known.update(a for a in dir(C.Config) if not a.startswith("__"))
    known.update(("raw",))
    return known


def _is_config_receiver(node: ast.AST) -> bool:
    name = dotted_name(node)
    if not name:
        return False
    if "." in name and name.split(".", 1)[0] in _FOREIGN_ROOTS:
        return False
    last = name.rsplit(".", 1)[-1]
    return last in _RECEIVER_SUFFIXES


def _check_reads(mod: SourceModule, known: Set[str],
                 findings: List[Finding]) -> None:
    C = _registry()
    for node in ast.walk(mod.tree):
        # config.<attr> (read or write — a write to an unknown field is
        # the same misspelling one assignment earlier)
        if isinstance(node, ast.Attribute) \
                and _is_config_receiver(node.value) \
                and not node.attr.startswith("_") \
                and node.attr not in known:
            findings.append(Finding(
                "config-unknown-read", PASS_NAME, mod.path, node.lineno,
                "config.%s is not a registered parameter or Config "
                "attribute" % node.attr,
                "register the field in Config._FIELDS "
                "(utils/config.py) or fix the name"))
        # config.raw["key"] / config.raw.get("key", ...)
        key_node = None
        if isinstance(node, ast.Subscript) \
                and isinstance(node.value, ast.Attribute) \
                and node.value.attr == "raw" \
                and _is_config_receiver(node.value.value):
            key_node = node.slice
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("get", "pop", "setdefault") \
                and isinstance(node.func.value, ast.Attribute) \
                and node.func.value.attr == "raw" \
                and _is_config_receiver(node.func.value.value) \
                and node.args:
            key_node = node.args[0]
        if key_node is not None:
            key = str_const(key_node)
            if key is not None and key not in C.PARAMETER_SET \
                    and key not in C.ALIAS_TABLE:
                findings.append(Finding(
                    "config-unknown-key", PASS_NAME, mod.path,
                    node.lineno,
                    "raw[%r] is neither a canonical parameter nor an "
                    "alias" % key,
                    "add the key to utils/config.py (PARAMETER_SET or "
                    "ALIAS_TABLE) or fix the spelling"))


def _check_registry(findings: List[Finding]) -> None:
    C = _registry()
    path = "lightgbm_tpu/utils/config.py"
    for alias, target in sorted(C.ALIAS_TABLE.items()):
        if target not in C.PARAMETER_SET:
            findings.append(Finding(
                "config-registry", PASS_NAME, path, 0,
                "alias %r resolves to unregistered parameter %r"
                % (alias, target),
                "register the target in PARAMETER_SET"))
    for field in sorted(C.Config._FIELDS):
        if field not in C.PARAMETER_SET:
            findings.append(Finding(
                "config-registry", PASS_NAME, path, 0,
                "Config._FIELDS[%r] is missing from PARAMETER_SET"
                % field,
                "every materialized field must be a declared parameter"))


def _check_doc(repo_root: str, findings: List[Finding]) -> None:
    import importlib.util
    gen_path = os.path.join(repo_root, "tools", "gen_params_doc.py")
    doc_path = os.path.join(repo_root, "docs", "Parameters.md")
    if not os.path.exists(gen_path):
        return                       # fixture trees have no tools/
    spec = importlib.util.spec_from_file_location("_gen_params_doc",
                                                  gen_path)
    gen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gen)
    fresh = gen.render()
    try:
        with open(doc_path, encoding="utf-8") as f:
            on_disk = f.read()
    except OSError:
        on_disk = ""
    if fresh != on_disk:
        findings.append(Finding(
            "params-doc-stale", PASS_NAME, "docs/Parameters.md", 0,
            "docs/Parameters.md is stale against utils/config.py",
            "regenerate: python tools/gen_params_doc.py"))


def run(modules: List[SourceModule], repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    known = _known_attrs()
    for mod in modules:
        if mod.path.endswith("utils/config.py"):
            continue                 # the registry defines, not reads
        _check_reads(mod, known, findings)
    _check_registry(findings)
    _check_doc(repo_root, findings)
    return findings
