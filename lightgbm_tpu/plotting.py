"""Plotting utilities — parity with python-package/plotting.py:1-428
(plot_importance, plot_metric, plot_tree, create_tree_digraph)."""
from __future__ import annotations

import numpy as np

from .basic import Booster
from .sklearn import LGBMModel


def _check_not_tuple_of_2_elements(obj, obj_name="obj"):
    if not isinstance(obj, tuple) or len(obj) != 2:
        raise TypeError("%s must be a tuple of 2 elements." % obj_name)


def _to_booster(booster):
    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be Booster or LGBMModel.")


def _importance_history_from(source, importance_type):
    """Importance trajectory from any of the supported sources: an obs
    timeline JSONL path, a list of event dicts (Booster.telemetry()), an
    importance_history() result, or a Booster/LGBMModel with telemetry.
    None means 'not a history source' (plain feature_importance plot)."""
    from .obs.model import importance_history
    if isinstance(source, str):
        from .obs.query import last_run, load_timeline
        return importance_history(last_run(load_timeline(source)),
                                  importance_type)
    if isinstance(source, (list, tuple)):
        src = list(source)
        if src and isinstance(src[0], dict) and "importance" in src[0]:
            return src                      # already a history result
        return importance_history(src, importance_type)
    if isinstance(source, (Booster, LGBMModel)):
        hist = _to_booster(source).importance_history(importance_type)
        return hist or None                 # no events -> snapshot plot
    return None


def plot_importance(booster, ax=None, height=0.2, xlim=None, ylim=None,
                    title="Feature importance", xlabel="Feature importance",
                    ylabel="Features", importance_type="split",
                    max_num_features=None, ignore_zero=True, figsize=None,
                    grid=True, **kwargs):
    """Bar chart of feature importances (plotting.py:18-112).

    ``booster`` may also be an obs timeline path, a telemetry event list,
    or a ``Booster.importance_history()`` result — the bars then show the
    final ``importance`` snapshot recorded by ``obs_importance_every``
    (see plot_importance_history for the trajectory view)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot importance.")
    if isinstance(booster, (str, list, tuple)):
        hist = _importance_history_from(booster, importance_type)
        if not hist:
            raise ValueError("No importance events in the timeline (train "
                             "with obs_importance_every=N)")
        final = hist[-1]["importance"]
        nf = (max(final) + 1) if final else 0
        importance = np.zeros(nf)
        for f, v in final.items():
            importance[f] = v
        feature_names = ["Column_%d" % i for i in range(nf)]
    else:
        booster = _to_booster(booster)
        importance = booster.feature_importance(importance_type)
        feature_names = booster.feature_name()
    tuples = sorted(zip(feature_names, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    if not tuples:
        raise ValueError("Cannot plot trees with zero importance")
    labels, values = zip(*tuples)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y, str(x), va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
        ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_importance_history(source, importance_type="gain", ax=None,
                            max_num_features=10, xlim=None, ylim=None,
                            title="Feature importance evolution",
                            xlabel="Iterations", ylabel="auto",
                            figsize=None, grid=True, **kwargs):
    """Per-feature importance trajectories over training iterations.

    ``source``: an obs timeline JSONL path, a telemetry event list, an
    ``importance_history()`` result, or a Booster trained with
    ``obs_importance_every=N``.  One line per feature, top
    ``max_num_features`` by final importance."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot importance.")
    hist = _importance_history_from(source, importance_type)
    if not hist:
        raise ValueError("No importance events in the source (train with "
                         "obs_importance_every=N)")
    final = hist[-1]["importance"]
    top = sorted(final, key=lambda f: -final[f])
    if max_num_features is not None and max_num_features > 0:
        top = top[:max_num_features]
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    its = [h["it"] for h in hist]
    for f in top:
        ax.plot(its, [h["importance"].get(f, 0.0) for h in hist],
                label="Column_%d" % f, **kwargs)
    ax.legend(loc="best")
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
        ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    ax.set_ylabel("%s importance" % importance_type
                  if ylabel == "auto" else ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric=None, dataset_names=None, ax=None, xlim=None,
                ylim=None, title="Metric during training", xlabel="Iterations",
                ylabel="auto", figsize=None, grid=True):
    """Plot metric curves from evals_result (plotting.py:114-214)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot metric.")
    if isinstance(booster, LGBMModel):
        eval_results = dict(booster.evals_result_)
    elif isinstance(booster, dict):
        eval_results = dict(booster)
    else:
        raise TypeError("booster must be dict or LGBMModel.")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    names = dataset_names or list(eval_results.keys())
    first = eval_results[names[0]]
    if metric is None:
        metric = list(first.keys())[0]
    for name in names:
        if metric in eval_results.get(name, {}):
            results = eval_results[name][metric]
            ax.plot(range(len(results)), results, label=name)
    ax.legend(loc="best")
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    ax.set_ylabel(metric if ylabel == "auto" else ylabel)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster, tree_index=0, show_info=None, precision=3,
                        name=None, comment=None, **kwargs):
    """Graphviz digraph of one tree (plotting.py:216-330)."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("You must install graphviz to plot tree.")
    booster = _to_booster(booster)
    model = booster.dump_model()
    if tree_index >= len(model["tree_info"]):
        raise IndexError("tree_index is out of range.")
    tree_info = model["tree_info"][tree_index]
    show_info = show_info or []
    graph = Digraph(name=name, comment=comment, **kwargs)

    def add(node, parent=None, decision=None):
        if "split_index" in node:
            nid = "split%d" % node["split_index"]
            label = "split_feature_index: %d" % node["split_feature"]
            label += r"\nthreshold: %s" % round(node["threshold"], precision)
            for info in show_info:
                if info in node:
                    label += r"\n%s: %s" % (info, round(float(node[info]), precision))
            graph.node(nid, label=label)
            add(node["left_child"], nid, "yes")
            add(node["right_child"], nid, "no")
        else:
            nid = "leaf%d" % node["leaf_index"]
            label = "leaf_index: %d" % node["leaf_index"]
            label += r"\nleaf_value: %s" % round(node["leaf_value"], precision)
            if "leaf_count" in show_info and "leaf_count" in node:
                label += r"\nleaf_count: %d" % node["leaf_count"]
            graph.node(nid, label=label)
        if parent is not None:
            graph.edge(parent, nid, decision)
        return nid

    add(tree_info["tree_structure"])
    return graph


def plot_tree(booster, ax=None, tree_index=0, figsize=None, graph_attr=None,
              node_attr=None, edge_attr=None, show_info=None, precision=3):
    """Render one tree with matplotlib via graphviz (plotting.py:332-428)."""
    try:
        import matplotlib.pyplot as plt
        import matplotlib.image as image
    except ImportError:
        raise ImportError("You must install matplotlib to plot tree.")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    graph = create_tree_digraph(booster=booster, tree_index=tree_index,
                                show_info=show_info, precision=precision,
                                graph_attr=graph_attr, node_attr=node_attr,
                                edge_attr=edge_attr)
    import io
    s = io.BytesIO()
    s.write(graph.pipe(format="png"))
    s.seek(0)
    img = image.imread(s)
    ax.imshow(img)
    ax.axis("off")
    return ax
