"""AOT-compiled predict executables for the serving tier.

``Booster.predict`` goes through ``jax.jit``: every call pays Python
dispatch, signature hashing, and — on a novel batch shape — a full XLA
compile.  A serving process cannot afford any of that on the hot path.
``PredictExecutableCache`` therefore compiles each predict program ONCE,
ahead of time, and steady-state scoring calls the compiled executable
directly:

* programs are keyed by ``(batch bucket, num_used trees, k, convert)``;
  request rows round up to a power-of-two bucket between
  ``serve_bucket_min`` and ``serve_max_batch``, bounding the cache at
  ``log2(max_batch / bucket_min) + 1`` programs per route;
* the encoded inputs (the int32 rank matrix + zero-range mask from
  ops/predict.py) are DONATED to the executable on accelerator
  backends — the runtime reuses their buffers for outputs instead of
  allocating per request;
* the tree stack is replicated to every local device once via
  ``NamedSharding`` (the GSPMD replication pattern: data parallel in
  rows, model broadcast), so multi-chip hosts score one bucket
  cooperatively with zero collectives;
* objective conversion (sigmoid / softmax) is fused into the executable
  when the objective's ``convert_output`` is one of the closed forms, so
  a converted prediction is still a single program;
* every compile is announced through the observer as ``compile`` +
  ``compile_attr`` events with a per-bucket entry name
  (``serve_predict_b<bucket>[_conv]``) — each entry compiles exactly
  once, which is precisely what ``obs recompiles --check`` asserts.

Leaf routing is bit-equal to the host f64 predictor (rank encoding);
values accumulate in f32 with Kahan compensation — and because every
row's arithmetic is element-wise and independent of its neighbors, a row
scores bit-identically whatever bucket it lands in.  That invariant is
what lets the microbatcher coalesce freely (tests/test_serve.py pins
it).
"""
from __future__ import annotations

import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from ..obs.compile import arg_signature, parse_compiled, render_signature
from ..obs.events import NULL_OBSERVER
from ..obs.metrics import REGISTRY
from ..obs.timers import fenced_get
from ..ops import predict as dev_predict
from ..utils.config import _TRUE_SET
from ..utils.log import Log


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << max(int(n) - 1, 0).bit_length()


def _fused_conversion(objective):
    """('sigmoid', scale) | ('softmax', None) | None (identity) — or the
    string 'host' when the objective's convert_output has no fusable
    closed form and must run on the host after the raw program."""
    from ..objectives import (BinaryLogloss, MulticlassOVA,
                              MulticlassSoftmax, ObjectiveFunction)
    if objective is None:
        return None
    if isinstance(objective, (BinaryLogloss, MulticlassOVA)):
        return ("sigmoid", float(objective.sigmoid))
    if isinstance(objective, MulticlassSoftmax):
        return ("softmax", None)
    if type(objective).convert_output is ObjectiveFunction.convert_output:
        return None                      # identity: converted == raw
    return "host"


class PredictExecutableCache:
    """AOT predict programs over a frozen model snapshot.

    Construction packs the GBDT's first ``num_used`` trees into the
    stacked ranked representation (raises ValueError exactly when the
    host fallback must serve instead — mixed categorical/numerical
    feature use); compiles happen lazily per bucket (or eagerly via
    ``warmup``) and are counted, so a serving loop can assert the
    steady state compiles nothing (``steady_state_compiles``).
    """

    def __init__(self, gbdt, num_iteration: int = -1, num_features=None,
                 devices=None, donate: str = "auto", bucket_min: int = 64,
                 max_batch: int = 8192, observer=None):
        gbdt._materialize()
        self.k = int(gbdt.num_tree_per_iteration)
        self.num_used = int(gbdt._used_trees(num_iteration))
        self.objective = gbdt.objective
        self._conv = _fused_conversion(gbdt.objective)
        if num_features is None:
            mf = 0
            for t in gbdt.models[:self.num_used]:
                ni = t.num_leaves - 1
                if ni > 0:
                    mf = max(mf, int(t.split_feature[:ni].max()) + 1)
            num_features = max(mf, 1)
        self.num_features = int(num_features)
        self.rp = dev_predict.build_ranked_predictor(
            gbdt.models[:self.num_used], self.k, self.num_features)
        if self.num_features < self.rp.max_feature + 1:
            raise ValueError(
                "num_features=%d but the model splits on feature %d"
                % (self.num_features, self.rp.max_feature))
        self.devices = list(devices) if devices else jax.local_devices()
        self.backend = self.devices[0].platform
        self.donate = (bool(self.backend != "cpu")
                       if str(donate).strip().lower() == "auto"
                       else str(donate).strip().lower() in _TRUE_SET)
        self.bucket_min = max(1, int(bucket_min))
        self.max_batch = max(self.bucket_min, int(max_batch))
        self.observer = observer if observer is not None else NULL_OBSERVER
        self._exe = {}                   # (bucket, convert) -> Compiled
        self._lock = threading.Lock()
        # stage decomposition of the LAST predict_batch call (encode /
        # pad / execute / convert seconds) — read by the serve worker
        # right after the call to label request trace spans.  Worker-
        # thread state, like the batch itself: concurrent predict_batch
        # callers should not share one cache instance's spans.
        self.last_spans = {}
        self.compiles = 0
        self._warm_compiles = None       # set by mark_warm()
        self._mesh_ctx = None
        if len(self.devices) > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ..parallel.mesh import DATA_AXIS, make_data_mesh
            mesh = make_data_mesh(self.devices)
            repl = NamedSharding(mesh, P())
            rows = NamedSharding(mesh, P(DATA_AXIS, None))
            self._mesh_ctx = (mesh, repl, rows)
            self._dev = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, repl), self.rp.dev)
        else:
            self._dev = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, self.devices[0]), self.rp.dev)

    # ------------------------------------------------------------ buckets
    def bucket_for(self, n: int) -> int:
        """Power-of-two bucket in [bucket_min, max_batch], rounded up to
        a device-mesh multiple so rows shard evenly."""
        b = min(max(next_pow2(max(n, 1)), self.bucket_min), self.max_batch)
        ndev = len(self.devices)
        return b + (-b) % ndev

    def mark_warm(self):
        """Declare warmup over: compiles from here on are steady-state
        violations (``steady_state_compiles`` counts them)."""
        self._warm_compiles = self.compiles

    @property
    def steady_state_compiles(self) -> int:
        if self._warm_compiles is None:
            return 0
        return self.compiles - self._warm_compiles

    # ----------------------------------------------------------- compile
    def _entry_name(self, bucket: int, convert: bool) -> str:
        return "serve_predict_b%d%s" % (bucket,
                                        "_conv" if convert else "")

    def _build(self, bucket: int, convert: bool):
        k, conv = self.k, (self._conv if convert else None)
        if conv == "host":               # fuse nothing; convert after
            conv = None

        def run(dev, V, D):
            score = dev_predict._ranked_predict_impl(dev, V, D, k)
            if conv is not None:
                kind, scale = conv
                if kind == "sigmoid":
                    score = 1.0 / (1.0 + jnp.exp(-scale * score))
                else:
                    score = jax.nn.softmax(score, axis=-1)
            return score

        donate = (1, 2) if self.donate else ()
        dev_avals = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), self._dev)
        if self._mesh_ctx is not None:
            from jax.sharding import PartitionSpec as P
            from jax import lax
            from ..parallel.mesh import DATA_AXIS, _shard_map_compat
            mesh, repl, rows_sh = self._mesh_ctx

            def local(dev, V, D):
                score = dev_predict._ranked_predict_impl(
                    dev, V, D, k, vary_axis=DATA_AXIS)
                if conv is not None:
                    kind, scale = conv
                    if kind == "sigmoid":
                        score = 1.0 / (1.0 + jnp.exp(-scale * score))
                    else:
                        score = jax.nn.softmax(score, axis=-1)
                return score

            checked = hasattr(lax, "pcast") or hasattr(lax, "pvary")
            fn = jax.jit(_shard_map_compat(
                local, mesh,
                in_specs=(P(), P(DATA_AXIS, None), P(DATA_AXIS, None)),
                out_specs=P(DATA_AXIS, None), checked=checked),
                donate_argnums=donate)
            dev_avals = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                               sharding=repl), self._dev)
            V_aval = jax.ShapeDtypeStruct((bucket, self.num_features),
                                          jnp.int32, sharding=rows_sh)
            D_aval = jax.ShapeDtypeStruct((bucket, self.num_features),
                                          jnp.bool_, sharding=rows_sh)
        else:
            fn = jax.jit(run, donate_argnums=donate)
            V_aval = jax.ShapeDtypeStruct((bucket, self.num_features),
                                          jnp.int32)
            D_aval = jax.ShapeDtypeStruct((bucket, self.num_features),
                                          jnp.bool_)
        t0 = time.perf_counter()
        compiled = fn.lower(dev_avals, V_aval, D_aval).compile()
        dt = time.perf_counter() - t0
        self.compiles += 1
        entry = self._entry_name(bucket, convert)
        REGISTRY.counter(
            "lgbm_serve_compiles_total",
            "predict executables AOT-compiled by the serving tier").inc()
        REGISTRY.histogram(
            "lgbm_serve_compile_seconds",
            "AOT lower+compile time per serving executable").observe(dt)
        obs = self.observer
        if obs.enabled:
            sig = arg_signature((dev_avals, V_aval, D_aval),
                                names=("trees", "V", "D"),
                                donate=set(donate))
            fields = {"entry": entry, "n_compiles": 1,
                      "sig": render_signature(sig), "sig_compiles": 1,
                      "diff": []}
            fields.update(parse_compiled(compiled))
            obs.event("compile", entry=entry, first_call_s=dt, fenced=True)
            obs.event("compile_attr", **fields)
        Log.debug("serve: compiled %s in %.3fs (donate=%s, devices=%d)",
                  entry, dt, self.donate, len(self.devices))
        if self._warm_compiles is not None:
            Log.warning("serve: steady-state compile of %s — warm the "
                        "bucket ladder before taking traffic", entry)
        return compiled

    def get(self, bucket: int, convert: bool = True):
        """The compiled program for one bucket (compile on first use)."""
        key = (int(bucket), bool(convert))
        exe = self._exe.get(key)
        if exe is None:
            with self._lock:
                exe = self._exe.get(key)
                if exe is None:
                    exe = self._build(*key)
                    self._exe[key] = exe
        return exe

    def warmup(self, sizes=(), convert: bool = True):
        """Pre-compile the buckets covering ``sizes`` (row counts or
        bucket values); returns the sorted bucket list compiled."""
        buckets = sorted({self.bucket_for(int(s)) for s in sizes})
        for b in buckets:
            self.get(b, convert)
        return buckets

    # ------------------------------------------------------------ execute
    def normalize(self, features) -> np.ndarray:
        """(n, num_features) f64 view of a request: 1-D rows promote to
        one row; wider matrices slice down; narrower ones that still
        cover every used feature zero-pad (unread columns)."""
        X = np.asarray(features, np.float64)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if X.shape[1] < self.rp.max_feature + 1:
            raise ValueError(
                "request has %d features; the model uses feature index %d"
                % (X.shape[1], self.rp.max_feature))
        if X.shape[1] > self.num_features:
            X = X[:, :self.num_features]
        elif X.shape[1] < self.num_features:
            X = np.concatenate(
                [X, np.zeros((X.shape[0],
                              self.num_features - X.shape[1]))], axis=1)
        return np.ascontiguousarray(X)

    def encode(self, features):
        """Host-side rank encoding of a normalized request block."""
        return dev_predict.rank_encode(self.rp, features)

    def run_encoded(self, V, D, n: int, convert: bool = True,
                    spans=None) -> np.ndarray:
        """Score ``n`` encoded rows through the bucket executable:
        pad to the bucket, execute, slice.  Returns (n, k) f64.
        ``spans`` (a dict) accumulates the stage decomposition —
        ``pad_s`` (bucket padding), ``execute_s`` (transfers + compiled
        program), ``convert_s`` (host-side objective conversion)."""
        t0 = time.perf_counter()
        bucket = self.bucket_for(n)
        exe = self.get(bucket, convert)
        pad = bucket - n
        if pad:
            V = np.concatenate(
                [V, np.zeros((pad, V.shape[1]), V.dtype)])
            D = np.concatenate(
                [D, np.zeros((pad, D.shape[1]), D.dtype)])
        t1 = time.perf_counter()
        if self._mesh_ctx is not None:
            rows_sh = self._mesh_ctx[2]
            Vd = jax.device_put(np.ascontiguousarray(V), rows_sh)
            Dd = jax.device_put(np.ascontiguousarray(D), rows_sh)
        else:
            Vd = jax.device_put(V, self.devices[0])
            Dd = jax.device_put(D, self.devices[0])
        out = np.asarray(fenced_get(exe(self._dev, Vd, Dd))[:n],
                         np.float64)
        t2 = time.perf_counter()
        if convert and self._conv == "host":
            out = np.asarray(self.objective.convert_output(
                out if self.k > 1 else out[:, 0]), np.float64)
            out = out.reshape(n, self.k) if self.k == 1 else out
        if spans is not None:
            t3 = time.perf_counter()
            spans["pad_s"] = spans.get("pad_s", 0.0) + (t1 - t0)
            spans["execute_s"] = spans.get("execute_s", 0.0) + (t2 - t1)
            if t3 - t2 > 0:
                spans["convert_s"] = spans.get("convert_s", 0.0) \
                    + (t3 - t2)
        return out

    def predict_batch(self, features, convert: bool = True) -> np.ndarray:
        """Normalize + encode + execute, chunking requests larger than
        ``max_batch`` through the top bucket.  Returns (n, k) f64.
        Refreshes ``last_spans`` with this call's stage decomposition."""
        spans = {}
        X = self.normalize(features)
        n = X.shape[0]
        out = np.empty((n, self.k), np.float64)
        for lo in range(0, max(n, 1), self.max_batch):
            part = X[lo:lo + self.max_batch]
            if part.shape[0] == 0:
                break
            te = time.perf_counter()
            V, D = self.encode(part)
            spans["encode_s"] = spans.get("encode_s", 0.0) \
                + (time.perf_counter() - te)
            out[lo:lo + part.shape[0]] = self.run_encoded(
                V, D, part.shape[0], convert, spans=spans)
        self.last_spans = spans
        return out

    def stats(self) -> dict:
        return {
            "compiles": self.compiles,
            "steady_state_compiles": self.steady_state_compiles,
            "buckets": sorted({b for b, _ in self._exe}),
            "devices": len(self.devices),
            "donate": self.donate,
            "num_used": self.num_used,
            "k": self.k,
        }
