"""Cross-run performance ledger: persistent run history + noise-aware
rolling baselines + regression attribution.

Every tool so far was per-run: ``tools/bench_compare.py`` gates one
candidate against one hand-picked parent, and the flagship trajectory
lived as hand-curated ``BENCH_r0*.json`` files.  The ledger makes the
history durable and statistically usable — the discipline 1809.04559
frames as the hard part of GBDT perf work applied *across* runs:

* **Ingest** — a finished timeline (or its in-memory event list) is
  reduced to one run record: the ``run_header`` context + provenance
  (git rev / dirty / host / argv, schema 10), the headline metrics
  ``bench_compare`` gates (iters/sec, compile_s, recompiles, serve
  QPS/p99/shed, autotune overhead, construct_s, final eval), and the
  run outcome.  Records are keyed by (suite, shape bucket, device
  kind) — the comparability cell — plus schema + git rev for
  attribution.
* **Store** — a ledger directory holds an append-only ``index.jsonl``
  (one line per run; a crash mid-append costs at most the trailing
  partial line, which readers skip) and a full per-run record under
  ``runs/`` written with the same tmp + ``os.replace`` idiom as
  ``autotune_cache.json``.  Readers rebuild index-lost runs from
  ``runs/`` — a corrupted index line never loses history.
* **Rolling baselines** — per (cell, metric): median/MAD over the last
  N clean comparable runs with a noise floor, exposed to
  ``tools/bench_compare.py --baseline rolling`` as z-score gates that
  replace the single-parent tolerance.
* **Trends & attribution** — ``python -m lightgbm_tpu obs history`` /
  ``obs trend [--check]`` render per-metric trend tables with
  sparklines and flag change-points: the first run where a metric
  shifted beyond the noise band, blamed on that run's recorded git
  rev.  ``--check`` exits nonzero when the CURRENT regime of a gated
  metric began with a bad-direction shift — the CI gate.

Ingestion is idempotent (dedup on run id + header timestamp): bench
retries and re-runs of a backfill are no-ops.  Every writer is
best-effort — the ledger must never take a finished run down.
"""
from __future__ import annotations

import json
import os
import time

from ..utils.log import Log

LEDGER_REV = 1
INDEX_NAME = "index.jsonl"
RUNS_DIR = "runs"

# metric -> +1 higher-is-better / -1 lower-is-better.  Matches
# tools/bench_compare.py METRICS (the gated set) plus the backfill-only
# series (vs_baseline, multichip_ok).  Metrics absent here are rendered
# in trends but never fail `obs trend --check` — a direction the tool
# would have to guess is not a gate.
METRIC_DIRECTIONS = {
    "iters_per_sec": +1,
    "compile_s": -1,
    "peak_mem_bytes": -1,
    "recompile_count": -1,
    "barrier_skew_max_s": -1,
    "final_eval_metric": +1,
    "serve_qps": +1,
    "serve_p99_s": -1,
    "serve_shed_rate": -1,
    "autotune_overhead_s": -1,
    "host_orchestration_s": -1,
    "construct_s": -1,
    "vs_baseline": +1,
    "multichip_ok": +1,
    # schema 12 scaling events (bench.py --mp): per-chip throughput and
    # weak-scaling efficiency — gated per (suite, shape, device,
    # world_size) cell so an N-rank run never regresses against a
    # single-host baseline
    "rows_per_sec_per_chip": +1,
    "weak_scaling_eff": +1,
    # schema 13 utilization rollups (obs/roofline.py): exec-weighted
    # achieved/peak fractions — a drop means a kernel moved AWAY from
    # its roof, the regression class the roofline layer exists to catch
    "flop_util": +1,
    "hbm_util": +1,
    # schema 14 drift monitoring (obs/drift.py): the worst per-feature
    # PSI vs the training fingerprint and the rolling online quality —
    # `obs trend` attributes drift onset to the window whose cell
    # first shifted
    "drift_psi_max": -1,
    "online_auc": +1,
    "online_logloss": -1,
    # schema 15 incident engine (obs/incident.py): a commit that starts
    # tripping incidents — or whose incidents correlate MORE signals —
    # is a change-point the existing attribution machinery blames on
    # the git rev that introduced it
    "incidents_opened": -1,
    "incident_max_signals": -1,
    # schema 16 host profiler (obs/prof.py): the sampler's self-measured
    # cost as a fraction of profiled wall time — a commit that makes
    # sampling more expensive (deeper stacks, more threads) drifts this
    # cell up, and `obs trend --check` catches it before the 1% budget
    # gate in bench.py --dry ever trips
    "prof_overhead_frac": -1,
}

# noise floors under the MAD estimate: a flat history has MAD 0, and a
# z-score against sigma 0 would flag float jitter as a regression.  The
# 1% relative floor says "identical history still tolerates 1% noise
# per sigma" — a 3-sigma gate on flat history fires at a 3% shift.
MAD_SIGMA = 1.4826          # MAD -> sigma for a normal distribution
REL_NOISE_FLOOR = 0.01
ABS_NOISE_FLOOR = 1e-9


def default_ledger_dir():
    """Ledger location: ``LGBM_TPU_LEDGER`` env, else a durable /tmp
    directory next to the XLA compile cache's default (utils/common.py).
    Set the env to ``0`` to disable automatic bench ingestion."""
    return os.environ.get("LGBM_TPU_LEDGER", "/tmp/lgbm_tpu_ledger")


# ---------------------------------------------------------------- ingest

def metrics_from_events(events):
    """{metric: value} of ONE run's events — the same headline set
    ``tools/bench_compare.py`` gates, derived the same way."""
    out = {}
    iters = [e for e in events if e.get("ev") == "iter"]
    total = sum(float(e.get("time_s", 0.0)) for e in iters)
    if iters and total > 0:
        out["iters_per_sec"] = len(iters) / total
    # schema 11: host glue between device program submissions (mean per
    # iteration) — the series that attributes a fused-iteration win
    orch = [float(e["host_orchestration_s"]) for e in iters
            if "host_orchestration_s" in e]
    if orch:
        out["host_orchestration_s"] = sum(orch) / len(orch)
    run_end = next((e for e in events if e.get("ev") == "run_end"), None)
    entries = (run_end or {}).get("entries") or {}
    if entries:
        out["compile_s"] = sum(st.get("first_s", 0.0)
                               for st in entries.values())
    else:
        compiles = [e for e in events if e.get("ev") == "compile"]
        if compiles:
            out["compile_s"] = sum(float(e.get("first_call_s", 0.0))
                                   for e in compiles)
    peak = 0
    for e in events:
        if e.get("ev") != "memory":
            continue
        for d in e.get("devices", ()):
            peak = max(peak, d.get("peak_bytes_in_use",
                                   d.get("bytes_in_use", 0)))
    if peak:
        out["peak_mem_bytes"] = peak
    attr = [e for e in events if e.get("ev") == "compile_attr"]
    if attr:
        worst = {}
        for e in attr:
            worst[e.get("entry")] = max(worst.get(e.get("entry"), 0),
                                        int(e.get("n_compiles", 1)))
        out["recompile_count"] = sum(n - 1 for n in worst.values())
    skews = [float(e["skew_s"]) for e in events
             if e.get("ev") == "host_collective" and "skew_s" in e]
    if skews:
        out["barrier_skew_max_s"] = max(skews)
    evals = [e for e in events if e.get("ev") == "eval"
             and e.get("results")]
    if evals:
        out["final_eval_metric"] = float(evals[-1]["results"][-1]["value"])
    serve = [e for e in events if e.get("ev") == "serve_bench"]
    if serve:
        out["serve_qps"] = float(serve[-1]["qps"])
        out["serve_p99_s"] = float(serve[-1]["p99_s"])
        if serve[-1].get("shed_rate") is not None:
            out["serve_shed_rate"] = float(serve[-1]["shed_rate"])
    decs = [e for e in events if e.get("ev") == "autotune_decision"]
    if decs:
        out["autotune_overhead_s"] = sum(
            float(e.get("overhead_s", 0.0)) for e in decs)
    cons = [e for e in events if e.get("ev") == "dataset_construct"]
    if cons:
        out["construct_s"] = sum(
            float(e.get("construct_s",
                        e.get("sketch_s", 0.0) + e.get("bin_s", 0.0)
                        + e.get("write_s", 0.0)))
            for e in cons)
    sc = [e for e in events if e.get("ev") == "scaling"]
    if sc:
        out["rows_per_sec_per_chip"] = float(sc[-1]["rows_per_sec_per_chip"])
        out["weak_scaling_eff"] = float(sc[-1]["efficiency"])
    # schema 13: the LAST utilization rollup is the steady-state one
    # (early iterations still amortize compile-time in their means)
    utils = [e for e in events if e.get("ev") == "utilization"]
    if utils and utils[-1].get("flop_util") is not None:
        out["flop_util"] = float(utils[-1]["flop_util"])
        out["hbm_util"] = float(utils[-1].get("hbm_util", 0.0))
    # schema 14: the run's WORST drift evaluation (not the last — a
    # window that drifted and then reset must still mark the run) and
    # the last online-quality rollup
    drifts = [e for e in events if e.get("ev") == "drift"]
    if drifts:
        out["drift_psi_max"] = max(float(e.get("psi_max", 0.0))
                                   for e in drifts)
    quality = [e for e in events if e.get("ev") == "online_quality"]
    if quality:
        if quality[-1].get("auc") is not None:
            out["online_auc"] = float(quality[-1]["auc"])
        if quality[-1].get("logloss") is not None:
            out["online_logloss"] = float(quality[-1]["logloss"])
    # schema 15: prefer the run_end digest — it is present (zeros
    # included) whenever the engine ran, giving incident-free runs a
    # real zero history to change-point against; fall back to counting
    # the events for timelines that aborted before run_end
    inc = (run_end or {}).get("incidents")
    if inc is not None:
        out["incidents_opened"] = int(inc.get("opened", 0) or 0)
        out["incident_max_signals"] = int(inc.get("max_signals", 0) or 0)
    else:
        opens = [e for e in events if e.get("ev") == "incident_open"]
        if opens:
            out["incidents_opened"] = len(opens)
            closes = [e for e in events if e.get("ev") == "incident_close"]
            if closes:
                out["incident_max_signals"] = max(
                    len(e.get("signals") or ()) for e in closes)
    # schema 16: exec-weighted sampling overhead across every profiler
    # window — sum(cost)/sum(duration), not a mean of per-window
    # fractions, so a long cheap window cannot mask a short hot one
    profs = [e for e in events if e.get("ev") == "prof_profile"]
    dur = sum(float(e.get("dur_s", 0.0) or 0.0) for e in profs)
    if dur > 0:
        out["prof_overhead_frac"] = (
            sum(float(e.get("cost_s", 0.0) or 0.0) for e in profs) / dur)
    return out


def _device_kind(header):
    for d in header.get("devices") or ():
        if isinstance(d, dict) and d.get("kind"):
            return str(d["kind"])
    return str(header.get("backend", "") or "")


def _shape_bucket(events, header):
    """Shape key of a run when the caller didn't name one: rows x
    features from the construction/profile events, else the request
    count of a serving run, else '-'."""
    cons = next((e for e in events if e.get("ev") == "dataset_construct"),
                None)
    prof = next((e for e in events if e.get("ev") == "data_profile"), None)
    if cons and prof:
        return "%dx%d" % (int(cons.get("rows", 0)),
                          int(prof.get("n_features", 0)))
    if cons:
        return "r%d" % int(cons.get("rows", 0))
    sb = next((e for e in events if e.get("ev") == "serve_bench"), None)
    if sb is not None:
        return "req%d" % int(sb.get("requests", 0))
    return "-"


def record_from_events(events, suite="", shape="", source="",
                       extra_metrics=None):
    """Reduce one run's events to a ledger record, or None when there is
    nothing worth keeping (no metrics at all)."""
    if not events:
        return None
    header = next((e for e in events if e.get("ev") == "run_header"), {})
    run_end = next((e for e in events if e.get("ev") == "run_end"), None)
    prov = header.get("provenance") or {}
    metrics = metrics_from_events(events)
    metrics.update(extra_metrics or {})
    if not metrics:
        return None
    ctx = header.get("context") or {}
    rec = {
        "rev": LEDGER_REV,
        "run": str(events[-1].get("run", "")),
        "t": float(header.get("t", events[0].get("t", 0.0)) or 0.0),
        "suite": str(suite or ctx.get("tool") or ctx.get("suite")
                     or "train"),
        "shape": str(shape or _shape_bucket(events, header)),
        "device_kind": _device_kind(header),
        "backend": str(header.get("backend", "") or ""),
        "schema": header.get("schema"),
        "world_size": int(header.get("world_size", 1) or 1),
        "git_rev": str(prov.get("git_rev", "") or ""),
        "git_dirty": bool(prov.get("git_dirty", False)),
        "host": str(prov.get("hostname", "") or ""),
        "argv": list(prov.get("argv", []))[:8],
        "status": str((run_end or {}).get("status", "unknown")),
        "metrics": metrics,
    }
    if source:
        rec["source"] = str(source)
    return rec


def _dedup_key(rec):
    # run ids are 4 random bytes; the header timestamp breaks the
    # (astronomically unlikely, but free to avoid) cross-run collision
    return "%s-%d" % (rec.get("run", "?"), int(rec.get("t", 0.0)))


class Ledger:
    """One ledger directory: append-only JSONL index + per-run records.

    Writers: ``ingest_events`` / ``ingest_timeline`` / ``ingest_record``
    (all idempotent).  Readers: ``entries()`` — corrupt index lines are
    skipped with a warning and runs missing from the index are recovered
    from ``runs/``."""

    def __init__(self, path):
        self.dir = str(path)
        self.index_path = os.path.join(self.dir, INDEX_NAME)
        self.runs_dir = os.path.join(self.dir, RUNS_DIR)

    # ------------------------------------------------------------- read
    def _index_entries(self):
        entries, bad = [], 0
        try:
            with open(self.index_path) as f:
                lines = f.read().splitlines()
        except OSError:
            return [], 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                if not isinstance(rec, dict) or "metrics" not in rec:
                    raise ValueError("not a ledger record")
            except ValueError:
                bad += 1
                continue
            entries.append(rec)
        return entries, bad

    def entries(self):
        """All run records, oldest first (header time, then ingest
        order).  Survives a torn index: unparseable lines are skipped
        and any run present only under ``runs/`` is recovered."""
        entries, bad = self._index_entries()
        if bad:
            Log.warning("obs ledger: skipped %d corrupt index line(s) in "
                        "%s; recovering from %s/", bad, self.index_path,
                        RUNS_DIR)
        seen = {_dedup_key(r) for r in entries}
        recovered = 0
        if bad or not entries:
            try:
                names = sorted(os.listdir(self.runs_dir))
            except OSError:
                names = []
            for name in names:
                if not name.endswith(".json"):
                    continue
                try:
                    with open(os.path.join(self.runs_dir, name)) as f:
                        rec = json.load(f)
                except (OSError, ValueError):
                    continue
                if isinstance(rec, dict) and "metrics" in rec \
                        and _dedup_key(rec) not in seen:
                    entries.append(rec)
                    seen.add(_dedup_key(rec))
                    recovered += 1
        if recovered:
            Log.warning("obs ledger: recovered %d run(s) from %s/",
                        recovered, RUNS_DIR)
        entries.sort(key=lambda r: (float(r.get("t", 0.0)),
                                    float(r.get("ingested_t", 0.0))))
        return entries

    # ------------------------------------------------------------ write
    def ingest_record(self, rec):
        """Append one record; returns True when it landed, False when an
        identical run is already present (idempotent re-ingest)."""
        if not isinstance(rec, dict) or not rec.get("metrics"):
            return False
        key = _dedup_key(rec)
        existing, _ = self._index_entries()
        if any(_dedup_key(r) == key for r in existing):
            return False
        if os.path.exists(os.path.join(self.runs_dir, key + ".json")):
            return False
        rec = dict(rec, ingested_t=time.time())
        os.makedirs(self.runs_dir, exist_ok=True)
        # full record first (atomic tmp+replace, the autotune-cache
        # idiom), THEN the index line — a crash between the two leaves a
        # recoverable runs/ file, never a dangling index entry
        run_path = os.path.join(self.runs_dir, key + ".json")
        tmp = run_path + ".tmp.%d" % os.getpid()
        with open(tmp, "w") as f:
            json.dump(rec, f, sort_keys=True, default=str)
        os.replace(tmp, run_path)
        with open(self.index_path, "a") as f:
            f.write(json.dumps(rec, sort_keys=True, default=str) + "\n")
            f.flush()
        return True

    def ingest_events(self, events, suite="", shape="", source="",
                      extra_metrics=None):
        """Ingest one run's in-memory event list; returns 1/0."""
        rec = record_from_events(events, suite=suite, shape=shape,
                                 source=source,
                                 extra_metrics=extra_metrics)
        if rec is None:
            return 0
        return int(self.ingest_record(rec))

    def ingest_timeline(self, path, suite="", shape="", source="",
                        extra_metrics=None):
        """Ingest every finished run of a JSONL timeline file; returns
        the number of runs that landed (0 on full re-ingest)."""
        from .events import read_events
        events = read_events(path, validate=False)
        by_run, order = {}, []
        for e in events:
            r = e.get("run")
            if r not in by_run:
                order.append(r)
            by_run.setdefault(r, []).append(e)
        n = 0
        for r in order:
            run_events = by_run[r]
            if not any(e.get("ev") == "run_end" for e in run_events):
                continue        # unfinished run: not history yet
            n += self.ingest_events(run_events, suite=suite, shape=shape,
                                    source=source or path,
                                    extra_metrics=extra_metrics)
        return n


# ----------------------------------------------------- rolling statistics

def _median(vals):
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def rolling_stats(values, window):
    """median / MAD / noise-floored sigma over the last ``window``
    values, or None when empty."""
    vals = [float(v) for v in values][-max(1, int(window)):]
    if not vals:
        return None
    med = _median(vals)
    mad = _median([abs(v - med) for v in vals])
    sigma = max(MAD_SIGMA * mad, REL_NOISE_FLOOR * abs(med),
                ABS_NOISE_FLOOR)
    return {"n": len(vals), "median": med, "mad": mad, "sigma": sigma}


def comparable_entries(entries, suite=None, shape=None, device_kind=None,
                       metric=None, status="ok", exclude_runs=(),
                       world_size=None):
    """The entries a candidate may be compared against: same suite /
    shape / device kind / world size (when given), clean outcome, metric
    present.  world_size is part of a run's shape identity: an N-rank
    run's per-chip throughput must never gate against single-host
    baselines (weak scaling is expected to be < 1.0)."""
    out = []
    for r in entries:
        if status and r.get("status") != status:
            continue
        if suite and r.get("suite") != suite:
            continue
        if shape and r.get("shape") != shape:
            continue
        if device_kind and r.get("device_kind") != device_kind:
            continue
        if world_size is not None and \
                int(r.get("world_size", 1) or 1) != int(world_size):
            continue
        if metric and metric not in (r.get("metrics") or {}):
            continue
        if r.get("run") in exclude_runs:
            continue
        out.append(r)
    return out


def rolling_baseline(entries, metric, window=8):
    """Rolling stats of one metric over already-filtered entries."""
    vals = [r["metrics"][metric] for r in entries
            if metric in (r.get("metrics") or {})]
    if not vals:
        return None
    return rolling_stats(vals, window)


def change_points(entries, metric, window=8, z_threshold=3.0,
                  min_history=3):
    """Change-points of one metric series: each is the FIRST run whose
    value left the noise band of the regime before it (|z| >= threshold
    against the rolling median/MAD of the current regime), attributed to
    that run's recorded git rev.  Detection restarts after each shift,
    so a step is flagged once, not once per following run."""
    series = [(r, float(r["metrics"][metric])) for r in entries
              if metric in (r.get("metrics") or {})]
    cps = []
    regime_start = 0
    for i in range(len(series)):
        hist = [v for _, v in series[regime_start:i]]
        if len(hist) < max(1, int(min_history)):
            continue
        st = rolling_stats(hist, window)
        rec, val = series[i]
        z = (val - st["median"]) / st["sigma"]
        if abs(z) < float(z_threshold):
            continue
        direction = METRIC_DIRECTIONS.get(metric, 0)
        cps.append({
            "metric": metric, "index": i, "run": rec.get("run", "?"),
            "t": rec.get("t", 0.0), "git_rev": rec.get("git_rev", ""),
            "git_dirty": rec.get("git_dirty", False),
            "suite": rec.get("suite", ""), "shape": rec.get("shape", ""),
            "device_kind": rec.get("device_kind", ""),
            "baseline": st["median"], "value": val, "z": z,
            "regression": bool(direction) and (direction * z < 0),
        })
        regime_start = i
    return cps


# ------------------------------------------------------------- rendering

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values, width=16):
    """Unicode sparkline of the last ``width`` values."""
    vals = [float(v) for v in values][-max(1, int(width)):]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi - lo <= 0:
        return _SPARK[3] * len(vals)
    scale = (len(_SPARK) - 1) / (hi - lo)
    return "".join(_SPARK[int(round((v - lo) * scale))] for v in vals)


def _fmt_t(t):
    t = float(t or 0.0)
    if t < 1e9:                 # backfilled rounds carry synthetic times
        return "      r%03d" % int(t) if 0 < t < 1000 else "         -"
    return time.strftime("%m-%d %H:%M", time.localtime(t))


def _fmt_rev(rec):
    rev = str(rec.get("git_rev", "") or "")[:12]
    if not rev:
        return "-"
    return rev + ("+" if rec.get("git_dirty") else "")


def _cells(entries):
    """{(suite, shape, device_kind, world_size): [entries]} in
    first-seen order.  world_size joined the cell key with schema 12: a
    2-rank run and a 1-rank run of the same shape are different
    performance regimes, and `obs trend --check` must never gate one
    against the other's history."""
    out = {}
    for r in entries:
        key = (r.get("suite", ""), r.get("shape", ""),
               r.get("device_kind", ""),
               int(r.get("world_size", 1) or 1))
        out.setdefault(key, []).append(r)
    return out


def render_history(entries, out=None, limit=20, suite=None, metric=None):
    """`obs history`: one line per run, newest last."""
    import sys
    out = out or sys.stdout
    w = lambda s="": out.write(s + "\n")
    if suite:
        entries = [r for r in entries if r.get("suite") == suite]
    if metric:
        entries = [r for r in entries
                   if metric in (r.get("metrics") or {})]
    if not entries:
        w("ledger is empty (no matching runs)")
        return
    total = len(entries)
    entries = entries[-max(1, int(limit)):]
    w("%-11s %-12s %-14s %-10s %-13s %-7s %s"
      % ("when", "suite", "shape", "device", "git rev", "status",
         "metrics"))
    for r in entries:
        m = r.get("metrics") or {}
        shown = [metric] if metric else sorted(
            m, key=lambda k: (k not in METRIC_DIRECTIONS, k))[:3]
        mtxt = "  ".join("%s=%.6g" % (k, float(m[k])) for k in shown
                         if k in m)
        w("%-11s %-12s %-14s %-10s %-13s %-7s %s"
          % (_fmt_t(r.get("t")), str(r.get("suite", ""))[:12],
             str(r.get("shape", ""))[:14],
             str(r.get("device_kind", ""))[:10], _fmt_rev(r),
             str(r.get("status", "?"))[:7], mtxt))
    if total > len(entries):
        w("(%d older run(s) not shown; -n %d to widen)"
          % (total - len(entries), total))


def render_trend(entries, out=None, suite=None, metric=None, window=8,
                 z_threshold=3.0, min_history=3):
    """`obs trend`: per-cell per-metric trend table with sparklines and
    change-point attribution.  Returns the list of ACTIVE regressions —
    gated metrics whose current regime began with a bad-direction shift
    (the `--check` failure set)."""
    import sys
    out = out or sys.stdout
    w = lambda s="": out.write(s + "\n")
    if suite:
        entries = [r for r in entries if r.get("suite") == suite]
    active = []
    wrote = False
    for (csuite, cshape, ckind, cworld), cell in _cells(entries).items():
        metrics = sorted({k for r in cell
                          for k in (r.get("metrics") or {})},
                        key=lambda k: (k not in METRIC_DIRECTIONS, k))
        if metric:
            metrics = [m for m in metrics if m == metric]
        clean = [r for r in cell if r.get("status") == "ok"]
        header_done = False
        for m in metrics:
            vals = [r["metrics"][m] for r in clean
                    if m in (r.get("metrics") or {})]
            if not vals:
                continue
            if not header_done:
                w("%s%s / %s / %s%s  (%d run(s), %d clean)"
                  % ("" if not wrote else "\n", csuite, cshape,
                     ckind or "-",
                     " / %d-rank" % cworld if cworld > 1 else "",
                     len(cell), len(clean)))
                w("  %-20s %4s %12s %12s %-16s  %s"
                  % ("metric", "n", "median", "last", "trend",
                     "change-points"))
                header_done = True
                wrote = True
            st = rolling_stats(vals, max(window, len(vals)))
            cps = change_points(clean, m, window=window,
                                z_threshold=z_threshold,
                                min_history=min_history)
            notes = []
            for cp in cps:
                notes.append("%s%+.1f%% at %s (%s)"
                             % ("REGRESSED " if cp["regression"] else "",
                                100.0 * (cp["value"] - cp["baseline"])
                                / (abs(cp["baseline"]) or 1.0),
                                _fmt_t(cp["t"]).strip(),
                                (cp["git_rev"] or cp["run"] or "?")))
            if cps and cps[-1]["regression"]:
                active.append(cps[-1])
            w("  %-20s %4d %12.6g %12.6g %-16s  %s"
              % (m, len(vals), st["median"], vals[-1], sparkline(vals),
                 "; ".join(notes) or "-"))
    if not wrote:
        w("ledger is empty (no matching runs)")
    if active:
        w()
        for cp in active:
            w("REGRESSION: %s %+.1f%% (z=%+.1f) in %s/%s since %s, "
              "introduced by rev %s (run %s)"
              % (cp["metric"],
                 100.0 * (cp["value"] - cp["baseline"])
                 / (abs(cp["baseline"]) or 1.0), cp["z"],
                 cp["suite"], cp["shape"], _fmt_t(cp["t"]).strip(),
                 cp["git_rev"] or "unknown", cp["run"]))
    return active
