"""MXU sparse histograms — entry-chunk store + Pallas contraction kernel.

Reference analog: OrderedSparseBin's per-leaf nonzero iteration
(src/io/ordered_sparse_bin.hpp:26-209) — histogram work proportional to
nnz, not N*F.  The first TPU form of that idea (ops/sparse_store.py)
reduces the coordinate list with one `segment_sum`, which is O(nnz) in
WORK but lowers to a serialized scatter-add on TPU (measured 58 s/iter
at the 1M x 968 @1% Bosch shape — 145x the reference CPU,
BENCH_NOTES.md "Device-side sparse store").  This module keeps the
O(nnz) economics but feeds the MXU instead:

* the nonzero (non-fill) entries are packed column-major into fixed
  ENTRY CHUNKS of E entries, each chunk owned by exactly ONE device
  column (columns are padded to whole chunks; pad entries carry bin -1,
  which matches no one-hot row, and row id N, which every gather/scatter
  drops) — so per-column skew costs at most E-1 pad entries per column,
  never a dense blow-up;
* per chunk the kernel builds the (Bp, E) bin one-hot and the (3K, E)
  per-child masked weights in VMEM (same exact-bf16 one-hot + hi/lo
  weight split as the dense wave kernels, ops/pallas_wave.py) and runs
  ONE (Bp, E) x (E, 3K) MXU contraction, accumulated into the chunk's
  column rows of the (F*Bp, 3K) output — no segment_sum, no scatter,
  no atomics (the TPU grid is sequential);
* per-entry leaf ids / gradient channels are row-gathers done XLA-side
  once per wave / per iteration — O(nnz) reads against the (N,) vectors.

HBM per histogram pass: 5 i32/f32 entry arrays = 20 bytes * nnz (at the
Bosch shape ~194 MB vs the dense wave's 968 MB bin-matrix read), and the
MXU work is B * 3K * nnz MACs — 1% of the dense wave's B * 3K * N * F.

Fill-bin slots stay ZERO exactly like the segment_sum store: the
histogram view reconstructs them from the leaf sums (FixHistogram,
src/treelearner/feature_histogram.hpp:904-941), so the store never
materializes fill entries at all.
"""
from __future__ import annotations

import functools
import math
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .wave import _bin_pad

ENTRY_CHUNK = 512     # entries per chunk (kernel lanes dim)
CHUNK_BLOCK = 8       # chunks per kernel grid step (block sublanes dim)


class ChunkedSparseStore(NamedTuple):
    """Column-major nonzero entries in whole-chunk-per-column layout.

    Pads: ent_row holds N (one past the last row — gathers clip it,
    partition scatters drop it), ent_bin holds -1 (matches no bin).
    """
    ent_row: jnp.ndarray    # (NC, E) i32 row ids
    ent_bin: jnp.ndarray    # (NC, E) i32 bin ids
    chunk_col: jnp.ndarray  # (NC, 1) i32 owning device column per chunk
    col_cptr: jnp.ndarray   # (F+1,) i32 chunk ranges per column
    fill: jnp.ndarray       # (F,) i32 per-column fill bin


def build_chunked_store(binned: np.ndarray, fill: np.ndarray,
                        num_bins: int, entry_chunk: int = ENTRY_CHUNK,
                        chunk_block: int = CHUNK_BLOCK,
                        auto_uniform: bool = False):
    """Host-side build from the (N, F) binned matrix.

    ``fill`` is the per-column bin the downstream view reconstructs (or
    never reads) — see sparse_store.column_fill_bins.  Returns
    (store, cap_chunks, device_bytes); cap_chunks bounds any single
    column's chunk count (the partition window size).

    auto_uniform (r5): when per-column skew is low, the entry chunk is
    widened so EVERY column is exactly one chunk (E = max column nnz
    rounded up to the base chunk).  Same structure, but the kernel then
    runs one (Bp, E) x (E, 3K) dot per COLUMN instead of ~cap tiny
    K=512 dots per column — at the Bosch shape that is ~19k
    M=64/N=96/K=512 dots collapsing into 968 K~10k dots (near-full MXU
    utilization, ~20x fewer dispatch+accumulate rounds).  Taken only
    when the pad overhead stays under 50% (skewed columns would blow
    the uniform layout up; they keep the narrow chunks).
    """
    n, f = binned.shape
    e = int(entry_chunk)
    mask_t = (binned != fill[None, :]).T            # (F, N) column-major
    cols, rows = np.nonzero(mask_t)
    bins = binned.T[mask_t].astype(np.int64)
    counts = np.bincount(cols, minlength=f).astype(np.int64)
    cchunks = -(-counts // e)                       # chunks per column
    if auto_uniform and f and len(rows):
        e_uni = max(e, -(-int(counts.max()) // e) * e)
        # all-fill columns cost zero chunks in EITHER layout — charge
        # the uniform layout only for its nonzero columns; and bound E
        # absolutely so a dense-ish low-skew store cannot widen past
        # what the kernel's VMEM blocks hold (5 x (8, E) i32/f32 input
        # blocks + the (3K, E) hi/lo weights + the (Bp, E) one-hot is
        # ~2 KB per entry at K=64 — 16384 keeps a grid step well under
        # the 100 MB budget)
        nzc = int(np.count_nonzero(counts))
        if (e_uni <= 16384
                and nzc * e_uni <= 1.5 * max(int(cchunks.sum()), 1) * e):
            e = e_uni
            cchunks = -(-counts // e)               # now <= 1 per column
    col_cptr = np.zeros(f + 1, np.int64)
    np.cumsum(cchunks, out=col_cptr[1:])
    nc = int(col_cptr[-1])
    nc_pad = -(-max(nc, 1) // chunk_block) * chunk_block
    ent_row = np.full((nc_pad, e), n, np.int32)
    ent_bin = np.full((nc_pad, e), -1, np.int32)
    chunk_col = np.zeros(nc_pad, np.int32)
    if nc:
        eptr = np.zeros(f + 1, np.int64)
        np.cumsum(counts, out=eptr[1:])
        within = np.arange(len(rows), dtype=np.int64) - eptr[cols]
        pos = col_cptr[cols] * e + within           # padded flat position
        ent_row.reshape(-1)[pos] = rows
        ent_bin.reshape(-1)[pos] = bins
        chunk_col[:nc] = np.repeat(np.arange(f, dtype=np.int32), cchunks)
    cap_chunks = int(cchunks.max()) if f and nc else 0
    store = ChunkedSparseStore(
        ent_row=jnp.asarray(ent_row), ent_bin=jnp.asarray(ent_bin),
        chunk_col=jnp.asarray(chunk_col[:, None]),
        col_cptr=jnp.asarray(col_cptr.astype(np.int32)),
        fill=jnp.asarray(np.asarray(fill, np.int64).astype(np.int32)))
    device_bytes = 4 * (2 * nc_pad * e + nc_pad + 2 * f + 1)
    return store, cap_chunks, device_bytes


def _chunk_hist_kernel(bin_ref, lid_ref, g_ref, h_ref, m_ref, cid_ref,
                       colv_ref, out_ref, *, bp, gc, hilo=True):
    """One grid step: gc chunks, each one (Bp, E) x (E, 3K) contraction
    accumulated into its column's row block of the (F*Bp, 3K) output."""
    from jax.experimental import pallas as pl

    from .pallas_wave import _hi_lo
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    for g in range(gc):
        # bin ids are < 2^24 — exact in f32; pad bins (-1) match no row
        binrow = bin_ref[g:g + 1, :].astype(jnp.float32)       # (1, E)
        match = (cid_ref[:] == lid_ref[g:g + 1, :]).astype(
            jnp.float32)                                       # (K, E)
        wmat = jnp.concatenate(
            [match * g_ref[g:g + 1, :], match * h_ref[g:g + 1, :],
             match * m_ref[g:g + 1, :]], axis=0)               # (3K, E)
        wh, wl = _hi_lo(wmat, hilo)
        e = binrow.shape[1]
        iota = jax.lax.broadcasted_iota(
            jnp.int32, (bp, e), 0).astype(jnp.float32)
        oh = jnp.where(binrow == iota, jnp.float32(1.0),
                       jnp.float32(0.0)).astype(jnp.bfloat16)  # (Bp, E)
        acc = jax.lax.dot_general(                             # A @ B^T
            oh, wh, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)                # (Bp, 3K)
        if wl is not None:
            acc = acc + jnp.float32(1.0 / 256.0) * jax.lax.dot_general(
                oh, wl, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
        col = colv_ref[g, 0]
        rows = pl.dslice(col * bp, bp)
        out_ref[rows, :] = out_ref[rows, :] + acc


def gather_entry_weights(store: ChunkedSparseStore, w3):
    """Per-entry weight channels (g_e, h_e, m_e), each (NC, E) f32 —
    the three O(nnz) gathers that are CONSTANT across one tree's waves.

    Measured r4 (Bosch 1M x 968 @1%, 2.72 s/iter): the per-wave cost of
    the MXU sparse path was dominated by four O(nnz) XLA gathers at the
    same ~8-cycle/row economics as the score-update gather — ~46 ms
    each, ~185 ms/wave against a ~3 ms kernel.  w3 never changes inside
    a tree, so callers hoist these three OUT of the wave loop (one
    gather per TREE) and pass the result via `entry_weights`; only the
    leaf-id gather remains per-wave."""
    rows_flat = store.ent_row.reshape(-1)
    nc, e = store.ent_bin.shape
    w3f = w3.astype(jnp.float32)
    g_e = jnp.take(w3f[:, 0], rows_flat, mode="clip").reshape(nc, e)
    h_e = jnp.take(w3f[:, 1], rows_flat, mode="clip").reshape(nc, e)
    m_e = jnp.take(w3f[:, 2], rows_flat, mode="clip").reshape(nc, e)
    return g_e, h_e, m_e


@functools.partial(jax.jit, static_argnames=("num_bins", "num_cols",
                                             "interpret", "hilo",
                                             "num_leaves"))
def sparse_wave_histogram_mxu(store: ChunkedSparseStore, leaf_id, w3,
                              child_id, num_bins: int, num_cols: int,
                              interpret: bool = False, hilo: bool = True,
                              entry_weights=None, num_leaves: int = 0):
    """(K, F, B, 3) histograms of the rows whose leaf is child_id[k],
    from nonzero entries only (fill slots zero — view reconstructs).

    leaf_id: (N,) int32; w3: (N, 3) [g*mult, h*mult, mult] channels;
    child_id: (K,) int32 target leaves, -1 entries yield zero histograms.
    entry_weights: optional pre-gathered (g_e, h_e, m_e) from
    gather_entry_weights — pass it from any per-wave loop (see there).
    num_leaves > 0 narrows the leaf-id gather (the dominant per-wave
    term after the weight hoist) to the smallest dtype holding the ids
    — a 4x traffic cut at <=256 leaves IF the TPU gather is byte-bound
    (index-bound would make it a wash; the r05b A/B decides).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nc, e = store.ent_bin.shape
    k = int(child_id.shape[0])
    bp = _bin_pad(num_bins)
    # largest grid-step chunk count that divides the store's (static)
    # chunk dimension — CHUNK_BLOCK for default-built stores, smaller
    # when the store was built with a different chunk_block pad
    gc = math.gcd(nc, CHUNK_BLOCK)

    # per-entry row gathers, XLA-side: O(nnz) reads of the (N,) vectors.
    # Pad rows (id N) clip to N-1; their bin -1 zeroes the contribution.
    rows_flat = store.ent_row.reshape(-1)
    lid_src = leaf_id
    if 0 < num_leaves <= 256:
        lid_src = leaf_id.astype(jnp.uint8)
    elif 0 < num_leaves <= 65536:
        lid_src = leaf_id.astype(jnp.uint16)
    lid_e = jnp.take(lid_src, rows_flat,
                     mode="clip").reshape(nc, e).astype(jnp.int32)
    if entry_weights is None:
        entry_weights = gather_entry_weights(store, w3)
    g_e, h_e, m_e = entry_weights

    kernel = functools.partial(_chunk_hist_kernel, bp=bp, gc=gc, hilo=hilo)
    flat = pl.pallas_call(
        kernel,
        grid=(nc // gc,),
        in_specs=[
            pl.BlockSpec((gc, e), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),   # ent_bin
            pl.BlockSpec((gc, e), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),   # lid_e
            pl.BlockSpec((gc, e), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),   # g_e
            pl.BlockSpec((gc, e), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),   # h_e
            pl.BlockSpec((gc, e), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),   # m_e
            pl.BlockSpec((k, 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),   # child ids
            pl.BlockSpec((gc, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),   # chunk cols
        ],
        out_specs=pl.BlockSpec((num_cols * bp, 3 * k), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((num_cols * bp, 3 * k),
                                       jnp.float32),
        # jax renamed TPUCompilerParams -> CompilerParams; accept either
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams",
                                        None))(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(store.ent_bin, lid_e, g_e, h_e, m_e, child_id[:, None],
      store.chunk_col)
    h = flat.reshape(num_cols, bp, 3, k)[:, :num_bins]
    return jnp.transpose(h, (3, 0, 1, 2))


def chunked_child_hists_ref(store: ChunkedSparseStore, leaf_id, w3,
                            child_id, num_bins: int, num_cols: int,
                            num_leaves: int):
    """Pure-XLA oracle / non-TPU fallback — same contract as the kernel,
    via the segment_sum form (fine on CPU, serialized on TPU)."""
    nc, e = store.ent_bin.shape
    k = child_id.shape[0]
    rows = store.ent_row.reshape(-1)
    bins = store.ent_bin.reshape(-1)
    cols = jnp.repeat(store.chunk_col[:, 0], e)
    lid = jnp.take(leaf_id, rows, mode="clip")
    slot_tbl = jnp.full(num_leaves, k, jnp.int32).at[
        jnp.where(child_id >= 0, child_id, num_leaves)].set(
        jnp.arange(k, dtype=jnp.int32), mode="drop")
    slot = jnp.take(slot_tbl, jnp.clip(lid, 0, num_leaves - 1))
    valid = (bins >= 0) & (slot < k)
    seg = jnp.where(valid,
                    slot * (num_cols * num_bins) + cols * num_bins + bins,
                    k * num_cols * num_bins)       # dropped by segment_sum
    wnz = jnp.take(w3, rows, axis=0, mode="clip")
    flat = jax.ops.segment_sum(wnz, seg,
                               num_segments=k * num_cols * num_bins)
    return flat.reshape(k, num_cols, num_bins, 3)


def chunked_split_column(store: ChunkedSparseStore, j, n: int,
                         cap_chunks: int):
    """Full-N int32 bin column j: fill value + the column's entries,
    read through a static cap_chunks chunk window (the chunked analog of
    sparse_store.sparse_split_column)."""
    nc, e = store.ent_row.shape
    if cap_chunks == 0:                 # every value sits at the fill bin
        return jnp.full(n, store.fill[j], jnp.int32)
    cap = min(cap_chunks, nc)
    start = store.col_cptr[j]
    end = store.col_cptr[j + 1]
    cs = jnp.minimum(start, nc - cap)   # window start after edge clamp
    blk_r = lax.dynamic_slice(store.ent_row, (cs, 0), (cap, e))
    blk_b = lax.dynamic_slice(store.ent_bin, (cs, 0), (cap, e))
    cidx = cs + jnp.arange(cap, dtype=jnp.int32)[:, None]
    ok = (cidx >= start) & (cidx < end)            # chunks of column j
    rows = jnp.where(ok, blk_r, n).reshape(-1)
    bins = jnp.where(ok, blk_b, 0).reshape(-1)
    col = jnp.full(n, store.fill[j], jnp.int32)
    return col.at[rows].set(bins, mode="drop")
