"""scikit-learn interface (reference python-guide/sklearn_example.py
scope): regressor with early stopping, grid search over the estimator,
classifier probabilities, and a ranker with query groups.

Run from the repo root:  python examples/python-guide/sklearn_example.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import lightgbm_tpu as lgb

rng = np.random.default_rng(5)

# ---- regression with early stopping on a holdout
X = rng.normal(size=(20_000, 8))
y = X[:, 0] * 2 + np.sin(X[:, 1]) + 0.1 * rng.normal(size=20_000)
X_tr, X_te, y_tr, y_te = X[:16_000], X[16_000:], y[:16_000], y[16_000:]

reg = lgb.LGBMRegressor(n_estimators=200, num_leaves=31, learning_rate=0.1)
reg.fit(X_tr, y_tr, eval_set=[(X_te, y_te)], eval_metric="l2",
        early_stopping_rounds=10, verbose=False)
pred = reg.predict(X_te, num_iteration=reg.best_iteration_)
print("regression rmse: %.4f (best_iter=%s)"
      % (float(np.sqrt(np.mean((pred - y_te) ** 2))), reg.best_iteration_))

# ---- grid search over the sklearn estimator
try:
    from sklearn.model_selection import GridSearchCV
    gs = GridSearchCV(lgb.LGBMRegressor(n_estimators=20),
                      {"num_leaves": [15, 31], "learning_rate": [0.05, 0.1]},
                      cv=3)
    gs.fit(X_tr[:4000], y_tr[:4000])
    print("grid search best:", gs.best_params_)
except ImportError:
    print("scikit-learn not installed; grid search skipped")

# ---- classifier probabilities
yc = (y > 0).astype(int)
clf = lgb.LGBMClassifier(n_estimators=40, num_leaves=31)
clf.fit(X_tr, yc[:16_000])
proba = clf.predict_proba(X_te)
print("classifier accuracy: %.3f"
      % ((proba[:, 1] > 0.5).astype(int) == yc[16_000:]).mean())

# ---- ranker with query groups
n_q, per_q = 200, 20
Xr = rng.normal(size=(n_q * per_q, 5))
rel = (Xr[:, 0] + 0.3 * rng.normal(size=n_q * per_q))
yr = np.clip((rel * 2).astype(int) - rel.astype(int), 0, 4)
group = np.full(n_q, per_q)
rk = lgb.LGBMRanker(n_estimators=30, num_leaves=15)
rk.fit(Xr, yr, group=group)
print("ranker trained; scores head:", np.round(rk.predict(Xr[:3]), 3))
