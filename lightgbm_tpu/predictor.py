"""Row-wise predictor with prediction early stopping.

Parity targets: src/application/predictor.hpp:24-96 and
src/boosting/prediction_early_stop.cpp — margin-based stop callbacks
(binary: 2|margin|, multiclass: top1-top2 gap) checked every
``round_period`` trees.
"""
from __future__ import annotations

import time

from typing import Callable, Optional

import numpy as np

from .models.gbdt import GBDT
from .obs.metrics import observe_predict
from .utils.log import Log


class PredictionEarlyStopInstance:
    """(callback, round_period) pair (include/LightGBM/prediction_early_stop.h).

    ``batch_callback`` is the vectorized form — (rows, k) margins in,
    bool stop-mask out — used by the tree-major predict loop; a custom
    instance that only supplies the scalar ``callback`` still works
    (the loop falls back to row-by-row evaluation of just the active
    rows)."""

    def __init__(self, callback: Callable[[np.ndarray], bool],
                 round_period: int,
                 batch_callback: Optional[Callable[[np.ndarray],
                                                   np.ndarray]] = None):
        self.callback = callback
        self.round_period = round_period
        self.batch_callback = batch_callback


def create_prediction_early_stop_instance(type_: str, round_period: int,
                                          margin_threshold: float
                                          ) -> PredictionEarlyStopInstance:
    if type_ == "none":
        return PredictionEarlyStopInstance(
            lambda pred: False, 1 << 30,
            lambda preds: np.zeros(preds.shape[0], dtype=bool))
    if type_ == "multiclass":
        def cb_multi(pred):
            if len(pred) < 2:
                Log.fatal("Multiclass early stopping needs predictions to be "
                          "of length two or larger")
            top2 = np.partition(pred, -2)[-2:]
            return (top2[1] - top2[0]) > margin_threshold

        def cb_multi_batch(preds):
            if preds.shape[1] < 2:
                Log.fatal("Multiclass early stopping needs predictions to be "
                          "of length two or larger")
            top2 = np.partition(preds, -2, axis=1)[:, -2:]
            return (top2[:, 1] - top2[:, 0]) > margin_threshold
        return PredictionEarlyStopInstance(cb_multi, round_period,
                                           cb_multi_batch)
    if type_ == "binary":
        def cb_binary(pred):
            if len(pred) != 1:
                Log.fatal("Binary early stopping needs predictions to be of "
                          "length one")
            return 2.0 * abs(pred[0]) > margin_threshold

        def cb_binary_batch(preds):
            if preds.shape[1] != 1:
                Log.fatal("Binary early stopping needs predictions to be of "
                          "length one")
            return 2.0 * np.abs(preds[:, 0]) > margin_threshold
        return PredictionEarlyStopInstance(cb_binary, round_period,
                                           cb_binary_batch)
    Log.fatal("Unknown early stopping type: %s", type_)


class Predictor:
    """Per-row predictor (predictor.hpp) honoring pred_early_stop."""

    def __init__(self, gbdt: GBDT, num_iteration: int = -1,
                 raw_score: bool = False, predict_leaf_index: bool = False,
                 pred_contrib: bool = False,
                 early_stop: bool = False, early_stop_freq: int = 10,
                 early_stop_margin: float = 10.0):
        self.gbdt = gbdt
        self.num_iteration = num_iteration
        self.raw_score = raw_score
        self.predict_leaf_index = predict_leaf_index
        self.pred_contrib = pred_contrib
        k = gbdt.num_tree_per_iteration
        if early_stop and not predict_leaf_index:
            kind = "multiclass" if k > 1 else "binary"
            self.early_stop = create_prediction_early_stop_instance(
                kind, early_stop_freq, early_stop_margin)
        else:
            self.early_stop = create_prediction_early_stop_instance(
                "none", early_stop_freq, early_stop_margin)

    def predict(self, features: np.ndarray) -> np.ndarray:
        t0 = time.perf_counter()
        out = self._predict_impl(features)
        # row accounting from the INPUT (a 1-D request is one row):
        # converted k=1 outputs are 1-D and multiclass outputs are
        # (n, k) — both count n rows, never ndim quirks
        rows = 1 if np.ndim(features) <= 1 else np.shape(features)[0]
        observe_predict(rows, time.perf_counter() - t0)
        return out

    def _predict_impl(self, features: np.ndarray) -> np.ndarray:
        features = np.ascontiguousarray(np.asarray(features, dtype=np.float64))
        if features.ndim == 1:
            features = features.reshape(1, -1)
        if self.predict_leaf_index:
            return self.gbdt.predict_leaf_index(features, self.num_iteration)
        if self.pred_contrib:
            # attribution debug path (host, f64): gain-weighted per-feature
            # contributions; early stopping does not apply — the whole
            # point is seeing every tree's share
            return self.gbdt.pred_contrib(features, self.num_iteration)
        gbdt = self.gbdt
        gbdt._materialize()
        k = gbdt.num_tree_per_iteration
        num_used = gbdt._used_trees(self.num_iteration)
        n = features.shape[0]
        out = np.zeros((n, k), dtype=np.float64)
        period = self.early_stop.round_period
        if period >= num_used:
            out = gbdt.predict_raw(features, self.num_iteration)
        else:
            # early-stopped traversal, tree-major over the still-active
            # rows: each tree is ONE batched descent over the rows that
            # haven't hit their margin yet, and the stop check at every
            # period boundary is a vectorized margin test — same
            # per-row semantics as the reference's OMP row loop
            # (predictor.hpp:33-96) at batch throughput (VERDICT r3
            # Weak #7: the old per-row Python loop was O(rows x trees)
            # interpreted)
            active = np.arange(n)
            fa = features       # re-gathered only when the set shrinks
            for t in range(num_used):
                out[active, t % k] += gbdt.models[t].predict(fa)
                if (t + 1) % (period * k) == 0:
                    margins = out[active]
                    if self.early_stop.batch_callback is not None:
                        stop = np.asarray(
                            self.early_stop.batch_callback(margins))
                    else:   # custom scalar-only instance
                        stop = np.fromiter(
                            (self.early_stop.callback(m) for m in margins),
                            dtype=bool, count=len(active))
                    if stop.any():
                        active = active[~stop]
                        if active.size == 0:
                            break
                        fa = features[active]
        if self.raw_score or gbdt.objective is None:
            return out[:, 0] if k == 1 else out
        conv = np.asarray(gbdt.objective.convert_output(
            out if k > 1 else out[:, 0]))
        return conv
