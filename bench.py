"""Benchmark: boosting iters/sec on a Higgs-like 1M x 28 binary workload.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload mirrors the reference's GPU benchmark recipe
(docs/GPU-Performance.md:84-117): num_leaves=63, max_bin=63, lr=0.1, binary
objective.  Data is a deterministic synthetic stand-in for Higgs (the real
10.5M x 28 set isn't shipped in-repo); the SAME data/config was run through
the reference CLI (built from /root/reference) on this host's CPU to set
BASELINE_ITERS_PER_SEC.

Run on whatever `jax.devices()` offers (the real TPU chip under the driver).
"""
import json
import time

import numpy as np

# Reference CLI built from /root/reference, same data + config, this host's
# CPU (1 core), measured 2026-07-29: 5.087 s/iter.  See BENCH_NOTES.md.
BASELINE_ITERS_PER_SEC = 0.197

N_ROWS = 1_000_000
N_FEATURES = 28
WARMUP = 5
MEASURED = 20


def make_data():
    rng = np.random.default_rng(42)
    X = rng.normal(size=(N_ROWS, N_FEATURES)).astype(np.float32)
    w = rng.normal(size=N_FEATURES) * (rng.random(N_FEATURES) > 0.3)
    logit = X @ w * 0.5 + 0.5 * rng.normal(size=N_ROWS)
    y = (logit > 0).astype(np.float64)
    return X.astype(np.float64), y


def main():
    import jax
    import lightgbm_tpu as lgb

    X, y = make_data()
    params = {"objective": "binary", "num_leaves": 63, "max_bin": 63,
              "learning_rate": 0.1, "min_data_in_leaf": 20, "verbose": -1,
              "metric": "auc"}
    train_set = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params=params, train_set=train_set)
    gbdt = bst._gbdt

    # warmup (compile)
    for _ in range(WARMUP):
        gbdt.train_one_iter(None, None, False)
    jax.block_until_ready(gbdt._score_dev)

    t0 = time.time()
    for _ in range(MEASURED):
        gbdt.train_one_iter(None, None, False)
    jax.block_until_ready(gbdt._score_dev)
    dt = time.time() - t0
    ips = MEASURED / dt

    # sanity: training must actually be learning
    auc = gbdt.get_eval_at(0)[0]
    assert auc > 0.7, "benchmark model failed to learn (auc=%.3f)" % auc

    print(json.dumps({
        "metric": "boosting_iters_per_sec_1Mx28_63leaves_63bins",
        "value": round(ips, 3),
        "unit": "iters/sec",
        "vs_baseline": round(ips / BASELINE_ITERS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
