"""Backfill the cross-run ledger from the historical bench artifacts.

The flagship trajectory predates the ledger (obs/ledger.py): rounds
live as hand-curated ``BENCH_r0*.json`` driver records, builder-witness
copies under ``bench_artifacts/``, and ``MULTICHIP_r0*.json`` smoke
outcomes.  This tool folds them into the ledger once so ``python -m
lightgbm_tpu obs trend`` shows the whole trajectory from day one
instead of starting blind at the PR that introduced the store.

Synthetic identity: backfilled records get run id ``bench-r0N`` /
``multichip-r0N`` and header time ``float(N)`` — monotone in round, and
obviously sub-epoch so the renderers show the round number, not a 1970
date.  Ingestion is idempotent (the ledger dedups on run id + time), so
re-running the backfill — or CI re-restoring an old cache — is a no-op.

Mapping:

* ``BENCH_r0N.json`` with a ``parsed`` block -> suite ``flagship``,
  metrics ``iters_per_sec`` + ``vs_baseline``; a null ``parsed`` (the
  wedged rounds) is recorded as status ``failed`` with no metrics so
  the trend's run count reflects the attempt without polluting stats;
* ``bench_artifacts/BENCH_*.json`` builder copies -> suite ``flagship``
  too (same cell — they are re-measurements of the same protocol),
  ``source`` naming the artifact;
* ``MULTICHIP_r0N.json`` -> suite ``multichip``, metric ``multichip_ok``
  1.0/0.0 so a future smoke flake shows as a step in the trend.

Usage:  python tools/ledger_backfill.py [--ledger DIR] [--repo DIR]
"""
import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu.obs.ledger import (Ledger, default_ledger_dir,  # noqa: E402
                                     LEDGER_REV)


def _shape_from_metric(name):
    """'boosting_iters_per_sec_1Mx28_63leaves_63bins' -> '1Mx28';
    'boosting_iters_per_sec_higgs10p5Mx28_...' -> 'higgs10p5Mx28'."""
    m = re.search(r"_([0-9a-zA-Z.]+x[0-9]+)_", str(name))
    return m.group(1) if m else "-"


def _record(run, t, suite, shape, status, metrics, source):
    return {"rev": LEDGER_REV, "run": run, "t": float(t), "suite": suite,
            "shape": shape, "device_kind": "tpu", "backend": "tpu",
            "schema": None, "world_size": 1, "git_rev": "",
            "git_dirty": False, "host": "", "argv": [],
            "status": status, "metrics": metrics, "source": source}


def bench_records(repo):
    out = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r0*.json"))):
        n = int(re.search(r"r0*(\d+)", os.path.basename(path)).group(1))
        with open(path) as f:
            doc = json.load(f)
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and parsed.get("value") is not None:
            metrics = {"iters_per_sec": float(parsed["value"])}
            if parsed.get("vs_baseline") is not None:
                metrics["vs_baseline"] = float(parsed["vs_baseline"])
            out.append(_record(
                "bench-r%02d" % n, n, "flagship",
                _shape_from_metric(parsed.get("metric", "")), "ok",
                metrics, os.path.basename(path)))
        else:
            # a wedged round (rc nonzero, nothing parsed): keep the
            # attempt visible without feeding the rolling stats
            out.append(_record("bench-r%02d" % n, n, "flagship", "-",
                               "failed", {"bench_rc": float(doc.get(
                                   "rc", -1))},
                               os.path.basename(path)))
    # builder-witness copies: same protocol, fractionally-offset time so
    # they sort after the driver record of their round
    arts = sorted(glob.glob(os.path.join(repo, "bench_artifacts",
                                         "BENCH_*.json")))
    for i, path in enumerate(arts):
        base = os.path.basename(path)
        m = re.search(r"r0*(\d+)", base)
        n = int(m.group(1)) if m else 0
        with open(path) as f:
            doc = json.load(f)
        if doc.get("value") is None:
            continue
        metrics = {"iters_per_sec": float(doc["value"])}
        if doc.get("vs_baseline") is not None:
            metrics["vs_baseline"] = float(doc["vs_baseline"])
        out.append(_record(base.replace(".json", ""), n + 0.1 + 0.01 * i,
                           "flagship",
                           _shape_from_metric(doc.get("metric", "")),
                           "ok", metrics, "bench_artifacts/" + base))
    return out


def multichip_records(repo):
    out = []
    for path in sorted(glob.glob(os.path.join(repo,
                                              "MULTICHIP_r0*.json"))):
        n = int(re.search(r"r0*(\d+)", os.path.basename(path)).group(1))
        with open(path) as f:
            doc = json.load(f)
        ok = bool(doc.get("ok"))
        out.append(_record(
            "multichip-r%02d" % n, n, "multichip",
            "%ddev" % int(doc.get("n_devices", 0) or 0),
            "ok" if ok else "failed",
            {"multichip_ok": 1.0 if ok else 0.0},
            os.path.basename(path)))
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="backfill the cross-run ledger from BENCH_r0*/"
                    "MULTICHIP_r0* artifacts (idempotent)")
    ap.add_argument("--ledger", default="",
                    help="ledger directory (default: LGBM_TPU_LEDGER or "
                         "/tmp/lgbm_tpu_ledger)")
    ap.add_argument("--repo", default=REPO,
                    help="repository root holding the artifacts")
    args = ap.parse_args(argv)
    ledger = Ledger(args.ledger or default_ledger_dir())
    records = bench_records(args.repo) + multichip_records(args.repo)
    if not records:
        print("no BENCH_r0*/MULTICHIP_r0* artifacts under %s" % args.repo)
        return 1
    landed = sum(ledger.ingest_record(r) for r in records)
    print("backfill: %d artifact record(s), %d newly ingested -> %s"
          % (len(records), landed, ledger.dir))
    return 0


if __name__ == "__main__":
    sys.exit(main())
