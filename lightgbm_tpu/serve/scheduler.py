"""Async microbatch scheduler: coalesce concurrent predict requests.

A serving process sees many small concurrent requests; the device wants
few large batches.  ``MicrobatchScheduler`` sits between them: callers
``submit()`` feature blocks and get a ``concurrent.futures.Future``; a
single worker thread coalesces the queue head into one batch until it
reaches ``max_batch`` rows or the OLDEST queued request has waited
``max_delay_ms`` — the deadline that bounds p99 latency when traffic is
too thin to fill a bucket.  The batch then runs ONCE through the bucket
executable (serve/executable.py) and the result is split back per
request.

Correctness leans on row independence: every row of the batched program
computes exactly what it would compute alone (element-wise Kahan lanes,
no cross-row reductions), so a caller cannot tell — bit for bit —
whether its rows shared a bucket with strangers.  tests/test_serve.py
pins concurrent-vs-solo equality.

Requests with different semantics (raw vs converted, early-stop,
pred_contrib) carry a route key; only same-route neighbors coalesce.
Early-stop and contrib requests batch through the host predictor paths
(row-independent f64, identical to ``Booster.predict``), so the one
queue fronts every prediction flavor.
"""
from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future

import numpy as np

from ..obs.events import NULL_OBSERVER
from ..obs.metrics import (REGISTRY, observe_serve_batch,
                           observe_serve_request)
from ..utils.log import Log


class _Request:
    __slots__ = ("features", "n", "future", "t")

    def __init__(self, features, n, future, t):
        self.features = features
        self.n = n
        self.future = future
        self.t = t


class MicrobatchScheduler:
    """The generic coalescing core: a FIFO of (route, features) requests
    drained by one worker thread into per-route batches.

    ``runner(route, features)`` scores one concatenated (n, F) block and
    returns an array whose leading axis is rows; the scheduler slices it
    back per request.  Head-of-line batching preserves submission order:
    only the leading run of same-route requests coalesces, so a stream
    of mixed routes drains fairly.
    """

    def __init__(self, runner, max_batch: int = 8192,
                 max_delay_ms: float = 2.0, observer=None,
                 batch_event_every: int = 0, name: str = "serve",
                 bucket_for=None):
        self._runner = runner
        # route-aware bucket sizing for the pad/bucket accounting on
        # serve_batch events (rows == bucket when absent — host routes)
        self._bucket_for = bucket_for or (lambda route, rows: rows)
        self.max_batch = max(1, int(max_batch))
        self.max_delay_s = max(0.0, float(max_delay_ms)) / 1e3
        self.observer = observer if observer is not None else NULL_OBSERVER
        self.batch_event_every = max(0, int(batch_event_every))
        self.name = name
        self._queue = collections.deque()   # (route, _Request)
        self._cv = threading.Condition()
        self._closing = False
        self._batches = 0
        self._rows = 0
        self._pad_rows = 0
        self._max_depth = 0
        self._inflight = REGISTRY.gauge(
            "lgbm_serve_queue_depth",
            "requests waiting in the microbatch queue")
        self._worker = threading.Thread(
            target=self._loop, name="%s-microbatch" % name, daemon=True)
        self._worker.start()

    # ------------------------------------------------------------- submit
    def submit(self, route, features, n_rows: int) -> Future:
        """Enqueue one request; resolves to the route runner's output
        rows for this request (exceptions propagate to the future)."""
        fut = Future()
        req = _Request(features, int(n_rows), fut, time.perf_counter())
        with self._cv:
            if self._closing:
                raise RuntimeError("%s: scheduler is closed" % self.name)
            self._queue.append((route, req))
            depth = len(self._queue)
            self._max_depth = max(self._max_depth, depth)
            self._inflight.set(depth)
            self._cv.notify()
        return fut

    # ------------------------------------------------------------- worker
    def _head_rows(self, route) -> int:
        rows = 0
        for r, req in self._queue:
            if r != route:
                break
            rows += req.n
        return rows

    def _pop_batch(self, route):
        """The leading same-route run, capped at max_batch rows (a
        single oversized request still pops alone — the runner chunks)."""
        batch = []
        rows = 0
        while self._queue and self._queue[0][0] == route:
            req = self._queue[0][1]
            if batch and rows + req.n > self.max_batch:
                break
            self._queue.popleft()
            batch.append(req)
            rows += req.n
        self._inflight.set(len(self._queue))
        return batch

    def _loop(self):
        while True:
            with self._cv:
                while not self._queue and not self._closing:
                    self._cv.wait()
                if not self._queue:
                    return                        # closing, drained
                route, head = self._queue[0]
                deadline = head.t + self.max_delay_s
                while not self._closing:
                    if self._head_rows(route) >= self.max_batch:
                        break
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                batch = self._pop_batch(route)
            try:
                self._run_batch(route, batch)
            except Exception as e:        # the worker must never die
                Log.warning("%s: microbatch worker error: %s",
                            self.name, e)
                for r in batch:
                    if not r.future.done():
                        try:
                            r.future.set_exception(e)
                        except Exception:
                            pass

    def _run_batch(self, route, batch):
        # claim every future first: a request cancelled while queued
        # drops out here, and a claimed future can no longer be
        # cancelled, so set_result/set_exception below cannot raise
        batch = [r for r in batch
                 if r.future.set_running_or_notify_cancel()]
        if not batch:
            return
        t0 = time.perf_counter()
        queue_s = t0 - batch[0].t
        try:
            if len(batch) == 1:
                feats = batch[0].features
            else:
                feats = np.concatenate([r.features for r in batch])
            out = self._runner(route, feats)
        except Exception as e:                    # surface per caller
            for r in batch:
                r.future.set_exception(e)
            return
        now = time.perf_counter()
        lo = 0
        for r in batch:
            # copy, not a view: callers own their result array and must
            # not be able to corrupt batch neighbors through it
            r.future.set_result(out[lo:lo + r.n].copy())
            lo += r.n
            observe_serve_request(now - r.t)
        rows = lo
        self._batches += 1
        self._rows += rows
        exec_s = now - t0
        bucket = self._bucket_for(route, rows)
        pad = max(bucket - rows, 0)
        self._pad_rows += pad
        observe_serve_batch(route, rows, pad, bucket, queue_s, exec_s)
        obs = self.observer
        if (obs.enabled and self.batch_event_every
                and self._batches % self.batch_event_every == 0):
            obs.event("serve_batch", route=str(route), rows=rows,
                      bucket=bucket, pad=pad, requests=len(batch),
                      queue_s=round(queue_s, 6), exec_s=round(exec_s, 6))

    # -------------------------------------------------------------- admin
    def stats(self) -> dict:
        return {"batches": self._batches, "rows": self._rows,
                "pad_rows": self._pad_rows,
                "max_queue_depth": self._max_depth}

    def close(self):
        """Flush the queue and stop the worker; idempotent."""
        with self._cv:
            if self._closing and not self._worker.is_alive():
                return
            self._closing = True
            self._cv.notify_all()
        self._worker.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False


class ServingPredictor:
    """The production predict front end: one object per model snapshot,
    shared by any number of submitting threads.

    * plain / raw predictions route through the AOT executable cache
      (device path, zero steady-state recompiles);
    * ``pred_early_stop`` / ``pred_contrib`` route through the host
      predictor paths — batched through the same queue, bit-identical
      to ``Booster.predict``;
    * a model whose features the device path cannot encode (mixed
      categorical/numerical use) falls back to the host predictor for
      every route, transparently.

    Output shapes match ``Booster.predict``: 1-D for single-output
    models, (n, k) for multiclass, (n, num_features + 1) for contrib.
    """

    def __init__(self, gbdt, num_iteration: int = -1, num_features=None,
                 max_batch: int = 8192, max_delay_ms: float = 2.0,
                 bucket_min: int = 64, donate: str = "auto",
                 devices=None, observer=None, batch_event_every: int = 0):
        from .executable import PredictExecutableCache
        self.gbdt = gbdt
        self.num_iteration = int(num_iteration)
        self.observer = observer if observer is not None else NULL_OBSERVER
        self.cache = None
        try:
            self.cache = PredictExecutableCache(
                gbdt, num_iteration=num_iteration,
                num_features=num_features, devices=devices, donate=donate,
                bucket_min=bucket_min, max_batch=max_batch,
                observer=self.observer)
        except ValueError as e:
            Log.warning("serve: device executables unavailable (%s); "
                        "serving from the host predictor", e)
        self._host_predictors = {}
        self._host_lock = threading.Lock()
        self.scheduler = MicrobatchScheduler(
            self._run_route, max_batch=max_batch,
            max_delay_ms=max_delay_ms, observer=self.observer,
            batch_event_every=batch_event_every,
            bucket_for=self._bucket_of)

    # -------------------------------------------------------------- routes
    def _bucket_of(self, route, rows):
        if self.cache is not None and route[0] == "dev" \
                and rows <= self.cache.max_batch:
            return self.cache.bucket_for(rows)
        return rows

    def _host_predictor(self, key):
        """Memoized host Predictor per (raw, early_stop, freq, margin)."""
        with self._host_lock:
            p = self._host_predictors.get(key)
            if p is None:
                from ..predictor import Predictor
                raw, early, freq, margin = key
                p = Predictor(self.gbdt, num_iteration=self.num_iteration,
                              raw_score=raw, early_stop=early,
                              early_stop_freq=freq,
                              early_stop_margin=margin)
                self._host_predictors[key] = p
            return p

    def _run_route(self, route, feats):
        kind = route[0]
        if kind == "dev":
            convert = route[1]
            out = self.cache.predict_batch(feats, convert=convert)
            return out[:, 0] if self.cache.k == 1 else out
        if kind == "contrib":
            return self.gbdt.pred_contrib(
                feats, num_iteration=self.num_iteration)
        # host routes: ("host", raw, width) and
        # ("es", raw, freq, margin, width) — width is part of the key
        # so only same-width requests coalesce (np.concatenate)
        if kind == "es":
            raw, freq, margin = route[1:4]
            return self._host_predictor((raw, True, freq, margin)
                                        ).predict(feats)
        return self._host_predictor((route[1], False, 10, 10.0)
                                    ).predict(feats)

    def _route_for(self, raw_score, pred_contrib, pred_early_stop,
                   freq, margin, width):
        if pred_contrib:
            return ("contrib", width)
        if pred_early_stop:
            return ("es", bool(raw_score), int(freq), float(margin),
                    width)
        if self.cache is not None:
            return ("dev", not raw_score)
        return ("host", bool(raw_score), width)

    # -------------------------------------------------------------- public
    def submit(self, features, raw_score: bool = False,
               pred_contrib: bool = False, pred_early_stop: bool = False,
               pred_early_stop_freq: int = 10,
               pred_early_stop_margin: float = 10.0) -> Future:
        """Enqueue one request; the future resolves to the same array
        ``Booster.predict`` would return for these rows."""
        X = np.asarray(features, np.float64)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        X = np.ascontiguousarray(X)
        route = self._route_for(raw_score, pred_contrib, pred_early_stop,
                                pred_early_stop_freq,
                                pred_early_stop_margin, X.shape[1])
        if route[0] == "dev":
            # one canonical width per dev route, so any two valid
            # requests can share a batch (too-narrow ones raise HERE,
            # in the caller, not inside a stranger's microbatch)
            X = self.cache.normalize(X)
        return self.scheduler.submit(route, X, X.shape[0])

    def predict(self, features, **kw) -> np.ndarray:
        """Synchronous convenience: submit + wait."""
        return self.submit(features, **kw).result()

    def warmup(self, sizes=(), raw_score: bool = False):
        """Pre-compile the bucket executables covering ``sizes`` row
        counts, then mark the cache warm so any later compile counts as
        a steady-state violation.  Returns the compiled bucket list."""
        buckets = []
        if self.cache is not None and sizes:
            buckets = self.cache.warmup(sizes, convert=not raw_score)
            self.cache.mark_warm()
        return buckets

    def stats(self) -> dict:
        out = dict(self.scheduler.stats())
        if self.cache is not None:
            out["executables"] = self.cache.stats()
        return out

    def close(self):
        self.scheduler.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
