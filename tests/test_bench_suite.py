"""tools/bench_suite.py child protocol: shape generation is process-stable,
the measurement JSON contract holds, and the dataset cache round-trips."""
import contextlib
import io
import json
import os
import zlib

import numpy as np


def test_suite_child_json_contract(monkeypatch):
    import tools.bench_suite as bs

    name = "tinytest"
    monkeypatch.setitem(bs.SHAPES, name, dict(n=6000, f=6, params={
        "objective": "binary", "metric": "auc", "num_leaves": 15,
        "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 1},
        warmup=1, measured=2, timeout=300))
    cache = bs.cache_path(name)
    if os.path.exists(cache):
        os.remove(cache)
    try:
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            bs.child(name)
        res = json.loads(buf.getvalue().strip().splitlines()[-1])
        for key in ("dt", "metric", "mode", "growth", "order", "W",
                    "wall"):
            assert key in res, key
        assert res["dt"] > 0 and 0.5 < res["metric"] <= 1.0
        assert os.path.exists(cache)
        # second run loads the cache and must agree on the metric
        buf2 = io.StringIO()
        with contextlib.redirect_stdout(buf2):
            bs.child(name)
        res2 = json.loads(buf2.getvalue().strip().splitlines()[-1])
        assert res2["metric"] == res["metric"]
    finally:
        if os.path.exists(cache):
            os.remove(cache)


def test_suite_shapes_are_process_stable(monkeypatch):
    """The seed must be a stable content hash — Python's salted hash()
    would give the TPU and reference-CLI arms different data.  Pin the
    actual bytes so a regression to hash(name) (stable in-process but
    not across) cannot stay green."""
    import tools.bench_suite as bs
    monkeypatch.setitem(bs.SHAPES, "tiny2", dict(
        n=2000, f=4, params={}, warmup=0, measured=1, timeout=60))
    X, y, _ = bs.make_shape("tiny2")
    rng = np.random.default_rng(zlib.crc32(b"tiny2"))
    w = rng.normal(size=4) * (rng.random(4) > 0.3)
    Xe = rng.normal(size=(2000, 4)).astype(np.float32)
    np.testing.assert_array_equal(X, Xe)
    ye = ((Xe @ w * 0.4 + 0.6 * rng.normal(size=2000)) > 0)
    np.testing.assert_array_equal(y, ye.astype(np.float64))
