"""lightgbm_tpu: a TPU-native gradient boosting framework.

A from-scratch JAX/XLA re-design of the capabilities of LightGBM
(tlikhomanenko/LightGBM line, incl. InfiniteBoost): histograms, split search
and partitioning run as fused XLA programs on TPU; data/feature-parallel
training lowers the reference's socket Allreduce to `jax.lax.psum` over an
ICI mesh; the Python API (`Dataset`, `Booster`, `train`, `cv`, sklearn
wrappers) and the text model format interchange with the reference
(python-package/lightgbm/__init__.py:26-30).
"""
from .basic import Booster, Dataset
from .engine import cv, train
from .utils.log import LightGBMError
from .callback import (EarlyStopException, early_stopping, print_evaluation,
                       record_evaluation, record_telemetry, reset_parameter)

try:
    from .sklearn import LGBMModel, LGBMRegressor, LGBMClassifier, LGBMRanker
    _SKLEARN_EXPORTS = ["LGBMModel", "LGBMRegressor", "LGBMClassifier",
                        "LGBMRanker"]
except ImportError:
    _SKLEARN_EXPORTS = []

try:
    from .plotting import plot_importance, plot_metric, plot_tree, create_tree_digraph
    _PLOT_EXPORTS = ["plot_importance", "plot_metric", "plot_tree",
                     "create_tree_digraph"]
except ImportError:
    _PLOT_EXPORTS = []

__version__ = "0.1.0"

__all__ = ["Dataset", "Booster", "train", "cv", "LightGBMError",
           "EarlyStopException", "early_stopping", "print_evaluation",
           "record_evaluation", "record_telemetry",
           "reset_parameter"] + _SKLEARN_EXPORTS + _PLOT_EXPORTS
