"""Sparse (CSR/CSC) ingestion without densification.

The sparse path (io/sparse.py + TrainingData.from_csc) must produce the
SAME constructed dataset as the dense path on the same values — same bin
mappers, same binned matrix, same trained model — while never building
the N x F float64 matrix.  Reference analog: SparseBin + the sparse
branches of DatasetLoader (sparse_bin.hpp:68, dataset_loader.cpp:840-930).
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.c_api import (LGBM_BoosterCreate,
                                LGBM_BoosterPredictForCSR,
                                LGBM_BoosterUpdateOneIter,
                                LGBM_DatasetCreateFromCSR)
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.io.sparse import (SparseColumns, csc_arrays, csr_to_csc,
                                    iter_dense_row_chunks)
from lightgbm_tpu.utils.config import Config

N, F = 3000, 40


def _sparse_fixture(density=0.05, seed=3):
    rng = np.random.default_rng(seed)
    dense = rng.normal(size=(N, F))
    dense[rng.random((N, F)) > density] = 0.0
    y = (dense[:, 0] + dense[:, 1] - dense[:, 2] > 0).astype(np.float64)
    # CSC arrays from the dense oracle
    cols, rows, vals = [], [], []
    colptr = [0]
    for j in range(F):
        nz = np.nonzero(dense[:, j])[0]
        rows.extend(nz.tolist())
        vals.extend(dense[nz, j].tolist())
        colptr.append(len(rows))
    sp = csc_arrays(np.asarray(colptr), np.asarray(rows),
                    np.asarray(vals), N)
    return dense, sp, y


def test_csc_construction_matches_dense():
    dense, sp, y = _sparse_fixture()
    cfg = Config({"max_bin": 63, "min_data_in_leaf": 5, "verbose": -1,
                  "enable_bundle": False})
    td_d = TrainingData.from_matrix(dense, label=y, config=cfg)
    td_s = TrainingData.from_csc(sp, label=y, config=cfg)
    assert td_s.used_feature_idx == td_d.used_feature_idx
    np.testing.assert_array_equal(td_s.num_bin_arr, td_d.num_bin_arr)
    np.testing.assert_array_equal(td_s.default_bin_arr, td_d.default_bin_arr)
    np.testing.assert_array_equal(td_s.binned, td_d.binned)


def test_csc_construction_matches_dense_with_efb():
    dense, sp, y = _sparse_fixture()
    cfg = Config({"max_bin": 63, "min_data_in_leaf": 5, "verbose": -1,
                  "enable_bundle": True})
    td_d = TrainingData.from_matrix(dense, label=y, config=cfg)
    td_s = TrainingData.from_csc(sp, label=y, config=cfg)
    assert (td_s.bundle is None) == (td_d.bundle is None)
    if td_s.bundle is not None:
        assert td_s.bundle.groups == td_d.bundle.groups
    np.testing.assert_array_equal(td_s.binned, td_d.binned)


def test_sparse_training_matches_dense():
    dense, sp, y = _sparse_fixture()
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
              "min_data_in_leaf": 5, "learning_rate": 0.2, "verbose": -1}
    bst_d = lgb.train(params, lgb.Dataset(dense, label=y),
                      num_boost_round=8)
    bst_s = lgb.train(params, lgb.Dataset(sp, label=y), num_boost_round=8)
    assert bst_d.model_to_string() == bst_s.model_to_string()
    # sparse prediction (chunked densify) == dense prediction
    p_d = bst_d.predict(dense)
    p_s = bst_s.predict(sp)
    np.testing.assert_allclose(p_s, p_d, rtol=1e-12)


def test_sparse_validation_alignment():
    dense, sp, y = _sparse_fixture()
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
              "min_data_in_leaf": 5, "verbose": -1}
    train = lgb.Dataset(sp, label=y, params=params)
    valid = train.create_valid(sp, label=y)
    res = {}
    lgb.train(params, train, num_boost_round=5, valid_sets=[valid],
              valid_names=["v"], evals_result=res,
              callbacks=[])
    assert "v" in res


def test_csr_roundtrip_and_chunks():
    dense, sp, y = _sparse_fixture()
    # CSR arrays from the dense oracle
    indptr = [0]
    cols, vals = [], []
    for i in range(N):
        nz = np.nonzero(dense[i])[0]
        cols.extend(nz.tolist())
        vals.extend(dense[i, nz].tolist())
        indptr.append(len(cols))
    sp2 = csr_to_csc(np.asarray(indptr), np.asarray(cols),
                     np.asarray(vals), F)
    np.testing.assert_array_equal(sp2.colptr, sp.colptr)
    np.testing.assert_array_equal(sp2.indices, sp.indices)
    np.testing.assert_array_equal(sp2.values, sp.values)
    # chunked densify reproduces the dense matrix
    rebuilt = np.zeros_like(dense)
    for s, block in iter_dense_row_chunks(sp2, chunk=700):
        rebuilt[s:s + block.shape[0]] = block
    np.testing.assert_array_equal(rebuilt, dense)


def test_scipy_ducktype():
    dense, sp, y = _sparse_fixture()

    class FakeCSC:
        shape = (N, F)
        indptr = np.asarray(sp.colptr, np.int32)
        indices = np.asarray(sp.indices, np.int32)
        data = sp.values

        def tocsc(self):
            return self

        def sort_indices(self):
            pass

    ds = lgb.Dataset(FakeCSC(), label=y,
                     params={"verbose": -1, "max_bin": 63})
    ds.construct()
    cfg = Config({"max_bin": 63, "verbose": -1})
    td_s = TrainingData.from_csc(sp, label=y, config=cfg)
    np.testing.assert_array_equal(ds._handle.binned, td_s.binned)


def test_c_api_sparse_create_and_predict():
    dense, sp, y = _sparse_fixture()
    indptr = [0]
    cols, vals = [], []
    for i in range(N):
        nz = np.nonzero(dense[i])[0]
        cols.extend(nz.tolist())
        vals.extend(dense[i, nz].tolist())
        indptr.append(len(cols))
    h = LGBM_DatasetCreateFromCSR(np.asarray(indptr), np.asarray(cols),
                                  np.asarray(vals), F,
                                  "objective=binary num_leaves=15 "
                                  "max_bin=63 verbose=-1")
    from lightgbm_tpu import c_api
    c_api.LGBM_DatasetSetField(h, "label", y)
    bh = LGBM_BoosterCreate(h, "objective=binary num_leaves=15 "
                            "max_bin=63 verbose=-1")
    for _ in range(3):
        LGBM_BoosterUpdateOneIter(bh)
    p = LGBM_BoosterPredictForCSR(bh, np.asarray(indptr),
                                  np.asarray(cols), np.asarray(vals), F)
    assert p.shape[0] == N and np.isfinite(p).all()


def test_sparse_subset_matches_dense_subset():
    dense, sp, y = _sparse_fixture()
    params = {"verbose": -1, "max_bin": 63}
    idx = np.arange(0, N, 3)
    ds = lgb.Dataset(sp, label=y, params=params)
    ds.construct()
    sub = ds.subset(idx)
    sub.construct()
    dd = lgb.Dataset(dense, label=y, params=params)
    dd.construct()
    dsub = dd.subset(idx)
    dsub.construct()
    np.testing.assert_array_equal(sub._handle.binned, dsub._handle.binned)


def test_sparse_nan_values_match_dense():
    dense, sp, y = _sparse_fixture()
    # inject NaNs as explicit sparse entries
    dense = dense.copy()
    vals = sp.values.copy()
    vals[::17] = np.nan
    sp2 = SparseColumns(sp.colptr, sp.indices, vals, sp.num_row, sp.num_col)
    cols = np.repeat(np.arange(F), np.diff(sp.colptr))
    dense[sp.indices[::17], cols[::17]] = np.nan
    cfg = Config({"max_bin": 63, "verbose": -1, "enable_bundle": True,
                  "use_missing": True})
    td_d = TrainingData.from_matrix(dense, label=y, config=cfg)
    td_s = TrainingData.from_csc(sp2, label=y, config=cfg)
    assert (td_s.bundle is None) == (td_d.bundle is None)
    np.testing.assert_array_equal(td_s.binned, td_d.binned)
