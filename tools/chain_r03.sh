#!/bin/bash
# Round-3 continuation chain: fire the flagship bench + suite arms the
# moment the A/B runner exits, so no tunnel window is wasted.
cd /root/repo
while pgrep -f "tpu_ab2.py 999424" > /dev/null; do sleep 60; done
echo "[chain] A/B finished at $(date -u)" >> /tmp/chain_r03.log
python bench.py > /tmp/bench_r03.out 2> /tmp/bench_r03.err
echo "[chain] bench rc=$? at $(date -u)" >> /tmp/chain_r03.log
python tools/bench_suite.py higgs higgs_w64 epsilon epsilon_p16 msltr expo_cat \
  >> /tmp/chain_r03.log 2>&1
echo "[chain] suite rc=$? at $(date -u)" >> /tmp/chain_r03.log
