"""Phase clocks and per-entry-point timers for the training loop.

JAX dispatch is asynchronous: a host-side ``time.perf_counter()`` around a
jitted call measures dispatch cost, not device time.  Device-accurate
timing requires fencing the result with ``jax.block_until_ready`` — which
also breaks the async pipeline, so every timer here takes fencing as a
parameter and the caller (RunObserver) decides per the ``obs_timing``
mode.  All timers are plain-Python and allocation-light; none of them is
on the disabled path (NULL_OBSERVER never constructs one).
"""
from __future__ import annotations

import time

# process-global count of host<->device synchronizations issued through
# fence().  Every deliberate block_until_ready in the training stack
# routes through fence() so this is a complete audit: a default run
# (NULL observer, no autotune probe) must leave it unchanged across
# training — asserted by bench.py --dry.
_FENCE_COUNT = 0


def fence_count() -> int:
    """Total fence()/fenced_get() syncs issued (sync audit)."""
    return _FENCE_COUNT


def fence(value):
    """Block until ``value`` (array / pytree / None) is device-complete.

    None-safe and forgiving: values that are not JAX types (python
    scalars, numpy arrays) pass through untouched, so call sites can hand
    over whatever the phase produced without type checks.
    """
    global _FENCE_COUNT
    if value is None:
        return
    try:
        import jax
        _FENCE_COUNT += 1
        jax.block_until_ready(value)
    except Exception:       # non-jax value, or backend already torn down
        pass


def fenced_get(value):
    """``jax.device_get`` that counts itself in the sync audit.

    The counted twin of ``fence()`` for readbacks that need the host
    value, not just completion: tree materialization, the periodic
    stop check, prediction drains.  A bare ``jax.device_get`` on the
    hot path is invisible to ``fence_count()`` (and flagged by the
    ``sync-device-get`` lint rule); this is the sanctioned spelling.
    Non-jax values pass through ``jax.device_get`` unchanged, so call
    sites need no type checks.
    """
    global _FENCE_COUNT
    import jax
    _FENCE_COUNT += 1
    return jax.device_get(value)


class PhaseClock:
    """Splits one iteration into named laps (boost / grow / partition /
    update / eval) and accumulates per-phase totals across iterations.

    ``begin()`` starts the iteration, ``lap(name, value)`` closes the
    current phase (optionally fencing ``value`` first), ``end(value)``
    closes the iteration and returns ``(total_s, {phase: s})``.  Repeated
    laps with the same name within one iteration accumulate (the tree
    loop laps "grow" once per tree).

    ``current`` is the name of the lap most recently crossed within the
    in-flight iteration (None between iterations) — the phase tag the
    sampling profiler (obs/prof.py) stamps on samples.  A plain
    attribute written by the training thread and read racily by the
    sampler: a torn read mis-tags one sample, which the window
    aggregate does not care about.
    """

    def __init__(self, fence_laps=True):
        self.fence_laps = bool(fence_laps)
        self.current = None         # last lap crossed, None between iters
        self._totals = {}           # phase -> cumulative seconds, all iters
        self._phases = {}           # phase -> seconds, current iteration
        self._t_begin = 0.0
        self._t_last = 0.0

    def begin(self):
        self._phases = {}
        self.current = None
        self._t_begin = self._t_last = time.perf_counter()

    def lap(self, name, value=None):
        if self.fence_laps:
            fence(value)
        now = time.perf_counter()
        self._phases[name] = self._phases.get(name, 0.0) + (now - self._t_last)
        self._t_last = now
        self.current = name

    def end(self, value=None):
        fence(value)
        self.current = None
        now = time.perf_counter()
        total = now - self._t_begin
        # time since the last lap (or begin) that no lap() claimed
        tail = now - self._t_last
        if tail > 0.0 and self._phases:
            self._phases["other"] = self._phases.get("other", 0.0) + tail
        phases = self._phases
        self._phases = {}
        for k, v in phases.items():
            self._totals[k] = self._totals.get(k, 0.0) + v
        return total, phases

    def totals(self):
        return dict(self._totals)


class EntryTimers:
    """Compile-vs-execute split per jitted entry point.

    The first fenced call of a jitted function pays trace + XLA compile
    (+ one execute); steady-state calls pay execute only.  ``record``
    returns True exactly once per entry name — the caller emits a
    ``compile`` event for that call — and folds every later call into
    execute statistics.
    """

    def __init__(self):
        self._entries = {}   # name -> stats dict

    def record(self, name, dt):
        st = self._entries.get(name)
        if st is None:
            self._entries[name] = {"first_s": dt, "exec_n": 0,
                                   "exec_total_s": 0.0, "exec_min_s": 0.0,
                                   "exec_max_s": 0.0}
            return True
        st["exec_n"] += 1
        st["exec_total_s"] += dt
        if st["exec_n"] == 1 or dt < st["exec_min_s"]:
            st["exec_min_s"] = dt
        if dt > st["exec_max_s"]:
            st["exec_max_s"] = dt
        return False

    def summary(self):
        out = {}
        for name, st in self._entries.items():
            n = st["exec_n"]
            out[name] = {
                "first_s": st["first_s"],
                "exec_n": n,
                "exec_total_s": st["exec_total_s"],
                "exec_mean_s": (st["exec_total_s"] / n) if n else 0.0,
                "exec_min_s": st["exec_min_s"],
                "exec_max_s": st["exec_max_s"],
                # compile estimate: first call minus a steady-state execute
                "compile_est_s": max(0.0, st["first_s"] -
                                     ((st["exec_total_s"] / n) if n
                                      else 0.0)),
            }
        return out


class OrchestrationClock:
    """Host time BETWEEN device program submissions within one iteration.

    Construction marks the iteration start; ``enter()``/``exit()``
    bracket each device-entry dispatch (the jitted call itself, which is
    asynchronous — its wall time is queueing, not orchestration); the
    remainder is the host's own per-iteration glue: gradient reshapes,
    padding, ``.at[].set`` staging, bookkeeping Python.  That remainder
    is the ``host_orchestration_s`` field on the schema-11 ``iter``
    event — the quantity the fused iteration (ops/fused_iter.py) is
    built to drive to ~0.  Never fences: measuring must not perturb the
    async pipeline.
    """

    __slots__ = ("_t0", "_t_enter", "_inside")

    def __init__(self):
        self._t0 = time.perf_counter()
        self._t_enter = 0.0
        self._inside = 0.0

    def enter(self):
        self._t_enter = time.perf_counter()

    def exit(self):
        self._inside += time.perf_counter() - self._t_enter

    def host_seconds(self) -> float:
        """Elapsed since construction minus time spent inside dispatches."""
        return max(0.0, (time.perf_counter() - self._t0) - self._inside)
