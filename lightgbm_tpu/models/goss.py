"""GOSS booster (src/boosting/goss.hpp).

Gradient-based One-Side Sampling: keep the top ``top_rate`` fraction of rows
by |g*h| (summed over classes), sample ``other_rate`` of the rest uniformly
and amplify their gradients by ``(cnt - top_k) / other_k``
(goss.hpp:79-125).  No sampling for the first ``1 / learning_rate``
iterations (goss.hpp:128-130).

TPU-native: the whole selection runs on device (top-k threshold via
jnp.partition-style sort, uniform rest-sample via a per-iteration hashed
key), producing the row multiplier the learner consumes plus rescaled
gradients — no host round-trip.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils.log import Log
from .gbdt import GBDT


class GOSS(GBDT):
    def __init__(self, config, train_data=None, objective=None,
                 training_metrics=()):
        super().__init__(config, train_data, objective, training_metrics)
        if config.bagging_freq > 0 and config.bagging_fraction != 1.0:
            Log.fatal("cannot use bagging in GOSS")
        Log.info("Using GOSS")
        if train_data is not None:
            self.bag_data_cnt = self.num_data

    def _bagging_with_grad(self, it: int, g_dev, h_dev):
        cfg = self.config
        self.row_mult = None
        if it < int(1.0 / cfg.learning_rate):
            return g_dev, h_dev
        n = self.num_data
        top_k = max(1, int(n * cfg.top_rate))
        other_k = int(n * cfg.other_rate)
        if other_k <= 0:
            return g_dev, h_dev
        multiply = (n - top_k) / other_k
        key = jax.random.PRNGKey(cfg.bagging_seed + it)

        absg = jnp.sum(jnp.abs(g_dev * h_dev), axis=0)
        # threshold = top_k-th largest |g*h| (ArgMaxAtK, goss.hpp:90-92)
        threshold = -jnp.sort(-absg)[top_k - 1]
        is_top = absg >= threshold
        # uniform exact-count sample of the rest: rank random keys, keep the
        # other_k smallest among non-top rows
        u = jax.random.uniform(key, (n,))
        u = jnp.where(is_top, jnp.inf, u)
        kth = jnp.sort(u)[other_k - 1]
        sampled = (~is_top) & (u <= kth)
        mult = jnp.where(is_top | sampled, 1.0, 0.0).astype(g_dev.dtype)
        scale = jnp.where(sampled, multiply, 1.0).astype(g_dev.dtype)
        self.row_mult = mult
        return g_dev * scale[None, :], h_dev * scale[None, :]
