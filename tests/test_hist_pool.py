"""HistogramPool budget policy (feature_histogram.hpp:398-565 analog).

When the (L, F, B, 3) per-leaf cache exceeds histogram_pool_size, the
learner disables the cache and recomputes larger children instead of
obtaining them by subtraction — the model must be IDENTICAL either way,
for both growth engines.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _fit(growth, pool_mb):
    rng = np.random.default_rng(17)
    X = rng.normal(size=(3000, 10))
    y = (X[:, 0] + np.sin(X[:, 3] * 2) + 0.3 * rng.normal(size=3000) > 0.3)
    params = {"objective": "binary", "num_leaves": 31, "max_bin": 63,
              "min_data_in_leaf": 3, "verbose": -1, "tpu_growth": growth,
              "histogram_pool_size": pool_mb}
    bst = lgb.train(params,
                    lgb.Dataset(X, label=y.astype(np.float64),
                                params=params),
                    num_boost_round=4)
    return bst, X


@pytest.mark.parametrize("growth", ["exact", "wave"])
def test_pool_fallback_identical_model(growth):
    # 31 leaves x 10 cols x 64 bins x 3 x 4B ~ 0.24 MB -> 0.01 MB budget
    # forces the no-cache recompute path
    b_cache, X = _fit(growth, -1.0)
    b_nocache, _ = _fit(growth, 0.01)
    assert b_cache._gbdt.learner.cache_hists is True
    assert b_nocache._gbdt.learner.cache_hists is False
    # recompute vs parent-minus-sibling subtraction differ in f32 low bits,
    # which can flip near-tie split choices (the reference's pool eviction
    # has the same property) — the contract is equal-quality training, not
    # bit-identical trees
    p_c, p_n = b_cache.predict(X), b_nocache.predict(X)
    np.testing.assert_allclose(p_c, p_n, atol=5e-3)
    for b in (b_cache, b_nocache):
        assert all(t.num_leaves == 31 for t in b._gbdt.models)


def test_pool_auto_budget_boundaries():
    """The auto budget admits Higgs- and Epsilon-shaped caches (both fit a
    16 GB chip alongside the data) but rejects unbounded growth, and an
    explicit histogram_pool_size always wins."""
    from lightgbm_tpu.ops.learner import hist_cache_enabled
    from lightgbm_tpu.utils.config import Config
    cfg = Config({"verbose": -1})
    assert hist_cache_enabled(cfg, 255, 28, 64, 4)        # Higgs: 5.5 MB
    assert hist_cache_enabled(cfg, 255, 2000, 255, 4)     # Epsilon: 1.6 GB
    assert not hist_cache_enabled(cfg, 255, 8000, 255, 4)   # 6.2 GB: no
    tight = Config({"verbose": -1, "histogram_pool_size": 512.0})
    assert not hist_cache_enabled(tight, 255, 2000, 255, 4)
