# Convenience trainer + unloader — parity with R-package/R/lightgbm.R
# and lgb.unloader.R at the reference.

#' Train directly from a matrix + label (wraps lgb.train)
#'
#' @param data matrix / data.frame / lgb.Dataset
#' @param label labels (ignored when data is already an lgb.Dataset)
#' @param save_name model file written after training ("" skips saving)
#' @export
lightgbm <- function(data, label = NULL, weight = NULL, params = list(),
                     nrounds = 10L, verbose = 1L, eval_freq = 1L,
                     early_stopping_rounds = NULL,
                     save_name = "lightgbm.model", init_model = NULL,
                     ...) {
  dtrain <- data
  if (!lgb.is.Dataset(dtrain)) {
    dtrain <- lgb.Dataset(data, label = label, weight = weight)
  }
  valids <- list()
  if (verbose > 0L) valids$train <- dtrain
  bst <- lgb.train(params = params, data = dtrain, nrounds = nrounds,
                   valids = valids, verbose = verbose,
                   eval_freq = eval_freq,
                   early_stopping_rounds = early_stopping_rounds,
                   init_model = init_model, ...)
  if (is.character(save_name) && nzchar(save_name)) {
    lgb.save(bst, save_name)
  }
  bst
}

#' Drop the cached Python runtime handle (the reference's lgb.unloader
#' unloads lib_lightgbm; here the runtime is the reticulate module)
#' @export
lgb.unloader <- function(restore = TRUE, wipe = FALSE, envir = .GlobalEnv) {
  .lgb_env$mod <- NULL
  if (wipe) {
    drop <- Filter(function(nm) {
      obj <- get(nm, envir = envir)
      lgb.is.Dataset(obj) || lgb.is.Booster(obj)
    }, ls(envir = envir))
    rm(list = drop, envir = envir)
  }
  invisible(NULL)
}
