"""Shared metric computations for the training-parity harness.

One metric implementation evaluates BOTH frameworks' prediction files, so
the reference-vs-lightgbm_tpu comparison (docs/GPU-Performance.md:134-145
CPU-vs-GPU pattern) cannot be skewed by metric-code differences.
"""
import numpy as np


def load_tsv(path):
    data = np.loadtxt(path, delimiter="\t")
    return data[:, 0], data[:, 1:]


def load_query(path):
    return np.loadtxt(path, dtype=int).reshape(-1)


def logloss(y, p, eps=1e-15):
    p = np.clip(p, eps, 1 - eps)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


def auc(y, p):
    order = np.argsort(p, kind="mergesort")
    sp = p[order]
    ranks = np.empty(len(p))
    # tie-averaged ranks in O(N log N): equal-value runs share their mean
    base = np.arange(1, len(p) + 1, dtype=np.float64)
    starts = np.flatnonzero(np.concatenate(([True], sp[1:] != sp[:-1])))
    run_sums = np.add.reduceat(base, starts)
    run_lens = np.diff(np.concatenate((starts, [len(p)])))
    mean_per_run = run_sums / run_lens
    ranks[order] = np.repeat(mean_per_run, run_lens)
    npos = y.sum()
    nneg = len(y) - npos
    return float((ranks[y > 0].sum() - npos * (npos + 1) / 2)
                 / (npos * nneg))


def rmse(y, p):
    return float(np.sqrt(np.mean((y - p) ** 2)))


def ndcg_at(y, p, counts, k):
    """NDCG@k with LightGBM's 2^label - 1 gains (metric/dcg_calculator)."""
    out, pos = [], 0
    for c in counts:
        yy, pp = y[pos:pos + c], p[pos:pos + c]
        pos += c
        kk = min(k, c)
        disc = 1.0 / np.log2(np.arange(2, kk + 2))
        dcg = float(((2 ** yy[np.argsort(-pp, kind="mergesort")][:kk] - 1)
                     * disc).sum())
        idcg = float(((2 ** np.sort(yy)[::-1][:kk] - 1) * disc).sum())
        if idcg > 0:
            out.append(dcg / idcg)
    return float(np.mean(out))


def multi_logloss(y, prob, eps=1e-15):
    prob = np.clip(prob, eps, 1.0)
    n = len(y)
    return float(-np.mean(np.log(prob[np.arange(n), y.astype(int)])))
