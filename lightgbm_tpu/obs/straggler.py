"""Distributed straggler profiling: per-device arrival-time skew.

Under the distributed learners every tree's histogram psum is a
barrier: the collective finishes when the *slowest* shard arrives, so
one lagging device serializes the whole mesh — the per-device load
imbalance arxiv 1809.04559 measures as the dominant multi-GPU cost.
The host cannot see inside the jitted program, but it can time when
each shard's output *becomes readable*: fetching the per-shard pieces
of a row-sharded artifact one by one (``jax.Array.addressable_shards``
+ a tiny ``device_get`` each) turns shard completion order into host
wall-clock.

``StragglerProfiler`` samples this every ``obs_straggler_every``
iterations (sampled, because each sample is a fence and costs the async
pipeline).  Per sample it records the marginal wait per device — the
time that device kept the host blocked beyond the shards already done —
and derives

    ``skew = (max_wait - median_wait) / total_wait``

the fraction of the sample spent waiting on the single slowest device
beyond the typical one.  Each sample lands in the timeline as a
schema-v3 ``straggler`` event; skew above ``obs_straggler_warn_skew``
is routed through the PR-2 health channel (a ``health`` event with
``check="straggler_skew"`` + the monitors' warn counter); ``run_end``
carries the rolling summary with the slowest-device attribution
(which device was slowest, how often).

Single-device values (serial learner, CPU without a forced mesh) have
nothing to compare — the sampler counts the skip and stays silent,
so the config can be left on unconditionally.
"""
from __future__ import annotations

import time

from ..utils.log import Log


def _sharded_leaf(value):
    """First leaf of ``value`` with >1 addressable shards, or None."""
    import jax

    for leaf in jax.tree_util.tree_leaves(value):
        if not isinstance(leaf, jax.Array):
            continue
        try:
            shards = leaf.addressable_shards
        except Exception:
            continue
        if len(shards) > 1:
            return leaf, shards
    return None, None


def _axis_of(leaf):
    """The mesh axis name(s) the leaf is partitioned over, best-effort."""
    try:
        spec = getattr(leaf.sharding, "spec", None)
        if spec is None:
            return ""
        return ",".join(str(a) for a in spec if a is not None)
    except Exception:
        return ""


class StragglerProfiler:
    """Rolling straggler state driven by the observer's
    ``straggler_sample`` hook (every ``obs_straggler_every`` iters)."""

    def __init__(self, every=0, warn_skew=0.5, registry=None):
        if registry is None:
            from .metrics import REGISTRY
            registry = REGISTRY
        self._registry = registry
        self.every = max(0, int(every))
        self.warn_skew = float(warn_skew)
        self.samples = 0
        self.skipped_single = 0
        self.warned = 0
        self.max_skew = 0.0
        self.max_skew_it = -1
        self.slowest_counts = {}      # device id -> times it was slowest

    def due(self, it):
        return self.every > 0 and it % self.every == 0

    def sample(self, obs, it, value):
        """Time per-shard arrival of ``value``'s first sharded leaf and
        emit a ``straggler`` event.  A full fence: only call on the
        sampling cadence."""
        import numpy as np

        leaf, shards = _sharded_leaf(value)
        if leaf is None:
            self.skipped_single += 1
            return
        waits = []
        prev = time.perf_counter()
        for sh in shards:
            # a tiny device_get per shard: returns when THIS shard's
            # producer is done, so the marginal wait is attributable
            np.asarray(sh.data)
            now = time.perf_counter()
            waits.append((int(sh.device.id), now - prev))
            prev = now
        total = sum(w for _, w in waits)
        ordered = sorted(w for _, w in waits)
        median = ordered[len(ordered) // 2]
        slowest_id, max_wait = max(waits, key=lambda p: p[1])
        skew = (max_wait - median) / total if total > 0 else 0.0
        self.samples += 1
        self.slowest_counts[slowest_id] = \
            self.slowest_counts.get(slowest_id, 0) + 1
        if skew > self.max_skew:
            self.max_skew, self.max_skew_it = skew, it
        axis = _axis_of(leaf)
        obs.event("straggler", it=it, axis=axis,
                  devices=[{"id": d, "wait_s": round(w, 6)}
                           for d, w in waits],
                  skew=round(skew, 4), slowest=slowest_id,
                  total_s=round(total, 6))
        self._registry.counter(
            "lgbm_straggler_samples_total",
            "per-shard arrival-skew samples taken").inc()
        self._registry.gauge(
            "lgbm_straggler_max_skew",
            "worst observed per-device arrival skew this run").set(
                self.max_skew)
        if skew > self.warn_skew:
            self.warned += 1
            detail = {"skew": round(skew, 4), "slowest": slowest_id,
                      "axis": axis, "threshold": self.warn_skew}
            # route through the PR-2 health channel: same event shape,
            # same warn counter, so one reader sees every anomaly
            obs.event("health", check="straggler_skew", status="warn",
                      it=it, detail=detail)
            if obs.health is not None:
                obs.health.counts["warn"] += 1
            Log.warning("obs: straggler skew %.0f%% at iter %d (device "
                        "%d slowest on axis %r)", 100.0 * skew, it,
                        slowest_id, axis or "?")

    def summary(self):
        """Folded into run_end: rolling attribution of who straggled."""
        return {"every": self.every, "samples": self.samples,
                "skipped_single_device": self.skipped_single,
                "warned": self.warned, "warn_skew": self.warn_skew,
                "max_skew": round(self.max_skew, 4),
                "max_skew_it": self.max_skew_it,
                "slowest_counts": {str(k): v for k, v in
                                   sorted(self.slowest_counts.items())}}
