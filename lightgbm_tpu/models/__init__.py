from .tree import Tree
from .gbdt import GBDT
from .factory import create_boosting

__all__ = ["Tree", "GBDT", "create_boosting"]
