"""Out-of-core streaming ingest — parallel two-pass binning in bounded RAM.

Parity target: the reference's two-round loading + pipelined reader
(src/io/dataset_loader.cpp:554-660, include/LightGBM/utils/
pipeline_reader.h:18), extended along the lines of "Out-of-Core GPU
Gradient Boosting" (arxiv 2005.09148): stream chunks through a mergeable
per-feature sample sketch, freeze the BinMapper set, then re-stream and
bin — optionally appending straight to the mmap-able pre-binned format
(io/binned_format.py) so the cost is paid once.

  pass 0  plan chunks (text: byte-range scan at ~GB/s, no float parsing;
          .npy/ndarray/CSR: row ranges)
  pass 1  workers read ONLY the sampled rows of their chunks; the parent
          merges the per-chunk sketches in row order — identical to the
          in-memory path (same Random seed, same ascending sample
          indices, so mappers match bit for bit) -> BinMapper + EFB +
          the data-quality profile, all on the streamed sample
  pass 2  workers re-read chunks, bin against the frozen mappers, and
          either ship compact uint8/16 blocks back (in-memory assembly)
          or write binned shards directly to disk (out_dir mode, no bin
          data on the IPC pipe)

Both passes fan out over a fork-based multiprocessing pool (sources are
inherited copy-on-write; nothing large is pickled).  Platforms without
fork, or ``ooc_workers=1``, run the same code path serially.  Peak host
RSS is O(chunk + sample), never O(N x F) floats.

Dense csv/tsv/space formats stream; libsvm falls back to the in-memory
parser (its natural streaming form is the sparse path, io/sparse.py).
"""
from __future__ import annotations

import io
import multiprocessing as mp
import os
import sys
import time
import warnings
from typing import List, Optional

import numpy as np

from ..utils.log import Log
from ..utils.random import Random
from . import binned_format
from . import parser as _parser
from .bundle import bin_rows_grouped

CHUNK_BYTES = 64 << 20          # text chunk per read
DEFAULT_CHUNK_ROWS = 1 << 18    # row chunk for array/sparse sources


def _peak_rss_bytes() -> int:
    """Process high-water RSS (ru_maxrss is KB on Linux, bytes on mac)."""
    try:
        import resource
    except ImportError:                      # pragma: no cover
        return 0
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    return int(peak) * (1 if sys.platform == "darwin" else 1024)


# ---------------------------------------------------------------------------
# text helpers (kept from the seed loader; count/round semantics unchanged)

def _iter_line_chunks(filename: str, skip_header: bool):
    """Yield (first_row_index, list_of_lines) per text chunk."""
    row = 0
    with open(filename, "r") as f:
        if skip_header:
            f.readline()
        rest = ""
        while True:
            block = f.read(CHUNK_BYTES)
            if not block:
                if rest.strip():
                    yield row, [rest]
                return
            block = rest + block
            lines = block.split("\n")
            rest = lines.pop()            # possibly incomplete tail
            lines = [l for l in lines if l.strip()]
            if lines:
                yield row, lines
                row += len(lines)


def count_rows(filename: str, skip_header: bool) -> int:
    """Number of NON-BLANK data lines — must agree exactly with what
    the chunk plan yields (blank lines are skipped everywhere, matching
    the in-memory parser)."""
    _, n = plan_text_chunks(filename, skip_header)
    return n


def plan_text_chunks(filename: str, skip_header: bool,
                     chunk_bytes: Optional[int] = None):
    """Line-aligned byte ranges: [(row_start, n_rows, byte_lo, byte_hi)].

    A binary newline scan (~GB/s, no float parsing) that lets pass-1/2
    workers seek independently.  n_rows counts non-blank lines only.
    """
    chunk_bytes = chunk_bytes or CHUNK_BYTES
    chunks = []
    row = 0
    with open(filename, "rb") as f:
        if skip_header:
            f.readline()
        pend = b""
        pend_start = f.tell()
        while True:
            block = f.read(chunk_bytes)
            if not block:
                if pend.strip():
                    n = sum(1 for l in pend.split(b"\n") if l.strip())
                    chunks.append((row, n, pend_start,
                                   pend_start + len(pend)))
                    row += n
                break
            data = pend + block
            cut = data.rfind(b"\n")
            if cut < 0:
                pend = data
                continue
            body, pend = data[:cut + 1], data[cut + 1:]
            end = pend_start + len(body)
            n = sum(1 for l in body.split(b"\n") if l.strip())
            if n:
                chunks.append((row, n, pend_start, end))
                row += n
            pend_start = end
    return chunks, row


def _parse_lines(lines: List[str], sep: Optional[str]) -> np.ndarray:
    buf = io.StringIO("\n".join(lines))
    try:
        return np.loadtxt(buf, delimiter=sep, dtype=np.float64, ndmin=2)
    except ValueError:
        rows = [[_parser._safe_float(t)
                 for t in (l.split(sep) if sep else l.split())]
                for l in lines]
        return np.asarray(rows, dtype=np.float64)


def stream_supported(filename: str, has_header: bool) -> bool:
    with open(filename, "r") as f:
        if has_header:
            f.readline()
        head = [f.readline().rstrip("\r\n") for _ in range(2)]
    return _parser.detect_format([l for l in head if l]) != "libsvm"


# ---------------------------------------------------------------------------
# chunk sources — uniform (plan / read / read_sampled) over text, dense
# arrays (.npy path or in-memory), and CSC sparse

class TextSource:
    """Dense csv/tsv/space file; workers seek line-aligned byte ranges."""

    kind = "text"

    def __init__(self, filename: str, skip_header: bool, label_idx: int,
                 keep: Optional[List[int]],
                 chunk_bytes: Optional[int] = None,
                 chunk_rows: Optional[int] = None):
        self.filename = filename
        self.label_idx = label_idx
        self.keep = keep
        with open(filename, "r") as f:
            if skip_header:
                f.readline()
            first = ""
            while True:
                line = f.readline()
                if not line:
                    break
                first = line.rstrip("\r\n")
                if first.strip():
                    break
        fmt = _parser.detect_format([first] if first else [])
        if fmt == "libsvm":
            Log.fatal("streaming ingest handles dense formats; libsvm "
                      "goes through the sparse path")
        self.sep = _parser._SEP[fmt]
        if chunk_bytes is None and chunk_rows and first:
            # honor the row-denominated chunk budget (ooc_chunk_rows)
            # via a bytes-per-row estimate from the probed first line
            chunk_bytes = max(1, int(chunk_rows) * (len(first) + 1))
        self._chunks, self.num_rows = plan_text_chunks(
            filename, skip_header, chunk_bytes)
        if self.num_rows:
            feats, _ = self.to_features(_parse_lines([first], self.sep))
            self.num_features = feats.shape[1]
        else:
            self.num_features = 0

    def to_features(self, mat: np.ndarray):
        if 0 <= self.label_idx < mat.shape[1]:
            label = mat[:, self.label_idx].copy()
            feats = np.delete(mat, self.label_idx, axis=1)
        else:
            label = np.zeros(mat.shape[0], dtype=np.float64)
            feats = mat
        if self.keep is not None:
            feats = feats[:, self.keep]
        return feats, label

    def plan(self):
        return list(self._chunks)

    def _lines(self, desc) -> List[str]:
        _, _, lo, hi = desc
        with open(self.filename, "rb") as f:
            f.seek(lo)
            data = f.read(hi - lo)
        return [l for l in data.decode("utf-8", "replace").split("\n")
                if l.strip()]

    def read(self, desc):
        start = desc[0]
        feats, label = self.to_features(
            _parse_lines(self._lines(desc), self.sep))
        return start, feats, label

    def read_sampled(self, desc, wanted: np.ndarray):
        """Floats are parsed for the PICKED lines only (string slicing
        first), so pass 1 stays cheap on mostly-unsampled files."""
        start, nrows = desc[0], desc[1]
        sel = np.flatnonzero(wanted[start:start + nrows])
        if len(sel) == 0:
            return start, np.zeros((0, self.num_features), np.float64)
        lines = self._lines(desc)
        feats, _ = self.to_features(
            _parse_lines([lines[i] for i in sel], self.sep))
        return start, feats


_NPY_CACHE: dict = {}   # per-process .npy layout / fallback-memmap cache


def _npy_layout(path: str):
    """(data_offset, shape, dtype) of a C-order .npy, or None when the
    file needs the memmap fallback (Fortran order / exotic header).

    Chunk reads then go through plain seek+read into fresh buffers
    instead of a long-lived memmap: clean mapped pages count toward RSS
    while resident, so memmap-scanning a 4x-RAM file would show a peak
    watermark the size of the FILE — the bounded-memory contract needs
    read buffers that actually die with the chunk.
    """
    lay = _NPY_CACHE.get(path)
    if lay is None:
        lay = False
        try:
            with open(path, "rb") as f:
                version = np.lib.format.read_magic(f)
                if version == (1, 0):
                    shape, fortran, dtype = \
                        np.lib.format.read_array_header_1_0(f)
                elif version == (2, 0):
                    shape, fortran, dtype = \
                        np.lib.format.read_array_header_2_0(f)
                else:
                    fortran = True
                if not fortran and not dtype.hasobject:
                    lay = (f.tell(), shape, dtype)
        except Exception:
            lay = False
        _NPY_CACHE[path] = lay
    return lay or None


class MatrixSource:
    """Dense float matrix: an in-memory ndarray (fork-shared, zero copy)
    or a .npy path (each worker opens its own read-only memmap)."""

    def __init__(self, data, label=None,
                 chunk_rows: int = DEFAULT_CHUNK_ROWS):
        if isinstance(data, (str, os.PathLike)):
            self.kind = "npy"
            self.path: Optional[str] = str(data)
            arr = np.load(self.path, mmap_mode="r")
        else:
            self.kind = "matrix"
            self.path = None
            arr = np.asarray(data)
        if arr.ndim != 2:
            Log.fatal("streaming ingest needs a 2-D matrix, got shape %s",
                      arr.shape)
        self.num_rows, self.num_features = arr.shape
        self._arr = None if self.path is not None else arr
        self.label = (None if label is None
                      else np.asarray(label, dtype=np.float64))
        self.chunk_rows = max(int(chunk_rows), 1)

    def plan(self):
        return [(s, min(s + self.chunk_rows, self.num_rows))
                for s in range(0, self.num_rows, self.chunk_rows)]

    def _rows(self, idx_or_slice) -> np.ndarray:
        if self.path is not None:
            lay = _npy_layout(self.path)
            if lay is None:                      # Fortran-order fallback
                m = _NPY_CACHE.get(("mm", self.path))
                if m is None:
                    m = np.load(self.path, mmap_mode="r")
                    _NPY_CACHE[("mm", self.path)] = m
                return np.asarray(m[idx_or_slice], dtype=np.float64)
            offset, shape, dtype = lay
            if isinstance(idx_or_slice, slice):
                s, e = idx_or_slice.start, idx_or_slice.stop
                sel = None
            else:
                idx = np.asarray(idx_or_slice)
                s, e = int(idx.min()), int(idx.max()) + 1
                sel = idx - s
            row_items = int(shape[1])
            with open(self.path, "rb") as f:
                f.seek(offset + s * row_items * dtype.itemsize)
                buf = np.fromfile(f, dtype=dtype,
                                  count=(e - s) * row_items)
            block = buf.reshape(e - s, row_items)
            if sel is not None:
                block = block[sel]
            return np.asarray(block, dtype=np.float64)
        return np.asarray(self._arr[idx_or_slice], dtype=np.float64)

    def _label(self, s, e):
        if self.label is None:
            return np.zeros(e - s, dtype=np.float64)
        return self.label[s:e]

    def read(self, desc):
        s, e = desc
        return s, self._rows(slice(s, e)), self._label(s, e)

    def read_sampled(self, desc, wanted):
        s, e = desc
        sel = np.flatnonzero(wanted[s:e])
        if len(sel) == 0:
            return s, np.zeros((0, self.num_features), np.float64)
        return s, self._rows(s + sel)


class SparseSource:
    """CSC SparseColumns; chunks densify their row window per column via
    searchsorted (rows are sorted within a column by construction)."""

    kind = "sparse"

    def __init__(self, sp, label=None,
                 chunk_rows: int = DEFAULT_CHUNK_ROWS):
        self.sp = sp
        self.num_rows = int(sp.num_row)
        self.num_features = int(sp.num_col)
        self.label = (None if label is None
                      else np.asarray(label, dtype=np.float64))
        self.chunk_rows = max(int(chunk_rows), 1)

    def plan(self):
        return [(s, min(s + self.chunk_rows, self.num_rows))
                for s in range(0, self.num_rows, self.chunk_rows)]

    def _block(self, s, e) -> np.ndarray:
        out = np.zeros((e - s, self.num_features), dtype=np.float64)
        for j in range(self.num_features):
            rows, vals = self.sp.column(j)
            lo, hi = np.searchsorted(rows, (s, e))
            out[rows[lo:hi] - s, j] = vals[lo:hi]
        return out

    def read(self, desc):
        s, e = desc
        label = (np.zeros(e - s, np.float64) if self.label is None
                 else self.label[s:e])
        return s, self._block(s, e), label

    def read_sampled(self, desc, wanted):
        s, e = desc
        sel = np.flatnonzero(wanted[s:e])
        if len(sel) == 0:
            return s, np.zeros((0, self.num_features), np.float64)
        return s, self._block(s, e)[sel]


# ---------------------------------------------------------------------------
# pass 1: mergeable sample sketch

class SampleSketch:
    """Mergeable per-feature sketch over the binning sample.

    Each chunk contributes its sampled rows keyed by chunk start; merging
    is order-insensitive (parts re-sort on row offset), and the assembled
    matrix is byte-identical to ``data[sample_idx]`` because Random.sample
    yields ascending indices — which is what makes streamed BinMapper /
    EFB construction bit-exact vs the one-shot in-memory path.
    """

    def __init__(self, n_features: int):
        self.n_features = int(n_features)
        self.parts: List = []           # (row_start, (rows_sel, F) float64)

    def add_chunk(self, row_start: int, sampled_rows: np.ndarray):
        if sampled_rows.shape[0]:
            self.parts.append((int(row_start), sampled_rows))

    def merge(self, other: "SampleSketch"):
        self.parts.extend(other.parts)

    def sample_matrix(self) -> np.ndarray:
        if not self.parts:
            return np.zeros((0, self.n_features), dtype=np.float64)
        self.parts.sort(key=lambda p: p[0])
        return np.concatenate([p[1] for p in self.parts], axis=0)


# ---------------------------------------------------------------------------
# worker pool: fork-shared state, serial fallback

_WSTATE: dict = {}


def _init_worker(state: dict):
    _WSTATE.clear()
    _WSTATE.update(state)


def _fork_available() -> bool:
    return "fork" in mp.get_all_start_methods()


def resolve_workers(config, num_tasks: Optional[int] = None) -> int:
    w = int(getattr(config, "ooc_workers", 0) or 0)
    if w <= 0:
        w = os.cpu_count() or 1
    if not _fork_available():
        # spawn would re-import the full package (jax and all) per worker;
        # serial is strictly cheaper at these chunk sizes
        w = 1
    if num_tasks is not None:
        w = min(w, max(int(num_tasks), 1))
    return max(w, 1)


def _run_pool(workers: int, fn, tasks, state: dict) -> list:
    tasks = list(tasks)
    if workers <= 1 or len(tasks) <= 1 or not _fork_available():
        _init_worker(state)
        try:
            return [fn(t) for t in tasks]
        finally:
            _WSTATE.clear()
    ctx = mp.get_context("fork")
    with warnings.catch_warnings():
        # fork-with-threads warnings (numpy/jax register at-fork hooks);
        # workers only run numpy over inherited read-only arrays
        warnings.simplefilter("ignore")
        with ctx.Pool(min(workers, len(tasks)), initializer=_init_worker,
                      initargs=(state,)) as pool:
            return pool.map(fn, tasks, chunksize=1)


def _sketch_task(desc):
    return _WSTATE["source"].read_sampled(desc, _WSTATE["wanted"])


def _bin_block(feats: np.ndarray):
    """Bin one dense float chunk against the frozen mappers (worker)."""
    st = _WSTATE
    used = st["used"]
    cols = np.empty((feats.shape[0], len(used)), dtype=np.int64)
    for i, r in enumerate(used):
        cols[:, i] = st["mappers"][r].value_to_bin(feats[:, r])
    if st["bundle"] is not None:
        out = bin_rows_grouped(cols, st["bundle"], st["default_bin_arr"])
        return out.astype(st["dtype"], copy=False)
    return cols.astype(st["dtype"])


def _bin_task(item):
    idx, desc = item
    st = _WSTATE
    t0 = time.time()
    start, feats, label = st["source"].read(desc)
    out = _bin_block(feats)
    del feats
    t1 = time.time()
    if st["out_dir"]:
        crc = binned_format.write_shard(
            os.path.join(st["out_dir"], binned_format.shard_name(idx)), out)
        return (idx, start, out.shape[0], crc, label,
                t1 - t0, time.time() - t1)
    return idx, start, out, label, t1 - t0, 0.0


# ---------------------------------------------------------------------------
# the two-pass driver

def stream_construct(td, source, config, categorical=(), reference=None,
                     out_dir: Optional[str] = None) -> None:
    """Fill TrainingData ``td`` from any chunk source in bounded memory.

    out_dir: also persist the result as a binned dataset directory
    (io/binned_format.py); td is then backed by its mmap reader and no
    full bin matrix is materialized on the host.
    """
    n = int(source.num_rows)
    if n == 0:
        Log.fatal("Streaming source (%s) is empty", source.kind)
    td.num_data = n
    td.num_total_features = int(source.num_features)
    td.max_bin = config.max_bin
    plan = source.plan()
    workers = resolve_workers(config, len(plan))
    rss0 = _peak_rss_bytes()
    t0 = time.time()

    # ---- pass 1: sketch the sample, freeze the mappers
    if reference is not None:
        if td.num_total_features != reference.num_total_features:
            Log.fatal("Validation data has %d features, train data has %d",
                      td.num_total_features, reference.num_total_features)
        td._copy_binning_from(reference)
        sketch_s = 0.0
    else:
        sample_cnt = min(config.bin_construct_sample_cnt, n)
        rng = Random(config.data_random_seed)
        sample_idx = np.asarray(rng.sample(n, sample_cnt))
        if len(sample_idx) == 0:
            sample_idx = np.arange(n, dtype=np.int32)
        wanted = np.zeros(n, dtype=bool)
        wanted[sample_idx] = True
        sketch = SampleSketch(td.num_total_features)
        for part in _run_pool(workers, _sketch_task, plan,
                              {"source": source, "wanted": wanted}):
            sketch.add_chunk(*part)
        sample = sketch.sample_matrix()
        td._fit_mappers_from_sample(sample, config, categorical)
        del sample, sketch, wanted, sample_idx
        sketch_s = time.time() - t0

    # ---- pass 2: re-stream, bin against the frozen mappers
    t1 = time.time()
    f_used = len(td.used_feature_idx)
    if td.bundle is not None:
        out_cols = td.bundle.num_groups
        gmax = int(td.bundle.num_group_bins.max(initial=2))
        dtype = np.uint8 if gmax <= 256 else np.uint16
    else:
        out_cols = f_used
        max_num_bin = int(td.num_bin_arr.max()) if f_used else 2
        dtype = np.uint8 if max_num_bin <= 256 else np.uint16

    writer = None
    if out_dir:
        writer = binned_format.BinnedWriter(out_dir, out_cols, dtype)
    state = {"source": source, "mappers": td.bin_mappers,
             "used": td.used_feature_idx, "bundle": td.bundle,
             "default_bin_arr": td.default_bin_arr, "dtype": dtype,
             "out_dir": str(out_dir) if out_dir else None}
    label_out = np.zeros(n, dtype=np.float64)
    binned = None if out_dir else np.zeros((n, out_cols), dtype=dtype)
    results = _run_pool(workers, _bin_task, list(enumerate(plan)), state)
    bin_cpu = write_cpu = 0.0
    for res in sorted(results, key=lambda r: r[0]):
        if out_dir:
            _, start, rows, crc, label, b_dt, w_dt = res
            writer.append_written(rows, crc)
        else:
            _, start, block, label, b_dt, w_dt = res
            rows = block.shape[0]
            binned[start:start + rows] = block
        label_out[start:start + rows] = label
        bin_cpu += b_dt
        write_cpu += w_dt
    td.metadata.set_label(label_out)
    pass2_s = time.time() - t1
    # phase attribution: split pass-2 wall time by worker-measured ratio
    # (bin vs shard write overlap inside each worker)
    frac = bin_cpu / (bin_cpu + write_cpu) if (bin_cpu + write_cpu) else 1.0
    bin_s = pass2_s * frac
    write_s = pass2_s - bin_s

    if out_dir:
        writer.finalize(
            num_total_features=td.num_total_features,
            used_feature_idx=td.used_feature_idx,
            feature_names=(td.feature_names
                           or ["Column_%d" % i
                               for i in range(td.num_total_features)]),
            max_bin=td.max_bin,
            bin_mappers=td.bin_mappers,
            bundle_groups=(td.bundle.groups if td.bundle is not None
                           else None),
            metadata=td.metadata)
        td._binned_reader = binned_format.BinnedReader(out_dir,
                                                       verify=False)
        td.binned = None
    else:
        td.binned = binned
    td._note_construct_stats("stream:" + source.kind, rows=n,
                             chunks=len(plan), sketch_s=sketch_s,
                             bin_s=bin_s, write_s=write_s, workers=workers,
                             rss_before=rss0)


def stream_load(td, filename: str, config, label_idx: int,
                categorical: set, keep: Optional[List[int]],
                reference=None, out_dir: Optional[str] = None) -> None:
    """Fill TrainingData ``td`` from a dense text file in bounded memory.

    keep: post-label FEATURE column indices retained (ignore_column
    support); None keeps all.  reference: share a train set's mappers
    (validation alignment) and skip pass 1's fitting.
    """
    source = TextSource(filename, bool(config.has_header), label_idx, keep,
                        chunk_rows=int(getattr(config, "ooc_chunk_rows", 0)
                                       or 0) or None)
    stream_construct(td, source, config, categorical=categorical,
                     reference=reference, out_dir=out_dir)
