"""Composable training callbacks — API parity with python-package/callback.py.

``CallbackEnv`` carries the same fields; ``early_stopping`` raises
``EarlyStopException`` exactly like the reference (callback.py:48-204).
"""
from __future__ import annotations

import collections
from operator import gt, lt

from .utils.log import Log


class EarlyStopException(Exception):
    """Raised by callbacks to stop training (callback.py:14-24)."""

    def __init__(self, best_iteration, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "LightGBMCallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def _format_eval_result(value, show_stdv: bool = True) -> str:
    if len(value) == 4:
        return "%s's %s: %g" % (value[0], value[1], value[2])
    if len(value) == 5:
        if show_stdv:
            return "%s's %s: %g + %g" % (value[0], value[1], value[2], value[4])
        return "%s's %s: %g" % (value[0], value[1], value[2])
    raise ValueError("Wrong metric value")


def print_evaluation(period: int = 1, show_stdv: bool = True):
    def callback(env: CallbackEnv):
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            result = "\t".join(_format_eval_result(x, show_stdv)
                               for x in env.evaluation_result_list)
            Log.info("[%d]\t%s", env.iteration + 1, result)
    callback.order = 10
    return callback


def record_evaluation(eval_result: dict):
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")
    eval_result.clear()

    def init(env: CallbackEnv):
        # items are 4-tuples from train and 5-tuples (with stdv) from cv
        for item in env.evaluation_result_list:
            eval_result.setdefault(item[0], collections.OrderedDict())
            eval_result[item[0]].setdefault(item[1], [])

    def callback(env: CallbackEnv):
        if not eval_result:
            init(env)
        for item in env.evaluation_result_list:
            eval_result[item[0]][item[1]].append(item[2])
    callback.order = 20
    return callback


def record_telemetry(records: list):
    """Mirror the run observer's event timeline into ``records`` after
    every iteration (requires an ``obs_*`` param to enable telemetry —
    otherwise the list stays empty).  The list is REPLACED with the full
    timeline each call, so it is always a consistent snapshot — fold
    boosters under cv() append interleaved and are distinguished by each
    record's ``run`` id.  See docs/Observability.md for the schema."""
    if not isinstance(records, list):
        raise TypeError("records should be a list")
    records.clear()

    def callback(env: CallbackEnv):
        timeline = env.model.telemetry()
        if timeline and isinstance(timeline[0], list):
            # CVBooster broadcasts telemetry() across folds
            merged = []
            for fold in timeline:
                merged.extend(fold)
            timeline = merged
        records.clear()
        records.extend(timeline)
    callback.order = 25
    return callback


def _schedule_arity(fn) -> int:
    """1 or 2: how many positional args a reset_parameter schedule takes.

    Only REQUIRED positional parameters count — a default (lambda i,
    base=0.3: ...) or **kwargs must not flip a 1-arg schedule into the
    2-arg calling convention.  An explicit ``lgb_schedule_arity``
    attribute wins (the R bridge sets it on reticulate wrappers, whose
    Python signatures are otherwise (*args, **kwargs)); other
    unintrospectable callables default to 1, the python-surface
    convention.
    """
    import inspect
    marked = getattr(fn, "lgb_schedule_arity", None)
    try:
        if marked is not None and int(marked) in (1, 2):
            return int(marked)
    except (TypeError, ValueError):
        pass
    try:
        params = inspect.signature(fn).parameters.values()
    except (TypeError, ValueError):
        return 1
    required = sum(
        1 for p in params
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
        and p.default is p.empty)
    return 2 if required >= 2 else 1


def reset_parameter(**kwargs):
    """Per-iteration parameter schedule (callback.py reset_parameter):
    delegates to Booster.reset_parameter, which rebuilds the running
    learner config in place — num_leaves, lambdas, bagging, etc. all take
    effect, with a fast path for learning_rate.

    Schedules may be lists (one value per round), f(iteration), or —
    matching the reference R package's cb.reset.parameters contract —
    f(iteration, num_boost_round); arity is resolved once here."""
    arity = {k: _schedule_arity(v) for k, v in kwargs.items()
             if callable(v) and not isinstance(v, list)}

    def callback(env: CallbackEnv):
        new_parameters = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError("Length of list %s has to equal to 'num_boost_round'." % key)
                new_param = value[env.iteration - env.begin_iteration]
            elif callable(value):
                it = env.iteration - env.begin_iteration
                if arity[key] >= 2:
                    new_param = value(it,
                                      env.end_iteration -
                                      env.begin_iteration)
                else:
                    new_param = value(it)
            else:
                raise ValueError("Only list and callable values are supported "
                                 "as a mapping from boosting round index to new parameter value.")
            # only CHANGED values trigger a reset (reference callback.py):
            # an unchanged key would still force a per-iteration learner
            # rebuild and wipe the bagging state
            if new_param != env.params.get(key, None):
                new_parameters[key] = new_param
        if new_parameters:
            env.model.reset_parameter(new_parameters)
            env.params.update(new_parameters)
    callback.before_iteration = True
    callback.order = 10
    return callback


def early_stopping(stopping_rounds: int, verbose: bool = True):
    """Stop when no metric improves for stopping_rounds (callback.py:133-204)."""
    best_score = []
    best_iter = []
    best_score_list = []
    cmp_op = []

    def init(env: CallbackEnv):
        if not env.evaluation_result_list:
            raise ValueError("For early stopping, at least one dataset and "
                             "eval metric is required for evaluation")
        if verbose:
            Log.info("Train until valid scores didn't improve in %d rounds.",
                     stopping_rounds)
        for eval_ret in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            if eval_ret[3]:
                best_score.append(float("-inf"))
                cmp_op.append(gt)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lt)

    def callback(env: CallbackEnv):
        if not cmp_op:
            init(env)
        for i, eval_ret in enumerate(env.evaluation_result_list):
            score = eval_ret[2]
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            elif env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    Log.info("Early stopping, best iteration is:\n[%d]\t%s",
                             best_iter[i] + 1,
                             "\t".join(_format_eval_result(x)
                                       for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
    callback.order = 30
    return callback
