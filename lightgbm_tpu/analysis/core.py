"""graftlint core: findings, suppressions, baselines, the pass driver.

The analyzer proves the hot-path invariants of this repo WITHOUT a TPU:
every pass is either a pure-AST walk over the package sources or an
evaluation of the repo's own geometry/registry functions (tile planners,
event schema, config registry) on a CPU-only runner.  The runtime
asserts in ``bench.py --dry`` / ``bench_serve.py --dry`` stay as the
last line of defense; the lint gate moves the whole violation class to
CI compile time (docs/StaticAnalysis.md).

Structure: each pass module exposes ``PASS_NAME``, ``RULES`` (rule id ->
one-line description) and ``run(modules, repo_root) -> [Finding]``.
``run_lint`` drives them all, applies inline suppressions
(``# lint: ignore[rule-id] reason``) and an optional checked-in baseline
(``lint_baseline.json``), and returns the surviving findings.

A pass that crashes is an INTERNAL ERROR (exit 2 from the CLI, the
``bench_compare`` convention) — never silently an empty result: a lint
gate that fails open is worse than no gate.
"""
from __future__ import annotations

import ast
import json
import os
import re
import tokenize
from typing import Dict, List, NamedTuple, Optional, Tuple

PACKAGE_DIRNAME = "lightgbm_tpu"

# hot-path scope of the host-sync pass: modules where an implicit
# device->host sync stalls the async dispatch pipeline (training inner
# loop, fused iteration, serving data plane, and the multi-host comm /
# mesh layer — a stray sync there stalls EVERY rank at the next
# collective, not just the offender).  obs/ is deliberately OUT of
# scope — fencing is its job — with one exception: the live scrape
# plane (obs/live.py) promises "observing is free", so its server
# thread must never touch device values; the pass proves it.
HOT_PATH_PREFIXES = (
    "lightgbm_tpu/ops/",
    "lightgbm_tpu/models/gbdt.py",
    "lightgbm_tpu/serve/",
    "lightgbm_tpu/parallel/comm.py",
    "lightgbm_tpu/parallel/mesh.py",
    "lightgbm_tpu/obs/live.py",
)


class Finding(NamedTuple):
    """One structured lint finding (file:line, pass, rule, suggestion)."""
    rule: str            # stable rule id, the suppression key
    pass_name: str       # hostsync / recompile / events / config / vmem
    file: str            # repo-relative posix path ("" for registry rules)
    line: int            # 1-based (0 for whole-repo findings)
    message: str
    suggestion: str = ""

    def key(self) -> Tuple[str, str, int]:
        return (self.rule, self.file, self.line)

    def as_dict(self) -> dict:
        return {"rule": self.rule, "pass": self.pass_name,
                "file": self.file, "line": self.line,
                "message": self.message, "suggestion": self.suggestion}


class LintInternalError(Exception):
    """A pass itself failed — the CLI exits 2, never 0 (fail closed)."""


class SourceModule(NamedTuple):
    """One parsed package source file shared by every AST pass."""
    path: str            # repo-relative posix path
    text: str
    tree: ast.Module
    lines: List[str]     # 1-based indexing via lines[line - 1]

    def in_hot_path(self) -> bool:
        return any(self.path == p or self.path.startswith(p)
                   for p in HOT_PATH_PREFIXES)


def discover_files(repo_root: str,
                   extra_dirs: Tuple[str, ...] = ()) -> List[str]:
    """Repo-relative paths of every package .py file (plus extra dirs)."""
    out: List[str] = []
    roots = (PACKAGE_DIRNAME,) + tuple(extra_dirs)
    for rel_root in roots:
        top = os.path.join(repo_root, rel_root)
        if os.path.isfile(top) and top.endswith(".py"):
            out.append(rel_root.replace(os.sep, "/"))
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          repo_root)
                    out.append(rel.replace(os.sep, "/"))
    return out


def load_modules(repo_root: str,
                 files: Optional[List[str]] = None) -> List[SourceModule]:
    if files is None:
        files = discover_files(repo_root)
    mods: List[SourceModule] = []
    for rel in files:
        path = os.path.join(repo_root, rel)
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
            tree = ast.parse(text, filename=rel)
        except (OSError, SyntaxError) as e:
            raise LintInternalError("cannot parse %s: %s" % (rel, e))
        mods.append(SourceModule(rel, text, tree, text.splitlines()))
    return mods


# -- inline suppressions --------------------------------------------------
# ``# lint: ignore[rule-id]`` or ``# lint: ignore[a, b] -- reason`` on the
# line the finding anchors to.  Suppressions are parsed from the token
# stream (not a substring scan) so the marker inside a string literal
# never suppresses anything.
_IGNORE_RE = re.compile(
    r"#\s*lint:\s*ignore\[([A-Za-z0-9_,\-\s*]+)\]")


def collect_suppressions(mod: SourceModule) -> Dict[int, set]:
    """line -> set of suppressed rule ids ('*' = every rule)."""
    out: Dict[int, set] = {}
    try:
        tokens = tokenize.generate_tokens(iter(mod.text.splitlines(
            keepends=True)).__next__)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _IGNORE_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            out.setdefault(tok.start[0], set()).update(rules)
    except tokenize.TokenError:
        pass            # the AST parse already vouched for the file
    return out


def apply_suppressions(findings: List[Finding],
                       modules: List[SourceModule]) -> List[Finding]:
    by_file = {m.path: collect_suppressions(m) for m in modules}
    kept = []
    for f in findings:
        rules = by_file.get(f.file, {}).get(f.line, set())
        if f.rule in rules or "*" in rules:
            continue
        kept.append(f)
    return kept


# -- baseline -------------------------------------------------------------
# A checked-in ``lint_baseline.json`` grandfathers known findings so the
# gate can land before the last fix does.  Entries match on
# (rule, file, line); ``--write-baseline`` regenerates the file from the
# current findings.  This repo ships with an EMPTY baseline — every true
# positive the analyzer surfaced was fixed in the PR that added it.

def load_baseline(path: str) -> List[dict]:
    try:
        with open(path) as f:
            data = json.load(f)
    except OSError:
        return []
    except ValueError as e:
        raise LintInternalError("corrupt baseline %s: %s" % (path, e))
    entries = data.get("findings", []) if isinstance(data, dict) else data
    if not isinstance(entries, list):
        raise LintInternalError("baseline %s: expected a findings list"
                                % path)
    return entries


def write_baseline(path: str, findings: List[Finding]) -> None:
    data = {"findings": [{"rule": f.rule, "file": f.file, "line": f.line}
                         for f in findings]}
    with open(path, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")


def apply_baseline(findings: List[Finding],
                   entries: List[dict]) -> List[Finding]:
    keys = {(str(e.get("rule", "")), str(e.get("file", "")),
             int(e.get("line", 0))) for e in entries}
    return [f for f in findings if f.key() not in keys]


# -- pass driver ----------------------------------------------------------

def all_passes():
    from . import (config_coherence, events_schema, hostsync, recompile,
                   vmem)
    return (hostsync, recompile, events_schema, config_coherence, vmem)


def rule_catalog() -> Dict[str, Tuple[str, str]]:
    """rule id -> (pass name, description) over every registered pass."""
    out: Dict[str, Tuple[str, str]] = {}
    for p in all_passes():
        for rule, desc in p.RULES.items():
            out[rule] = (p.PASS_NAME, desc)
    return out


def run_lint(repo_root: str, passes=None,
             files: Optional[List[str]] = None,
             baseline_path: str = "") -> List[Finding]:
    """Run the passes and return suppression/baseline-surviving findings,
    sorted by (file, line, rule) for stable output."""
    modules = load_modules(repo_root, files=files)
    findings: List[Finding] = []
    for p in (passes if passes is not None else all_passes()):
        try:
            findings.extend(p.run(modules, repo_root))
        except LintInternalError:
            raise
        except Exception as e:
            raise LintInternalError("pass %s crashed: %r"
                                    % (p.PASS_NAME, e))
    findings = list(dict.fromkeys(findings))    # nested-scope dedup
    findings = apply_suppressions(findings, modules)
    if baseline_path:
        findings = apply_baseline(findings, load_baseline(baseline_path))
    return sorted(findings, key=lambda f: (f.file, f.line, f.rule))


# -- shared AST helpers ---------------------------------------------------

def dotted_name(node: ast.AST) -> str:
    """'jax.experimental.pallas' for nested Attribute/Name chains, ''
    for anything dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def root_name(node: ast.AST) -> str:
    """Leftmost Name of an Attribute/Subscript/Call chain, '' if dynamic."""
    while True:
        if isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        else:
            break
    return node.id if isinstance(node, ast.Name) else ""


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
