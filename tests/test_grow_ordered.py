"""Ordered-partition (segment) growth == masked growth, bit for bit.

The ordered schedule (ops/grow.py: SEG_AFTER masked splits, one stable sort,
then in-segment partitions + gathered segment histograms) only engages for
num_leaves - 1 > 128, which no other test reaches.  This pins it against the
legacy masked path on identical inputs: same tree arrays, same row->leaf map.
Reference semantics under test: DataPartition::Split (data_partition.hpp:
118-147) + ordered histogram iteration (serial_tree_learner.cpp:424-450).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.ops.grow import default_row_capacities, make_grow_fn
from lightgbm_tpu.ops.learner import build_split_params
from lightgbm_tpu.ops.split_finder import FeatureMeta
from lightgbm_tpu.utils.config import Config

N, F, LEAVES = 4096, 10, 255


def _setup(categorical=False):
    rng = np.random.default_rng(7)
    X = rng.normal(size=(N, F))
    if categorical:
        X[:, 0] = rng.integers(0, 12, size=N)
    y = (X[:, 1] + np.sin(X[:, 2] * 3) + 0.3 * rng.normal(size=N) > 0)
    cfg = Config({"num_leaves": LEAVES, "min_data_in_leaf": 1,
                  "max_bin": 63, "verbose": -1,
                  "categorical_feature": "0" if categorical else ""})
    td = TrainingData.from_matrix(X, label=y.astype(np.float64), config=cfg)
    meta = FeatureMeta(num_bin=jnp.asarray(td.num_bin_arr),
                       default_bin=jnp.asarray(td.default_bin_arr),
                       is_categorical=jnp.asarray(td.is_categorical_arr))
    p = 0.5
    grad = jnp.asarray((p - y).astype(np.float32))
    hess = jnp.full(N, p * (1 - p), jnp.float32)
    return cfg, td, meta, grad, hess


@pytest.mark.parametrize("categorical", [False, True])
def test_ordered_matches_masked(categorical):
    cfg, td, meta, grad, hess = _setup(categorical)
    params = build_split_params(cfg)
    nb = int(td.num_bin_arr.max())
    common = dict(hist_mode="scatter", max_depth=-1)
    kw_masked = dict(common, row_capacities=())
    kw_seg = dict(common, row_capacities=default_row_capacities(N))
    ones = jnp.ones(N, jnp.float32)
    fmask = jnp.ones(td.num_features, dtype=bool)
    args = (jnp.asarray(td.binned), grad, hess, ones, fmask)

    tree_m, lid_m = jax.jit(make_grow_fn(LEAVES, nb, meta, params,
                                         **kw_masked))(*args)
    tree_s, lid_s = jax.jit(make_grow_fn(LEAVES, nb, meta, params,
                                         **kw_seg))(*args)

    nl = int(tree_m.num_leaves)
    assert nl > 140, "tree too shallow to exercise the segment phase"
    assert int(tree_s.num_leaves) == nl
    np.testing.assert_array_equal(np.asarray(tree_s.split_feature),
                                  np.asarray(tree_m.split_feature))
    np.testing.assert_array_equal(np.asarray(tree_s.threshold_bin),
                                  np.asarray(tree_m.threshold_bin))
    np.testing.assert_array_equal(np.asarray(tree_s.left_child),
                                  np.asarray(tree_m.left_child))
    np.testing.assert_array_equal(np.asarray(tree_s.leaf_count),
                                  np.asarray(tree_m.leaf_count))
    np.testing.assert_allclose(np.asarray(tree_s.leaf_value),
                               np.asarray(tree_m.leaf_value), rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(lid_s), np.asarray(lid_m))


def test_ordered_matches_masked_with_bagging():
    cfg, td, meta, grad, hess = _setup()
    params = build_split_params(cfg)
    nb = int(td.num_bin_arr.max())
    rng = np.random.default_rng(3)
    mult = jnp.asarray((rng.random(N) > 0.3).astype(np.float32))
    fmask = jnp.ones(td.num_features, dtype=bool)
    args = (jnp.asarray(td.binned), grad, hess, mult, fmask)
    tree_m, lid_m = jax.jit(make_grow_fn(
        LEAVES, nb, meta, params, hist_mode="scatter", max_depth=-1,
        row_capacities=()))(*args)
    tree_s, lid_s = jax.jit(make_grow_fn(
        LEAVES, nb, meta, params, hist_mode="scatter", max_depth=-1,
        row_capacities=default_row_capacities(N)))(*args)
    assert int(tree_s.num_leaves) == int(tree_m.num_leaves)
    np.testing.assert_array_equal(np.asarray(tree_s.split_feature),
                                  np.asarray(tree_m.split_feature))
    np.testing.assert_array_equal(np.asarray(lid_s), np.asarray(lid_m))
