"""Serving benchmark: latency distribution + sustained QPS of the serve tier.

Drives ``Booster.serve()`` (lightgbm_tpu/serve) with a closed-loop load
generator — N submitter threads, each firing mixed-size requests and
waiting for its future — and reports p50/p99 request latency and
sustained queries/sec.  The numbers land in an obs JSONL timeline as a
``serve_bench`` event (next to the ``compile_attr`` and sampled
``serve_batch`` events the serve tier emits), so ``tools/bench_compare.py``
can gate ``serve_qps`` / ``serve_p99_s`` between runs and ``obs
recompiles --check`` can assert the steady state compiled nothing.

Prints ONE JSON line:
    {"metric", "value", "unit", "serve_qps", "serve_p50_s", "serve_p99_s",
     "requests", "path"}

``--dry`` is the CI smoke (JAX_PLATFORMS=cpu): a tiny model, a short
mixed-size burst, then hard asserts — schema-valid timeline, zero
steady-state compiles, every ``compile_attr`` entry compiled exactly
once, and serve output matching ``Booster.predict``.
"""
import argparse
import json
import os
import sys
import threading
import time

import numpy as np


def build_model(rows, features, leaves, rounds):
    import lightgbm_tpu as lgb
    rng = np.random.default_rng(11)
    X = rng.normal(size=(rows, features)).astype(np.float32)
    w = rng.normal(size=features)
    y = (X @ w > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": leaves, "max_bin": 63,
              "verbose": -1}
    bst = lgb.train(params, lgb.Dataset(X, label=y),
                    num_boost_round=rounds)
    return bst, np.asarray(X, np.float64)


def run_load(sp, X, requests, threads, sizes, seed=5):
    """Closed-loop load: each thread submits ``requests // threads``
    mixed-size blocks and waits for each future.  Returns (latencies,
    wall_s, rows_scored)."""
    lat = [[] for _ in range(threads)]
    rows = [0] * threads
    per = max(requests // threads, 1)

    def worker(i):
        rng = np.random.default_rng(seed + i)
        for _ in range(per):
            n = int(rng.choice(sizes))
            lo = int(rng.integers(0, max(X.shape[0] - n, 1)))
            t0 = time.perf_counter()
            sp.submit(X[lo:lo + n]).result()
            lat[i].append(time.perf_counter() - t0)
            rows[i] += n

    ts = [threading.Thread(target=worker, args=(i,))
          for i in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    return np.concatenate([np.asarray(x) for x in lat]), wall, sum(rows)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="serving-tier load benchmark (p50/p99 latency, QPS)")
    ap.add_argument("--dry", action="store_true",
                    help="CI smoke: tiny shape + hard telemetry asserts")
    ap.add_argument("--rows", type=int, default=None,
                    help="training rows (default 4000 dry / 200000 full)")
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--leaves", type=int, default=None)
    ap.add_argument("--rounds", type=int, default=None)
    ap.add_argument("--requests", type=int, default=None,
                    help="total requests (default 400 dry / 5000 full)")
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--max-delay-ms", type=float, default=2.0)
    ap.add_argument("--max-batch", type=int, default=4096)
    ap.add_argument("--obs-path", default=None,
                    help="serve timeline path (default /tmp/bench_serve_"
                         "obs_<pid>.jsonl)")
    args = ap.parse_args(argv)

    from lightgbm_tpu.utils.common import honor_jax_platforms
    honor_jax_platforms()

    rows = args.rows or (4000 if args.dry else 200_000)
    leaves = args.leaves or (15 if args.dry else 255)
    rounds = args.rounds or (10 if args.dry else 100)
    requests = args.requests or (400 if args.dry else 5000)
    obs_path = args.obs_path or ("/tmp/bench_serve_obs_%d.jsonl"
                                 % os.getpid())
    try:
        os.unlink(obs_path)
    except OSError:
        pass

    bst, X = build_model(rows, args.features, leaves, rounds)

    # the serve run gets its OWN timeline (training closes its observer
    # when lgb.train returns): compile attribution lands here so `obs
    # recompiles --check` sees the per-bucket serve entries, plus a
    # sampled serve_batch trail for postmortems
    import jax
    from lightgbm_tpu.obs import RunObserver
    obs = RunObserver(events_path=obs_path, compile_attr=True)
    obs.run_header(backend=jax.default_backend(),
                   devices=[str(d) for d in jax.local_devices()],
                   params={"requests": requests, "threads": args.threads,
                           "max_delay_ms": args.max_delay_ms,
                           "max_batch": args.max_batch},
                   context={"tool": "bench_serve"})

    # request-size mix: singletons up to full buckets, so the deadline
    # flush, padding, and every bucket rung all see traffic
    sizes = [1, 3, 16, 50, 120, 400] if args.dry else \
            [1, 8, 32, 100, 256, 512, 1024]
    with bst.serve(max_delay_ms=args.max_delay_ms,
                   max_batch=args.max_batch, observer=obs,
                   batch_event_every=8) as sp:
        # warm the FULL rung ladder (coalesced batches can land on any
        # bucket up to max_batch), then mark warm: any later compile is
        # a steady-state violation
        buckets = []
        if sp.cache is not None:
            rungs, b = [], sp.cache.bucket_min
            while b < sp.cache.max_batch:
                rungs.append(b)
                b <<= 1
            rungs.append(sp.cache.max_batch)
            buckets = sp.cache.warmup(rungs)
            sp.cache.mark_warm()
        lat, wall, nrows = run_load(sp, X, requests, args.threads, sizes)
        stats = sp.stats()
    qps = len(lat) / wall
    p50 = float(np.percentile(lat, 50))
    p99 = float(np.percentile(lat, 99))
    ssc = (stats.get("executables") or {}).get("steady_state_compiles")

    obs.event("serve_bench", qps=round(qps, 3),
              p50_s=round(p50, 6), p99_s=round(p99, 6),
              requests=len(lat), rows=int(nrows),
              rows_per_s=round(nrows / wall, 1),
              threads=args.threads, wall_s=round(wall, 3),
              batches=stats["batches"], pad_rows=stats["pad_rows"],
              buckets=buckets,
              steady_state_compiles=ssc)
    obs.close()

    if args.dry:
        _dry_asserts(bst, X, obs_path, ssc)

    print(json.dumps({
        "metric": "serve_qps_mixed%dthreads" % args.threads,
        "value": round(qps, 3), "unit": "req/s",
        "serve_qps": round(qps, 3),
        "serve_p50_s": round(p50, 6), "serve_p99_s": round(p99, 6),
        "requests": len(lat), "rows": int(nrows),
        "steady_state_compiles": ssc,
        "path": obs_path,
    }))


def _dry_asserts(bst, X, obs_path, steady_state_compiles):
    """The CI gates: parseable timeline, the serve event trail present,
    zero steady-state compiles, and correct predictions."""
    from lightgbm_tpu.obs import read_events
    evs = read_events(obs_path)          # validates every record
    kinds = {e["ev"] for e in evs}
    for need in ("run_header", "compile", "compile_attr", "serve_batch",
                 "serve_bench", "run_end"):
        assert need in kinds, "serve timeline missing %r events" % need
    serve_attr = [e for e in evs if e["ev"] == "compile_attr"
                  and str(e.get("entry", "")).startswith("serve_predict")]
    assert serve_attr, "no serve compile_attr entries recorded"
    thrash = [e for e in serve_attr if e.get("sig_compiles", 1) > 1
              or e.get("n_compiles", 1) > 1]
    assert not thrash, "serve entry recompiled: %r" % thrash
    assert steady_state_compiles == 0, \
        "steady state compiled %r executables" % steady_state_compiles
    sb = [e for e in evs if e["ev"] == "serve_bench"][-1]
    assert sb["qps"] > 0 and sb["p99_s"] >= sb["p50_s"] > 0
    # correctness probe: the serve path must match Booster.predict
    with bst.serve(max_delay_ms=0.5) as sp:
        got = sp.predict(X[:100])
    want = bst.predict(X[:100])
    assert np.allclose(got, want, rtol=2e-6, atol=1e-7), \
        "serve prediction diverged from Booster.predict"
    print(json.dumps({"status": "serve_dry_ok", "events": len(evs),
                      "serve_compiles": len(serve_attr)}),
          file=sys.stderr)


if __name__ == "__main__":
    main()
