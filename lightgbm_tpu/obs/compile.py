"""XLA compile-cache introspection and recompile attribution.

A jitted entry point recompiles when the abstract signature of its
arguments changes — a shape, a dtype, a weak-type promotion, or the
donation set — or when the caller rebuilt the jitted program object
itself (new learner, new ``num_leaves``).  On TPU either case costs
seconds of XLA time per occurrence, and a *recompile storm* (the same
entry bouncing between signatures every iteration) silently dominates
small-tree runs: the launch/compile overhead regime both GPU boosting
papers single out (arxiv 1806.11248 §4, 1809.04559 §5).

``CompileTracker`` hangs off the RunObserver (``obs_compile=true``) and
watches every registered entry:

* ``before_call`` snapshots the argument signature and the entry's jit
  cache size (``PjitFunction._cache_size`` where available);
* ``after_call`` detects a compile (cache growth, or an unseen
  signature when the cache is unreadable), diffs the signature against
  the previous *compiled* one so the event names the offending axis,
  and attaches ``Compiled.cost_analysis()`` / ``memory_analysis()``
  FLOPs + memory estimates from the AOT lowering path;
* every compile lands in the timeline as a schema-v3 ``compile_attr``
  event and bumps the ``lgbm_entry_compiles_total`` /
  ``lgbm_entry_compile_cache_size`` registry instruments.

``sig_compiles`` on the event counts compiles of the *same* signature —
anything above 1 means the XLA cache is being evicted or the program
object is being rebuilt per call, the thrash case the CI gate
(``python -m lightgbm_tpu obs recompiles --check``) fails on.

Everything here is best-effort instrumentation: a signature that cannot
be read or an AOT analysis that fails degrades to a smaller event, never
to a broken training run.
"""
from __future__ import annotations

import weakref

from ..utils.log import Log

# default labels for the top-level positions of a registered entry call
_POS = "a%d"

# fn_ref sentinel: the entry's callable is not weakrefable, so program
# identity cannot be tracked without pinning the object in memory
_UNTRACKABLE = object()


def _leaf_descr(leaf):
    """(kind, shape, dtype) of one flattened argument leaf."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return ("array", tuple(int(d) for d in shape), str(dtype))
    return ("static", (), repr(leaf))


def arg_signature(args, names=None, donate=()):
    """Flatten ``args`` into a tuple of per-leaf descriptors.

    Each descriptor is ``(label, kind, shape, dtype, donated)`` where
    ``label`` is ``<top-level name><sub-path>`` (``names`` labels the
    top-level positions; pytree leaves below keep their key path) —
    hashable, order-stable, and cheap enough to compute per call.
    """
    import jax

    donate = frozenset(donate)
    flat, _ = jax.tree_util.tree_flatten_with_path(tuple(args))
    sig = []
    for path, leaf in flat:
        idx = getattr(path[0], "idx", None) if path else None
        if names is not None and idx is not None and idx < len(names):
            label = names[idx]
        else:
            label = _POS % (idx if idx is not None else 0)
        if len(path) > 1:
            label += jax.tree_util.keystr(path[1:])
        kind, shape, dtype = _leaf_descr(leaf)
        sig.append((label, kind, shape, dtype, idx in donate))
    return tuple(sig)


def render_signature(sig):
    """Compact human/JSON form: {label: 'float32[2000,8]'}."""
    out = {}
    for label, kind, shape, dtype, donated in sig:
        if kind == "array":
            s = "%s[%s]" % (dtype, ",".join(str(d) for d in shape))
        else:
            s = "static:%s" % (dtype,)
        if donated:
            s += " (donated)"
        out[label] = s
    return out


def diff_signatures(prev, cur):
    """Name what changed between two signatures, one dict per change.

    Shape changes are reported per axis (``axis``/``before``/``after``)
    so the event can say *which dimension* moved — the actionable bit
    when hunting a shape-unstable input.
    """
    if prev is None:
        return []
    a = {leaf[0]: leaf for leaf in prev}
    b = {leaf[0]: leaf for leaf in cur}
    diff = []
    for label in a.keys() - b.keys():
        diff.append({"arg": label, "field": "removed"})
    for label in b.keys() - a.keys():
        diff.append({"arg": label, "field": "added", "after":
                     render_signature((b[label],))[label]})
    for label in a.keys() & b.keys():
        _, kind_a, shape_a, dtype_a, don_a = a[label]
        _, kind_b, shape_b, dtype_b, don_b = b[label]
        if kind_a != kind_b:
            diff.append({"arg": label, "field": "kind",
                         "before": kind_a, "after": kind_b})
            continue
        if kind_a == "static":
            if dtype_a != dtype_b:
                diff.append({"arg": label, "field": "value",
                             "before": dtype_a, "after": dtype_b})
            continue
        if len(shape_a) != len(shape_b):
            diff.append({"arg": label, "field": "rank",
                         "before": len(shape_a), "after": len(shape_b)})
        else:
            for axis, (da, db) in enumerate(zip(shape_a, shape_b)):
                if da != db:
                    diff.append({"arg": label, "field": "shape",
                                 "axis": axis, "before": da, "after": db})
        if dtype_a != dtype_b:
            diff.append({"arg": label, "field": "dtype",
                         "before": dtype_a, "after": dtype_b})
        if don_a != don_b:
            diff.append({"arg": label, "field": "donated",
                         "before": don_a, "after": don_b})
    return diff


def format_diff(d):
    """One change dict -> one human-readable clause."""
    arg = d.get("arg", "?")
    field = d.get("field", "?")
    if field == "shape":
        return "%s.shape[%d]: %s -> %s" % (arg, d.get("axis", -1),
                                           d.get("before"), d.get("after"))
    if field == "program":
        return d.get("note", "program object rebuilt")
    if field in ("added", "removed"):
        return "%s %s" % (arg, field)
    return "%s.%s: %s -> %s" % (arg, field, d.get("before"),
                                d.get("after"))


def _cache_size(fn):
    """The entry's jit-cache entry count, or None when unreadable."""
    try:
        getter = fn._cache_size
    except AttributeError:
        return None
    try:
        return int(getter())
    except Exception:
        return None


def parse_compiled(compiled):
    """Cost/memory estimates of an already-``Compiled`` program, as the
    ``{cost: {...}, memory: {...}}`` sub-dicts of a ``compile_attr``
    event.  THE shared parser — the JIT path here (``analyze_compiled``)
    and the serve tier's AOT executables (serve/executable.py) both
    read XLA's analyses through it, so the list-vs-dict
    ``cost_analysis`` backend quirk is handled exactly once.

    ``cost_analysis`` returns a list of per-program dicts on recent jax
    CPU backends and a bare dict elsewhere; ``memory_analysis`` returns
    a ``CompiledMemoryStats``.  Both are optional per backend, so every
    step is guarded — analysis failure only shrinks the event.
    """
    out = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            cost = {}
            if "flops" in ca:
                cost["flops"] = float(ca["flops"])
            if "bytes accessed" in ca:
                cost["bytes_accessed"] = float(ca["bytes accessed"])
            if cost:
                out["cost"] = cost
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        mem = {}
        for field in ("argument_size_in_bytes", "output_size_in_bytes",
                      "temp_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(ma, field, None)
            if v is not None:
                mem[field.replace("_size_in_bytes", "_bytes")] = int(v)
        if mem:
            out["memory"] = mem
    except Exception:
        pass
    return out


def analyze_compiled(fn, args):
    """FLOPs + memory estimates via the AOT path (fn.lower().compile())
    of a jitted entry; the parse itself is ``parse_compiled``.

    Entries registered through a plain-Python wrapper (e.g. the learner's
    ``tree_grow`` closure binding meta/bundle onto the memoized jit core)
    have no ``.lower`` of their own; they expose the core's lowering as an
    ``_aot_lower(*observed_args)`` attribute instead.
    """
    try:
        aot = getattr(fn, "_aot_lower", None)
        lowered = aot(*args) if aot is not None else fn.lower(*args)
        compiled = lowered.compile()
    except Exception as e:                      # non-jit entry, AOT refusal
        Log.debug("obs: compile analysis unavailable for %r: %s", fn, e)
        return {}
    return parse_compiled(compiled)


class CompileTracker:
    """Per-entry compile-cache state machine driven by the observer's
    ``entry_args`` (before the call) / ``entry_end`` (after) hooks."""

    def __init__(self, registry=None, analyze=True):
        if registry is None:
            from .metrics import REGISTRY
            registry = REGISTRY
        self._registry = registry
        self._analyze = bool(analyze)
        self._entries = {}        # name -> state dict
        self._pending = {}        # name -> (fn, args, sig, cache_before)

    def before_call(self, name, fn, args, names=None, donate=()):
        try:
            sig = arg_signature(args, names=names, donate=donate)
        except Exception as e:    # exotic pytree: never break the call
            Log.debug("obs: signature of entry %s unreadable: %s", name, e)
            self._pending.pop(name, None)
            return
        self._pending[name] = (fn, args, sig, _cache_size(fn))

    def after_call(self, name, obs):
        pending = self._pending.pop(name, None)
        if pending is None:
            return
        fn, args, sig, cache0 = pending
        st = self._entries.setdefault(name, {
            "calls": 0, "compiles": 0, "sig_compiles": {},
            "last_compiled_sig": None, "fn_ref": None})
        st["calls"] += 1
        cache1 = _cache_size(fn)
        # identity via weakref, not id(): a GC'd program can hand its id
        # to the replacement, masking the rebuild — and a dead ref IS a
        # rebuild (the old program object is gone)
        prev = st["fn_ref"]
        rebuilt = (prev is not None and prev is not _UNTRACKABLE
                   and prev() is not fn)
        try:
            st["fn_ref"] = weakref.ref(fn)
        except TypeError:
            # non-weakrefable callable: a strong reference would pin the
            # old program for the tracker's lifetime and id() can be
            # reused after GC, so identity is simply untrackable here —
            # rebuild detection degrades, cache-size counting does not
            st["fn_ref"] = _UNTRACKABLE
        if cache0 is not None and cache1 is not None:
            compiled = cache1 > cache0
        else:
            # no cache introspection: fall back to signature novelty
            compiled = rebuilt or sig not in st["sig_compiles"]
        if not compiled:
            return
        st["compiles"] += 1
        n_sig = st["sig_compiles"].get(sig, 0) + 1
        st["sig_compiles"][sig] = n_sig
        diff = diff_signatures(st["last_compiled_sig"], sig)
        if rebuilt:
            diff.insert(0, {"field": "program",
                            "note": "entry rebuilt (new jitted program "
                                    "object)"})
        fields = {"entry": name, "n_compiles": st["compiles"],
                  "sig": render_signature(sig), "sig_compiles": n_sig,
                  "diff": diff}
        if cache1 is not None:
            fields["cache_size"] = cache1
        if self._analyze:
            fields.update(analyze_compiled(fn, args))
            if "cost" in fields:
                # steady-state cost of the entry's LAST compile — the
                # roofline rollup (obs/roofline.py) joins these against
                # the entry timers every obs_utilization_every iters
                st["last_cost"] = fields["cost"]
        st["last_compiled_sig"] = sig
        obs.event("compile_attr", **fields)
        labels = {"entry": name}
        self._registry.counter(
            "lgbm_entry_compiles_total",
            "XLA compiles per registered jitted entry point",
            labels=labels).inc()
        if cache1 is not None:
            self._registry.gauge(
                "lgbm_entry_compile_cache_size",
                "live jit-cache entries per registered entry point",
                labels=labels).set(cache1)
        cost = fields.get("cost") or {}
        if "flops" in cost:
            self._registry.gauge(
                "lgbm_entry_flops",
                "XLA cost-analysis FLOPs estimate of the last compile",
                labels=labels).set(cost["flops"])
        mem = fields.get("memory") or {}
        if mem:
            self._registry.gauge(
                "lgbm_entry_memory_bytes",
                "argument+output+temp bytes of the last compiled "
                "program (memory_analysis)",
                labels=labels).set(
                    mem.get("argument_bytes", 0)
                    + mem.get("output_bytes", 0)
                    + mem.get("temp_bytes", 0))
        if n_sig > 1:
            Log.warning("obs: entry %s recompiled signature it already "
                        "compiled (%d times) — jit-cache thrash", name,
                        n_sig)
        elif st["compiles"] > 1:
            Log.warning("obs: entry %s recompiled (compile #%d): %s",
                        name, st["compiles"],
                        "; ".join(format_diff(d) for d in diff)
                        or "signature unchanged")

    def costs(self):
        """{entry: cost dict} of the last compile per entry — the
        roofline join's FLOPs/bytes side (obs/roofline.py)."""
        return {name: st["last_cost"] for name, st in self._entries.items()
                if st.get("last_cost")}

    def summary(self):
        """Folded into run_end: per-entry compile/call/signature counts."""
        out = {}
        for name, st in self._entries.items():
            out[name] = {
                "calls": st["calls"],
                "compiles": st["compiles"],
                "signatures": len(st["sig_compiles"]),
                "max_sig_compiles": max(st["sig_compiles"].values(),
                                        default=0),
            }
        return out
