"""Streaming two-round text ingest == in-memory ingest, bit for bit.

The streaming loader (io/streaming.py) must reproduce the in-memory
path's dataset exactly — same Random sample indices drive BinMapper
construction, and GreedyFindBin is row-order independent — while touching
only one chunk of text at a time (dataset_loader.cpp:554-660 semantics).
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.io.streaming import count_rows, stream_supported
from lightgbm_tpu.utils.config import Config


@pytest.fixture(scope="module")
def tsv_file(tmp_path_factory):
    rng = np.random.default_rng(13)
    n, f = 5000, 7
    X = rng.normal(size=(n, f))
    X[rng.random((n, f)) > 0.9] = 0.0            # some zeros
    y = (X[:, 0] + X[:, 1] > 0).astype(np.int64)
    path = tmp_path_factory.mktemp("stream") / "data.tsv"
    with open(path, "w") as fh:
        for i in range(n):
            fh.write("\t".join([str(y[i])] + ["%.17g" % v for v in X[i]]))
            fh.write("\n")
    return str(path), X, y


def test_count_and_detect(tsv_file):
    path, X, y = tsv_file
    assert count_rows(path, skip_header=False) == len(y)
    assert stream_supported(path, has_header=False)


@pytest.mark.parametrize("efb", [False, True])
def test_streaming_matches_in_memory(tsv_file, efb):
    path, X, y = tsv_file
    cfg_mem = Config({"max_bin": 63, "verbose": -1, "enable_bundle": efb})
    cfg_str = Config({"max_bin": 63, "verbose": -1, "enable_bundle": efb,
                      "use_two_round_loading": True})
    td_mem = TrainingData.from_file(path, cfg_mem)
    td_str = TrainingData.from_file(path, cfg_str)
    assert td_str.num_data == td_mem.num_data
    assert td_str.used_feature_idx == td_mem.used_feature_idx
    np.testing.assert_array_equal(td_str.num_bin_arr, td_mem.num_bin_arr)
    assert (td_str.bundle is None) == (td_mem.bundle is None)
    np.testing.assert_array_equal(td_str.binned, td_mem.binned)
    np.testing.assert_array_equal(np.asarray(td_str.metadata.label),
                                  np.asarray(td_mem.metadata.label))


def test_streaming_valid_alignment(tsv_file):
    path, X, y = tsv_file
    cfg = Config({"max_bin": 63, "verbose": -1,
                  "use_two_round_loading": True})
    train = TrainingData.from_file(path, cfg)
    valid = TrainingData.from_file(path, cfg, reference=train)
    np.testing.assert_array_equal(valid.binned, train.binned)


def test_streaming_train_end_to_end(tsv_file):
    path, X, y = tsv_file
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
              "verbose": -1, "use_two_round_loading": True}
    ds = lgb.Dataset(path, params=params)
    bst = lgb.train(params, ds, num_boost_round=8)
    p = bst.predict(X)
    order = np.argsort(p)
    ranks = np.empty(len(p)); ranks[order] = np.arange(1, len(p) + 1)
    npos = y.sum(); auc = (ranks[y > 0].sum() - npos * (npos + 1) / 2) / (
        npos * (len(y) - npos))
    assert auc > 0.9


def test_streaming_with_header_and_ignore(tmp_path):
    rng = np.random.default_rng(3)
    n = 2000
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] > 0).astype(np.int64)
    path = tmp_path / "h.csv"
    with open(path, "w") as fh:
        fh.write("target,a,b,junk,c\n")
        for i in range(n):
            fh.write("%d,%.17g,%.17g,%.17g,%.17g\n"
                     % (y[i], X[i, 0], X[i, 1], X[i, 2], X[i, 3]))
    cfg = dict(max_bin=63, verbose=-1, header=True,
               label_column="name:target", ignore_column="name:junk",
               use_two_round_loading=True)
    td_str = TrainingData.from_file(str(path), Config(dict(cfg)))
    cfg.pop("use_two_round_loading")
    td_mem = TrainingData.from_file(str(path), Config(cfg))
    np.testing.assert_array_equal(td_str.binned, td_mem.binned)
    assert td_str.feature_names == td_mem.feature_names


def test_streaming_blank_lines(tmp_path):
    path = tmp_path / "blanks.csv"
    with open(path, "w") as fh:
        fh.write("1,0.5,1.5\n\n0,2.5,0.25\n   \n1,0.75,3.5\n\n")
    assert count_rows(str(path), skip_header=False) == 3
    cfg = Config({"max_bin": 15, "verbose": -1, "min_data_in_leaf": 1,
                  "use_two_round_loading": True, "min_data_in_bin": 1})
    td = TrainingData.from_file(str(path), cfg)
    assert td.num_data == 3
    cfg2 = Config({"max_bin": 15, "verbose": -1, "min_data_in_leaf": 1,
                   "min_data_in_bin": 1})
    td2 = TrainingData.from_file(str(path), cfg2)
    np.testing.assert_array_equal(td.binned, td2.binned)
    np.testing.assert_array_equal(np.asarray(td.metadata.label),
                                  np.asarray(td2.metadata.label))
