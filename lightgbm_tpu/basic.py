"""Python-facing Dataset / Booster — API parity with python-package/basic.py.

The reference wraps the C library through ctypes (basic.py:21,546,1171); here
the same public surface drives the in-process TPU engine directly, so there
is no language boundary to cross.  Semantics kept: lazy Dataset
construction, reference-alignment of validation sets, parameter dict
handling, custom objective ``fobj(preds, train_data) -> (grad, hess)`` via
``Booster.update``, prediction modes (raw/prob/leaf-index), model file
round-trip, continued training via ``init_model``.
"""
from __future__ import annotations

import time as _time
from typing import Dict, List, Optional

import numpy as np

from .io.dataset import TrainingData
from .metrics import create_metric
from .models.gbdt import GBDT
from .models.factory import create_boosting
from .objectives import create_objective
from .obs.metrics import observe_predict
from .utils.config import Config
from .utils.log import LightGBMError, Log

__all__ = ["Dataset", "Booster", "LightGBMError"]


def _data_from_any(data, label=None):
    """Accept numpy 2-D, pandas DataFrame, scipy sparse, list-of-lists, or
    file path.  Sparse inputs stay sparse (io/sparse.py) — they are binned
    column-by-column without densification."""
    if isinstance(data, str):
        return data, label
    from .io.sparse import SparseColumns, from_scipy, is_scipy_sparse
    if isinstance(data, SparseColumns):
        return data, label
    if is_scipy_sparse(data):
        return from_scipy(data), label
    try:
        import pandas as pd
        if isinstance(data, pd.DataFrame):
            # kept as a frame until construct(): category columns must be
            # coded against the *reference* dataset's category lists, and
            # the reference may be attached after __init__ (set_reference)
            return data, label
        if label is not None and isinstance(label, (pd.Series, pd.DataFrame)):
            label = label.values
    except ImportError:
        pass
    return np.asarray(data, dtype=np.float64), label


_PANDAS_OK_KINDS = "biuf"   # bool / int / uint / float columns train directly


def _is_pandas_frame(data) -> bool:
    try:
        import pandas as pd
    except ImportError:
        return False
    return isinstance(data, pd.DataFrame)


def _data_from_pandas(data, feature_name, categorical_feature,
                      pandas_categorical):
    """Code category-dtype columns and resolve auto names — the semantics of
    the reference's pandas path (python-package/lightgbm/basic.py:224-291).

    Train call: ``pandas_categorical=None`` -> the per-column category lists
    are recorded from ``data`` and returned.  Valid/predict call: pass the
    train-time lists; each category column is re-coded against them so the
    integer codes agree across datasets even when the frames saw different
    category orders.  Returns ``(matrix, feature_name, categorical_feature,
    pandas_categorical)``.

    NaN/unseen categories code to -1, kept as-is: this vintage of the
    reference counts -1 as an ordinary category at train time
    (src/io/bin.cpp:242-255 has no negative filter) and maps values absent
    from the bin map to the last bin at predict (bin.h:435-439) — our
    binning does the same, so -1 handling is parity, not an accident.
    """
    cat_cols = [c for c in data.columns
                if str(data[c].dtype) == "category"]
    if pandas_categorical is None:          # train dataset records the maps
        pandas_categorical = [list(data[c].cat.categories) for c in cat_cols]
    else:                                   # valid/predict aligns to train
        if len(cat_cols) != len(pandas_categorical):
            raise LightGBMError(
                "train and valid dataset categorical_feature do not match.")
    if cat_cols:
        data = data.copy()      # never alter the caller's frame
        for c, train_cats in zip(cat_cols, pandas_categorical):
            if list(data[c].cat.categories) != list(train_cats):
                data[c] = data[c].cat.set_categories(train_cats)
            data[c] = data[c].cat.codes
    if categorical_feature is not None:
        if categorical_feature == "auto":
            categorical_feature = [str(c) for c in cat_cols]
        else:
            categorical_feature = (list(categorical_feature)
                                   + [str(c) for c in cat_cols])
    if feature_name == "auto":
        feature_name = [str(c) for c in data.columns]
    bad = [str(c) for c, dt in zip(data.columns, data.dtypes)
           if getattr(dt, "kind", "O") not in _PANDAS_OK_KINDS]
    if bad:
        raise LightGBMError(
            "DataFrame.dtypes for data must be int, float or bool; found "
            "unsupported dtypes in fields: " + ", ".join(bad))
    return (data.values.astype(np.float64), feature_name,
            categorical_feature, pandas_categorical)


def _json_default_numpy(obj):
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    raise TypeError("Cannot serialize %s in pandas_categorical"
                    % type(obj).__name__)


def _dump_pandas_categorical(pandas_categorical) -> str:
    import json
    return json.dumps(pandas_categorical, default=_json_default_numpy)


def _parse_pandas_categorical(model_str: str):
    """Read the trailing ``pandas_categorical:`` line a saved model carries
    (reference appends it after the model text, basic.py:283-291)."""
    import json
    idx = model_str.rfind("pandas_categorical:")
    if idx < 0:
        return None
    line = model_str[idx + len("pandas_categorical:"):].splitlines()[0]
    try:
        return json.loads(line)
    except ValueError:
        return None


class Dataset:
    """Lazily-constructed training dataset (python-package basic.py:546)."""

    def __init__(self, data, label=None, max_bin=None, reference=None,
                 weight=None, group=None, init_score=None, silent=False,
                 feature_name="auto", categorical_feature="auto", params=None,
                 free_raw_data=True):
        data, label = _data_from_any(data, label)
        self.data = data
        self.label = label
        self.max_bin = max_bin
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.silent = silent
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params or {})
        if max_bin is not None:
            self.params.setdefault("max_bin", max_bin)
        self.free_raw_data = free_raw_data
        self.pandas_categorical = None
        self._handle: Optional[TrainingData] = None
        self.used_indices: Optional[np.ndarray] = None
        self._predictor = None

    # ------------------------------------------------------------ construct
    def construct(self) -> "Dataset":
        """Bin the raw data and build the device-ready store (lazy; no-op when already constructed)."""
        if self._handle is not None:
            return self
        params = dict(self.params)
        cfg = Config(params)
        cat = []
        feature_names = None
        if isinstance(self.data, str):
            ref_td = self.reference._handle if self.reference is not None else None
            if TrainingData.can_load_binned(self.data):
                # pre-binned mmap directory: zero re-binning work
                self._handle = TrainingData.from_binned(self.data)
            elif TrainingData.can_load_binary(self.data):
                self._handle = TrainingData.load_binary(self.data)
            else:
                self._handle = TrainingData.from_file(self.data, cfg,
                                                      reference=ref_td)
        else:
            from .io.sparse import SparseColumns
            if self.reference is not None:
                self.reference.construct()
            data = self.data
            if _is_pandas_frame(data):
                ref_pc = (self.reference.pandas_categorical
                          if self.reference is not None else None)
                data, self.feature_name, self.categorical_feature, \
                    self.pandas_categorical = _data_from_pandas(
                        data, self.feature_name, self.categorical_feature,
                        ref_pc)
                self.data = data
            sparse = isinstance(data, SparseColumns)
            data = data if sparse else np.asarray(data, dtype=np.float64)
            if self.feature_name not in (None, "auto"):
                feature_names = list(self.feature_name)
            if self.categorical_feature not in (None, "auto"):
                spec = self.categorical_feature
                if isinstance(spec, (int, str)):
                    spec = [spec]      # scalar from bindings (e.g. R)
                cat = []
                for c in spec:
                    if isinstance(c, str):
                        # column-name spec (basic.py:224-291 pandas path
                        # semantics): resolve against explicit feature names
                        # or, with feature_name='auto', the generated
                        # Column_%d names — never silently drop the spec
                        if feature_names and c in feature_names:
                            cat.append(feature_names.index(c))
                        elif not feature_names and c.startswith("Column_") \
                                and c[len("Column_"):].isdigit():
                            cat.append(int(c[len("Column_"):]))
                        else:
                            raise LightGBMError(
                                "Unknown categorical column %r (known "
                                "feature names: %s)"
                                % (c, feature_names or "auto Column_<i>"))
                    else:
                        cat.append(int(c))
            ref_td = (self.reference._handle       # constructed above
                      if self.reference is not None else None)
            if sparse:
                self._handle = TrainingData.from_csc(
                    data, label=self.label, config=cfg,
                    weights=self.weight, group=self.group,
                    init_score=self.init_score,
                    categorical_feature=cat, feature_names=feature_names,
                    reference=ref_td)
            else:
                self._handle = TrainingData.from_matrix(
                    data, label=self.label, config=cfg,
                    weights=self.weight, group=self.group,
                    init_score=self.init_score,
                    categorical_feature=cat, feature_names=feature_names,
                    reference=ref_td, keep_raw=True)
        if self.label is not None and self._handle.metadata.label is None:
            self._handle.metadata.set_label(self.label)
        if not self.free_raw_data and isinstance(self.data, np.ndarray):
            self._handle.raw_data = self.data
        # continued-training predictor fills init scores
        # (engine.py:92-98 / dataset predict_fun_ path)
        if self._predictor is not None:
            from .io.sparse import SparseColumns, iter_dense_row_chunks
            if self._handle.raw_data is not None:
                raw = self._predictor.predict_raw_for_init(
                    self._handle.raw_data)
                self._handle.metadata.set_init_score(raw.T.reshape(-1))
            elif isinstance(self.data, SparseColumns):
                raw = np.concatenate(
                    [self._predictor.predict_raw_for_init(block)
                     for _, block in iter_dense_row_chunks(self.data)])
                self._handle.metadata.set_init_score(raw.T.reshape(-1))
        return self

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, silent=False, params=None) -> "Dataset":
        """Validation Dataset aligned to this one's bin mappers."""
        return Dataset(data, label=label, reference=self,
                       weight=weight, group=group, init_score=init_score,
                       silent=silent, params=params or self.params,
                       free_raw_data=self.free_raw_data)

    def set_reference(self, reference: "Dataset") -> "Dataset":
        """Align this dataset's bin mappers with a reference (train) dataset."""
        self.reference = reference
        return self

    def subset(self, used_indices, params=None) -> "Dataset":
        """New Dataset over a row subset, sharing this one's bin mappers."""
        self.construct()
        used_indices = np.asarray(used_indices)
        from .io.sparse import SparseColumns
        if isinstance(self.data, SparseColumns):
            sub = Dataset(self.data.take_rows(used_indices),
                          label=None if self.label is None
                          else np.asarray(self.label)[used_indices],
                          reference=self,
                          weight=None if self.weight is None
                          else np.asarray(self.weight)[used_indices],
                          params=params or self.params,
                          free_raw_data=self.free_raw_data)
            sub.used_indices = used_indices
            return sub
        if self._handle.raw_data is None:
            Log.fatal("Cannot subset a Dataset whose raw data was freed")
        sub = Dataset(self._handle.raw_data[used_indices],
                      label=None if self.label is None else np.asarray(self.label)[used_indices],
                      reference=self,
                      weight=None if self.weight is None else np.asarray(self.weight)[used_indices],
                      params=params or self.params,
                      free_raw_data=self.free_raw_data)
        sub.used_indices = used_indices
        return sub

    # ------------------------------------------------------------- metadata
    def set_label(self, label) -> "Dataset":
        """Set the target vector."""
        self.label = label
        if self._handle is not None:
            self._handle.metadata.set_label(label)
        return self

    def get_label(self):
        """The target vector, or None before it is set."""
        if self._handle is not None and self._handle.metadata.label is not None:
            return np.asarray(self._handle.metadata.label)
        return self.label

    def set_weight(self, weight) -> "Dataset":
        """Set per-row weights."""
        self.weight = weight
        if self._handle is not None:
            self._handle.metadata.set_weights(weight)
        return self

    def get_weight(self):
        """Per-row weights, or None."""
        if self._handle is not None and self._handle.metadata.weights is not None:
            return np.asarray(self._handle.metadata.weights)
        return self.weight

    def set_group(self, group) -> "Dataset":
        """Set query/group sizes for ranking."""
        self.group = group
        if self._handle is not None:
            self._handle.metadata.set_query_counts(group)
        return self

    def get_group(self):
        """Query/group sizes, or None."""
        if self._handle is not None and self._handle.metadata.query_boundaries is not None:
            return np.diff(self._handle.metadata.query_boundaries)
        return self.group

    def set_init_score(self, init_score) -> "Dataset":
        """Set initial scores added to every prediction."""
        self.init_score = init_score
        if self._handle is not None:
            self._handle.metadata.set_init_score(init_score)
        return self

    def get_init_score(self):
        """Initial scores, or None."""
        if self._handle is not None:
            return self._handle.metadata.init_score
        return self.init_score

    def set_field(self, field_name: str, data) -> None:
        """Set a metadata field by name (label/weight/group/init_score)."""
        self.construct()
        self._handle.metadata.set_field(field_name, data)

    def get_field(self, field_name: str):
        """Get a metadata field by name."""
        self.construct()
        return self._handle.metadata.get_field(field_name)

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        """Set the categorical feature spec (indices or names)."""
        if self._handle is not None and categorical_feature != self.categorical_feature:
            Log.warning("categorical_feature in Dataset is overridden; "
                        "new categorical_feature is %s", str(categorical_feature))
        self.categorical_feature = categorical_feature
        return self

    def set_feature_name(self, feature_name) -> "Dataset":
        """Set feature names (list of str)."""
        self.feature_name = feature_name
        if feature_name not in (None, "auto") and self._handle is not None:
            self._handle.feature_names = list(feature_name)
        return self

    def _update_params(self, params: Optional[dict]) -> "Dataset":
        if params:
            self.params.update(params)
        return self

    def _set_predictor(self, predictor) -> "Dataset":
        self._predictor = predictor
        return self

    # ------------------------------------------------------------------ info
    def num_data(self) -> int:
        """Row count; requires raw ndarray data or a constructed
        dataset (matches the reference's construct-first contract)."""
        if self._handle is not None:
            return self._handle.num_data
        if isinstance(self.data, np.ndarray):
            return self.data.shape[0]
        Log.fatal("Cannot get num_data before construct")

    def get_feature_name(self) -> List[str]:
        """Feature names after construction (auto names resolved)."""
        self.construct()
        return list(self._handle.feature_names)

    def num_feature(self) -> int:
        """Feature count; requires raw ndarray data or a constructed
        dataset (matches the reference's construct-first contract)."""
        if self._handle is not None:
            return self._handle.num_total_features
        if isinstance(self.data, np.ndarray):
            return self.data.shape[1]
        Log.fatal("Cannot get num_feature before construct")

    def save_binary(self, filename: str) -> None:
        """Save the constructed (binned) dataset for fast reload."""
        self.construct()
        self._handle.save_binary(filename)

    def save_binned(self, path: str) -> "Dataset":
        """Persist as the mmap-able pre-binned directory format: later
        runs open it with Dataset.from_binned (or just Dataset(path)) and
        skip host-side binning entirely."""
        self.construct()
        self._handle.save_binned(path)
        return self

    @classmethod
    def from_binned(cls, path: str, params=None, comm=None,
                    row_range=None) -> "Dataset":
        """Open a pre-binned dataset directory written by save_binned()
        or the streaming `ooc_binned_dir` ingest; shards stay mmap-backed
        and page to the device without a host-side bin matrix.  With a
        multi-process ``comm`` (or an explicit ``row_range``) the open is
        rank-sharded: this process maps only its own row range and the
        dataset trains over the global mesh (docs/Distributed.md)."""
        ds = cls(path, params=params)
        ds._handle = TrainingData.from_binned(path, comm=comm,
                                              row_range=row_range)
        return ds


class _InnerPredictor:
    """Continued-training score provider (basic.py:293-543 analog)."""

    def __init__(self, booster: Optional["Booster"] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        if booster is not None:
            self.gbdt = booster._gbdt
        elif model_file is not None:
            cfg = Config()
            self.gbdt = GBDT(cfg)
            with open(model_file) as f:
                self.gbdt.load_model_from_string(f.read())
        elif model_str is not None:
            # checkpoint resume (models/checkpoint.py): the model text
            # arrives in-memory, never via a file of its own
            cfg = Config()
            self.gbdt = GBDT(cfg)
            self.gbdt.load_model_from_string(model_str)
        else:
            raise LightGBMError("Need booster, model_file or model_str")

    def predict_raw_for_init(self, features: np.ndarray) -> np.ndarray:
        # exact f64 host path: continued-training init scores feed the
        # training parity contract (engine.py init_model), so the f32
        # device bulk path must not round them
        return self.gbdt.predict_raw(features, allow_device=False)


class Booster:
    """Training-capable model wrapper (python-package basic.py:1171)."""

    def __init__(self, params: Optional[dict] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None, silent: bool = False):
        self.params = dict(params or {})
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._train_set = train_set
        self._valid_sets: List[Dataset] = []
        self.name_valid_sets: List[str] = []
        self._network = False
        self.pandas_categorical = None
        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError("Training data should be Dataset instance, met %s"
                                % type(train_set).__name__)
            cfg = Config(self.params)
            train_set._update_params(self.params).construct()
            self.pandas_categorical = train_set.pandas_categorical
            objective = create_objective(cfg.objective, cfg)
            if objective is not None:
                objective.init(train_set._handle.metadata,
                               train_set._handle.num_data)
            # training metrics always exist; is_training_metric only gates
            # auto-printing (c_api.cpp CreateObjectiveAndMetrics semantics)
            training_metrics = []
            for mname in cfg.metrics():
                m = create_metric(mname, cfg)
                if m is not None:
                    m.init(train_set._handle.metadata,
                           train_set._handle.num_data)
                    training_metrics.append(m)
            self._gbdt = create_boosting(cfg.boosting_type, cfg,
                                         train_set._handle, objective,
                                         training_metrics)
            self._cfg = cfg
            # continuation: fold loaded models in
            if train_set._predictor is not None:
                base = train_set._predictor.gbdt
                base._materialize()
                self._gbdt.models = list(base.models) + self._gbdt.models
                self._gbdt._models_dev = [None] * len(base.models) + self._gbdt._models_dev
                self._gbdt._models_shrink = [1.0] * len(base.models) + self._gbdt._models_shrink
                self._gbdt.num_init_iteration = (
                    len(base.models) // max(base.num_tree_per_iteration, 1))
                self._gbdt.boost_from_average_used = base.boost_from_average_used
        elif model_file is not None:
            with open(model_file) as f:
                self._load_from_string(f.read())
        elif model_str is not None:
            self._load_from_string(model_str)
        else:
            raise TypeError("Need at least one training dataset or model "
                            "file to create booster instance")

    def _load_from_string(self, model_str: str) -> None:
        self._cfg = Config(self.params)
        self._gbdt = GBDT(self._cfg)
        self._gbdt.load_model_from_string(model_str)
        self.pandas_categorical = _parse_pandas_categorical(model_str)
        self._train_set = None

    # ------------------------------------------------------------- training
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        """Register a validation set for eval/early stopping."""
        if not isinstance(data, Dataset):
            raise TypeError("Validation data should be Dataset instance, met %s"
                            % type(data).__name__)
        data._update_params(self.params).construct()
        metrics = []
        for mname in self._cfg.metrics():
            m = create_metric(mname, self._cfg)
            if m is not None:
                m.init(data._handle.metadata, data._handle.num_data)
                metrics.append(m)
        self._gbdt.add_valid_dataset(data._handle, metrics)
        self._valid_sets.append(data)
        self.name_valid_sets.append(name)
        return self

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration; with fobj mirrors the __boost path
        (basic.py:1331-1412)."""
        if train_set is not None and train_set is not self._train_set:
            Log.fatal("Resetting train set inside update is not supported yet")
        if fobj is None:
            return self._gbdt.train_one_iter(None, None, False)
        if self._train_set is None:
            raise LightGBMError(
                "Custom objective needs the train Dataset, but it was "
                "released by free_dataset()")
        grad, hess = fobj(self.__inner_predict_raw(0), self._train_set)
        return self.__boost(grad, hess)

    def __boost(self, grad, hess) -> bool:
        grad = np.asarray(grad, dtype=np.float32)
        hess = np.asarray(hess, dtype=np.float32)
        if len(grad) != len(hess):
            raise ValueError("Lengths of gradient(%d) and hessian(%d) don't match"
                             % (len(grad), len(hess)))
        return self._gbdt.train_one_iter(grad, hess, False)

    def __inner_predict_raw(self, data_idx: int) -> np.ndarray:
        if data_idx == 0:
            raw = self._gbdt.train_score
        else:
            raw = self._gbdt.valid_score_host(data_idx - 1)
        return raw[0] if raw.shape[0] == 1 else raw.reshape(-1)

    def telemetry(self) -> list:
        """The run observer's in-memory event timeline (lightgbm_tpu/obs)
        as a list of event dicts — empty unless an ``obs_*`` param enabled
        telemetry.  The list is a snapshot copy; docs/Observability.md
        describes the schema."""
        return list(self._gbdt._obs.timeline)

    def finalize_telemetry(self, status: str = "ok") -> None:
        """Emit the run_end summary event and flush/close the JSONL
        writer.  Called by engine.train()/cv() after the boosting loop —
        with ``status="aborted"`` on the exception path, so a crashed run
        still ends with a parseable timeline; idempotent, and safe when
        telemetry is disabled."""
        self._gbdt._obs.close(status=status)

    def reset_parameter(self, params: dict) -> "Booster":
        """LGBM_BoosterResetParameter semantics: rebuild the running config
        like GBDT::ResetConfig.  learning_rate alone takes a fast path (it
        is read every iteration anyway); any other key rebuilds the tree
        learner from the updated full parameter set so num_leaves,
        lambda_l1/l2, bagging, etc. actually take effect."""
        params = dict(params or {})
        self.params.update(params)
        if "learning_rate" in params:
            self._gbdt.shrinkage_rate = float(params["learning_rate"])
        rest = [k for k in params if k != "learning_rate"]
        if rest:
            if "objective" in rest:
                raise LightGBMError(
                    "Cannot change objective during training; "
                    "create a new Booster instead")
            cfg = Config(dict(self.params))
            gb = self._gbdt
            if gb.train_data is not None:
                gb.reset_config(cfg)     # in place: scores/dataset kept
            else:
                gb.config = cfg
        return self

    def set_train_data(self, train_set: "Dataset") -> "Booster":
        """LGBM_BoosterResetTrainingData: swap the training dataset while
        keeping the model (GBDT::ResetTrainingData, gbdt.cpp:64-208)."""
        cfg = Config(dict(self.params))
        train_set._update_params(self.params).construct()
        objective = create_objective(cfg.objective, cfg)
        if objective is not None:
            objective.init(train_set._handle.metadata,
                           train_set._handle.num_data)
        metrics = []
        for mname in cfg.metrics():
            m = create_metric(mname, cfg)
            if m is not None:
                m.init(train_set._handle.metadata, train_set._handle.num_data)
                metrics.append(m)
        self._gbdt.reset_training_data(cfg, train_set._handle, objective,
                                       metrics)
        self._train_set = train_set
        self._cfg = cfg          # later add_valid must see the new config
        return self

    # ------------------------------------------------------- attributes
    def attr(self, key: str):
        """Get a user attribute (basic.py:1769), or None when unset."""
        return getattr(self, "_attr", {}).get(key)

    def set_attr(self, **kwargs) -> "Booster":
        """Set STRING attributes; a None value deletes the key
        (basic.py:1785-1800 — non-strings raise like the reference)."""
        store = self.__dict__.setdefault("_attr", {})
        for key, value in kwargs.items():
            if value is None:
                store.pop(key, None)
            elif not isinstance(value, str):
                raise ValueError("Set attr only accepts strings")
            else:
                store[key] = value
        return self

    def set_train_data_name(self, name: str) -> "Booster":
        """Display name of the training set in eval output."""
        self._train_data_name = name
        return self

    def free_dataset(self) -> "Booster":
        """Release the Python-side train/valid Dataset references
        (basic.py:1281-1283; the reference engine calls this after
        training to let raw data be collected).  The engine retains its
        device-side data, so prediction, update(), and built-in-metric
        eval keep working; only custom fevals need the freed Dataset
        objects and will raise.  Valid slots become None PLACEHOLDERS so
        later add_valid keeps eval indices aligned."""
        self._train_set = None
        self._valid_sets = [None] * len(self._valid_sets)
        return self

    def rollback_one_iter(self) -> "Booster":
        """Undo the most recent boosting iteration."""
        self._gbdt.rollback_one_iter()
        return self

    def current_iteration(self) -> int:
        """Number of completed boosting iterations."""
        return self._gbdt.total_iterations()

    # ----------------------------------------------------------------- eval
    def eval(self, data: Dataset, name: str, feval=None) -> List[tuple]:
        """Evaluate on an arbitrary dataset."""
        if data is self._train_set:
            return self.eval_train(feval)
        for i, vs in enumerate(self._valid_sets):
            if data is vs:
                return self.__eval(i + 1, self.name_valid_sets[i], feval)
        raise LightGBMError("Data should be train set or a validation set")

    def eval_train(self, feval=None) -> List[tuple]:
        """Evaluate on the training data."""
        return self.__eval(0, getattr(self, "_train_data_name",
                                      "training"), feval)

    def eval_valid(self, feval=None) -> List[tuple]:
        """Evaluate on every registered validation set."""
        out = []
        for i, name in enumerate(self.name_valid_sets):
            out.extend(self.__eval(i + 1, name, feval))
        return out

    def __eval(self, data_idx: int, name: str, feval=None) -> List[tuple]:
        out = []
        scores = self._gbdt.get_eval_at(data_idx)
        names = self._gbdt.eval_names(data_idx)
        higher_better = self._eval_higher_better(data_idx)
        for mname, s, hb in zip(names, scores, higher_better):
            out.append((name, mname, s, hb))
        if feval is not None:
            if data_idx == 0:
                ds = self._train_set
            else:
                ds = self._valid_sets[data_idx - 1]
            if ds is None:
                raise LightGBMError(
                    "Custom eval needs the Dataset, but it was released "
                    "by free_dataset()")
            ret = feval(self.__inner_predict_for_eval(data_idx), ds)
            if isinstance(ret, list):
                for fname, val, hb in ret:
                    out.append((name, fname, val, hb))
            else:
                fname, val, hb = ret
                out.append((name, fname, val, hb))
        return out

    def _eval_higher_better(self, data_idx: int) -> List[bool]:
        ms = (self._gbdt.training_metrics if data_idx == 0
              else self._gbdt.valid_metrics[data_idx - 1])
        out = []
        for m in ms:
            out.extend([m.factor_to_bigger_better > 0] * len(m.get_names()))
        return out

    def __inner_predict_for_eval(self, data_idx: int) -> np.ndarray:
        raw = (self._gbdt.train_score if data_idx == 0
               else self._gbdt.valid_score_host(data_idx - 1))
        return raw[0] if raw.shape[0] == 1 else raw.reshape(-1)

    # -------------------------------------------------------------- predict
    def predict(self, data, num_iteration: int = -1, raw_score: bool = False,
                pred_leaf: bool = False, pred_contrib: bool = False,
                data_has_header: bool = False,
                is_reshape: bool = True, pred_early_stop: bool = False,
                pred_early_stop_freq: int = 10,
                pred_early_stop_margin: float = 10.0):
        """Predict rows (numpy/pandas/CSR/CSC or a data file path).

        ``pred_contrib=True`` returns per-feature contributions
        (N, num_features + 1) — gain-weighted path attribution, last
        column = bias; rows sum to the raw score (GBDT.pred_contrib).

        The serving choke point: per-request latency and batch size land
        in the process metrics registry (lightgbm_tpu/obs/metrics.py) —
        the C API and file-path predicts all funnel through here.
        """
        if isinstance(data, Dataset):
            raise TypeError("Cannot use Dataset instance for prediction, "
                            "please use raw data instead")
        t0 = _time.perf_counter()
        out, rows = self._predict_data(data, num_iteration, raw_score,
                                       pred_leaf, pred_contrib,
                                       data_has_header, pred_early_stop,
                                       pred_early_stop_freq,
                                       pred_early_stop_margin)
        # rows counted from the INPUT blocks (1-D converted outputs and
        # (n, k) multiclass matrices both count n rows)
        observe_predict(rows, _time.perf_counter() - t0)
        return out

    def _predict_drift(self):
        """Lazy booster-level DriftMonitor (obs/drift.py) for the
        synchronous predict path; the ServingPredictor builds its own.
        Requires ``obs_drift_every`` > 0, an enabled observer and a
        fingerprinted model; ``False`` caches 'checked, unavailable'."""
        mon = self.__dict__.get("_drift_monitor")
        if mon is not None:
            return mon or None
        cfg = self._cfg
        obs = self._gbdt._obs
        mon = False
        if int(getattr(cfg, "obs_drift_every", 0) or 0) > 0 and obs.enabled:
            fp = self._gbdt.drift_fingerprint()
            if fp is not None:
                from .obs.drift import DriftMonitor
                m = DriftMonitor(
                    fp, observer=obs,
                    mode=(cfg.obs_health if cfg.obs_health != "off"
                          else "warn"),
                    every_rows=cfg.obs_drift_every,
                    window_rows=cfg.obs_drift_window,
                    psi_threshold=cfg.obs_drift_psi,
                    topk=cfg.obs_drift_topk,
                    min_labels=cfg.obs_drift_min_labels)
                if m.enabled:
                    mon = m
        self._drift_monitor = mon
        return mon or None

    def _predict_data(self, data, num_iteration, raw_score, pred_leaf,
                      pred_contrib, data_has_header,
                      pred_early_stop=False, pred_early_stop_freq=10,
                      pred_early_stop_margin=10.0):
        """-> (predictions, input row count)."""
        early_predictor = None
        if pred_early_stop and not (pred_leaf or pred_contrib):
            # margin-based prediction early stopping (predictor.hpp):
            # the tree-major loop drops rows whose margin cleared the
            # threshold — approximate by design, like the reference
            from .predictor import Predictor
            early_predictor = Predictor(
                self._gbdt, num_iteration=num_iteration,
                raw_score=raw_score, early_stop=True,
                early_stop_freq=pred_early_stop_freq,
                early_stop_margin=pred_early_stop_margin)
        drift = self._predict_drift()

        def run(block):
            if drift is not None:
                drift.observe_features(block)
            if early_predictor is not None:
                out = early_predictor._predict_impl(block)
            elif pred_contrib:
                return self._gbdt.pred_contrib(block,
                                               num_iteration=num_iteration)
            else:
                out = self._gbdt.predict(block,
                                         num_iteration=num_iteration,
                                         raw_score=raw_score,
                                         pred_leaf=pred_leaf)
            if drift is not None and not pred_leaf:
                drift.observe_scores(out, raw=raw_score)
            return out

        if isinstance(data, str):
            from .io import parser as _parser
            parsed = _parser.parse_file(data, has_header=data_has_header)
            mat = parsed.features
        else:
            if _is_pandas_frame(data):
                data, _, _, _ = _data_from_pandas(
                    data, None, None, self.pandas_categorical)
            mat, _ = _data_from_any(data)
            from .io.sparse import SparseColumns, iter_dense_row_chunks
            if isinstance(mat, SparseColumns):
                # bounded-memory sparse prediction: densify row chunks
                # (tree traversal wants raw values, O(chunk * F) at a time)
                rows = 0
                outs = []
                for _, block in iter_dense_row_chunks(mat):
                    rows += block.shape[0]
                    outs.append(run(block))
                return ((np.concatenate(outs) if outs
                         else np.zeros(0, dtype=np.float64)), rows)
            mat = np.asarray(mat, dtype=np.float64)
            if mat.ndim == 1:
                mat = mat.reshape(1, -1)
        return run(mat), mat.shape[0]

    def serve(self, num_iteration: int = -1, **overrides):
        """Build a ``ServingPredictor`` for this model — the production
        predict front end (docs/Serving.md).

        Concurrent callers ``submit()`` feature rows and get futures;
        requests coalesce into padded power-of-two batches that run
        through AOT-compiled per-bucket executables (zero steady-state
        recompiles), with ``pred_early_stop`` / ``pred_contrib`` served
        from the same queue.  Overload protection and SLO tracking ride
        the same parameters: ``serve_queue_limit`` /
        ``serve_request_deadline_ms`` shed doomed work at admission,
        and the ``serve_slo_*`` targets drive the rolling SLO engine
        whose burn-rate alerts route through the ``obs_health`` channel
        (docs/Observability.md, "Serving observability & SLOs").

        Configured from the booster's ``serve_*`` parameters
        (docs/Parameters.md); keyword ``overrides`` take precedence
        (``max_batch``, ``max_delay_ms``, ``bucket_min``, ``donate``,
        ``batch_event_every``, ``queue_limit``,
        ``request_deadline_ms``, ``request_event_every``,
        ``slo_p99_ms``, ``slo_qps``, ``slo_window_s``, ``slo_every_s``,
        ``slo_mode``, ``drift_every``, ``drift_window``, ``drift_psi``,
        ``drift_topk``, ``drift_min_labels``, ``num_features``,
        ``devices``).  With ``obs_drift_every`` > 0 and a fingerprinted
        model, a DriftMonitor watches the submitted traffic for
        distribution shift vs the training-time reference
        (docs/Observability.md, "Drift & online quality").  Close it
        (or use as a context manager) to flush the queue, stop the
        worker thread and leave the ``serve_summary`` lifetime record.
        """
        from .serve import ServingPredictor
        cfg = self._cfg
        kw = {"max_batch": cfg.serve_max_batch,
              "max_delay_ms": cfg.serve_max_delay_ms,
              "bucket_min": cfg.serve_bucket_min,
              "donate": cfg.serve_donate,
              "batch_event_every": cfg.serve_batch_event_every,
              "queue_limit": cfg.serve_queue_limit,
              "request_deadline_ms": cfg.serve_request_deadline_ms,
              "request_event_every": cfg.serve_request_event_every,
              "slo_p99_ms": cfg.serve_slo_p99_ms,
              "slo_qps": cfg.serve_slo_qps,
              "slo_window_s": cfg.serve_slo_window_s,
              "slo_every_s": cfg.serve_slo_every_s,
              # burn-rate alerts follow the training health channel's
              # consequence mode; obs_health=off still WARNS (an SLO
              # breach must never be silent once targets are set)
              "slo_mode": (cfg.obs_health if cfg.obs_health != "off"
                           else "warn"),
              "drift_every": cfg.obs_drift_every,
              "drift_window": cfg.obs_drift_window,
              "drift_psi": cfg.obs_drift_psi,
              "drift_topk": cfg.obs_drift_topk,
              "drift_min_labels": cfg.obs_drift_min_labels,
              "observer": self._gbdt._obs}
        kw.update(overrides)
        # live telemetry plane (obs/live.py): a serving process exposes
        # the same /metrics /healthz /statusz /events endpoints a
        # training run does — the SLO headline and queue depth ride in
        # through the observer's flight-provider registry
        obs = kw.get("observer")
        http_port = int(getattr(cfg, "obs_http_port", -1))
        if http_port >= 0 and obs is not None and obs.enabled:
            obs.ensure_live_server(
                http_port, str(getattr(cfg, "obs_http_addr", "127.0.0.1")
                               or "127.0.0.1"))
        return ServingPredictor(self._gbdt, num_iteration=num_iteration,
                                **kw)

    # ------------------------------------------------------------ model I/O
    def save_model(self, filename: str, num_iteration: int = -1) -> "Booster":
        """Write the model text file (loadable by the reference too)."""
        self._gbdt.save_model_to_file(filename, num_iteration)
        with open(filename, "a") as f:
            f.write("\npandas_categorical:%s\n"
                    % _dump_pandas_categorical(self.pandas_categorical))
        return self

    def model_to_string(self, num_iteration: int = -1) -> str:
        """Model in the reference-compatible text format."""
        return (self._gbdt.save_model_to_string(num_iteration)
                + "\npandas_categorical:%s\n"
                % _dump_pandas_categorical(self.pandas_categorical))

    def dump_model(self, num_iteration: int = -1) -> dict:
        """Model as a JSON-compatible dict."""
        import json
        return json.loads(self._gbdt.dump_model(num_iteration))

    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        """Per-feature importance: 'split' counts or total 'gain'."""
        return self._gbdt.feature_importance(importance_type)

    def importance_history(self, importance_type: str = "split") -> list:
        """Importance trajectory from the telemetry timeline — the
        ``importance`` events written at the ``obs_importance_every``
        cadence, as ``[{"it", "importance": {feature_index: value}}]``.
        Empty when importance tracking was off for this run."""
        from .obs.model import importance_history as _history
        return _history(self.telemetry(), importance_type)

    def feature_name(self) -> List[str]:
        """Feature names of the training data."""
        return list(self._gbdt.feature_names)

    def num_feature(self) -> int:
        """Number of features the model was trained on."""
        return self._gbdt.max_feature_idx + 1

    def num_trees(self) -> int:
        """Total number of trees across all iterations."""
        return len(self._gbdt.models)

    # pickling support: serialize through the text model format
    def __getstate__(self):
        state = {"params": self.params,
                 "model_str": self.model_to_string(),
                 "best_iteration": self.best_iteration,
                 "best_score": self.best_score,
                 "attr": dict(getattr(self, "_attr", {})),
                 "train_data_name": getattr(self, "_train_data_name",
                                            "training")}
        return state

    def __setstate__(self, state):
        self.params = state["params"]
        self.best_iteration = state.get("best_iteration", -1)
        self.best_score = state.get("best_score", {})
        self._attr = dict(state.get("attr", {}))
        self._train_data_name = state.get("train_data_name", "training")
        self._train_set = None
        self._valid_sets = []
        self.name_valid_sets = []
        self._load_from_string(state["model_str"])

    def __copy__(self):
        return self.__deepcopy__(None)

    def __deepcopy__(self, memo):
        return Booster(params=dict(self.params),
                       model_str=self.model_to_string())
