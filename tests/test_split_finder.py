"""Split-finder tests against a numpy oracle implementing
feature_histogram.hpp:78-387 literally (sequential scans)."""
import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.split_finder import (FeatureMeta, SplitParams,
                                           find_best_split, GAIN, FEATURE,
                                           THRESHOLD, DEFAULT_BIN_FOR_ZERO,
                                           LEFT_OUTPUT, RIGHT_OUTPUT,
                                           LEFT_COUNT, RIGHT_COUNT)

kEps = 1e-15


def oracle_gls(g, h, l1, l2):
    reg = max(abs(g) - l1, 0.0)
    return reg * reg / (h + l2)


def oracle_numerical(hist_g, hist_h, hist_c, num_bin, default_bin,
                     total_g, total_h, total_cnt, p: SplitParams):
    """Literal port of FindBestThresholdNumerical + 3 sequences."""
    total_h = total_h + 2 * kEps
    gain_shift = oracle_gls(total_g, total_h, p.lambda_l1, p.lambda_l2)
    min_gain_shift = gain_shift + p.min_gain_to_split
    best = {"gain": -np.inf}

    def sequence(dbz):
        nonlocal best
        dirn = 1 if dbz == num_bin - 1 else -1
        skip_default = not (0 < dbz < num_bin - 1)
        sg, sh, sc = 0.0, kEps, 0.0
        bb = {"gain": -np.inf}
        if dirn == -1:
            for t in range(num_bin - 1, 0, -1):
                if skip_default and t == default_bin:
                    continue
                sg += hist_g[t]; sh += hist_h[t]; sc += hist_c[t]
                if sc < p.min_data_in_leaf or sh < p.min_sum_hessian_in_leaf:
                    continue
                lc = total_cnt - sc
                if lc < p.min_data_in_leaf:
                    break
                lh = total_h - sh
                if lh < p.min_sum_hessian_in_leaf:
                    break
                lg = total_g - sg
                cur = oracle_gls(lg, lh, p.lambda_l1, p.lambda_l2) + \
                    oracle_gls(sg, sh, p.lambda_l1, p.lambda_l2)
                if cur <= min_gain_shift:
                    continue
                if cur > bb["gain"]:
                    bb = {"gain": cur, "thr": t - 1, "lg": lg, "lh": lh,
                          "lc": lc, "dbz": dbz}
        else:
            for t in range(0, num_bin - 1):
                if skip_default and t == default_bin:
                    continue
                sg += hist_g[t]; sh += hist_h[t]; sc += hist_c[t]
                if sc < p.min_data_in_leaf or sh < p.min_sum_hessian_in_leaf:
                    continue
                rc = total_cnt - sc
                if rc < p.min_data_in_leaf:
                    break
                rh = total_h - sh
                if rh < p.min_sum_hessian_in_leaf:
                    break
                rg = total_g - sg
                cur = oracle_gls(sg, sh, p.lambda_l1, p.lambda_l2) + \
                    oracle_gls(rg, rh, p.lambda_l1, p.lambda_l2)
                if cur <= min_gain_shift:
                    continue
                if cur > bb["gain"]:
                    bb = {"gain": cur, "thr": t, "lg": sg, "lh": sh,
                          "lc": sc, "dbz": dbz}
        if bb["gain"] > best["gain"]:
            best = bb

    if p.use_missing:
        sequence(0)
        if 0 < default_bin < num_bin - 1:
            sequence(default_bin)
        if num_bin > 2:
            sequence(num_bin - 1)
    else:
        sequence(default_bin)
    if best["gain"] == -np.inf:
        return None
    best["gain"] -= min_gain_shift
    return best


def run_case(rng, num_bin, default_bin, l1=0.0, l2=0.0, min_data=1,
             min_hess=1e-3, use_missing=True, min_gain=0.0):
    B = 16
    hist_g = np.zeros(B)
    hist_h = np.zeros(B)
    hist_c = np.zeros(B)
    hist_g[:num_bin] = rng.normal(size=num_bin) * 10
    hist_h[:num_bin] = rng.uniform(0.5, 2.0, size=num_bin) * 5
    hist_c[:num_bin] = rng.integers(1, 50, size=num_bin)
    tg, th, tc = hist_g.sum(), hist_h.sum(), hist_c.sum()
    params = SplitParams(l1, l2, min_gain, float(min_data), min_hess,
                         use_missing)
    meta = FeatureMeta(num_bin=jnp.asarray([num_bin], jnp.int32),
                       default_bin=jnp.asarray([default_bin], jnp.int32),
                       is_categorical=jnp.asarray([False]))
    hist = jnp.asarray(np.stack([hist_g, hist_h, hist_c], -1)[None],
                       jnp.float32)
    out = np.asarray(find_best_split(hist, tg, th, tc, meta,
                                     jnp.asarray([True]), params))
    oracle = oracle_numerical(hist_g, hist_h, hist_c, num_bin, default_bin,
                              tg, th, tc, params)
    return out, oracle


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("default_bin", [0, 3, 9])
def test_numerical_matches_oracle(seed, default_bin):
    rng = np.random.default_rng(seed)
    out, oracle = run_case(rng, num_bin=10, default_bin=default_bin)
    if oracle is None:
        assert out[GAIN] == -np.inf or out[GAIN] <= 0
        return
    assert out[GAIN] == pytest.approx(oracle["gain"], rel=2e-5)
    assert int(out[THRESHOLD]) == oracle["thr"]
    assert int(out[DEFAULT_BIN_FOR_ZERO]) == oracle["dbz"]
    assert out[LEFT_COUNT] == pytest.approx(oracle["lc"])


@pytest.mark.parametrize("seed", range(4))
def test_numerical_with_l1_l2_and_constraints(seed):
    rng = np.random.default_rng(100 + seed)
    out, oracle = run_case(rng, num_bin=12, default_bin=5, l1=2.0, l2=3.0,
                           min_data=30, min_hess=1.0)
    if oracle is None:
        assert not np.isfinite(out[GAIN]) or out[GAIN] <= 0
        return
    assert out[GAIN] == pytest.approx(oracle["gain"], rel=2e-5)
    assert int(out[THRESHOLD]) == oracle["thr"]


@pytest.mark.parametrize("seed", range(4))
def test_numerical_no_missing(seed):
    rng = np.random.default_rng(200 + seed)
    out, oracle = run_case(rng, num_bin=8, default_bin=4, use_missing=False)
    if oracle is None:
        return
    assert out[GAIN] == pytest.approx(oracle["gain"], rel=2e-5)
    assert int(out[THRESHOLD]) == oracle["thr"]


def test_categorical_one_vs_rest():
    # categorical: best split isolates the bin with extreme gradient
    B = 8
    hist_g = np.array([1.0, -30.0, 2.0, 1.5, 0, 0, 0, 0])
    hist_h = np.array([5.0, 10.0, 5.0, 5.0, 0, 0, 0, 0])
    hist_c = np.array([10, 20, 10, 10, 0, 0, 0, 0])
    params = SplitParams(0.0, 0.0, 0.0, 1.0, 1e-3, True)
    meta = FeatureMeta(num_bin=jnp.asarray([4], jnp.int32),
                       default_bin=jnp.asarray([0], jnp.int32),
                       is_categorical=jnp.asarray([True]))
    hist = jnp.asarray(np.stack([hist_g, hist_h, hist_c], -1)[None], jnp.float32)
    out = np.asarray(find_best_split(hist, hist_g.sum(), hist_h.sum(),
                                     hist_c.sum(), meta, jnp.asarray([True]),
                                     params))
    assert int(out[THRESHOLD]) == 1   # isolate category bin 1
    lg = hist_g[1]
    lh = hist_h[1]
    assert out[LEFT_OUTPUT] == pytest.approx(-lg / (lh + kEps), rel=1e-4)


def test_feature_tiebreak_prefers_smaller_index():
    # two identical features -> argmax picks feature 0
    hist_g = np.array([5.0, -5.0, 0, 0])
    hist_h = np.array([3.0, 3.0, 0, 0])
    hist_c = np.array([10, 10, 0, 0])
    one = np.stack([hist_g, hist_h, hist_c], -1)
    hist = jnp.asarray(np.stack([one, one]), jnp.float32)
    params = SplitParams(0.0, 0.0, 0.0, 1.0, 1e-3, True)
    meta = FeatureMeta(num_bin=jnp.asarray([2, 2], jnp.int32),
                       default_bin=jnp.asarray([0, 0], jnp.int32),
                       is_categorical=jnp.asarray([False, False]))
    out = np.asarray(find_best_split(hist, 0.0, 6.0, 20.0, meta,
                                     jnp.asarray([True, True]), params))
    assert int(out[FEATURE]) == 0
