"""Model conversion tools: C++ if-else codegen (compiled & compared — the
reference's tests/cpp_test determinism check), PMML, predictor early stop,
CLI train/predict round trip."""
import os
import subprocess
import ctypes

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.convert_model import model_to_cpp
from lightgbm_tpu.pmml import model_to_pmml
from lightgbm_tpu.predictor import Predictor


def make_model(tmp_path, n=500, f=5, rounds=8):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, f))
    X[::11, 2] = 0.0   # exercise zero/default paths
    y = (X[:, 0] - 0.5 * X[:, 2] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "verbose": -1, "num_leaves": 15},
                    lgb.Dataset(X, label=y), num_boost_round=rounds,
                    verbose_eval=False)
    return bst, X, y


def test_cpp_codegen_matches_predictions(tmp_path):
    """Generate C++ if-else code, compile, and require 5-decimal equality
    with library predictions (tests/cpp_test/test.py:1-6 semantics)."""
    bst, X, y = make_model(tmp_path)
    code = model_to_cpp(bst._gbdt)
    src = tmp_path / "gen.cpp"
    src.write_text(code)
    so = tmp_path / "gen.so"
    subprocess.run(["g++", "-O2", "-shared", "-fPIC", str(src), "-o", str(so)],
                   check=True)
    lib = ctypes.CDLL(str(so))
    lib.LGBMTPU_GenPredictRaw.restype = None
    out = np.zeros(1)
    ours = bst.predict(X, raw_score=True)
    for i in range(0, len(X), 17):
        row = np.ascontiguousarray(X[i], dtype=np.float64)
        lib.LGBMTPU_GenPredictRaw(
            row.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        assert round(out[0], 5) == round(ours[i], 5)


def test_pmml_output_well_formed(tmp_path):
    bst, X, y = make_model(tmp_path)
    xml = model_to_pmml(bst._gbdt)
    import xml.etree.ElementTree as ET
    root = ET.fromstring(xml)
    assert root.tag.endswith("PMML")
    segs = root.findall(".//{http://www.dmg.org/PMML-4_2}Segment")
    assert len(segs) == bst.num_trees()


def test_predictor_early_stop(tmp_path):
    bst, X, y = make_model(tmp_path, rounds=40)
    full = bst.predict(X, raw_score=True)
    pred = Predictor(bst._gbdt, raw_score=True, early_stop=True,
                     early_stop_freq=5, early_stop_margin=1.0)
    stopped = pred.predict(X)
    # early-stopped margins must agree in sign with the full prediction
    assert (np.sign(stopped) == np.sign(full)).mean() > 0.95


def test_cli_train_predict_roundtrip(tmp_path):
    rng = np.random.default_rng(1)
    X = rng.normal(size=(400, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    train_file = tmp_path / "train.tsv"
    np.savetxt(train_file, np.column_stack([y, X]), fmt="%.6f", delimiter="\t")
    model_file = tmp_path / "model.txt"
    result_file = tmp_path / "pred.txt"
    from lightgbm_tpu.cli import main
    main(["task=train", "data=%s" % train_file, "objective=binary",
          "num_trees=5", "verbose=-1", "min_data_in_leaf=5",
          "output_model=%s" % model_file, "snapshot_freq=-1"])
    assert model_file.exists()
    main(["task=predict", "data=%s" % train_file,
          "input_model=%s" % model_file, "output_result=%s" % result_file])
    preds = np.loadtxt(result_file)
    assert len(preds) == 400
    bst = lgb.Booster(model_file=str(model_file))
    np.testing.assert_allclose(preds, bst.predict(X), atol=1e-6)
    # convert_model task
    gen = tmp_path / "gen.cpp"
    main(["task=convert_model", "input_model=%s" % model_file,
          "convert_model=%s" % gen])
    assert "PredictTree0" in gen.read_text()


def test_sklearn_wrappers():
    from lightgbm_tpu.sklearn import LGBMClassifier, LGBMRegressor, LGBMRanker
    rng = np.random.default_rng(2)
    X = rng.normal(size=(400, 5))
    y = (X[:, 0] > 0).astype(int)
    clf = LGBMClassifier(n_estimators=10, num_leaves=15)
    clf.fit(X, y)
    acc = (clf.predict(X) == y).mean()
    assert acc > 0.9
    proba = clf.predict_proba(X)
    assert proba.shape == (400, 2)
    assert clf.feature_importances_.sum() > 0

    yr = X[:, 0] * 2 + 0.1 * rng.normal(size=400)
    reg = LGBMRegressor(n_estimators=20, num_leaves=15)
    reg.fit(X, yr)
    mse = ((reg.predict(X) - yr) ** 2).mean()
    assert mse < 0.5

    # 3-class
    y3 = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
    clf3 = LGBMClassifier(n_estimators=10, num_leaves=15)
    clf3.fit(X, y3)
    assert clf3.n_classes_ == 3
    assert clf3.predict_proba(X).shape == (400, 3)
    assert (clf3.predict(X) == y3).mean() > 0.8

    # ranker
    yrank = np.clip((X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5), 0, 3)
    rk = LGBMRanker(n_estimators=5, num_leaves=7, min_child_samples=5)
    rk.fit(X, yrank.astype(float), group=np.full(40, 10))
    assert rk.booster_.num_trees() > 0


def test_sklearn_custom_objective():
    from lightgbm_tpu.sklearn import LGBMRegressor
    rng = np.random.default_rng(3)
    X = rng.normal(size=(300, 4))
    y = X[:, 0] * 3

    def l2_obj(y_true, y_pred):
        return y_pred - y_true, np.ones_like(y_true)

    reg = LGBMRegressor(n_estimators=30, objective=l2_obj)
    reg.fit(X, y)
    pred = reg.predict(X, raw_score=True)
    assert ((pred - y) ** 2).mean() < 1.0


def _per_row_early_stop_oracle(gbdt, X, instance, num_used, k):
    """The reference's per-row loop (predictor.hpp:33-96), kept as the
    oracle for the vectorized tree-major implementation."""
    n = X.shape[0]
    out = np.zeros((n, k), dtype=np.float64)
    for r in range(n):
        row = X[r:r + 1]
        pred = np.zeros(k)
        for t in range(num_used):
            pred[t % k] += gbdt.models[t].predict(row)[0]
            if (t + 1) % (instance.round_period * k) == 0 and \
                    instance.callback(pred):
                break
        out[r] = pred
    return out


def test_predictor_early_stop_matches_per_row_oracle(tmp_path):
    """The vectorized active-set loop must reproduce the per-row
    semantics EXACTLY — every row stops at the same tree."""
    bst, X, y = make_model(tmp_path, rounds=40)
    pred = Predictor(bst._gbdt, raw_score=True, early_stop=True,
                     early_stop_freq=3, early_stop_margin=0.8)
    got = pred.predict(X)
    k = bst._gbdt.num_tree_per_iteration
    oracle = _per_row_early_stop_oracle(bst._gbdt, X, pred.early_stop,
                                        bst._gbdt._used_trees(-1), k)
    np.testing.assert_array_equal(got, oracle[:, 0])


def test_predictor_early_stop_multiclass_matches_oracle():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(300, 5))
    y = np.argmax(X[:, :3] + 0.3 * rng.normal(size=(300, 3)), axis=1)
    params = {"objective": "multiclass", "num_class": 3, "verbose": -1,
              "num_leaves": 7, "min_data_in_leaf": 5}
    bst = lgb.train(params, lgb.Dataset(X, label=y.astype(np.float64),
                                        params=params), num_boost_round=30)
    pred = Predictor(bst._gbdt, raw_score=True, early_stop=True,
                     early_stop_freq=4, early_stop_margin=0.5)
    got = pred.predict(X)
    k = bst._gbdt.num_tree_per_iteration
    oracle = _per_row_early_stop_oracle(bst._gbdt, X, pred.early_stop,
                                        bst._gbdt._used_trees(-1), k)
    np.testing.assert_array_equal(got, oracle)


def test_predictor_early_stop_custom_scalar_instance(tmp_path):
    """A custom instance without batch_callback rides the scalar
    fallback and must agree with the oracle too."""
    from lightgbm_tpu.predictor import PredictionEarlyStopInstance
    bst, X, y = make_model(tmp_path, rounds=30)
    pred = Predictor(bst._gbdt, raw_score=True, early_stop=True,
                     early_stop_freq=5, early_stop_margin=1.0)
    pred.early_stop = PredictionEarlyStopInstance(
        lambda p: abs(p[0]) > 0.6, 5)        # scalar-only
    got = pred.predict(X)
    k = bst._gbdt.num_tree_per_iteration
    oracle = _per_row_early_stop_oracle(bst._gbdt, X, pred.early_stop,
                                        bst._gbdt._used_trees(-1), k)
    np.testing.assert_array_equal(got, oracle[:, 0])
