# Callback constructors — parity with the reference R-package/R/callback.R
# surface (cb.reset.parameters / cb.print.evaluation / cb.record.evaluation /
# cb.early.stop).
#
# The reference builds an R-side callback engine (R6 CB_ENV, add.cb,
# categorize.callbacks) because its training loop lives in R.  Here the
# loop lives in the Python engine (engine.py), which already runs
# callback objects with before/after-iteration phases — so each R
# constructor returns the corresponding Python callback, and lgb.train /
# lgb.cv forward them through the `callbacks` argument.  One loop, one
# behavior, both languages.

#' Reset parameters on a schedule
#'
#' @param new_params named list; each element is either a vector with one
#'   value per boosting round or a function(iter, nrounds) -> value
#'   (0-based iter, as the reference documents)
#' @return a callback for the callbacks argument of lgb.train / lgb.cv
#' @export
cb.reset.parameters <- function(new_params) {
  lgb <- .lgb_py()
  stopifnot(is.list(new_params), length(names(new_params)) ==
              length(new_params))
  py_args <- lapply(new_params, function(v) {
    if (is.function(v)) .as_py_schedule(v) else as.list(v)
  })
  do.call(lgb$reset_parameter, py_args)
}

# A plain reticulate wrapper has the Python signature (*args, **kwargs),
# so the engine cannot see whether an R schedule is function(iter) or
# function(iter, nrounds).  Tag the wrapper with the explicit arity
# marker the Python side honors (callback.py _schedule_arity); the
# 2-arg form additionally goes through py_func so the call crosses with
# both positional arguments.
.as_py_schedule <- function(v) {
  arity <- length(formals(v))
  pyf <- tryCatch(reticulate::py_func(v), error = function(e) {
    reticulate::r_to_py(v)
  })
  tryCatch(reticulate::py_set_attr(pyf, "lgb_schedule_arity",
                                   if (arity >= 2L) 2L else 1L),
           error = function(e) NULL)
  pyf
}

#' Print evaluation results every `period` iterations
#'
#' @param period print frequency
#' @param show_stdv show fold stdv (cv records)
#' @export
cb.print.evaluation <- function(period = 1L, show_stdv = TRUE) {
  .lgb_py()$print_evaluation(as.integer(period), show_stdv)
}

#' Record evaluation results
#'
#' The recorded history is attached to the returned callback as
#' attr(cb, "eval_result") (a reticulate dict; read it after training
#' with reticulate::py_to_r).  lgb.train already records into
#' attr(bst, "record_evals") by default — this constructor exists for
#' explicit reference-style pipelines.
#' @export
cb.record.evaluation <- function() {
  store <- reticulate::dict()
  cb <- .lgb_py()$record_evaluation(store)
  attr(cb, "eval_result") <- store
  cb
}

#' Early stopping on validation metrics
#'
#' @param stopping_rounds stop when no metric improves this many rounds
#' @param verbose print the early-stop decision
#' @export
cb.early.stop <- function(stopping_rounds, verbose = TRUE) {
  .lgb_py()$early_stopping(as.integer(stopping_rounds), verbose)
}
