"""Persistence round-trips the reference's python tests cover:
pickle/deepcopy of Booster (test_engine.py), and sklearn-ecosystem
integration — clone, GridSearchCV, joblib — (test_sklearn.py).
"""
import copy
import pickle

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _fit(n=1200, f=6, rounds=5):
    rng = np.random.default_rng(21)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1}
    bst = lgb.train(params, lgb.Dataset(X, label=y, params=params),
                    num_boost_round=rounds)
    return bst, X


def test_booster_pickle_roundtrip():
    bst, X = _fit()
    want = bst.predict(X)
    clone = pickle.loads(pickle.dumps(bst))
    np.testing.assert_allclose(clone.predict(X), want, rtol=1e-12)
    assert clone.num_trees() == bst.num_trees()


def test_booster_deepcopy_independent():
    bst, X = _fit()
    want = bst.predict(X)
    dup = copy.deepcopy(bst)
    np.testing.assert_allclose(dup.predict(X), want, rtol=1e-12)
    # mutating the copy's trees must not touch the original
    dup._gbdt.models[0].shrink(0.1)
    assert not np.allclose(dup.predict(X), want)
    np.testing.assert_allclose(bst.predict(X), want, rtol=1e-12)


def test_sklearn_clone_and_refit():
    from sklearn.base import clone
    rng = np.random.default_rng(3)
    X = rng.normal(size=(800, 5))
    y = (X[:, 0] > 0).astype(int)
    est = lgb.LGBMClassifier(n_estimators=5, num_leaves=15)
    est.fit(X, y)
    dup = clone(est)                     # unfitted copy with same params
    assert dup.get_params()["n_estimators"] == 5
    dup.fit(X, y)
    np.testing.assert_allclose(dup.predict_proba(X), est.predict_proba(X),
                               rtol=1e-9)


def test_sklearn_gridsearch():
    from sklearn.model_selection import GridSearchCV
    rng = np.random.default_rng(4)
    X = rng.normal(size=(600, 5))
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    gs = GridSearchCV(lgb.LGBMClassifier(n_estimators=4, verbose=-1),
                      {"num_leaves": [7, 15]}, cv=2, scoring="accuracy")
    gs.fit(X, y)
    assert gs.best_params_["num_leaves"] in (7, 15)
    assert gs.best_score_ > 0.7


def test_sklearn_joblib_roundtrip(tmp_path):
    joblib = pytest.importorskip("joblib")
    rng = np.random.default_rng(5)
    X = rng.normal(size=(800, 5))
    y = rng.normal(size=800) + X[:, 0]
    est = lgb.LGBMRegressor(n_estimators=5, num_leaves=15)
    est.fit(X, y)
    path = tmp_path / "est.joblib"
    joblib.dump(est, path)
    loaded = joblib.load(path)
    np.testing.assert_allclose(loaded.predict(X), est.predict(X),
                               rtol=1e-12)


def test_feature_importance_types():
    """'split' counts and 'gain' totals (basic.py:1646-1680); unknown
    types raise KeyError like the reference."""
    bst, X = _fit()
    split = bst.feature_importance("split")
    gain = bst.feature_importance("gain")
    assert split.sum() > 0 and gain.sum() > 0
    assert split.shape == gain.shape
    # the engineered signal features dominate both measures
    assert split[0] + split[1] >= split[2:].sum()
    assert gain[0] + gain[1] > gain[2:].sum()
    with pytest.raises(KeyError):
        bst.feature_importance("cover")


def test_booster_attrs_and_free_dataset():
    """attr/set_attr (basic.py:1769-1800), set_train_data_name,
    free_dataset."""
    bst, X = _fit()
    assert bst.attr("note") is None
    bst.set_attr(note="hello", run="7")
    assert bst.attr("note") == "hello" and bst.attr("run") == "7"
    bst.set_attr(note=None)
    assert bst.attr("note") is None
    bst.set_train_data_name("mytrain")
    want = bst.predict(X)
    bst.free_dataset()
    np.testing.assert_allclose(bst.predict(X), want, rtol=1e-12)


def test_sklearn_apply_leaf_indices():
    rng = np.random.default_rng(6)
    X = rng.normal(size=(500, 5))
    y = (X[:, 0] > 0).astype(int)
    est = lgb.LGBMClassifier(n_estimators=3, num_leaves=7)
    est.fit(X, y)
    leaves = est.apply(X)
    assert leaves.shape == (500, 3)
    assert leaves.min() >= 0 and leaves.max() < 7


def test_attrs_survive_pickle_and_train_name_shows():
    bst, X = _fit()
    bst.set_attr(best_note="0.9")
    bst.set_train_data_name("mytrain")
    names = [t[0] for t in bst.eval_train()]
    assert names and all(n == "mytrain" for n in names)
    clone = pickle.loads(pickle.dumps(bst))
    assert clone.attr("best_note") == "0.9"
    assert clone._train_data_name == "mytrain"


def test_set_attr_rejects_non_strings():
    bst, _ = _fit()
    with pytest.raises(ValueError):
        bst.set_attr(threshold=0.5)


def test_free_dataset_keeps_valid_indices_aligned():
    """After free_dataset, a new add_valid must NOT report the old
    dataset's scores under the new name, and custom fevals on freed
    slots raise instead of mixing datasets."""
    rng = np.random.default_rng(30)
    X = rng.normal(size=(1200, 6))
    y = (X[:, 0] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "metric": "auc"}
    train = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params=params, train_set=train)
    v1 = train.create_valid(X[:300], label=y[:300])
    bst.add_valid(v1, "v1")
    for _ in range(3):
        bst.update()
    bst.free_dataset()
    # built-in metrics still work on the engine-retained data
    names = [t[0] for t in bst.eval_valid()]
    assert names == ["v1"]
    # custom eval needs the freed Dataset -> clear error
    with pytest.raises(lgb.LightGBMError):
        bst.eval_valid(feval=lambda preds, ds: ("f", float(ds.num_data()),
                                                True))
