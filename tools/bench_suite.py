"""Headline-benchmark suite: the reference's GPU-Performance.md shapes,
synthetic stand-ins, timed on the current backend with wedge resilience.

Shapes (docs/GPU-Performance.md:75-82; sizes scaled to this host where
noted): Higgs 10.5M x 28 dense binary; Epsilon 400k x 2000 dense binary;
MS-LTR 2.27M x 137 lambdarank; Expo-style categorical (2M x 40, 10
high-cardinality categorical columns — the categorical-direct path the
reference claims ~8x over one-hot on, README.md:31).  Bosch's sparse
shape is covered by tools/tpu_ab2.py.

Each shape's BINNED dataset is cached as /tmp/suite_<name>.bin (atomic
publish) so wedge retries skip the one-core host binning.  One
measurement per subprocess, probe between shapes, results appended to
tools/BENCH_SUITE.md as they land.

Usage:  python tools/bench_suite.py [shape ...]      # default: all
        python tools/bench_suite.py --ref [shape ..] # reference-CLI arms
        python tools/bench_suite.py --child <json>   # internal
REF_LGBM points at the reference binary (default /tmp/refbuild/lightgbm).
"""
import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "tools", "BENCH_SUITE.md")

SHAPES = {
    # name: (rows, features, task-params, warmup, measured, timeout_s)
    "higgs": dict(n=10_500_000, f=28, params={
        "objective": "binary", "metric": "auc", "num_leaves": 255,
        "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 1},
        warmup=3, measured=10, timeout=2700),
    "epsilon": dict(n=400_000, f=2000, params={
        "objective": "binary", "metric": "auc", "num_leaves": 255,
        "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 1},
        warmup=2, measured=5, timeout=2700),
    "msltr": dict(n=2_270_000, f=137, params={
        "objective": "lambdarank", "metric": "ndcg", "ndcg_eval_at": "10",
        "num_leaves": 255, "max_bin": 63, "learning_rate": 0.1,
        "min_data_in_leaf": 1}, warmup=2, measured=5, timeout=2700,
        query_size=120),
    # Yahoo-LTR stand-in (473,134 x 700 ranking, GPU-Performance.md:80):
    # the wide-feature ranking point of the reference's six-dataset table
    "yahoo": dict(n=473_134, f=700, params={
        "objective": "lambdarank", "metric": "ndcg", "ndcg_eval_at": "1,10",
        "num_leaves": 255, "max_bin": 63, "learning_rate": 0.1,
        "min_data_in_leaf": 1}, warmup=2, measured=5, timeout=2700,
        query_size=23),
    # width arm at the WIDE shape: epsilon's in-VMEM block at the auto
    # W=32 is 2000*64*3*32*4B ~= 49 MB — inside the 64 MB gate, so auto
    # runs pallas_t W=32; this arm measures W=16 against it (wide
    # shapes pay more VMEM per wave slot, so the width economics can
    # flip vs the 28-col flagship)
    "epsilon_p16": dict(n=400_000, f=2000, cache_as="epsilon", params={
        "objective": "binary", "metric": "auc", "num_leaves": 255,
        "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 1,
        "tpu_histogram_mode": "pallas_t", "tpu_wave_width": 16},
        warmup=2, measured=5, timeout=2700),
    "expo_cat": dict(n=2_000_000, f=40, params={
        "objective": "binary", "metric": "auc", "num_leaves": 255,
        "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 1,
        "categorical_feature": ",".join(str(i) for i in range(10))},
        warmup=2, measured=5, timeout=2700, n_cat=10, cardinality=100),
    # width arm at the FLAGSHIP shape: at 1M the W=64 arm lost to W=32
    # (fixed per-wave cost dominates); at 10.5M each sweep is a full
    # pass over 10x the rows, so halving sweeps/tree may flip the
    # economics — measure, don't extrapolate
    "higgs_w64": dict(n=10_500_000, f=28, cache_as="higgs", params={
        "objective": "binary", "metric": "auc", "num_leaves": 255,
        "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 1,
        "tpu_histogram_mode": "pallas_t", "tpu_wave_width": 64},
        warmup=3, measured=10, timeout=2700),
    # v5 fused kernel at the flagship shape (one Xt read per wave, no
    # partition scan) — the candidate to beat pallas_t's auto default
    "higgs_ct": dict(n=10_500_000, f=28, cache_as="higgs", params={
        "objective": "binary", "metric": "auc", "num_leaves": 255,
        "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 1,
        "tpu_histogram_mode": "pallas_ct", "tpu_wave_width": 32},
        warmup=3, measured=10, timeout=2700),
    # spectator-row compaction at the flagship (tpu_wave_compact): late
    # waves gather only active rows (~35% of kernel row work is
    # spectator rows, ROADMAP r4).  Split structure is exact; float
    # fields can drift by f32 ulps at multi-tile N (tile-boundary
    # reassociation, tests/test_wave_compact.py).  Promote to auto iff
    # AUC within 5e-5 of the higgs_ct arm (reassociation noise is
    # ~1e-7 relative; anything larger is a real bug) and it/s >= 1.1x
    # the ct number
    "higgs_compact": dict(n=10_500_000, f=28, cache_as="higgs", params={
        "objective": "binary", "metric": "auc", "num_leaves": 255,
        "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 1,
        "tpu_histogram_mode": "pallas_ct", "tpu_wave_width": 32,
        "tpu_wave_compact": True},
        warmup=3, measured=10, timeout=2700),
    # exact-commit-order waves at the flagship (tpu_wave_order=exact):
    # trees match tpu_wave_width=1 bit-for-bit, so its AUC delta vs the
    # reference equals the EXACT arm's (+7.7e-6 at 10.5M).  This is the
    # fallback headline config if the 10.5M batched-wave parity arm
    # lands >1e-4 (VERDICT r4 #5) — this arm prices that fallback.
    "higgs_xo": dict(n=10_500_000, f=28, cache_as="higgs", params={
        "objective": "binary", "metric": "auc", "num_leaves": 255,
        "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 1,
        "tpu_histogram_mode": "pallas_ct", "tpu_wave_width": 32,
        "tpu_wave_order": "exact"},
        warmup=3, measured=10, timeout=2700),
    # single-bf16-product histograms (tpu_hist_precision=bf16, the
    # gpu_use_dp=false analog): the kernel is MXU-FLOP-bound (~71%
    # utilization at the flagship, 13:17 trace), so halving the dots
    # should land ~1.7-1.9x — quality delta vs the hi/lo arm decides
    # whether it can ever be a default
    "higgs_bf16": dict(n=10_500_000, f=28, cache_as="higgs", params={
        "objective": "binary", "metric": "auc", "num_leaves": 255,
        "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 1,
        "tpu_histogram_mode": "pallas_ct", "tpu_wave_width": 32,
        "tpu_hist_precision": "bf16"},
        warmup=3, measured=10, timeout=2700),
    # compare-select score update at the flagship (the 86 ms/iter = 11%
    # gather term, 13:17 trace); and the everything-on arm stacking it
    # with bf16 single-product histograms
    "higgs_su": dict(n=10_500_000, f=28, cache_as="higgs", params={
        "objective": "binary", "metric": "auc", "num_leaves": 255,
        "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 1,
        "tpu_score_update": "pallas"},
        warmup=3, measured=10, timeout=2700),
    "higgs_fast": dict(n=10_500_000, f=28, cache_as="higgs", params={
        "objective": "binary", "metric": "auc", "num_leaves": 255,
        "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 1,
        "tpu_score_update": "pallas", "tpu_hist_precision": "bf16"},
        warmup=3, measured=10, timeout=2700),
    # pallas_ct at the WIDE shapes (promotion widening: ct auto is
    # currently gated to ncols*bin_pad <= 2048 — these arms supply the
    # wide-F datapoints; the W=16-epsilon / W=32-bosch pathology says
    # wide-F cells can surprise)
    # wide-F compaction arm (r5): epsilon under pallas_t + the
    # vector-partition compact tier — the wide-shape form of
    # higgs_compact; run when a window allows (not in the armed chain)
    "epsilon_tc": dict(n=400_000, f=2000, cache_as="epsilon", params={
        "objective": "binary", "metric": "auc", "num_leaves": 255,
        "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 1,
        "tpu_histogram_mode": "pallas_t", "tpu_wave_width": 32,
        "tpu_wave_compact": True},
        warmup=2, measured=5, timeout=2700),
    # expo_cat sits just past the ct auto bound (40 cols x 64-pad =
    # 2560 > 2048) so it pays the pallas_t two-pass pipeline; this arm
    # prices ct there — with the small per-wave work of 2M x 40, the
    # saved partition pass is the biggest single lever the 3.9x shape
    # has (VERDICT r4 weak #7)
    "expo_ct": dict(n=2_000_000, f=40, cache_as="expo_cat", params={
        "objective": "binary", "metric": "auc", "num_leaves": 255,
        "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 1,
        "categorical_feature": ",".join(str(i) for i in range(10)),
        "tpu_histogram_mode": "pallas_ct", "tpu_wave_width": 32},
        warmup=2, measured=5, timeout=2700, n_cat=10, cardinality=100),
    "epsilon_ct": dict(n=400_000, f=2000, cache_as="epsilon", params={
        "objective": "binary", "metric": "auc", "num_leaves": 255,
        "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 1,
        "tpu_histogram_mode": "pallas_ct", "tpu_wave_width": 32},
        warmup=2, measured=5, timeout=2700),
    "msltr_ct": dict(n=2_270_000, f=137, cache_as="msltr", params={
        "objective": "lambdarank", "metric": "ndcg", "ndcg_eval_at": "10",
        "num_leaves": 255, "max_bin": 63, "learning_rate": 0.1,
        "min_data_in_leaf": 1,
        "tpu_histogram_mode": "pallas_ct", "tpu_wave_width": 32},
        warmup=2, measured=5, timeout=2700, query_size=120),
    # width probe at the yahoo shape: if its 7.06 s/iter sits in the
    # same ~17-24 MB hist-block pathology band as epsilon-W16/bosch-W32,
    # W=64 (34 MB block) should be sharply faster
    "yahoo_w64": dict(n=473_134, f=700, cache_as="yahoo", params={
        "objective": "lambdarank", "metric": "ndcg",
        "ndcg_eval_at": "1,10", "num_leaves": 255, "max_bin": 63,
        "learning_rate": 0.1, "min_data_in_leaf": 1,
        "tpu_wave_width": 64}, warmup=2, measured=5, timeout=2700,
        query_size=23),
}


def _check_aliases():
    """cache_as arms must agree with their target on every data-defining
    field — a mismatch would silently benchmark the wrong dataset."""
    for name, spec in SHAPES.items():
        tgt = spec.get("cache_as")
        if not tgt:
            continue
        for k in ("n", "f", "n_cat", "cardinality", "query_size"):
            assert spec.get(k) == SHAPES[tgt].get(k), (
                "%s.%s=%r != %s.%s=%r" % (name, k, spec.get(k),
                                          tgt, k, SHAPES[tgt].get(k)))


_check_aliases()


def make_shape(name):
    """Deterministic synthetic data for a shape; returns (X, y, query).
    Seeded by a STABLE hash — Python's hash() is salted per process,
    which would give the TPU and reference-CLI arms different data."""
    import zlib

    import numpy as np
    spec = SHAPES[name]
    n, f = spec["n"], spec["f"]
    seed_name = spec.get("cache_as", name)
    rng = np.random.default_rng(zlib.crc32(seed_name.encode()))
    chunks, ys = [], []
    w = rng.normal(size=f) * (rng.random(f) > 0.3)
    n_cat = spec.get("n_cat", 0)
    card = spec.get("cardinality", 0)
    cat_effect = (rng.normal(size=(n_cat, card)) * 0.6
                  if n_cat else None)
    for start in range(0, n, 500_000):
        m = min(500_000, n - start)
        X = rng.normal(size=(m, f)).astype(np.float32)
        logit = X @ w * 0.4
        if n_cat:
            codes = rng.integers(0, card, size=(m, n_cat))
            X[:, :n_cat] = codes
            logit = logit + cat_effect[np.arange(n_cat), codes].sum(axis=1)
        logit = logit + 0.6 * rng.normal(size=m)
        chunks.append(X)
        ys.append(logit)
    X = np.concatenate(chunks)
    raw = np.concatenate(ys)
    query = None
    if spec.get("query_size"):
        qs = spec["query_size"]
        nq = n // qs
        query = np.full(nq + (1 if n % qs else 0), qs, np.int32)
        if n % qs:
            query[-1] = n % qs
        # graded relevance 0-4 from the standardized raw score
        y = np.clip((raw - raw.mean()) / raw.std() * 1.2 + 2, 0,
                    4).round().astype(np.float64)
    else:
        y = (raw > 0).astype(np.float64)
    return X, y, query


def cache_path(name):
    return "/tmp/suite_%s.bin" % SHAPES.get(name, {}).get("cache_as", name)


def cached_dataset(name):
    import lightgbm_tpu as lgb
    spec = SHAPES[name]
    cache = cache_path(name)
    if os.path.exists(cache):
        return lgb.Dataset(cache)
    X, y, query = make_shape(name)
    ds = lgb.Dataset(X, label=y, params=dict(spec["params"], verbose=-1))
    if query is not None:
        ds.set_group(query)
    ds.construct()
    ds.save_binary(cache + ".tmp")
    os.replace(cache + ".tmp", cache)
    return lgb.Dataset(cache)


def child(name):
    """One timed measurement on the current backend; prints a JSON line.
    Timing protocol lives in bench_modes.run (one copy)."""
    from tools.bench_modes import run
    spec = SHAPES[name]
    ds = cached_dataset(name)
    t_load = time.time()
    # pin the timeline path (bench_modes.run only setdefaults it) so the
    # measurement can be ingested into the cross-run ledger afterwards
    obs_path = "/tmp/suite_obs_%s_%d.jsonl" % (name, os.getpid())
    try:
        os.unlink(obs_path)
    except OSError:
        pass
    # mode=auto + width -1: measure what a DEFAULT user gets at the shape
    dt, metric, g = run(None, None, "auto", wave_width=-1,
                        warmup=spec["warmup"], measured=spec["measured"],
                        extra=dict(spec["params"], tpu_growth="auto",
                                   verbose=-1, obs_events_path=obs_path),
                        train_set=ds, details=True)
    lrn = g.learner
    # ledger ingestion is explicit here (the observer belongs to
    # bench_modes): suite = the shape arm, shape = its nominal size —
    # best-effort, a ledger problem must not void the measurement
    try:
        from lightgbm_tpu.obs.ledger import Ledger, default_ledger_dir
        if default_ledger_dir():
            Ledger(default_ledger_dir()).ingest_timeline(
                obs_path, suite="suite_" + name,
                shape="%dx%d" % (spec["n"], spec["f"]))
    except Exception as e:
        print("suite: ledger ingest failed: %s" % e, file=sys.stderr)
    print(json.dumps({
        "dt": dt, "metric": float(metric),
        "mode": lrn.hist_mode, "growth": lrn.growth,
        "order": getattr(lrn, "wave_order", "-"),
        "W": int(getattr(lrn, "wave_width", 0)),
        "source": "obs_timeline",       # dt from the emitted telemetry
        "wall": time.time() - t_load}), flush=True)


def ref_arm(name, iters=3):
    """Time the reference CLI on the same data (s/iter from per-iteration
    wall lines); writes the shape as TSV once (cached)."""
    import numpy as np
    ref = os.environ.get("REF_LGBM", "/tmp/refbuild/lightgbm")
    if not os.path.exists(ref):
        raise RuntimeError("reference binary not found at %s" % ref)
    tsv = "/tmp/suite_%s.tsv" % name
    spec = SHAPES[name]
    if not os.path.exists(tsv):
        import pandas as pd
        X, y, query = make_shape(name)
        df = pd.DataFrame(X)
        df.insert(0, "label", y)
        df.to_csv(tsv + ".tmp", sep="\t", header=False, index=False,
                  float_format="%g")
        if query is not None:
            # the .query side-file must exist BEFORE the TSV publish —
            # the cache check tests only the TSV, so the reverse order
            # could publish a permanently query-less dataset
            np.savetxt(tsv + ".query", query, fmt="%d")
        os.replace(tsv + ".tmp", tsv)
    conf = dict(spec["params"])
    conf.update({"task": "train", "data": tsv, "num_trees": iters + 2,
                 "verbosity": 2, "output_model": "/tmp/suite_ref.model"})
    args = [ref] + ["%s=%s" % kv for kv in conf.items()]
    t0 = time.time()
    r = subprocess.run(args, capture_output=True, text=True,
                       timeout=3 * 3600)
    wall = time.time() - t0
    # per-iteration seconds from the CLI's timing lines
    import re
    if r.returncode != 0:
        raise RuntimeError("reference CLI rc=%d: %s"
                           % (r.returncode,
                              (r.stderr or r.stdout).strip()[-300:]))
    secs = [float(m.group(1)) for m in re.finditer(
        r"(\d+\.\d+) seconds elapsed", r.stdout + r.stderr)]
    if len(secs) < 2:
        raise RuntimeError("reference CLI produced no per-iteration "
                           "timing lines; cannot derive s/iter")
    dt = (secs[-1] - secs[0]) / (len(secs) - 1)
    print(json.dumps({"dt": dt, "wall": wall}), flush=True)


def append(line):
    print(line, flush=True)
    with open(OUT, "a") as f:
        f.write(line + "\n")


def main():
    from tools.tpu_ab2 import probe_with_retries, _last_error_line
    names = [a for a in sys.argv[1:] if not a.startswith("--")] \
        or list(SHAPES)
    ref_mode = "--ref" in sys.argv
    if ref_mode:
        # cache_as arms differ only in TPU-side knobs — the CPU CLI
        # baseline would duplicate the target shape's number (and balk
        # at the tpu_* params), so they have no reference arm
        names = [n for n in names if "cache_as" not in SHAPES[n]]
    stamp = datetime.datetime.now(datetime.timezone.utc)
    if not os.path.exists(OUT):
        with open(OUT, "w") as f:
            f.write("# Headline-shape benchmark results "
                    "(tools/bench_suite.py)\n")
    append("\n## %s UTC — %s arms: %s"
           % (stamp.isoformat(timespec="seconds"),
              "reference-CLI" if ref_mode else "TPU", " ".join(names)))
    for name in list(names):
        if not ref_mode:
            break
        names.remove(name)
        t0 = time.time()
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--child-ref", name], capture_output=True, text=True,
                timeout=3 * 3600, cwd=REPO)
            res = json.loads(out.stdout.strip().splitlines()[-1])
            append("    %-10s reference-CLI: %.3f s/iter (%.3f it/s) "
                   "[wall %.0fs]" % (name, res["dt"],
                                     1.0 / res["dt"],
                                     time.time() - t0))
        except Exception as e:
            append("    %-10s reference-CLI: FAILED (%s)" % (name, e))

    # TPU arms: wedge-resilient like tpu_ab2 — a shape skipped because
    # the tunnel is down goes back on the queue and the outer loop keeps
    # grinding until the deadline, so a mid-run wedge costs retries, not
    # the arm (observed: wedges of 2h+ that then recover)
    deadline = time.time() + float(os.environ.get("SUITE_DEADLINE_S",
                                                  6 * 3600))
    pending = list(names)
    down_since = None      # one line per outage, not one per probe pass
    timeouts = {n: 0 for n in names}   # per-shape give-up cap (as tpu_ab2)
    while pending and time.time() < deadline:
        name = pending.pop(0)
        backend = probe_with_retries()
        # a transient CPU fallback mid-tunnel-recovery must NOT start a
        # flagship-sized measurement on the host CPU (hours, and the
        # number would be meaningless) — non-tpu counts as unreachable,
        # but the log says which it was so outage durations stay honest
        usable = backend == "tpu" or (backend is not None
                                      and os.environ.get("SUITE_ALLOW_CPU"))
        if not usable:
            if down_since is None:
                down_since = time.time()
                reason = ("unreachable" if backend is None
                          else "on non-tpu backend %r" % backend)
                append("    (device %s; %d shape(s) queued, "
                       "retrying until deadline)"
                       % (reason, len(pending) + 1))
            pending.append(name)
            time.sleep(120)
            continue
        if down_since is not None:
            append("    (device back after %.0f min down)"
                   % ((time.time() - down_since) / 60.0))
            down_since = None
        t0 = time.time()
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child",
                 name], capture_output=True, text=True,
                timeout=SHAPES[name]["timeout"], cwd=REPO)
            if r.returncode != 0:
                raise RuntimeError(_last_error_line(r.stderr,
                                                    "suite_" + name,
                                                    r.returncode))
            res = json.loads(r.stdout.strip().splitlines()[-1])
            append("    %-10s: %.3f s/iter (%.2f it/s) metric=%.5f "
                   "[%s/%s/%s W=%d, wall %.0fs]"
                   % (name, res["dt"], 1.0 / res["dt"], res["metric"],
                      res["mode"], res["growth"], res["order"], res["W"],
                      time.time() - t0))
        except subprocess.TimeoutExpired:
            timeouts[name] += 1
            if timeouts[name] >= 2:
                # twice through the full per-shape timeout with a live
                # probe in between = deterministic hang, not a wedge —
                # give up so it can't starve the shapes behind it
                append("    %-10s: TIMEOUT x%d after %ds each — giving up"
                       % (name, timeouts[name], SHAPES[name]["timeout"]))
            else:
                append("    %-10s: TIMEOUT after %ds (re-queued)"
                       % (name, SHAPES[name]["timeout"]))
                pending.append(name)
        except Exception as e:
            append("    %-10s: FAILED (%s)" % (name, e))
    for name in pending:
        append("    %-10s: UNMEASURED (deadline exhausted)" % name)


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--child":
        child(sys.argv[2])
    elif len(sys.argv) > 2 and sys.argv[1] == "--child-ref":
        ref_arm(sys.argv[2])
    else:
        main()
