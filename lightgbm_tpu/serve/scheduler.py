"""Async microbatch scheduler: coalesce concurrent predict requests.

A serving process sees many small concurrent requests; the device wants
few large batches.  ``MicrobatchScheduler`` sits between them: callers
``submit()`` feature blocks and get a ``concurrent.futures.Future``; a
single worker thread coalesces the queue head into one batch until it
reaches ``max_batch`` rows or the OLDEST queued request has waited
``max_delay_ms`` — the deadline that bounds p99 latency when traffic is
too thin to fill a bucket.  The batch then runs ONCE through the bucket
executable (serve/executable.py) and the result is split back per
request.

Correctness leans on row independence: every row of the batched program
computes exactly what it would compute alone (element-wise Kahan lanes,
no cross-row reductions), so a caller cannot tell — bit for bit —
whether its rows shared a bucket with strangers.  tests/test_serve.py
pins concurrent-vs-solo equality.

Requests with different semantics (raw vs converted, early-stop,
pred_contrib) carry a route key; only same-route neighbors coalesce.
Early-stop and contrib requests batch through the host predictor paths
(row-independent f64, identical to ``Booster.predict``), so the one
queue fronts every prediction flavor.

Overload protection (PR 7): an unbounded queue turns overload into
unbounded latency — every admitted request waits behind the whole
backlog, so by the time it runs its caller has long timed out and the
server does the work anyway ("the goodput collapse").  The scheduler
therefore sheds AT ADMISSION: ``queue_limit`` bounds the queue outright,
and a request carrying a deadline is rejected immediately when the
queue's projected wait (coalescing delay + backlog batches x EWMA batch
execute time) already exceeds it.  Shed requests fail fast with
``ServeOverloadError`` on their future; shedding is never silent — it
counts into ``lgbm_serve_shed_total`` (by route kind and reason), the
SLO engine's shed rate, ``stats()`` and the close-time ``serve_summary``
event, and the queue-age gauge shows the backlog building first.  A
sustained shed STORM (every ``SHED_STORM_AFTER``-th shed) additionally
signals the incident engine (obs/incident.py), which debounces the
repeats into one grouped incident with an evidence bundle.

Observability: every completed request feeds the rolling SLO engine
(obs/serve.py) and every Nth (``request_event_every``) emits a
``serve_request`` trace event decomposing its latency into enqueue →
coalesce-wait → encode/pad/execute (spans reported by the route runner
via ``record_span``) → respond, tagged with batch id and bucket.  The
worker arms the hang watchdog around every runner call, and registers a
flight-context provider so a wedged runner's flight record carries the
live queue depth and pending route kinds.
"""
from __future__ import annotations

import collections
import math
import threading
import time
from concurrent.futures import Future

import numpy as np

from ..obs.events import NULL_OBSERVER
from ..obs.metrics import (REGISTRY, observe_serve_batch,
                           observe_serve_queue_age, observe_serve_request,
                           observe_serve_shed)
from ..obs.serve import route_kind
from ..utils.log import Log

# EWMA weight for the per-batch execute-time estimate behind the
# deadline admission check (same alpha discipline as obs/health.py)
_EWMA_ALPHA = 0.3

# every Nth shed signals the incident engine (obs/incident.py): a lone
# shed is a blip the one-time warning already covers, a run of them is
# a storm worth an evidence bundle — the engine debounces repeats into
# one grouped incident
SHED_STORM_AFTER = 8


class ServeOverloadError(RuntimeError):
    """A request shed at admission by overload protection.  Carries the
    machine-readable ``reason``: ``queue_full`` (bounded queue at
    ``serve_queue_limit``) or ``deadline`` (projected wait exceeds the
    request's deadline)."""

    def __init__(self, message, reason):
        super().__init__(message)
        self.reason = reason


class _Request:
    __slots__ = ("features", "n", "future", "t", "deadline_s")

    def __init__(self, features, n, future, t, deadline_s=None):
        self.features = features
        self.n = n
        self.future = future
        self.t = t
        self.deadline_s = deadline_s


class MicrobatchScheduler:
    """The generic coalescing core: a FIFO of (route, features) requests
    drained by one worker thread into per-route batches.

    ``runner(route, features)`` scores one concatenated (n, F) block and
    returns an array whose leading axis is rows; the scheduler slices it
    back per request.  Head-of-line batching preserves submission order:
    only the leading run of same-route requests coalesces, so a stream
    of mixed routes drains fairly.
    """

    def __init__(self, runner, max_batch: int = 8192,
                 max_delay_ms: float = 2.0, observer=None,
                 batch_event_every: int = 0, name: str = "serve",
                 bucket_for=None, queue_limit: int = 0,
                 default_deadline_s: float = 0.0, slo=None,
                 request_event_every: int = 0, fault_hook=None):
        self._runner = runner
        # route-aware bucket sizing for the pad/bucket accounting on
        # serve_batch events (rows == bucket when absent — host routes)
        self._bucket_for = bucket_for or (lambda route, rows: rows)
        self.max_batch = max(1, int(max_batch))
        self.max_delay_s = max(0.0, float(max_delay_ms)) / 1e3
        self.observer = observer if observer is not None else NULL_OBSERVER
        self.batch_event_every = max(0, int(batch_event_every))
        self.request_event_every = max(0, int(request_event_every))
        self.queue_limit = max(0, int(queue_limit))      # 0 = unbounded
        self.default_deadline_s = max(0.0, float(default_deadline_s))
        self.slo = slo                       # obs.serve.SloEngine or None
        # fault-injection hook for tests/bench: called as
        # fault_hook(route, batch) on the worker just before the runner
        # — a sleeping/blocking hook simulates a slow or wedged runner
        self._fault_hook = fault_hook
        self.name = name
        self._queue = collections.deque()   # (route, _Request)
        self._cv = threading.Condition()
        self._closing = False
        self._batches = 0
        self._rows = 0
        self._pad_rows = 0
        self._max_depth = 0
        self._queued_rows = 0
        self._requests_done = 0
        self._shed = {}                     # reason -> count
        self._ewma_exec_s = 0.0
        self._spans = {}                    # runner-reported trace spans
        self._inflight = REGISTRY.gauge(
            "lgbm_serve_queue_depth",
            "requests waiting in the microbatch queue")
        # a wedged runner's flight record must show what was stuck
        # behind it: queue depth, queued rows, pending route kinds
        self.observer.add_flight_provider(self._flight_state)
        # lgbm- prefix: the host profiler (obs/prof.py), flight records
        # and external tools all attribute thread samples by this name
        self._worker = threading.Thread(
            target=self._loop, name="lgbm-%s-microbatch" % name,
            daemon=True)
        self._worker.start()

    # ------------------------------------------------------------- submit
    def _projected_wait_locked(self, n_rows: int) -> float:
        """Admission-time wait estimate for a request of ``n_rows``:
        the coalescing delay plus the batches ahead of it (backlog +
        itself) at the EWMA per-batch execute time.  Zero until the
        first batch completes — a cold scheduler never deadline-sheds
        on a guess."""
        if self._ewma_exec_s <= 0.0:
            return 0.0
        batches = math.ceil((self._queued_rows + n_rows)
                            / float(self.max_batch))
        return self.max_delay_s + batches * self._ewma_exec_s

    def submit(self, route, features, n_rows: int,
               deadline_s=None) -> Future:
        """Enqueue one request; resolves to the route runner's output
        rows for this request (exceptions propagate to the future).

        ``deadline_s`` is the caller's end-to-end latency budget
        (default ``default_deadline_s``; 0/None = no deadline).  A
        request whose projected wait already exceeds its deadline — or
        that arrives with the queue at ``queue_limit`` — is shed: its
        future fails immediately with ``ServeOverloadError`` instead of
        queueing work whose answer nobody will be around to read."""
        fut = Future()
        now = time.perf_counter()
        if deadline_s is None:
            deadline_s = self.default_deadline_s or None
        reason = None
        with self._cv:
            if self._closing:
                raise RuntimeError("%s: scheduler is closed" % self.name)
            if self.queue_limit and len(self._queue) >= self.queue_limit:
                reason = "queue_full"
                detail = ("queue at limit (%d requests)"
                          % len(self._queue))
            elif deadline_s:
                projected = self._projected_wait_locked(int(n_rows))
                if projected > deadline_s:
                    reason = "deadline"
                    detail = ("projected wait %.1fms > deadline %.1fms"
                              % (projected * 1e3, deadline_s * 1e3))
            if reason is None:
                req = _Request(features, int(n_rows), fut, now,
                               deadline_s)
                self._queue.append((route, req))
                depth = len(self._queue)
                self._max_depth = max(self._max_depth, depth)
                self._queued_rows += req.n
                self._inflight.set(depth)
                observe_serve_queue_age(now - self._queue[0][1].t)
                self._cv.notify()
            else:
                first = reason not in self._shed
                self._shed[reason] = self._shed.get(reason, 0) + 1
                shed_total = sum(self._shed.values())
        if reason is not None:
            observe_serve_shed(route, reason)
            if self.slo is not None:
                self.slo.record_shed(route, reason)
            if first:        # never silent, never per-request log spam
                Log.warning("%s: shedding route %s (%s) — overload "
                            "protection engaged; see lgbm_serve_shed_"
                            "total for the running count", self.name,
                            route_kind(route), detail)
            if shed_total % SHED_STORM_AFTER == 0:
                # a storm, not a blip: every SHED_STORM_AFTER-th shed
                # feeds the incident engine so sustained overload opens
                # ONE grouped incident (obs/incident.py debounces);
                # host-side dict work off the worker thread, no fence
                try:
                    self.observer.incident_signal(
                        "shed_storm",
                        {"shed_total": shed_total, "reason": reason,
                         "route": route_kind(route),
                         "queue_limit": self.queue_limit})
                except Exception:
                    pass
            fut.set_exception(ServeOverloadError(
                "%s: request shed (%s)" % (self.name, detail), reason))
        return fut

    def record_span(self, name: str, seconds: float):
        """Route runners report per-stage timings (encode/pad/execute,
        serve/executable.py) for the CURRENT batch here; the scheduler
        folds them into sampled ``serve_request`` trace events.  Worker
        thread only — cleared before every runner call."""
        self._spans[name] = self._spans.get(name, 0.0) + float(seconds)

    # ------------------------------------------------------------- worker
    def _head_rows(self, route) -> int:
        rows = 0
        for r, req in self._queue:
            if r != route:
                break
            rows += req.n
        return rows

    def _pop_batch(self, route):
        """The leading same-route run, capped at max_batch rows (a
        single oversized request still pops alone — the runner chunks)."""
        batch = []
        rows = 0
        while self._queue and self._queue[0][0] == route:
            req = self._queue[0][1]
            if batch and rows + req.n > self.max_batch:
                break
            self._queue.popleft()
            batch.append(req)
            rows += req.n
        self._queued_rows = max(0, self._queued_rows - rows)
        self._inflight.set(len(self._queue))
        observe_serve_queue_age(
            time.perf_counter() - self._queue[0][1].t
            if self._queue else 0.0)
        return batch

    def _loop(self):
        while True:
            with self._cv:
                while not self._queue and not self._closing:
                    self._cv.wait()
                if not self._queue:
                    return                        # closing, drained
                route, head = self._queue[0]
                deadline = head.t + self.max_delay_s
                while not self._closing:
                    if self._head_rows(route) >= self.max_batch:
                        break
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                batch = self._pop_batch(route)
            try:
                self._run_batch(route, batch)
            except Exception as e:        # the worker must never die
                Log.warning("%s: microbatch worker error: %s",
                            self.name, e)
                for r in batch:
                    if not r.future.done():
                        try:
                            r.future.set_exception(e)
                        except Exception:
                            pass

    def _run_batch(self, route, batch):
        # claim every future first: a request cancelled while queued
        # drops out here, and a claimed future can no longer be
        # cancelled, so set_result/set_exception below cannot raise
        batch = [r for r in batch
                 if r.future.set_running_or_notify_cancel()]
        if not batch:
            return
        t0 = time.perf_counter()
        queue_s = t0 - batch[0].t
        rows_in = sum(r.n for r in batch)
        obs = self.observer
        self._spans = {}
        # arm the hang watchdog around the runner: a wedged device call
        # or deadlocked host predictor dumps a flight record naming
        # this batch (plus the queue state via the flight provider)
        obs.watchdog_arm("serve batch route=%s rows=%d"
                         % (route_kind(route), rows_in))
        try:
            if self._fault_hook is not None:
                self._fault_hook(route, batch)
            if len(batch) == 1:
                feats = batch[0].features
            else:
                feats = np.concatenate([r.features for r in batch])
            out = self._runner(route, feats)
        except Exception as e:                    # surface per caller
            now = time.perf_counter()
            for r in batch:
                r.future.set_exception(e)
                if self.slo is not None:
                    self.slo.record(route, now - r.t, error=True)
            return
        finally:
            obs.watchdog_disarm()
        now = time.perf_counter()
        lo = 0
        for r in batch:
            # copy, not a view: callers own their result array and must
            # not be able to corrupt batch neighbors through it
            r.future.set_result(out[lo:lo + r.n].copy())
            lo += r.n
            self._requests_done += 1
            observe_serve_request(now - r.t)
            if self.slo is not None:
                self.slo.record(route, now - r.t)
        respond_s = time.perf_counter() - now
        rows = lo
        self._batches += 1
        self._rows += rows
        exec_s = now - t0
        self._ewma_exec_s = (exec_s if self._ewma_exec_s <= 0.0 else
                             (1.0 - _EWMA_ALPHA) * self._ewma_exec_s
                             + _EWMA_ALPHA * exec_s)
        bucket = self._bucket_for(route, rows)
        pad = max(bucket - rows, 0)
        self._pad_rows += pad
        observe_serve_batch(route, rows, pad, bucket, queue_s, exec_s)
        if (obs.enabled and self.batch_event_every
                and self._batches % self.batch_event_every == 0):
            obs.event("serve_batch", route=str(route),
                      kind=route_kind(route), rows=rows,
                      bucket=bucket, pad=pad, requests=len(batch),
                      queue_s=round(queue_s, 6), exec_s=round(exec_s, 6))
        if obs.enabled and self.request_event_every:
            self._trace_requests(obs, route, batch, bucket, t0, exec_s,
                                 respond_s)

    def _trace_requests(self, obs, route, batch, bucket, t0, exec_s,
                        respond_s):
        """Every ``request_event_every``-th completed request leaves a
        ``serve_request`` trace: its latency decomposed into the queue
        wait it personally paid, the batch's encode/pad/execute spans
        (record_span, serve/executable.py) and the respond (slice+copy)
        time, tagged with the batch id and bucket it rode in."""
        base = dict(self._spans)
        first = self._requests_done - len(batch)
        for i, r in enumerate(batch):
            if (first + i + 1) % self.request_event_every:
                continue
            spans = {"queue_s": round(t0 - r.t, 6),
                     "exec_s": round(exec_s, 6),
                     "respond_s": round(respond_s, 6)}
            for name, v in base.items():
                spans[name] = round(v, 6)
            rec = {"route": str(route), "kind": route_kind(route),
                   "rows": r.n, "bucket": bucket, "batch": self._batches,
                   "requests": len(batch), "spans": spans,
                   "total_s": round(time.perf_counter() - r.t, 6)}
            if r.deadline_s:
                rec["deadline_s"] = round(r.deadline_s, 6)
            obs.event("serve_request", **rec)

    # --------------------------------------------------------- forensics
    def _flight_state(self):
        """Flight-record context (obs/watchdog.py): best-effort snapshot
        of the live queue — called from the watchdog/signal thread,
        possibly while the queue is mutating, so it must never block or
        raise."""
        try:
            pending = list(self._queue)
        except RuntimeError:           # deque mutated mid-iteration
            pending = []
        kinds = {}
        oldest = None
        for rt, req in pending:
            kinds[route_kind(rt)] = kinds.get(route_kind(rt), 0) + 1
            if oldest is None or req.t < oldest:
                oldest = req.t
        state = {"name": self.name, "queue_depth": len(pending),
                 "queued_rows": sum(req.n for _, req in pending),
                 "pending_routes": kinds, "batches": self._batches,
                 "shed": dict(self._shed),
                 "ewma_exec_s": round(self._ewma_exec_s, 6)}
        if oldest is not None:
            state["oldest_wait_s"] = round(
                time.perf_counter() - oldest, 6)
        return {"serve": state}

    # -------------------------------------------------------------- admin
    def stats(self) -> dict:
        return {"batches": self._batches, "rows": self._rows,
                "pad_rows": self._pad_rows,
                "max_queue_depth": self._max_depth,
                "requests": self._requests_done,
                "shed": dict(self._shed),
                "shed_total": sum(self._shed.values())}

    def close(self):
        """Flush the queue and stop the worker; idempotent."""
        with self._cv:
            if self._closing and not self._worker.is_alive():
                return
            self._closing = True
            self._cv.notify_all()
        self._worker.join()
        self.observer.remove_flight_provider(self._flight_state)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False


class ServingPredictor:
    """The production predict front end: one object per model snapshot,
    shared by any number of submitting threads.

    * plain / raw predictions route through the AOT executable cache
      (device path, zero steady-state recompiles);
    * ``pred_early_stop`` / ``pred_contrib`` route through the host
      predictor paths — batched through the same queue, bit-identical
      to ``Booster.predict``;
    * a model whose features the device path cannot encode (mixed
      categorical/numerical use) falls back to the host predictor for
      every route, transparently.

    Output shapes match ``Booster.predict``: 1-D for single-output
    models, (n, k) for multiclass, (n, num_features + 1) for contrib.
    """

    def __init__(self, gbdt, num_iteration: int = -1, num_features=None,
                 max_batch: int = 8192, max_delay_ms: float = 2.0,
                 bucket_min: int = 64, donate: str = "auto",
                 devices=None, observer=None, batch_event_every: int = 0,
                 queue_limit: int = 0, request_deadline_ms: float = 0.0,
                 request_event_every: int = 0, slo_p99_ms: float = 0.0,
                 slo_qps: float = 0.0, slo_window_s: float = 60.0,
                 slo_every_s: float = 10.0, slo_mode: str = "warn",
                 fault_hook=None, drift_every: int = 0,
                 drift_window: int = 8192, drift_psi: float = 0.2,
                 drift_topk: int = 10, drift_min_labels: int = 100,
                 drift_fingerprint=None, drift_mode: str = None):
        from .executable import PredictExecutableCache
        self.gbdt = gbdt
        self.num_iteration = int(num_iteration)
        self.observer = observer if observer is not None else NULL_OBSERVER
        self._summary_done = False
        self.cache = None
        try:
            self.cache = PredictExecutableCache(
                gbdt, num_iteration=num_iteration,
                num_features=num_features, devices=devices, donate=donate,
                bucket_min=bucket_min, max_batch=max_batch,
                observer=self.observer)
        except ValueError as e:
            Log.warning("serve: device executables unavailable (%s); "
                        "serving from the host predictor", e)
        self._host_predictors = {}
        self._host_lock = threading.Lock()
        # SLO engine only when it has something to do: targets to
        # verdict/page on, or an observer to snapshot into — the
        # default un-observed predictor keeps its hot path unchanged
        self.slo = None
        if (float(slo_p99_ms or 0) > 0 or float(slo_qps or 0) > 0
                or (self.observer.enabled and float(slo_every_s or 0) > 0)):
            from ..obs.serve import SloEngine
            self.slo = SloEngine(
                observer=self.observer, mode=slo_mode, p99_ms=slo_p99_ms,
                qps=slo_qps, window_s=slo_window_s, every_s=slo_every_s)
        # drift monitor only when asked for AND the model carries a
        # training-time fingerprint to compare against (obs/drift.py);
        # like the SLO engine, absent means the hot path is unchanged
        self.drift = None
        if int(drift_every or 0) > 0:
            fp = (drift_fingerprint if drift_fingerprint is not None
                  else gbdt.drift_fingerprint())
            if fp is None:
                Log.warning("serve: obs_drift_every=%d but the model "
                            "has no drift fingerprint (trained before "
                            "schema 14, or obs_drift_fingerprint=false)"
                            "; drift monitoring disabled",
                            int(drift_every))
            else:
                from ..obs.drift import DriftMonitor
                mon = DriftMonitor(
                    fp, observer=self.observer,
                    mode=(drift_mode if drift_mode is not None
                          else slo_mode),
                    every_rows=drift_every, window_rows=drift_window,
                    psi_threshold=drift_psi, topk=drift_topk,
                    min_labels=drift_min_labels)
                if mon.enabled:
                    self.drift = mon
        self.scheduler = MicrobatchScheduler(
            self._run_route, max_batch=max_batch,
            max_delay_ms=max_delay_ms, observer=self.observer,
            batch_event_every=batch_event_every,
            bucket_for=self._bucket_of, queue_limit=queue_limit,
            default_deadline_s=max(0.0, float(request_deadline_ms)) / 1e3,
            slo=self.slo, request_event_every=request_event_every,
            fault_hook=fault_hook)
        # the SLO headline rides into flight records AND the live
        # /statusz plane (obs/live.py) through the same provider
        # registry the scheduler's queue state uses
        self._slo_flight = None
        if self.slo is not None:
            self._slo_flight = lambda: {"slo": self.slo.headline()}
            self.observer.add_flight_provider(self._slo_flight)
        self._drift_flight = None
        if self.drift is not None:
            self._drift_flight = lambda: {"drift": self.drift.headline()}
            self.observer.add_flight_provider(self._drift_flight)

    # -------------------------------------------------------------- routes
    def _bucket_of(self, route, rows):
        if self.cache is not None and route[0] == "dev" \
                and rows <= self.cache.max_batch:
            return self.cache.bucket_for(rows)
        return rows

    def _host_predictor(self, key):
        """Memoized host Predictor per (raw, early_stop, freq, margin)."""
        with self._host_lock:
            p = self._host_predictors.get(key)
            if p is None:
                from ..predictor import Predictor
                raw, early, freq, margin = key
                p = Predictor(self.gbdt, num_iteration=self.num_iteration,
                              raw_score=raw, early_stop=early,
                              early_stop_freq=freq,
                              early_stop_margin=margin)
                self._host_predictors[key] = p
            return p

    def _run_route(self, route, feats):
        kind = route[0]
        if kind == "dev":
            convert = route[1]
            out = self.cache.predict_batch(feats, convert=convert)
            # forward the executable's stage decomposition (encode /
            # pad / execute / convert) into this batch's trace spans
            for name, v in self.cache.last_spans.items():
                self.scheduler.record_span(name, v)
            return out[:, 0] if self.cache.k == 1 else out
        if kind == "contrib":
            return self.gbdt.pred_contrib(
                feats, num_iteration=self.num_iteration)
        # host routes: ("host", raw, width) and
        # ("es", raw, freq, margin, width) — width is part of the key
        # so only same-width requests coalesce (np.concatenate)
        if kind == "es":
            raw, freq, margin = route[1:4]
            return self._host_predictor((raw, True, freq, margin)
                                        ).predict(feats)
        return self._host_predictor((route[1], False, 10, 10.0)
                                    ).predict(feats)

    def _route_for(self, raw_score, pred_contrib, pred_early_stop,
                   freq, margin, width):
        if pred_contrib:
            return ("contrib", width)
        if pred_early_stop:
            return ("es", bool(raw_score), int(freq), float(margin),
                    width)
        if self.cache is not None:
            return ("dev", not raw_score)
        return ("host", bool(raw_score), width)

    # -------------------------------------------------------------- public
    def submit(self, features, raw_score: bool = False,
               pred_contrib: bool = False, pred_early_stop: bool = False,
               pred_early_stop_freq: int = 10,
               pred_early_stop_margin: float = 10.0,
               deadline_ms=None, ids=None) -> Future:
        """Enqueue one request; the future resolves to the same array
        ``Booster.predict`` would return for these rows.

        ``deadline_ms`` overrides the predictor-wide
        ``serve_request_deadline_ms`` for this request; when the queue's
        projected wait already exceeds it the future fails fast with
        ``ServeOverloadError`` instead of queueing doomed work.

        ``ids``: optional per-row request ids; with drift monitoring on
        they key this request's predictions for the delayed-label
        channel (``record_outcome``)."""
        X = np.asarray(features, np.float64)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        X = np.ascontiguousarray(X)
        # the drift monitor reads the host array we already hold — bins
        # with the frozen training mappers, no device work, no fences
        if self.drift is not None:
            self.drift.observe_features(X)
        route = self._route_for(raw_score, pred_contrib, pred_early_stop,
                                pred_early_stop_freq,
                                pred_early_stop_margin, X.shape[1])
        if route[0] == "dev":
            # one canonical width per dev route, so any two valid
            # requests can share a batch (too-narrow ones raise HERE,
            # in the caller, not inside a stranger's microbatch)
            X = self.cache.normalize(X)
        deadline_s = (None if deadline_ms is None
                      else max(0.0, float(deadline_ms)) / 1e3 or None)
        fut = self.scheduler.submit(route, X, X.shape[0],
                                    deadline_s=deadline_s)
        if self.drift is not None and not pred_contrib:
            raw = bool(raw_score)

            def _capture(f, raw=raw, ids=ids):
                if f.cancelled() or f.exception() is not None:
                    return
                try:
                    out = f.result()
                    self.drift.observe_scores(out, raw=raw)
                    if ids is not None:
                        self.drift.note_predictions(ids, out, raw=raw)
                except Exception as e:   # monitoring never fails a request
                    Log.warning("drift: score capture failed: %s", e)
            fut.add_done_callback(_capture)
        return fut

    def predict(self, features, **kw) -> np.ndarray:
        """Synchronous convenience: submit + wait."""
        return self.submit(features, **kw).result()

    def record_outcome(self, ids, labels) -> int:
        """The delayed-label channel: join ground-truth labels with the
        predictions earlier ``submit(..., ids=...)`` calls recorded, so
        the drift monitor can track rolling online AUC/logloss vs the
        training-time reference.  Returns how many ids joined; 0 with
        drift monitoring off."""
        if self.drift is None:
            return 0
        return self.drift.record_outcome(ids, labels)

    def warmup(self, sizes=(), raw_score: bool = False):
        """Pre-compile the bucket executables covering ``sizes`` row
        counts, then mark the cache warm so any later compile counts as
        a steady-state violation.  Returns the compiled bucket list."""
        buckets = []
        if self.cache is not None and sizes:
            buckets = self.cache.warmup(sizes, convert=not raw_score)
            self.cache.mark_warm()
        return buckets

    def stats(self) -> dict:
        out = dict(self.scheduler.stats())
        if self.cache is not None:
            out["executables"] = self.cache.stats()
        if self.slo is not None:
            out["slo"] = self.slo.summary()
        if self.drift is not None:
            out["drift"] = self.drift.summary()
        return out

    def close(self):
        """Stop the worker, then leave the lifetime record: a
        ``serve_summary`` event (the run_end of a serving session — a
        short-lived server still shows up on the timeline), a final SLO
        snapshot, and the close-time watermarks in the metrics export.
        Idempotent."""
        self.scheduler.close()
        if self._slo_flight is not None:
            self.observer.remove_flight_provider(self._slo_flight)
            self._slo_flight = None
        if self._drift_flight is not None:
            self.observer.remove_flight_provider(self._drift_flight)
            self._drift_flight = None
        if self._summary_done:
            return
        self._summary_done = True
        if self.drift is not None:
            self.drift.close()
        st = self.stats()
        REGISTRY.gauge(
            "lgbm_serve_max_queue_depth",
            "peak microbatch queue depth over the predictor's life").max(
                st["max_queue_depth"])
        if self.slo is not None:
            self.slo.close()
        obs = self.observer
        if obs.enabled:
            rec = {"batches": st["batches"], "rows": st["rows"],
                   "pad_rows": st["pad_rows"],
                   "max_queue_depth": st["max_queue_depth"],
                   "requests": st["requests"],
                   "shed": st["shed"], "shed_total": st["shed_total"]}
            if "executables" in st:
                rec["executables"] = st["executables"]
            if "slo" in st:
                rec["slo"] = st["slo"]
            if "drift" in st:
                rec["drift"] = st["drift"]
            obs.event("serve_summary", **rec)
            obs.flush()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
