"""Pre-binned mmap-able dataset format (io/binned_format.py).

Round trips must train bit-identical trees; corruption, truncation, and
schema drift must fail loudly; and the streamed build must honor the
bounded-host-memory contract (peak-RSS watermark in a fresh process).
"""
import json
import os
import subprocess
import sys
import zlib

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io import binned_format as bf
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.utils.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def xy():
    rng = np.random.default_rng(31)
    X = rng.normal(size=(3000, 8))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
          "min_data_in_leaf": 5, "verbose": -1}


def _binned_dir(xy, tmp_path, name="binned"):
    X, y = xy
    out = str(tmp_path / name)
    TrainingData.from_streamed(X, y, Config(dict(PARAMS)), out_dir=out)
    return out


def test_round_trip_trains_identical_trees(xy, tmp_path):
    X, y = xy
    b1 = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y,
                                             params=dict(PARAMS)),
                   num_boost_round=8)
    out = str(tmp_path / "rt")
    lgb.Dataset(X, label=y, params=dict(PARAMS)).save_binned(out)
    b2 = lgb.train(dict(PARAMS), lgb.Dataset(out, params=dict(PARAMS)),
                   num_boost_round=8)
    assert b1.model_to_string() == b2.model_to_string()
    # engine.train accepts the directory path directly
    b3 = lgb.train(dict(PARAMS), out, num_boost_round=8)
    assert b1.model_to_string() == b3.model_to_string()


def test_reload_is_mmap_backed_with_zero_rebinning(xy, tmp_path):
    X, y = xy
    out = _binned_dir(xy, tmp_path)
    td = TrainingData.from_binned(out)
    assert isinstance(td._binned_reader.shard(0), np.memmap)
    assert td._binned is None            # nothing materialized yet
    st = td._construct_stats
    assert st["source"] == "binned"
    assert st["sketch_s"] == 0.0 and st["bin_s"] == 0.0
    ref = TrainingData.from_matrix(X, y, Config(dict(PARAMS)))
    np.testing.assert_array_equal(td.binned, ref.binned)
    np.testing.assert_array_equal(np.asarray(td.metadata.label),
                                  np.asarray(ref.metadata.label))


def test_metadata_round_trip(xy, tmp_path):
    X, y = xy
    rng = np.random.default_rng(7)
    w = rng.random(len(y)).astype(np.float64)
    group = [1000, 1200, 800]
    out = str(tmp_path / "meta")
    TrainingData.from_streamed(X, y, Config(dict(PARAMS)), weights=w,
                               group=group, out_dir=out)
    td = TrainingData.from_binned(out)
    np.testing.assert_allclose(np.asarray(td.metadata.weights), w,
                               rtol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(td.metadata.query_boundaries),
        np.cumsum([0] + group))


def test_corrupt_shard_fails_loudly(xy, tmp_path):
    out = _binned_dir(xy, tmp_path)
    shard = os.path.join(out, bf.shard_name(0))
    with open(shard, "r+b") as f:
        f.seek(100)
        b = f.read(1)
        f.seek(100)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(bf.BinnedFormatError, match="checksum"):
        TrainingData.from_binned(out)


def test_truncated_shard_fails_loudly(xy, tmp_path):
    out = _binned_dir(xy, tmp_path)
    shard = os.path.join(out, bf.shard_name(0))
    with open(shard, "r+b") as f:
        f.truncate(os.path.getsize(shard) - 64)
    with pytest.raises(bf.BinnedFormatError):
        TrainingData.from_binned(out, verify=False)   # size check alone


def test_schema_rev_mismatch_fails_loudly(xy, tmp_path):
    out = _binned_dir(xy, tmp_path)
    hp = os.path.join(out, bf.HEADER_NAME)
    with open(hp) as f:
        header = json.load(f)
    header["schema_rev"] = bf.SCHEMA_REV + 1
    with open(hp, "w") as f:
        json.dump(header, f)
    with pytest.raises(bf.BinnedFormatError, match="schema"):
        TrainingData.from_binned(out)


def test_can_load_binned_rejects_non_dirs(tmp_path):
    assert not TrainingData.can_load_binned(str(tmp_path / "absent"))
    assert not TrainingData.can_load_binned(str(tmp_path))  # no header
    plain = tmp_path / "plain.txt"
    plain.write_text("1,2,3\n")
    assert not TrainingData.can_load_binned(str(plain))


def test_streamed_npy_rss_watermark(tmp_path):
    """The out-of-core contract, measured: a 64 MiB .npy (4x a 16 MiB
    host-RAM budget) streams into a binned dir in a FRESH process with
    peak-RSS growth <= 32 MiB (2x budget).  Materializing the raw
    matrix — the bug class satellite (a) audits for — adds 64 MiB+ and
    fails the watermark."""
    n, f = 500_000, 16
    path = str(tmp_path / "big.npy")
    arr = np.lib.format.open_memmap(path, mode="w+", dtype=np.float64,
                                    shape=(n, f))
    rng = np.random.default_rng(3)
    for s in range(0, n, 50_000):            # slab writes: test process
        arr[s:s + 50_000] = rng.normal(size=(50_000, f))
    del arr
    script = r"""
import resource
import sys

import numpy as np

sys.path.insert(0, sys.argv[3])
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.utils.config import Config

path, out = sys.argv[1], sys.argv[2]
cfg = {"max_bin": 63, "verbose": -1, "bin_construct_sample_cnt": 50000,
       "ooc_chunk_rows": 32768}
# warm lazy allocations (parser tables, pool plumbing) on a tiny build
# so the watermark below measures ONLY the big streamed construction
TrainingData.from_streamed(np.zeros((64, 4)), np.zeros(64),
                           Config(dict(cfg)))
scale = 1 if sys.platform == "darwin" else 1024
rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * scale
td = TrainingData.from_streamed(path, np.zeros(500_000),
                                Config(dict(cfg)), out_dir=out)
rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * scale
assert td._binned_reader is not None and td._binned is None
growth = rss1 - rss0
print("rss growth bytes:", growth)
assert growth <= 32 << 20, \
    "peak RSS grew %.1f MiB > 32 MiB budget" % (growth / 2**20)
assert td._construct_stats["rows"] == 500_000
"""
    r = subprocess.run(
        [sys.executable, "-c", script, path, str(tmp_path / "out"), REPO],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, \
        "watermark subprocess failed:\n%s\n%s" % (r.stdout, r.stderr)


def test_shard_crc_matches_recompute(xy, tmp_path):
    out = _binned_dir(xy, tmp_path)
    with open(os.path.join(out, bf.HEADER_NAME)) as f:
        header = json.load(f)
    for sh in header["shards"]:
        with open(os.path.join(out, sh["file"]), "rb") as f:
            assert (zlib.crc32(f.read()) & 0xFFFFFFFF) == sh["crc32"]
