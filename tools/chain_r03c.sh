#!/bin/bash
# Stage 3: after stage-2's bench exits, validate the now-legal pallas_t
# layouts + lookup combos at 1M, then re-run the headline-shape TPU arms.
cd /root/repo
while pgrep -f "chain_r03b.sh" > /dev/null; do sleep 60; done
echo "[chain3] stage2 done at $(date -u)" >> /tmp/chain_r03.log
python tools/tpu_ab2.py 999424 --r03b > /tmp/ab2_r03c.out 2>&1
echo "[chain3] ab rc=$? at $(date -u)" >> /tmp/chain_r03.log
python tools/bench_suite.py higgs higgs_w64 epsilon epsilon_p16 msltr expo_cat >> /tmp/chain_r03.log 2>&1
echo "[chain3] suite rc=$? at $(date -u)" >> /tmp/chain_r03.log
