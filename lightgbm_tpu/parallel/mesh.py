"""Distributed tree learning over a device mesh — the Network layer reborn.

The reference distributes with a socket/MPI Allreduce stack
(src/network/network.cpp:23-185, linkers_socket.cpp) and three learner
subclasses (feature/data/voting parallel, src/treelearner/
*_parallel_tree_learner.cpp).  TPU-native, the whole Network layer collapses
into XLA collectives over an ICI mesh:

* data-parallel  — rows sharded, histograms psum'd inside the grow program
  (`lax.psum` == ReduceScatter+Allgather of HistogramBinEntry sums,
  data_parallel_tree_learner.cpp:148-222);
* feature-parallel — all rows everywhere, features sharded; only the best
  SplitInfo crosses devices (an argmax-reduce of the packed split vector,
  feature_parallel_tree_learner.cpp:52-76);
* voting-parallel — data-parallel with top-k histogram exchange
  (voting_parallel_tree_learner.cpp); on ICI bandwidth the full psum is
  usually faster, so voting maps to the data-parallel path (kept as a
  config alias; a true top-k exchange is a DCN-scale optimization).

Multi-host: `jax.distributed.initialize` + the same mesh spanning all
processes replaces machine_list_file/port handshakes (linkers_socket.cpp).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..io.dataset import TrainingData
from ..ops.grow import make_grow_fn
from ..ops.learner import SerialTreeLearner, build_split_params
from ..ops.split_finder import FeatureMeta
from ..utils.config import Config
from ..utils.log import Log

DATA_AXIS = "data"


def make_data_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def pad_rows(n: int, num_shards: int) -> int:
    """Rows padded so each shard holds the same count (XLA static shapes)."""
    return (-n) % num_shards


class DataParallelTreeLearner(SerialTreeLearner):
    """Row-sharded learner; one psum per histogram construction.

    The same grow program as the serial learner runs under shard_map with
    `psum_axis='data'`: per-leaf histograms and root sums are all-reduced so
    every shard sees identical split decisions and applies them to its local
    rows — the lock-step SPMD structure of the reference's data-parallel
    loop (SURVEY.md §3.5) with XLA supplying the ring reductions.
    """

    def __init__(self, config: Config, train_data: TrainingData,
                 mesh: Optional[Mesh] = None):
        self.mesh = mesh if mesh is not None else make_data_mesh()
        n_shards = self.mesh.devices.size
        n = train_data.num_data
        pad = pad_rows(n, n_shards)
        self._pad = pad
        binned = train_data.binned
        if pad:
            binned = np.concatenate(
                [binned, np.zeros((pad, binned.shape[1]), binned.dtype)])
        x_sharding = NamedSharding(self.mesh, P(DATA_AXIS, None))
        X_dev = jax.device_put(binned, x_sharding)
        super().__init__(config, train_data, psum_axis=DATA_AXIS,
                         device_data=X_dev)
        self._row_sharding = NamedSharding(self.mesh, P(DATA_AXIS))
        self._ones = jax.device_put(
            np.concatenate([np.ones(n, np.float32),
                            np.zeros(pad, np.float32)]).astype(self.dtype),
            self._row_sharding)
        grow = make_grow_fn(self.num_leaves, self.num_bins, self.meta,
                            self.params, config.max_depth,
                            hist_mode="scatter", hist_dtype=self.dtype,
                            psum_axis=DATA_AXIS)
        try:
            sharded_grow = shard_map(
                grow, mesh=self.mesh,
                in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS),
                          P(DATA_AXIS), P()),
                out_specs=(jax.tree_util.tree_map(lambda _: P(),
                                                  self._dummy_tree_spec()),
                           P(DATA_AXIS)))
        except TypeError:
            sharded_grow = shard_map(
                grow, mesh=self.mesh,
                in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS),
                          P(DATA_AXIS), P()),
                out_specs=(jax.tree_util.tree_map(lambda _: P(),
                                                  self._dummy_tree_spec()),
                           P(DATA_AXIS)),
                check_rep=False)
        self._grow = jax.jit(sharded_grow)
        Log.info("Data-parallel learner over %d devices (%d padded rows)",
                 n_shards, pad)

    def _dummy_tree_spec(self):
        # a TreeArrays-shaped pytree of None leaves for out_specs mapping
        from ..ops.grow import TreeArrays
        return TreeArrays(*([0] * len(TreeArrays._fields)))

    def _pad_rows_dev(self, arr, fill=0.0):
        arr = jnp.asarray(arr, self.dtype)
        if self._pad:
            arr = jnp.concatenate(
                [arr, jnp.full((self._pad,), fill, self.dtype)])
        return jax.device_put(arr, self._row_sharding)

    def train_device(self, grad, hess, row_mult=None, feature_mask=None):
        grad = self._pad_rows_dev(grad)
        hess = self._pad_rows_dev(hess)
        if row_mult is None:
            row_mult = self._ones
        else:
            row_mult = self._pad_rows_dev(row_mult)
        if feature_mask is None:
            feature_mask = self.sample_feature_mask()
        tree, leaf_id = self._grow(self.X, grad, hess, row_mult, feature_mask)
        return tree, leaf_id[:self.train_data.num_data] if self._pad else leaf_id


def create_tree_learner(config: Config, train_data: TrainingData,
                        mesh: Optional[Mesh] = None):
    """TreeLearner::CreateTreeLearner (tree_learner.h:19-82) — learner type
    x device dispatch.  'serial' on one device; 'data'/'feature'/'voting'
    over the mesh ('feature' currently routes to data-parallel: with rows
    sharded the search is already feature-complete per shard; a dedicated
    feature-sharded search is tracked for wide datasets)."""
    ltype = config.tree_learner
    n_dev = len(jax.devices()) if mesh is None else mesh.devices.size
    if ltype in ("data", "feature", "voting", "data_parallel",
                 "feature_parallel", "voting_parallel") and n_dev > 1:
        return DataParallelTreeLearner(config, train_data, mesh)
    if ltype not in ("serial", "data", "feature", "voting", "data_parallel",
                     "feature_parallel", "voting_parallel"):
        Log.fatal("Unknown tree learner type %s", ltype)
    return SerialTreeLearner(config, train_data)
