"""The python-guide examples must run end-to-end (reference parity:
examples/python-guide/*.py are executable documentation).

Each example executes in a child process that pins the CPU platform
BEFORE any jax import (the conftest trick — the env var alone does not
override an axon TPU platform), so the suite stays hermetic on machines
with a flaky device tunnel.
"""
import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
GUIDE = os.path.join(REPO, "examples", "python-guide")

RUNNER = """
import jax
jax.config.update("jax_platforms", "cpu")
import runpy, sys
runpy.run_path(sys.argv[1], run_name="__main__")
"""


@pytest.mark.parametrize("name", ["simple_example", "advanced_example",
                                  "plot_example", "sklearn_example"])
def test_python_guide_example_runs(name):
    r = subprocess.run(
        [sys.executable, "-c", RUNNER,
         os.path.join(GUIDE, name + ".py")],
        capture_output=True, text=True, timeout=900, cwd=REPO)
    assert r.returncode == 0, "%s failed:\n%s" % (name, r.stderr[-2000:])
    assert r.stdout.strip(), "%s produced no output" % name
