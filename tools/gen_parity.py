"""Training-quality parity: reference CLI vs lightgbm_tpu, head to head.

Trains BOTH frameworks on the golden datasets (tests/data/golden/) with
IDENTICAL configs, predicts the held-out test split with each, and scores
both prediction sets with the same metric code (tools/parity_metrics.py).
This is the analog of the reference's CPU-vs-GPU accuracy table
(docs/GPU-Performance.md:134-145): training quality must match, not just
model-file compatibility.

Writes PARITY_TRAINING.json + a markdown table into PARITY_TRAINING.md.
tests/test_parity_vs_reference.py pins the committed deltas and, when a
reference binary is present, re-verifies live.

Usage: python tools/gen_parity.py [/path/to/reference-cli]
       (default binary: $REF_LGBM or /tmp/refbuild/lightgbm)
"""
import json
import os
import subprocess
import sys
import tempfile

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
GOLDEN = os.path.join(REPO, "tests", "data", "golden")
sys.path.insert(0, REPO)
sys.path.insert(0, HERE)

from parity_metrics import (auc, load_query, load_tsv, logloss,  # noqa: E402
                            multi_logloss, ndcg_at, rmse)

TASKS = {
    "binary": {
        "params": {"objective": "binary", "num_trees": 60, "num_leaves": 15,
                   "max_bin": 63, "learning_rate": 0.1,
                   "min_data_in_leaf": 5},
        "metrics": lambda y, p, q: {"auc": auc(y, p),
                                    "logloss": logloss(y, p)},
    },
    "regression": {
        "params": {"objective": "regression", "num_trees": 60,
                   "num_leaves": 15, "max_bin": 63, "learning_rate": 0.1,
                   "min_data_in_leaf": 5},
        "metrics": lambda y, p, q: {"rmse": rmse(y, p)},
    },
    "multiclass": {
        "params": {"objective": "multiclass", "num_class": 3,
                   "num_trees": 40, "num_leaves": 15, "max_bin": 63,
                   "learning_rate": 0.1, "min_data_in_leaf": 5},
        "metrics": lambda y, p, q: {
            "multi_logloss": multi_logloss(y, p.reshape(len(y), -1))},
    },
    "lambdarank": {
        "params": {"objective": "lambdarank", "num_trees": 60,
                   "num_leaves": 15, "max_bin": 63, "learning_rate": 0.1,
                   "min_data_in_leaf": 5},
        "metrics": lambda y, p, q: {"ndcg@5": ndcg_at(y, p, q, 5),
                                    "ndcg@10": ndcg_at(y, p, q, 10)},
    },
}


def run_reference(binary, task, spec, tmp):
    train = os.path.join(GOLDEN, "%s.train" % task)
    test = os.path.join(GOLDEN, "%s.test" % task)
    model = os.path.join(tmp, "%s.ref.model" % task)
    pred = os.path.join(tmp, "%s.ref.pred" % task)
    args = ["task=train", "data=%s" % train, "output_model=%s" % model,
            "verbosity=-1"]
    args += ["%s=%s" % (k, v) for k, v in spec["params"].items()]
    subprocess.run([binary] + args, check=True, cwd=tmp,
                   capture_output=True)
    subprocess.run([binary, "task=predict", "data=%s" % test,
                    "input_model=%s" % model, "output_result=%s" % pred,
                    "verbosity=-1"], check=True, cwd=tmp,
                   capture_output=True)
    return np.loadtxt(pred)


def run_ours(task, spec, tmp, extra=None):
    from lightgbm_tpu import cli
    train = os.path.join(GOLDEN, "%s.train" % task)
    test = os.path.join(GOLDEN, "%s.test" % task)
    model = os.path.join(tmp, "%s.tpu.model" % task)
    pred = os.path.join(tmp, "%s.tpu.pred" % task)
    args = ["task=train", "data=%s" % train, "output_model=%s" % model,
            "verbosity=-1"]
    args += ["%s=%s" % (k, v) for k, v in spec["params"].items()]
    args += ["%s=%s" % (k, v) for k, v in (extra or {}).items()]
    cli.main(args)
    cli.main(["task=predict", "data=%s" % test, "input_model=%s" % model,
              "output_result=%s" % pred, "verbosity=-1"])
    return np.loadtxt(pred)


def main():
    # deterministic, device-independent quality comparison: force the CPU
    # backend before lightgbm_tpu/jax initialize (the env var alone does
    # not override an installed accelerator plugin)
    import jax
    jax.config.update("jax_platforms", "cpu")
    binary = (sys.argv[1] if len(sys.argv) > 1
              else os.environ.get("REF_LGBM", "/tmp/refbuild/lightgbm"))
    if not os.path.exists(binary):
        sys.exit("reference binary not found: %s" % binary)
    rows = []
    table = {}
    with tempfile.TemporaryDirectory() as tmp:
        for task, spec in TASKS.items():
            y, _ = load_tsv(os.path.join(GOLDEN, "%s.test" % task))
            qpath = os.path.join(GOLDEN, "%s.test.query" % task)
            q = load_query(qpath) if os.path.exists(qpath) else None
            ref = run_reference(binary, task, spec, tmp)
            ours = run_ours(task, spec, tmp)
            waved = run_ours(task, spec, tmp,
                             {"tpu_growth": "wave", "tpu_wave_width": 8})
            mref = spec["metrics"](y, ref, q)
            mours = spec["metrics"](y, ours, q)
            mwave = spec["metrics"](y, waved, q)
            table[task] = {"reference": mref, "lightgbm_tpu": mours,
                           "lightgbm_tpu_wave8": mwave}
            for m in mref:
                rows.append((task, m, mref[m], mours[m], mwave[m]))
                print("%-11s %-13s ref=%.6f tpu=%.6f (d=%+.2e) "
                      "wave8=%.6f (d=%+.2e)"
                      % (task, m, mref[m], mours[m], mours[m] - mref[m],
                         mwave[m], mwave[m] - mref[m]))

    with open(os.path.join(REPO, "PARITY_TRAINING.json"), "w") as f:
        json.dump(table, f, indent=2, sort_keys=True)
    with open(os.path.join(REPO, "PARITY_TRAINING.md"), "w") as f:
        f.write(
            "# Training-quality parity vs the reference CLI\n\n"
            "Both frameworks trained on the golden data "
            "(`tests/data/golden/`) with identical configs; test-split\n"
            "predictions scored by the same metric code "
            "(`tools/parity_metrics.py`).  Regenerate with\n"
            "`python tools/gen_parity.py <reference-cli>` "
            "(reference built unmodified from /root/reference).\n"
            "The pattern mirrors docs/GPU-Performance.md:134-145 "
            "(CPU-vs-GPU accuracy table).\n\n"
            "| task | metric | reference | lightgbm_tpu | delta | "
            "wave8 | wave8 delta |\n|---|---|---|---|---|---|---|\n")
        for task, m, r, o, w in rows:
            f.write("| %s | %s | %.6f | %.6f | %+.2e | %.6f | %+.2e |\n"
                    % (task, m, r, o, o - r, w, w - r))
    print("wrote PARITY_TRAINING.{json,md}")


if __name__ == "__main__":
    main()
