"""Incident engine (lightgbm_tpu/obs/incident.py).

Covers the evidence ring slice on a wrapped / concurrently-written /
empty ring, signal classification, debounce-and-group semantics (one
incident per co-occurrence window, quiet-window close, finalize
close), the on-disk evidence bundle and its best-effort error path,
the edge-triggered health warn channel (a repeating guard emits ONE
event until a clean evaluation re-arms it), the armed one-iteration
trace window, the live plane's /incidents listing and loopback-only
POST control endpoints, the `obs incident` reader + --check gate, and
the run_end digest -> ledger cells.
"""
import io
import json
import os
import threading
import time
import urllib.request

from lightgbm_tpu.obs import read_events
from lightgbm_tpu.obs.events import RingBuffer, RunObserver
from lightgbm_tpu.obs.health import HealthMonitors
from lightgbm_tpu.obs.incident import (classify_signal,
                                       evidence_ring_slice,
                                       rank_root_causes,
                                       render_incident_report)
from lightgbm_tpu.obs.ledger import METRIC_DIRECTIONS, metrics_from_events
from lightgbm_tpu.obs.live import watch
from lightgbm_tpu.obs.query import main as query_main


def _obs(tmp_path, **kw):
    kw.setdefault("incident", True)
    kw.setdefault("incident_window_s", 30.0)
    kw.setdefault("incident_dir", str(tmp_path / "bundles"))
    obs = RunObserver(events_path=str(tmp_path / "ev.jsonl"),
                      timing="off", **kw)
    obs.run_header("cpu", [{"id": 0, "kind": "cpu"}],
                   {"num_leaves": 31}, {})
    return obs


def _post(url, timeout=5.0):
    import urllib.error
    req = urllib.request.Request(url, data=b"", method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        body = e.read().decode()
        return e.code, (json.loads(body) if body else {})


def _events(obs):
    return read_events(obs.events_path)


# ---------------------------------------------------------- ring slice

def test_ring_slice_windows_around_seq():
    ring = RingBuffer(capacity=64)
    for i in range(40):
        ring.append({"ev": "iter", "it": i})
    rows = evidence_ring_slice(ring, 30, before=5, after=3)
    assert [r["seq"] for r in rows] == list(range(26, 34))
    assert all(r["ev"] == "iter" for r in rows)


def test_ring_slice_survives_wraparound():
    ring = RingBuffer(capacity=8)
    for i in range(100):                 # seqs 1..100, ring holds 93..100
        ring.append({"ev": "iter", "it": i})
    rows = evidence_ring_slice(ring, 100, before=160, after=64)
    assert [r["seq"] for r in rows] == list(range(93, 101))
    # a slice whose window rolled entirely off the ring is empty, not
    # an error
    assert evidence_ring_slice(ring, 10, before=5, after=5) == []


def test_ring_slice_empty_ring_cold_start():
    assert evidence_ring_slice(RingBuffer(capacity=8), 0) == []
    assert evidence_ring_slice(RingBuffer(capacity=8), 500) == []


def test_ring_slice_with_concurrent_writer():
    ring = RingBuffer(capacity=32)
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            ring.append({"ev": "iter", "it": i})
            i += 1
    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        for _ in range(200):
            rows = evidence_ring_slice(ring, ring.last_seq)
            # never corrupt: every row carries its seq and the record,
            # seqs strictly increasing within one slice
            seqs = [r["seq"] for r in rows]
            assert seqs == sorted(seqs)
            assert all(isinstance(r.get("it"), int) for r in rows)
    finally:
        stop.set()
        t.join(timeout=5)


# ------------------------------------------------------- classification

def test_classify_signal():
    assert classify_signal({"ev": "health", "status": "warn",
                            "check": "nonfinite_gradients"}) \
        == "nonfinite_gradients"
    assert classify_signal({"ev": "health", "status": "fatal",
                            "check": "loss_divergence"}) \
        == "loss_divergence"
    # ok verdicts and the periodic stats record are not anomalies
    assert classify_signal({"ev": "health", "status": "ok",
                            "check": "nonfinite_gradients"}) is None
    assert classify_signal({"ev": "health", "status": "warn",
                            "check": "stats"}) is None
    # steady-state recompile fires, the first compile does not
    assert classify_signal({"ev": "compile_attr",
                            "sig_compiles": 2}) == "recompile"
    assert classify_signal({"ev": "compile_attr",
                            "sig_compiles": 1}) is None
    assert classify_signal({"ev": "drift", "alert": "firing"}) == "drift"
    assert classify_signal({"ev": "drift", "alert": "armed"}) is None
    assert classify_signal({"ev": "iter", "it": 3}) is None


# ----------------------------------------------------- group & debounce

def test_cooccurring_signals_group_into_one_incident(tmp_path):
    obs = _obs(tmp_path)
    try:
        obs.event("health", check="straggler_skew", status="warn",
                  it=1, detail={"skew": 0.8})
        obs.event("health", check="slo_burn_rate", status="warn",
                  it=1, detail={"burn": 9.0})
        obs.incident_signal("shed_storm", {"shed_total": 8})
    finally:
        obs.close()
    evs = _events(obs)
    opens = [e for e in evs if e["ev"] == "incident_open"]
    closes = [e for e in evs if e["ev"] == "incident_close"]
    assert len(opens) == 1 and len(closes) == 1
    # first-occurrence order is preserved in the grouped close
    assert closes[0]["signals"] == ["straggler_skew", "slo_burn_rate",
                                    "shed_storm"]
    assert closes[0]["counts"]["straggler_skew"] == 1
    # incident events sort after their trigger on the timeline
    trigger_i = next(i for i, e in enumerate(evs)
                     if e.get("check") == "straggler_skew")
    open_i = evs.index(opens[0])
    assert open_i > trigger_i
    end = [e for e in evs if e["ev"] == "run_end"][-1]
    assert end["incidents"] == {"opened": 1, "max_signals": 3}


def test_repeated_signal_counts_not_duplicates(tmp_path):
    obs = _obs(tmp_path)
    try:
        for i in range(4):
            obs.incident_signal("shed_storm", {"shed_total": 8 * (i + 1)})
    finally:
        obs.close()
    evs = _events(obs)
    assert len([e for e in evs if e["ev"] == "incident_open"]) == 1
    close = [e for e in evs if e["ev"] == "incident_close"][-1]
    assert close["signals"] == ["shed_storm"]
    assert close["counts"]["shed_storm"] == 4


def test_quiet_window_closes_and_next_signal_reopens(tmp_path):
    obs = _obs(tmp_path, incident_window_s=0.1)
    try:
        obs.incident_signal("shed_storm", {"shed_total": 8})
        time.sleep(0.25)
        # any timeline event ticks the quiet-window close
        obs.event("memory", it=1, devices=[])
        obs.flush()
        evs_mid = _events(obs)
        assert [e["ev"] for e in evs_mid
                if e["ev"].startswith("incident_")][-1] == "incident_close"
        time.sleep(0.25)
        obs.incident_signal("operator", None)
    finally:
        obs.close()
    evs = _events(obs)
    opens = [e for e in evs if e["ev"] == "incident_open"]
    assert len(opens) == 2
    assert opens[0]["id"] != opens[1]["id"]
    end = [e for e in evs if e["ev"] == "run_end"][-1]
    assert end["incidents"]["opened"] == 2


def test_clean_run_digests_zero(tmp_path):
    obs = _obs(tmp_path)
    try:
        obs.iter_begin(0)
        obs.iter_end(0)
    finally:
        obs.close()
    evs = _events(obs)
    assert not [e for e in evs if e["ev"].startswith("incident_")]
    end = [e for e in evs if e["ev"] == "run_end"][-1]
    # zeros are RECORDED (not omitted) so the ledger cell has a real
    # zero history to change-point against
    assert end["incidents"] == {"opened": 0, "max_signals": 0}


def test_incident_signal_none_when_engine_off(tmp_path):
    obs = RunObserver(events_path=str(tmp_path / "ev.jsonl"),
                      timing="off")
    try:
        assert obs.incident_signal("shed_storm") is None
        assert obs.incidents() == {"enabled": False, "open": [],
                                   "closed": []}
    finally:
        obs.close()
    end = [e for e in _events(obs) if e["ev"] == "run_end"][-1]
    assert "incidents" not in end


# ------------------------------------------------------ evidence bundle

def test_evidence_bundle_on_disk(tmp_path):
    obs = _obs(tmp_path)
    try:
        obs.iter_begin(0)
        obs.iter_end(0)
        obs.stamp_context(stage="boost", it=0)
        obs.event("health", check="nonfinite_gradients", status="warn",
                  it=0, detail={"grad_abs_mean": "nan"})
    finally:
        obs.close()
    evs = _events(obs)
    open_rec = [e for e in evs if e["ev"] == "incident_open"][0]
    inc_dir = open_rec["dir"]
    assert os.path.isdir(inc_dir)
    arts = {e["artifact"]: e for e in evs
            if e["ev"] == "incident_evidence"}
    for need in ("ring", "metrics", "flight_context", "statusz",
                 "threads", "ring_post"):
        assert need in arts, arts
        assert "error" not in arts[need]
        assert os.path.isfile(arts[need]["path"])
        assert arts[need]["bytes"] > 0
    # the ring slice holds the lead-up, the meta carries the rollup
    with open(os.path.join(inc_dir, "ring.jsonl")) as f:
        ring_rows = [json.loads(ln) for ln in f]
    assert any(r.get("ev") == "iter" for r in ring_rows)
    with open(os.path.join(inc_dir, "incident.json")) as f:
        meta = json.load(f)
    assert meta["status"] == "closed"
    assert meta["signals"] == ["nonfinite_gradients"]
    assert {a["artifact"] for a in meta["artifacts"]} >= {"ring",
                                                          "metrics"}
    # the statusz snapshot carries the stamped iteration context
    with open(os.path.join(inc_dir, "statusz.json")) as f:
        snap = json.load(f)
    assert snap.get("context", {}).get("stage") == "boost"


def test_evidence_capture_is_best_effort(tmp_path):
    # an unwritable bundle dir must degrade to error-stamped evidence
    # events, never an exception into the run
    blocker = tmp_path / "bundles"
    blocker.write_text("a file where the dir should go")
    obs = _obs(tmp_path, incident_dir=str(blocker))
    try:
        obs.incident_signal("operator", None)
    finally:
        obs.close()
    evs = _events(obs)
    assert len([e for e in evs if e["ev"] == "incident_open"]) == 1
    errs = [e for e in evs if e["ev"] == "incident_evidence"
            and e.get("error")]
    assert errs, "failed captures must surface as error-stamped events"
    assert [e for e in evs if e["ev"] == "incident_close"]


# ------------------------------------------- health warn edge-triggering

def test_repeating_warn_dedups_to_one_event(tmp_path):
    obs = RunObserver(events_path=str(tmp_path / "ev.jsonl"),
                      timing="off")
    hm = HealthMonitors(mode="warn")
    problem = [("nonfinite_gradients", {"grad_abs_mean": "nan"})]
    try:
        # the guard fires every iteration while gradients stay bad —
        # only the TRANSITION reaches the timeline
        for it in range(5):
            hm._resolve(obs, it, problem,
                        evaluated=("nonfinite_gradients",))
        # a clean evaluation re-arms the check ...
        hm._resolve(obs, 5, [], evaluated=("nonfinite_gradients",))
        # ... so the next firing is a new transition
        hm._resolve(obs, 6, problem,
                    evaluated=("nonfinite_gradients",))
    finally:
        obs.close()
    health = [e for e in _events(obs) if e["ev"] == "health"
              and e.get("check") == "nonfinite_gradients"]
    assert len(health) == 2
    assert [e["it"] for e in health] == [0, 6]


def test_unevaluated_checks_stay_latched(tmp_path):
    obs = RunObserver(events_path=str(tmp_path / "ev.jsonl"),
                      timing="off")
    hm = HealthMonitors(mode="warn")
    try:
        hm._resolve(obs, 0, [("memory_watermark", {"frac": 0.95})],
                    evaluated=("memory_watermark",))
        # an evaluation of OTHER checks must not re-arm this one
        hm._resolve(obs, 1, [], evaluated=("nonfinite_gradients",))
        hm._resolve(obs, 2, [("memory_watermark", {"frac": 0.96})],
                    evaluated=("memory_watermark",))
    finally:
        obs.close()
    mem = [e for e in _events(obs) if e["ev"] == "health"
           and e.get("check") == "memory_watermark"]
    assert len(mem) == 1


# -------------------------------------------------- armed trace window

def test_incident_trace_arms_one_iteration(tmp_path, monkeypatch):
    calls = []
    from lightgbm_tpu.obs import profile
    monkeypatch.setattr(profile, "_start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(profile, "_stop_trace",
                        lambda: calls.append(("stop",)))
    obs = _obs(tmp_path, incident_trace=True)
    try:
        obs.iter_begin(0)
        obs.event("health", check="nonfinite_gradients", status="warn",
                  it=0, detail={})
        # armed at open, NOT started mid-iteration
        assert calls == []
        obs.iter_end(0)
        obs.iter_begin(1)               # window opens here ...
        assert calls and calls[0][0] == "start"
        obs.iter_end(1)                 # ... and closes here
        assert calls[-1] == ("stop",)
    finally:
        obs.close()
    assert len(calls) == 2, "trace must scope exactly one iteration"
    evs = _events(obs)
    tw = [e for e in evs if e["ev"] == "trace_window"]
    assert [e["action"] for e in tw] == ["start", "stop"]
    assert any(e["ev"] == "incident_evidence"
               and e.get("artifact") == "trace" for e in evs)


def test_no_trace_outside_training(tmp_path, monkeypatch):
    calls = []
    from lightgbm_tpu.obs import profile
    monkeypatch.setattr(profile, "_start_trace",
                        lambda d: calls.append(d))
    obs = _obs(tmp_path, incident_trace=True)
    try:
        obs._lifecycle = "serve"
        obs.incident_signal("shed_storm", {"shed_total": 8})
    finally:
        obs.close()
    assert calls == [], "serve-path incidents must never arm a trace"


# ------------------------------------------------------------ live plane

def test_incidents_endpoint_and_post_control(tmp_path):
    obs = _obs(tmp_path, http_port=0)
    try:
        url = obs.live_url
        assert url.startswith("http://127.0.0.1:")
        with urllib.request.urlopen(url + "/incidents", timeout=5) as r:
            listing = json.loads(r.read().decode())
        assert listing == {"enabled": True, "opened": 0, "open": [],
                           "closed": []}
        code, body = _post(url + "/trigger/incident")
        assert code == 200 and body["triggered"] == "incident"
        iid = body["id"]
        with urllib.request.urlopen(url + "/incidents", timeout=5) as r:
            listing = json.loads(r.read().decode())
        assert listing["open"] and listing["open"][0]["id"] == iid
        assert listing["open"][0]["signals"] == ["operator"]
        code, body = _post(url + "/trigger/flight")
        assert code == 200 and os.path.isfile(body["path"])
        code, _ = _post(url + "/trigger/nope")
        assert code == 404
        # the open incident rides /statusz via the flight provider
        with urllib.request.urlopen(url + "/statusz", timeout=5) as r:
            sz = json.loads(r.read().decode())
        assert sz["flight"]["incidents"]["open"] == 1
        assert sz["flight"]["incidents"]["last"]["id"] == iid
    finally:
        obs.close()


def test_post_trigger_incident_409_when_engine_off(tmp_path):
    obs = RunObserver(events_path=str(tmp_path / "ev.jsonl"),
                      timing="off", http_port=0)
    obs.run_header("cpu", [{"id": 0, "kind": "cpu"}], {}, {})
    try:
        code, body = _post(obs.live_url + "/trigger/incident")
        assert code == 409
        with urllib.request.urlopen(obs.live_url + "/incidents",
                                    timeout=5) as r:
            assert json.loads(r.read().decode())["enabled"] is False
    finally:
        obs.close()


# ------------------------------------------------------------- reader

def _fault_timeline(tmp_path):
    obs = _obs(tmp_path)
    try:
        obs.iter_begin(0)
        obs.iter_end(0)
        obs.event("health", check="straggler_skew", status="warn",
                  it=0, detail={"skew": 0.9})
        obs.event("health", check="slo_burn_rate", status="warn",
                  it=0, detail={"burn": 9.0})
    finally:
        obs.close()
    return obs


def test_render_report_from_timeline_and_bundle(tmp_path, capsys):
    obs = _fault_timeline(tmp_path)
    out = io.StringIO()
    n = render_incident_report(obs.events_path, out=out)
    text = out.getvalue()
    assert n == 1
    assert "straggler_skew" in text and "slo_burn_rate" in text
    assert "root-cause ranking" in text
    assert "straggler-induced latency" in text.splitlines()[
        next(i for i, ln in enumerate(text.splitlines())
             if "root-cause ranking" in ln) + 1]
    # first-occurrence ordering in the correlation table
    assert text.index("straggler_skew") < text.index("slo_burn_rate")
    # same report from the bundle directory (parent of all incidents)
    out2 = io.StringIO()
    n2 = render_incident_report(str(tmp_path / "bundles"), out=out2)
    assert n2 == 1
    assert "evidence" in out2.getvalue()
    # the CLI gate: fault exits 1 under --check, 0 without
    assert query_main(["incident", obs.events_path, "--check"]) == 1
    assert query_main(["incident", obs.events_path]) == 0
    capsys.readouterr()


def test_check_gate_clean_and_error(tmp_path, capsys):
    obs = _obs(tmp_path)
    obs.close()
    assert query_main(["incident", obs.events_path, "--check"]) == 0
    assert query_main(["incident",
                       str(tmp_path / "missing.jsonl"), "--check"]) == 2
    capsys.readouterr()


def test_root_cause_ranking_deterministic():
    ranked = rank_root_causes(["straggler_skew", "slo_burn_rate"],
                              {"straggler_skew": 2, "slo_burn_rate": 3})
    # the 2-kind match outranks every 1-kind match
    assert ranked[0][0].startswith("straggler-induced latency")
    assert ranked[0][1] == ["slo_burn_rate", "straggler_skew"]
    assert ranked == rank_root_causes(
        ["slo_burn_rate", "straggler_skew"],
        {"straggler_skew": 2, "slo_burn_rate": 3})
    # unknown signal sets fall back, never raise
    fallback = rank_root_causes(["mystery_check"], {})
    assert len(fallback) == 1 and "no heuristic" in fallback[0][0]


def test_watch_renders_incident_lines(tmp_path):
    obs = _fault_timeline(tmp_path)
    out = io.StringIO()
    assert watch(obs.events_path, once=True, out=out) == 0
    text = out.getvalue()
    assert "INCIDENT OPEN" in text
    assert "INCIDENT CLOSE" in text


# ------------------------------------------------------------- ledger

def test_ledger_cells_from_run_end_digest(tmp_path):
    obs = _fault_timeline(tmp_path)
    m = metrics_from_events(_events(obs))
    assert m["incidents_opened"] == 1
    assert m["incident_max_signals"] == 2
    assert METRIC_DIRECTIONS["incidents_opened"] == -1
    assert METRIC_DIRECTIONS["incident_max_signals"] == -1


def test_ledger_cells_fallback_without_digest():
    evs = [{"ev": "incident_open", "id": "r-001", "t": 1.0},
           {"ev": "incident_close", "id": "r-001", "t": 2.0,
            "signals": ["shed_storm", "slo_burn_rate"]},
           {"ev": "run_end", "iters": 0, "t": 3.0}]
    m = metrics_from_events(evs)
    assert m["incidents_opened"] == 1
    assert m["incident_max_signals"] == 2
