"""Device-resident leaf-wise tree growth — ONE dispatch per tree.

The reference drives the leaf loop from the host (SerialTreeLearner::Train,
serial_tree_learner.cpp:168-223), which is fine at C++ latencies but fatal
when the accelerator sits behind a link with ~100ms round-trips.  Here the
entire grow loop is a `lax.while_loop` inside one jitted program:

  carry: (step, done, leaf_id, leaf-ordered row permutation + segment
          table (order/lstart/lcount, used by the ordered schedule), per-leaf
          histogram cache (absent when histogram_pool_size disables it),
          per-leaf packed best splits, per-leaf sums, flat tree arrays)
  body:  pick best leaf (argmax over packed gains) -> apply split to the
         row->leaf map (masked full-N update, or an in-segment partition of
         the permutation once the ordered schedule engages) -> smaller child
         histogram by masked scan or segment gather, larger by
         parent-subtraction (feature_histogram.hpp:63-69) when the cache is
         on, else rescanned -> best-split scan for both children.

Tree arrays come back as a device pytree; the host materializes a
models.Tree from them once per tree (real-valued thresholds resolved on host
in float64 from the BinMappers).  Under a data-parallel mesh the same
program shard_maps with a psum around the histogram — the reference's
ReduceScatter path (data_parallel_tree_learner.cpp:148-222).
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .histogram import (compact_rows, compact_rows_topk, gathered_histogram,
                        leaf_histogram_onehot, leaf_histogram_scatter)
from .split_finder import (DEFAULT_BIN_FOR_ZERO, FEATURE, GAIN, IS_CAT,
                           LEFT_COUNT, LEFT_OUTPUT, LEFT_SUM_G, LEFT_SUM_H,
                           RIGHT_COUNT, RIGHT_OUTPUT, RIGHT_SUM_G, RIGHT_SUM_H,
                           SECOND_FEATURE, SECOND_GAIN, SPLIT_VEC_SIZE,
                           THRESHOLD, FeatureMeta, SplitParams,
                           depth_gated_best, find_best_split_impl,
                           per_feature_candidates)


class BundleArrays(NamedTuple):
    """Device-side EFB layout (io/bundle.py BundleLayout uploaded).

    The learner's histograms are built over GROUP columns (G, Bg, 3); the
    split scan runs on per-FEATURE views gathered via `gather_idx` with the
    default bin reconstructed by subtraction — the FixHistogram trick
    (dataset.cpp:764-783) vectorized over all features at once.
    """
    group_of: jnp.ndarray        # (F,) i32 feature -> group column
    bin_off: jnp.ndarray         # (F,) i32
    bin_adj: jnp.ndarray         # (F,) i32
    bin_span: jnp.ndarray        # (F,) i32
    gather_idx: jnp.ndarray      # (F, B) i32 into flattened (G*Bg)
    valid_mask: jnp.ndarray      # (F, B) bool — non-default, in-range bins


class TreeArrays(NamedTuple):
    """Flat SoA tree mirroring tree.h:195-229, device-resident."""
    num_leaves: jnp.ndarray          # scalar i32
    split_feature: jnp.ndarray       # (L-1,) i32 inner feature index
    threshold_bin: jnp.ndarray       # (L-1,) i32
    default_bin_for_zero: jnp.ndarray  # (L-1,) i32
    default_bin: jnp.ndarray         # (L-1,) i32 (feature's zero bin)
    is_cat: jnp.ndarray              # (L-1,) i32
    left_child: jnp.ndarray          # (L-1,) i32 (~leaf for leaves)
    right_child: jnp.ndarray         # (L-1,) i32
    split_gain: jnp.ndarray          # (L-1,) f
    internal_value: jnp.ndarray      # (L-1,) f
    internal_count: jnp.ndarray      # (L-1,) i32
    leaf_parent: jnp.ndarray         # (L,) i32
    leaf_value: jnp.ndarray          # (L,) f  (unshrunk outputs)
    leaf_count: jnp.ndarray          # (L,) i32
    leaf_depth: jnp.ndarray          # (L,) i32
    # split-audit trail: the runner-up feature each split beat and its
    # gain (-1 / 0 when the winner was the only valid candidate)
    second_feature: jnp.ndarray      # (L-1,) i32
    second_gain: jnp.ndarray         # (L-1,) f


def feature_hist_view(ghist, sums, meta, bundle, has_bundle: bool,
                      fix_default: bool = False):
    """Group histograms -> per-feature (F, B, 3) views with the default
    bin rebuilt by subtraction (FixHistogram, dataset.cpp:764-783).
    Shared by the exact (grow) and wave growth engines.

    fix_default: reconstruct the default-bin slot even without a bundle —
    the sparse store (ops/sparse_store.py) never materializes fill-bin
    entries, so their slots arrive zero and carry the remainder."""
    if not has_bundle:
        if fix_default:
            fidx = jnp.arange(ghist.shape[0])
            return ghist.at[fidx, meta.default_bin].set(
                sums[None, :] - ghist.sum(axis=1))
        return ghist
    flat = ghist.reshape(-1, 3)
    v = flat[bundle.gather_idx] * bundle.valid_mask[..., None].astype(
        ghist.dtype)
    fidx = jnp.arange(v.shape[0])
    v = v.at[fidx, meta.default_bin].set(sums[None, :] - v.sum(axis=1))
    return v


def pvary_for(x, axis: str):
    """Mark x shard-varying over `axis` under shard_map (VMA rules),
    across jax versions (pcast is the newer spelling of pvary).  jax
    lines old enough to have neither primitive predate the VMA checker
    entirely, so the cast is a no-op there."""
    try:
        return lax.pcast(x, (axis,), to="varying")
    except (AttributeError, TypeError):
        pass
    try:
        return lax.pvary(x, (axis,))
    except AttributeError:
        return x


def default_row_capacities(n: int, min_capacity: int = 2048,
                           max_tiers: int = 10):
    """Descending static row-gather capacities n, n/2, n/4, ... — the tier
    ladder for compacted leaf histograms.  The top tier is full-N (under a
    data mesh a shard can hold ALL its local rows of the globally-smaller
    child), lower tiers bound wasted work to <2x the leaf's true row count
    until the ladder bottoms out."""
    caps = []
    c = int(n)
    while len(caps) < max_tiers:
        caps.append(c)
        if c <= min_capacity or c <= 1:
            break
        c = (c + 1) // 2
    return tuple(caps)


def make_grow_fn(num_leaves: int, num_bins: int, meta: FeatureMeta,
                 params: SplitParams, max_depth: int,
                 hist_mode: str = "scatter", hist_dtype=jnp.float32,
                 psum_axis: str = None, feature_axis: str = None,
                 voting_k: int = 0, num_voting_machines: int = 1,
                 bundle: BundleArrays = None, group_bins: int = 0,
                 row_capacities: tuple = (), cache_hists: bool = True,
                 seg_after: int = 15, packed_cols: int = 0,
                 sparse_col_cap: int = 0):
    """Bind `meta`/`bundle` onto the shared memoized grow program.

    The heavy lifting lives in `make_grow_core`, which is cached on the
    STATIC configuration only — two boosters (e.g. cv() folds) with the
    same shapes share one compiled XLA program instead of paying a fresh
    ~30s trace+compile each (meta/bundle arrays are call-time arguments
    of the cached function, not closure constants).
    """
    core = make_grow_core(num_leaves, num_bins, params, max_depth,
                          hist_mode, hist_dtype, psum_axis, feature_axis,
                          voting_k, num_voting_machines,
                          bundle is not None, group_bins,
                          row_capacities, cache_hists, seg_after,
                          packed_cols, sparse_col_cap)

    def grow(X, grad, hess, row_mult, feature_mask):
        return core(X, grad, hess, row_mult, feature_mask, meta, bundle)

    grow.core = core
    return grow


@functools.lru_cache(maxsize=64)
def make_grow_jit(*static_args):
    """jit(make_grow_core(...)) cached on the same static key, so repeated
    boosters/folds reuse one compiled executable, not just one traceable."""
    return jax.jit(make_grow_core(*static_args))


@functools.lru_cache(maxsize=64)
def make_grow_core(num_leaves: int, num_bins: int,
                   params: SplitParams, max_depth: int,
                   hist_mode: str = "scatter", hist_dtype=jnp.float32,
                   psum_axis: str = None, feature_axis: str = None,
                   voting_k: int = 0, num_voting_machines: int = 1,
                   has_bundle: bool = False, group_bins: int = 0,
                   row_capacities: tuple = (), cache_hists: bool = True,
                   seg_after: int = 15, packed_cols: int = 0,
                   sparse_col_cap: int = 0):
    """Build the jitted grow(X, grad, hess, row_mult, feature_mask) program.

    psum_axis: when set, histograms and scalar sums are psum'd over that
    mesh axis (data-parallel training under shard_map).

    feature_axis: when set, X arrives feature-sharded ((N, F_local) per
    shard, rows replicated) and only the packed best-split vector crosses
    devices — an all_gather + strict-> fold reproducing the reference's
    SplitInfo MaxReduce with its smaller-feature tie-break
    (feature_parallel_tree_learner.cpp:52-76, split_info.hpp:102-107).
    `meta`/`feature_mask` stay full-width; each shard slices its block.

    voting_k > 0 (with psum_axis): voting-parallel — per leaf, each shard
    proposes its local top-k features by leaf-size-weighted gain, the global
    top-k of the pmax'd weighted gains are selected, and ONLY those k
    histograms are psum'd (voting_parallel_tree_learner.cpp:164-300).
    Cross-device traffic per leaf drops from F*B*3 to k*B*3.
    num_voting_machines divides the local min_data/min_hessian constraints
    as the reference does (voting_parallel_tree_learner.cpp:54-56).
    """
    L = num_leaves
    voting = voting_k > 0 and psum_axis is not None
    if has_bundle and feature_axis is not None:
        raise ValueError("EFB bundling is not supported with the "
                         "feature-parallel learner (set enable_bundle=false)")
    sparse_mode = hist_mode == "sparse"
    if sparse_mode and (feature_axis is not None or voting_k > 0
                        or packed_cols):
        raise ValueError("tpu_sparse supports the serial/data-parallel "
                         "exact engine only (no feature-parallel, voting, "
                         "or 4-bit packing)")
    hist_bins = group_bins if has_bundle else num_bins
    # Pallas kernels take the full-N mask form; gathering only applies to
    # the onehot/scatter kernels.  The sparse store has no row-gatherable
    # dense matrix at all.
    use_gather = (len(row_capacities) > 0
                  and hist_mode not in ("pallas", "sparse"))
    # Ordered-partition mode: the carry holds a leaf-grouped row permutation
    # (DataPartition's indices_/leaf_begin_/leaf_count_, data_partition.hpp:
    # 94-147).  Each split touches ONLY the parent's segment — partition is
    # O(rows_in_parent) and the smaller-child histogram O(rows_in_child * F)
    # like the reference's ordered iteration (serial_tree_learner.cpp:424-450,
    # dense_bin.hpp:66-98) — instead of O(N) per split.  Static shapes via
    # the capacity-tier ladder.
    #
    # TPU economics force a two-phase schedule: random scatter/gather runs
    # ~125M elem/s on v5e while the masked one-hot pass streams all N rows
    # in ~2.4ms/1M — so for the first SEG_AFTER splits (big leaves) the
    # masked full-N path is cheaper, and ONE stable sort of leaf_id at the
    # transition builds the permutation that every later (small) split
    # partitions in-segment.  The sort amortizes over the L-1-SEG_AFTER
    # deep splits that dominate a 255-leaf tree.
    #
    # Disabled under the feature-parallel learner (its go-left bitmask psum
    # would sit inside a tier switch, which collectives cannot: branches
    # must agree across shards); FP keeps the compact-per-split gather.
    SEG_AFTER = seg_after
    # measured on v5e (1M x 28 x 63 bins): segment splits cost ~1.5-1.8ms in
    # gather/scatter versus ~2.3ms for a full masked pass, so the ordered
    # schedule only wins when deep cheap splits dominate (large trees);
    # below the crossover the pure masked streaming path is faster
    ordered = (use_gather and feature_axis is None
               and num_leaves - 1 > 128)
    # TPU: sort-based compaction (scatter ~8ms + cumsum ~2.4ms vs top_k
    # ~3.4ms at 1M rows, measured); CPU: cumsum+scatter is cheaper.
    compact_mode = "topk" if jax.default_backend() == "tpu" else "scatter"

    def seg_tier(count):
        """Index of the smallest capacity tier holding `count` rows."""
        capv = jnp.asarray(row_capacities, jnp.int32)      # descending
        return jnp.clip(jnp.sum((capv >= count).astype(jnp.int32)) - 1, 0,
                        len(row_capacities) - 1)

    def seg_block(order, start, count, cap: int):
        """A (cap,) window of `order` covering segment [start, start+count).

        The slice start is clamped so the window stays in bounds without
        padding; `valid` marks the segment's positions inside the window.
        off + count <= cap always holds because start + count <= n.
        """
        n = order.shape[0]
        s = jnp.clip(start, 0, max(n - cap, 0))
        off = start - s
        blk = lax.dynamic_slice(order, (s,), (cap,))
        pos = jnp.arange(cap, dtype=jnp.int32)
        valid = (pos >= off) & (pos < off + count)
        return s, off, blk, valid

    def seg_hist(X, g, h, row_mult, order, start, count):
        """(F, B, 3) histogram of the rows in segment [start, start+count)
        of `order` — this shard's part, no collectives (tier switches may
        diverge across shards; callers psum outside)."""
        def branch(cap):
            def run(_):
                _, _, blk, valid = seg_block(order, start, count, cap)
                return gathered_histogram(X, g, h, row_mult, blk, valid,
                                          hist_bins, hist_mode,
                                          logical_cols=packed_cols)
            return run
        return lax.switch(seg_tier(count),
                          [branch(c) for c in row_capacities], None)

    if packed_cols and hist_mode == "pallas":
        raise ValueError("4-bit packing is not supported by the pallas "
                         "exact-growth kernel (use onehot/scatter)")
    if sparse_mode:
        from .sparse_store import leaf_histogram_sparse

        def hist_fn(X, g, h, leaf_id, leaf, row_mult):
            return leaf_histogram_sparse(X, g, h, leaf_id, leaf, row_mult,
                                         hist_bins, X.fill.shape[0])
    elif hist_mode == "onehot":
        hist_fn = functools.partial(leaf_histogram_onehot,
                                    num_bins=hist_bins,
                                    logical_cols=packed_cols)
    elif hist_mode == "pallas":
        from .pallas_hist import leaf_histogram_pallas
        hist_fn = functools.partial(leaf_histogram_pallas, num_bins=hist_bins)
    elif hist_mode == "scatter":
        hist_fn = functools.partial(leaf_histogram_scatter,
                                    num_bins=hist_bins,
                                    logical_cols=packed_cols)
    else:
        from ..utils.log import Log
        Log.fatal("Unknown tpu_histogram_mode %s "
                  "(expected auto/scatter/onehot/pallas)", hist_mode)

    def to_feature_hist(ghist, sums, meta, bundle):
        return feature_hist_view(ghist, sums, meta, bundle, has_bundle,
                                 fix_default=sparse_mode)

    def maybe_psum(x):
        if psum_axis is not None:
            return lax.psum(x, psum_axis)
        return x

    # compact-per-split gathers only pay where the masked pass is repeated
    # per shard over replicated rows (the feature-parallel learner, which
    # cannot run ordered mode); serial/data-parallel non-ordered growth
    # keeps the cheaper masked streaming pass (measured: top_k compaction
    # ~3.4ms vs masked one-hot ~2.4ms at 1M x 28 x 63 on v5e)
    compact_gather = use_gather and not ordered and feature_axis is not None

    def local_hist(X, g, h, leaf_id, leaf, row_mult):
        """This shard's histogram of `leaf` — compact-gathered under the
        feature-parallel learner (O(rows_in_leaf) like dense_bin.hpp:66-98),
        else the full-N masked scan.  Ordered mode handles small leaves via
        segments, so its remaining callers (root + big-leaf phase) always
        take the masked streaming pass."""
        if not compact_gather:
            return hist_fn(X, g, h, leaf_id, leaf, row_mult)
        mask = leaf_id == leaf
        count = jnp.sum(mask.astype(jnp.int32))
        if compact_mode == "scatter":
            pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
        tier = seg_tier(count)

        def tier_branch(c):
            def run(_):
                if compact_mode == "scatter":
                    idx = compact_rows(mask, pos, c)
                else:
                    idx = compact_rows_topk(mask, c)
                valid = jnp.arange(c, dtype=jnp.int32) < count
                return gathered_histogram(X, g, h, row_mult, idx, valid,
                                          hist_bins, hist_mode,
                                          logical_cols=packed_cols)
            return run

        return lax.switch(tier, [tier_branch(c) for c in row_capacities],
                          None)

    def hist_of_leaf(X, g, h, leaf_id, leaf, row_mult):
        h_local = local_hist(X, g, h, leaf_id, leaf, row_mult)
        if voting:
            return h_local          # voting: keep local, psum only top-k
        return maybe_psum(h_local)

    if voting:
        local_params = params._replace(
            min_data_in_leaf=params.min_data_in_leaf / num_voting_machines,
            min_sum_hessian_in_leaf=(params.min_sum_hessian_in_leaf
                                     / num_voting_machines))

    def depth_gate(b, depth):
        if max_depth > 0:
            b = b.at[GAIN].set(jnp.where(depth < max_depth, b[GAIN], -jnp.inf))
        return b

    def best_of_serial(hist, sums, feature_mask, depth, meta, bundle):
        return depth_gated_best(to_feature_hist(hist, sums, meta, bundle),
                                sums, meta, feature_mask, params, max_depth,
                                depth)

    def best_of_feature_parallel(hist, sums, feature_mask, depth,
                                 local_meta, offset):
        F_local = hist.shape[0]
        local_mask = lax.dynamic_slice_in_dim(feature_mask, offset, F_local)
        b = find_best_split_impl(hist, sums[0], sums[1], sums[2], local_meta,
                                 local_mask, params)
        b = b.at[FEATURE].add(offset.astype(b.dtype))
        sf = b[SECOND_FEATURE]
        b = b.at[SECOND_FEATURE].set(
            jnp.where(sf >= 0, sf + offset.astype(b.dtype), sf))
        gathered = lax.all_gather(b, feature_axis)      # (n_shards, V)
        # strict-> fold keeps the earlier shard on ties; shards hold
        # contiguous feature blocks, so this IS the smaller-global-feature
        # tie-break of SplitInfo::MaxReducer (split_info.hpp:60-76,102-107)
        best = gathered[0]
        for i in range(1, gathered.shape[0]):
            take = gathered[i][GAIN] > best[GAIN]
            win = jnp.where(take, gathered[i], best)
            lose = jnp.where(take, best, gathered[i])
            # merged runner-up: the loser's winning candidate competes
            # with the winner's own runner-up (both are valid non-winners)
            loser_valid = jnp.isfinite(lose[GAIN]) & (lose[GAIN] > 0.0)
            use_loser = loser_valid & (lose[GAIN] > win[SECOND_GAIN])
            win = win.at[SECOND_GAIN].set(
                jnp.where(use_loser, lose[GAIN], win[SECOND_GAIN]))
            win = win.at[SECOND_FEATURE].set(
                jnp.where(use_loser, lose[FEATURE], win[SECOND_FEATURE]))
            best = win
        return depth_gate(best, depth)

    def best_of_voting(ghist_local, sums, feature_mask, depth, meta,
                       bundle):
        # local candidates against LOCAL leaf sums with constraints divided
        # by num_machines (voting_parallel_tree_learner.cpp:54-56)
        local_sums = jnp.sum(ghist_local[0], axis=0)    # (3,) of this shard
        hist_local = to_feature_hist(ghist_local, local_sums, meta, bundle)
        F = hist_local.shape[0]
        k = min(voting_k, F)
        cand, _, _, _, local_shift = per_feature_candidates(
            hist_local, local_sums[0], local_sums[1], local_sums[2], meta,
            local_params)
        # vote on the improvement (gain minus this shard's gain_shift), the
        # quantity the reference's SplitInfo.gain carries into GlobalVoting —
        # raw gains would bias the vote toward shards with skewed parent sums
        gains = jnp.where(feature_mask, cand.gain - local_shift, -jnp.inf)
        # weight by local leaf size vs global mean (GlobalVoting,
        # voting_parallel_tree_learner.cpp:164-193)
        mean_cnt = jnp.maximum(sums[2] / num_voting_machines, 1.0)
        weighted = gains * (local_sums[2] / mean_cnt)
        weighted = jnp.where(jnp.isfinite(gains), weighted, -jnp.inf)
        # keep only this shard's top-k proposals
        kth = lax.top_k(weighted, k)[0][-1]
        proposal = jnp.where(weighted >= kth, weighted, -jnp.inf)
        global_gain = lax.pmax(proposal, psum_axis)     # (F,)
        sel = lax.top_k(global_gain, k)[1]              # global top-k features
        # ONLY the selected histograms cross the wire
        hist_sel = lax.psum(jnp.take(hist_local, sel, axis=0), psum_axis)
        sub_meta = FeatureMeta(num_bin=meta.num_bin[sel],
                               default_bin=meta.default_bin[sel],
                               is_categorical=meta.is_categorical[sel])
        b = find_best_split_impl(hist_sel, sums[0], sums[1], sums[2],
                                 sub_meta, feature_mask[sel], params)
        f_local = b[FEATURE].astype(jnp.int32)
        b = b.at[FEATURE].set(sel[f_local].astype(b.dtype))
        sf_local = b[SECOND_FEATURE].astype(jnp.int32)
        b = b.at[SECOND_FEATURE].set(
            jnp.where(sf_local >= 0,
                      sel[jnp.clip(sf_local, 0, k - 1)].astype(b.dtype),
                      b[SECOND_FEATURE]))
        return depth_gate(b, depth)

    def grow(X, grad, hess, row_mult, feature_mask, meta, bundle):
        n = grad.shape[0]       # X may be a SparseDeviceStore pytree
        grad = grad.astype(hist_dtype)
        hess = hess.astype(hist_dtype)
        row_mult = row_mult.astype(hist_dtype)
        leaf_id = jnp.zeros(n, dtype=jnp.int32)
        # ordered mode: leaf-grouped row permutation + per-leaf segment
        # table (DataPartition's indices_/leaf_begin_/leaf_count_);
        # size-0 placeholders otherwise so non-ordered growers don't carry
        # dead O(N) loop state
        if ordered:
            order = jnp.arange(n, dtype=jnp.int32)
            lstart = jnp.zeros(L, dtype=jnp.int32)
            lcount = jnp.zeros(L, dtype=jnp.int32).at[0].set(n)
        else:
            order = jnp.zeros(0, jnp.int32)
            lstart = jnp.zeros(0, jnp.int32)
            lcount = jnp.zeros(0, jnp.int32)
        if psum_axis is not None:
            # under shard_map the row->leaf map is shard-varying from the
            # first split on; mark the initial carry accordingly (VMA rules)
            leaf_id = pvary_for(leaf_id, psum_axis)
            order = pvary_for(order, psum_axis)
            lstart = pvary_for(lstart, psum_axis)
            lcount = pvary_for(lcount, psum_axis)

        if feature_axis is not None:
            F_local = X.shape[1]
            offset = lax.axis_index(feature_axis) * F_local
            local_meta = FeatureMeta(
                num_bin=lax.dynamic_slice_in_dim(
                    meta.num_bin, offset, F_local),
                default_bin=lax.dynamic_slice_in_dim(
                    meta.default_bin, offset, F_local),
                is_categorical=lax.dynamic_slice_in_dim(
                    meta.is_categorical, offset, F_local))

            def best_of(h, s, m, d):
                return best_of_feature_parallel(h, s, m, d, local_meta, offset)
        elif voting:
            def best_of(h, s, m, d):
                return best_of_voting(h, s, m, d, meta, bundle)
        else:
            def best_of(h, s, m, d):
                return best_of_serial(h, s, m, d, meta, bundle)

        root_sums = maybe_psum(jnp.stack([
            jnp.sum(grad * row_mult), jnp.sum(hess * row_mult),
            jnp.sum(row_mult)]))
        hist0 = hist_of_leaf(X, grad, hess, leaf_id, 0, row_mult)

        F = hist0.shape[0]
        B = hist0.shape[1]
        if cache_hists:
            hists = jnp.zeros((L, F, B, 3), dtype=hist_dtype).at[0].set(hist0)
        else:
            # HistogramPool disabled (histogram_pool_size budget exceeded):
            # no per-leaf cache, larger children are re-scanned instead of
            # obtained by parent subtraction — memory O(F*B*3) instead of
            # O(L*F*B*3), the recompute arm of feature_histogram.hpp:398-565.
            hists = jnp.zeros((0,), dtype=hist_dtype)
        bests = jnp.full((L, SPLIT_VEC_SIZE), -jnp.inf, dtype=hist_dtype)
        bests = bests.at[0].set(best_of(hist0, root_sums, feature_mask, 0))
        sums = jnp.zeros((L, 3), dtype=hist_dtype).at[0].set(root_sums)

        tree = TreeArrays(
            num_leaves=jnp.asarray(1, jnp.int32),
            split_feature=jnp.zeros(L - 1, jnp.int32),
            threshold_bin=jnp.zeros(L - 1, jnp.int32),
            default_bin_for_zero=jnp.zeros(L - 1, jnp.int32),
            default_bin=jnp.zeros(L - 1, jnp.int32),
            is_cat=jnp.zeros(L - 1, jnp.int32),
            left_child=jnp.zeros(L - 1, jnp.int32),
            right_child=jnp.zeros(L - 1, jnp.int32),
            split_gain=jnp.zeros(L - 1, hist_dtype),
            internal_value=jnp.zeros(L - 1, hist_dtype),
            internal_count=jnp.zeros(L - 1, jnp.int32),
            leaf_parent=jnp.full(L, -1, jnp.int32),
            leaf_value=jnp.zeros(L, hist_dtype),
            leaf_count=jnp.zeros(L, jnp.int32).at[0].set(
                root_sums[2].astype(jnp.int32)),
            leaf_depth=jnp.zeros(L, jnp.int32),
            second_feature=jnp.full(L - 1, -1, jnp.int32),
            second_gain=jnp.zeros(L - 1, hist_dtype),
        )

        def cond(carry):
            step, done = carry[0], carry[1]
            return (step < L - 1) & ~done

        def body(carry):
            (step, done, leaf_id, order, lstart, lcount, hists, bests, sums,
             tree) = carry
            gains = bests[:, GAIN]
            best_leaf = jnp.argmax(gains).astype(jnp.int32)
            info = bests[best_leaf]
            ok = info[GAIN] > 0.0     # SerialTreeLearner::Train:203-207

            node = step                       # new internal node index
            new_leaf = step + 1               # right child leaf index
            f = info[FEATURE].astype(jnp.int32)
            thr = info[THRESHOLD].astype(jnp.int32)
            dbz = info[DEFAULT_BIN_FOR_ZERO].astype(jnp.int32)
            cat = info[IS_CAT] > 0.5
            fdefault = meta.default_bin[f]
            default_left = jnp.where(cat, dbz == thr, dbz <= thr)

            def bundle_remap(gcol):
                # group column -> feature-local bins (feature_group.h
                # PushData inverted); out-of-range rows sit at the default
                goff = bundle.bin_off[f]
                in_range = (gcol >= goff) & (gcol < goff + bundle.bin_span[f])
                return jnp.where(in_range, gcol - goff + bundle.bin_adj[f],
                                 fdefault)

            def fetch_col_of(Xs, j):
                """Device column j of Xs as int32 bins — nibble-extracted
                when the store is 4-bit packed (ops/pack.py split-half:
                logical j < Fh lives in col j's low nibble, j >= Fh in
                col j-Fh's high nibble)."""
                if not packed_cols:
                    return jnp.take(Xs, j, axis=-1).astype(jnp.int32)
                fh = Xs.shape[-1]
                pj = jnp.where(j < fh, j, j - fh)
                raw = jnp.take(Xs, pj, axis=-1).astype(jnp.int32)
                return jnp.where(j < fh, raw & 15, raw >> 4)

            def split_column_full():
                """Winning feature's bin values for ALL rows (this shard)."""
                j = bundle.group_of[f] if has_bundle else f
                if sparse_mode:
                    from .sparse_store import sparse_split_column
                    col = sparse_split_column(X, j, n, sparse_col_cap)
                else:
                    col = fetch_col_of(X, j)
                return bundle_remap(col) if has_bundle else col

            def go_left_of(col):
                """dense_bin.hpp:190-222: threshold compare with the default
                bin routed by default_left."""
                gl = jnp.where(cat, col == thr, col <= thr)
                return jnp.where(col == fdefault, default_left, gl)

            # ---- partition (dense_bin.hpp:190-222 semantics)
            if ordered:
                # transition: ONE stable sort of leaf_id builds the
                # leaf-grouped permutation + segment table that all later
                # (small) splits partition in-segment
                def do_sort(_):
                    o = jnp.argsort(leaf_id).astype(jnp.int32)
                    slid = jnp.take(leaf_id, o)
                    lid_iota = jnp.arange(L, dtype=jnp.int32)
                    ls = jnp.searchsorted(slid, lid_iota,
                                          side="left").astype(jnp.int32)
                    le = jnp.searchsorted(slid, lid_iota,
                                          side="right").astype(jnp.int32)
                    return o, ls, le - ls

                order, lstart, lcount = lax.cond(
                    step == SEG_AFTER, do_sort,
                    lambda _: (order, lstart, lcount), None)

                def phase_masked(_):
                    # big-leaf phase: full-N masked update (VPU streaming
                    # beats scatter at these row counts)
                    in_leaf = leaf_id == best_leaf
                    go_left = go_left_of(split_column_full())
                    new_lid = jnp.where(in_leaf & ~go_left, new_leaf,
                                        leaf_id)
                    return (jnp.where(ok, new_lid, leaf_id), order, lstart,
                            lcount)

                def phase_seg(_):
                    # small-leaf phase: split ONLY the parent's segment
                    # (DataPartition::Split, data_partition.hpp:118-147) —
                    # stable in-segment partition + leaf_id scatter for the
                    # rows that moved right
                    s_p = lstart[best_leaf]
                    c_p = lcount[best_leaf]

                    def part_branch(cap):
                        def run(_):
                            s, off, blk, valid = seg_block(order, s_p, c_p,
                                                           cap)
                            j = bundle.group_of[f] if has_bundle else f
                            # two gather orders, chosen statically per tier:
                            # rows-then-column touches cap*F bytes, column-
                            # then-rows touches n
                            if cap * X.shape[1] <= n:
                                colb = fetch_col_of(
                                    jnp.take(X, blk, axis=0), j)
                            else:
                                colb = jnp.take(fetch_col_of(X, j),
                                                blk).astype(jnp.int32)
                            if has_bundle:
                                colb = bundle_remap(colb)
                            gl = go_left_of(colb) & valid
                            nleft = jnp.sum(gl.astype(jnp.int32))
                            posl = jnp.cumsum(gl.astype(jnp.int32)) - 1
                            posr = (nleft - 1
                                    + jnp.cumsum(
                                        (valid & ~gl).astype(jnp.int32)))
                            tgt = jnp.where(gl, posl, posr) + off
                            tgt = jnp.where(valid & ok, tgt, cap)  # ~ok: noop
                            new_blk = blk.at[tgt].set(blk, mode="drop")
                            new_order = lax.dynamic_update_slice(
                                order, new_blk, (s,))
                            ridx = jnp.where(valid & ~gl & ok, blk, n)
                            new_lid = leaf_id.at[ridx].set(new_leaf,
                                                           mode="drop")
                            return new_order, new_lid, nleft
                        return run

                    new_order, new_lid, nleft = lax.switch(
                        seg_tier(c_p),
                        [part_branch(c) for c in row_capacities], None)
                    ls = lstart.at[new_leaf].set(
                        jnp.where(ok, s_p + nleft, lstart[new_leaf]))
                    lc = lcount.at[new_leaf].set(
                        jnp.where(ok, c_p - nleft, lcount[new_leaf]))
                    lc = lc.at[best_leaf].set(
                        jnp.where(ok, nleft, lc[best_leaf]))
                    return new_lid, new_order, ls, lc

                leaf_id, order, lstart, lcount = lax.cond(
                    step < SEG_AFTER, phase_masked, phase_seg, None)
            else:
                if feature_axis is not None:
                    # the winning column lives on exactly one feature shard;
                    # compute its go-left mask there and psum it to everyone —
                    # the "every rank re-executes the split" step of the
                    # reference collapses to one bitmask broadcast
                    own = (f >= offset) & (f < offset + F_local)
                    fl = jnp.clip(f - offset, 0, F_local - 1)
                    col = jnp.take(X, fl, axis=1).astype(jnp.int32)
                    go_left = lax.psum(
                        (go_left_of(col) & own).astype(jnp.int32),
                        feature_axis) > 0
                else:
                    go_left = go_left_of(split_column_full())
                in_leaf = leaf_id == best_leaf
                new_leaf_id = jnp.where(in_leaf & ~go_left, new_leaf, leaf_id)
                leaf_id = jnp.where(ok, new_leaf_id, leaf_id)

            # ---- tree bookkeeping (tree.cpp:55-110)
            parent = tree.leaf_parent[best_leaf]
            # fix the grandparent's child pointer
            lc = tree.left_child
            rc = tree.right_child
            was_left = lc[jnp.maximum(parent, 0)] == ~best_leaf
            lc = lc.at[jnp.maximum(parent, 0)].set(
                jnp.where(ok & (parent >= 0) & was_left, node,
                          lc[jnp.maximum(parent, 0)]))
            rc = rc.at[jnp.maximum(parent, 0)].set(
                jnp.where(ok & (parent >= 0) & ~was_left, node,
                          rc[jnp.maximum(parent, 0)]))
            lc = lc.at[node].set(jnp.where(ok, ~best_leaf, lc[node]))
            rc = rc.at[node].set(jnp.where(ok, ~new_leaf, rc[node]))

            depth = tree.leaf_depth[best_leaf] + 1
            upd = lambda arr, idx, val: arr.at[idx].set(
                jnp.where(ok, val, arr[idx]))
            tree = tree._replace(
                num_leaves=tree.num_leaves + ok.astype(jnp.int32),
                split_feature=upd(tree.split_feature, node, f),
                threshold_bin=upd(tree.threshold_bin, node, thr),
                default_bin_for_zero=upd(tree.default_bin_for_zero, node, dbz),
                default_bin=upd(tree.default_bin, node, fdefault),
                is_cat=upd(tree.is_cat, node, cat.astype(jnp.int32)),
                left_child=lc,
                right_child=rc,
                split_gain=upd(tree.split_gain, node, info[GAIN]),
                internal_value=upd(tree.internal_value, node,
                                   tree.leaf_value[best_leaf]),
                internal_count=upd(tree.internal_count, node,
                                   (info[LEFT_COUNT] + info[RIGHT_COUNT])
                                   .astype(jnp.int32)),
                leaf_parent=upd(upd(tree.leaf_parent, best_leaf, node),
                                new_leaf, jnp.where(ok, node, -1)),
                leaf_value=upd(upd(tree.leaf_value, best_leaf,
                                   info[LEFT_OUTPUT]),
                               new_leaf, info[RIGHT_OUTPUT]),
                leaf_count=upd(upd(tree.leaf_count, best_leaf,
                                   info[LEFT_COUNT].astype(jnp.int32)),
                               new_leaf, info[RIGHT_COUNT].astype(jnp.int32)),
                leaf_depth=upd(upd(tree.leaf_depth, best_leaf, depth),
                               new_leaf, depth),
                second_feature=upd(tree.second_feature, node,
                                   info[SECOND_FEATURE].astype(jnp.int32)),
                second_gain=upd(tree.second_gain, node,
                                jnp.where(jnp.isfinite(info[SECOND_GAIN]),
                                          info[SECOND_GAIN], 0.0)),
            )

            # ---- children: smaller scanned, larger by subtraction
            left_sums = jnp.stack([info[LEFT_SUM_G], info[LEFT_SUM_H],
                                   info[LEFT_COUNT]])
            right_sums = jnp.stack([info[RIGHT_SUM_G], info[RIGHT_SUM_H],
                                    info[RIGHT_COUNT]])
            left_smaller = info[LEFT_COUNT] < info[RIGHT_COUNT]
            small = jnp.where(left_smaller, best_leaf, new_leaf)
            large = jnp.where(left_smaller, new_leaf, best_leaf)
            small_sums = jnp.where(left_smaller, left_sums, right_sums)
            large_sums = jnp.where(left_smaller, right_sums, left_sums)

            if ordered:
                def hist_of_seg(leaf):
                    # phase-matched local histogram; the psum sits OUTSIDE
                    # both the phase cond and the tier switch (tier choice
                    # is shard-varying under the data mesh)
                    hl = lax.cond(
                        step < SEG_AFTER,
                        lambda lf: hist_fn(X, grad, hess, leaf_id, lf,
                                           row_mult),
                        lambda lf: seg_hist(X, grad, hess, row_mult, order,
                                            lstart[lf], lcount[lf]),
                        leaf)
                    if voting:
                        return hl
                    return maybe_psum(hl)
                hist_small = hist_of_seg(small)
            else:
                hist_small = hist_of_leaf(X, grad, hess, leaf_id, small,
                                          row_mult)
            if cache_hists:
                # larger child by parent subtraction (feature_histogram.hpp:63)
                hist_large = hists[best_leaf] - hist_small
                hists = hists.at[small].set(
                    jnp.where(ok, hist_small, hists[small]))
                hists = hists.at[large].set(
                    jnp.where(ok, hist_large, hists[large]))
            elif ordered:
                hist_large = hist_of_seg(large)
            else:
                hist_large = hist_of_leaf(X, grad, hess, leaf_id, large,
                                          row_mult)
            sums = sums.at[small].set(jnp.where(ok, small_sums, sums[small]))
            sums = sums.at[large].set(jnp.where(ok, large_sums, sums[large]))

            best_small = best_of(hist_small, small_sums, feature_mask, depth)
            best_large = best_of(hist_large, large_sums, feature_mask, depth)
            neg = jnp.full((SPLIT_VEC_SIZE,), -jnp.inf, bests.dtype)
            bests = bests.at[best_leaf].set(neg)   # consumed
            bests = bests.at[small].set(jnp.where(ok, best_small, bests[small]))
            bests = bests.at[large].set(jnp.where(ok, best_large, bests[large]))

            return (step + ok.astype(jnp.int32), ~ok, leaf_id, order, lstart,
                    lcount, hists, bests, sums, tree)

        carry = (jnp.asarray(0, jnp.int32), jnp.asarray(False), leaf_id,
                 order, lstart, lcount, hists, bests, sums, tree)
        carry = lax.while_loop(cond, body, carry)
        leaf_id, tree = carry[2], carry[-1]
        return tree, leaf_id

    return grow
