"""Measured on-device kernel autotuner (ROADMAP item 3).

Every hot-path kernel decision used to be hand-coded lore from chip
sessions, scattered through ``ops/learner.py`` as inline conditionals:
the 18-30 MB hist-block pathology band, the ``pallas_ct`` promotion
bound (``ncols * bin_pad <= 2560``), the W-ladder cap at 32, and a deck
of pre-registered but never-applied promotion rules (BENCH_NOTES.md
"Armed decks").  This module inverts that architecture: selection is a
single decision function (`decide`) that treats the old heuristics as
the *prior*, enumerates the 3-6 viable (hist_kernel, wave_width,
precision, compaction, fused-iteration) cells for the actual shape, microbenches each
cell for a few waves on the real device with real-shaped data, picks
the winner, and persists it in an on-disk cache keyed by
(shape-bucket, device-kind, schema rev) next to the XLA compile cache
— so subsequent runs on the same shape pay zero tuning cost.

Hard gates are CORRECTNESS constraints and are never tuning candidates:

- the 64 MB VMEM budget (`WAVE_VMEM_GATE`) — cells whose accumulator
  block would not compile are not enumerated;
- the W=1 order-sensitivity quality pin (`resolve_wave_width`) — a
  speed measurement must not undo a quality decision, so a pinned
  width (explicit user width, or the DART/GOSS/lambdarank batched-order
  pin) excludes width from the tuned dimensions entirely (`Pins`).

Modes (``tpu_autotune``):

- ``off``     — prior only; no cache read, no probes (the CPU-CI
                default: selected cells are bit-identical to the
                legacy heuristics, tests/test_autotune.py).
- ``prior``   — use a cached winner when one exists, else the prior;
                never probe.
- ``measure`` — cache hit, else probe the candidate cells and persist
                the winner.
- ``force``   — always re-probe and overwrite the cache entry.

Observability: one ``autotune_decision`` event per learner
construction (whatever the mode — `obs explain` shows *why* a kernel
was chosen, including "heuristic prior, tuning off"), plus one
``autotune_probe`` event per measured cell with its s/wave (schema v8,
obs/events.py).  The learner queues these until its observer is
attached (gbdt.py wires the observer after construction).

Testing: `install_probe_hooks` injects a fake timer and/or a synthetic
bench function (the same injectable-clock pattern as ``SloEngine`` in
obs/serve.py) so winner selection, cache round-trips and invalidation
are deterministic off-TPU — that is also how the CI smoke step runs
measure mode on the CPU backend (tools/autotune_smoke.py).
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, List, NamedTuple, Optional, Tuple

import jax

from ..utils.config import Config
from ..utils.log import Log
from .wave import WAVE_ONLY_MODES, hist_block_bytes

# the VMEM budget the Pallas wave kernels compile under, shared with the
# auto hist-mode gate (64 MB of the kernels' 100 MB compiler limit so
# input tiles and temporaries fit too).  HARD gate: candidate cells
# beyond it are not enumerated, they would not compile.
WAVE_VMEM_GATE = 64 << 20

# The 18-30 MB mid-size accumulator-block pathology band and its
# `band_adjusted_width` escape prior were DELETED in v11: the root cause
# was the wave kernels' row-tile planner sizing input tiles against a
# fixed 16 MB budget that ignored the VMEM-resident accumulator block,
# so exactly the mid-band blocks oversubscribed VMEM under Mosaic
# double-buffering and spilled.  The planner now subtracts the resident
# block from the tile budget (ops/pallas_wave.py::_tile_plan; regression
# probe `tile_plan_vmem_report`), so in-band cells are ordinary measured
# candidates — see docs/FusedIteration.md for the post-mortem.

# the measured pallas_ct promotion bound (ncols * bin_pad) — a PRIOR
# heuristic, not a hard gate: in measure mode ct cells beyond it are
# legitimate candidates (the round-5 "ct-bound widening" armed deck
# becomes a tested cell instead of a dead comment)
CT_PROMOTION_BOUND = 2560

# bump when the meaning of a cached cell changes (new tuned dimension,
# changed probe workload, kernel semantics change): old entries carry
# the old rev in their key and simply stop matching, and `load_cache`
# drops whole files written at another rev so stale entries can never
# be re-merged into a new-rev file by `store_cache`.
# rev 2: cells gained the `fused` dimension (ops/fused_iter.py) and the
# wave kernels' tile plan changed (accumulator-aware budget) — rev-1
# timings measured the old plan and do not transfer.
CACHE_SCHEMA_REV = 2

# enumeration cap — a probe costs a compile + a few waves, and past ~6
# cells the marginal candidate is a long shot (the prior and its
# single-step neighbours cover the measured surprises).  Raised 5 -> 6
# at rev 2 so the fused-iteration flip fits alongside the original four
# neighbour arms.
MAX_CELLS = 6

_CACHE_ENV = "LGBM_TPU_COMPILE_CACHE"
_CACHE_DEFAULT_DIR = "/tmp/lgbm_tpu_xla_cache"
_CACHE_FILENAME = "autotune_cache.json"


def _order_sensitive(config: Config) -> bool:
    """Configs whose quality measurably depends on the leaf-wise split
    ORDER (PARITY_TRAINING.md: lambdarank NDCG; DART/GOSS/InfiniteBoost
    compound the approximation through tree re-weighting / sampling)."""
    return (str(config.objective) in ("lambdarank", "rank")
            or str(config.boosting_type) in ("dart", "goss", "infinite",
                                             "infiniteboost"))


def resolve_wave_order(config: Config) -> str:
    """tpu_wave_order: auto -> 'exact' where order matters (those configs
    then keep wave-width speed WITH the reference's split sequence),
    'batched' otherwise (proven quality parity at full speed)."""
    v = str(config.tpu_wave_order).strip().lower()
    if v not in ("auto", "batched", "exact"):
        Log.fatal("Unknown tpu_wave_order %s (expected auto/batched/"
                  "exact)", v)
    if v != "auto":
        return v
    return "exact" if _order_sensitive(config) else "batched"


def resolve_wave_width(config: Config, num_leaves: int,
                       wave_order: str = "batched") -> int:
    """tpu_wave_width=-1 -> auto: scale the wave to the frontier size,
    gated on QUALITY, not only speed.

    Speed (v5e, 1M x 28, BENCH_NOTES.md): W=16 is fastest at 63 leaves,
    W=32 at 255 — bigger waves amortize the per-sweep pass over more
    splits, but at small trees they just pad the frontier.

    Quality (PARITY_TRAINING.md): BATCHED frontiers approximate the
    leaf-wise split ORDER; at W=8 the measured deltas vs the reference
    are within ~1e-3 for plain-GBDT binary/multiclass metrics but
    -6.4e-3 NDCG@10 on lambdarank (ranking gains are order-sensitive)
    and +0.9e-2..+3e-2 logloss under DART/GOSS/InfiniteBoost (their
    tree re-weighting / gradient sampling compounds the order
    approximation).  Those configs auto-resolve to tpu_wave_order=exact
    (which reproduces the leaf-wise sequence bit-for-bit at any W,
    tests/test_wave_exact_order.py) and KEEP the width ladder; under an
    explicit tpu_wave_order=batched they fall back to W=1.  Explicit
    user widths always pass through.
    """
    w = int(config.tpu_wave_width)
    if w > 0:
        return w
    if w != -1:
        Log.fatal("tpu_wave_width must be positive or -1 (auto), got %d", w)
    if _order_sensitive(config) and wave_order != "exact":
        # batched waves approximate the split order — these configs pay
        # W=1 unless the exact-order schedule carries them
        return 1
    if num_leaves <= 31:
        return 8
    if num_leaves <= 127:
        return 16
    return 32


def prior_hist_mode(config: Config, ncols: int, bin_pad: int,
                    num_leaves: int, psum_axis: Optional[str],
                    on_tpu: Optional[bool] = None) -> str:
    """The legacy ``tpu_histogram_mode=auto`` heuristic — now the
    autotuner's cache-miss PRIOR and the fallback when tuning is
    disabled or off-TPU.

    Measured on v5e (1M x 28, varying inputs to defeat dispatch dedup):
    onehot 7.2ms/25.6ms at B=63/255 vs scatter 226ms at either — XLA's
    fused one-hot reduce is at the VPU roofline, scatter-add
    serializes.  On CPU the opposite holds.

    On-chip A/B at the 255-leaf recipe (tools/AB_RESULTS.md, 1M x 28):
    the transposed Pallas wave kernel (one-hot generated in VMEM,
    MXU-native dot) beats the XLA one-hot engine 6.60 vs 5.56 it/s —
    and the gap widens with N as the materialized one-hot's HBM floor
    grows.  auto therefore picks it whenever the wave engine will
    actually run it: TPU, f32 accumulation (the kernels are
    single-dtype), the dense store, a learner whose engine is the wave
    schedule (serial/data; voting+feature run the exact engine), and a
    shape whose VMEM-resident histogram block leaves headroom inside
    the kernels' 100 MB compiler budget — the gate uses 64 MB so input
    tiles/temporaries fit too (the A/B covered 28 cols x 63 bins; a
    Bosch-wide 968 x 256-pad block would NOT compile — those shapes
    keep the HBM-streaming onehot engine).

    v5 fused kernel promotion (round-4 on-chip A/Bs): at the narrow-F
    recipe pallas_ct beats pallas_t at BOTH measured shapes — 1.30 vs
    1.16 it/s at the 10.5M x 28 flagship (tools/BENCH_SUITE.md
    higgs_ct) and 11.66 vs 10.92 at 1M x 28 (tools/AB_RESULTS.md) — by
    fusing the partition sweep into the histogram kernel (ONE Xt read
    per wave).  Wide-F shapes keep pallas_t until ct has on-chip
    datapoints there; in measure mode the autotuner now probes exactly
    that arm instead of leaving it queued.  Both ct measurements are
    single-chip serial arms, so the promotion is scoped to serial
    EXECUTION — psum_axis is None, which includes data configs falling
    back to the serial engine on one device (ADVICE r4); the true DP
    learner keeps pallas_t until a DP A/B lands.  Round-5 widening
    (tools/BENCH_SUITE.md 15:50 block): ct won 15% at expo_cat (40 x
    64-pad = 2560, 4.07 vs 3.53 it/s) so the bound moves to that
    measured shape.  It is NOT widened further by hand: msltr's
    0.68-vs-0.66 is within noise, and epsilon (2000 x 64 = 128000)
    LOSES 5.6x (0.40 vs 2.23) — wide-F keeps pallas_t.
    """
    if on_tpu is None:
        on_tpu = jax.default_backend() == "tpu"
    wave_capable = (
        str(config.tpu_growth) in ("auto", "wave")
        and not config.tpu_use_dp
        and not config.tpu_sparse
        and str(config.tree_learner) in ("serial", "data",
                                         "data_parallel"))
    # width only resolved (and validated) when the wave engine will
    # actually run — off-TPU growth resolves to exact here and a
    # garbage tpu_wave_width must keep training (ADVICE r2)
    vmem_hist_bytes = (hist_block_bytes(
        ncols, bin_pad,
        resolve_wave_width(config, num_leaves, resolve_wave_order(config)))
        if on_tpu and wave_capable else 0)
    if on_tpu and wave_capable and vmem_hist_bytes <= WAVE_VMEM_GATE:
        return ("pallas_ct"
                if ncols * bin_pad <= CT_PROMOTION_BOUND
                and psum_axis is None
                else "pallas_t")
    return "onehot" if on_tpu else "scatter"


def prior_hist_hilo(growth: str, psum_axis: Optional[str],
                    hist_mode: str, hist_dtype) -> bool:
    """The legacy ``tpu_hist_precision=auto`` resolution — the
    autotuner's precision PRIOR.

    Applies only where the Pallas wave kernels run.  Round-5 promotion
    (pre-registered rule, BENCH_NOTES.md "Armed decks"; measured
    tools/BENCH_SUITE.md 15:50 + tools/AB_RESULTS.md 16:41 blocks):
    auto -> single-bf16-product for WAVE growth — 2.12 vs 1.30 it/s at
    the 10.5M flagship (1.63x, gate 1.4x) with 13-iter AUC 0.89305 vs
    hi/lo 0.89295 (1.0e-4, gate 1e-3) and 1M AUC 0.9362 vs 0.9357
    (5e-4, gate 1e-3).  The reference ships the same trade as ITS
    default (gpu_use_dp=false, docs/GPU-Performance.md).  Exact growth
    keeps hi/lo — it is the parity anchor (+7.7e-6 at 10.5M) and its
    engines never ran the bf16 kernels.  Scoped to serial EXECUTION
    (psum_axis is None) like the pallas_ct promotion: every bf16 gate
    was measured on single-chip serial arms, so the true DP learner
    keeps hi/lo until a DP A/B lands.
    """
    from .wave import pallas_wave_active
    return not (growth == "wave" and psum_axis is None
                and pallas_wave_active(hist_mode, hist_dtype))


def row_bucket(num_data: int) -> int:
    """Shape-bucket N: the next power of two ≥ num_data.  Nearby dataset
    sizes share a tuned cell (wave cost scales ~linearly in N, so the
    winner is stable inside a 2x band), while the flagship and a unit
    test do not."""
    n, b = max(int(num_data), 1), 1
    while b < n:
        b <<= 1
    return b


class Cell(NamedTuple):
    """One point of the kernel design space: everything the probe
    harness needs to instantiate a wave core standalone beyond the
    learner's fixed statics."""
    hist_mode: str      # pallas_t / pallas_ct
    wave_width: int     # W
    hist_hilo: bool     # True = hi/lo f32 pair, False = single-bf16
    compact: bool       # frontier compaction (tpu_wave_compact)
    # rev 2: run the whole iteration as one fused device program
    # (ops/fused_iter.py) instead of the staged gradient/grow/score
    # entry chain — a measured dimension because fusion trades XLA
    # scheduling freedom for zero host orchestration
    fused: bool = False

    def as_dict(self) -> dict:
        return {"hist_mode": self.hist_mode,
                "wave_width": int(self.wave_width),
                "hist_hilo": bool(self.hist_hilo),
                "compact": bool(self.compact),
                "fused": bool(self.fused)}

    @classmethod
    def from_dict(cls, d: dict) -> "Cell":
        return cls(str(d["hist_mode"]), int(d["wave_width"]),
                   bool(d["hist_hilo"]), bool(d["compact"]),
                   bool(d.get("fused", False)))


class ShapeBucket(NamedTuple):
    """The cache/decision key: what the measured surface actually
    varies over.  ncols/bin_pad set the accumulator block, num_leaves
    sets the frontier, n_bucket (power-of-two row count) the sweep
    length."""
    ncols: int
    bin_pad: int
    num_leaves: int
    n_bucket: int

    def key(self) -> str:
        return "c%d_b%d_l%d_n%d" % self


class Pins(NamedTuple):
    """Dimensions excluded from tuning (True = pinned).  Pins encode
    explicit user choices and quality gates — correctness constraints,
    not candidates — and are re-applied to cached winners so a cache
    entry tuned under different pins cannot override them."""
    kernel: bool = False
    width: bool = False
    precision: bool = False
    compact: bool = False
    fused: bool = False


class Decision(NamedTuple):
    """What `decide` resolved, plus the observability trail: ``events``
    is a list of (ev, fields) the caller queues on its observer."""
    cell: Cell
    mode: str            # off / prior / measure / force
    source: str          # off / ineligible / prior / cache / measured
    bucket: str
    probes: Tuple        # ((Cell, s_per_wave), ...) measured this call
    margin: float        # runner-up s/wave over winner's, minus 1
    overhead_s: float    # probe seconds paid in this construction
    cache_hit: bool
    events: List


def resolve_mode(config: Config) -> str:
    v = str(config.tpu_autotune).strip().lower()
    if v not in ("off", "prior", "measure", "force"):
        Log.fatal("Unknown tpu_autotune %s (expected off/prior/measure/"
                  "force)", config.tpu_autotune)
    return v


def resolve_cache_path(config: Config) -> str:
    """``tpu_autotune_cache`` when set, else ``autotune_cache.json``
    next to the XLA compile cache (utils/common.py
    enable_compilation_cache uses the same root)."""
    p = str(config.tpu_autotune_cache).strip()
    if p:
        return p
    root = os.environ.get(_CACHE_ENV, _CACHE_DEFAULT_DIR) \
        or _CACHE_DEFAULT_DIR
    return os.path.join(root, _CACHE_FILENAME)


def cache_key(device_kind: str, bucket: ShapeBucket) -> str:
    return "%s|v%d|%s" % (device_kind, CACHE_SCHEMA_REV, bucket.key())


def _device_kind() -> str:
    try:
        return str(jax.devices()[0].device_kind).strip().replace(" ", "_")
    except Exception:
        return jax.default_backend()


def load_cache(path: str) -> dict:
    """Read the cache file; a missing or corrupt file is an empty cache
    (the tuner must never take training down).

    A file written at another ``CACHE_SCHEMA_REV`` is ALSO an empty
    cache: its entries were measured against different cell semantics
    (and carry old-rev keys), and returning them here would let
    ``store_cache`` re-merge them — verbatim, pins and all — into a
    file it then stamps with the new rev, resurrecting stale winners
    forever.  Dropping the whole file invalidates cleanly; the next
    measure-mode run re-probes (tests/test_autotune.py)."""
    try:
        with open(path) as f:
            data = json.load(f)
        if data.get("version") != CACHE_SCHEMA_REV:
            return {}
        entries = data.get("entries", {})
        return entries if isinstance(entries, dict) else {}
    except (OSError, ValueError, AttributeError):
        return {}


def store_cache(path: str, key: str, entry: dict) -> bool:
    """Merge ``key: entry`` into the cache file atomically (tmp +
    os.replace, same crash-safety idiom as the event writer's barriers).
    Returns False — without raising — when the cache dir is unwritable."""
    try:
        entries = load_cache(path)
        entries[key] = entry
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp.%d" % os.getpid()
        with open(tmp, "w") as f:
            json.dump({"version": CACHE_SCHEMA_REV, "entries": entries},
                      f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return True
    except OSError as e:
        Log.warning("autotune cache not persisted to %s (%s); this run "
                    "keeps its measured cell, the next run re-probes",
                    path, e)
        return False


def apply_pins(cell: Cell, prior: Cell, pins: Pins) -> Cell:
    """Pinned dimensions always take the prior's (validated) value —
    a cached winner tuned under different pins must not override an
    explicit user choice or a quality gate."""
    return Cell(
        hist_mode=prior.hist_mode if pins.kernel else cell.hist_mode,
        wave_width=prior.wave_width if pins.width else cell.wave_width,
        hist_hilo=prior.hist_hilo if pins.precision else cell.hist_hilo,
        compact=prior.compact if pins.compact else cell.compact,
        fused=prior.fused if pins.fused else cell.fused)


def enumerate_cells(prior: Cell, bucket: ShapeBucket, pins: Pins,
                    ct_allowed: bool = True) -> List[Cell]:
    """The 3-6 candidate cells: the prior plus its single-step
    neighbours along each unpinned dimension, hard-gated on VMEM.

    Neighbour choices mirror the measured surprises: width one tier up
    or down (the band pathology and the W-ladder cap were both
    width-tier effects), the alternate transposed kernel (the round-5
    "ct-bound widening" arm — ct beyond 2560 is a candidate here, not
    a dead comment), the flipped precision (the bf16 armed deck), and
    compaction-on (the compaction auto-on armed deck).  The prior is
    always candidate 0 so a tie keeps the measured-by-default choice.
    """
    if prior.hist_mode not in WAVE_ONLY_MODES:
        # width/precision/compaction are wave-kernel dimensions; other
        # engines have no neighbours to probe
        return [prior]
    cands: List[Cell] = [prior]
    if not pins.fused:
        # the staged/fused flip (rev 2): same kernels, different entry
        # granularity — measured because fusing removes host gaps but
        # also removes XLA's freedom to overlap the stages.  Enumerated
        # FIRST among the neighbours: it is the rev-2 headline dimension
        # and must not fall off the MAX_CELLS tail when every other
        # dimension is unpinned too.
        cands.append(prior._replace(fused=not prior.fused))
    if not pins.width:
        for w in (prior.wave_width * 2, prior.wave_width // 2):
            if 1 <= w <= 64:
                cands.append(prior._replace(wave_width=w))
    if not pins.kernel:
        alt = {"pallas_t": "pallas_ct",
               "pallas_ct": "pallas_t"}.get(prior.hist_mode)
        if alt and (alt != "pallas_ct" or ct_allowed):
            cands.append(prior._replace(hist_mode=alt))
    if not pins.precision:
        cands.append(prior._replace(hist_hilo=not prior.hist_hilo))
    if not pins.compact and not prior.compact:
        cands.append(prior._replace(compact=True))
    out: List[Cell] = []
    for c in cands:
        if c in out:
            continue
        # HARD gate: the accumulator block must leave VMEM headroom —
        # same budget as the prior's auto promotion.  The prior itself
        # is exempt: it is the already-validated fallback.
        if c is not prior and hist_block_bytes(
                bucket.ncols, bucket.bin_pad,
                c.wave_width) > WAVE_VMEM_GATE:
            continue
        out.append(c)
    return out[:MAX_CELLS]


# ---------------------------------------------------------------- probing
# injectable measurement hooks (the SloEngine injectable-clock pattern,
# obs/serve.py): "timer" replaces time.perf_counter, "bench" replaces
# the whole build+run probe with a synthetic (cell, bucket) -> s/wave,
# "force" lets measure mode probe off-TPU — tests and the CI CPU smoke
# install these; production never touches them
_HOOKS = {"timer": None, "bench": None, "force": False}


def install_probe_hooks(timer: Optional[Callable[[], float]] = None,
                        bench: Optional[Callable] = None,
                        force: bool = True) -> None:
    _HOOKS["timer"] = timer
    _HOOKS["bench"] = bench
    _HOOKS["force"] = bool(force)


def clear_probe_hooks() -> None:
    _HOOKS["timer"] = None
    _HOOKS["bench"] = None
    _HOOKS["force"] = False


def probe_available(probe) -> bool:
    """Probing needs a real device (or an injected bench/force hook):
    measure mode off-TPU is a documented no-op falling back to the
    prior — CPU CI must not pay wave compiles per shape."""
    if _HOOKS["bench"] is not None:
        return True
    if probe is None:
        return False
    return jax.default_backend() == "tpu" or _HOOKS["force"]


def measure_cells(cells: List[Cell], bucket: ShapeBucket, probe,
                  waves: int, events: List) -> List[Tuple[Cell, float]]:
    """Microbench each candidate: build the cell's core via ``probe``
    (compile + one warmup wave outside the timed window), then time
    ``waves`` waves and record s/wave.  A cell whose build or run
    raises (e.g. a Mosaic compile failure on an untested shape) is
    skipped with a warning — a failed candidate must never take
    training down, the prior still works."""
    timer = _HOOKS["timer"] or time.perf_counter
    bench = _HOOKS["bench"]
    waves = max(1, int(waves))
    out: List[Tuple[Cell, float]] = []
    for cell in cells:
        try:
            if bench is not None:
                s_per_wave = float(bench(cell, bucket))
            else:
                run = probe(cell)
                run()                      # compile + warmup, untimed
                t0 = timer()
                for _ in range(waves):
                    run()
                s_per_wave = (timer() - t0) / waves
        except Exception as e:  # noqa: BLE001 — any candidate may fail
            Log.warning("autotune probe failed for cell %s on %s (%s); "
                        "candidate dropped", cell, bucket.key(), e)
            continue
        events.append(("autotune_probe", {
            "bucket": bucket.key(), "cell": cell.as_dict(),
            "waves": waves, "s_per_wave": s_per_wave,
            "roofline": _cell_roofline(bucket, cell, s_per_wave)}))
        out.append((cell, s_per_wave))
    return out


def _cell_roofline(bucket: ShapeBucket, cell: Cell, s_per_wave: float):
    """Schema-13 roofline stamp for one probed cell (obs/roofline.py):
    where its measured s/wave sits against this chip's compute and
    memory roofs, so `obs explain` can say why the winner won.
    Best-effort — attribution must never fail a probe."""
    try:
        from ..obs.roofline import cell_roofline
        return cell_roofline(bucket, cell, s_per_wave,
                             kind=_device_kind())
    except Exception:  # noqa: BLE001 — stamp is telemetry, not control
        return None


def decide(config: Config, bucket: ShapeBucket, prior: Cell, pins: Pins,
           eligible: bool, probe=None,
           ct_allowed: bool = True) -> Decision:
    """The single kernel-selection decision for one learner
    construction.  Always returns a Decision carrying exactly one
    ``autotune_decision`` event (plus any probe events) so the timeline
    records why the kernel was chosen even when tuning is off."""
    mode = resolve_mode(config)
    waves = int(config.tpu_autotune_waves)
    if waves <= 0:
        Log.fatal("tpu_autotune_waves must be positive, got %d", waves)
    events: List = []

    def _finish(cell, source, probes=(), margin=0.0, overhead=0.0,
                cache_hit=False, cache_path=""):
        events.append(("autotune_decision", {
            "mode": mode, "source": source, "bucket": bucket.key(),
            "device_kind": _device_kind(), "cell": cell.as_dict(),
            "prior": prior.as_dict(),
            "cells": [{"cell": c.as_dict(), "s_per_wave": s,
                       "roofline": _cell_roofline(bucket, c, s)}
                      for c, s in probes],
            "margin": float(margin), "overhead_s": float(overhead),
            "cache_hit": bool(cache_hit), "cache_path": cache_path}))
        return Decision(cell=cell, mode=mode, source=source,
                        bucket=bucket.key(), probes=tuple(probes),
                        margin=float(margin), overhead_s=float(overhead),
                        cache_hit=bool(cache_hit), events=events)

    if mode == "off":
        return _finish(prior, "off")
    if not eligible:
        return _finish(prior, "ineligible")
    path = resolve_cache_path(config)
    key = cache_key(_device_kind(), bucket)
    if mode != "force":
        entry = load_cache(path).get(key)
        if entry is not None:
            try:
                cell = apply_pins(Cell.from_dict(entry["cell"]), prior,
                                  pins)
            except (KeyError, TypeError, ValueError):
                cell = None
            if cell is not None:
                return _finish(cell, "cache", cache_hit=True,
                               cache_path=path)
    if mode == "prior" or not probe_available(probe):
        # prior mode never probes; measure/force off-device (no TPU, no
        # injected bench) fall back to the prior — documented no-op
        return _finish(prior, "prior", cache_path=path)
    cells = enumerate_cells(prior, bucket, pins, ct_allowed=ct_allowed)
    probes = measure_cells(cells, bucket, probe, waves, events)
    if not probes:
        return _finish(prior, "prior", cache_path=path)
    best = min(probes, key=lambda p: p[1])
    others = sorted(s for c, s in probes if c is not best[0])
    margin = (others[0] / best[1] - 1.0) if others and best[1] > 0 else 0.0
    overhead = sum(s * waves for _, s in probes)
    store_cache(path, key, {
        "cell": best[0].as_dict(), "s_per_wave": best[1],
        "waves": waves,
        "cells": [{"cell": c.as_dict(), "s_per_wave": s}
                  for c, s in probes]})
    return _finish(best[0], "measured", probes=probes, margin=margin,
                   overhead=overhead, cache_path=path)
