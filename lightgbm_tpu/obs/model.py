"""Model observability: split audit trail + importance evolution.

Two event families on the obs timeline (events.py, schema v5):

* ``split_audit`` — one event per materialized tree listing every realized
  split: feature, bin threshold, real-valued threshold, gain, child row
  counts, and the runner-up candidate the winner beat (``second_feature`` /
  ``second_gain`` threaded host-side from the device split search,
  ops/split_finder.py SECOND_*).  The ``margin`` (gain - second_gain) is
  the cheapest single signal for "was this split decisive or a coin flip".
* ``importance`` — top-k sparse split/gain importance vectors at a cadence
  (``obs_importance_every``), so importance can be read as a trajectory
  instead of a single end-of-training snapshot.

Everything here works on host numpy arrays of already-materialized
models/tree.py Trees — nothing touches the device.  Readers
(``importance_history``, ``audit_margin_stats``) operate on the event
dicts returned by events.read_events and back Booster.importance_history
and the ``obs explain`` report (query.py).
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

# Cap on splits recorded per tree: a 64k-leaf tree would otherwise write a
# multi-MB event line.  Splits are recorded in node order (creation order),
# so the cap keeps the earliest — highest-level — splits.
MAX_AUDIT_SPLITS = 512


def tree_split_records(tree, max_splits: int = MAX_AUDIT_SPLITS
                       ) -> Tuple[List[dict], bool]:
    """Per-split audit records for one materialized Tree.

    Child row counts are reconstructed from the final arrays: a side that
    stayed a leaf keeps its count in ``leaf_count``; a side that split
    again had its leaf_count overwritten, but the child internal node's
    ``internal_count`` preserves the side's row count at split time.
    """
    ni = int(tree.num_leaves) - 1
    if ni <= 0:
        return [], False
    truncated = ni > max_splits
    records: List[dict] = []
    for i in range(min(ni, max_splits)):
        def side_count(child):
            c = int(child)
            return int(tree.leaf_count[~c]) if c < 0 \
                else int(tree.internal_count[c])
        left_cnt = side_count(tree.left_child[i])
        right_cnt = side_count(tree.right_child[i])
        rec = {
            "node": i,
            "feature": int(tree.split_feature[i]),
            "bin": int(tree.threshold_in_bin[i]),
            "threshold": float(tree.threshold[i]),
            "gain": float(tree.split_gain[i]),
            "count": int(tree.internal_count[i]),
            "left_count": left_cnt,
            "right_count": right_cnt,
            "cat": bool(tree.decision_type[i] == 1),
        }
        sf = int(tree.second_feature[i])
        if sf >= 0:
            sg = float(tree.second_gain[i])
            rec["second_feature"] = sf
            rec["second_gain"] = sg
            rec["margin"] = float(tree.split_gain[i]) - sg
        records.append(rec)
    return records, truncated


def emit_split_audit(obs, it: int, tree_index: int, tree,
                     max_splits: int = MAX_AUDIT_SPLITS) -> None:
    """Write one ``split_audit`` event for a materialized tree (skips
    stubs — a tree with no splits has nothing to audit)."""
    splits, truncated = tree_split_records(tree, max_splits)
    if not splits:
        return
    obs.event("split_audit", it=int(it), tree=int(tree_index),
              num_leaves=int(tree.num_leaves),
              shrinkage=float(tree.shrinkage),
              truncated=bool(truncated), splits=splits)


def emit_importance(obs, it: int, split_counts: np.ndarray,
                    gains: np.ndarray, topk: int = 20) -> None:
    """Write one top-k sparse ``importance`` event.

    ``split_counts`` / ``gains`` are the dense per-real-feature vectors
    from GBDT.feature_importance; top-k is chosen by gain (the more
    discriminative of the two), ties broken by feature index.
    """
    gains = np.asarray(gains, dtype=np.float64)
    split_counts = np.asarray(split_counts, dtype=np.float64)
    used = np.nonzero((gains > 0) | (split_counts > 0))[0]
    if len(used) == 0:
        return
    order = used[np.argsort(-gains[used], kind="stable")]
    if topk > 0:
        order = order[:topk]
    obs.event("importance", it=int(it),
              n_features=int(len(gains)),
              n_used=int(len(used)),
              features=[int(f) for f in order],
              split=[int(split_counts[f]) for f in order],
              gain=[float(gains[f]) for f in order])


# ------------------------------------------------------------------ readers

def importance_history(events: Sequence[dict],
                       importance_type: str = "split") -> List[dict]:
    """``importance`` events -> ``[{"it", "importance": {feature: value}}]``.

    ``importance_type``: 'split' (number of uses) or 'gain' (total gain).
    Only the last run in the timeline is considered (a timeline can hold
    several runs back to back).
    """
    if importance_type not in ("split", "gain"):
        raise ValueError("importance_type must be 'split' or 'gain', got %r"
                         % (importance_type,))
    # restart at the last run_header, like query.timeline_metrics
    start = 0
    for i, ev in enumerate(events):
        if ev.get("ev") == "run_header":
            start = i
    out: List[dict] = []
    for ev in events[start:]:
        if ev.get("ev") != "importance":
            continue
        feats = ev.get("features") or []
        vals = ev.get(importance_type) or []
        out.append({"it": int(ev.get("it", -1)),
                    "importance": {int(f): float(v)
                                   for f, v in zip(feats, vals)}})
    return out


def audit_margin_stats(events: Sequence[dict]) -> Dict[int, dict]:
    """Aggregate ``split_audit`` margins per winning feature.

    Returns ``{feature: {"splits", "total_gain", "contested",
    "min_margin_rel", "median_margin_rel", "runner_ups": {feat: n}}}``
    where margin_rel = margin / gain (0 = coin flip, 1 = unopposed among
    contested splits).  Uncontested splits (no runner-up) count toward
    ``splits`` but not the margin percentiles.
    """
    margins: Dict[int, List[float]] = {}
    stats: Dict[int, dict] = {}
    for ev in events:
        if ev.get("ev") != "split_audit":
            continue
        for s in ev.get("splits") or []:
            f = int(s.get("feature", -1))
            st = stats.setdefault(f, {"splits": 0, "total_gain": 0.0,
                                      "contested": 0, "runner_ups": {}})
            st["splits"] += 1
            st["total_gain"] += float(s.get("gain", 0.0))
            if "second_feature" in s:
                st["contested"] += 1
                g = float(s.get("gain", 0.0))
                if g > 0:
                    margins.setdefault(f, []).append(
                        float(s.get("margin", 0.0)) / g)
                sf = int(s["second_feature"])
                st["runner_ups"][sf] = st["runner_ups"].get(sf, 0) + 1
    for f, st in stats.items():
        rel = margins.get(f)
        if rel:
            st["min_margin_rel"] = float(np.min(rel))
            st["median_margin_rel"] = float(np.median(rel))
        else:
            st["min_margin_rel"] = None
            st["median_margin_rel"] = None
    return stats
