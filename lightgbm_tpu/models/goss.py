"""GOSS booster (src/boosting/goss.hpp).

Gradient-based One-Side Sampling: keep the top ``top_rate`` fraction of rows
by |g*h| (summed over classes), sample ``other_rate`` of the rest uniformly
and amplify their gradients by (1-top_rate-ish) factor
``(cnt - top_k) / other_k`` (goss.hpp:79-125).  No sampling for the first
``1 / learning_rate`` iterations (goss.hpp:128-130).

Realized as the row-multiplier mask the TPU learner already consumes —
gradient amplification is applied in place to the gradient arrays, exactly
like the reference mutates ``gradients_``.
"""
from __future__ import annotations

import numpy as np

from ..utils.log import Log
from .gbdt import GBDT


class GOSS(GBDT):
    def __init__(self, config, train_data=None, objective=None,
                 training_metrics=()):
        super().__init__(config, train_data, objective, training_metrics)
        if config.bagging_freq > 0 and config.bagging_fraction != 1.0:
            Log.fatal("cannot use bagging in GOSS")
        Log.info("Using GOSS")
        if train_data is not None:
            # GOSS owns bagging entirely
            self.bag_data_cnt = self.num_data

    def _bagging(self, it: int, gradients=None, hessians=None) -> None:
        cfg = self.config
        self.row_mult = None
        if it < int(1.0 / cfg.learning_rate):
            return
        if gradients is None:
            return
        n = self.num_data
        g = np.abs(np.asarray(gradients) * np.asarray(hessians)).reshape(
            self.num_tree_per_iteration, n).sum(axis=0)
        top_k = max(1, int(n * cfg.top_rate))
        other_k = int(n * cfg.other_rate)
        order = np.argpartition(-g, top_k - 1)
        threshold = g[order[top_k - 1]]
        is_top = g >= threshold
        rest_idx = np.nonzero(~is_top)[0]
        mult = np.zeros(n, dtype=np.float32)
        mult[is_top] = 1.0
        if other_k > 0 and len(rest_idx) > 0:
            rng = np.random.default_rng(cfg.bagging_seed + it)
            take = min(other_k, len(rest_idx))
            sampled = rng.choice(rest_idx, size=take, replace=False)
            mult[sampled] = 1.0
            multiply = (n - top_k) / other_k
            for tid in range(self.num_tree_per_iteration):
                gradients[tid][sampled] *= multiply
                hessians[tid][sampled] *= multiply
        self.row_mult = mult
