"""Training-quality parity vs the reference, pinned.

PARITY_TRAINING.json holds head-to-head metrics produced by
tools/gen_parity.py (reference CLI and lightgbm_tpu trained on the golden
data with identical configs, same metric code on both prediction sets —
the docs/GPU-Performance.md:134-145 CPU-vs-GPU accuracy pattern).

This test retrains OUR side and asserts (a) we still reproduce our own
committed numbers (training determinism / no silent regression) and
(b) we remain within tolerance of the committed REFERENCE numbers.
When a reference binary is available ($REF_LGBM or /tmp/refbuild/lightgbm)
the full live comparison can be regenerated with tools/gen_parity.py.
"""
import json
import os
import sys
import tempfile

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
GOLDEN = os.path.join(HERE, "data", "golden")
sys.path.insert(0, os.path.join(REPO, "tools"))

from parity_metrics import load_query, load_tsv  # noqa: E402

# |ours - reference| bound for exact (leaf-wise) growth; the committed
# table (PARITY_TRAINING.md) shows actual deltas <= 8e-4
EXACT_TOL = 2e-3
# reproducibility bound vs our own committed numbers (fp noise only)
SELF_TOL = 5e-6


def _committed():
    path = os.path.join(REPO, "PARITY_TRAINING.json")
    with open(path) as f:
        return json.load(f)


@pytest.mark.parametrize("task", ["binary", "regression", "multiclass",
                                  "lambdarank"])
def test_training_quality_parity(task):
    from gen_parity import TASKS, run_ours
    table = _committed()[task]
    spec = TASKS[task]
    y, _ = load_tsv(os.path.join(GOLDEN, "%s.test" % task))
    qpath = os.path.join(GOLDEN, "%s.test.query" % task)
    q = load_query(qpath) if os.path.exists(qpath) else None
    with tempfile.TemporaryDirectory() as tmp:
        pred = run_ours(task, spec, tmp)
    got = spec["metrics"](y, pred, q)
    for metric, ref_val in table["reference"].items():
        mine = got[metric]
        committed_mine = table["lightgbm_tpu"][metric]
        assert abs(mine - committed_mine) < SELF_TOL, (
            "%s/%s drifted from committed value: %.6f vs %.6f"
            % (task, metric, mine, committed_mine))
        assert abs(mine - ref_val) < EXACT_TOL, (
            "%s/%s out of parity with reference: %.6f vs %.6f"
            % (task, metric, mine, ref_val))
