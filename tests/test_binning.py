"""Binning unit tests against hand-computed oracles (bin.cpp semantics)."""
import numpy as np
import pytest

from lightgbm_tpu.io.binning import (BinMapper, CATEGORICAL, NUMERICAL,
                                     greedy_find_bin, need_filter)


def test_greedy_few_distinct():
    # num_distinct <= max_bin: bounds at midpoints, honoring min_data_in_bin
    dv = np.array([1.0, 2.0, 3.0])
    cnt = np.array([10, 10, 10])
    bounds = greedy_find_bin(dv, cnt, 3, 255, 30, 5)
    assert bounds == [1.5, 2.5, np.inf]


def test_greedy_min_data_in_bin_merges():
    dv = np.array([1.0, 2.0, 3.0])
    cnt = np.array([2, 2, 10])
    bounds = greedy_find_bin(dv, cnt, 3, 255, 14, 3)
    # first bin must absorb >=3 samples -> boundary after value 2
    assert bounds[0] == 2.5


def test_find_bin_zero_bin_reserved():
    m = BinMapper()
    vals = np.array([-2.0, -1.0, 1.0, 2.0, 3.0] * 10)
    m.find_bin(vals, 100, 255, 1, 1, NUMERICAL)  # 50 implicit zeros
    # the zero range must have a dedicated bin, default_bin = bin of 0.0
    assert m.default_bin == m.value_to_bin(0.0)
    assert m.value_to_bin(1e-21) == m.default_bin
    assert m.value_to_bin(-1e-21) == m.default_bin
    assert m.value_to_bin(-1.0) < m.default_bin
    assert m.value_to_bin(1.0) > m.default_bin


def test_find_bin_monotone_and_bounds():
    rng = np.random.default_rng(0)
    vals = rng.normal(size=1000)
    m = BinMapper()
    m.find_bin(vals, 1000, 63, 3, 5, NUMERICAL)
    assert m.num_bin <= 63
    # mapping must be monotone for numerical features
    xs = np.linspace(-3, 3, 500)
    bins = m.value_to_bin(xs)
    assert (np.diff(bins) >= 0).all()
    # each value lands in the bin whose upper bound dominates it
    for x in (-2.5, -0.5, 0.0, 0.7, 2.9):
        b = m.value_to_bin(x)
        assert x <= m.bin_upper_bound[b]
        if b > 0:
            assert x > m.bin_upper_bound[b - 1]


def test_categorical_count_order():
    vals = np.array([3.0] * 50 + [7.0] * 30 + [1.0] * 20)
    m = BinMapper()
    m.find_bin(vals, 100, 255, 1, 1, CATEGORICAL)
    # count-sorted: category 3 -> bin 0, 7 -> bin 1, 1 -> bin 2
    assert m.value_to_bin(3) == 0
    assert m.value_to_bin(7) == 1
    assert m.value_to_bin(1) == 2
    # unseen category maps to last bin (bin.h:433-440)
    assert m.value_to_bin(99) == m.num_bin - 1


def test_trivial_feature():
    m = BinMapper()
    m.find_bin(np.array([]), 100, 255, 3, 5, NUMERICAL)  # all zeros
    assert m.is_trivial


def test_need_filter():
    # 10 in bin0, 10 in bin1: a split at bin0 leaves 10/10
    assert not need_filter([10, 10], 20, 5, NUMERICAL)
    assert need_filter([1, 19], 20, 5, NUMERICAL)


def test_serialization_roundtrip():
    rng = np.random.default_rng(1)
    m = BinMapper()
    m.find_bin(rng.normal(size=500), 600, 63, 3, 5, NUMERICAL)
    m2 = BinMapper.from_dict(m.to_dict())
    xs = rng.normal(size=100)
    assert (m.value_to_bin(xs) == m2.value_to_bin(xs)).all()
