#!/bin/bash
# Round-4 late-window deck, armed after the ~14:10 UTC re-wedge:
# waits for the tunnel, then runs (1) the precision/ct-widening/width
# suite arms, (2) the 1M bf16 kernel A/Bs, (3) the missing 10.5M
# parity WAVE arm, (4) a final bench warm pass.  Budget-gated so the
# chip is released well before the driver's round-end bench.
cd /root/repo || exit 1
LOG=/tmp/chain_r04.log
log() { echo "[chain4d] $(date -u +%F\ %T) $*" >> "$LOG"; }

END=${CHAIN4D_END_EPOCH:-$(( $(date +%s) + 25200 ))}
left() { echo $(( END - $(date +%s) )); }

stage() {  # stage <name> <cap_seconds> <cmd...>
  local name=$1 cap=$2; shift 2
  local l; l=$(left)
  if [ "$l" -le 300 ]; then log "$name SKIPPED (budget spent)"; return; fi
  [ "$cap" -gt "$l" ] && cap=$l
  log "$name start (cap ${cap}s)"
  timeout "$cap" "$@" ; log "$name rc=$?"
}

log "armed (end $(date -u -d @$END +%T))"
while :; do
  [ "$(left)" -le 600 ] && { log "tunnel never returned; idle-exit"; exit 0; }
  timeout 150 python - <<'EOF' >/dev/null 2>&1 && break
from lightgbm_tpu.utils.common import probe_device
import sys
sys.exit(0 if probe_device(timeout=120) == "tpu" else 1)
EOF
  sleep 120
done
log "tunnel ALIVE"

stage suite2 9000 env SUITE_DEADLINE_S=8700 \
  python tools/bench_suite.py higgs_bf16 epsilon_ct msltr_ct yahoo_w64

stage ab2p 3600 env AB2_DEADLINE_S=3300 \
  bash -c 'python tools/tpu_ab2.py 999424 --r04p > /tmp/ab2_r04p.out 2>&1'

stage paritywave 3600 env PARITY_N=10500000 PARITY_DEADLINE_S=3300 \
  bash -c 'python tools/parity_flagship.py --wave-only > /tmp/parity_fs10m_wave.out 2>&1'

stage bench3 2100 env BENCH_DEADLINE_S=1800 \
  bash -c 'python bench.py > /tmp/bench_r04_final.json 2> /tmp/bench_r04_final.err'

log "chain4d complete; chip released"
