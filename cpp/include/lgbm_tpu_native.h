/* C ABI for the lightgbm_tpu native data plane.
 *
 * The reference implements its host-side data plane in C++ (text parsing
 * src/io/parser.cpp, bin finding src/io/bin.cpp, row-wise prediction
 * src/application/predictor.hpp); this library provides the same hot paths
 * for the TPU framework, consumed from Python via ctypes (no pybind11 in
 * the image).  The TPU compute plane (histograms/split/partition) stays in
 * XLA — this is the part XLA cannot do: text ingest, per-feature greedy
 * binning, and latency-sensitive ensemble prediction on raw features.
 */
#ifndef LGBM_TPU_NATIVE_H_
#define LGBM_TPU_NATIVE_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- bin finding (semantics of BinMapper::FindBin, src/io/bin.cpp:139) */

/* values: the non-zero sample values (unsorted, NaN allowed -> dropped).
 * Outputs the numerical upper-bound array (<= max_bin entries) plus the
 * bookkeeping fields.  Returns 0 on success. */
int LGBMTPU_FindBinNumerical(const double* values, int32_t num_values,
                             int32_t total_cnt, int32_t max_bin,
                             int32_t min_data_in_bin, int32_t min_split_data,
                             double* out_upper_bounds, int32_t* out_num_bin,
                             int32_t* out_is_trivial, double* out_min_val,
                             double* out_max_val, int32_t* out_default_bin,
                             double* out_sparse_rate);

/* Batch value->bin via binary search over upper bounds
 * (BinMapper::ValueToBin, include/LightGBM/bin.h:419). out is uint16. */
int LGBMTPU_ValueToBin(const double* upper_bounds, int32_t num_bin,
                       const double* values, int64_t n, uint16_t* out);

/* ---- text parsing (CSV/TSV/space/LibSVM autodetect, src/io/parser.cpp) */

/* Parses the file into a dense row-major feature matrix + label column.
 * The function allocates *out_features ((*out_rows) x (*out_cols)) and
 * *out_label; free both with LGBMTPU_Free.  Returns 0 on success. */
int LGBMTPU_ParseFile(const char* path, int32_t has_header,
                      int32_t label_idx, int64_t* out_rows,
                      int32_t* out_cols, double** out_features,
                      double** out_label);

void LGBMTPU_Free(void* ptr);

/* ---- ensemble prediction on raw features (Tree::GetLeaf semantics,
 *      include/LightGBM/tree.h:250-276; zero-range default redirect) */

/* Flat ensemble layout: trees concatenated; node_offsets[t] /
 * leaf_offsets[t] give tree t's start in the node/leaf arrays
 * (node_offsets[n_trees] = total nodes, same for leaves). */
int LGBMTPU_PredictRaw(int32_t n_trees, const int64_t* node_offsets,
                       const int64_t* leaf_offsets,
                       const int32_t* split_feature, const double* threshold,
                       const int8_t* decision_type,
                       const double* default_value, const int32_t* left_child,
                       const int32_t* right_child, const double* leaf_value,
                       const int32_t* tree_class, int32_t n_class,
                       const double* features, int64_t n_rows,
                       int32_t n_cols, double* out /* n_rows x n_class */);

#ifdef __cplusplus
}
#endif

#endif /* LGBM_TPU_NATIVE_H_ */
