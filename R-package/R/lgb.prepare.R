# Data preparation helpers — parity with R-package/R/lgb.prepare.R,
# lgb.prepare2.R, lgb.prepare_rules.R, lgb.prepare_rules2.R: convert
# factor/character columns to numeric codes, optionally recording the
# level maps so validation/test frames code identically.

#' Convert factor/character columns to numeric (no rules recorded)
#' @export
lgb.prepare <- function(data) {
  lgb.prepare_rules(data)$data
}

#' Convert factor/character columns to integer (no rules recorded)
#' @export
lgb.prepare2 <- function(data) {
  lgb.prepare_rules2(data)$data
}

#' Convert to numeric and record per-column level maps
#'
#' @param data data.frame
#' @param rules previously recorded rules to re-apply (valid/test data)
#' @return list(data = converted frame, rules = named list of level maps)
#' @export
lgb.prepare_rules <- function(data, rules = NULL) {
  out <- .lgb_prepare_impl(data, rules, as_fun = as.numeric)
  out
}

#' Integer-coded variant of lgb.prepare_rules
#' @export
lgb.prepare_rules2 <- function(data, rules = NULL) {
  .lgb_prepare_impl(data, rules, as_fun = as.integer)
}

.lgb_prepare_impl <- function(data, rules, as_fun) {
  if (!is.data.frame(data)) {
    return(list(data = data, rules = if (is.null(rules)) list() else rules))
  }
  new_rules <- if (is.null(rules)) list() else rules
  for (col in names(data)) {
    v <- data[[col]]
    if (is.character(v)) v <- factor(v)
    if (is.factor(v)) {
      if (!is.null(new_rules[[col]])) {
        lv <- new_rules[[col]]
        v <- factor(as.character(v), levels = names(lv))
        data[[col]] <- as_fun(unname(lv[as.character(v)]))
      } else {
        lv <- stats::setNames(seq_along(levels(v)), levels(v))
        new_rules[[col]] <- lv
        data[[col]] <- as_fun(lv[as.character(v)])
      }
    }
  }
  list(data = data, rules = new_rules)
}
