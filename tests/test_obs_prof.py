"""Continuous host sampling profiler (lightgbm_tpu/obs/prof.py).

Covers the ISSUE-20 contract: fake-clock window aggregation / folding /
top-K truncation, the shared stack-capture path (sampler + watchdog
flight records), the gated overhead budget, the wedged-sampler drill
(injected exception -> loud ``error`` window -> ``obs prof --check``
exits 1), burst captures with idle-thread filtering, the reader side
(top table / flamegraph / check), and a concurrent serve-load test
proving the profiler adds zero sheds and zero steady-state compiles."""
import os
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import NULL_OBSERVER, RunObserver, read_events
from lightgbm_tpu.obs.prof import (OVERHEAD_BUDGET_FRAC, HostProfiler,
                                   _is_idle_stack, _short_path, _Window,
                                   aggregate_window, burst,
                                   capture_thread_stacks, check_profiles,
                                   evidence_profile, fold_frames,
                                   folded_text, merged_profile,
                                   profile_events, render_flame,
                                   render_top, thread_roles)
from lightgbm_tpu.obs.query import main as query_main


class FakeClock:
    """Injectable monotonic clock: ticks cost zero unless advanced."""

    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def _collector():
    payloads = []
    return payloads, lambda ev, **fields: payloads.append(fields)


# ------------------------------------------------------------------ folding
def test_short_path_keeps_package_suffix():
    sep = os.sep
    p = sep.join(("", "x", "lightgbm_tpu", "obs", "prof.py"))
    assert _short_path(p) == "lightgbm_tpu/obs/prof.py"
    q = sep.join(("", "usr", "lib", "python3.11", "threading.py"))
    assert _short_path(q) == "python3.11/threading.py"


def test_fold_frames_root_to_leaf_order():
    import sys
    frame = sys._current_frames()[threading.get_ident()]
    labels = fold_frames(frame)
    assert labels, "live stack folds to at least one label"
    # leaf (last label) is this very test function; the root is the
    # interpreter / pytest entry, nowhere near the leaf
    assert labels[-1].endswith(":test_fold_frames_root_to_leaf_order")
    assert not labels[0].endswith(":test_fold_frames_root_to_leaf_order")
    assert all(":" in lb for lb in labels)


def test_idle_stack_filter():
    assert _is_idle_stack([])                               # gone thread
    assert _is_idle_stack(["python3.11/selectors.py:select"])
    assert _is_idle_stack(["python3.11/threading.py:wait"])
    # any lightgbm_tpu frame keeps the stack, whatever the leaf
    assert not _is_idle_stack(["lightgbm_tpu/obs/events.py:run",
                               "python3.11/threading.py:wait"])
    # busy non-package work is kept too
    assert not _is_idle_stack(["tests/test_obs_prof.py:spin"])


def test_capture_thread_stacks_shape_and_watchdog_delegation():
    me = threading.current_thread()
    out = capture_thread_stacks()
    label = "%s (%d)" % (me.name, me.ident)
    assert label in out
    assert isinstance(out[label], list)
    assert any("capture_thread_stacks" in ln for ln in out[label])
    # the watchdog's flight-record capture is the SAME path (one
    # sys._current_frames walker in tree) — same keys, same shape
    from lightgbm_tpu.obs.watchdog import _thread_stacks
    out2 = _thread_stacks()
    assert label in out2
    assert isinstance(out2[label], list)
    assert thread_roles()[me.ident] == me.name


# ----------------------------------------------------- fake-clock windowing
def test_window_aggregation_with_fake_clock():
    clk = FakeClock(100.0)
    payloads, emit = _collector()
    iters = iter([3, 4, 7])
    prof = HostProfiler(emit=emit, hz=10, window_s=5.0, topk=0,
                        context={"stage": "boost"},
                        phase_of=lambda: "grow",
                        iter_of=lambda: next(iters), clock=clk)
    for _ in range(3):
        prof.tick()
    clk.t = 102.0
    payload = prof.flush_now()
    assert payloads[-1] is payload or payloads[-1] == payload
    assert payload["samples"] == 3
    assert payload["dur_s"] == pytest.approx(2.0)
    assert payload["hz"] == 10
    assert payload["cost_s"] == 0.0         # fake clock never advanced
    assert payload["overhead_frac"] == 0.0
    assert payload["stages"] == {"boost": 3}
    assert payload["phases"] == {"grow": 3}
    assert payload["iter_lo"] == 3 and payload["iter_hi"] == 7
    # the ticking (main) thread is sampled through prof.tick itself, so
    # every stack key carries its role and a lightgbm_tpu frame
    assert payload["stacks"]
    assert all(k.split(";", 1)[0] == "MainThread"
               for k in payload["stacks"])
    assert all("lightgbm_tpu/obs/prof.py:tick" in k
               for k in payload["stacks"])
    # flush swapped in a fresh window
    assert prof.peek()["samples"] == 0


def test_topk_truncation_deterministic():
    w = _Window(0.0)
    w.samples = 9
    w.stacks = {"r;a:f": 5, "r;b:g": 3, "r;c:h": 1}
    p = aggregate_window(w, 1.0, 29, topk=2)
    assert p["stacks"] == {"r;a:f": 5, "r;b:g": 3}
    assert p["truncated"] == 1 and p["topk"] == 2
    # count ties break on the stack name (deterministic order)
    w2 = _Window(0.0)
    w2.stacks = {"r;z": 2, "r;a": 2, "r;m": 2}
    p2 = aggregate_window(w2, 1.0, 29, topk=2)
    assert list(p2["stacks"]) == ["r;a", "r;m"]
    # topk <= 0 keeps everything (burst captures)
    assert aggregate_window(w, 1.0, 29, topk=0)["truncated"] == 0


def test_overhead_frac_self_measured():
    class CostClock:
        """Every call advances 1ms — each tick 'costs' exactly 1ms."""

        def __init__(self):
            self.t = 0.0

        def __call__(self):
            self.t += 0.001
            return self.t

    payloads, emit = _collector()
    prof = HostProfiler(emit=emit, hz=10, window_s=5.0, clock=CostClock())
    for _ in range(5):
        prof.tick()
    payload = prof.flush_now(now=1.0)
    assert payload["cost_s"] == pytest.approx(0.005, abs=1e-6)
    assert payload["overhead_frac"] == pytest.approx(
        payload["cost_s"] / payload["dur_s"], abs=1e-4)


# ------------------------------------------------------------ check gate
def _prof_ev(**kw):
    base = {"ev": "prof_profile", "samples": 10, "dur_s": 1.0, "hz": 29,
            "cost_s": 0.001, "overhead_frac": 0.001}
    base.update(kw)
    return base


def test_check_profiles_rules():
    iters = [{"ev": "iter", "it": i} for i in range(2)]
    assert check_profiles(iters + [_prof_ev()]) == []
    # no prof events at all: the profiler may be off — pass
    assert check_profiles(iters) == []
    # sampler error window is loud
    probs = check_profiles(iters + [_prof_ev(error="RuntimeError('x')")])
    assert any("sampler error" in p for p in probs)
    # blown overhead budget
    probs = check_profiles(
        iters + [_prof_ev(cost_s=0.5, overhead_frac=0.5)])
    assert any("budget" in p for p in probs)
    assert OVERHEAD_BUDGET_FRAC == 0.01
    # zero samples while iterations advanced = wedged sampler
    probs = check_profiles(iters + [_prof_ev(samples=0)])
    assert any("zero samples" in p for p in probs)
    # ... but zero samples with no training loop is fine (serve-only)
    assert check_profiles([_prof_ev(samples=0)]) == []


# ------------------------------------------------------- wedged sampler
def test_wedged_sampler_is_loud_and_stops():
    payloads, emit = _collector()

    def boom():
        raise RuntimeError("frames exploded")

    prof = HostProfiler(emit=emit, hz=200, window_s=60.0, frames_fn=boom)
    prof.start()
    deadline = time.monotonic() + 5.0
    while not prof.wedged and time.monotonic() < deadline:
        time.sleep(0.01)
    assert prof.wedged
    prof._thread.join(timeout=2.0)
    assert not prof.running              # sampling stopped, not spinning
    assert len(payloads) == 1            # exactly one poisoned window
    assert "frames exploded" in payloads[0]["error"]
    evs = [dict(payloads[0], ev="prof_profile")]
    assert any("sampler error" in p for p in check_profiles(evs))
    prof.stop()                          # idempotent, no second flush
    assert len(payloads) == 1


def test_cli_check_exits_1_on_wedged_timeline(tmp_path, capsys):
    path = str(tmp_path / "wedged" / "t.jsonl")
    os.makedirs(os.path.dirname(path))
    obs = RunObserver(events_path=path)
    obs.run_header(backend="cpu", devices=[], params={}, context={})
    for i in range(2):
        obs.event("iter", it=i, time_s=0.01, phases={}, fenced=False)
    obs.event("prof_profile", samples=0, dur_s=1.0, hz=29, cost_s=0.0,
              error="RuntimeError('boom')", source="train")
    obs.close()
    assert query_main(["prof", path, "--check"]) == 1
    text = capsys.readouterr().out
    assert "PROF CHECK" in text and "sampler error" in text
    # without --check the report prints but the exit stays 0
    assert query_main(["prof", path]) == 0


def test_cli_check_exits_0_on_clean_timeline(tmp_path, capsys):
    path = str(tmp_path / "clean.jsonl")
    obs = RunObserver(events_path=path)
    obs.run_header(backend="cpu", devices=[], params={}, context={})
    for i in range(2):
        obs.event("iter", it=i, time_s=0.01, phases={}, fenced=False)
    obs.event("prof_profile", samples=5, dur_s=1.0, hz=29, cost_s=0.001,
              stacks={"MainThread;lightgbm_tpu/x.py:f": 5},
              roles={"MainThread": 5}, source="train")
    obs.close()
    # a directory target resolves to its newest *.jsonl
    flame = str(tmp_path / "f.html")
    assert query_main(["prof", str(tmp_path), "--check",
                       "--flame", flame, "--top", "5"]) == 0
    out = capsys.readouterr().out
    assert "PROF CHECK: ok" in out and "host profile:" in out
    assert os.path.exists(flame)
    with open(flame) as f:
        html = f.read()
    assert "host sampling profile" in html and "lightgbm_tpu/x.py:f" in html


def test_cli_errors_exit_2(tmp_path, capsys):
    assert query_main(["prof", str(tmp_path / "missing.jsonl"),
                       "--check"]) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert query_main(["prof", str(empty)]) == 2    # no .jsonl inside
    capsys.readouterr()


# ------------------------------------------------------- burst / evidence
def test_burst_samples_other_threads_not_self():
    stop = threading.Event()

    def spin():
        x = 0
        while not stop.is_set():
            x += 1

    t = threading.Thread(target=spin, name="lgbm-test-busy", daemon=True)
    t.start()
    try:
        payload = burst(seconds=0.15, hz=200)
    finally:
        stop.set()
        t.join()
    assert payload["samples"] > 0
    assert payload["source"] == "burst"
    roles = {k.split(";", 1)[0] for k in payload["stacks"]}
    assert "lgbm-test-busy" in roles         # busy thread sampled
    assert "MainThread" not in roles         # the capturing thread is not
    text = folded_text(payload)
    assert text.startswith("# samples=")
    assert "lgbm-test-busy;" in text


def test_evidence_profile_prefers_live_window_else_bursts():
    class Obs:
        _run_context = {"stage": "boost"}

    payload = evidence_profile(Obs(), seconds=0.05)   # no armed profiler
    assert payload["source"] == "incident"

    payloads, emit = _collector()
    live = HostProfiler(emit=emit, hz=50, window_s=60.0)
    live.start()
    try:
        deadline = time.monotonic() + 5.0
        while live.peek()["samples"] == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        armed = Obs()
        armed._prof = live
        snap = evidence_profile(armed)
    finally:
        live.stop()
    assert snap["source"] == "train" and snap["samples"] > 0
    assert payloads == [] or snap["samples"] >= 0    # peek never flushes


# --------------------------------------------------------- observer wiring
def test_run_observer_arm_disarm_and_null_paths(tmp_path):
    assert NULL_OBSERVER.prof_arm() is None
    NULL_OBSERVER.prof_disarm()                      # no-op, no raise

    off = RunObserver(events_path=str(tmp_path / "off.jsonl"), prof_hz=0)
    off.run_header(backend="cpu", devices=[], params={}, context={})
    assert off.prof_arm() is None                    # hz=0 keeps it off
    off.close()
    assert profile_events(read_events(str(tmp_path / "off.jsonl"))) == []

    path = str(tmp_path / "on.jsonl")
    obs = RunObserver(events_path=path, prof_hz=100, prof_window_s=60.0)
    obs.run_header(backend="cpu", devices=[], params={}, context={})
    prof = obs.prof_arm()
    assert prof is not None and prof.running
    assert obs.prof_arm() is prof                    # idempotent
    assert "lgbm-obs-prof" in {t.name for t in threading.enumerate()}
    deadline = time.monotonic() + 5.0
    while prof.peek()["samples"] == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    obs.close()                                      # disarms + final flush
    assert not prof.running
    profs = profile_events(read_events(path))
    assert profs and profs[-1]["samples"] > 0


def test_phase_clock_current_transitions():
    from lightgbm_tpu.obs.timers import PhaseClock
    pc = PhaseClock(fence_laps=False)
    assert pc.current is None
    pc.begin()
    assert pc.current is None
    pc.lap("grow")
    assert pc.current == "grow"
    pc.lap("update")
    assert pc.current == "update"
    pc.end()
    assert pc.current is None


# --------------------------------------------------------- end-to-end runs
def test_training_run_emits_schema_valid_profiles(tmp_path):
    rng = np.random.default_rng(7)
    X = rng.normal(size=(2000, 10))
    y = X @ rng.normal(size=10) + 0.1 * rng.normal(size=2000)
    path = str(tmp_path / "train.jsonl")
    params = {"objective": "regression", "verbose": -1, "num_leaves": 31,
              "obs_events_path": path, "obs_timing": "iter",
              "obs_prof_hz": 29, "obs_prof_window_s": 0.5}
    lgb.train(params, lgb.Dataset(X, label=y, params=params),
              num_boost_round=30)
    evs = read_events(path)                 # schema-validates everything
    profs = profile_events(evs)
    assert profs, "a training run lands >= 1 prof_profile window"
    m = merged_profile(profs)
    assert m["samples"] > 0
    top_stack = max(m["stacks"].items(), key=lambda kv: (kv[1], kv[0]))[0]
    assert "lightgbm_tpu/" in top_stack
    assert m["overhead_frac"] < OVERHEAD_BUDGET_FRAC
    assert check_profiles(evs) == []
    assert "MainThread" in m["roles"]
    # every sample was stage-tagged from the live run context
    assert sum(m["stages"].values()) == m["samples"]
    # the ledger gates the same overhead number as a recorded cell
    from lightgbm_tpu.obs.ledger import metrics_from_events
    frac = metrics_from_events(evs).get("prof_overhead_frac")
    assert frac is not None and frac < OVERHEAD_BUDGET_FRAC
    # reader side renders over the real run
    import io
    buf = io.StringIO()
    rollup = render_top(evs, top=5, out=buf)
    assert rollup["samples"] == m["samples"]
    assert "host profile:" in buf.getvalue()
    assert render_flame(evs, str(tmp_path / "flame.html")) > 0
    # the sampler thread died with the run
    assert "lgbm-obs-prof" not in {t.name for t in threading.enumerate()}


def test_profiler_off_by_obs_prof_hz_zero(tmp_path):
    rng = np.random.default_rng(11)
    X = rng.normal(size=(300, 5))
    y = X @ rng.normal(size=5)
    path = str(tmp_path / "off.jsonl")
    params = {"objective": "regression", "verbose": -1,
              "obs_events_path": path, "obs_prof_hz": 0}
    lgb.train(params, lgb.Dataset(X, label=y, params=params),
              num_boost_round=3)
    evs = read_events(path)
    assert profile_events(evs) == []
    assert check_profiles(evs) == []        # off is a pass, not a wedge


def test_serve_load_zero_sheds_zero_steady_compiles(tmp_path):
    from lightgbm_tpu.serve import ServingPredictor
    rng = np.random.default_rng(3)
    X = rng.normal(size=(600, 8))
    w = rng.normal(size=8)
    y = (X @ w > 0).astype(np.float64)
    p = {"objective": "binary", "verbose": -1, "num_leaves": 15,
         "min_data_in_leaf": 5}
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p),
                    num_boost_round=10)
    path = str(tmp_path / "serve.jsonl")
    obs = RunObserver(events_path=path, prof_hz=97, prof_window_s=0.5)
    obs.run_header(backend="cpu", devices=[], params={}, context={})
    obs.prof_arm()
    errs = []
    with ServingPredictor(bst._gbdt, max_delay_ms=1.0, observer=obs,
                          queue_limit=256) as sp:
        # warm every bucket coalesced load can land on: 4 submitters x
        # 40 rows microbatch into up to 160-row batches
        sp.cache.warmup(sizes=[64, 128, 256])
        sp.cache.mark_warm()
        warm_compiles = sp.cache.compiles
        stop = threading.Event()

        def pound():
            try:
                while not stop.is_set():
                    sp.predict(X[:40])
            except Exception as e:          # pragma: no cover - fail loud
                errs.append(e)

        threads = [threading.Thread(target=pound,
                                    name="lgbm-test-load-%d" % i)
                   for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.6)
        stop.set()
        for t in threads:
            t.join()
        assert errs == []
        # the profiler rode along: no new compiles, nothing shed
        assert sp.cache.compiles == warm_compiles
        assert sp.scheduler.stats()["shed_total"] == 0
    obs.close()
    evs = read_events(path)
    profs = profile_events(evs)
    assert profs
    m = merged_profile(profs)
    assert m["samples"] > 0
    assert not m["errors"]
    # role attribution: the serve worker carries its stable thread name
    assert any("microbatch" in role for role in m["roles"])
