"""docs/Parameters.md must stay in sync with the config registry."""
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def test_parameters_doc_is_current(tmp_path):
    doc = os.path.join(REPO, "docs", "Parameters.md")
    with open(doc) as f:
        committed = f.read()
    out = str(tmp_path / "Parameters.md")   # never mutate the checkout
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    subprocess.run([sys.executable,
                    os.path.join(REPO, "tools", "gen_params_doc.py"), out],
                   check=True, env=env, cwd=REPO)
    with open(out) as f:
        regenerated = f.read()
    assert committed == regenerated, (
        "docs/Parameters.md is stale — run tools/gen_params_doc.py")


def test_every_registry_key_documented():
    """Every ACCEPTED parameter — the typed field table AND the
    PARAMETER_SET-only keys — must have a row."""
    from lightgbm_tpu.utils.config import PARAMETER_SET, Config
    with open(os.path.join(REPO, "docs", "Parameters.md")) as f:
        text = f.read()
    keys = set(Config._FIELDS) | set(PARAMETER_SET)
    missing = [k for k in sorted(keys) if "| %s |" % k not in text]
    assert not missing, "undocumented parameters: %s" % missing


def test_python_api_doc_is_current(tmp_path):
    doc = os.path.join(REPO, "docs", "Python-API.md")
    with open(doc) as f:
        committed = f.read()
    out = str(tmp_path / "Python-API.md")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    subprocess.run([sys.executable,
                    os.path.join(REPO, "tools", "gen_api_doc.py"), out],
                   check=True, env=env, cwd=REPO)
    with open(out) as f:
        regenerated = f.read()
    assert committed == regenerated, (
        "docs/Python-API.md is stale — run tools/gen_api_doc.py")
