"""A/B timing of wave-growth histogram modes on the current backend.

Usage: python tools/bench_modes.py [n_rows] [mode ...]
Modes are tpu_histogram_mode values ('onehot', 'pallas', ...).
Prints s/iter + AUC per mode at the 255-leaf, 63-bin recipe.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_data(n_rows, n_features=28):
    rng = np.random.default_rng(42)
    X = rng.normal(size=(n_rows, n_features)).astype(np.float32)
    w = rng.normal(size=n_features) * (rng.random(n_features) > 0.3)
    logit = X @ w * 0.5 + 0.5 * rng.normal(size=n_rows)
    return X, (logit > 0).astype(np.float64)


def run(X, y, mode, wave_width=32, warmup=3, measured=10,
        extra=None, train_set=None, details=False):
    """Time one engine config; X/y are ignored when a prebuilt train_set
    (e.g. loaded from a .bin dataset cache) is passed instead.  The ONE
    copy of the measurement protocol (warmup -> block -> timed loop ->
    block) — tpu_ab2 and bench_suite both go through it.  details=True
    additionally returns the trained GBDT for learner introspection."""
    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.common import enable_compilation_cache
    enable_compilation_cache()   # wedge retries skip recompiles
    params = {"objective": "binary", "num_leaves": 255, "max_bin": 63,
              "learning_rate": 0.1, "min_data_in_leaf": 1, "verbose": -1,
              "metric": "auc", "tpu_growth": "wave",
              "tpu_wave_width": wave_width, "tpu_histogram_mode": mode}
    params.update(extra or {})
    # the per-iteration times come from the obs timeline (obs_timing=iter
    # fences once per iteration, so they sum to the fenced end-to-end
    # time) unless the caller routed the events elsewhere via extra
    params.setdefault("obs_events_path",
                      "/tmp/bench_modes_obs_%d.jsonl" % os.getpid())
    params.setdefault("obs_timing", "iter")
    if train_set is None:
        train_set = lgb.Dataset(X, label=y, params=params)
    else:
        train_set.params = dict(train_set.params or {}, **params)
    bst = lgb.Booster(params=params, train_set=train_set)
    gbdt = bst._gbdt
    for _ in range(warmup):
        gbdt.train_one_iter(None, None, False)
    jax.block_until_ready(gbdt._score_dev)
    t0 = time.time()
    for _ in range(measured):
        gbdt.train_one_iter(None, None, False)
    jax.block_until_ready(gbdt._score_dev)
    dt = (time.time() - t0) / measured
    # prefer the telemetry: same instrument as bench.py's headline number
    timeline = gbdt._obs.timeline
    iter_recs = [e for e in timeline
                 if e["ev"] == "iter" and e.get("fenced")]
    if len(iter_recs) >= warmup + measured:
        dt = sum(e["time_s"] for e in iter_recs[-measured:]) / measured
    gbdt._obs.close()
    metric = gbdt.get_eval_at(0)[0]
    if details:
        return dt, metric, gbdt
    return dt, metric


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    modes = sys.argv[2:] or ["onehot", "pallas"]
    X, y = make_data(n)
    for mode in modes:
        t0 = time.time()
        dt, auc = run(X, y, mode)
        total = time.time() - t0
        print("%s: %.3f s/iter (%.2f it/s)  auc=%.4f  [wall %.0fs]"
              % (mode, dt, 1.0 / dt, auc, total), flush=True)


if __name__ == "__main__":
    main()
