"""Training-quality parity vs the reference, pinned.

PARITY_TRAINING.json holds head-to-head metrics produced by
tools/gen_parity.py (reference CLI and lightgbm_tpu trained on the golden
data AND deterministic synthetic sets with identical configs, same metric
code on both prediction sets — the docs/GPU-Performance.md:134-145
CPU-vs-GPU accuracy pattern).

This test retrains OUR side and asserts (a) we still reproduce our own
committed numbers (training determinism / no silent regression) and
(b) we remain within tolerance of the committed REFERENCE numbers.
When a reference binary is available ($REF_LGBM or /tmp/refbuild/lightgbm)
the full live comparison can be regenerated with tools/gen_parity.py.
"""
import json
import os
import sys
import tempfile

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
GOLDEN = os.path.join(HERE, "data", "golden")
sys.path.insert(0, os.path.join(REPO, "tools"))

from parity_metrics import load_query, load_tsv  # noqa: E402

# |ours - reference| bound for exact (leaf-wise) growth; the committed
# table (PARITY_TRAINING.md) shows actual deltas <= 1.5e-3 except GOSS,
# whose gradient-sampling RNG consumption differs legitimately from the
# reference's (our committed GOSS quality is BETTER on both metrics)
REF_TOL = {"default": 2e-3, "dart": 3e-3, "goss": 2.5e-2}
# reproducibility bound vs our own committed numbers (fp noise only)
SELF_TOL = 5e-6


def _committed():
    path = os.path.join(REPO, "PARITY_TRAINING.json")
    with open(path) as f:
        return json.load(f)


@pytest.mark.parametrize("task", ["binary", "regression", "multiclass",
                                  "lambdarank", "dart", "goss",
                                  "infiniteboost"])
def test_training_quality_parity(task):
    from gen_parity import TASKS, _data_paths, run_ours
    table = _committed()[task]
    spec = TASKS[task]
    train, test = _data_paths(task, spec, {})
    y, _ = load_tsv(test)
    qpath = test + ".query"
    q = load_query(qpath) if os.path.exists(qpath) else None
    with tempfile.TemporaryDirectory() as tmp:
        pred = run_ours(task, spec, tmp, train, test)
    got = spec["metrics"](y, pred, q)
    tol = REF_TOL.get(task, REF_TOL["default"])
    for metric, ref_val in table["reference"].items():
        mine = got[metric]
        committed_mine = table["lightgbm_tpu"][metric]
        assert abs(mine - committed_mine) < SELF_TOL, (
            "%s/%s drifted from committed value: %.6f vs %.6f"
            % (task, metric, mine, committed_mine))
        assert abs(mine - ref_val) < tol, (
            "%s/%s out of parity with reference: %.6f vs %.6f"
            % (task, metric, mine, ref_val))


def test_sparse_synthetic_parity_pin():
    """The 95%-sparse synthetic task, both engines: the dense default and
    the tpu_sparse device store must reproduce their committed numbers,
    and the sparse store must stay within tolerance of the committed
    reference (its committed logloss delta is 1.5e-6 — the store mirrors
    the reference's SparseBin behavior almost exactly)."""
    from gen_parity import SYNTHETIC_TASKS, _gen_synthetic, run_ours
    table = _committed()["sparse95"]
    spec = SYNTHETIC_TASKS["sparse95"]
    with tempfile.TemporaryDirectory() as tmp:
        train, test = _gen_synthetic(tmp)["sparse95"]
        y, _ = load_tsv(test)
        pred = run_ours("sparse95", spec, tmp, train, test)
        pred_sp = run_ours("sparse95", spec, tmp, train, test,
                           spec["extra_arms"]["tpu_sparse"])
    got = spec["metrics"](y, pred, None)
    got_sp = spec["metrics"](y, pred_sp, None)
    for metric in table["reference"]:
        assert abs(got[metric]
                   - table["lightgbm_tpu"][metric]) < SELF_TOL
        assert abs(got_sp[metric]
                   - table["lightgbm_tpu_tpu_sparse"][metric]) < SELF_TOL
        assert abs(got_sp[metric]
                   - table["reference"][metric]) < REF_TOL["default"]
