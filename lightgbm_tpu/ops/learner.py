"""Leaf-wise (best-first) tree learner driving the XLA ops.

Parity target: src/treelearner/serial_tree_learner.cpp:168-223 — the same
grow loop (root sums -> repeat: construct smaller-leaf histogram, derive the
larger leaf by subtraction (feature_histogram.hpp:63-69), best-split scan,
split the winning leaf) with the device doing all O(N) work:

* histograms: ops.histogram.leaf_histogram (masked scatter / one-hot matmul);
* split search: ops.split_finder.find_best_split (whole-histogram scan);
* partition: ops.partition.apply_split (masked leaf_id rewrite).

The host keeps only the tiny per-leaf bookkeeping (sums, gains, tree arrays),
mirroring how the GPU learner kept control flow on CPU
(gpu_tree_learner.cpp:977-1072).  Under data-parallel sharding the same code
runs unchanged: the histogram reduction becomes a psum across the row-sharded
mesh (see parallel/mesh.py), which is the reference's ReduceScatter path
(data_parallel_tree_learner.cpp:148-222) collapsed into XLA collectives.

Bagging and GOSS enter through ``row_mult`` — a per-row multiplier folded
into histogram weights, replacing bag-index re-partitioning
(gbdt.cpp:265-324).
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..io.dataset import TrainingData
from ..models.tree import Tree
from ..utils.config import Config
from ..utils.random import Random
from .histogram import leaf_histogram, leaf_sums
from .partition import apply_split
from .split_finder import (DEFAULT_BIN_FOR_ZERO, FEATURE, GAIN, IS_CAT,
                           LEFT_COUNT, LEFT_OUTPUT, LEFT_SUM_G, LEFT_SUM_H,
                           RIGHT_COUNT, RIGHT_OUTPUT, RIGHT_SUM_G, RIGHT_SUM_H,
                           THRESHOLD, FeatureMeta, SplitParams, find_best_split)


class SerialTreeLearner:
    """One tree per call; reused across iterations (TreeLearner::Train)."""

    def __init__(self, config: Config, train_data: TrainingData):
        self.config = config
        self.train_data = train_data
        self.num_leaves = config.num_leaves
        self.max_depth = config.max_depth
        self.dtype = jnp.float64 if config.tpu_use_dp else jnp.float32
        self.num_bins = int(train_data.num_bin_arr.max()) if train_data.num_features else 2
        self.X = jnp.asarray(train_data.binned)
        self.meta = FeatureMeta(
            num_bin=jnp.asarray(train_data.num_bin_arr),
            default_bin=jnp.asarray(train_data.default_bin_arr),
            is_categorical=jnp.asarray(train_data.is_categorical_arr),
        )
        self.params = SplitParams(
            lambda_l1=float(config.lambda_l1),
            lambda_l2=float(config.lambda_l2),
            min_gain_to_split=float(config.min_gain_to_split),
            min_data_in_leaf=float(config.min_data_in_leaf),
            min_sum_hessian_in_leaf=float(config.min_sum_hessian_in_leaf),
            use_missing=bool(config.use_missing),
        )
        self.hist_mode = config.tpu_histogram_mode
        # feature_fraction RNG persists across trees
        # (serial_tree_learner.cpp:40-96 Init + :257-275 BeforeTrain)
        self._feature_rng = Random(config.feature_fraction_seed)
        self.leaf_id: Optional[jnp.ndarray] = None

    # ------------------------------------------------------------ internals
    def _sample_features(self) -> np.ndarray:
        f = self.train_data.num_features
        mask = np.ones(f, dtype=bool)
        if self.config.feature_fraction < 1.0:
            used_cnt = int(f * self.config.feature_fraction)
            idx = self._feature_rng.sample(f, used_cnt)
            mask[:] = False
            mask[idx] = True
        return mask

    def _depth_ok(self, depth: int) -> bool:
        return self.max_depth <= 0 or depth < self.max_depth

    # ----------------------------------------------------------------- train
    def train(self, grad, hess, row_mult=None) -> Tuple[Tree, jnp.ndarray]:
        """Grow one tree; returns (tree, final per-row leaf assignment)."""
        td = self.train_data
        n = td.num_data
        grad = jnp.asarray(grad, self.dtype)
        hess = jnp.asarray(hess, self.dtype)
        if row_mult is not None:
            row_mult = jnp.asarray(row_mult, self.dtype)
        feature_mask = jnp.asarray(self._sample_features())

        leaf_id = jnp.zeros(n, dtype=jnp.int32)
        tree = Tree(self.num_leaves)
        if td.num_features == 0:
            return tree, leaf_id

        root = np.asarray(leaf_sums(grad, hess, leaf_id, 0, row_mult))
        hists: Dict[int, jnp.ndarray] = {}
        bests: Dict[int, np.ndarray] = {}
        sums: Dict[int, Tuple[float, float, float]] = {0: tuple(root)}

        hists[0] = leaf_histogram(self.X, grad, hess, leaf_id, 0, row_mult,
                                  self.num_bins, self.hist_mode)
        bests[0] = np.asarray(find_best_split(
            hists[0], root[0], root[1], root[2], self.meta, feature_mask,
            self.params))
        if not self._depth_ok(0):
            bests[0][GAIN] = -np.inf

        for _ in range(self.num_leaves - 1):
            # global best leaf (ArgMax over best_split_per_leaf_,
            # serial_tree_learner.cpp:203)
            best_leaf, best_gain = -1, 0.0
            for leaf, b in bests.items():
                if np.isfinite(b[GAIN]) and b[GAIN] > best_gain:
                    best_leaf, best_gain = leaf, b[GAIN]
            if best_leaf < 0:
                break
            info = bests.pop(best_leaf)
            inner_f = int(info[FEATURE])
            thr_bin = int(info[THRESHOLD])
            dbz = int(info[DEFAULT_BIN_FOR_ZERO])
            is_cat = bool(info[IS_CAT])
            mapper = td.feature_bin_mapper(inner_f)
            default_bin = mapper.default_bin
            real_f = td.real_feature_index(inner_f)
            # default_value only differs from 0 when the zero bin moved
            # (serial_tree_learner.cpp:546-549)
            default_value = 0.0
            if default_bin != dbz:
                default_value = td.real_threshold(inner_f, dbz)

            right_leaf = tree.split(
                best_leaf, inner_f, is_cat, thr_bin, real_f,
                td.real_threshold(inner_f, thr_bin),
                float(info[LEFT_OUTPUT]), float(info[RIGHT_OUTPUT]),
                int(info[LEFT_COUNT]), int(info[RIGHT_COUNT]),
                float(info[GAIN]), default_bin, dbz, default_value)

            default_left = (dbz == thr_bin) if is_cat else (dbz <= thr_bin)
            leaf_id = apply_split(self.X, leaf_id, best_leaf, inner_f, thr_bin,
                                  default_bin, default_left, is_cat, right_leaf)

            left_sums = (float(info[LEFT_SUM_G]), float(info[LEFT_SUM_H]),
                         float(info[LEFT_COUNT]))
            right_sums = (float(info[RIGHT_SUM_G]), float(info[RIGHT_SUM_H]),
                          float(info[RIGHT_COUNT]))
            sums[best_leaf] = left_sums
            sums[right_leaf] = right_sums

            if tree.num_leaves >= self.num_leaves:
                break

            # smaller child scanned, larger derived by subtraction
            # (serial_tree_learner.cpp:452-534)
            if info[LEFT_COUNT] < info[RIGHT_COUNT]:
                small, large = best_leaf, right_leaf
            else:
                small, large = right_leaf, best_leaf
            parent_hist = hists.pop(best_leaf)
            hist_small = leaf_histogram(self.X, grad, hess, leaf_id, small,
                                        row_mult, self.num_bins, self.hist_mode)
            hist_large = parent_hist - hist_small
            hists[small] = hist_small
            hists[large] = hist_large

            depth = tree.depth_of_leaf(best_leaf)
            for child, hist in ((small, hist_small), (large, hist_large)):
                sg, sh, sc = sums[child]
                b = np.asarray(find_best_split(
                    hist, sg, sh, sc, self.meta, feature_mask, self.params))
                if not self._depth_ok(depth):
                    b[GAIN] = -np.inf
                bests[child] = b

        self.leaf_id = leaf_id
        return tree, leaf_id

    # ------------------------------------------------------------ DART refit
    def fit_by_existing_tree(self, tree: Tree, grad, hess) -> Tree:
        """Refit leaf outputs of an existing structure on new gradients
        (SerialTreeLearner::FitByExistingTree, serial_tree_learner.cpp:225-250).
        """
        leaves = self._leaf_index_binned(tree)
        grad = np.asarray(grad, dtype=np.float64)
        hess = np.asarray(hess, dtype=np.float64)
        l1, l2 = self.config.lambda_l1, self.config.lambda_l2
        for leaf in range(tree.num_leaves):
            m = leaves == leaf
            sum_g = grad[m].sum()
            sum_h = hess[m].sum()
            reg = max(abs(sum_g) - l1, 0.0)
            out = -np.sign(sum_g) * reg / (sum_h + l2 + 1e-15)
            tree.set_leaf_value(leaf, out)
        return tree

    def _leaf_index_binned(self, tree: Tree) -> np.ndarray:
        binned = self.train_data.binned
        n = binned.shape[0]
        if tree.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        node = np.zeros(n, dtype=np.int32)
        active = node >= 0
        while active.any():
            idx = np.nonzero(active)[0]
            nd = node[idx]
            b = binned[idx, tree.split_feature_inner[nd]].astype(np.int64)
            th = tree.threshold_in_bin[nd]
            is_cat = tree.decision_type[nd] == 1
            go_left = np.where(is_cat, b == th, b <= th)
            is_def = b == tree.zero_bin[nd]
            dbz = tree.default_bin_for_zero[nd]
            def_left = np.where(is_cat, dbz == th, dbz <= th)
            go_left = np.where(is_def, def_left, go_left)
            node[idx] = np.where(go_left, tree.left_child[nd], tree.right_child[nd])
            active = node >= 0
        return (~node).astype(np.int32)
