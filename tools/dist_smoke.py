"""CI pod smoke: 2-rank subprocess train -> obs merge -> bit-identity,
then the kill-one-rank elastic drill (.github/workflows/ci.yml dist-obs).

Exit 0 is "the machinery works on this runner": where jaxlib's CPU
client can't run multi-process mesh programs (MultiprocessUnsupported —
the same limit the subprocess tests skip on) the subprocess leg prints
a notice and the drill falls back to thread-mode ranks, so the
detect -> flight-record -> shrink -> resume mechanism is still proven
and still leaves artifacts.  Any assertion failure exits nonzero.

Artifacts land under --out (default /tmp/dist_obs): per-rank pod
timelines + merged view, the elastic flight record, and the drill's
resumed timeline shards.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def pod_smoke(out):
    """2 real processes, one pod; every rank must build the same model,
    and the per-rank timelines must merge into one valid view."""
    from lightgbm_tpu.parallel.launch import (MultiprocessUnsupported,
                                              run_ranks_subprocess)
    base = os.path.join(out, "pod.jsonl")
    payload = {"rows": 1024, "cols": 6, "num_rounds": 3, "seed": 2,
               "obs_path": base,
               "params": {"tree_learner": "data"}}
    try:
        res = run_ranks_subprocess(
            2, "lightgbm_tpu.parallel.worker:train_worker", payload,
            local_devices=2, timeout=420.0)
    except MultiprocessUnsupported as e:
        print("dist_smoke: pod leg skipped (%s)" % e)
        return False
    digests = {r["digest"] for r in res}
    assert len(digests) == 1, "pod ranks disagree: %s" % digests
    print("dist_smoke: 2-rank pod bit-identical (digest %s)"
          % digests.pop())

    from lightgbm_tpu.obs.merge import (discover_shards, load_shards,
                                        merge_shards)
    ranks = load_shards(discover_shards(base + ".r0"))
    assert set(ranks) == {0, 1}, "expected 2 timeline shards"
    merged, report = merge_shards(ranks)
    assert report["world_size"] == 2 and report["ranks"] == [0, 1]
    mpath = os.path.join(out, "merged_pod.jsonl")
    with open(mpath, "w") as f:
        for e in merged:
            f.write(json.dumps(e) + "\n")
    print("dist_smoke: merged pod timeline -> %s (%d events)"
          % (mpath, len(merged)))
    return True


def elastic_drill(out, subprocess_ok):
    """Kill rank 1 mid-run; resume must reach the uninterrupted tree
    count and record the mesh shrink."""
    from lightgbm_tpu.parallel import worker
    from lightgbm_tpu.parallel.comm import SingleProcessComm
    from lightgbm_tpu.parallel.elastic import (run_elastic,
                                               run_elastic_threads)
    ckdir = os.path.join(out, "elastic")
    os.makedirs(ckdir, exist_ok=True)
    obs = os.path.join(ckdir, "drill.jsonl")
    payload = {"rows": 512, "cols": 5, "num_rounds": 5, "seed": 4,
               "checkpoint_dir": ckdir, "checkpoint_every": 1,
               "kill_rank": 1, "kill_iter": 2, "obs_path": obs}
    if subprocess_ok:
        payload["params"] = {"tree_learner": "data"}
        result = run_elastic(
            2, "lightgbm_tpu.parallel.worker:train_worker", payload,
            timeout=420.0)
    else:
        payload.update(kill_hard=False,
                       params={"tree_learner": "serial"})
        result = run_elastic_threads(
            2, lambda comm: worker.train_worker(comm, payload),
            barrier_timeout=60.0)
    assert result["attempts"] == 2 and result["world_size"] == 1, result
    assert result["flight_records"], "no flight record of the lost rank"
    fpath = os.path.join(out, "elastic_flight.json")
    with open(fpath, "w") as f:
        json.dump(result["flight_records"], f, indent=2)

    ref = worker.train_worker(
        SingleProcessComm(),
        {"rows": 512, "cols": 5, "num_rounds": 5, "seed": 4,
         "params": dict(payload["params"])})
    got = [r["num_trees"] for r in result["results"]]
    assert got == [ref["num_trees"]], \
        "resumed run finished %s trees, uninterrupted %d" \
        % (got, ref["num_trees"])

    from lightgbm_tpu.obs import read_events
    evs = []
    for name in sorted(os.listdir(ckdir)):
        if name.startswith("drill.jsonl"):
            evs += read_events(os.path.join(ckdir, name), validate=False)
    shrink = [e for e in evs if e.get("ev") == "mesh_shrink"]
    assert shrink, "resumed timeline has no mesh_shrink event"
    print("dist_smoke: elastic drill ok — %d trees after shrink %d->%d, "
          "flight record -> %s"
          % (got[0], shrink[0]["world_size_from"],
             shrink[0]["world_size_to"], fpath))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="/tmp/dist_obs")
    args = ap.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)
    subprocess_ok = pod_smoke(args.out)
    elastic_drill(args.out, subprocess_ok)
    print("dist_smoke: ok (subprocess pod %s)"
          % ("ran" if subprocess_ok else "unsupported on this runner"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
