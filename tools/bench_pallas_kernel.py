"""Microbench: wave_histogram_pallas vs the XLA one-hot contraction.

Times ONLY the histogram op (K children) and the partition-style scan,
to locate where the end-to-end pallas-mode regression comes from.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def timeit(fn, *args, reps=10, vary=None):
    """vary: index of an f32 arg to scale per-rep (defeats the axon
    tunnel's identical-dispatch dedup)."""
    def call(i):
        a = list(args)
        if vary is not None:
            a[vary] = a[vary] * (1.0 + 0.001 * i)
        return fn(*a)

    def force(o):
        # axon block_until_ready is unreliable; pull one scalar to host
        leaves = jax.tree_util.tree_leaves(o)
        return float(jnp.sum(leaves[0].astype(jnp.float32).ravel()[:8]))

    def chain(k):
        # k reps chained by a data dependency (each rep's vary-arg is
        # perturbed by the previous output), ONE readback at the end —
        # amortizes the tunnel round-trip out of the per-rep time
        # all per-rep scalars live on device: a fresh Python constant would
        # trigger a fresh eager compile (seconds each over the tunnel)
        eps = jnp.float32(0.0)
        one = jnp.float32(1.0)
        tiny = jnp.float32(1e-6)
        nano = jnp.float32(1e-9)
        step = jnp.float32(0.001)
        i_dev = jnp.float32(1.0)
        for i in range(k):
            a = list(args)
            if vary is not None:
                a[vary] = a[vary] * (one + tiny * eps + step * i_dev)
            o = fn(*a)
            lv = jax.tree_util.tree_leaves(o)[0]
            eps = jnp.sum(lv.astype(jnp.float32).ravel()[:8]) * nano
            i_dev = i_dev + one
        return float(eps)

    chain(1)
    t0 = time.time()
    chain(1)
    t1 = time.time()
    chain(1 + reps)
    t2 = time.time()
    return ((t2 - t1) - (t1 - t0)) / reps


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    fc, b, k = 28, 63, 32
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.integers(0, b, size=(n, fc), dtype=np.uint8))
    leaf_id = jnp.asarray(rng.integers(0, 255, size=n, dtype=np.int32))
    w3 = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32))
    cid = jnp.asarray(np.arange(k, dtype=np.int32))

    from lightgbm_tpu.ops.pallas_wave import wave_histogram_pallas

    t = timeit(jax.jit(lambda *a: wave_histogram_pallas(*a, num_bins=b)),
               X, leaf_id, w3, cid, vary=2)
    print("pallas kernel: %.2f ms" % (t * 1e3), flush=True)

    # XLA equivalent: chunked scan, one-hot einsum (the wave_pass hist half)
    chunk = 16384
    nch = n // chunk

    @jax.jit
    def xla_hist(X, leaf_id, w3, cid):
        xb = X.reshape(nch, chunk, fc)
        lb = leaf_id.reshape(nch, chunk)
        wb = w3.reshape(nch, chunk, 3)

        def step(acc, args):
            xc, lc, wc = args
            match = (lc[:, None] == cid[None, :]).astype(jnp.float32)
            wmat = (match[:, :, None] * wc[:, None, :]).reshape(chunk, 3 * k)
            oh = jax.nn.one_hot(xc.astype(jnp.int32), b, dtype=jnp.bfloat16)
            return acc + jnp.einsum(
                "cq,cw->qw", oh.reshape(chunk, fc * b), wmat,
                preferred_element_type=jnp.float32), None

        acc, _ = jax.lax.scan(step, jnp.zeros((fc * b, 3 * k), jnp.float32),
                              (xb, lb, wb))
        return acc

    t = timeit(xla_hist, X, leaf_id, w3, cid, vary=2)
    print("xla scan hist: %.2f ms" % (t * 1e3), flush=True)

    # partition-only scan (the non-hist half of wave_pass in pallas mode)
    tbl = jnp.asarray(rng.normal(size=(255, 10)).astype(np.float32))

    @jax.jit
    def part_scan(X, leaf_id, tbl):
        xb = X.reshape(nch, chunk, fc)
        lb = leaf_id.reshape(nch, chunk)
        l_iota = jnp.arange(255, dtype=jnp.int32)
        f_iota = jnp.arange(fc, dtype=jnp.int32)

        def step(_, args):
            xc, lc = args
            leaf_oh = (lc[:, None] == l_iota[None, :]).astype(jnp.float32)
            r = jnp.matmul(leaf_oh, tbl,
                           precision=jax.lax.Precision.HIGHEST)
            cj = r[:, 1].astype(jnp.int32)
            colv = jnp.sum(jnp.where(cj[:, None] == f_iota[None, :], xc, 0)
                           .astype(jnp.int32), axis=1)
            lc2 = jnp.where(colv <= r[:, 2].astype(jnp.int32),
                            lc, r[:, 6].astype(jnp.int32))
            return _, lc2

        _, lid = jax.lax.scan(step, 0, (xb, lb))
        return lid

    t = timeit(part_scan, X, leaf_id, tbl, vary=2)
    print("partition scan: %.2f ms" % (t * 1e3), flush=True)


if __name__ == "__main__":
    main()
