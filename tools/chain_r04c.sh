#!/bin/bash
# Round-4 stage: the Yahoo-LTR shape's TPU arm, after chain_r04b frees
# the chip; budget-gated like the other follow-ups.
cd /root/repo || exit 1
LOG=/tmp/chain_r04.log
log() { echo "[chain4c] $(date -u +%F\ %T) $*" >> "$LOG"; }
log "armed (waits for chain_r04.sh AND chain_r04b.sh)"
# r04b can budget-exit while the MAIN chain still owns the chip — wait
# on both so the yahoo arm never contends with a running measurement
while pgrep -f "chain_r04\.sh" > /dev/null || \
      pgrep -f "chain_r04b\.sh" > /dev/null; do sleep 120; done
END=${CHAIN4C_END_EPOCH:-$(( $(date +%s) + 1800 ))}
[ "$(date +%s)" -ge "$(( END - 600 ))" ] && { log "no budget; exit"; exit 0; }
SUITE_DEADLINE_S=$(( END - $(date +%s) - 120 )) timeout $(( END - $(date +%s) )) \
  python tools/bench_suite.py yahoo >> "$LOG" 2>&1
log "yahoo arm rc=$?"
