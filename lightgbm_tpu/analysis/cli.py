"""``python -m lightgbm_tpu lint`` — drive graftlint from the shell.

    python -m lightgbm_tpu lint [paths...] [--check] [--json]
                                [--baseline lint_baseline.json]
                                [--write-baseline] [--rules]

Exit codes follow the bench_compare / ``obs --check`` convention:
0 clean, 1 findings, 2 internal analyzer error.  ``--check`` is the CI
spelling — identical analysis, but a non-empty result prints a one-line
verdict suited to a gate log.  Paths default to the whole package; a
path argument narrows the AST passes but never the whole-repo passes
(registry, doc freshness, tile-planner sweeps), which don't depend on
which files were selected.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from .core import (LintInternalError, discover_files, load_baseline,
                   rule_catalog, run_lint, write_baseline)


def _repo_root() -> str:
    """The directory that holds the lightgbm_tpu package (the repo
    checkout when run in-tree, site-packages otherwise)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def _render(findings, as_json: bool, stream) -> None:
    if as_json:
        json.dump({"findings": [f.as_dict() for f in findings]},
                  stream, indent=1, sort_keys=True)
        stream.write("\n")
        return
    for f in findings:
        loc = "%s:%d" % (f.file, f.line) if f.file else "<repo>"
        stream.write("%s: [%s/%s] %s\n" % (loc, f.pass_name, f.rule,
                                           f.message))
        if f.suggestion:
            stream.write("    -> %s\n" % f.suggestion)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu lint",
        description="graftlint: prove the hot-path invariants "
                    "statically (docs/StaticAnalysis.md)")
    p.add_argument("paths", nargs="*",
                   help="repo-relative files/dirs to lint "
                        "(default: the whole package)")
    p.add_argument("--check", action="store_true",
                   help="CI gate mode: terse verdict, exit 1 on any "
                        "finding")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings")
    p.add_argument("--baseline", default="",
                   help="checked-in baseline JSON; matching findings "
                        "are grandfathered")
    p.add_argument("--write-baseline", default="",
                   metavar="PATH",
                   help="write current findings to PATH and exit 0")
    p.add_argument("--rules", action="store_true",
                   help="list every rule id and exit")
    args = p.parse_args(argv)

    if args.rules:
        for rule, (pass_name, desc) in sorted(rule_catalog().items()):
            sys.stdout.write("%-24s %-10s %s\n" % (rule, pass_name,
                                                   desc))
        return 0

    root = _repo_root()
    files = None
    if args.paths:
        files = []
        for path in args.paths:
            rel = os.path.relpath(os.path.abspath(path), root)
            rel = rel.replace(os.sep, "/")
            if os.path.isdir(os.path.join(root, rel)):
                files.extend(f for f in discover_files(root)
                             if f.startswith(rel.rstrip("/") + "/"))
            else:
                files.append(rel)
    try:
        if args.baseline:
            # surface a corrupt baseline as exit 2 even with 0 findings
            load_baseline(args.baseline)
        findings = run_lint(root, files=files,
                            baseline_path=args.baseline)
        if args.write_baseline:
            write_baseline(args.write_baseline, findings)
            sys.stdout.write("wrote %d finding(s) to %s\n"
                             % (len(findings), args.write_baseline))
            return 0
    except LintInternalError as e:
        sys.stderr.write("lint: internal error: %s\n" % e)
        return 2

    _render(findings, args.as_json, sys.stdout)
    if findings:
        if args.check:
            sys.stdout.write("lint: FAIL — %d unsuppressed finding(s)\n"
                             % len(findings))
        return 1
    if args.check:
        sys.stdout.write("lint: clean\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
