"""Roofline attribution: achieved vs peak for every jitted entry.

The timeline has carried the two halves of a roofline model since PR 1
without ever joining them: ``compile_attr`` events record XLA's
``cost_analysis`` FLOPs / bytes-accessed estimates per compiled entry
(obs/compile.py), and ``run_end.entries`` records the measured
compile-vs-execute wall-time split (obs/timers.py).  This module closes
the loop against a device-peak registry:

    achieved FLOP/s   = flops / exec_mean_s
    achieved B/s      = bytes_accessed / exec_mean_s
    arithmetic intensity (AI) = flops / bytes_accessed
    roof_s   = max(flops / peak_flops, bytes / peak_hbm [, ici terms])
    headroom = (exec_mean_s - roof_s) * exec_n     # seconds recoverable

and classifies each entry as **compute**-, **memory**-,
**collective**- or **host-orchestration**-bound — the instrument the
GPU-GBDT literature (arxiv 1706.08359 frames histogram building as a
memory-bandwidth roofline problem) and the accelerator-design paper
(arxiv 2011.02022, per-stage utilization) both assume exists.

Three consumers:

* ``python -m lightgbm_tpu obs roofline RUN.jsonl [--check]``
  (obs/query.py) renders the headroom-ranked table; ``--check`` fails
  when the timeline is structurally unusable (no finished run, or no
  cost estimates at all — run with ``obs_compile=true``);
* ``RunObserver.iter_end`` emits a per-iteration ``utilization``
  rollup event (schema 13, ``obs_utilization_every``) whose
  ``flop_util`` / ``hbm_util`` feed the cross-run ledger and the
  ``bench_compare`` gate exactly like it/s;
* ``ops/autotune.py`` stamps every probed cell with its roofline
  position (``cell_roofline``) so ``obs explain`` can say *why* a
  winner won ("pallas_ct at 71% HBM vs pallas_t at 34%").

Peaks are **dataplane ceilings, not promises**: the table below holds
published per-chip figures for the TPU generations the wave engine
targets plus a deliberately modest CPU fallback profile so the whole
layer is testable off-TPU.  An unknown ``device_kind`` falls back with
``source="fallback"`` rather than failing — a wrong-but-labelled roof
still ranks entries correctly relative to each other.  Override or
extend via ``obs_roofline_peaks`` (a JSON file mapping device kinds to
profiles, merged over the defaults).
"""
from __future__ import annotations

import json
import sys

from ..utils.log import Log

# -- device-peak registry ------------------------------------------------
# Per-chip dataplane peaks keyed by normalized device_kind.  Fields:
#   flops_f32 / flops_bf16  peak FLOP/s by compute dtype (MXU)
#   hbm_bytes_per_s         main-memory bandwidth
#   ici_bytes_per_s         aggregate interconnect bandwidth per chip
#   vmem_bytes              on-chip vector memory
# Figures are the published per-chip numbers (bf16 MXU peak; f32 taken
# as half the bf16 rate where the generation has no native f32 MXU
# path).  They bound attribution, they do not certify hardware.
DEFAULT_PEAKS = {
    "tpu_v4": {
        "flops_f32": 137.5e12, "flops_bf16": 275e12,
        "hbm_bytes_per_s": 1228e9, "ici_bytes_per_s": 300e9,
        "vmem_bytes": 128 * 2**20,
    },
    "tpu_v5_lite": {
        "flops_f32": 98.5e12, "flops_bf16": 197e12,
        "hbm_bytes_per_s": 819e9, "ici_bytes_per_s": 400e9,
        "vmem_bytes": 128 * 2**20,
    },
    "tpu_v5p": {
        "flops_f32": 229.5e12, "flops_bf16": 459e12,
        "hbm_bytes_per_s": 2765e9, "ici_bytes_per_s": 600e9,
        "vmem_bytes": 128 * 2**20,
    },
    "tpu_v6_lite": {
        "flops_f32": 459e12, "flops_bf16": 918e12,
        "hbm_bytes_per_s": 1640e9, "ici_bytes_per_s": 448e9,
        "vmem_bytes": 128 * 2**20,
    },
    # off-TPU fallback: a deliberately modest single-socket profile so
    # CPU timelines (CI, tests) produce finite, clearly-labelled
    # utilization numbers instead of failing the join
    "cpu": {
        "flops_f32": 100e9, "flops_bf16": 100e9,
        "hbm_bytes_per_s": 25e9, "ici_bytes_per_s": 10e9,
        "vmem_bytes": 32 * 2**20,
    },
}

# aliases seen in the wild for jax's device_kind strings
_KIND_ALIASES = {
    "tpu_v5e": "tpu_v5_lite",
    "tpu_v5litepod": "tpu_v5_lite",
    "tpu_v6e": "tpu_v6_lite",
    "trillium": "tpu_v6_lite",
    "cpu_device": "cpu",
}

# below this fraction of EVERY roof the entry is dominated by dispatch /
# host glue, not the dataplane — the launch-overhead regime both GPU
# boosting papers single out (arxiv 1806.11248 §4, 1809.04559 §5)
ORCH_FLOOR = 0.02

BOUNDS = ("compute", "memory", "collective", "host-orchestration")


def normalize_kind(kind):
    """Canonical registry key for a raw ``device_kind`` string."""
    k = str(kind or "").strip().lower().replace(" ", "_").replace("-", "_")
    return _KIND_ALIASES.get(k, k)


def device_kind():
    """This process's device kind (autotune's cache key convention):
    ``jax.devices()[0].device_kind``, else the backend name."""
    try:
        import jax
        return str(jax.devices()[0].device_kind).strip().replace(" ", "_")
    except Exception:
        try:
            import jax
            return str(jax.default_backend())
        except Exception:
            return "cpu"


def load_peak_overrides(path):
    """Parse an ``obs_roofline_peaks`` JSON file: {kind: profile}."""
    if not path:
        return {}
    try:
        with open(path) as f:
            raw = json.load(f)
        return {normalize_kind(k): dict(v) for k, v in raw.items()
                if isinstance(v, dict)}
    except Exception as e:
        Log.warning("obs: roofline peak overrides %s unreadable: %s",
                    path, e)
        return {}


def peaks_for(kind, overrides=None):
    """The peak profile of ``kind`` with provenance attached.

    Resolution: exact normalized match in ``overrides``, then in the
    default table, then a prefix match against the defaults (a
    ``tpu_v5p_pod`` kind still finds ``tpu_v5p``), else the CPU
    fallback with ``source="fallback"`` — an unknown chip must degrade
    to labelled estimates, never to a crash.
    """
    nk = normalize_kind(kind)
    table = dict(DEFAULT_PEAKS)
    for k, v in (overrides or {}).items():
        base = dict(table.get(normalize_kind(k), DEFAULT_PEAKS["cpu"]))
        base.update(v)
        table[normalize_kind(k)] = base
    if nk in table:
        return dict(table[nk], kind=nk,
                    source=("override" if nk in (overrides or {})
                            else "table"))
    for k in table:
        if k != "cpu" and (nk.startswith(k) or k.startswith(nk)) and nk:
            return dict(table[k], kind=k, source="table")
    return dict(table["cpu"], kind=nk or "unknown", source="fallback")


# -- the per-entry join --------------------------------------------------

def entry_roofline(cost, exec_mean_s, exec_n, peaks, dtype="f32",
                   ici_bytes=0.0, world_size=1):
    """Join one entry's cost estimate with its measured execute time.

    ``cost`` is the ``compile_attr`` cost dict ({flops, bytes_accessed},
    either may be missing); an entry with no estimate at all classifies
    as host-orchestration with zero utilization — XLA saw nothing worth
    modelling, so dispatch is what its wall time buys.
    """
    cost = cost or {}
    flops = float(cost.get("flops") or 0.0)
    nbytes = float(cost.get("bytes_accessed") or 0.0)
    ici = float(ici_bytes or 0.0) if int(world_size or 1) > 1 else 0.0
    mean = max(float(exec_mean_s or 0.0), 0.0)
    peak_flops = float(peaks.get("flops_%s" % dtype)
                       or peaks.get("flops_f32") or 1.0)
    peak_hbm = float(peaks.get("hbm_bytes_per_s") or 1.0)
    peak_ici = float(peaks.get("ici_bytes_per_s") or 1.0)
    t_compute = flops / peak_flops
    t_memory = nbytes / peak_hbm
    t_ici = ici / peak_ici
    roof_s = max(t_compute, t_memory, t_ici)
    out = {
        "flops": flops, "bytes_accessed": nbytes,
        "achieved_flops_per_s": (flops / mean) if mean > 0 else 0.0,
        "achieved_bytes_per_s": (nbytes / mean) if mean > 0 else 0.0,
        "ai": (flops / nbytes) if nbytes > 0 else None,
        "flop_util": min(1.0, t_compute / mean) if mean > 0 else 0.0,
        "hbm_util": min(1.0, t_memory / mean) if mean > 0 else 0.0,
        "roof_s": roof_s,
        "headroom_s": max(0.0, mean - roof_s) * max(int(exec_n or 0), 0),
        "exec_mean_s": mean, "exec_n": int(exec_n or 0),
    }
    if ici > 0:
        out["ici_util"] = min(1.0, t_ici / mean) if mean > 0 else 0.0
    # bound: the tallest roof wins; under the floor on every roof the
    # entry is pinned by host orchestration, not the dataplane
    fracs = {"compute": out["flop_util"], "memory": out["hbm_util"]}
    if ici > 0:
        fracs["collective"] = out["ici_util"]
    bound = max(fracs, key=lambda k: fracs[k])
    if fracs[bound] < ORCH_FLOOR:
        bound = "host-orchestration"
    out["bound"] = bound
    return out


def _entry_costs(events):
    """{entry: cost dict} — the LAST compile_attr per entry wins (the
    steady-state program; early shape-warmup compiles are superseded)."""
    costs = {}
    for e in events:
        if e.get("ev") == "compile_attr" and e.get("cost"):
            costs[e.get("entry")] = e.get("cost")
    return costs


def _collective_bytes(events, entry):
    """Static per-call ICI byte estimate for ``entry`` from the
    ``collectives`` event, when the learner published one that names
    it; else 0 (the host cannot time collectives inside a program)."""
    for e in events:
        if e.get("ev") != "collectives":
            continue
        est = e.get("estimates") or {}
        if isinstance(est, dict):
            v = est.get(entry)
            if isinstance(v, (int, float)):
                return float(v)
        for key in ("psum", "allgather"):
            v = e.get(key)
            if isinstance(v, dict) and entry in str(v.get("entry", "")):
                b = v.get("bytes")
                if isinstance(b, (int, float)):
                    return float(b)
    return 0.0


def timeline_roofline(events, overrides=None, peaks_path=""):
    """The roofline join of ONE run's events (use query.last_run first).

    Returns ``{device_kind, peaks, rows, problems}`` where ``rows`` is
    headroom-ranked (most recoverable seconds first) and ``problems``
    lists the structural defects ``--check`` fails on.
    """
    if overrides is None:
        overrides = load_peak_overrides(peaks_path)
    problems = []
    header = next((e for e in events if e.get("ev") == "run_header"), {})
    kind = ""
    for d in header.get("devices") or ():
        if isinstance(d, dict) and d.get("kind"):
            kind = str(d["kind"])
            break
    kind = kind or str(header.get("backend", "") or "")
    world_size = int(header.get("world_size") or 1)
    peaks = peaks_for(kind, overrides)
    run_end = next((e for e in events if e.get("ev") == "run_end"), None)
    entries = (run_end or {}).get("entries") or {}
    if not entries:
        problems.append("no run_end entry stats on the timeline "
                        "(run did not finalize, or never timed an entry)")
    costs = _entry_costs(events)
    if entries and not costs:
        problems.append("no cost estimates on the timeline — run with "
                        "obs_compile=true so compile_attr events carry "
                        "cost_analysis")
    rows = []
    for name, st in entries.items():
        r = entry_roofline(
            costs.get(name), st.get("exec_mean_s", 0.0),
            st.get("exec_n", 0), peaks,
            ici_bytes=_collective_bytes(events, name),
            world_size=world_size)
        r["entry"] = name
        r["has_cost"] = name in costs
        r["exec_total_s"] = float(st.get("exec_total_s", 0.0))
        rows.append(r)
    rows.sort(key=lambda r: -r["headroom_s"])
    return {"device_kind": kind or "unknown", "peaks": peaks,
            "world_size": world_size, "rows": rows, "problems": problems}


# -- per-iteration rollup (the `utilization` event, schema 13) ----------

def utilization_rollup(entry_summary, costs, peaks, world_size=1):
    """Aggregate roofline position across entries for ONE iteration's
    ``utilization`` event: exec-time-weighted mean utilization plus the
    bound of the entry with the most absolute headroom.

    ``entry_summary`` is EntryTimers.summary() (mid-run snapshots work);
    ``costs`` is CompileTracker.costs().  Returns None when nothing can
    be said yet (no timed entries, or no cost estimate on any of them).
    """
    rows = []
    for name, st in (entry_summary or {}).items():
        if name not in costs:
            continue
        r = entry_roofline(costs.get(name), st.get("exec_mean_s", 0.0),
                           st.get("exec_n", 0), peaks,
                           world_size=world_size)
        r["entry"] = name
        r["weight"] = float(st.get("exec_total_s", 0.0))
        rows.append(r)
    if not rows:
        return None
    wsum = sum(r["weight"] for r in rows) or 1.0
    worst = max(rows, key=lambda r: r["headroom_s"])
    return {
        "flop_util": sum(r["flop_util"] * r["weight"] for r in rows) / wsum,
        "hbm_util": sum(r["hbm_util"] * r["weight"] for r in rows) / wsum,
        "headroom_s": sum(r["headroom_s"] for r in rows),
        "bound": worst["bound"],
        "device_kind": peaks.get("kind", "unknown"),
        "roof_source": peaks.get("source", "fallback"),
        "entries": {r["entry"]: {"flop_util": round(r["flop_util"], 6),
                                 "hbm_util": round(r["hbm_util"], 6),
                                 "bound": r["bound"]}
                    for r in rows},
    }


# -- the autotuner's analytic cell model --------------------------------

def cell_traffic(bucket, cell):
    """Static (flops, hbm_bytes) per wave of one autotune cell.

    The wave histogram pass reads every bucketed row's bin byte per
    column plus its gradient/hessian pair (8 B in exact hilo precision,
    4 B in the bf16 trade) and writes W padded (bins x cols) f32
    hi/lo histogram pairs; MXU work is the one-hot dot, 2 FLOPs per
    (row, col) MAC.  A static model — same spirit as the collectives
    event's byte estimates: shape arithmetic the host can do without
    timing anything inside the program.
    """
    n = float(getattr(bucket, "n_bucket", 0) or 0)
    ncols = float(getattr(bucket, "ncols", 0) or 0)
    bin_pad = float(getattr(bucket, "bin_pad", 0) or 0)
    width = float(getattr(cell, "wave_width", 1) or 1)
    gh_bytes = 4.0 if getattr(cell, "hist_hilo", True) is False else 8.0
    flops = 2.0 * n * ncols * max(width, 1.0)
    nbytes = (n * ncols                       # bin bytes, once per wave
              + n * gh_bytes * max(width, 1.0)  # grad/hess per sweep
              + width * bin_pad * ncols * 8.0)  # f32 hi+lo hist writes
    return flops, nbytes


def cell_roofline(bucket, cell, s_per_wave, kind=None, overrides=None):
    """The roofline stamp for one probed autotune cell: where its
    measured s/wave sits against this chip's compute and memory roofs.
    ops/autotune.py attaches this dict to every ``autotune_probe``
    event so ``obs explain`` can say why the winner won."""
    if kind is None:
        kind = device_kind()
    peaks = peaks_for(kind, overrides)
    flops, nbytes = cell_traffic(bucket, cell)
    r = entry_roofline({"flops": flops, "bytes_accessed": nbytes},
                       s_per_wave, 1, peaks)
    return {"flop_util": round(r["flop_util"], 4),
            "hbm_util": round(r["hbm_util"], 4),
            "ai": round(r["ai"], 3) if r["ai"] else None,
            "bound": r["bound"], "device_kind": peaks.get("kind"),
            "roof_source": peaks.get("source")}


# -- rendering -----------------------------------------------------------

def fmt_quantity(v, unit=""):
    """Humanize a count into K/M/G/T units (1e9 -> '1.00 G')."""
    v = float(v or 0.0)
    for thresh, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"),
                           (1e3, "K")):
        if abs(v) >= thresh:
            return "%.2f %s%s" % (v / thresh, suffix, unit)
    return "%.3g %s" % (v, unit) if unit else "%.3g" % v


def fmt_bytes(v):
    v = float(v or 0.0)
    for thresh, suffix in ((2**40, "TiB"), (2**30, "GiB"),
                           (2**20, "MiB"), (2**10, "KiB")):
        if abs(v) >= thresh:
            return "%.2f %s" % (v / thresh, suffix)
    return "%d B" % int(v)


def describe_roofline_position(r):
    """One clause for an autotune cell / entry stamp: '71% HBM' or
    '12% MXU' — the dominant roof, as obs explain prints it."""
    if not isinstance(r, dict):
        return ""
    bound = r.get("bound", "")
    if bound == "memory":
        return "%d%% HBM" % round(100 * float(r.get("hbm_util") or 0.0))
    if bound == "compute":
        return "%d%% MXU" % round(100 * float(r.get("flop_util") or 0.0))
    if bound == "collective":
        return "%d%% ICI" % round(100 * float(r.get("ici_util") or 0.0))
    if bound:
        top = max(float(r.get("hbm_util") or 0.0),
                  float(r.get("flop_util") or 0.0))
        return "%s, %d%% of roof" % (bound, round(100 * top))
    return ""


def render_roofline(events, out=None, check=False, peaks_path=""):
    """Print the headroom-ranked roofline table of the last run; return
    the problems list (``--check`` exits nonzero when non-empty)."""
    out = out or sys.stdout
    w = lambda s="": print(s, file=out)  # noqa: E731
    res = timeline_roofline(events, peaks_path=peaks_path)
    peaks = res["peaks"]
    w("== roofline: %s (%s peaks%s) ==" % (
        res["device_kind"], peaks.get("source", "?"),
        ", world_size=%d" % res["world_size"]
        if res["world_size"] > 1 else ""))
    w("  peak %sFLOP/s f32, %s/s HBM, %s/s ICI, %s VMEM" % (
        fmt_quantity(peaks.get("flops_f32")),
        fmt_bytes(peaks.get("hbm_bytes_per_s")),
        fmt_bytes(peaks.get("ici_bytes_per_s")),
        fmt_bytes(peaks.get("vmem_bytes"))))
    rows = res["rows"]
    if rows:
        w()
        w("  %-34s %5s %10s %6s %6s %8s %-18s %10s" % (
            "entry", "calls", "mean", "MXU%", "HBM%", "AI",
            "bound", "headroom"))
        for r in rows:
            w("  %-34s %5d %9.2fms %5.1f%% %5.1f%% %8s %-18s %9.3fs%s" % (
                r["entry"][:34], r["exec_n"], r["exec_mean_s"] * 1e3,
                100 * r["flop_util"], 100 * r["hbm_util"],
                ("%.2f" % r["ai"]) if r["ai"] is not None else "-",
                r["bound"], r["headroom_s"],
                "" if r["has_cost"] else "  (no cost estimate)"))
        total = sum(r["headroom_s"] for r in rows)
        w()
        w("  total headroom %.3fs across %d entries — seconds recoverable"
          " if every entry hit its roof" % (total, len(rows)))
        counts = {}
        for r in rows:
            counts[r["bound"]] = counts.get(r["bound"], 0) + 1
        w("  bound mix: " + ", ".join(
            "%s x%d" % (b, counts[b]) for b in BOUNDS if b in counts))
    for p in res["problems"]:
        w("  PROBLEM: %s" % p)
    return res["problems"] if check else []
