"""tools/bench_suite.py child protocol: shape generation is process-stable,
the measurement JSON contract holds, and the dataset cache round-trips."""
import contextlib
import io
import json
import os
import zlib

import numpy as np


def test_suite_child_json_contract(monkeypatch):
    import tools.bench_suite as bs

    name = "tinytest"
    monkeypatch.setitem(bs.SHAPES, name, dict(n=6000, f=6, params={
        "objective": "binary", "metric": "auc", "num_leaves": 15,
        "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 1},
        warmup=1, measured=2, timeout=300))
    cache = bs.cache_path(name)
    if os.path.exists(cache):
        os.remove(cache)
    try:
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            bs.child(name)
        res = json.loads(buf.getvalue().strip().splitlines()[-1])
        for key in ("dt", "metric", "mode", "growth", "order", "W",
                    "wall"):
            assert key in res, key
        assert res["dt"] > 0 and 0.5 < res["metric"] <= 1.0
        assert os.path.exists(cache)
        # second run loads the cache and must agree on the metric
        buf2 = io.StringIO()
        with contextlib.redirect_stdout(buf2):
            bs.child(name)
        res2 = json.loads(buf2.getvalue().strip().splitlines()[-1])
        assert res2["metric"] == res["metric"]
    finally:
        if os.path.exists(cache):
            os.remove(cache)


def test_suite_shapes_are_process_stable(monkeypatch):
    """The seed must be a stable content hash — Python's salted hash()
    would give the TPU and reference-CLI arms different data.  Pin the
    actual bytes so a regression to hash(name) (stable in-process but
    not across) cannot stay green."""
    import tools.bench_suite as bs
    monkeypatch.setitem(bs.SHAPES, "tiny2", dict(
        n=2000, f=4, params={}, warmup=0, measured=1, timeout=60))
    X, y, _ = bs.make_shape("tiny2")
    rng = np.random.default_rng(zlib.crc32(b"tiny2"))
    w = rng.normal(size=4) * (rng.random(4) > 0.3)
    Xe = rng.normal(size=(2000, 4)).astype(np.float32)
    np.testing.assert_array_equal(X, Xe)
    ye = ((Xe @ w * 0.4 + 0.6 * rng.normal(size=2000)) > 0)
    np.testing.assert_array_equal(y, ye.astype(np.float64))


def _run_main(monkeypatch, tmp_path, probe_results, child_behavior=None,
              names=("alpha", "beta"), deadline="30"):
    """Drive bench_suite.main() with a scripted probe and child."""
    import subprocess
    import sys
    import time

    import tools.bench_suite as bs
    import tools.tpu_ab2 as ab2

    for name in names:
        monkeypatch.setitem(bs.SHAPES, name, dict(n=100, f=2, params={},
                                                  warmup=0, measured=1,
                                                  timeout=5))
    out = tmp_path / "results.md"
    monkeypatch.setattr(bs, "OUT", str(out))
    monkeypatch.setenv("SUITE_DEADLINE_S", deadline)
    # fail fast on exhaustion: sleep is a no-op here, so a regression
    # that consumes probes off-pattern must raise, not hot-spin until
    # the deadline
    seq = iter(probe_results)
    monkeypatch.setattr(ab2, "probe_with_retries", lambda: next(seq))
    monkeypatch.setattr(time, "sleep", lambda s: None)

    calls = []

    def fake_run(args, **kw):
        name = args[-1]
        calls.append(name)
        if child_behavior == "timeout":
            raise subprocess.TimeoutExpired(args, 5)
        r = subprocess.CompletedProcess(args, 0)
        r.stdout = ('{"dt": 0.5, "metric": 0.9, "mode": "onehot", '
                    '"growth": "wave", "order": "batched", "W": 8}')
        r.stderr = ""
        return r
    monkeypatch.setattr(bs.subprocess, "run", fake_run)
    monkeypatch.setattr(sys, "argv", ["bench_suite.py"] + list(names))
    bs.main()
    return out.read_text(), calls


def test_suite_non_tpu_backend_counts_as_unreachable(monkeypatch,
                                                     tmp_path):
    """A transient CPU fallback must NOT start a measurement, and the
    outage line names the backend; one line per outage (dedup)."""
    monkeypatch.delenv("SUITE_ALLOW_CPU", raising=False)
    text, calls = _run_main(monkeypatch, tmp_path,
                            probe_results=["cpu", "cpu", None, None,
                                           "tpu", "tpu"])
    assert calls == ["alpha", "beta"]          # only after tpu came up
    assert text.count("non-tpu backend 'cpu'") == 1      # deduped
    assert "device back after" in text


def test_suite_timeout_gives_up_after_two(monkeypatch, tmp_path):
    """A deterministically-hanging shape re-queues once, then gives up
    instead of starving the shapes behind it."""
    text, calls = _run_main(monkeypatch, tmp_path,
                            probe_results=["tpu"] * 10,
                            child_behavior="timeout",
                            names=("alpha",))
    assert calls == ["alpha", "alpha"]         # exactly two attempts
    assert "TIMEOUT x2" in text and "giving up" in text
