"""Distributed tree learning over a device mesh — the Network layer reborn.

The reference distributes with a socket/MPI Allreduce stack
(src/network/network.cpp:23-185, linkers_socket.cpp) and three learner
subclasses (feature/data/voting parallel, src/treelearner/
*_parallel_tree_learner.cpp).  TPU-native, the whole Network layer collapses
into XLA collectives over an ICI mesh:

* data-parallel  — rows sharded, histograms psum'd inside the grow program
  (`lax.psum` == ReduceScatter+Allgather of HistogramBinEntry sums,
  data_parallel_tree_learner.cpp:148-222);
* feature-parallel — all rows everywhere, features sharded; only the best
  SplitInfo crosses devices (an argmax-reduce of the packed split vector,
  feature_parallel_tree_learner.cpp:52-76) plus one row-bitmask psum for
  the partition;
* voting-parallel — data-parallel with top-k histogram exchange: local
  top-k proposals by leaf-size-weighted gain, pmax-vote, psum of only the
  k selected histograms (voting_parallel_tree_learner.cpp:164-300) —
  per-leaf traffic drops from F*B*3 to top_k*B*3, the PV-Tree compression
  for DCN-spanning meshes.

Multi-host: `jax.distributed.initialize` + the same mesh spanning all
processes replaces machine_list_file/port handshakes (linkers_socket.cpp).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..io.dataset import TrainingData
from ..ops.grow import make_grow_fn
from ..ops.learner import SerialTreeLearner
from ..ops.wave import WAVE_ONLY_MODES
from ..ops.split_finder import FeatureMeta
from ..utils.config import Config
from ..utils.log import Log

DATA_AXIS = "data"
FEATURE_AXIS = "feature"


def make_data_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    # Device HANDLES (host metadata), not a device array — no transfer
    return Mesh(np.asarray(devices), (DATA_AXIS,))  # lint: ignore[sync-asarray]


def make_feature_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    # Device HANDLES (host metadata), not a device array — no transfer
    return Mesh(np.asarray(devices), (FEATURE_AXIS,))  # lint: ignore[sync-asarray]


def _shard_map_compat(fn, mesh, in_specs, out_specs, checked=True):
    """shard_map across jax versions (check_rep renamed check_vma, removed).

    checked=False disables the varying-manual-axes checker: the
    feature-parallel grower's all_gather'd SplitInfo fold is replicated by
    construction but the VMA analysis cannot prove it.
    """
    if not checked:
        for kw in ({"check_vma": False}, {"check_rep": False}, {}):
            try:
                return shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kw)
            except TypeError:
                continue
    try:
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs)
    except TypeError:
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)


def pad_rows(n: int, num_shards: int) -> int:
    """Rows padded so each shard holds the same count (XLA static shapes)."""
    return (-n) % num_shards


def make_row_sharded(mesh: Mesh, host_local: np.ndarray, extra_dims=0):
    """A row-sharded global jax.Array from host data.

    Single-process: a plain device_put.  Multi-process (jax.distributed
    initialized, the DCN path replacing linkers_socket.cpp): `host_local`
    is THIS process's row shard and the global array is assembled from the
    per-process shards — rows must already be padded so every process
    contributes the same count.
    """
    spec = P(DATA_AXIS, *([None] * extra_dims))
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(host_local, sharding)
    return jax.make_array_from_process_local_data(sharding, host_local)


def _per_tree_collective_bytes(learner) -> int:
    """Per-tree collective traffic from collective_info()'s per-reduce
    estimates x the number of reduces a tree issues (splits, or wave
    sweeps) — the increment train_device adds to the registry counter."""
    info = learner.collective_info()
    splits = max(int(learner.num_leaves) - 1, 1)
    total = 0
    for coll in ("psum", "allgather"):
        d = info.get(coll) or {}
        if "per_wave_bytes" in d:
            w = max(int(getattr(learner, "wave_width", 1) or 1), 1)
            total += d["per_wave_bytes"] * ((splits + w - 1) // w)
        elif "per_leaf_bytes" in d:
            total += d["per_leaf_bytes"] * splits
        elif "per_split_bytes" in d:
            total += d["per_split_bytes"] * splits
    return int(total)


def _init_collective_counter(learner, obs) -> None:
    """set_observer for distributed learners: the base contract
    (learner._obs = obs) plus the collective-bytes counter
    (obs/metrics.py), accumulated per grown tree — created only when the
    observer is on so the disabled hot path stays allocation-free."""
    learner._obs = obs
    learner._m_coll = None
    if getattr(obs, "enabled", False):
        from ..obs import REGISTRY
        learner._m_coll = REGISTRY.counter(
            "lgbm_collective_bytes_total",
            "estimated bytes moved by cross-device collectives "
            "(psum/all_gather) during tree growth")
        learner._coll_tree_bytes = _per_tree_collective_bytes(learner)


class DataParallelTreeLearner(SerialTreeLearner):
    """Row-sharded learner; one psum per histogram construction.

    The same grow program as the serial learner runs under shard_map with
    `psum_axis='data'`: per-leaf histograms and root sums are all-reduced so
    every shard sees identical split decisions and applies them to its local
    rows — the lock-step SPMD structure of the reference's data-parallel
    loop (SURVEY.md §3.5) with XLA supplying the ring reductions.
    """

    def __init__(self, config: Config, train_data: TrainingData,
                 mesh: Optional[Mesh] = None):
        self.mesh = mesh if mesh is not None else make_data_mesh()
        n_shards = self.mesh.devices.size
        self._nproc = jax.process_count()
        n = train_data.num_data        # multi-process: THIS process's rows
        if self._nproc > 1 and n_shards % self._nproc != 0:
            Log.fatal("Data mesh of %d devices cannot be split across %d "
                      "processes evenly", n_shards, self._nproc)
        if self._nproc > 1 and n % max(n_shards // self._nproc, 1) != 0:
            # global arrays must align with the caller's global score/grad
            # buffers; implicit tail padding would desync their lengths
            Log.fatal("Multi-process training needs local rows (%d) "
                      "pre-padded to a multiple of the per-process shard "
                      "count (%d)", n, max(n_shards // self._nproc, 1))
        # every process must contribute identically-shaped shards (equal
        # per-process row counts pre-partitioned by the caller, padded to
        # the per-process shard quantum here)
        local_shards = max(n_shards // self._nproc, 1)
        pad = pad_rows(n, local_shards)
        self._pad = pad
        binned = train_data.binned
        if pad:
            binned = np.concatenate(
                [binned, np.zeros((pad, binned.shape[1]), binned.dtype)])
        # the sparse store replaces X below — don't upload (and orphan)
        # the dense matrix when it will never be used.  Must mirror the
        # base ctor's gate exactly (voting subclasses stay dense).
        want_sparse = (bool(config.tpu_sparse)
                       and str(config.tree_learner)
                       in ("data", "data_parallel"))
        X_dev = (None if want_sparse
                 else make_row_sharded(self.mesh, binned, extra_dims=1))
        super().__init__(config, train_data, psum_axis=DATA_AXIS,
                         device_data=X_dev)
        # GLOBAL row count: every process contributes n+pad rows
        self._global_rows = (n + pad) * self._nproc
        if self.sparse_on:
            # row-block coordinate stores, flat-concatenated so
            # P(DATA_AXIS) hands each device its local store with LOCAL
            # row ids (ops/sparse_store.py).  Multi-process: every rank
            # builds its OWN blocks and allgathers (nnz, col_cap) so all
            # sections pad identically — the sparse analog of the
            # distributed bin-mapper agreement (dataset_loader.cpp:768).
            from ..ops.sparse_store import (SparseDeviceStore,
                                            assemble_sharded_store,
                                            column_fill_bins,
                                            sharded_store_parts)
            nbins_dev = (self.group_bins
                         if train_data.bundle is not None
                         else self.num_bins)
            sp_binned = binned
            if sp_binned.shape[1] == 0:
                sp_binned = np.zeros((n + pad, 1), np.uint8)
                fill = np.zeros(1, np.int64)
            else:
                fill = column_fill_bins(train_data.num_bin_arr,
                                        train_data.default_bin_arr,
                                        train_data.bundle)
            parts, nnz_needed, col_cap = sharded_store_parts(
                sp_binned, fill, nbins_dev, local_shards)
            if self._nproc > 1:
                from .comm import JaxProcessComm
                agreed = JaxProcessComm().allgather_obj(
                    [int(nnz_needed), int(col_cap)])
                nnz_needed = max(a[0] for a in agreed)
                col_cap = max(a[1] for a in agreed)
            host_store, self.sparse_device_bytes = assemble_sharded_store(
                parts, sp_binned.shape[1], nbins_dev, nnz_needed)
            self.sparse_col_cap = col_cap
            self.X = SparseDeviceStore(*[
                make_row_sharded(self.mesh, np.asarray(leaf))
                for leaf in host_store])
        self._row_sharding = NamedSharding(self.mesh, P(DATA_AXIS))
        self._ones = make_row_sharded(
            self.mesh,
            np.concatenate([np.ones(n, np.float32),
                            np.zeros(pad, np.float32)]).astype(self.dtype))
        from ..ops.grow import default_row_capacities
        local_rows = (n + pad) // local_shards
        caps = (default_row_capacities(local_rows)
                if self.row_capacities else ())   # same gate, per-shard rows
        voting = bool(self._grow_kwargs(n_shards).get("voting_k", 0))
        self._Xt = None
        if self.growth == "wave" and not voting:
            # wave schedule under the data mesh: the per-wave histogram
            # block is psum'd ONCE (W splits per collective instead of one)
            from ..ops.wave import make_wave_grow_fn
            # transposed Pallas kernels: materialize Xt ONCE per booster
            # (a row-shard of X and the matching column-shard of Xt live
            # on the same device, so this transpose is comm-free) instead
            # of once per tree dispatch inside the shard-mapped grow
            from ..ops.wave import transposed_wave_active
            needs_xt = (transposed_wave_active(self.hist_mode, self.dtype)
                        and not self.sparse_on)
            if bool(config.tpu_wave_compact):
                # the compaction tiers are serial-execution only (no DP
                # measurement yet, ops/wave.py) — an explicit opt-in
                # must not be dropped silently
                Log.warning("tpu_wave_compact=true ignored: not "
                            "supported under the distributed learners")
            grow = make_wave_grow_fn(
                self.num_leaves, self.num_bins, self.meta, self.params,
                config.max_depth, wave_width=self.wave_width,
                hist_dtype=self.dtype, psum_axis=DATA_AXIS,
                bundle=self.bundle_arrays, group_bins=self.group_bins,
                cache_hists=self.cache_hists, hist_mode=self.hist_mode,
                chunk=int(config.tpu_wave_chunk),
                sparse_col_cap=self.sparse_col_cap, with_xt=needs_xt,
                exact_order=self.wave_order == "exact",
                lookup=self.wave_lookup, hist_hilo=self.hist_hilo)
            if needs_xt:
                self._Xt = jax.jit(
                    jnp.transpose,
                    out_shardings=NamedSharding(self.mesh,
                                                P(None, DATA_AXIS)))(self.X)
        else:
            if self.hist_mode in WAVE_ONLY_MODES:
                Log.fatal("tpu_histogram_mode=%s is wave-only; the "
                          "voting-parallel learner's exact engine does not "
                          "support it" % self.hist_mode)
            grow = make_grow_fn(self.num_leaves, self.num_bins, self.meta,
                                self.params, config.max_depth,
                                hist_mode=self.hist_mode,
                                hist_dtype=self.dtype,
                                psum_axis=DATA_AXIS,
                                bundle=self.bundle_arrays,
                                group_bins=self.group_bins,
                                row_capacities=caps,
                                cache_hists=self.cache_hists,
                                sparse_col_cap=self.sparse_col_cap,
                                **self._grow_kwargs(n_shards))
        if self.sparse_on:
            from ..ops.sparse_store import SparseDeviceStore
            x_spec = SparseDeviceStore(*([P(DATA_AXIS)] * 5))
        else:
            x_spec = P(DATA_AXIS, None)
        in_specs = (x_spec, P(DATA_AXIS), P(DATA_AXIS),
                    P(DATA_AXIS), P())
        if self._Xt is not None:
            in_specs += (P(None, DATA_AXIS),)
        sharded_grow = _shard_map_compat(
            grow, mesh=self.mesh,
            in_specs=in_specs,
            out_specs=(jax.tree_util.tree_map(lambda _: P(),
                                              self._dummy_tree_spec()),
                       P(DATA_AXIS)))
        self._grow = jax.jit(sharded_grow)
        Log.info("%s over %d devices (%d padded rows)",
                 type(self).__name__, n_shards, pad)

    def _grow_kwargs(self, n_shards):
        return {}

    def collective_info(self):
        """Static topology + per-collective byte ESTIMATES for the run
        header.  The host cannot time XLA collectives (they live inside
        the one jitted grow program) — measured collective time needs an
        obs_trace_iters profiler window; these numbers size the traffic.
        Histograms are (grad, hess, count) triples per (feature, bin)."""
        dtype_bytes = jnp.dtype(self.dtype).itemsize
        f = max(self.train_data.num_features, 1)
        info = {"learner": type(self).__name__, "axis": DATA_AXIS,
                "n_devices": int(self.mesh.devices.size),
                "n_processes": int(self._nproc),
                "global_rows": int(self._global_rows),
                "estimates": True}
        if self.growth == "wave":
            w = int(self.wave_width)
            info["psum"] = {"what": "wave histogram block (W splits "
                                    "per collective)",
                            "per_wave_bytes":
                                f * self.num_bins * 3 * w * dtype_bytes}
        else:
            info["psum"] = {"what": "per-leaf histogram",
                            "per_leaf_bytes":
                                f * self.num_bins * 3 * dtype_bytes}
        return info

    def set_observer(self, obs) -> None:
        _init_collective_counter(self, obs)

    def _dummy_tree_spec(self):
        # a TreeArrays-shaped pytree of None leaves for out_specs mapping
        from ..ops.grow import TreeArrays
        return TreeArrays(*([0] * len(TreeArrays._fields)))

    def _pad_rows_dev(self, arr, fill=0.0):
        if isinstance(arr, jax.Array) and arr.ndim == 1 \
                and arr.shape[0] == self._global_rows \
                and arr.dtype == self.dtype:
            return arr          # already a (global) row-sharded device array
        if self._nproc == 1:
            # async on-device pad + placement (no host round-trip: the
            # boosting loop stays fully pipelined, gbdt.py:344-350)
            arr = jnp.asarray(arr, self.dtype)
            if self._pad:
                arr = jnp.concatenate(
                    [arr, jnp.full((self._pad,), fill, self.dtype)])
            return jax.device_put(arr, self._row_sharding)
        arr = np.asarray(arr, self.dtype)     # local shard -> global array
        if self._pad:
            arr = np.concatenate(
                [arr, np.full((self._pad,), fill, self.dtype)])
        return make_row_sharded(self.mesh, arr)

    def local_rows(self, global_arr):
        """This process's rows of a row-sharded global array, pad
        dropped — the bridge that lets the per-rank GBDT controller keep
        LOCAL score/gradient arrays while the grow program psums over
        the global mesh.  Pure addressable-shard reads: no cross-process
        transfer, no host round-trip."""
        shards = sorted(global_arr.addressable_shards,
                        key=lambda s: int(s.index[0].start or 0))
        parts = [s.data for s in shards]
        loc = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        return loc[:self.train_data.num_data]

    def train_device(self, grad, hess, row_mult=None, feature_mask=None):
        """Grow one tree.  Multi-process callers pass LOCAL row shards
        (host or device); they come back as a GLOBAL row-sharded array
        from _pad_rows_dev and the returned row->leaf map is global too
        (tests/mp_worker.py drives this directly; the GBDT layer slices
        it back to local rows via ``local_rows``).  Single-process
        callers pass host arrays and get unpadded local maps."""
        grad = self._pad_rows_dev(grad)
        hess = self._pad_rows_dev(hess)
        if row_mult is None:
            row_mult = self._ones
        else:
            row_mult = self._pad_rows_dev(row_mult)
        if feature_mask is None:
            feature_mask = self.sample_feature_mask()
        args = (self.X, grad, hess, row_mult, feature_mask)
        if self._Xt is not None:
            args += (self._Xt,)
        obs = self._obs
        obs.entry_args("tree_grow", self._grow, args,
                       names=("X", "grad", "hess", "row_mult",
                              "feature_mask", "Xt")[:len(args)])
        t0 = obs.entry_start()
        tree, leaf_id = self._grow(*args)
        obs.entry_end("tree_grow", t0, (tree, leaf_id))
        if getattr(self, "_m_coll", None) is not None:
            self._m_coll.inc(self._coll_tree_bytes)
        if self._nproc > 1:
            return tree, leaf_id     # global, matches global score arrays
        return tree, leaf_id[:self.train_data.num_data] if self._pad else leaf_id


class VotingParallelTreeLearner(DataParallelTreeLearner):
    """Data-parallel with PV-Tree top-k histogram exchange.

    Identical row sharding; the grow program votes per leaf (local top_k
    proposals weighted by leaf size, pmax, global top_k) and psums only the
    selected feature histograms (voting_parallel_tree_learner.cpp:164-300).
    Exact when top_k >= num_features; an approximation that preserves tree
    quality in the PV-Tree regime otherwise.
    """

    def _grow_kwargs(self, n_shards):
        return {"voting_k": int(self.config.top_k),
                "num_voting_machines": int(n_shards)}

    def collective_info(self):
        info = super().collective_info()
        top_k = int(self.config.top_k)
        info["psum"] = {"what": "PV-Tree voted histograms (top_k "
                                "features per leaf)",
                        "per_leaf_bytes": top_k * self.num_bins * 3
                        * jnp.dtype(self.dtype).itemsize,
                        "top_k": top_k}
        return info


class FeatureParallelTreeLearner(SerialTreeLearner):
    """Feature-sharded learner: rows replicated, split search partitioned.

    Each device scans its contiguous feature block; one packed SplitInfo
    all_gather + strict-> fold picks the global best (the reference's
    Allreduce(MaxReducer), feature_parallel_tree_learner.cpp:52-76), and a
    single row-bitmask psum re-executes the split everywhere.  Histogram
    memory per device shrinks by n_shards — this is the wide-dataset
    (tensor-parallel-over-features) axis of the mesh.
    """

    def __init__(self, config: Config, train_data: TrainingData,
                 mesh: Optional[Mesh] = None):
        if train_data.bundle is not None:
            Log.fatal("The feature-parallel learner requires "
                      "enable_bundle=false (dataset was built with EFB)")
        self.mesh = mesh if mesh is not None else make_feature_mesh()
        if FEATURE_AXIS not in self.mesh.axis_names:
            self.mesh = make_feature_mesh(self.mesh.devices.reshape(-1))
        n_shards = self.mesh.devices.size
        f = max(train_data.num_features, 1)
        fpad = (-f) % n_shards
        self._fpad = fpad
        binned = train_data.binned
        if binned.size == 0:
            binned = np.zeros((train_data.num_data, f), np.uint8)
        if fpad:
            binned = np.concatenate(
                [binned, np.zeros((binned.shape[0], fpad), binned.dtype)],
                axis=1)
        x_sharding = NamedSharding(self.mesh, P(None, FEATURE_AXIS))
        X_dev = jax.device_put(binned, x_sharding)
        super().__init__(config, train_data, device_data=X_dev)
        # padded features: num_bin=1 -> no valid threshold -> gain stays -inf
        pad_i32 = lambda a, v: jnp.concatenate(
            [jnp.asarray(a, jnp.int32), jnp.full(fpad, v, jnp.int32)])
        self.meta = FeatureMeta(
            num_bin=pad_i32(train_data.num_bin_arr, 1),
            default_bin=pad_i32(train_data.default_bin_arr, 0),
            is_categorical=jnp.concatenate(
                [jnp.asarray(train_data.is_categorical_arr, bool),
                 jnp.zeros(fpad, bool)]))
        if self.hist_mode in WAVE_ONLY_MODES:
            Log.fatal("tpu_histogram_mode=%s is wave-only; the "
                      "feature-parallel learner's exact engine does not "
                      "support it" % self.hist_mode)
        grow = make_grow_fn(self.num_leaves, self.num_bins, self.meta,
                            self.params, config.max_depth,
                            hist_mode=self.hist_mode, hist_dtype=self.dtype,
                            feature_axis=FEATURE_AXIS,
                            row_capacities=self.row_capacities,
                            cache_hists=self.cache_hists)
        from ..ops.grow import TreeArrays
        tree_specs = jax.tree_util.tree_map(
            lambda _: P(), TreeArrays(*([0] * len(TreeArrays._fields))))
        sharded_grow = _shard_map_compat(
            grow, mesh=self.mesh,
            in_specs=(P(None, FEATURE_AXIS), P(), P(), P(), P()),
            out_specs=(tree_specs, P()), checked=False)
        self._grow = jax.jit(sharded_grow)
        Log.info("Feature-parallel learner over %d devices "
                 "(%d padded features)", n_shards, fpad)

    def sample_feature_mask(self):
        mask = super().sample_feature_mask()
        if self._fpad:
            mask = jnp.concatenate([mask, jnp.zeros(self._fpad, bool)])
        return mask

    def set_observer(self, obs) -> None:
        _init_collective_counter(self, obs)

    def train_device(self, grad, hess, row_mult=None, feature_mask=None):
        out = super().train_device(grad, hess, row_mult, feature_mask)
        if getattr(self, "_m_coll", None) is not None:
            self._m_coll.inc(self._coll_tree_bytes)
        return out

    def collective_info(self):
        """Per-split traffic: one packed-SplitInfo all_gather (the
        Allreduce(MaxReducer) analog) + one row-bitmask psum.  Estimates
        only — see DataParallelTreeLearner.collective_info."""
        n_shards = int(self.mesh.devices.size)
        return {"learner": type(self).__name__, "axis": FEATURE_AXIS,
                "n_devices": n_shards, "n_processes": 1,
                "global_rows": int(self.train_data.num_data),
                "estimates": True,
                "allgather": {"what": "packed SplitInfo per split",
                              "per_split_bytes": 13 * 4 * n_shards},
                "psum": {"what": "row-bitmask split re-execution",
                         "per_split_bytes":
                             int(self.train_data.num_data) * 4}}


def create_tree_learner(config: Config, train_data: TrainingData,
                        mesh: Optional[Mesh] = None):
    """TreeLearner::CreateTreeLearner (tree_learner.h:19-82) — learner type
    x device dispatch: 'serial' on one device; 'data'/'feature'/'voting'
    parallel over the mesh."""
    ltype = config.tree_learner
    n_dev = len(jax.devices()) if mesh is None else mesh.devices.size
    if n_dev > 1:
        if ltype in ("data", "data_parallel"):
            return DataParallelTreeLearner(config, train_data, mesh)
        if ltype in ("voting", "voting_parallel"):
            return VotingParallelTreeLearner(config, train_data, mesh)
        if ltype in ("feature", "feature_parallel"):
            return FeatureParallelTreeLearner(config, train_data, mesh)
    if ltype not in ("serial", "data", "feature", "voting", "data_parallel",
                     "feature_parallel", "voting_parallel"):
        Log.fatal("Unknown tree learner type %s", ltype)
    return SerialTreeLearner(config, train_data)
