"""Distributed bin finding (dataset_loader.cpp:733-833 analog): features
partitioned across ranks, mappers allgathered — driven by the threaded
multi-rank fixture in parallel/comm.py."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.parallel.comm import SingleProcessComm, run_ranks
from lightgbm_tpu.utils.config import Config


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    n, f = 4000, 11
    X = rng.normal(size=(n, f))
    X[:, 2] = rng.integers(0, 6, n)          # low-cardinality column
    X[rng.uniform(size=n) < 0.3, 4] = 0.0    # sparse-ish column
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    return X, y


def _mapper_sig(td):
    return [(m.num_bin, m.bin_type, list(np.round(m.bin_upper_bound, 12)))
            for m in td.bin_mappers]


def test_same_data_matches_serial(data):
    """Every rank holding the full data must reproduce the serial mappers
    exactly (same sample, same greedy packing)."""
    X, y = data
    cfg = Config({"verbose": -1})
    serial = TrainingData.from_matrix(X, label=y, config=cfg)

    def load(comm):
        return TrainingData.from_matrix(X, label=y, config=Config(
            {"verbose": -1}), comm=comm)

    for td in run_ranks(4, load):
        assert _mapper_sig(td) == _mapper_sig(serial)
        np.testing.assert_array_equal(td.binned, serial.binned)


def test_row_sharded_ranks_agree(data):
    """Pre-partitioned rows: all ranks must end with the IDENTICAL mapper
    set (each rank contributed its feature block, then allgathered)."""
    X, y = data
    shards = np.array_split(np.arange(len(y)), 4)

    def load(comm):
        idx = shards[comm.rank]
        return TrainingData.from_matrix(X[idx], label=y[idx],
                                        config=Config({"verbose": -1}),
                                        comm=comm)

    tds = run_ranks(4, load)
    sig0 = _mapper_sig(tds[0])
    for td in tds[1:]:
        assert _mapper_sig(td) == sig0
    # local shard shapes
    for r, td in enumerate(tds):
        assert td.num_data == len(shards[r])
        assert td.binned.shape == (len(shards[r]), td.num_features)


def test_row_sharded_training_works(data):
    """A shard loaded distributed trains to a sane model end-to-end."""
    X, y = data
    shards = np.array_split(np.arange(len(y)), 2)

    def load(comm):
        idx = shards[comm.rank]
        return TrainingData.from_matrix(X[idx], label=y[idx],
                                        config=Config({"verbose": -1}),
                                        comm=comm)

    td0 = run_ranks(2, load)[0]
    ds = lgb.Dataset(X[shards[0]], label=y[shards[0]])
    ds._handle = td0
    bst = lgb.train({"objective": "binary", "verbose": -1, "num_leaves": 15,
                     "min_data_in_leaf": 5}, ds, num_boost_round=10)
    p = bst.predict(X[shards[1]])
    acc = ((p > 0.5) == (y[shards[1]] > 0)).mean()
    assert acc > 0.9


def test_single_process_comm_is_identity():
    c = SingleProcessComm()
    assert c.rank == 0 and c.size == 1
    assert c.allgather_obj({"a": 1}) == [{"a": 1}]


def test_distributed_efb_consistent(data):
    """EFB under distribution: rank 0 decides the bundles, every rank ends
    with the identical group structure; same-data ranks match serial."""
    rng = np.random.default_rng(9)
    n, cats = 3000, 6
    c = rng.integers(0, cats, n)
    X = np.concatenate([rng.normal(size=(n, 2)), np.eye(cats)[c]], axis=1)
    y = (c % 2 == 0).astype(np.float64)
    serial = TrainingData.from_matrix(X, label=y, config=Config(
        {"verbose": -1}))
    assert serial.bundle is not None

    def load_same(comm):
        return TrainingData.from_matrix(X, label=y, config=Config(
            {"verbose": -1}), comm=comm)

    for td in run_ranks(3, load_same):
        assert td.bundle is not None
        assert [list(g) for g in td.bundle.groups] == \
            [list(g) for g in serial.bundle.groups]
        np.testing.assert_array_equal(td.binned, serial.binned)

    shards = np.array_split(np.arange(n), 3)

    def load_shard(comm):
        idx = shards[comm.rank]
        return TrainingData.from_matrix(X[idx], label=y[idx], config=Config(
            {"verbose": -1}), comm=comm)

    tds = run_ranks(3, load_shard)
    g0 = [list(g) for g in tds[0].bundle.groups]
    assert all([list(g) for g in td.bundle.groups] == g0 for td in tds[1:])


def test_more_ranks_than_features(data):
    """Ranks beyond the feature count contribute empty blocks."""
    X, y = data
    Xs = X[:, :3]

    def load(comm):
        return TrainingData.from_matrix(Xs, label=y, config=Config(
            {"verbose": -1}), comm=comm)

    tds = run_ranks(6, load)
    assert all(len(td.bin_mappers) == 3 for td in tds)


def test_sync_config_across_ranks():
    """GlobalSyncUpByMin analog (application.cpp:118-199): ranks that
    were launched with divergent RNG-bearing params converge to the
    minimum so every machine grows identical trees."""
    from lightgbm_tpu.parallel.comm import sync_config_across_ranks

    def worker(comm):
        cfg = Config({"verbose": -1,
                      "data_random_seed": 10 + comm.rank,
                      "feature_fraction_seed": 5 - comm.rank,
                      "feature_fraction": 1.0 - 0.1 * comm.rank,
                      "drop_seed": 100 * (comm.rank + 1)})
        sync_config_across_ranks(comm, cfg)
        derived = cfg.copy_with(num_leaves=7)   # must keep synced values
        return (cfg.data_random_seed, cfg.feature_fraction_seed,
                cfg.feature_fraction, cfg.drop_seed,
                derived.feature_fraction, derived.drop_seed)

    results = run_ranks(3, worker)
    assert len(set(results)) == 1
    assert results[0] == (10, 3, pytest.approx(0.8), 100,
                          pytest.approx(0.8), 100)
