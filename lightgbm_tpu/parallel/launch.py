"""Subprocess multi-process simulation: real ``jax.distributed`` workers.

``run_ranks`` (comm.py) simulates ranks on threads — one process, one
backend, host barriers only.  This module is the other half of the PR-4
fixture: ``run_ranks_subprocess`` drives N REAL processes, each with its
own CPU backend and its own ``jax.distributed.initialize`` handshake, so
the exact code path a TPU pod runs (process bootstrap → global mesh →
cross-process psum) is exercised in CI with no pod.  The moral
equivalent of the reference running `mpirun -np 2` on localhost
(.travis.yml:45-52) instead of only linking against MPI.

Worker protocol: the child bootstraps via ``distributed_init()`` (env
autodetect — the parent exports ``JAX_COORDINATOR_ADDRESS`` /
``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` exactly like a pod launcher),
imports ``spec`` ("package.module:function"; callables can't cross a
process boundary), calls ``fn(comm, payload)`` and prints its
JSON-serializable return as a final ``MPRESULT {...}`` line.  The PR-4
``LGBM_MP_*`` fault hooks ride through the inherited environment.

jaxlib's CPU client only grew cross-process collectives in some builds;
on hosts without them workers die with "Multiprocess computations
aren't implemented" and the launcher raises ``MultiprocessUnsupported``
so tests skip instead of fail — same contract as
tests/test_multiprocess.py always had.
"""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import time
from typing import Any, List, Optional

HERE = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(os.path.dirname(HERE))

# jaxlib messages that mean "this CPU client cannot do cross-process
# collectives at all" — an environment limitation, not a code failure
_UNSUPPORTED_MARKERS = (
    "Multiprocess computations aren't implemented",
    "multiprocess computations aren't implemented",
    # older shard_map cannot trace the mesh grow programs' while loops
    # on CPU (the same jaxlib limit tests/test_parallel.py carries at
    # the seed) — an environment limit of the runner, not a code bug
    "No replication rule for while",
)

DEFAULT_WORKER_TIMEOUT = 540.0


class MultiprocessUnsupported(RuntimeError):
    """The installed jaxlib CPU client lacks cross-process collectives."""


class RankFailure(RuntimeError):
    """One or more worker processes died.  Carries everything the
    elastic driver (parallel/elastic.py) needs to shrink and resume:
    which ranks failed, every rank's exit code, and the per-rank output
    tails (where the watchdog flight-record paths land)."""

    def __init__(self, failed, returncodes, tails, results=None):
        self.failed = sorted(failed)
        self.returncodes = dict(returncodes)
        self.tails = dict(tails)
        self.results = dict(results or {})
        super().__init__(
            "worker rank(s) %s died (exit %s); %d/%d ranks returned"
            % (self.failed,
               {r: self.returncodes.get(r) for r in self.failed},
               len(self.results), len(returncodes)))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env(coordinator, size, rank, local_devices, extra_env):
    env = dict(os.environ)
    # the child must see exactly `local_devices` CPU devices, whatever
    # the parent's (test-harness) XLA_FLAGS said
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=%d"
                 % int(local_devices))
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    # the pod-launcher contract distributed_init() autodetects from
    env["JAX_COORDINATOR_ADDRESS"] = coordinator
    env["JAX_NUM_PROCESSES"] = str(int(size))
    env["JAX_PROCESS_ID"] = str(int(rank))
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    if extra_env:
        env.update({str(k): str(v) for k, v in extra_env.items()})
    return env


def _tail(path, nbytes=8192):
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            f.seek(max(0, f.tell() - nbytes))
            return f.read().decode("utf-8", "replace")
    except OSError:
        return ""


def _parse_result(out):
    """Last MPRESULT line of a worker log, or None."""
    for line in reversed(out.splitlines()):
        if line.startswith("MPRESULT "):
            return json.loads(line[len("MPRESULT "):])["result"]
    return None


def run_ranks_subprocess(size: int, spec: str, payload: Any = None, *,
                         local_devices: int = 1,
                         timeout: float = DEFAULT_WORKER_TIMEOUT,
                         extra_env: Optional[dict] = None,
                         fail_grace: float = 8.0) -> List[Any]:
    """Drive ``fn(comm, payload)`` for ``size`` real processes over a
    localhost coordinator; per-rank results in rank order.

    ``spec`` is a "package.module:function" import target.  ``payload``
    must be JSON-serializable and reaches every rank verbatim.  A rank
    death raises :class:`RankFailure` after giving surviving ranks
    ``fail_grace`` seconds to notice (watchdog flight records) before
    they are killed; a jaxlib without cross-process CPU collectives
    raises :class:`MultiprocessUnsupported`.
    """
    coordinator = "127.0.0.1:%d" % free_port()
    procs, logs = [], []
    with tempfile.TemporaryDirectory(prefix="lgbm_mp_") as td:
        payload_path = os.path.join(td, "payload.json")
        with open(payload_path, "w") as f:
            json.dump(payload, f)
        for rank in range(size):
            log_path = os.path.join(td, "rank%d.log" % rank)
            logs.append(log_path)
            lf = open(log_path, "wb")
            procs.append((subprocess.Popen(
                [sys.executable, "-m", "lightgbm_tpu.parallel.launch",
                 "--child", spec, payload_path],
                stdout=lf, stderr=subprocess.STDOUT, cwd=REPO_ROOT,
                env=_worker_env(coordinator, size, rank, local_devices,
                                extra_env)), lf))
        deadline = time.time() + float(timeout)
        first_death = None
        try:
            while True:
                codes = [p.poll() for p, _ in procs]
                if all(c is not None for c in codes):
                    break
                dead = [r for r, c in enumerate(codes)
                        if c is not None and c != 0]
                if dead and first_death is None:
                    # a rank died; give survivors a bounded window to
                    # hit their barrier timeout / dump flight records
                    first_death = time.time()
                if first_death is not None and \
                        time.time() - first_death > float(fail_grace):
                    break
                if time.time() > deadline:
                    break
                time.sleep(0.1)
        finally:
            for p, lf in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
                lf.close()
        outs = [_tail(path, 65536) for path in logs]
        codes = {r: p.poll() for r, (p, _) in enumerate(procs)}
        if any(any(m in out for m in _UNSUPPORTED_MARKERS)
               for out in outs):
            raise MultiprocessUnsupported(
                "jax CPU backend on this host cannot run the "
                "multi-process mesh program")
        results = {r: _parse_result(out) for r, out in enumerate(outs)}
        failed = [r for r, c in codes.items()
                  if c != 0 or results[r] is None]
        if failed:
            raise RankFailure(
                failed, codes, {r: outs[r][-2048:] for r in failed},
                {r: v for r, v in results.items() if v is not None})
        return [results[r] for r in range(size)]


def _child(argv):
    """Worker entry: bootstrap the distributed runtime, run the spec."""
    spec, payload_path = argv
    import jax
    jax.config.update("jax_platforms", "cpu")
    from .comm import JaxProcessComm, distributed_init  # noqa: F401
    comm = distributed_init()
    with open(payload_path) as f:
        payload = json.load(f)
    import importlib
    mod_name, fn_name = spec.split(":")
    fn = getattr(importlib.import_module(mod_name), fn_name)
    result = fn(comm, payload)
    sys.stdout.write("MPRESULT " + json.dumps(
        {"rank": comm.rank, "result": result}) + "\n")
    sys.stdout.flush()
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--child":
        sys.exit(_child(sys.argv[2:]))
    sys.exit("usage: python -m lightgbm_tpu.parallel.launch --child "
             "<pkg.mod:fn> <payload.json>")
