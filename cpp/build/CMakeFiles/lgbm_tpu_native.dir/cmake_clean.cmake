file(REMOVE_RECURSE
  "/root/repo/lightgbm_tpu/lib/liblgbm_tpu_native.pdb"
  "/root/repo/lightgbm_tpu/lib/liblgbm_tpu_native.so"
  "CMakeFiles/lgbm_tpu_native.dir/src/native.cpp.o"
  "CMakeFiles/lgbm_tpu_native.dir/src/native.cpp.o.d"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/lgbm_tpu_native.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
