"""Wave-histogram Pallas kernels vs the XLA oracle (interpret mode, CPU).

Covers the shipped kernel layouts (v1 row-major `pallas`, v2 transposed
`pallas_t`, v5 fused compact-table row-vector `pallas_ct`) and the
4-bit packed input path of each.  The v3/v4 fused kernels and their
tests were deleted in round 4 (measured losers — BENCH_NOTES.md).
"""
import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.pack import pack4_host
from lightgbm_tpu.ops.pallas_wave import (wave_histogram_pallas,
                                          wave_histogram_pallas_t,
                                          wave_histogram_reference)


def _data(n=3000, f=7, b=14, k=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.integers(0, b, size=(n, f), dtype=np.uint8)
    leaf_id = rng.integers(0, 2 * k, size=n).astype(np.int32)
    w3 = rng.normal(size=(n, 3)).astype(np.float32)
    cid = np.array([0, 2, 4, -1, 7], dtype=np.int32)[:k]
    return X, leaf_id, w3, cid, b


@pytest.mark.parametrize("layout", ["v1", "v2"])
def test_kernel_matches_oracle(layout):
    X, leaf_id, w3, cid, b = _data()
    want = np.array(wave_histogram_reference(
        jnp.asarray(X), jnp.asarray(leaf_id), jnp.asarray(w3),
        jnp.asarray(cid), b))
    want[np.asarray(cid) < 0] = 0.0
    if layout == "v1":
        got = wave_histogram_pallas(
            jnp.asarray(X), jnp.asarray(leaf_id), jnp.asarray(w3),
            jnp.asarray(cid), b, interpret=True)
    else:
        got = wave_histogram_pallas_t(
            jnp.asarray(X.T), jnp.asarray(leaf_id), jnp.asarray(w3),
            jnp.asarray(cid), b, interpret=True)
    np.testing.assert_allclose(np.asarray(got), want, rtol=5e-4, atol=5e-4)


@pytest.mark.parametrize("layout", ["v1", "v2", "v5"])
def test_kernel_bf16_precision_mode(layout):
    """tpu_hist_precision=bf16 (single round-to-nearest product, half the
    MXU work — the reference GPU's gpu_use_dp=false analog): sums must
    stay within the 2^-9-per-product class of the exact oracle, measured
    against the histogram's scale (signed gradients make tiny individual
    cells legitimately high-relative-error)."""
    X, leaf_id, w3, cid, b = _data()
    want = np.array(wave_histogram_reference(
        jnp.asarray(X), jnp.asarray(leaf_id), jnp.asarray(w3),
        jnp.asarray(cid), b))
    want[np.asarray(cid) < 0] = 0.0
    if layout == "v1":
        got = wave_histogram_pallas(
            jnp.asarray(X), jnp.asarray(leaf_id), jnp.asarray(w3),
            jnp.asarray(cid), b, interpret=True, hilo=False)
    elif layout == "v2":
        got = wave_histogram_pallas_t(
            jnp.asarray(X.T), jnp.asarray(leaf_id), jnp.asarray(w3),
            jnp.asarray(cid), b, interpret=True, hilo=False)
    else:
        from lightgbm_tpu.ops.pallas_wave import (
            wave_partition_hist_pallas_ct)
        # inactive table: no splits commit, histograms of cid as-is
        cols = np.zeros((4, 10), np.float32)
        psrc = np.full(4, -3, np.int32)
        _, got = wave_partition_hist_pallas_ct(
            jnp.asarray(X.T), jnp.asarray(leaf_id), jnp.asarray(w3),
            jnp.asarray(cid), jnp.asarray(cols), jnp.asarray(psrc), b,
            interpret=True, hilo=False)
    scale = np.abs(want).max()
    assert np.abs(np.asarray(got) - want).max() <= 5e-3 * scale


@pytest.mark.parametrize("mode", ["pallas_t", "pallas_ct"])
def test_pallas_wave_data_parallel_constructs(mode):
    """tree_learner=data + a wave-only pallas mode must reach the mesh
    wave branch (the base constructor's exact-engine fallback maps these
    modes to onehot instead of crashing) and train."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(2)
    X = rng.normal(size=(1600, 6))
    y = (X[:, 0] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "tree_learner": "data", "tpu_histogram_mode": mode}
    bst = lgb.train(params, lgb.Dataset(X, label=y, params=params),
                    num_boost_round=2)
    assert bst.predict(X).shape == (1600,)


@pytest.mark.parametrize("mode", ["pallas_t", "pallas_ct"])
def test_pallas_wave_mode_plumbing(mode):
    """Wave-only pallas modes resolve to wave growth and train (falling
    back to the einsum path off-TPU); exact growth rejects them."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.log import LightGBMError

    rng = np.random.default_rng(1)
    X = rng.normal(size=(1200, 6))
    y = (X[:, 0] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "tpu_histogram_mode": mode}
    bst = lgb.train(params, lgb.Dataset(X, label=y, params=params),
                    num_boost_round=3)
    assert bst._gbdt.learner.growth == "wave"
    p = bst.predict(X)
    assert p.shape == (1200,)

    bad = dict(params, tpu_growth="exact")
    with pytest.raises(LightGBMError):
        lgb.train(bad, lgb.Dataset(X, label=y, params=bad),
                  num_boost_round=1)


@pytest.mark.parametrize("layout", ["v1", "v2"])
def test_kernel_packed_matches_oracle(layout):
    X, leaf_id, w3, cid, b = _data(f=9, b=15, seed=3)
    want = np.array(wave_histogram_reference(
        jnp.asarray(X), jnp.asarray(leaf_id), jnp.asarray(w3),
        jnp.asarray(cid), b))
    want[np.asarray(cid) < 0] = 0.0
    packed = pack4_host(X)
    if layout == "v1":
        got = wave_histogram_pallas(
            jnp.asarray(packed), jnp.asarray(leaf_id), jnp.asarray(w3),
            jnp.asarray(cid), b, interpret=True, logical_cols=X.shape[1])
    else:
        got = wave_histogram_pallas_t(
            jnp.asarray(packed.T), jnp.asarray(leaf_id), jnp.asarray(w3),
            jnp.asarray(cid), b, interpret=True, logical_cols=X.shape[1])
    np.testing.assert_allclose(np.asarray(got), want, rtol=5e-4, atol=5e-4)


def _route_numpy(X, leaf_id, tbl, bundled=False):
    """Numpy replica of the wave partition routing (ops/wave.py)."""
    r = tbl[np.clip(leaf_id, 0, tbl.shape[0] - 1)]
    r = np.where((leaf_id >= 0)[:, None], r, 0.0)
    active = r[:, 0] > 0.5
    cj = r[:, 1].astype(np.int32)
    colv = X[np.arange(len(X)), np.clip(cj, 0, X.shape[1] - 1)].astype(
        np.int32)
    if bundled:
        goff = r[:, 7].astype(np.int32)
        span = r[:, 9].astype(np.int32)
        in_range = (colv >= goff) & (colv < goff + span)
        colv = np.where(in_range, colv - goff + r[:, 8].astype(np.int32),
                        r[:, 4].astype(np.int32))
    thr = r[:, 2].astype(np.int32)
    cat = r[:, 3] > 0.5
    gl = np.where(cat, colv == thr, colv <= thr)
    gl = np.where(colv == r[:, 4].astype(np.int32), r[:, 5] > 0.5, gl)
    return np.where(active & ~gl, r[:, 6].astype(np.int32), leaf_id)




def test_auto_hist_mode_resolution(monkeypatch):
    """tpu_histogram_mode=auto picks the measured winner per backend:
    on TPU, when the wave engine will run it, pallas_ct for narrow
    shapes (ncols * bin_pad <= 2048) and pallas_t for wider
    VMEM-feasible ones; onehot on TPU otherwise; scatter on CPU
    (tools/AB_RESULTS.md, tools/BENCH_SUITE.md higgs_ct)."""
    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.ops.learner import SerialTreeLearner
    from lightgbm_tpu.io.dataset import TrainingData
    from lightgbm_tpu.utils.config import Config

    rng = np.random.default_rng(3)
    X = rng.normal(size=(600, 5))
    y = (X[:, 0] > 0).astype(np.float64)

    def learner_for(**over):
        cfg = Config(dict({"objective": "binary", "num_leaves": 7,
                           "verbose": -1}, **over))
        td = TrainingData.from_matrix(X, label=y, config=cfg)
        return SerialTreeLearner(cfg, td)

    # CPU truth (this process): scatter; auto precision stays hi/lo
    # off-TPU (no pallas kernel ever runs the bf16 product there)
    assert learner_for().hist_mode == "scatter"
    assert learner_for().hist_hilo is True

    # tpu_hist_precision is validated unconditionally (like
    # tpu_histogram_mode); bf16 resolves the kernels' hilo flag off
    from lightgbm_tpu.utils.log import LightGBMError
    with pytest.raises(LightGBMError):
        learner_for(tpu_hist_precision="f64")
    assert learner_for(tpu_hist_precision="bf16").hist_hilo is False
    assert learner_for(tpu_hist_precision="hilo").hist_hilo is True

    # fake the TPU backend: resolution must flip to pallas_t / onehot.
    # Clear the wave-core caches before AND after — cores built under the
    # fake bake use_pallas_hist=True into lru_cache entries whose static
    # keys later CPU tests could hit (dispatching real Pallas on CPU).
    from lightgbm_tpu.ops.wave import make_wave_core, make_wave_jit
    make_wave_core.cache_clear(); make_wave_jit.cache_clear()
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    # narrow-F under the fused-kernel bound (5 cols * 256-pad = 1280
    # <= 2048): the round-4 promoted pallas_ct (measured winner at
    # 10.5M x 28 and 1M x 28 — learner.py auto block)
    assert learner_for().hist_mode == "pallas_ct"
    assert learner_for(tpu_growth="exact").hist_mode == "onehot"
    # round-5 promoted auto precision (BENCH_NOTES.md "Armed decks"):
    # auto -> single-bf16-product where the pallas wave kernel runs;
    # exact growth (parity anchor) and explicit hilo stay hi/lo.  A
    # refactor reverting the auto resolution must fail here, not ship
    # a silent 1.63x flagship slowdown.
    assert learner_for().hist_hilo is False
    assert learner_for(tpu_growth="exact").hist_hilo is True
    assert learner_for(tpu_hist_precision="hilo").hist_hilo is True
    # ...and scoped to serial execution: the DP learner (psum_axis set)
    # keeps hi/lo — every bf16 gate was a single-chip serial arm
    cfg_dp = Config({"objective": "binary", "num_leaves": 7,
                     "verbose": -1})
    td_dp = TrainingData.from_matrix(X, label=y, config=cfg_dp)
    assert SerialTreeLearner(cfg_dp, td_dp,
                             psum_axis="d").hist_hilo is True
    # inside the round-5 widened fused-kernel bound (40 cols * 64-pad =
    # 2560 <= 8768): ct measured a 15% win at exactly this shape
    # (tools/BENCH_SUITE.md expo_ct 4.07 vs expo_cat 3.53 it/s)
    Xm = rng.normal(size=(600, 40))
    ym = (Xm[:, 0] > 0).astype(np.float64)
    cfgm = Config({"objective": "binary", "num_leaves": 7,
                   "max_bin": 63, "verbose": -1})
    tdm = TrainingData.from_matrix(Xm, label=ym, config=cfgm)
    assert SerialTreeLearner(cfgm, tdm).hist_mode == "pallas_ct"
    # past the bound but inside the VMEM gate: pallas_t stays (200 cols
    # * 64-pad = 12800 > 8768 — epsilon-class wide-F measured ct 5.6x
    # SLOWER, tools/BENCH_SUITE.md epsilon_ct)
    Xm2 = rng.normal(size=(600, 200))
    ym2 = (Xm2[:, 0] > 0).astype(np.float64)
    tdm2 = TrainingData.from_matrix(Xm2, label=ym2, config=cfgm)
    assert SerialTreeLearner(cfgm, tdm2).hist_mode == "pallas_t"
    assert learner_for(tpu_use_dp=True).hist_mode == "onehot"
    sp = learner_for(tpu_sparse=True)
    assert sp.hist_mode == "sparse"    # sparse store keeps its own path
    assert learner_for(tree_learner="voting").hist_mode == "onehot"

    # VMEM feasibility: a wide/high-bin shape whose in-VMEM histogram
    # block (ncols * bin_pad * 3W * 4B) exceeds the kernels' budget must
    # keep the HBM-streaming onehot engine (800 cols * 256-pad * 3 * 64
    # * 4B ~= 157 MB > 64 MB; 700 rows bin to >128 levels -> pad 256)
    Xw = rng.normal(size=(700, 800))
    yw = (Xw[:, 0] > 0).astype(np.float64)
    cfg = Config({"objective": "binary", "num_leaves": 255,
                  "max_bin": 255, "tpu_wave_width": 64, "verbose": -1})
    tdw = TrainingData.from_matrix(Xw, label=yw, config=cfg)
    assert SerialTreeLearner(cfg, tdw).hist_mode == "onehot"
    # wipe cores built under the fake before later CPU tests can hit them
    make_wave_core.cache_clear(); make_wave_jit.cache_clear()


def test_with_xt_grow_signature_matches():
    """make_wave_grow_fn(with_xt=True) takes Xt positionally and produces
    the identical tree off-TPU (where the kernel is bypassed and Xt is
    ignored) — the mesh learner's per-booster-Xt plumbing contract."""
    from lightgbm_tpu.ops.wave import make_wave_grow_fn
    from lightgbm_tpu.ops.learner import build_split_params
    from lightgbm_tpu.ops.split_finder import FeatureMeta
    from lightgbm_tpu.io.dataset import TrainingData
    from lightgbm_tpu.utils.config import Config
    import jax

    rng = np.random.default_rng(21)
    X = rng.normal(size=(900, 6))
    y = (X[:, 0] > 0).astype(np.float64)
    cfg = Config({"num_leaves": 15, "verbose": -1})
    td = TrainingData.from_matrix(X, label=y, config=cfg)
    meta = FeatureMeta(num_bin=jnp.asarray(td.num_bin_arr),
                       default_bin=jnp.asarray(td.default_bin_arr),
                       is_categorical=jnp.asarray(td.is_categorical_arr))
    common = dict(wave_width=4, hist_mode="pallas_t")
    g = (0.5 - y).astype(np.float32)
    h = np.full(len(y), 0.25, np.float32)
    args = (jnp.asarray(td.binned), jnp.asarray(g), jnp.asarray(h),
            jnp.ones(len(y), jnp.float32),
            jnp.ones(td.num_features, dtype=bool))
    grow0 = make_wave_grow_fn(15, int(td.num_bin_arr.max()), meta,
                              build_split_params(cfg), -1, **common)
    grow1 = make_wave_grow_fn(15, int(td.num_bin_arr.max()), meta,
                              build_split_params(cfg), -1, with_xt=True,
                              **common)
    t0, l0 = grow0(*args)
    t1, l1 = grow1(*args, jnp.transpose(args[0]))
    np.testing.assert_array_equal(np.asarray(l0), np.asarray(l1))
    np.testing.assert_array_equal(np.asarray(t0.leaf_value),
                                  np.asarray(t1.leaf_value))


def test_mesh_precomputes_xt_for_transposed_kernels(monkeypatch):
    """Under the data mesh with a transposed pallas mode on (a faked) TPU
    backend, the learner materializes the (F, N) transposed matrix ONCE
    per booster with a column sharding — not per tree inside the grow."""
    import jax
    from lightgbm_tpu.parallel.mesh import (DataParallelTreeLearner,
                                            make_data_mesh)
    from lightgbm_tpu.io.dataset import TrainingData
    from lightgbm_tpu.utils.config import Config

    rng = np.random.default_rng(22)
    X = rng.normal(size=(1100, 6))
    y = (X[:, 0] > 0).astype(np.float64)
    cfg = Config({"num_leaves": 15, "verbose": -1, "tree_learner": "data",
                  "tpu_histogram_mode": "pallas_t"})
    td = TrainingData.from_matrix(X, label=y, config=cfg)
    mesh = make_data_mesh(jax.devices()[:4])

    from lightgbm_tpu.ops.wave import make_wave_core, make_wave_jit
    make_wave_core.cache_clear(); make_wave_jit.cache_clear()
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    dp = DataParallelTreeLearner(cfg, td, mesh)
    assert dp._Xt is not None
    n_pad = dp.X.shape[0]
    assert dp._Xt.shape == (td.binned.shape[1], n_pad)
    # column-sharded: each device holds the transpose of its row shard
    spec = dp._Xt.sharding.spec
    assert tuple(spec) == (None, "data")

    # off-TPU (real backend): no Xt is pinned.  Cache-clear first: the
    # faked-backend cores above share static keys with real-CPU ones.
    monkeypatch.undo()
    make_wave_core.cache_clear(); make_wave_jit.cache_clear()
    dp2 = DataParallelTreeLearner(cfg, td, mesh)
    assert dp2._Xt is None


def test_tile_plan_block_legality():
    """Pallas TPU block rule: the row-tile c (the transposed kernels'
    LANES dim) must be a multiple of 128 unless it equals the padded
    array dim (c == n fallthrough).  fc=2000 (epsilon's width) caught
    the un-aligned 2096 tile on chip."""
    from lightgbm_tpu.ops.pallas_wave import _tile_plan, _bin_pad
    for fc in (28, 137, 968, 2000):
        for n in (513, 8192, 999424, 2_270_000):
            for row_tile in (1000, 8192):     # non-128-multiple too
                bsub, c = _tile_plan(n, fc, _bin_pad(64), row_tile)
                assert c % 128 == 0 or c == n, (fc, n, bsub, c)
                assert _bin_pad(64) % bsub == 0


def test_kernel_wide_shape_epsilon_width():
    """F=2000 (epsilon's width): the widest headline shape runs the
    transposed kernel end-to-end in interpret mode.  Pins the wide-fc
    tile plan + output reshape path that the on-chip epsilon failure
    exposed (tools/ab_err_suite_epsilon.log); auto resolves epsilon to
    pallas_t (49 MB hist block < the 64 MB gate), so this is the shape's
    production kernel."""
    rng = np.random.default_rng(0)
    n, f, b, k = 512, 2000, 63, 8
    X = rng.integers(0, b, size=(n, f), dtype=np.uint8)
    lid = rng.integers(0, 16, size=n).astype(np.int32)
    w3 = rng.normal(size=(n, 3)).astype(np.float32)
    cid = np.arange(k, dtype=np.int32)
    cid[5] = -1
    got = wave_histogram_pallas_t(jnp.asarray(X.T), jnp.asarray(lid),
                                  jnp.asarray(w3), jnp.asarray(cid), b,
                                  interpret=True)
    want = np.array(wave_histogram_reference(
        jnp.asarray(X), jnp.asarray(lid), jnp.asarray(w3),
        jnp.asarray(cid), b))
    want[cid < 0] = 0
    np.testing.assert_allclose(np.asarray(got), want, rtol=5e-4,
                               atol=5e-4)


def _compact_from_tbl(tbl, w):
    """(cols (W,10), psrc (W,)) compact operands from a dense (L,10)
    table — active rows scatter into slots, the rest get psrc=-3."""
    act = [l for l in range(len(tbl)) if tbl[l, 0] > 0.5]
    cols = np.zeros((w, 10), np.float32)
    psrc = np.full(w, -3, np.int32)
    for j, l in enumerate(act):
        cols[j] = tbl[l]
        psrc[j] = l
    return cols, psrc


@pytest.mark.parametrize("packed", [False, True])
def test_fused_compact_kernel_matches_oracle(packed):
    from lightgbm_tpu.ops.pallas_wave import wave_partition_hist_pallas_ct

    X, leaf_id, w3, cid, b = _data(n=2500, f=9 if packed else 7,
                                   b=15 if packed else 14, k=5, seed=9)
    L = 16
    rng = np.random.default_rng(10)
    leaf_id = rng.integers(0, 8, size=len(X)).astype(np.int32)
    tbl = np.zeros((L, 10), np.float32)
    for leaf in (1, 3, 5):
        tbl[leaf] = [1, rng.integers(0, X.shape[1]), rng.integers(0, b),
                     0, 0, rng.integers(0, 2), 8 + leaf, 0, 0, 0]
    cols, psrc = _compact_from_tbl(tbl, w=5)

    want_lid = _route_numpy(X, leaf_id, tbl)
    want_hist = np.array(wave_histogram_reference(
        jnp.asarray(X), jnp.asarray(want_lid), jnp.asarray(w3),
        jnp.asarray(cid), b))
    want_hist[np.asarray(cid) < 0] = 0.0

    if packed:
        Xdev = pack4_host(X).T
        lc = X.shape[1]
    else:
        Xdev, lc = X.T, 0
    got_lid, got_hist = wave_partition_hist_pallas_ct(
        jnp.asarray(Xdev), jnp.asarray(leaf_id), jnp.asarray(w3),
        jnp.asarray(cid), jnp.asarray(cols), jnp.asarray(psrc), b,
        interpret=True, logical_cols=lc)
    np.testing.assert_array_equal(np.asarray(got_lid), want_lid)
    np.testing.assert_allclose(np.asarray(got_hist), want_hist,
                               rtol=5e-4, atol=5e-4)


def test_fused_compact_kernel_bundled_remap():
    """The ct kernel's bundled branch (group offset / bin adjust / span
    remap) routes identically to the numpy oracle — nonzero goff/adj/span
    rows exercised, including out-of-range -> default-bin redirect."""
    from lightgbm_tpu.ops.pallas_wave import wave_partition_hist_pallas_ct

    X, leaf_id, w3, cid, b = _data(n=2200, f=7, b=14, k=5, seed=15)
    rng = np.random.default_rng(16)
    leaf_id = rng.integers(0, 8, size=len(X)).astype(np.int32)
    tbl = np.zeros((16, 10), np.float32)
    # leaf 2: group column 3, bins [4, 4+6) remap to adj 1, default bin 2
    tbl[2] = [1, 3, 5, 0, 2, 1, 10, 4, 1, 6]
    # leaf 5: group column 1, bins [0, 5), adj 0, default-right
    tbl[5] = [1, 1, 2, 0, 7, 0, 13, 0, 0, 5]
    cols, psrc = _compact_from_tbl(tbl, w=5)
    want_lid = _route_numpy(X, leaf_id, tbl, bundled=True)
    got_lid, _ = wave_partition_hist_pallas_ct(
        jnp.asarray(X.T), jnp.asarray(leaf_id), jnp.asarray(w3),
        jnp.asarray(cid), jnp.asarray(cols), jnp.asarray(psrc), b,
        bundled=True, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_lid), want_lid)


def test_kernel_hist_w_invariant_per_child():
    """Per-child histogram sums are independent of the wave width K:
    child c's (F, B, 3) block is bitwise identical whether the kernel
    runs with K=1 or c embedded in a K=5 slot set — each child owns its
    own output columns and tiles accumulate in the same order.  This is
    the structural property behind exact-order waves keeping the W
    ladder on TPU (tpu_wave_order=exact + pallas kernels)."""
    from lightgbm_tpu.ops.pallas_wave import (wave_histogram_pallas_t,
                                              wave_partition_hist_pallas_ct)
    X, leaf_id, w3, cid, b = _data(n=3100, f=7, b=14, k=5, seed=21)
    wide = np.asarray(wave_histogram_pallas_t(
        jnp.asarray(X.T), jnp.asarray(leaf_id), jnp.asarray(w3),
        jnp.asarray(cid), b, interpret=True))
    for j, c in enumerate(cid):
        if c < 0:
            continue
        solo = np.asarray(wave_histogram_pallas_t(
            jnp.asarray(X.T), jnp.asarray(leaf_id), jnp.asarray(w3),
            jnp.asarray(np.array([c], np.int32)), b, interpret=True))
        np.testing.assert_array_equal(solo[0], wide[j])

    # same property for the fused kernel (empty split table: routing is
    # the identity, so the hist half sees the same leaf ids)
    cols = np.zeros((5, 10), np.float32)
    psrc = np.full(5, -3, np.int32)
    _, wide_ct = wave_partition_hist_pallas_ct(
        jnp.asarray(X.T), jnp.asarray(leaf_id), jnp.asarray(w3),
        jnp.asarray(cid), jnp.asarray(cols), jnp.asarray(psrc), b,
        interpret=True)
    wide_ct = np.asarray(wide_ct)
    for j, c in enumerate(cid):
        if c < 0:
            continue
        _, solo_ct = wave_partition_hist_pallas_ct(
            jnp.asarray(X.T), jnp.asarray(leaf_id), jnp.asarray(w3),
            jnp.asarray(np.array([c], np.int32)),
            jnp.asarray(cols[:1]), jnp.asarray(psrc[:1]), b,
            interpret=True)
        np.testing.assert_array_equal(np.asarray(solo_ct)[0], wide_ct[j])
