"""Rank-encoded device bulk prediction (ops/predict.py RankedPredictor):
leaf ROUTING must be bit-equal to the host f64 predictor — the ranks
encode every f64 threshold compare — including the zero-range default
redirect, NaN-goes-right, and integer-cast categorical equality; scores
match the host f64 sums to f32 rounding."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops import predict as dev_predict


def _train(X, y, params, rounds=10):
    p = dict({"verbose": -1, "num_leaves": 15, "min_data_in_leaf": 5},
             **params)
    return lgb.train(p, lgb.Dataset(X, label=y, params=p),
                     num_boost_round=rounds)


def _routing_and_scores(bst, Xq):
    g = bst._gbdt
    g._materialize()
    k = g.num_tree_per_iteration
    rp = dev_predict.build_ranked_predictor(g.models, k, Xq.shape[1])
    V, D = dev_predict.rank_encode(rp, Xq)
    import jax.numpy as jnp
    leaves = np.asarray(dev_predict.ranked_leaf_indices_device(
        rp.dev, jnp.asarray(V), jnp.asarray(D)))
    score = np.asarray(dev_predict.ranked_predict_device(
        rp.dev, jnp.asarray(V), jnp.asarray(D), k))
    host_leaves = np.stack(
        [t.predict_leaf_index(np.asarray(Xq, np.float64))
         for t in g.models], axis=1)
    host_raw = np.zeros((len(Xq), k))
    for t, tree in enumerate(g.models):
        host_raw[:, t % k] += tree.predict(np.asarray(Xq, np.float64))
    return leaves, host_leaves, score, host_raw


def test_routing_bit_equal_binary_with_zeros_and_nan():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(4000, 6))
    X[rng.random(X.shape) < 0.2] = 0.0          # exercise zero redirect
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    bst = _train(X, y, {"objective": "binary"})
    Xq = X.copy()
    Xq[rng.random(Xq.shape) < 0.05] = np.nan    # NaN -> right
    Xq[rng.random(Xq.shape) < 0.05] = 0.0
    leaves, host_leaves, score, host_raw = _routing_and_scores(bst, Xq)
    np.testing.assert_array_equal(leaves, host_leaves)
    np.testing.assert_allclose(score, host_raw, rtol=2e-6, atol=2e-6)


def test_routing_bit_equal_categorical_multiclass():
    rng = np.random.default_rng(4)
    X = rng.normal(size=(3000, 5))
    X[:, 0] = rng.integers(0, 12, size=3000)
    X[:, 1] = rng.integers(0, 5, size=3000)
    y = rng.integers(0, 3, size=3000).astype(np.float64)
    bst = _train(X, y, {"objective": "multiclass", "num_class": 3,
                        "categorical_feature": [0, 1]}, rounds=5)
    Xq = X.copy()
    Xq[:50, 0] = 99.0                           # unseen category
    leaves, host_leaves, score, host_raw = _routing_and_scores(bst, Xq)
    np.testing.assert_array_equal(leaves, host_leaves)
    np.testing.assert_allclose(score, host_raw, rtol=2e-6, atol=2e-6)


def test_bulk_predict_engages_and_matches(monkeypatch):
    """tpu_predict=true forces the device path through Booster.predict;
    results match the host path (tpu_predict=false) to f32 rounding."""
    rng = np.random.default_rng(5)
    X = rng.normal(size=(2500, 6))
    y = X[:, 0] * 2 + X[:, 2] + 0.1 * rng.normal(size=2500)
    bst = _train(X, y, {"objective": "regression"})
    g = bst._gbdt
    g.config = g.config.copy_with(tpu_predict="true")
    p_dev = bst.predict(X)
    calls = {"n": 0}
    orig = dev_predict.ranked_predict_device

    def spy(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)
    monkeypatch.setattr(dev_predict, "ranked_predict_device", spy)
    g.config = g.config.copy_with(tpu_predict="true")
    g._ranked_pred_key = None
    p_dev2 = bst.predict(X)
    assert calls["n"] >= 1, "device path did not engage"
    g.config = g.config.copy_with(tpu_predict="false")
    p_host = bst.predict(X)
    np.testing.assert_allclose(p_dev, p_host, rtol=2e-6, atol=2e-6)
    np.testing.assert_allclose(p_dev2, p_host, rtol=2e-6, atol=2e-6)


def test_loaded_model_device_predict(tmp_path):
    """A Booster loaded from a model FILE (real-valued thresholds only)
    routes identically on device."""
    rng = np.random.default_rng(6)
    X = rng.normal(size=(2000, 5))
    y = (X[:, 0] - 0.3 * X[:, 4] > 0).astype(np.float64)
    bst = _train(X, y, {"objective": "binary"})
    fn = str(tmp_path / "m.txt")
    bst.save_model(fn)
    loaded = lgb.Booster(model_file=fn)
    g = loaded._gbdt
    g._materialize()
    rp = dev_predict.build_ranked_predictor(
        g.models, g.num_tree_per_iteration, X.shape[1])
    V, D = dev_predict.rank_encode(rp, X)
    import jax.numpy as jnp
    leaves = np.asarray(dev_predict.ranked_leaf_indices_device(
        rp.dev, jnp.asarray(V), jnp.asarray(D)))
    host_leaves = np.stack(
        [t.predict_leaf_index(np.asarray(X, np.float64))
         for t in g.models], axis=1)
    np.testing.assert_array_equal(leaves, host_leaves)
