"""Deterministic per-seed RNG with the reference's sequence semantics.

Reference: include/LightGBM/utils/random.h:14-112 — an LCG (x = 214013*x +
2531011) with 15/31-bit extraction and a two-regime ``Sample(N, K)``
(Bernoulli sweep when K > N/2, random-stride jump otherwise).  Reproducing the
exact integer sequence keeps feature_fraction / bagging subsets identical to
the reference for a given seed, which matters for convergence-parity tests.
"""
from __future__ import annotations

import numpy as np


class Random:
    def __init__(self, seed: int = 123456789):
        self.x = int(seed) & 0xFFFFFFFF

    def _next(self) -> int:
        self.x = (214013 * self.x + 2531011) & 0xFFFFFFFF
        return self.x

    def next_short(self, lower: int, upper: int) -> int:
        """Random int in [lower, upper) from the 15-bit extraction."""
        r = (self._next() >> 16) & 0x7FFF
        return r % (upper - lower) + lower

    def next_int(self, lower: int, upper: int) -> int:
        r = self._next() & 0x7FFFFFFF
        return r % (upper - lower) + lower

    def next_float(self) -> float:
        r = (self._next() >> 16) & 0x7FFF
        return r / 32768.0

    def sample(self, n: int, k: int) -> np.ndarray:
        """K ordered samples from {0..N-1}; sequence-identical to
        ``Random::Sample`` (random.h:65-95)."""
        ret = []
        if k > n or k < 0:
            return np.asarray(ret, dtype=np.int32)
        if k == n:
            return np.arange(n, dtype=np.int32)
        if k > n // 2:
            for i in range(n):
                prob = (k - len(ret)) / float(n - i)
                if self.next_float() < prob:
                    ret.append(i)
        else:
            min_step = 1
            avg_step = n // k
            max_step = 2 * avg_step - min_step
            start = -1
            for _ in range(k):
                step = self.next_short(min_step, max_step + 1)
                start += step
                if start >= n:
                    break
                ret.append(start)
        return np.asarray(ret, dtype=np.int32)
