"""Recompile attribution, straggler profiling, and the obs query CLI.

Covers the PR-3 attribution layer on the CPU backend: signature
diffing down to the offending axis, CompileTracker driven through the
observer hooks on a real jitted function, end-to-end attribution when
a learner is rebuilt against shape-unstable input, the straggler
profiler on both a single device (no-op) and the virtual 8-device mesh,
the ``python -m lightgbm_tpu obs`` subcommands, bench_compare's
``recompile_count`` gate, and forward/backward schema compatibility.
"""
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import RunObserver, read_events
from lightgbm_tpu.obs.compile import (CompileTracker, arg_signature,
                                      diff_signatures, format_diff,
                                      render_signature)
from lightgbm_tpu.obs.straggler import StragglerProfiler
from lightgbm_tpu.obs import query
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.ops.learner import SerialTreeLearner
from lightgbm_tpu.utils.config import Config

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _obs(path, **kw):
    kw.setdefault("timing", "off")
    return RunObserver(events_path=str(path), **kw)


def _drive(obs, name, fn, *args, names=None):
    """One observed entry call, the way the learners drive the hooks."""
    obs.entry_args(name, fn, args, names=names)
    t0 = obs.entry_start()
    out = fn(*args)
    obs.entry_end(name, t0, out)
    return out


# ----------------------------------------------------------- signatures

def test_signature_render_and_axis_diff():
    x = jnp.zeros((8, 4), jnp.float32)
    g = jnp.zeros(8, jnp.float32)
    sig = arg_signature((x, g), names=("x", "grad"), donate=(1,))
    assert render_signature(sig) == {"x": "float32[8,4]",
                                     "grad": "float32[8] (donated)"}
    sig2 = arg_signature((jnp.zeros((8, 5), jnp.float32), g),
                         names=("x", "grad"), donate=(1,))
    diff = diff_signatures(sig, sig2)
    assert diff == [{"arg": "x", "field": "shape", "axis": 1,
                     "before": 4, "after": 5}]
    assert format_diff(diff[0]) == "x.shape[1]: 4 -> 5"
    # first compile has nothing to diff against
    assert diff_signatures(None, sig) == []


def test_diff_catches_dtype_rank_and_donation():
    a = arg_signature((jnp.zeros((4, 2), jnp.float32),), names=("x",))
    fields = {d["field"] for d in diff_signatures(
        a, arg_signature((jnp.zeros((4, 2), jnp.int32),), names=("x",)))}
    assert fields == {"dtype"}
    fields = {d["field"] for d in diff_signatures(
        a, arg_signature((jnp.zeros(4, jnp.float32),), names=("x",)))}
    assert fields == {"rank"}
    fields = {d["field"] for d in diff_signatures(
        a, arg_signature((jnp.zeros((4, 2), jnp.float32),), names=("x",),
                         donate=(0,)))}
    assert fields == {"donated"}


# ------------------------------------------------------- CompileTracker

def test_tracker_attributes_recompile_to_changed_axis(tmp_path):
    path = tmp_path / "ev.jsonl"
    obs = _obs(path, compile_attr=True)
    fn = jax.jit(lambda x: (x * 2.0).sum(axis=0))
    with obs:
        _drive(obs, "f", fn, jnp.ones((8, 4), jnp.float32), names=("x",))
        _drive(obs, "f", fn, jnp.ones((8, 4), jnp.float32), names=("x",))
        _drive(obs, "f", fn, jnp.ones((8, 5), jnp.float32), names=("x",))
    attr = [e for e in read_events(path) if e["ev"] == "compile_attr"]
    assert len(attr) == 2          # the repeat call hit the jit cache
    first, second = attr
    assert first["n_compiles"] == 1 and first["diff"] == []
    assert first["sig"] == {"x": "float32[8,4]"}
    assert second["n_compiles"] == 2 and second["sig_compiles"] == 1
    assert {"arg": "x", "field": "shape", "axis": 1,
            "before": 4, "after": 5} in second["diff"]
    # AOT analysis works on the CPU backend: both estimates present
    assert second["cost"]["flops"] > 0
    assert second["memory"]["argument_bytes"] > 0
    assert "output_bytes" in second["memory"]
    # run_end folds the per-entry summary
    end = [e for e in read_events(path) if e["ev"] == "run_end"][-1]
    assert end["compile_attr"]["f"] == {"calls": 3, "compiles": 2,
                                        "signatures": 2,
                                        "max_sig_compiles": 1}


def test_tracker_flags_program_rebuild_as_thrash(tmp_path):
    path = tmp_path / "ev.jsonl"
    obs = _obs(path, compile_attr=True)
    x = jnp.ones((16,), jnp.float32)
    with obs:
        # rebuilding the jitted program per call recompiles the SAME
        # signature — the thrash case the CI gate exists for
        _drive(obs, "f", jax.jit(lambda v: v + 1.0), x, names=("x",))
        _drive(obs, "f", jax.jit(lambda v: v + 1.0), x, names=("x",))
    attr = [e for e in read_events(path) if e["ev"] == "compile_attr"]
    assert attr[-1]["sig_compiles"] == 2
    assert attr[-1]["diff"][0]["field"] == "program"
    events = query.last_run(query.load_timeline(str(path)))
    assert query.render_recompiles(events, out=open(os.devnull, "w")) \
        is True


def test_tracker_does_not_pin_non_weakrefable_callable(tmp_path):
    """A __slots__ callable cannot be weakref'd; the tracker must not
    fall back to a strong reference that pins the program (and whatever
    it closes over) for the tracker's lifetime."""
    import gc
    import weakref

    class Canary:                # weakrefable marker held only by fn
        pass

    class Slotted:
        __slots__ = ("canary",)

        def __call__(self, x):
            return x

    path = tmp_path / "ev.jsonl"
    obs = _obs(path, compile_attr=True)
    x = jnp.ones((4,), jnp.float32)
    fn = Slotted()
    fn.canary = Canary()
    ref = weakref.ref(fn.canary)
    with obs:
        _drive(obs, "g", fn, x, names=("x",))
        _drive(obs, "g", fn, x, names=("x",))     # sentinel path reused
    attr = [e for e in read_events(path) if e["ev"] == "compile_attr"]
    assert len(attr) == 1        # repeat signature, no phantom rebuild
    del fn
    gc.collect()
    assert ref() is None         # the tracker held no strong reference


def test_learner_rebuild_names_the_row_axis(tmp_path):
    """Shape-unstable input end to end: two learners whose padded row
    counts differ, under one observer; the second compile_attr must
    name axis 0 of the gradient arrays AND the program rebuild.  The
    row sizes straddle a padding bucket so the device shapes really
    change (the learner pads rows, so 256 vs 512 would both land on the
    same padded size and diff only as a program rebuild)."""
    path = tmp_path / "ev.jsonl"
    obs = _obs(path, compile_attr=True)
    cfg = Config({"num_leaves": 7, "min_data_in_leaf": 5, "verbose": -1})
    rng = np.random.default_rng(0)
    with obs:
        for n in (600, 1500):
            X = rng.normal(size=(n, 4))
            y = (X[:, 0] > 0).astype(np.float64)
            td = TrainingData.from_matrix(X, label=y, config=cfg)
            lr = SerialTreeLearner(cfg, td)
            lr.set_observer(obs)
            g = rng.normal(size=n).astype(np.float32)
            h = np.full(n, 0.25, np.float32)
            lr.train(g, h)
    attr = [e for e in read_events(path) if e["ev"] == "compile_attr"]
    assert len(attr) == 2 and attr[0]["entry"] == "tree_grow"
    diff = attr[-1]["diff"]
    assert diff[0]["field"] == "program"
    rows = [d for d in diff if d.get("arg") == "grad"
            and d.get("field") == "shape"]
    assert rows and rows[0]["axis"] == 0
    assert rows[0]["before"] < rows[0]["after"]
    assert attr[-1]["sig"]["grad"] == "float32[%d]" % rows[0]["after"]


def test_end_to_end_train_emits_compile_attr(tmp_path):
    path = tmp_path / "ev.jsonl"
    rng = np.random.default_rng(1)
    X = rng.normal(size=(300, 5))
    y = (X[:, 0] > 0).astype(np.float64)
    lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
               "obs_events_path": str(path), "obs_compile": True},
              lgb.Dataset(X, label=y), num_boost_round=2)
    events = query.last_run(query.load_timeline(str(path)))
    rows = query.recompile_rows(events)
    assert rows and rows[0]["entry"] == "tree_grow"
    # a shape-stable run compiles once and never again
    assert query.recompile_count(events) == 0
    end = events[-1]
    assert end["ev"] == "run_end"
    assert end["compile_attr"]["tree_grow"]["compiles"] == 1
    assert end["compile_attr"]["tree_grow"]["calls"] == 2


# ------------------------------------------------------------ straggler

def test_straggler_noop_on_single_device(tmp_path):
    path = tmp_path / "ev.jsonl"
    obs = _obs(path, straggler_every=1)
    with obs:
        obs.straggler_sample(0, jnp.ones(32, jnp.float32))
        obs.straggler_sample(1, {"leaf": jnp.ones(8)})
    events = read_events(path)
    assert not [e for e in events if e["ev"] == "straggler"]
    summ = events[-1]["stragglers"]
    assert summ["samples"] == 0
    assert summ["skipped_single_device"] == 2


def test_straggler_cadence_gates_sampling(tmp_path):
    obs = _obs(tmp_path / "ev.jsonl", straggler_every=3)
    prof = obs._straggler
    assert [it for it in range(10) if prof.due(it)] == [0, 3, 6, 9]
    assert StragglerProfiler(every=0).due(0) is False


def _sharded(n=64):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()), ("data",))
    return jax.device_put(np.arange(n, dtype=np.float32),
                          NamedSharding(mesh, P("data")))


def test_straggler_sample_on_virtual_mesh(tmp_path):
    path = tmp_path / "ev.jsonl"
    obs = _obs(path, straggler_every=1)
    with obs:
        obs.straggler_sample(0, _sharded())
    events = read_events(path)
    samples = [e for e in events if e["ev"] == "straggler"]
    assert len(samples) == 1
    s = samples[0]
    assert len(s["devices"]) == 8
    ids = {d["id"] for d in s["devices"]}
    assert s["slowest"] in ids and len(ids) == 8
    assert 0.0 <= s["skew"] <= 1.0
    assert s["axis"] == "data"
    summ = events[-1]["stragglers"]
    assert summ["samples"] == 1
    assert summ["slowest_counts"] == {str(s["slowest"]): 1}


def test_straggler_warn_routes_through_health_channel(tmp_path):
    path = tmp_path / "ev.jsonl"
    # warn_skew=-1: every sample warns, deterministically
    obs = _obs(path, straggler_every=1, straggler_warn_skew=-1.0)
    with obs:
        obs.straggler_sample(0, _sharded())
    events = read_events(path)
    warns = [e for e in events if e["ev"] == "health"]
    assert len(warns) == 1
    assert warns[0]["check"] == "straggler_skew"
    assert warns[0]["status"] == "warn"
    assert warns[0]["detail"]["slowest"] == \
        [e for e in events if e["ev"] == "straggler"][0]["slowest"]
    assert events[-1]["stragglers"]["warned"] == 1


# ------------------------------------------------------------ query CLI

@pytest.fixture(scope="module")
def timeline(tmp_path_factory):
    """One instrumented 3-iteration training run, queried many ways."""
    path = tmp_path_factory.mktemp("obs") / "run.jsonl"
    rng = np.random.default_rng(2)
    X = rng.normal(size=(400, 6))
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
               "obs_events_path": str(path), "obs_compile": True,
               "obs_straggler_every": 1},
              lgb.Dataset(X, label=y), num_boost_round=3)
    return str(path)


def test_cli_summary(timeline, capsys):
    assert query.main(["summary", timeline]) == 0
    out = capsys.readouterr().out
    assert "status ok" in out
    assert "iters 3" in out
    assert "recompiles: 0 beyond first compile" in out
    assert "entry tree_grow" in out


def test_cli_recompiles_clean(timeline, capsys):
    assert query.main(["recompiles", timeline, "--check"]) == 0
    out = capsys.readouterr().out
    assert "tree_grow" in out
    assert "first compile" in out
    assert "THRASH" not in out


def test_cli_stragglers_and_diff(timeline, capsys):
    # serial CPU learner -> single device -> no straggler events
    assert query.main(["stragglers", timeline]) == 0
    assert "no straggler events" in capsys.readouterr().out
    assert query.main(["diff", timeline, timeline]) == 0
    out = capsys.readouterr().out
    assert "recompile_count" in out
    for line in out.splitlines():
        if line.startswith(("iters", "compile_s", "recompile_count")):
            assert line.rstrip().endswith(("+0.0%", "+0%"))


def test_cli_trace_export(timeline, tmp_path, capsys):
    out_path = str(tmp_path / "trace.json")
    assert query.main(["trace", timeline, "-o", out_path]) == 0
    with open(out_path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    names = {e["name"] for e in evs}
    assert {"iter 0", "iter 1", "iter 2"} <= names
    assert {"boost", "grow", "partition"} <= names
    assert any(n.startswith("recompile:tree_grow") for n in names)
    spans = [e for e in evs if e["ph"] == "X"]
    assert all(e["dur"] >= 0 and e["ts"] >= 0 for e in spans)


def test_cli_missing_file_is_usage_error(tmp_path, capsys):
    assert query.main(["summary", str(tmp_path / "nope.jsonl")]) == 2
    assert "error:" in capsys.readouterr().err


def _synth_timeline(path, run, n_compiles, extra_sig_compiles=1):
    """A minimal schema-valid timeline with a controllable recompile
    count (the shape bench_compare and --check gate on)."""
    recs = [{"ev": "run_header", "t": 0.0, "run": run, "schema": 3,
             "backend": "cpu", "devices": [{"id": 0}], "params": {},
             "context": {}, "timing": "phase"}]
    t = 1.0
    for i in range(2):
        recs.append({"ev": "iter", "t": t, "run": run, "it": i,
                     "time_s": 0.5, "phases": {"grow": 0.4}, "fenced": True})
        t += 1.0
    for n in range(1, n_compiles + 1):
        recs.append({"ev": "compile_attr", "t": t, "run": run,
                     "entry": "tree_grow", "n_compiles": n,
                     "sig": {"x": "float32[%d,4]" % (8 * n)},
                     "sig_compiles": extra_sig_compiles if n > 1 else 1,
                     "diff": [] if n == 1 else
                     [{"arg": "x", "field": "shape", "axis": 0,
                       "before": 8 * (n - 1), "after": 8 * n}]})
    recs.append({"ev": "run_end", "t": t + 1, "run": run, "iters": 2,
                 "phase_totals": {"grow": 0.8}, "entries": {},
                 "status": "ok"})
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    return str(path)


def test_cli_check_exits_1_on_thrash(tmp_path, capsys):
    p = _synth_timeline(tmp_path / "thrash.jsonl", "r1", n_compiles=2,
                        extra_sig_compiles=3)
    assert query.main(["recompiles", p, "--check"]) == 1
    assert "THRASH" in capsys.readouterr().out
    # without --check the same timeline only reports
    assert query.main(["recompiles", p]) == 0
    capsys.readouterr()


# --------------------------------------------------------- perf gating

def _bench_compare(argv):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_compare
    finally:
        sys.path.pop(0)
    return bench_compare.main(argv)


def test_bench_compare_gates_on_recompiles(tmp_path, capsys):
    clean = _synth_timeline(tmp_path / "clean.jsonl", "a", n_compiles=1)
    churn = _synth_timeline(tmp_path / "churn.jsonl", "b", n_compiles=3)
    assert _bench_compare([clean, clean]) == 0
    capsys.readouterr()
    assert _bench_compare([clean, churn]) == 1
    out = capsys.readouterr().out
    assert "recompile_count" in out and "REGRESSED" in out
    # a regressed candidate used as its own baseline still passes
    assert _bench_compare([churn, churn]) == 0
    capsys.readouterr()


def test_obs_diff_shows_regression(tmp_path, capsys):
    clean = _synth_timeline(tmp_path / "clean.jsonl", "a", n_compiles=1)
    churn = _synth_timeline(tmp_path / "churn.jsonl", "b", n_compiles=3)
    assert query.main(["diff", clean, churn]) == 0
    out = capsys.readouterr().out
    row = [ln for ln in out.splitlines()
           if ln.startswith("recompile_count")]
    assert row and row[0].rstrip().endswith("new")


# -------------------------------------------------------------- compat

def test_schema_v2_timeline_still_loads(tmp_path):
    p = tmp_path / "v2.jsonl"
    recs = [{"ev": "run_header", "t": 0.0, "run": "old", "schema": 2,
             "backend": "cpu", "devices": [{"id": 0}], "params": {},
             "context": {}, "timing": "phase"},
            {"ev": "iter", "t": 1.0, "run": "old", "it": 0, "time_s": 0.5,
             "phases": {"grow": 0.4}, "fenced": True},
            {"ev": "run_end", "t": 2.0, "run": "old", "iters": 1,
             "phase_totals": {"grow": 0.4}, "entries": {},
             "status": "ok"}]
    with open(p, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    events = query.last_run(query.load_timeline(str(p)))
    m = query.timeline_metrics(events)
    assert m["schema"] == 2 and m["iters"] == 1
    # pre-v3 runs simply have no recompile data, not a zero
    assert "recompile_count" not in m
    assert query.recompile_count(events) == 0


def test_unknown_future_event_passes_loader(tmp_path):
    p = tmp_path / "v9.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"ev": "quantum_flux", "t": 0.0, "run": "f",
                            "qubits": 3}) + "\n")
    events = query.load_timeline(str(p))
    assert events[0]["ev"] == "quantum_flux"


def test_config_aliases_resolve():
    cfg = Config({"obs_compile_attr": "true", "obs_straggler_freq": "4",
                  "obs_straggler_skew": "0.3", "verbose": -1})
    assert cfg.obs_compile is True
    assert cfg.obs_straggler_every == 4
    assert cfg.obs_straggler_warn_skew == 0.3
