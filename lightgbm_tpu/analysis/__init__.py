"""graftlint — the repo-native static analyzer (docs/StaticAnalysis.md).

Five passes prove the hot-path invariants on a CPU-only runner, each
the static twin of a runtime audit:

  hostsync   implicit device->host syncs in ops//models/gbdt.py/serve/
             (runtime twin: the bench.py --dry fence-count assert)
  recompile  jit-cache hazards at decorator and call sites
             (runtime twin: obs recompiles --check)
  events     emit sites vs the obs/events.py field tables
             (runtime twin: validate_event, which sees only runs)
  config     Config reads vs utils/config.py vs docs/Parameters.md
  vmem       the Pallas tile planners evaluated over the autotuner's
             shape grid against the VMEM budgets
             (runtime twin: the v5e probes behind docs/Autotuning.md)

Entry point: ``python -m lightgbm_tpu lint`` (analysis/cli.py).
"""
from .core import (Finding, LintInternalError, rule_catalog,  # noqa: F401
                   run_lint)
