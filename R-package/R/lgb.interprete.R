# Per-prediction feature contributions — parity with
# R-package/R/lgb.interprete.R: for each observation, walk each tree's
# root-to-leaf path and attribute the change in expected value at every
# split to its feature.

#' Feature contributions for individual predictions
#'
#' @param model lgb.Booster
#' @param data feature matrix
#' @param idxset 1-based row indices to interpret
#' @return list (one per row) of data.frames Feature / Contribution,
#'   sorted by absolute contribution
#' @export
lgb.interprete <- function(model, data, idxset, num_iteration = -1L) {
  if (!lgb.is.Booster(model)) stop("lgb.interprete: need an lgb.Booster")
  if (is.data.frame(data)) data <- data.matrix(data)
  dump <- lgb.dump(model, num_iteration = num_iteration)
  feat_names <- unlist(dump$feature_names)

  interpret_row <- function(x) {
    contrib <- stats::setNames(numeric(length(feat_names)), feat_names)
    for (t in dump$tree_info) {
      node <- t$tree_structure
      prev <- as.numeric(node$internal_value)
      while (is.null(node$leaf_value) || !is.null(node$split_feature)) {
        f <- as.integer(node$split_feature) + 1L
        thr <- as.numeric(node$threshold)
        v <- x[f]
        # mirror Tree.predict (models/tree.py:125-142): values in the
        # missing range take the node's default_value redirect; the dump
        # writes decision_type "is" (categorical ==) or "no_greater"
        # (numerical <=); NaN comparisons go RIGHT like the C++ <=
        if (!is.na(v) && v > -1e-20 && v <= 1e-20) {
          v <- as.numeric(node$default_value)
        }
        go_left <- if (identical(node$decision_type, "is")) {
          !is.na(v) && as.integer(v) == as.integer(thr)
        } else {
          !is.na(v) && v <= thr
        }
        node <- if (go_left) node$left_child else node$right_child
        val <- if (!is.null(node$leaf_value) && is.null(node$split_feature)) {
          as.numeric(node$leaf_value)
        } else {
          as.numeric(node$internal_value)
        }
        contrib[f] <- contrib[f] + (val - prev)
        prev <- val
      }
    }
    out <- data.frame(Feature = names(contrib),
                      Contribution = as.numeric(contrib),
                      stringsAsFactors = FALSE)
    out <- out[out$Contribution != 0, , drop = FALSE]
    out <- out[order(-abs(out$Contribution)), , drop = FALSE]
    rownames(out) <- NULL
    out
  }

  lapply(idxset, function(i) interpret_row(as.numeric(data[i, ])))
}
