"""Native data plane vs pure-Python parity (cpp/src/native.cpp)."""
import numpy as np
import pytest

from lightgbm_tpu import native
from lightgbm_tpu.io.binning import BinMapper, NUMERICAL
from lightgbm_tpu.models.tree import Tree

needs_native = pytest.mark.skipif(not native.available(),
                                  reason="native lib not built")


def _python_find_bin(values, total, max_bin, mdib, msd):
    """Run the pure-Python path regardless of the native lib."""
    import lightgbm_tpu.native as nat
    saved = nat._LIB, nat._TRIED
    nat._LIB, nat._TRIED = None, True
    try:
        m = BinMapper()
        m.find_bin(values, total, max_bin, mdib, msd, NUMERICAL)
        return m
    finally:
        nat._LIB, nat._TRIED = saved


@needs_native
@pytest.mark.parametrize("seed", range(5))
def test_find_bin_matches_python(seed):
    rng = np.random.default_rng(seed)
    n = 5000
    vals = rng.normal(size=n) * (rng.random(n) > 0.3)   # some zeros
    nonzero = vals[vals != 0.0]
    total = n
    mp = _python_find_bin(nonzero, total, 63, 3, 5)
    mn = BinMapper()
    mn.find_bin(nonzero, total, 63, 3, 5, NUMERICAL)
    assert mn.num_bin == mp.num_bin
    np.testing.assert_allclose(mn.bin_upper_bound, mp.bin_upper_bound)
    assert mn.default_bin == mp.default_bin
    assert mn.is_trivial == mp.is_trivial
    xs = rng.normal(size=200)
    np.testing.assert_array_equal(mn.value_to_bin(xs), mp.value_to_bin(xs))


@needs_native
def test_parse_file_matches_python(tmp_path):
    from lightgbm_tpu.io import parser
    rng = np.random.default_rng(0)
    X = rng.normal(size=(50, 4))
    y = rng.integers(0, 2, 50)
    path = str(tmp_path / "data.tsv")
    np.savetxt(path, np.column_stack([y, X]), fmt="%.6f", delimiter="\t")
    feat, lab = native.parse_file(path, False, 0)
    parsed = parser.parse_file(path)
    np.testing.assert_allclose(feat, parsed.features, atol=1e-12)
    np.testing.assert_allclose(lab, parsed.label, atol=1e-12)


@needs_native
def test_native_predict_matches_python():
    t = Tree(4)
    t.split(0, 0, False, 1, 0, 0.5, -1.0, 1.0, 10, 20, 5.0, 0, 0, 0.0)
    t.split(1, 2, False, 3, 2, -0.2, 0.5, 2.0, 8, 12, 3.0, 1, 1, 0.0)
    rng = np.random.default_rng(1)
    X = rng.normal(size=(100, 3))
    X[::7, 0] = 0.0   # exercise the zero-redirect path
    py = t.predict(X)
    nat = native.predict_raw([(t, 0)], 1, X)
    np.testing.assert_allclose(nat[:, 0], py, rtol=1e-15)


@needs_native
def test_end_to_end_with_native_binning():
    import lightgbm_tpu as lgb
    rng = np.random.default_rng(2)
    X = rng.normal(size=(800, 6))
    y = (X[:, 0] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "verbose": -1},
                    lgb.Dataset(X, label=y), num_boost_round=10,
                    verbose_eval=False)
    p = bst.predict(X)
    assert ((p > 0.5) == (y > 0)).mean() > 0.93
