"""Wave growth engine (ops/wave.py) correctness.

* wave_width=1 must reproduce the exact leaf-wise grower bit for bit
  (same argmax order, same node numbering) — serial and under the data
  mesh.
* wave_width>1 batches the top-W frontier: the tree differs only in split
  scheduling, so row accounting and quality must hold.
* data-parallel wave == serial wave, exact structure (the psum'd wave
  histogram block must reproduce single-shard histograms).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.ops.grow import make_grow_fn
from lightgbm_tpu.ops.learner import build_split_params
from lightgbm_tpu.ops.split_finder import FeatureMeta
from lightgbm_tpu.ops.wave import make_wave_grow_fn
from lightgbm_tpu.utils.config import Config

N, F, L = 6000, 8, 31


def test_wave_width_auto_policy():
    """tpu_wave_width=-1 scales with num_leaves; explicit values win."""
    from lightgbm_tpu.ops.learner import resolve_wave_width
    assert resolve_wave_width(Config({"verbose": -1}), 15) == 8
    assert resolve_wave_width(Config({"verbose": -1}), 63) == 16
    assert resolve_wave_width(Config({"verbose": -1}), 255) == 32
    cfg = Config({"verbose": -1, "tpu_wave_width": 1})
    assert resolve_wave_width(cfg, 255) == 1


def test_tile_plan_covers_the_measured_band_cells():
    """The 18-30 MB band escape (BENCH_NOTES.md r4/r5: epsilon W16 43x
    slower, bosch W32 10.8x, yahoo's W=64 'escape' itself 3.2x slower)
    was deleted — root cause was the row-tile planner ignoring the
    VMEM-resident accumulator block (ops/pallas_wave.py::_tile_plan).
    Every measured cell the band encoded must land right under the
    live-set accounting: the slow cells are pathological under the old
    plan and fixed under the new one, and the cells that measured fine
    (yahoo W32, the flagship) keep their full row tile."""
    from lightgbm_tpu.ops.pallas_wave import tile_plan_vmem_report
    for fc, bp, k in [(2000, 64, 16), (968, 64, 32)]:   # epsilon, bosch
        rep = tile_plan_vmem_report(6000, fc, bp, k)
        assert rep["pathological_old"] and not rep["pathological_new"]
    for fc, bp, k in [(699, 64, 32), (28, 64, 32)]:     # yahoo, flagship
        rep = tile_plan_vmem_report(1 << 20, fc, bp, k)
        assert not rep["pathological_old"]
        assert rep["c_new"] == rep["c_old"]


def test_auto_width_no_longer_bent_in_serial_learner(monkeypatch):
    """With the band escape gone the learner's AUTO width is exactly the
    resolve_wave_width ladder even where the pallas wave kernel will run
    (faked TPU backend; the 1200-col 255-leaf shape used to bend
    32 -> 64), and an explicit width still passes through untouched."""
    import jax
    from lightgbm_tpu.ops.learner import SerialTreeLearner
    from lightgbm_tpu.ops.wave import make_wave_core, make_wave_jit

    rng = np.random.default_rng(23)
    Xw = rng.normal(size=(600, 1200))
    yw = (Xw[:, 0] > 0).astype(np.float64)
    cfg = Config({"num_leaves": 255, "verbose": -1, "max_bin": 63,
                  "enable_bundle": False})
    td = TrainingData.from_matrix(Xw, label=yw, config=cfg)
    make_wave_core.cache_clear(); make_wave_jit.cache_clear()
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    try:
        lrn = SerialTreeLearner(cfg, td)
        assert lrn.hist_mode == "pallas_t"       # wide-F kernel
        assert lrn.wave_width == 32              # raw ladder, no bend
        assert not [ev for ev, _ in lrn._pending_events
                    if ev == "wave_band_escape"]
        cfg2 = Config({"num_leaves": 255, "verbose": -1, "max_bin": 63,
                       "enable_bundle": False, "tpu_wave_width": 16})
        lrn2 = SerialTreeLearner(cfg2, td)
        assert lrn2.wave_width == 16             # explicit width wins
    finally:
        monkeypatch.undo()
        make_wave_core.cache_clear(); make_wave_jit.cache_clear()


def _setup(categorical=False, efb=False):
    rng = np.random.default_rng(11)
    X = rng.normal(size=(N, F))
    if categorical:
        X[:, 0] = rng.integers(0, 9, size=N)
    if efb:   # two near-exclusive sparse features bundle together
        m = rng.random(N) < 0.5
        X[:, 2] = np.where(m, X[:, 2], 0.0)
        X[:, 3] = np.where(~m, X[:, 3], 0.0)
    y = (X[:, 1] + np.cos(X[:, 4] * 2) + 0.4 * rng.normal(size=N) > 0.5)
    cfg = Config({"num_leaves": L, "min_data_in_leaf": 3, "max_bin": 63,
                  "verbose": -1, "enable_bundle": efb,
                  "categorical_feature": "0" if categorical else ""})
    td = TrainingData.from_matrix(X, label=y.astype(np.float64), config=cfg)
    meta = FeatureMeta(num_bin=jnp.asarray(td.num_bin_arr),
                       default_bin=jnp.asarray(td.default_bin_arr),
                       is_categorical=jnp.asarray(td.is_categorical_arr))
    grad = jnp.asarray((0.5 - y).astype(np.float32))
    hess = jnp.full(N, 0.25, jnp.float32)
    return cfg, td, meta, grad, hess


def _trees_equal(a, b):
    np.testing.assert_array_equal(np.asarray(a.split_feature),
                                  np.asarray(b.split_feature))
    np.testing.assert_array_equal(np.asarray(a.threshold_bin),
                                  np.asarray(b.threshold_bin))
    np.testing.assert_array_equal(np.asarray(a.left_child),
                                  np.asarray(b.left_child))
    np.testing.assert_array_equal(np.asarray(a.right_child),
                                  np.asarray(b.right_child))
    np.testing.assert_array_equal(np.asarray(a.leaf_count),
                                  np.asarray(b.leaf_count))
    np.testing.assert_allclose(np.asarray(a.leaf_value),
                               np.asarray(b.leaf_value), rtol=1e-5)


@pytest.mark.parametrize("categorical", [False, True])
def test_wave1_is_exact_leafwise(categorical):
    cfg, td, meta, grad, hess = _setup(categorical)
    params = build_split_params(cfg)
    nb = int(td.num_bin_arr.max())
    ones = jnp.ones(N, jnp.float32)
    fm = jnp.ones(td.num_features, dtype=bool)
    args = (jnp.asarray(td.binned), grad, hess, ones, fm)
    tg, lg = jax.jit(make_grow_fn(L, nb, meta, params, -1,
                                  hist_mode="scatter",
                                  row_capacities=()))(*args)
    tw, lw = jax.jit(make_wave_grow_fn(L, nb, meta, params, -1,
                                       wave_width=1,
                                       hist_mode="scatter"))(*args)
    assert int(tg.num_leaves) == int(tw.num_leaves)
    _trees_equal(tg, tw)
    np.testing.assert_array_equal(np.asarray(lg), np.asarray(lw))


def test_wave1_is_exact_leafwise_efb():
    cfg, td, meta, grad, hess = _setup(efb=True)
    assert td.bundle is not None, "EFB bundle expected for this fixture"
    from lightgbm_tpu.ops.learner import build_bundle_arrays
    bundle, group_bins = build_bundle_arrays(td)
    params = build_split_params(cfg)
    nb = int(td.num_bin_arr.max())
    ones = jnp.ones(N, jnp.float32)
    fm = jnp.ones(td.num_features, dtype=bool)
    args = (jnp.asarray(td.binned), grad, hess, ones, fm)
    tg, lg = jax.jit(make_grow_fn(L, nb, meta, params, -1,
                                  hist_mode="scatter", bundle=bundle,
                                  group_bins=group_bins,
                                  row_capacities=()))(*args)
    tw, lw = jax.jit(make_wave_grow_fn(L, nb, meta, params, -1,
                                       wave_width=1, hist_mode="scatter",
                                       bundle=bundle,
                                       group_bins=group_bins))(*args)
    _trees_equal(tg, tw)
    np.testing.assert_array_equal(np.asarray(lg), np.asarray(lw))


def test_wave_batched_accounting_and_depth():
    cfg, td, meta, grad, hess = _setup()
    params = build_split_params(cfg)
    nb = int(td.num_bin_arr.max())
    ones = jnp.ones(N, jnp.float32)
    fm = jnp.ones(td.num_features, dtype=bool)
    args = (jnp.asarray(td.binned), grad, hess, ones, fm)
    tw, lw = jax.jit(make_wave_grow_fn(L, nb, meta, params, 4,
                                       wave_width=8,
                                       hist_mode="scatter"))(*args)
    nl = int(tw.num_leaves)
    assert nl > 8
    lc = np.asarray(tw.leaf_count)[:nl]
    assert lc.sum() == N and (lc >= 3).all()
    assert (np.asarray(tw.leaf_depth)[:nl] <= 4).all()
    # leaf_id agrees with leaf_count
    ids, cnts = np.unique(np.asarray(lw), return_counts=True)
    assert set(ids.tolist()) <= set(range(nl))
    got = dict(zip(ids.tolist(), cnts.tolist()))
    for i in range(nl):
        assert got.get(i, 0) == lc[i]


def test_wave_quality_close_to_exact():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(20000, 10))
    w = rng.normal(size=10)
    y = ((X @ w + 0.5 * rng.normal(size=20000)) > 0).astype(np.float64)
    out = {}
    for mode, ww in (("exact", 1), ("wave", 8)):
        params = {"objective": "binary", "num_leaves": 63, "max_bin": 63,
                  "learning_rate": 0.2, "min_data_in_leaf": 5,
                  "verbose": -1, "metric": "auc", "tpu_growth": mode,
                  "tpu_wave_width": ww}
        ds = lgb.Dataset(X, label=y, params=params)
        bst = lgb.train(params, ds, num_boost_round=15)
        p = bst.predict(X)
        order = np.argsort(p)
        ranks = np.empty(len(p)); ranks[order] = np.arange(1, len(p) + 1)
        npos = y.sum(); nneg = len(y) - npos
        out[mode] = (ranks[y > 0].sum() - npos * (npos + 1) / 2) / (
            npos * nneg)
    assert abs(out["wave"] - out["exact"]) < 5e-3, out


def test_wave_data_parallel_matches_serial():
    if len(jax.devices()) < 4:
        pytest.skip("needs multi-device mesh")
    cfg, td, meta, grad, hess = _setup()
    from lightgbm_tpu.parallel.mesh import (DataParallelTreeLearner,
                                            make_data_mesh)
    cfg2 = cfg.copy_with(tpu_growth="wave", tpu_wave_width=8)
    serial_cfg = cfg2
    from lightgbm_tpu.ops.learner import SerialTreeLearner
    sl = SerialTreeLearner(serial_cfg, td)
    dp = DataParallelTreeLearner(cfg2, td,
                                 make_data_mesh(jax.devices()[:4]))
    assert sl.growth == "wave" and dp.growth == "wave"
    g = np.asarray(grad, np.float32)
    h = np.asarray(hess, np.float32)
    ts, _ = sl.train_device(g, h)
    tdp, _ = dp.train_device(g, h)
    assert int(ts.num_leaves) == int(tdp.num_leaves)
    np.testing.assert_array_equal(np.asarray(ts.split_feature),
                                  np.asarray(tdp.split_feature))
    np.testing.assert_array_equal(np.asarray(ts.threshold_bin),
                                  np.asarray(tdp.threshold_bin))
    np.testing.assert_array_equal(np.asarray(ts.leaf_count),
                                  np.asarray(tdp.leaf_count))


@pytest.mark.parametrize("boosting,extra", [
    ("gbdt", {"bagging_fraction": 0.6, "bagging_freq": 1}),
    ("goss", {}),
    ("dart", {"drop_rate": 0.3}),
])
def test_wave_with_row_weighted_boosters(boosting, extra):
    """Wave growth must honor row multipliers (bagging masks, GOSS
    amplification, DART drops) exactly as the exact engine does."""
    rng = np.random.default_rng(17)
    X = rng.normal(size=(8000, 6))
    y = (X[:, 0] - X[:, 1] > 0).astype(np.float64)
    out = {}
    for mode in ("exact", "wave"):
        params = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
                  "verbose": -1, "boosting": boosting,
                  "bagging_seed": 3, "tpu_growth": mode,
                  "tpu_wave_width": 1, **extra}
        bst = lgb.train(params, lgb.Dataset(X, label=y),
                        num_boost_round=6)
        out[mode] = bst.model_to_string()
    # W=1 wave == exact leaf-wise, so the full booster stack must produce
    # structurally identical models (float-valued fields may differ in the
    # last ulp from histogram accumulation order)
    structural = ("split_feature=", "threshold=", "left_child=",
                  "right_child=", "leaf_count=", "num_leaves=",
                  "decision_type=")
    pick = lambda s: [l for l in s.splitlines()
                      if l.startswith(structural)]
    assert pick(out["wave"]) == pick(out["exact"])


def test_wave_width_auto_ranking_quality_gate():
    """Auto wave width resolves to 1 (the reference's exact split order)
    for ranking objectives — PARITY_TRAINING.md measured -6.4e-3 NDCG@10
    at W=8, so the auto policy is gated on quality, not only speed."""
    from lightgbm_tpu.ops.learner import resolve_wave_width
    cfg = Config({"verbose": -1, "objective": "lambdarank"})
    assert resolve_wave_width(cfg, 255) == 1
    # explicit values still win
    cfg2 = Config({"verbose": -1, "objective": "lambdarank",
                   "tpu_wave_width": 16})
    assert resolve_wave_width(cfg2, 255) == 16
    # DART/InfiniteBoost re-weighting compounds the order approximation
    # (PARITY_TRAINING: +2.7e-2 / +2.5e-2 logloss at W=8) -> W=1 on auto
    assert resolve_wave_width(Config({"verbose": -1, "objective": "binary",
                                      "boosting_type": "dart"}), 255) == 1
    assert resolve_wave_width(
        Config({"verbose": -1, "boosting_type": "infiniteboost"}), 255) == 1
    assert resolve_wave_width(
        Config({"verbose": -1, "boosting_type": "goss"}), 255) == 1
    # plain GBDT keeps the speed ladder
    assert resolve_wave_width(Config({"verbose": -1,
                                      "objective": "binary"}), 255) == 32


def test_wave_lookup_modes_identical_trees():
    """The three partition-lookup strategies (onehot / compact / gather)
    are algebraically identical — each row's split row r is the same
    exact f32 vector — so full trainings must produce byte-identical
    models, including under EFB bundling and at several widths."""
    rng = np.random.default_rng(23)
    X = rng.normal(size=(4000, 10))
    X[rng.random(X.shape) < 0.15] = 0.0
    y = (X[:, 0] - 0.5 * X[:, 3] + 0.2 * rng.normal(size=4000) > 0)
    base = {"objective": "binary", "num_leaves": 31, "verbose": -1,
            "min_data_in_leaf": 5, "tpu_growth": "wave"}
    for width in (4, 8):
        models = {}
        for lk in ("onehot", "compact", "gather"):
            p = dict(base, tpu_wave_width=width, tpu_wave_lookup=lk)
            bst = lgb.train(p, lgb.Dataset(X, label=y.astype(np.float64),
                                           params=p), num_boost_round=8)
            models[lk] = bst.model_to_string()
        assert models["compact"] == models["onehot"], \
            "compact lookup diverged at W=%d" % width
        assert models["gather"] == models["onehot"], \
            "gather lookup diverged at W=%d" % width


def test_wave_lookup_validation():
    p = {"objective": "binary", "verbose": -1, "tpu_growth": "wave",
         "tpu_wave_lookup": "bogus"}
    X = np.random.default_rng(0).normal(size=(200, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    from lightgbm_tpu.utils.log import LightGBMError
    with pytest.raises(LightGBMError):
        lgb.train(p, lgb.Dataset(X, label=y, params=p), num_boost_round=2)


def test_wave_auto_width_quality_envelope():
    """Every width the auto ladder can resolve to (8/16/32 at its
    num_leaves breakpoints) trains within epsilon of the exact W=1 order
    on a fixed dataset.  Pins VERDICT r3 Weak #4: W=128 measurably
    degrades AUC (0.9319 vs 0.9362 at the 1M on-chip A/B,
    tools/AB_RESULTS.md 11:30 block); the ladder caps at 32 to stay off
    that cliff, and a future ladder change that ships a quality-losing
    width must fail here."""
    from lightgbm_tpu.ops.learner import resolve_wave_width
    from lightgbm_tpu.utils.config import Config

    # the ladder must never resolve past the measured-safe 32
    for leaves in (31, 127, 255, 1023, 4095):
        w = resolve_wave_width(Config({"verbose": -1,
                                       "objective": "binary"}), leaves)
        assert w <= 32, "auto ladder shipped W=%d at %d leaves" % (w,
                                                                   leaves)

    rng = np.random.default_rng(11)
    X = rng.normal(size=(20000, 10))
    wvec = rng.normal(size=10)
    y = ((X @ wvec + 0.5 * rng.normal(size=20000)) > 0).astype(np.float64)

    def auc_of(params, rounds=12):
        ds = lgb.Dataset(X, label=y, params=params)
        p = lgb.train(params, ds, num_boost_round=rounds).predict(X)
        order = np.argsort(p)
        ranks = np.empty(len(p)); ranks[order] = np.arange(1, len(p) + 1)
        npos = y.sum(); nneg = len(y) - npos
        return (ranks[y > 0].sum() - npos * (npos + 1) / 2) / (npos * nneg)

    # one (num_leaves -> auto width) point per ladder rung
    for leaves, expect_w in ((31, 8), (127, 16), (255, 32)):
        base = {"objective": "binary", "num_leaves": leaves, "max_bin": 63,
                "learning_rate": 0.2, "min_data_in_leaf": 5, "verbose": -1}
        cfg = Config(dict(base, tpu_growth="wave", tpu_wave_width=-1))
        assert resolve_wave_width(cfg, leaves) == expect_w
        auc_exact = auc_of(dict(base, tpu_growth="exact"))
        auc_wave = auc_of(dict(base, tpu_growth="wave", tpu_wave_width=-1))
        assert auc_wave > auc_exact - 5e-3, \
            "auto W=%d at %d leaves lost %.2e AUC vs exact" % (
                expect_w, leaves, auc_exact - auc_wave)


def test_wave_lookup_validated_under_exact_growth_too():
    """A typo'd tpu_wave_lookup must be fatal even when growth resolves
    to exact (where the value is never applied) — like
    tpu_histogram_mode, validation is unconditional (ADVICE r3)."""
    p = {"objective": "binary", "verbose": -1, "tpu_growth": "exact",
         "tpu_wave_lookup": "bogus"}
    X = np.random.default_rng(0).normal(size=(200, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    from lightgbm_tpu.utils.log import LightGBMError
    with pytest.raises(LightGBMError):
        lgb.train(p, lgb.Dataset(X, label=y, params=p), num_boost_round=2)
