"""Pass 5 — VMEM budget: evaluate the repo's own tile planners on a
CPU-only runner, against the budgets the kernels assume on device.

This is the pass that makes the PR-11 band fix regression-proof without
a TPU: instead of pattern-matching kernel source, it IMPORTS
``ops/pallas_wave._tile_plan`` / ``tile_plan_vmem_report`` and
``ops/pallas_hist.tile_shape`` and sweeps them over the autotuner's
shape-bucket grid (ops/autotune.py enumerates cells over exactly these
axes).  Three invariants:

* ``vmem-budget``         — a wave cell whose hist block passes the
  64 MB resident gate (``autotune.WAVE_VMEM_GATE``) must plan a TOTAL
  live set (resident + transients) that fits physical VMEM.  In the
  chunked-RMW regime the planner deliberately runs resident blocks up
  to the gate with ~60 MB of transients on top — legal on v5e's 128 MB
  arena, and this rule is what keeps a future budget bump honest.
* ``vmem-serialized-rmw`` — the accumulator-aware live-set rule from
  PR-11: when the resident block leaves less than the chunked-RMW
  window, the planner must clamp the chunk (``pathological`` False in
  ``tile_plan_vmem_report``).  A True here is the 18-30 MB band
  pathology resurrected.
* ``vmem-hist-tile``      — the standalone Pallas histogram kernel's
  (one-hot tile + resident accumulator) must respect its own ~6 MB
  budget at every bin width the binner can produce.

Findings anchor at the planner's ``def`` line in the owning module, so
an inline suppression there covers a deliberately-over-budget regime.

Grid: ncols from the bucketization tests/benches (epsilon 2000, bosch
968, higgs 28, airline 8, synthetic 40/136/700), bin_pad from
ops/wave._bin_pad's two products (64, 128) plus 256 for deep-bin runs,
wave widths from autotune's candidate ladder.  ~200 cells, < 1 s on CPU.
"""
from __future__ import annotations

import ast
from typing import List

from .core import Finding, SourceModule

PASS_NAME = "vmem"

RULES = {
    "vmem-budget":
        "wave tile plan's total live set exceeds physical VMEM for a "
        "cell the autotuner would admit",
    "vmem-serialized-rmw":
        "tile planner re-creates the serialized chunked-RMW pathology "
        "(PR-11 accumulator-aware clamp regressed)",
    "vmem-hist-tile":
        "pallas_hist tile_shape oversubscribes its VMEM budget at some "
        "bin width",
}

N_ROWS = 1 << 20
NCOLS_GRID = (8, 28, 40, 136, 700, 968, 2000)
BIN_PAD_GRID = (64, 128, 256)
WIDTH_GRID = (1, 8, 16, 32, 64)
NUM_BINS_GRID = (16, 63, 64, 255, 256, 1024, 4096)

# v5e VMEM arena per core (the autotuner's target part; the measured
# ceiling every budget constant in ops/pallas_wave.py is derived from)
TOTAL_VMEM_BYTES = 128 << 20



def _def_line(modules: List[SourceModule], path_suffix: str,
              func_name: str) -> int:
    for mod in modules:
        if not mod.path.endswith(path_suffix):
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.FunctionDef) \
                    and node.name == func_name:
                return node.lineno
    return 0


def _check_wave(modules: List[SourceModule],
                findings: List[Finding]) -> None:
    from ..ops.autotune import WAVE_VMEM_GATE
    from ..ops.pallas_wave import tile_plan_vmem_report
    from ..ops.wave import hist_block_bytes

    path = "lightgbm_tpu/ops/pallas_wave.py"
    line = _def_line(modules, "ops/pallas_wave.py", "_tile_plan")
    for fc in NCOLS_GRID:
        for bp in BIN_PAD_GRID:
            for w in WIDTH_GRID:
                if hist_block_bytes(fc, bp, w) > WAVE_VMEM_GATE:
                    continue        # the autotuner gates this cell out
                rep = tile_plan_vmem_report(N_ROWS, fc, bp, w)
                live = rep["live_new"]     # resident + transients
                if live > TOTAL_VMEM_BYTES:
                    findings.append(Finding(
                        "vmem-budget", PASS_NAME, path, line,
                        "live set %.1f MB > %.0f MB physical VMEM at "
                        "ncols=%d bin_pad=%d W=%d"
                        % (live / 2**20, TOTAL_VMEM_BYTES / 2**20,
                           fc, bp, w),
                        "shrink the chunk/bsub plan for this regime "
                        "in _tile_plan"))
                if rep["pathological_new"]:
                    findings.append(Finding(
                        "vmem-serialized-rmw", PASS_NAME, path, line,
                        "serialized chunked-RMW plan at ncols=%d "
                        "bin_pad=%d W=%d (resident %.1f MB)"
                        % (fc, bp, w,
                           rep["resident_bytes"] / 2**20),
                        "restore the accumulator-aware chunk clamp "
                        "(PR-11, docs/FusedIteration.md)"))


def _check_hist(modules: List[SourceModule],
                findings: List[Finding]) -> None:
    from ..ops.pallas_hist import TILE_BUDGET, supports_bins, tile_shape

    path = "lightgbm_tpu/ops/pallas_hist.py"
    line = _def_line(modules, "ops/pallas_hist.py", "tile_shape")
    for num_bins in NUM_BINS_GRID:
        if not supports_bins(num_bins):
            # the kernel refuses this width at runtime
            # (leaf_histogram_pallas falls back to onehot) — the budget
            # invariant only binds widths the kernel claims
            continue
        f_blk, row_chunk = tile_shape(num_bins)
        resident = f_blk * num_bins * 3 * 4
        onehot = f_blk * num_bins * row_chunk * 4
        if resident + onehot > TILE_BUDGET:
            findings.append(Finding(
                "vmem-hist-tile", PASS_NAME, path, line,
                "tile (F_BLK=%d, C=%d) at B=%d holds %.1f MB "
                "(one-hot %.1f + resident %.1f) > %.0f MB budget"
                % (f_blk, row_chunk, num_bins,
                   (resident + onehot) / 2**20, onehot / 2**20,
                   resident / 2**20, TILE_BUDGET / 2**20),
                "let the row-chunk floor drop further (lanes stay "
                "%%128) or block the bin axis"))


def run(modules: List[SourceModule], repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    _check_wave(modules, findings)
    _check_hist(modules, findings)
    return list(dict.fromkeys(findings))
