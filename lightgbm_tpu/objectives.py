"""Objective functions — gradients/hessians on device.

Parity targets: src/objective/regression_objective.hpp,
binary_objective.hpp, multiclass_objective.hpp, rank_objective.hpp and the
factory in src/objective/objective_function.cpp:9-56.  Elementwise objectives
are jnp expressions (fused by XLA into the boosting step); lambdarank runs
the reference's per-query pairwise semantics fully on device as a jitted
vmap over padded query segments (the numpy per-query path is kept as the
test oracle, get_gradients_host).

Multi-class score layout matches the reference: column-major per class, i.e.
``score[k * num_data + i]`` (multiclass_objective.hpp:60-75); arrays here are
shaped (num_class, num_data) with the same meaning.
"""
from __future__ import annotations

import functools
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .io.metadata import Metadata
from .utils.config import Config
from .utils.log import Log

kEpsilon = 1e-15


def _apply_weights(g, h, w):
    if w is None:
        return g, h
    return g * w, h * w


class ObjectiveFunction:
    name = "base"

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = None if metadata.label is None else jnp.asarray(metadata.label)
        self.weights = None if metadata.weights is None else jnp.asarray(metadata.weights)

    def get_gradients(self, score):
        raise NotImplementedError

    def convert_output(self, x):
        return x

    def is_constant_hessian(self) -> bool:
        return False

    def boost_from_average(self) -> bool:
        return False

    def skip_empty_class(self) -> bool:
        return False

    def num_tree_per_iteration(self) -> int:
        return 1

    def num_predict_one_row(self) -> int:
        return 1

    def to_string(self) -> str:
        return self.name

    def get_name(self) -> str:
        return self.name


class RegressionL2loss(ObjectiveFunction):
    """regression_objective.hpp:11-73: g = score - label, h = 1."""
    name = "regression"

    def get_gradients(self, score):
        g = score - self.label
        h = jnp.ones_like(score)
        return _apply_weights(g, h, self.weights)

    def is_constant_hessian(self) -> bool:
        return self.weights is None

    def boost_from_average(self) -> bool:
        return True


def _approx_hessian_with_gaussian(score, label, g, eta, w=1.0):
    """Common::ApproximateHessianWithGaussian (utils/common.h:486-495)."""
    diff = score - label
    x = jnp.abs(diff)
    a = 2.0 * jnp.abs(g) * w
    c = jnp.maximum((jnp.abs(score) + jnp.abs(label)) * eta, 1.0e-10)
    return w * jnp.exp(-x * x / (2.0 * c * c)) * a / (c * jnp.sqrt(2 * jnp.pi))


class RegressionL1loss(ObjectiveFunction):
    """regression_objective.hpp:78-146: sign gradient + gaussian-approx hessian."""
    name = "regression_l1"

    def __init__(self, config: Config):
        self.eta = float(config.gaussian_eta)

    def get_gradients(self, score):
        diff = score - self.label
        w = self.weights if self.weights is not None else 1.0
        g = jnp.where(diff >= 0.0, 1.0, -1.0) * w
        h = _approx_hessian_with_gaussian(score, self.label, g, self.eta,
                                          w if self.weights is not None else 1.0)
        return g, h

    def boost_from_average(self) -> bool:
        return True


class RegressionHuberLoss(ObjectiveFunction):
    """regression_objective.hpp:149-230."""
    name = "huber"

    def __init__(self, config: Config):
        self.delta = float(config.huber_delta)
        self.eta = float(config.gaussian_eta)

    def get_gradients(self, score):
        diff = score - self.label
        w = self.weights if self.weights is not None else 1.0
        small = jnp.abs(diff) <= self.delta
        g = jnp.where(small, diff, jnp.where(diff >= 0.0, self.delta, -self.delta)) * w
        h_large = _approx_hessian_with_gaussian(
            score, self.label, g, self.eta,
            w if self.weights is not None else 1.0)
        h = jnp.where(small, jnp.ones_like(score) * w, h_large)
        return g, h

    def boost_from_average(self) -> bool:
        return True


class RegressionFairLoss(ObjectiveFunction):
    """regression_objective.hpp:235-296."""
    name = "fair"

    def __init__(self, config: Config):
        self.c = float(config.fair_c)

    def get_gradients(self, score):
        x = score - self.label
        g = self.c * x / (jnp.abs(x) + self.c)
        h = self.c * self.c / ((jnp.abs(x) + self.c) ** 2)
        return _apply_weights(g, h, self.weights)

    def boost_from_average(self) -> bool:
        return True


class RegressionPoissonLoss(ObjectiveFunction):
    """regression_objective.hpp:299-355: this line's Poisson works on the raw
    score with h = score + max_delta_step."""
    name = "poisson"

    def __init__(self, config: Config):
        self.max_delta_step = float(config.poisson_max_delta_step)

    def get_gradients(self, score):
        g = score - self.label
        h = score + self.max_delta_step
        return _apply_weights(g, h, self.weights)

    def boost_from_average(self) -> bool:
        return True


class BinaryLogloss(ObjectiveFunction):
    """binary_objective.hpp:13-154 incl. is_unbalance label weights and
    scale_pos_weight."""
    name = "binary"

    def __init__(self, config: Optional[Config] = None, is_pos=None,
                 sigmoid: Optional[float] = None,
                 scale_pos_weight: Optional[float] = None,
                 is_unbalance: Optional[bool] = None):
        if config is not None:
            self.sigmoid = float(config.sigmoid)
            self.scale_pos_weight = float(config.scale_pos_weight)
            self.is_unbalance = bool(config.is_unbalance)
        else:
            self.sigmoid = 1.0 if sigmoid is None else float(sigmoid)
            self.scale_pos_weight = 1.0 if scale_pos_weight is None else scale_pos_weight
            self.is_unbalance = bool(is_unbalance)
        if self.sigmoid <= 0.0:
            Log.fatal("Sigmoid parameter %f should be greater than zero", self.sigmoid)
        self._is_pos = is_pos if is_pos is not None else (lambda label: label > 0)

    def init(self, metadata: Metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        lab = np.asarray(metadata.label)
        pos_mask = self._is_pos(lab)
        cnt_pos = int(pos_mask.sum())
        cnt_neg = int(num_data - cnt_pos)
        self.trainable = not (cnt_pos == 0 or cnt_neg == 0)
        if not self.trainable:
            Log.warning("Only contain one class.")
        lw = [1.0, 1.0]
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                lw[0] = cnt_pos / cnt_neg
            else:
                lw[1] = cnt_neg / cnt_pos
        lw[1] *= self.scale_pos_weight
        Log.info("Number of positive: %d, number of negative: %d", cnt_pos, cnt_neg)
        self.sign = jnp.asarray(np.where(pos_mask, 1.0, -1.0), jnp.float32)
        self.label_weight = jnp.asarray(np.where(pos_mask, lw[1], lw[0]), jnp.float32)

    def get_gradients(self, score):
        if not self.trainable:
            z = jnp.zeros(self.num_data, score.dtype)
            return z, z
        # binary_objective.hpp:94-97
        response = -self.sign * self.sigmoid / (1.0 + jnp.exp(self.sign * self.sigmoid * score))
        abs_resp = jnp.abs(response)
        g = response * self.label_weight
        h = abs_resp * (self.sigmoid - abs_resp) * self.label_weight
        if self.weights is not None:
            g = g * self.weights
            h = h * self.weights
        return g, h

    def convert_output(self, x):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * np.asarray(x)))

    def skip_empty_class(self) -> bool:
        return True

    def to_string(self) -> str:
        return "binary sigmoid:%g" % self.sigmoid


class MulticlassSoftmax(ObjectiveFunction):
    """multiclass_objective.hpp:16-137; score shaped (num_class, num_data)."""
    name = "multiclass"

    def __init__(self, config: Optional[Config] = None, num_class: int = None):
        self.num_class = int(config.num_class if config is not None else num_class)

    def init(self, metadata: Metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        lab = np.asarray(metadata.label).astype(np.int32)
        if lab.min() < 0 or lab.max() >= self.num_class:
            Log.fatal("Label must be in [0, %d)", self.num_class)
        self.label_int = jnp.asarray(lab)

    def get_gradients(self, score):
        score = score.reshape(self.num_class, self.num_data)
        p = jnp.exp(score - jnp.max(score, axis=0, keepdims=True))
        p = p / jnp.sum(p, axis=0, keepdims=True)
        onehot = (jnp.arange(self.num_class)[:, None] == self.label_int[None, :])
        g = p - onehot.astype(p.dtype)
        h = 2.0 * p * (1.0 - p)
        if self.weights is not None:
            g = g * self.weights[None, :]
            h = h * self.weights[None, :]
        return g.reshape(-1), h.reshape(-1)

    def convert_output(self, x):
        x = np.asarray(x, dtype=np.float64)
        e = np.exp(x - x.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)

    def skip_empty_class(self) -> bool:
        return True

    def num_tree_per_iteration(self) -> int:
        return self.num_class

    def num_predict_one_row(self) -> int:
        return self.num_class

    def to_string(self) -> str:
        return "multiclass num_class:%d" % self.num_class


class MulticlassOVA(ObjectiveFunction):
    """multiclass_objective.hpp:139-248: per-class BinaryLogloss."""
    name = "multiclassova"

    def __init__(self, config: Optional[Config] = None, num_class: int = None,
                 sigmoid: float = 1.0):
        if config is not None:
            self.num_class = int(config.num_class)
            self.sigmoid = float(config.sigmoid)
            self.binary = [BinaryLogloss(config, is_pos=_make_is_pos(i))
                           for i in range(self.num_class)]
        else:
            self.num_class = int(num_class)
            self.sigmoid = float(sigmoid)
            self.binary = [BinaryLogloss(sigmoid=sigmoid, is_pos=_make_is_pos(i))
                           for i in range(self.num_class)]

    def init(self, metadata: Metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        for b in self.binary:
            b.init(metadata, num_data)

    def get_gradients(self, score):
        score = score.reshape(self.num_class, self.num_data)
        gs, hs = [], []
        for i, b in enumerate(self.binary):
            g, h = b.get_gradients(score[i])
            gs.append(g)
            hs.append(h)
        return jnp.concatenate(gs), jnp.concatenate(hs)

    def convert_output(self, x):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * np.asarray(x)))

    def skip_empty_class(self) -> bool:
        return True

    def num_tree_per_iteration(self) -> int:
        return self.num_class

    def num_predict_one_row(self) -> int:
        return self.num_class

    def to_string(self) -> str:
        return "multiclassova num_class:%d sigmoid:%g" % (self.num_class, self.sigmoid)


def _make_is_pos(i: int):
    return lambda label: np.asarray(label).astype(np.int32) == i


def default_label_gain(size: int = 31) -> List[float]:
    """label_gain = 2^i - 1 (src/io/config.cpp:273-277)."""
    return [float((1 << i) - 1) for i in range(size)]


def get_discounts(n: int) -> np.ndarray:
    """DCG position discount 1/log2(2+i) (dcg_calculator.cpp:22-25)."""
    return 1.0 / np.log2(2.0 + np.arange(n))


class LambdarankNDCG(ObjectiveFunction):
    """rank_objective.hpp:19-244: pairwise lambdas weighted by |ΔNDCG|.

    Exact sigmoid instead of the reference's 1M-entry lookup table (same
    function, no quantization error); per-query numpy vectorization of the
    O(n^2) pair loop.
    """
    name = "lambdarank"

    def __init__(self, config: Optional[Config] = None):
        config = config or Config()
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0.0:
            Log.fatal("Sigmoid param %f should be greater than zero", self.sigmoid)
        self.label_gain = np.asarray(config.label_gain or default_label_gain())
        self.optimize_pos_at = int(config.max_position)

    def init(self, metadata: Metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            Log.fatal("Lambdarank tasks require query information")
        self.qb = np.asarray(metadata.query_boundaries)
        self.labels_np = np.asarray(metadata.label)
        self.weights_np = None if metadata.weights is None else np.asarray(metadata.weights)
        self.num_queries = len(self.qb) - 1
        self.inverse_max_dcgs = np.zeros(self.num_queries)
        for q in range(self.num_queries):
            lab = self.labels_np[self.qb[q]:self.qb[q + 1]]
            m = _max_dcg_at_k(self.optimize_pos_at, lab, self.label_gain)
            self.inverse_max_dcgs[q] = 1.0 / m if m > 0.0 else m
        self._build_device_layout()

    def _build_device_layout(self) -> None:
        """Padded per-query layout for the jitted gradient program.

        Queries are BUCKETED by padded width (powers of two): each bucket
        is a (Qb, w) table, so total table memory is O(sum of padded query
        sizes) <= 2N — one 5000-doc query among 500k small ones costs its
        own tiny bucket instead of widening every row to 5000.  Within a
        bucket the design is the `vmap over padded query segments` of
        SURVEY.md §7 step 4 replacing rank_objective.hpp:19-244's per-query
        OMP loop; a handful of bucket-shaped jit calls per iteration
        replaces the reference's single loop.
        """
        counts = np.diff(self.qb)
        nq = self.num_queries
        self._dev_label_gain = jnp.asarray(self.label_gain.astype(np.float32))
        self._dev_sigmoid = float(self.sigmoid)
        widths = np.maximum(
            2, 2 ** np.ceil(np.log2(np.maximum(counts, 1))).astype(np.int64))
        self._buckets = []
        for w in np.unique(widths):
            qs = np.flatnonzero(widths == w)
            c = counts[qs]
            w = int(w)
            slot = np.arange(w)[None, :]
            valid = slot < c[:, None]
            idx = self.qb[:-1][qs][:, None] + slot       # (Qb, w)
            idx = np.minimum(idx, self.num_data - 1)     # clamp padding
            labels = np.where(valid,
                              self.labels_np[idx].astype(np.int32), 0)
            # this bucket's score-vector rows, and their table slots, in
            # matching (row-major) order — the device program returns the
            # per-row values and the caller scatters them into (N,)
            qi, si = np.nonzero(valid)
            rows = idx[valid]
            tabpos = qi * w + si
            # block the query axis so the pairwise (w, w) tensors stay
            # bounded: ~64MB of f32 pair matrices per block
            blk = max(1, min(len(qs), int(16_000_000 // (w * w)) or 1))
            self._buckets.append({
                "idx": jnp.asarray(idx.astype(np.int32)),
                "valid": jnp.asarray(valid),
                "labels": jnp.asarray(labels),
                "counts": jnp.asarray(c.astype(np.int32)),
                "inv": jnp.asarray(
                    self.inverse_max_dcgs[qs].astype(np.float32)),
                "discounts": jnp.asarray(
                    get_discounts(w).astype(np.float32)),
                "rows": jnp.asarray(rows.astype(np.int32)),
                "tabpos": jnp.asarray(tabpos.astype(np.int32)),
                "block": blk,
            })

    def get_gradients(self, score):
        """Jitted padded-query lambdas — no host round-trip per iteration.

        The numpy implementation (get_gradients_host) is kept as the oracle
        for tests/test_objectives parity checks.
        """
        score = jnp.asarray(score, jnp.float32)
        lam = jnp.zeros(self.num_data, jnp.float32)
        hes = jnp.zeros(self.num_data, jnp.float32)
        for b in self._buckets:
            lb, hb = _lambdarank_device(
                score, b["idx"], b["valid"], b["labels"], b["counts"],
                b["inv"], b["discounts"], self._dev_label_gain,
                b["tabpos"], self._dev_sigmoid, b["block"])
            lam = lam.at[b["rows"]].set(lb)
            hes = hes.at[b["rows"]].set(hb)
        return _apply_weights(lam, hes, self.weights)

    def get_gradients_host(self, score):
        """Reference-shaped numpy path (rank_objective.hpp:100-190)."""
        score = np.asarray(score, dtype=np.float64)
        lambdas = np.zeros(self.num_data, dtype=np.float32)
        hessians = np.zeros(self.num_data, dtype=np.float32)
        for q in range(self.num_queries):
            s, e = self.qb[q], self.qb[q + 1]
            self._one_query(score[s:e], self.labels_np[s:e],
                            self.inverse_max_dcgs[q],
                            lambdas[s:e], hessians[s:e])
        if self.weights_np is not None:
            lambdas *= self.weights_np
            hessians *= self.weights_np
        return jnp.asarray(lambdas), jnp.asarray(hessians)

    def _one_query(self, score, label, inv_max_dcg, out_l, out_h):
        cnt = len(score)
        if cnt <= 1 or inv_max_dcg <= 0:
            return
        sorted_idx = np.argsort(-score, kind="stable")
        ranked_score = score[sorted_idx]
        ranked_label = label[sorted_idx].astype(np.int32)
        best_score = ranked_score[0]
        worst_idx = cnt - 1
        if worst_idx > 0 and ranked_score[worst_idx] == -np.inf:
            worst_idx -= 1
        worst_score = ranked_score[worst_idx]
        discounts = get_discounts(cnt)
        gains = self.label_gain[ranked_label]
        # pair (i=high rank pos, j=low rank pos) matrices over ranked order
        valid = (ranked_label[:, None] > ranked_label[None, :])
        valid &= np.isfinite(ranked_score)[:, None] & np.isfinite(ranked_score)[None, :]
        delta_score = ranked_score[:, None] - ranked_score[None, :]
        dcg_gap = gains[:, None] - gains[None, :]
        paired_discount = np.abs(discounts[:, None] - discounts[None, :])
        delta_ndcg = dcg_gap * paired_discount * inv_max_dcg
        if best_score != worst_score:
            delta_ndcg = delta_ndcg / (0.01 + np.abs(delta_score))
        p_lambda = 2.0 / (1.0 + np.exp(2.0 * delta_score * self.sigmoid))
        p_hess = p_lambda * (2.0 - p_lambda)
        p_lambda = np.where(valid, -p_lambda * delta_ndcg, 0.0)
        p_hess = np.where(valid, 2.0 * p_hess * delta_ndcg, 0.0)
        lam = p_lambda.sum(axis=1) - p_lambda.sum(axis=0)
        hes = p_hess.sum(axis=1) + p_hess.sum(axis=0)
        out_l[sorted_idx] += lam.astype(np.float32)
        out_h[sorted_idx] += hes.astype(np.float32)


def _lambdarank_one_query(s, labels, cnt, inv_max_dcg, discounts,
                          label_gain, sigmoid):
    """Pairwise lambdas for ONE padded query (rank_objective.hpp:100-190).

    s: (qmax,) scores with padding at -inf; labels: (qmax,) int32;
    cnt: scalar real count.  Returns (lam, hes) in ORIGINAL segment order.
    """
    sorted_idx = jnp.argsort(-s)                   # stable: ties keep order
    rs = s[sorted_idx]
    rl = labels[sorted_idx]
    gains = label_gain[rl]
    finite = jnp.isfinite(rs)
    valid = (rl[:, None] > rl[None, :]) & finite[:, None] & finite[None, :]
    delta_score = rs[:, None] - rs[None, :]
    dcg_gap = gains[:, None] - gains[None, :]
    paired_discount = jnp.abs(discounts[:, None] - discounts[None, :])
    delta_ndcg = dcg_gap * paired_discount * inv_max_dcg
    best_score = rs[0]
    wi = jnp.maximum(cnt - 1, 0)
    wi = jnp.where((wi > 0) & jnp.isneginf(rs[wi]), wi - 1, wi)
    worst_score = rs[wi]
    norm = jnp.where(best_score != worst_score,
                     1.0 / (0.01 + jnp.abs(delta_score)), 1.0)
    delta_ndcg = delta_ndcg * norm
    p_lambda = 2.0 / (1.0 + jnp.exp(2.0 * delta_score * sigmoid))
    p_hess = p_lambda * (2.0 - p_lambda)
    p_lambda = jnp.where(valid, -p_lambda * delta_ndcg, 0.0)
    p_hess = jnp.where(valid, 2.0 * p_hess * delta_ndcg, 0.0)
    lam = jnp.sum(p_lambda, axis=1) - jnp.sum(p_lambda, axis=0)
    hes = jnp.sum(p_hess, axis=1) + jnp.sum(p_hess, axis=0)
    live = (cnt > 1) & (inv_max_dcg > 0.0)
    lam = jnp.where(live, lam, 0.0)
    hes = jnp.where(live, hes, 0.0)
    inv = jnp.argsort(sorted_idx)                  # unsort to segment order
    return lam[inv], hes[inv]


@functools.partial(jax.jit, static_argnums=(9, 10))
def _lambdarank_device(score, idx, valid, labels, counts, inv_max_dcg,
                       discounts, label_gain, tab_pos, sigmoid,
                       block):
    """Per-bucket lambdas: (R,) values for the rows whose table slots are
    tab_pos (callers scatter them back into the (N,) gradient vectors)."""
    from jax import lax
    nq, qmax = idx.shape
    s = jnp.where(valid, score[idx].astype(jnp.float32), -jnp.inf)
    pad_q = (-nq) % block
    if pad_q:
        zpadi = lambda a: jnp.concatenate(
            [a, jnp.zeros((pad_q,) + a.shape[1:], a.dtype)])
        s = jnp.concatenate([s, jnp.full((pad_q, qmax), -jnp.inf, s.dtype)])
        labels = zpadi(labels)
        counts = zpadi(counts)
        inv_max_dcg = zpadi(inv_max_dcg)
    nb = (nq + pad_q) // block

    per_query = jax.vmap(_lambdarank_one_query,
                         in_axes=(0, 0, 0, 0, None, None, None))

    def one_block(args):
        sb, lb, cb, ib = args
        return per_query(sb, lb, cb, ib, discounts, label_gain, sigmoid)

    lam, hes = lax.map(one_block,
                       (s.reshape(nb, block, qmax),
                        labels.reshape(nb, block, qmax),
                        counts.reshape(nb, block),
                        inv_max_dcg.reshape(nb, block)))
    lam = lam.reshape(-1)[tab_pos]                 # (R,) gather-back
    hes = hes.reshape(-1)[tab_pos]
    return lam, hes


def _max_dcg_at_k(k: int, label: np.ndarray, label_gain: np.ndarray) -> float:
    """DCGCalculator::CalMaxDCGAtK (dcg_calculator.cpp:28-50)."""
    k = min(k, len(label))
    sorted_label = np.sort(label.astype(np.int32))[::-1][:k]
    return float((label_gain[sorted_label] * get_discounts(k)).sum())


_OBJECTIVE_FACTORY = {
    "regression": RegressionL2loss,
    "regression_l2": RegressionL2loss,
    "mean_squared_error": RegressionL2loss,
    "mse": RegressionL2loss,
    "regression_l1": RegressionL1loss,
    "mean_absolute_error": RegressionL1loss,
    "mae": RegressionL1loss,
    "huber": RegressionHuberLoss,
    "fair": RegressionFairLoss,
    "poisson": RegressionPoissonLoss,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "softmax": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "multiclass_ova": MulticlassOVA,
    "ova": MulticlassOVA,
    "ovr": MulticlassOVA,
    "lambdarank": LambdarankNDCG,
}


def create_objective(name: str, config: Config) -> Optional[ObjectiveFunction]:
    """ObjectiveFunction::CreateObjectiveFunction (objective_function.cpp:9-35)."""
    if name in ("none", "null", "custom", "na"):
        return None
    cls = _OBJECTIVE_FACTORY.get(name)
    if cls is None:
        Log.fatal("Unknown objective type name: %s", name)
    if cls in (RegressionL2loss,):
        return cls()
    return cls(config)


def load_objective_from_string(s: str) -> Optional[ObjectiveFunction]:
    """Round-trip from model files (objective_function.cpp:37-56)."""
    toks = s.split()
    if not toks:
        return None
    name = toks[0]
    kv = {}
    for t in toks[1:]:
        if ":" in t:
            k, _, v = t.partition(":")
            kv[k] = v
    if name == "binary":
        return BinaryLogloss(sigmoid=float(kv.get("sigmoid", 1.0)))
    if name == "multiclass":
        return MulticlassSoftmax(num_class=int(kv.get("num_class", 2)))
    if name == "multiclassova":
        return MulticlassOVA(num_class=int(kv.get("num_class", 2)),
                             sigmoid=float(kv.get("sigmoid", 1.0)))
    cfg = Config()
    cls = _OBJECTIVE_FACTORY.get(name)
    if cls is None:
        return None
    if cls is RegressionL2loss:
        return cls()
    return cls(cfg)
