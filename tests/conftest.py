"""Test harness config: force the CPU backend with 8 virtual devices.

This is the moral equivalent of the reference testing its GPU code on an
OpenCL CPU driver and MPI single-process (.travis.yml:15-25,45-59): the
multi-device psum paths run on a virtual 8-device CPU mesh, no TPU pod
needed (SURVEY.md §4).
"""
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

import pytest


@pytest.fixture(autouse=True, scope="module")
def _bound_compile_accumulation():
    """Drop compiled-executable references at module boundaries.

    XLA:CPU's backend_compile_and_load segfaulted 3/3 full-suite runs at
    the same late test (test_wave_exact_order, ~90% through) on this
    host, while every subset run passes — the crash needs the full
    suite's in-process compile history (~360 tests' worth of live CPU
    executables).  Clearing at module boundaries bounds that
    accumulation; jitted callables recompile transparently on next use.
    Same jaxlib-CPU fragility class as the executable-serialization
    segfault that keeps the persistent compile cache TPU-only
    (lightgbm_tpu/utils/common.py).
    """
    yield
    jax.clear_caches()
