"""Flagship-scale AUC parity: ours (TPU) vs the reference CLI, identical bytes.

VERDICT r3 item 4: the quality half of the north star — a multi-hundred-
iteration head-to-head at >=1M rows (the prior parity pins stop at 50k
rows / 13 iters).  Mirrors the discipline of the reference's published
speed/accuracy table (/root/reference/docs/GPU-Performance.md:127-145):
same bytes, same recipe, compare the final validation AUC.

Protocol:
  * One deterministic synthetic Higgs-like set (PARITY_N train rows x 28,
    250k valid rows), written ONCE as TSV (%.7g) — both frameworks read
    the SAME text file, so binning sees identical input bytes.
  * Reference arm: the unmodified CLI (REF_LGBM) with valid= + metric=auc,
    final "Iteration:<last> ... auc : <v>" line parsed from its log.
  * Our arms (each in a wedge-isolated child, retried to a deadline):
      exact — tpu_growth=exact, the reference's split order: the parity
              claim (target |delta| <= 1e-4);
      wave  — the TPU speed default (auto -> wave/pallas_t/compact):
              the headline config's quality envelope (expect <= ~1e-3).
  * Results append to PARITY_TRAINING.md and print as one JSON line.

Usage: python tools/parity_flagship.py            # 1M x 28, 150 iters
       PARITY_N=10500000 python tools/parity_flagship.py
"""
import datetime
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_TRAIN = int(os.environ.get("PARITY_N", 1_000_000))
N_VALID = int(os.environ.get("PARITY_NVALID", 250_000))
N_FEAT = 28
ITERS = int(os.environ.get("PARITY_ITERS", 150))
DEADLINE_S = float(os.environ.get("PARITY_DEADLINE_S", 5400))
CHILD_TIMEOUT = float(os.environ.get("PARITY_CHILD_S", 2400))
REF = os.environ.get("REF_LGBM", "/tmp/refbuild/lightgbm")

TRAIN_TSV = "/tmp/parity_fs_%d.train.tsv" % N_TRAIN
VALID_TSV = "/tmp/parity_fs_%d.valid.tsv" % N_TRAIN

PARAMS = {"objective": "binary", "metric": "auc", "num_leaves": 255,
          "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 1}


def write_tsvs():
    if os.path.exists(TRAIN_TSV) and os.path.exists(VALID_TSV):
        return
    import numpy as np
    rng = np.random.default_rng(4242)
    w = None

    def emit(path, rows):
        nonlocal w
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            done = 0
            while done < rows:
                n = min(500_000, rows - done)
                X = rng.normal(size=(n, N_FEAT)).astype(np.float32)
                if w is None:
                    w = rng.normal(size=N_FEAT) * (rng.random(N_FEAT) > 0.3)
                logit = X @ w * 0.5 + 0.5 * rng.normal(size=n)
                y = (logit > 0).astype(np.int32)
                block = np.column_stack([y.astype(np.float32), X])
                np.savetxt(f, block, fmt="%.7g", delimiter="\t")
                done += n
        os.replace(tmp, path)

    emit(TRAIN_TSV, N_TRAIN)
    emit(VALID_TSV, N_VALID)


def ref_arm():
    """Train the reference CLI; return (final_valid_auc, s_per_iter)."""
    conf = dict(PARAMS)
    conf.update({"task": "train", "data": TRAIN_TSV, "valid": VALID_TSV,
                 "num_trees": ITERS, "verbosity": 2, "metric_freq": ITERS,
                 "output_model": "/tmp/parity_fs_ref.model",
                 "num_threads": 1})
    args = [REF] + ["%s=%s" % kv for kv in conf.items()]
    t0 = time.time()
    r = subprocess.run(args, capture_output=True, text=True,
                       timeout=6 * 3600)
    wall = time.time() - t0
    text = r.stdout + r.stderr
    if r.returncode != 0:
        raise RuntimeError("reference CLI rc=%d:\n%s"
                           % (r.returncode, text[-1000:]))
    aucs = re.findall(r"Iteration:(\d+).*?auc\s*:\s*([0-9.]+)", text)
    if not aucs:
        raise RuntimeError("no auc lines in reference log:\n" + text[-1000:])
    last_iter, auc = max(((int(i), float(a)) for i, a in aucs))
    iters_timed = re.findall(r"([0-9.]+) seconds elapsed, finished iteration"
                             r"\s*(\d+)", text)
    spi = wall / ITERS
    if len(iters_timed) >= 2:
        (t_a, i_a), (t_b, i_b) = iters_timed[0], iters_timed[-1]
        if int(i_b) > int(i_a):
            spi = (float(t_b) - float(t_a)) / (int(i_b) - int(i_a))
    return auc, spi


def child(growth):
    """Our arm on the current backend; prints one JSON line."""
    from lightgbm_tpu.utils.common import honor_jax_platforms
    honor_jax_platforms()
    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.common import enable_compilation_cache
    enable_compilation_cache()
    params = dict(PARAMS, verbose=-1, tpu_growth=growth)
    cache = "/tmp/parity_fs_%d_%s.bin" % (N_TRAIN, "ds")
    if os.path.exists(cache):
        dtrain = lgb.Dataset(cache)
        dtrain.construct()
        dtrain.params = dict(dtrain.params or {}, **params)
    else:
        dtrain = lgb.Dataset(TRAIN_TSV, params=params)
        dtrain.construct()
        try:
            tmp = "%s.tmp.%d" % (cache, os.getpid())
            dtrain.save_binary(tmp)
            os.replace(tmp, cache)
        except Exception as e:
            print("cache write failed: %s" % e, file=sys.stderr)
    dvalid = lgb.Dataset(VALID_TSV, reference=dtrain, params=params)
    evals = {}
    t0 = time.time()
    lgb.train(params, dtrain, num_boost_round=ITERS, valid_sets=[dvalid],
              evals_result=evals)
    wall = time.time() - t0
    auc = float(evals["valid_0"]["auc"][-1])
    print(json.dumps({"auc": auc, "spi": wall / ITERS,
                      "backend": jax.default_backend()}), flush=True)


def our_arm(growth, deadline):
    """Wedge-isolated child with retries until the deadline.

    Hang -> retry (tunnel wedge); the SAME exit code twice in a row with
    a live probe in between -> deterministic failure, give up so one
    broken arm can't starve the other (bench.py's childfail discipline).
    """
    from tools.tpu_ab2 import probe_with_retries
    fails, last_rc = 0, None
    while time.time() < deadline:
        backend = probe_with_retries()
        usable = backend == "tpu" or (backend is not None and
                                      os.environ.get("PARITY_ALLOW_CPU"))
        if not usable:
            time.sleep(120)
            continue
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child",
                 growth], capture_output=True, text=True,
                timeout=CHILD_TIMEOUT, cwd=REPO)
        except subprocess.TimeoutExpired:
            print("our[%s]: child timed out (wedge?); retrying" % growth,
                  flush=True)
            fails, last_rc = 0, None       # a wedge breaks the rc chain
            continue
        if r.returncode == 0 and r.stdout.strip():
            return json.loads(r.stdout.strip().splitlines()[-1])
        print("our[%s]: rc=%d\n%s" % (growth, r.returncode,
                                      r.stderr[-800:]), flush=True)
        fails = fails + 1 if r.returncode == last_rc else 1
        last_rc = r.returncode
        if fails >= 2:
            print("our[%s]: same failure twice — giving up" % growth,
                  flush=True)
            return None
        time.sleep(60)
    return None


def main():
    deadline = time.time() + DEADLINE_S
    print("writing TSVs (cached: %s)" % os.path.exists(TRAIN_TSV),
          flush=True)
    write_tsvs()
    # the reference arm is deterministic for (N, ITERS) — cache it so a
    # tunnel-window invocation spends the window on OUR arms only
    ref_cache = "/tmp/parity_fs_ref_%d_%d.json" % (N_TRAIN, ITERS)
    if os.path.exists(ref_cache) and not os.environ.get("PARITY_REF_FRESH"):
        rec = json.load(open(ref_cache))
        ref_auc, ref_spi = rec["auc"], rec["spi"]
        print("reference arm: cached", flush=True)
    else:
        print("reference arm...", flush=True)
        ref_auc, ref_spi = ref_arm()
        tmp = "%s.tmp.%d" % (ref_cache, os.getpid())
        json.dump({"auc": ref_auc, "spi": ref_spi}, open(tmp, "w"))
        os.replace(tmp, ref_cache)
    print("reference: auc=%.6f  %.3f s/iter" % (ref_auc, ref_spi),
          flush=True)
    if "--ref-only" in sys.argv:     # precompute while the tunnel is down
        print(json.dumps({"ref_auc": ref_auc, "ref_spi": ref_spi}),
              flush=True)
        return
    # --wave-only / --exact-only: re-run a single arm (e.g. after a
    # tunnel wedge killed one of the pair — the ref arm and the other
    # arm's committed row stay valid)
    arms = ("exact", "wave")
    if "--wave-only" in sys.argv:
        arms = ("wave",)
    elif "--exact-only" in sys.argv:
        arms = ("exact",)
    rows = []
    for growth in arms:
        res = our_arm(growth, deadline)
        if res is None:
            rows.append((growth, None, None, None))
            continue
        rows.append((growth, res["auc"], res["auc"] - ref_auc,
                     res["spi"]))
        print("ours[%s]: auc=%.6f delta=%+.2e  %.3f s/iter"
              % (growth, res["auc"], res["auc"] - ref_auc, res["spi"]),
              flush=True)
    stamp = datetime.datetime.now(datetime.timezone.utc)
    with open(os.path.join(REPO, "PARITY_TRAINING.md"), "a") as f:
        f.write("\n## Flagship-scale AUC parity — %s UTC\n\n"
                % stamp.isoformat(timespec="seconds"))
        f.write("%d train rows x %d, %d valid rows, %d iterations, "
                "identical TSV bytes both sides (tools/parity_flagship.py).\n\n"
                % (N_TRAIN, N_FEAT, N_VALID, ITERS))
        f.write("| arm | valid AUC | delta vs ref | s/iter |\n")
        f.write("|---|---|---|---|\n")
        f.write("| reference CLI | %.6f | — | %.3f |\n" % (ref_auc, ref_spi))
        for growth, auc, delta, spi in rows:
            if auc is None:
                f.write("| ours (%s) | UNMEASURED (device) | — | — |\n"
                        % growth)
            else:
                f.write("| ours (%s) | %.6f | %+.2e | %.3f |\n"
                        % (growth, auc, delta, spi))
    print(json.dumps({
        "ref_auc": ref_auc,
        "arms": {g: ({"auc": a, "delta": d, "spi": s}
                     if a is not None else None)
                 for g, a, d, s in rows}}), flush=True)


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--child":
        child(sys.argv[2])
    else:
        main()
