"""Fused boosting iteration (ops/fused_iter.py).

* bit-identity: with ``tpu_fused_iter=on`` the single-entry program
  reproduces the staged chain's model file and predictions bit for bit —
  on the default (exact) grower AND on the CPU-interpret Pallas wave
  path (``tpu_pallas_interpret=true``) across scaled-down versions of
  the flagship/epsilon/msltr/expo_cat benchmark shape buckets.
* eligibility: DART/GOSS/multiclass/custom-fobj/gradient-health configs
  fall back to the staged chain (with a warning under ``on``), and
  ``auto`` keeps the staged chain on plain-CPU default runs.
* the default boosting loop issues ZERO mid-tree host syncs — every
  deliberate block routes through obs/timers.fence, whose counter is
  the audit (the async-dispatch contract both paths rely on).
* band-probe regression: `tile_plan_vmem_report` (ops/pallas_wave.py)
  reproduces and fixes the former 18-30 MB band degeneracy the fused
  probe work root-caused.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.fused_iter import fused_supported
from lightgbm_tpu.obs import timers as obs_timers


def _xy(n, f, seed, classification=True):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, f)).astype(np.float32)
    raw = X[:, 0] - 0.5 * X[:, 1 % f] + 0.1 * rng.standard_normal(n)
    y = (raw > 0).astype(np.float32) if classification \
        else raw.astype(np.float32)
    return X, y


def _pair(params, X, y, rounds):
    """Train the same data fused and staged; return both boosters."""
    pf = dict(params, tpu_fused_iter="on")
    ps = dict(params, tpu_fused_iter="off")
    bf = lgb.train(pf, lgb.Dataset(X, label=y, params=pf),
                   num_boost_round=rounds)
    bs = lgb.train(ps, lgb.Dataset(X, label=y, params=ps),
                   num_boost_round=rounds)
    return bf, bs


def _assert_identical(bf, bs, X):
    assert bf._gbdt._fused_state[0] is not None, \
        "tpu_fused_iter=on did not resolve to the fused program"
    assert bs._gbdt._fused_state[0] is None
    assert bf.model_to_string() == bs.model_to_string()
    np.testing.assert_array_equal(bf.predict(X), bs.predict(X))


# ------------------------------------------------------------ bit identity

def test_fused_matches_staged_default_growth():
    X, y = _xy(500, 12, 0)
    p = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
         "verbose": -1}
    bf, bs = _pair(p, X, y, rounds=8)
    _assert_identical(bf, bs, X)


def test_fused_matches_staged_regression_objective():
    X, y = _xy(400, 8, 1, classification=False)
    p = {"objective": "regression", "num_leaves": 7,
         "min_data_in_leaf": 5, "verbose": -1}
    bf, bs = _pair(p, X, y, rounds=6)
    _assert_identical(bf, bs, X)


# scaled-down benchmark shape buckets (tools/BENCH_SUITE.md): the axes
# that select different wave-kernel layouts — narrow-F (flagship),
# wide-F (epsilon), mid-F deep trees (msltr), and the pallas_ct fused
# partition kernel (expo_cat's ct-bound shape)
PARITY_MATRIX = [
    ("flagship", 400, 12, 15, "pallas_t", "binary"),
    ("epsilon", 260, 48, 15, "pallas_t", "binary"),
    ("msltr", 350, 24, 31, "pallas_t", "regression"),
    ("expo_cat", 300, 10, 7, "pallas_ct", "binary"),
]


@pytest.mark.parametrize("name,n,f,leaves,mode,obj", PARITY_MATRIX)
def test_fused_matches_staged_on_interpret_pallas_wave(name, n, f, leaves,
                                                       mode, obj):
    """The fused program inlines the learner's own grow closure, so the
    Pallas wave kernels (run in interpret mode on CPU) must produce the
    same trees through either entry granularity."""
    X, y = _xy(n, f, 7, classification=obj == "binary")
    p = {"objective": obj, "num_leaves": leaves, "min_data_in_leaf": 5,
         "verbose": -1, "tpu_growth": "wave", "tpu_histogram_mode": mode,
         "tpu_pallas_interpret": True}
    bf, bs = _pair(p, X, y, rounds=3)
    _assert_identical(bf, bs, X)


# ------------------------------------------------------------- eligibility

def _train_one(extra, n=300, f=6, **data_kw):
    X, y = _xy(n, f, 11, **data_kw)
    p = dict({"objective": "binary", "num_leaves": 7, "verbose": -1,
              "min_data_in_leaf": 5}, **extra)
    return lgb.train(p, lgb.Dataset(X, label=y, params=p),
                     num_boost_round=2)


def test_fused_supported_rejects_special_modes():
    cases = [
        ({"boosting_type": "dart"}, "dart"),
        ({"boosting_type": "goss"}, "goss"),
        ({"obs_health": "warn"}, "health"),
    ]
    for extra, tag in cases:
        bst = _train_one(extra)
        ok, why = fused_supported(bst._gbdt)
        assert not ok and why, tag

    X, y = _xy(300, 6, 11)
    p = {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
         "verbose": -1, "min_data_in_leaf": 5}
    bst = lgb.train(p, lgb.Dataset(X, label=(y + (X[:, 1] > 0)),
                                   params=p), num_boost_round=2)
    ok, why = fused_supported(bst._gbdt)
    assert not ok and "multiclass" in why

    def fobj(preds, ds):
        g = preds - ds.get_label()
        return g, np.ones_like(g)

    p = {"objective": "none", "num_leaves": 7, "verbose": -1,
         "min_data_in_leaf": 5}
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p),
                    num_boost_round=2, fobj=fobj)
    ok, why = fused_supported(bst._gbdt)
    assert not ok and "fobj" in why


def test_fused_on_with_ineligible_config_stays_staged():
    """`on` must degrade to the staged chain (resolved once, cached as
    (None,)) instead of crashing when the config cannot fuse."""
    bst = _train_one({"boosting_type": "dart", "tpu_fused_iter": "on"})
    assert bst._gbdt._fused_state == (None,)


def test_fused_auto_stays_staged_on_plain_cpu():
    """auto only fuses where the wave Pallas kernels are active or the
    autotuner measured the fused cell as the winner — a default CPU run
    is neither."""
    bst = _train_one({})
    assert bst._gbdt._fused_state == (None,)


def test_fused_iter_mode_validated():
    from lightgbm_tpu.utils.log import LightGBMError
    with pytest.raises(LightGBMError, match="tpu_fused_iter"):
        _train_one({"tpu_fused_iter": "sometimes"})


# -------------------------------------------------------- zero host syncs

def test_default_boosting_loop_is_fence_free():
    """The complete-audit contract: every deliberate host sync in the
    training stack routes through obs/timers.fence, and a default run
    (NULL observer) must never hit it mid-tree.  Iteration 0 is burned
    outside the window — the periodic stop-check device_get fires every
    16 iterations starting there."""
    X, y = _xy(400, 6, 3)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "verbose": -1},
                      train_set=lgb.Dataset(X, label=y))
    bst.update()
    before = obs_timers.fence_count()
    for _ in range(3):
        bst.update()
    assert obs_timers.fence_count() == before


# --------------------------------------------------- band-probe regression

def test_band_probe_reproduces_and_fixes_the_degeneracy():
    """The minimal reproduction of the former 18-30 MB band: epsilon's
    W16 cell oversubscribes the Mosaic overlap window under the legacy
    row-tile plan and fits under the accumulator-aware one, while bosch
    W64 (chunked-RMW regime) never needed fixing."""
    from lightgbm_tpu.ops.pallas_wave import tile_plan_vmem_report
    rep = tile_plan_vmem_report(1 << 20, 2000, 64, 16)
    assert rep["pathological_old"] and not rep["pathological_new"]
    assert rep["live_new"] <= rep["overlap_window"] < rep["live_old"]
    chunked = tile_plan_vmem_report(1 << 20, 968, 64, 64)
    assert chunked["chunked_rmw"] and not chunked["pathological_old"]
