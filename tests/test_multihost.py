"""Multi-host pod scale-out: sharded ingest, comm-integrated training,
checkpoint/elastic shrink-and-resume (PR 14).

Two gears, mirroring tests/test_multiprocess.py:

* SUBPROCESS worlds (parallel/launch.py): real OS processes, real
  ``jax.distributed`` worlds.  Bit-identity across world sizes is
  asserted here — and the tests skip cleanly (MultiprocessUnsupported)
  where this jaxlib's CPU client lacks cross-process collectives, the
  same environment limit test_multiprocess.py skips on.
* THREAD worlds (parallel/comm.py run_ranks): one process, host-comm
  collectives only — ranks share one backend, so each trains its own
  shard on the local mesh (no cross-rank device psum).  These drill the
  layers that don't need one: rank-sharded ingest accounting, host
  metric/vote collectives, checkpoint resume, and the kill-one-rank
  elastic drill — everywhere, including this host.
"""
import json
import os

import numpy as np
import pytest

from lightgbm_tpu.parallel import worker
from lightgbm_tpu.parallel.comm import SingleProcessComm, run_ranks
from lightgbm_tpu.parallel.elastic import run_elastic, run_elastic_threads
from lightgbm_tpu.parallel.launch import (MultiprocessUnsupported,
                                          run_ranks_subprocess)

SPEC = "lightgbm_tpu.parallel.worker:train_worker"


def _subprocess(size, payload, **kw):
    try:
        return run_ranks_subprocess(size, SPEC, payload, **kw)
    except MultiprocessUnsupported as e:
        pytest.skip(str(e))


# ---------------------------------------------------------------- comms

def test_reduce_metrics_weighted_mean_and_vote_stop():
    from lightgbm_tpu.parallel.comm import reduce_metrics, vote_stop

    def fn(comm):
        red = reduce_metrics(comm, {"m": float(comm.rank)},
                             weight=float(comm.rank + 1))
        votes = (vote_stop(comm, True),
                 vote_stop(comm, comm.rank != 1))
        return red["m"], votes

    out = run_ranks(3, fn)
    # weighted mean (0*1 + 1*2 + 2*3) / 6 — identical on every rank
    for m, votes in out:
        assert m == pytest.approx(8.0 / 6.0)
        assert votes == (True, False)   # unanimity: no rank stops alone

    # single-process fast path: no collective, values pass through
    one = SingleProcessComm()
    assert reduce_metrics(one, {"m": 3.5})["m"] == 3.5
    assert vote_stop(one, True) is True


# ----------------------------------------------------------- checkpoint

def test_checkpoint_roundtrip_and_fingerprint_guard(tmp_path):
    import lightgbm_tpu as lgb
    from lightgbm_tpu.models import checkpoint as ck
    from lightgbm_tpu.utils.log import LightGBMError

    X, y = worker.make_data(300, 5, 1)
    params = dict(worker.default_params(), tree_learner="serial")
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3)

    d = str(tmp_path / "ck")
    os.makedirs(d)
    path = ck.save_checkpoint(d, bst._gbdt, 3, params, world_size=2)
    assert os.path.basename(path) == "checkpoint.json"
    loaded = ck.load_checkpoint(d)
    assert loaded["iteration"] == 3 and loaded["world_size"] == 2
    assert loaded["seeds"]["bagging_seed"] == params["bagging_seed"]
    # the payload is the whole model: restoring it restores the booster
    rt = lgb.Booster(model_str=loaded["model"])
    assert rt.model_to_string() == bst.model_to_string()

    # same training params (operational keys may drift) -> resumable
    ck.check_resumable(loaded, dict(params, obs_events_path="/tmp/x",
                                    checkpoint_every=7, verbose=2))
    # a TRAINING param drift must refuse loudly, not train a chimera
    with pytest.raises(LightGBMError):
        ck.check_resumable(loaded, dict(params, learning_rate=0.5))

    assert ck.load_checkpoint(str(tmp_path / "missing")) is None


def test_engine_resumes_from_checkpoint_same_tree_count(tmp_path):
    d = str(tmp_path / "ck")
    os.makedirs(d)
    # serial learner: the thread-mode tests drill checkpoint/comm/ingest
    # mechanics; the mesh learners' exact-growth path cannot trace on
    # this jaxlib (same environment limit tests/test_parallel.py hits)
    ser = {"params": {"tree_learner": "serial"}}
    base = {"rows": 400, "cols": 5, "seed": 9,
            "checkpoint_dir": d, "checkpoint_every": 2, **ser}
    r1 = worker.train_worker(SingleProcessComm(),
                             dict(base, num_rounds=4))
    assert r1["num_trees"] == 4
    # a later train() with the same config picks the checkpoint up and
    # only trains the remaining rounds
    r2 = worker.train_worker(SingleProcessComm(),
                             dict(base, num_rounds=6))
    assert r2["iter"] == 2               # resumed: 4 done, 2 remain
    assert r2["num_trees"] == 6
    ref = worker.train_worker(SingleProcessComm(),
                              {"rows": 400, "cols": 5, "seed": 9,
                               "num_rounds": 6, **ser})
    assert r2["num_trees"] == ref["num_trees"]


# ------------------------------------------------------- sharded ingest

@pytest.mark.parametrize("size", [2, 4])
def test_rank_sharded_from_binned_maps_only_local_shards(tmp_path, size):
    from lightgbm_tpu.io.dataset import TrainingData
    from lightgbm_tpu.utils.config import Config

    X, y = worker.make_data(1000, 6, 7)
    out = str(tmp_path / "binned")
    cfg = Config({"max_bin": 63, "verbose": -1})
    full = TrainingData.from_streamed(X, y, cfg, out_dir=out,
                                      chunk_rows=128)

    def open_shard(comm):
        td = TrainingData.from_binned(out, comm=comm)
        r = td._binned_reader
        mat = np.asarray(td.binned)      # materialize local rows only
        return {"rank": comm.rank, "row_range": r.row_range,
                "mapped": sorted(r.mapped_shards),
                "active": sorted(r.active_shards),
                "n_shards": r.num_shards,
                "mat": mat, "label": np.asarray(td.metadata.label),
                "mappers": [None if m is None else m.to_dict()
                            for m in td.bin_mappers]}

    res = run_ranks(size, open_shard)
    full_mat = np.asarray(full.binned)
    lo_seen = 0
    for r in res:
        lo, hi = r["row_range"]
        assert lo == lo_seen            # balanced, gap-free row split
        lo_seen = hi
        # the mmap accounting invariant: a rank NEVER maps a shard that
        # doesn't intersect its row range
        assert set(r["mapped"]) <= set(r["active"])
        assert len(r["active"]) < r["n_shards"], \
            "a %d-rank shard mapped the whole table" % size
        # bit-identical binning from the shared header
        assert r["mappers"] == res[0]["mappers"]
        assert np.array_equal(r["mat"], full_mat[lo:hi])
        assert np.allclose(r["label"], y[lo:hi])
    assert lo_seen == 1000              # ranges cover every row exactly


def test_sharded_train_reports_shard_accounting(tmp_path):
    """The worker result carries the reader accounting end-to-end:
    training a rank-sharded binned open never touches foreign shards."""
    from lightgbm_tpu.io.dataset import TrainingData
    from lightgbm_tpu.utils.config import Config

    X, y = worker.make_data(800, 6, 3)
    out = str(tmp_path / "binned")
    TrainingData.from_streamed(X, y, Config({"max_bin": 63,
                                             "verbose": -1}),
                               out_dir=out, chunk_rows=128)
    payload = {"binned_dir": out, "num_rounds": 2,
               "params": {"tree_learner": "serial"}}
    res = run_ranks(2, lambda c: worker.train_worker(c, payload))
    for r in res:
        assert r["num_data"] == 400
        assert set(r["mapped_shards"]) <= set(r["active_shards"])
        assert r["num_trees"] == 2


# -------------------------------------------------------- elastic drill

def test_elastic_thread_drill_kill_one_rank(tmp_path):
    """Kill rank 1 mid-run; the world shrinks to 1 and resumes from the
    checkpoint to the SAME final tree count as an uninterrupted run,
    with the mesh-shrink event recorded on the resumed timeline."""
    d = str(tmp_path / "el")
    os.makedirs(d)
    obs = os.path.join(d, "tl.jsonl")
    payload = {"rows": 500, "cols": 5, "num_rounds": 6, "seed": 5,
               "checkpoint_dir": d, "checkpoint_every": 1,
               "kill_rank": 1, "kill_iter": 3, "kill_hard": False,
               "obs_path": obs,
               "params": {"tree_learner": "serial"}}

    out = run_elastic_threads(
        2, lambda comm: worker.train_worker(comm, payload),
        barrier_timeout=30.0)
    assert out["attempts"] == 2 and out["world_size"] == 1
    assert len(out["flight_records"]) == 1
    assert "injected rank kill" in out["flight_records"][0]["error"]

    ref = worker.train_worker(SingleProcessComm(),
                              {"rows": 500, "cols": 5, "num_rounds": 6,
                               "seed": 5,
                               "params": {"tree_learner": "serial"}})
    assert [r["num_trees"] for r in out["results"]] == [ref["num_trees"]]

    from lightgbm_tpu.obs import read_events
    evs = []
    for name in sorted(os.listdir(d)):
        if name.startswith("tl.jsonl"):
            evs += read_events(os.path.join(d, name), validate=False)
    shrink = [e for e in evs if e.get("ev") == "mesh_shrink"]
    assert shrink and shrink[0]["world_size_from"] == 2 \
        and shrink[0]["world_size_to"] == 1
    assert any(e.get("ev") == "checkpoint" for e in evs)


def test_elastic_exhausted_carries_flight_records():
    from lightgbm_tpu.parallel.elastic import ElasticExhausted

    def always_dies(comm):
        raise RuntimeError("rank %d down" % comm.rank)

    with pytest.raises(ElasticExhausted) as ei:
        run_elastic_threads(2, always_dies, min_size=2)
    assert ei.value.flight_records \
        and ei.value.flight_records[0]["world_size"] == 2


# --------------------------------------------------- subprocess worlds

@pytest.mark.slow
@pytest.mark.parametrize("mode", ["staged", "fused"])
def test_subprocess_worlds_bit_identical_to_single_host(mode):
    """1/2/4-rank pods over a CONSTANT 4-device global mesh (4, 2x2,
    1x4 local devices): same shard layout, same psum axis — every rank
    of every world must produce the single-host model bit-for-bit."""
    payload = {"rows": 1024, "cols": 6, "num_rounds": 3, "seed": 2,
               "params": {"tree_learner": "data",
                          "tpu_fused_iter":
                          "on" if mode == "fused" else "off"}}
    digests = {}
    for size, local in ((1, 4), (2, 2), (4, 1)):
        res = _subprocess(size, payload, local_devices=local)
        ds = {r["digest"] for r in res}
        assert len(ds) == 1, \
            "ranks of the %d-proc world disagree: %s" % (size, ds)
        digests[size] = ds.pop()
    assert digests[2] == digests[1], "2-rank pod diverged from 1-host"
    assert digests[4] == digests[1], "4-rank pod diverged from 1-host"


@pytest.mark.slow
def test_subprocess_elastic_drill_resumes(tmp_path):
    d = str(tmp_path / "ck")
    os.makedirs(d)
    payload = {"rows": 512, "cols": 5, "num_rounds": 5, "seed": 4,
               "params": {"tree_learner": "data"},
               "checkpoint_dir": d, "checkpoint_every": 1,
               "kill_rank": 1, "kill_iter": 2}
    try:
        out = run_elastic(2, SPEC, payload, timeout=300.0)
    except MultiprocessUnsupported as e:
        pytest.skip(str(e))
    assert out["attempts"] == 2 and out["world_size"] == 1
    assert out["flight_records"][0]["failed_ranks"] == [1]
    assert [r["num_trees"] for r in out["results"]] == [5]


@pytest.mark.slow
def test_subprocess_single_rank_roundtrip():
    """World size 1 through the FULL launcher path (env contract,
    distributed_init autodetect, MPRESULT protocol) runs everywhere —
    the pod plumbing itself needs no pod."""
    res = _subprocess(1, {"rows": 256, "cols": 4, "num_rounds": 2})
    assert res[0]["rank"] == 0 and res[0]["size"] == 1
    assert res[0]["num_trees"] == 2
    assert json.dumps(res[0])           # the MPRESULT contract is JSON
