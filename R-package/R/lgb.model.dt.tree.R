# Flat per-node model table — parity with R-package/R/lgb.model.dt.tree.R
# (tree_index / split_index / split_feature / threshold / children /
# internal and leaf values), built from the JSON dump.  Returns a
# data.frame instead of the reference's data.table.

#' Per-node table of the model's trees
#'
#' @param model lgb.Booster
#' @param num_iteration trees of the first n iterations (-1 = all)
#' @export
lgb.model.dt.tree <- function(model, num_iteration = -1L) {
  if (!lgb.is.Booster(model)) stop("lgb.model.dt.tree: need an lgb.Booster")
  dump <- lgb.dump(model, num_iteration = num_iteration)
  feat_names <- unlist(dump$feature_names)
  rows <- list()

  walk <- function(node, tree_index, parent_index, depth) {
    is_leaf <- !is.null(node$leaf_value) && is.null(node$split_feature)
    idx <- length(rows) + 1L
    if (is_leaf) {
      rows[[idx]] <<- data.frame(
        tree_index = tree_index, depth = depth,
        split_index = NA_integer_,
        split_feature = NA_character_,
        node_parent = parent_index,
        leaf_index = as.integer(node$leaf_index),
        leaf_parent = parent_index,
        split_gain = NA_real_, threshold = NA_real_,
        decision_type = NA_character_,
        internal_value = NA_real_,
        internal_count = NA_integer_,
        leaf_value = as.numeric(node$leaf_value),
        leaf_count = as.integer(
          if (is.null(node$leaf_count)) NA else node$leaf_count),
        stringsAsFactors = FALSE)
      return(invisible(NULL))
    }
    sidx <- as.integer(node$split_index)
    f <- as.integer(node$split_feature)
    rows[[idx]] <<- data.frame(
      tree_index = tree_index, depth = depth,
      split_index = sidx,
      split_feature = if (f + 1L <= length(feat_names)) feat_names[f + 1L]
                      else as.character(f),
      node_parent = parent_index,
      leaf_index = NA_integer_, leaf_parent = NA_integer_,
      split_gain = as.numeric(node$split_gain),
      threshold = as.numeric(node$threshold),
      decision_type = as.character(node$decision_type),
      internal_value = as.numeric(node$internal_value),
      internal_count = as.integer(
        if (is.null(node$internal_count)) NA else node$internal_count),
      leaf_value = NA_real_, leaf_count = NA_integer_,
      stringsAsFactors = FALSE)
    walk(node$left_child, tree_index, sidx, depth + 1L)
    walk(node$right_child, tree_index, sidx, depth + 1L)
  }

  for (t in dump$tree_info) {
    walk(t$tree_structure, as.integer(t$tree_index), NA_integer_, 0L)
  }
  do.call(rbind, rows)
}
