"""End-to-end convergence thresholds per task — the reference's
tests/python_package_test/test_engine.py:33-91 strategy."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def make_binary(n=1200, f=12, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    logit = X[:, 0] * 1.5 + X[:, 1] - X[:, 2] * 0.5 + 0.3 * rng.normal(size=n)
    y = (logit > 0).astype(np.float64)
    return X, y


def make_regression(n=1200, f=10, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 3 + np.sin(X[:, 1] * 2) * 2 + 0.1 * rng.normal(size=n)
    return X, y


def test_binary_convergence():
    X, y = make_binary()
    Xt, yt = make_binary(seed=7)
    train = lgb.Dataset(X, label=y)
    valid = train.create_valid(Xt, yt)
    evals = {}
    bst = lgb.train({"objective": "binary", "metric": "binary_logloss",
                     "num_leaves": 31, "verbose": -1},
                    train, num_boost_round=60, valid_sets=[valid],
                    evals_result=evals, verbose_eval=False)
    loss = evals["valid_0"]["binary_logloss"][-1]
    assert loss < 0.25
    # probability output in [0,1]
    p = bst.predict(Xt)
    assert p.min() >= 0 and p.max() <= 1


def test_regression_convergence():
    X, y = make_regression()
    Xt, yt = make_regression(seed=9)
    train = lgb.Dataset(X, label=y)
    valid = train.create_valid(Xt, yt)
    evals = {}
    lgb.train({"objective": "regression", "metric": "l2", "verbose": -1},
              train, num_boost_round=80, valid_sets=[valid],
              evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["l2"][-1] < 1.0


def test_multiclass_convergence():
    rng = np.random.default_rng(3)
    n = 900
    X = rng.normal(size=(n, 8))
    y = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
    train = lgb.Dataset(X, label=y.astype(float))
    evals = {}
    lgb.train({"objective": "multiclass", "num_class": 3,
               "metric": "multi_logloss", "verbose": -1},
              train, num_boost_round=50, valid_sets=[train],
              evals_result=evals, verbose_eval=False)
    assert evals["training"]["multi_logloss"][-1] < 0.35


def test_early_stopping():
    X, y = make_binary()
    Xt, yt = make_binary(seed=11)
    train = lgb.Dataset(X, label=y)
    valid = train.create_valid(Xt, yt)
    bst = lgb.train({"objective": "binary", "metric": "binary_logloss",
                     "verbose": -1, "num_leaves": 63, "learning_rate": 0.5},
                    train, num_boost_round=400, valid_sets=[valid],
                    early_stopping_rounds=5, verbose_eval=False)
    assert bst.best_iteration > 0
    assert bst.current_iteration() < 400


def test_model_file_roundtrip(tmp_path):
    X, y = make_binary()
    train = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "verbose": -1}, train,
                    num_boost_round=10, verbose_eval=False)
    path = str(tmp_path / "model.txt")
    bst.save_model(path)
    bst2 = lgb.Booster(model_file=path)
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X), rtol=1e-15)
    # string round-trip preserves re-save exactly (test_basic.py:40-47)
    assert bst2.model_to_string() == lgb.Booster(
        model_str=bst.model_to_string()).model_to_string()


def test_continued_training():
    X, y = make_binary()
    train = lgb.Dataset(X, label=y, free_raw_data=False)
    bst1 = lgb.train({"objective": "binary", "verbose": -1}, train,
                     num_boost_round=10, verbose_eval=False)
    train2 = lgb.Dataset(X, label=y, free_raw_data=False)
    bst2 = lgb.train({"objective": "binary", "verbose": -1}, train2,
                     num_boost_round=10, init_model=bst1, verbose_eval=False)
    assert bst2.num_trees() > bst1.num_trees()
    # continued model must improve (or match) training loss
    p1 = bst1.predict(X)
    p2 = bst2.predict(X)
    def logloss(p):
        p = np.clip(p, 1e-12, 1 - 1e-12)
        return -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()
    assert logloss(p2) <= logloss(p1) + 1e-9


def test_custom_objective_fobj():
    X, y = make_binary()
    train = lgb.Dataset(X, label=y)

    def fobj(preds, dataset):
        labels = dataset.get_label()
        p = 1.0 / (1.0 + np.exp(-preds))
        return p - labels, p * (1 - p)

    bst = lgb.train({"verbose": -1, "num_leaves": 31}, train,
                    num_boost_round=30, fobj=fobj, verbose_eval=False)
    p = 1.0 / (1.0 + np.exp(-bst.predict(X, raw_score=True)))
    acc = ((p > 0.5) == (y > 0)).mean()
    assert acc > 0.9


def test_feval():
    X, y = make_binary()
    train = lgb.Dataset(X, label=y)
    valid = train.create_valid(X, y)

    def feval(preds, dataset):
        labels = dataset.get_label()
        p = 1.0 / (1.0 + np.exp(-preds))
        return "my_err", float(((p > 0.5) != (labels > 0)).mean()), False

    evals = {}
    lgb.train({"objective": "binary", "metric": "binary_logloss",
               "verbose": -1}, train, num_boost_round=10,
              valid_sets=[valid], feval=feval, evals_result=evals,
              verbose_eval=False)
    assert "my_err" in evals["valid_0"]


def test_bagging_and_feature_fraction():
    X, y = make_binary(n=2000)
    train = lgb.Dataset(X, label=y)
    evals = {}
    lgb.train({"objective": "binary", "metric": "auc", "verbose": -1,
               "bagging_fraction": 0.7, "bagging_freq": 1,
               "feature_fraction": 0.8},
              train, num_boost_round=40, valid_sets=[train],
              evals_result=evals, verbose_eval=False)
    assert evals["training"]["auc"][-1] > 0.95


def test_weights_affect_training():
    X, y = make_binary()
    w = np.where(y > 0, 10.0, 1.0)
    train = lgb.Dataset(X, label=y, weight=w)
    bst = lgb.train({"objective": "binary", "verbose": -1}, train,
                    num_boost_round=20, verbose_eval=False)
    p_w = bst.predict(X).mean()
    train0 = lgb.Dataset(X, label=y)
    bst0 = lgb.train({"objective": "binary", "verbose": -1}, train0,
                     num_boost_round=20, verbose_eval=False)
    assert p_w > bst0.predict(X).mean()   # upweighted positives shift probs


def test_max_depth():
    X, y = make_binary()
    train = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "verbose": -1, "max_depth": 3,
                     "num_leaves": 63}, train, num_boost_round=5,
                    verbose_eval=False)
    model = bst.dump_model()
    def depth(node, d=0):
        if "leaf_index" in node:
            return d
        return max(depth(node["left_child"], d + 1),
                   depth(node["right_child"], d + 1))
    for info in model["tree_info"]:
        assert depth(info["tree_structure"]) <= 3


def test_lambdarank():
    rng = np.random.default_rng(5)
    n_q, per_q = 60, 12
    n = n_q * per_q
    X = rng.normal(size=(n, 6))
    rel = (X[:, 0] + 0.5 * rng.normal(size=n))
    y = np.clip(np.digitize(rel, [-0.5, 0.5, 1.2]), 0, 3).astype(float)
    group = np.full(n_q, per_q)
    train = lgb.Dataset(X, label=y, group=group)
    evals = {}
    lgb.train({"objective": "lambdarank", "metric": "ndcg",
               "ndcg_eval_at": [3], "verbose": -1, "min_data_in_leaf": 5},
              train, num_boost_round=30, valid_sets=[train],
              evals_result=evals, verbose_eval=False)
    ndcg = evals["training"]["ndcg@3"]
    assert ndcg[-1] > ndcg[0]
    assert ndcg[-1] > 0.8


def test_cv():
    X, y = make_binary()
    train = lgb.Dataset(X, label=y, free_raw_data=False)
    res = lgb.cv({"objective": "binary", "metric": "binary_logloss",
                  "verbose": -1}, train, num_boost_round=10, nfold=3)
    assert "binary_logloss-mean" in res
    assert len(res["binary_logloss-mean"]) == 10


def test_cvbooster_broadcast():
    """engine.CVBooster mirrors the reference container (engine.py:206-224):
    .boosters holds the fold boosters and unknown attributes broadcast the
    method call, returning one result per fold."""
    from lightgbm_tpu.engine import CVBooster
    X, y = make_binary()
    cvb = CVBooster()
    for seed in (1, 2, 3):
        train = lgb.Dataset(X, label=y)
        cvb.append(lgb.train({"objective": "binary", "verbose": -1,
                              "seed": seed}, train, num_boost_round=3))
    assert len(cvb.boosters) == 3
    preds = cvb.predict(X)          # broadcast through __getattr__
    assert len(preds) == 3 and all(p.shape == (len(y),) for p in preds)
    assert cvb.best_iteration == -1


def test_sklearn_deprecated_aliases():
    import warnings
    X, y = make_binary()
    clf = lgb.LGBMClassifier(n_estimators=3, verbose=-1)
    clf.fit(X, y)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert clf.booster() is clf.booster_
        assert np.array_equal(clf.feature_importance(),
                              clf.feature_importances_)
    assert all(issubclass(x.category, DeprecationWarning) for x in w)
    assert len(w) == 2


def test_boosting_variants():
    X, y = make_binary()
    for boosting in ("dart", "goss"):
        train = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "binary", "boosting": boosting,
                         "verbose": -1, "learning_rate": 0.1},
                        train, num_boost_round=15, verbose_eval=False)
        p = bst.predict(X)
        acc = ((p > 0.5) == (y > 0)).mean()
        assert acc > 0.85, boosting


def test_infiniteboost():
    X, y = make_binary()
    train = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "boosting": "infinite",
                     "capacity": 20.0, "verbose": -1},
                    train, num_boost_round=25, verbose_eval=False)
    p = bst.predict(X)
    acc = ((p > 0.5) == (y > 0)).mean()
    assert acc > 0.85


def test_reset_parameter_in_place():
    """Booster.reset_parameter rebuilds hyperparameters without resetting
    training state (GBDT::ResetConfig semantics): existing trees keep
    contributing, and subsequent trees honor the new num_leaves."""
    rng = np.random.default_rng(9)
    X = rng.normal(size=(3000, 6))
    y = (X[:, 0] + 0.5 * rng.normal(size=3000) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 31, "verbose": -1,
              "min_data_in_leaf": 5, "metric": "binary_logloss"}
    bst = lgb.Booster(params=params, train_set=lgb.Dataset(X, label=y))
    for _ in range(3):
        bst.update()
    before = bst._gbdt.get_eval_at(0)[0]
    bst.reset_parameter({"num_leaves": 7, "lambda_l2": 1.0})
    for _ in range(3):
        bst.update()
    after = bst._gbdt.get_eval_at(0)[0]
    assert after < before          # scores carried over, still improving
    assert bst.num_trees() == 6
    bst._gbdt._materialize()               # device trees -> host Tree objs
    dumped = bst._gbdt.models
    assert dumped[0].num_leaves > 7        # pre-reset trees: old width
    assert dumped[-1].num_leaves <= 7      # post-reset trees: new width


def test_reset_parameter_callback_all_keys():
    """The reset_parameter CALLBACK applies every scheduled key (it
    delegates to Booster.reset_parameter), not just learning_rate."""
    rng = np.random.default_rng(12)
    X = rng.normal(size=(2000, 6))
    y = (X[:, 0] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 31, "verbose": -1}
    bst = lgb.train(params, lgb.Dataset(X, label=y, params=params),
                    num_boost_round=4,
                    callbacks=[lgb.reset_parameter(
                        learning_rate=[0.3, 0.2, 0.1, 0.05],
                        lambda_l2=[0.0, 0.5, 1.0, 2.0])])
    gb = bst._gbdt
    assert gb.shrinkage_rate == 0.05
    assert gb.learner.config.lambda_l2 == 2.0


def test_reset_parameter_callback_skips_unchanged():
    """A constant schedule must NOT trigger per-iteration resets (which
    would wipe bagging state off-schedule and rebuild the learner)."""
    rng = np.random.default_rng(13)
    X = rng.normal(size=(2000, 6))
    y = (X[:, 0] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 31, "verbose": -1,
              "bagging_fraction": 0.8, "bagging_freq": 5, "lambda_l2": 1.0}

    def fit(callbacks):
        return lgb.train(dict(params),
                         lgb.Dataset(X, label=y, params=params),
                         num_boost_round=6, callbacks=callbacks)

    plain = fit([])
    constant = fit([lgb.reset_parameter(lambda_l2=lambda i: 1.0)])
    np.testing.assert_allclose(plain.predict(X), constant.predict(X),
                               rtol=1e-12)


def test_zero_boost_rounds():
    # num_boost_round=0 must return an empty booster, not NameError
    # (reference engine.py handles 0 rounds).
    X, y = make_binary(n=200)
    bst = lgb.train({"objective": "binary", "verbose": -1},
                    lgb.Dataset(X, label=y), num_boost_round=0,
                    verbose_eval=False)
    assert bst.current_iteration() == 0
    assert dict(bst.best_score) == {}


def test_exact_growth_ignores_bad_wave_width():
    # ADVICE r2: exact growth never uses the wave width, so a garbage
    # tpu_wave_width must not abort training.
    X, y = make_binary(n=300)
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "tpu_growth": "exact", "tpu_wave_width": 0,
                     "num_leaves": 7},
                    lgb.Dataset(X, label=y), num_boost_round=3,
                    verbose_eval=False)
    assert bst.current_iteration() == 3


def test_cv_runs_callbacks():
    """cv() must actually drive the callback engine (reset schedules,
    record, early stop) over the fold boosters — R's lgb.cv forwards
    callbacks here, so a silent no-op would strand that surface."""
    import lightgbm_tpu as lgb
    rng = np.random.default_rng(9)
    X = rng.normal(size=(600, 4))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              "metric": "binary_logloss"}

    seen = []
    store = {}

    def spy(env):
        seen.append((env.iteration,
                     [i[:3] for i in env.evaluation_result_list]))
    spy.order = 25

    out = lgb.cv(params, lgb.Dataset(X, label=y), num_boost_round=4,
                 nfold=3, stratified=False, verbose_eval=False,
                 callbacks=[spy, lgb.record_evaluation(store),
                            lgb.reset_parameter(
                                learning_rate=lambda i, n: 0.3 * 0.9 ** i)])
    assert len(out["binary_logloss-mean"]) == 4
    assert [s[0] for s in seen] == [0, 1, 2, 3]
    # 5-tuple cv_agg entries reached the callbacks with the mean score
    assert seen[0][1][0][0] == "cv_agg"
    assert store["cv_agg"]["binary_logloss"] == out["binary_logloss-mean"]

    # early stopping via the callback engine truncates the records
    out2 = lgb.cv(params, lgb.Dataset(X, label=y), num_boost_round=300,
                  nfold=3, stratified=False, verbose_eval=False,
                  callbacks=[lgb.early_stopping(5, False)])
    assert len(out2["binary_logloss-mean"]) < 300


def test_reset_parameter_schedule_arities():
    """f(iter), f(iter, nrounds), and f(iter, base=default) must all be
    called correctly — a defaulted second arg is NOT the 2-arg form."""
    import lightgbm_tpu as lgb
    rng = np.random.default_rng(10)
    X = rng.normal(size=(300, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    calls = {"one": [], "two": [], "defaulted": []}

    def one(i):
        calls["one"].append(i)
        return 0.1

    def two(i, n):
        calls["two"].append((i, n))
        return 0.1

    def defaulted(i, base=0.2):
        calls["defaulted"].append((i, base))
        return base

    for fn in (one, two, defaulted):
        lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=3,
                  callbacks=[lgb.reset_parameter(learning_rate=fn)],
                  verbose_eval=False)
    assert calls["one"] == [0, 1, 2]
    assert calls["two"] == [(0, 3), (1, 3), (2, 3)]
    # the default survived: nrounds was NOT substituted for base
    assert calls["defaulted"] == [(0, 0.2), (1, 0.2), (2, 0.2)]


def test_reset_parameter_honors_arity_marker():
    """The R bridge tags reticulate wrappers (Python signature
    (*args, **kwargs)) with lgb_schedule_arity; the marker must win
    over signature inspection."""
    import lightgbm_tpu as lgb
    rng = np.random.default_rng(11)
    X = rng.normal(size=(300, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    calls = []

    def wrapperish(*args, **kwargs):       # uninformative signature
        calls.append(args)
        return 0.1
    wrapperish.lgb_schedule_arity = 2

    lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1},
              lgb.Dataset(X, label=y), num_boost_round=2,
              callbacks=[lgb.reset_parameter(learning_rate=wrapperish)],
              verbose_eval=False)
    assert calls == [(0, 2), (1, 2)]
