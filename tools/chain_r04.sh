#!/bin/bash
# Round-4 measurement chain (VERDICT.md "Next round" items 1-5).
#
# One orchestrator, armed at round start while the tunnel is wedged:
# waits for the device, then runs the priority list with per-stage caps
# and a global end time so the driver's round-end bench always gets the
# chip back.  Every stage is itself wedge-resilient (bench.py /
# bench_suite.py / tpu_ab2.py re-probe + re-queue internally), so a
# mid-stage wedge costs retries, not the stage.
cd /root/repo || exit 1
LOG=/tmp/chain_r04.log
log() { echo "[chain4] $(date -u +%F\ %T) $*" >> "$LOG"; }

# global budget: stop launching stages after this many seconds from arming
TOTAL_S=${CHAIN_TOTAL_S:-34200}        # 9.5h default
END=$(( $(date +%s) + TOTAL_S ))
left() { echo $(( END - $(date +%s) )); }

stage() {  # stage <name> <cap_seconds> <cmd...>
  local name=$1 cap=$2; shift 2
  local l; l=$(left)
  if [ "$l" -le 300 ]; then log "$name SKIPPED (global budget spent)"; return; fi
  [ "$cap" -gt "$l" ] && cap=$l
  log "$name start (cap ${cap}s)"
  timeout "$cap" "$@" ; log "$name rc=$?"
}

log "armed (end $(date -u -d @$END +%T))"

# stage 0: wait for the tunnel (cheap subprocess probes; safe while wedged)
while :; do
  [ "$(left)" -le 600 ] && { log "tunnel never returned; chain idle-exit"; exit 0; }
  timeout 150 python - <<'EOF' >/dev/null 2>&1 && break
from lightgbm_tpu.utils.common import probe_device
import sys
sys.exit(0 if probe_device(timeout=120) == "tpu" else 1)
EOF
  sleep 120
done
log "tunnel ALIVE"

# 1) flagship bench — the >=8x number; also warms the persistent compile
#    cache so the driver's round-end run reaches its timed loop in seconds
stage bench1 3000 env BENCH_DEADLINE_S=2700 BENCH_ATTEMPT_S=1800 \
  bash -c 'python bench.py > /tmp/bench_r04_early.json 2> /tmp/bench_r04_early.err'

# 2) headline-shape table (VERDICT item 2): higgs/epsilon/msltr/expo + variants
stage suite 14400 env SUITE_DEADLINE_S=13800 \
  python tools/bench_suite.py higgs higgs_w64 epsilon epsilon_p16 msltr expo_cat higgs_ct

# 3) kernel zoo + Bosch dense-wave arms (VERDICT items 3 & 5)
stage ab2 7200 env AB2_DEADLINE_S=6900 \
  bash -c 'python tools/tpu_ab2.py 999424 --r03e > /tmp/ab2_r04.out 2>&1'

# 4) flagship-scale AUC parity, ours-vs-reference on identical bytes
#    (VERDICT item 4)
stage parity 7200 bash -c 'python tools/parity_flagship.py > /tmp/parity_flagship.out 2>&1'

# 5) re-warm: a final bench pass right before handing the chip back, so
#    the driver's run hits a hot compile cache and a published dataset cache
stage bench2 2100 env BENCH_DEADLINE_S=1800 \
  bash -c 'python bench.py > /tmp/bench_r04_late.json 2> /tmp/bench_r04_late.err'

log "chain complete; chip released"
