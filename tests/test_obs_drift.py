"""Drift & online model-quality monitoring (lightgbm_tpu/obs/drift.py).

The retrain-now loop: the training-time fingerprint (per-feature binned
histograms + score distribution + eval snapshot) must round-trip
byte-identical through BOTH persistence paths (model text, binned
dataset dir); the DriftMonitor must fire on a genuinely shifted stream
while an i.i.d. holdout stays clean over many windows (no PSI
small-sample false positives); the serving-input anomaly guard must
count and warn exactly once per feature; delayed labels must join into
``online_quality`` events; and the ``obs drift --check`` gate must
exit nonzero exactly when an alert fired (or monitoring never ran).
"""
import io
import json

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import RunObserver, read_events
from lightgbm_tpu.obs.drift import (DriftMonitor, drift_metrics, ks_stat,
                                    psi, render_drift_report,
                                    score_histogram, _group_map)
from lightgbm_tpu.obs.events import validate_event
from lightgbm_tpu.obs.metrics import REGISTRY

N_FEATURES = 6


def _data(n=1500, f=N_FEATURES, seed=0, loc=0.0):
    rng = np.random.default_rng(seed)
    X = rng.normal(loc=loc, size=(n, f))
    w = np.linspace(1.0, -1.0, f)
    y = (X @ w + 0.2 * rng.normal(size=n) > 0).astype(np.float64)
    return X, y


@pytest.fixture(scope="module")
def booster():
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    return lgb.train({"objective": "binary", "num_leaves": 15,
                      "verbose": -1, "metric": ["auc", "binary_logloss"]},
                     ds, num_boost_round=8,
                     valid_sets=[ds], valid_names=["train"],
                     verbose_eval=False)


def _canon(fp):
    return json.dumps(fp, sort_keys=True)


# ------------------------------------------------- fingerprint round trips

def test_fingerprint_captures_features_scores_eval(booster):
    fp = booster._gbdt.drift_fingerprint()
    assert fp is not None and fp["version"] == 1
    assert len(fp["features"]) == N_FEATURES
    for f in fp["features"]:
        assert sum(f["counts"]) > 0 and "mapper" in f
    assert "raw" in fp["scores"] and "output" in fp["scores"]
    metrics = {r["metric"] for r in fp["eval"]}
    assert "auc" in metrics and "binary_logloss" in metrics


def test_fingerprint_model_text_roundtrip(booster):
    fp = booster._gbdt.drift_fingerprint()
    s = booster.model_to_string()
    assert "drift_fingerprint=" in s
    loaded = lgb.Booster(model_str=s)
    fp2 = loaded._gbdt.drift_fingerprint()
    assert _canon(fp2) == _canon(fp)          # byte-identical
    # and the re-save carries it unchanged
    assert _canon(lgb.Booster(
        model_str=loaded.model_to_string())._gbdt.drift_fingerprint()) \
        == _canon(fp)


def test_fingerprint_binned_dir_roundtrip(tmp_path):
    from lightgbm_tpu.io.binned_format import save_training_data
    from lightgbm_tpu.io.dataset import TrainingData
    X, y = _data(n=600)
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    td = ds._handle
    fp = td._drift_fingerprint
    assert fp is not None
    out = str(tmp_path / "binned")
    save_training_data(td, out)
    td2 = TrainingData.from_binned(out)
    assert _canon(td2._drift_fingerprint) == _canon(fp)


def test_fingerprint_off_switch(tmp_path):
    X, y = _data(n=400)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1, "obs_drift_fingerprint": False},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    assert bst._gbdt.drift_fingerprint() is None
    assert "drift_fingerprint=" not in bst.model_to_string()


# --------------------------------------------------------- divergence math

def test_psi_ks_basics():
    ref = np.array([100, 100, 100, 100])
    assert psi(ref, ref * 3) < 0.01           # scale-invariant
    assert psi(ref, np.array([10, 10, 10, 370])) > 1.0
    assert ks_stat(ref, ref) < 0.01
    assert 0.7 < ks_stat(ref, np.array([0, 0, 0, 400])) <= 1.0


def test_group_map_equalizes_reference_mass():
    rng = np.random.default_rng(1)
    ref = rng.integers(1, 50, size=255)
    gmap, n = _group_map(ref)
    assert n <= 16 and gmap.shape == (255,)
    masses = np.bincount(gmap, weights=ref, minlength=n)
    assert masses.min() > 0
    # greedy equal-mass packing: no group hoards the distribution
    assert masses.max() / ref.sum() < 0.25


def test_score_histogram_edges_monotone():
    h = score_histogram(np.random.default_rng(2).normal(size=500))
    edges = np.asarray(h["edges"])
    assert np.all(np.diff(edges) > 0)
    assert sum(h["counts"]) == 500
    assert len(h["counts"]) == len(edges) + 1


# ------------------------------------------------- monitor: drill + guard

def test_shifted_stream_fires_iid_stays_clean(booster, tmp_path):
    """The acceptance drill at unit scale: 50 i.i.d. windows with real
    model scores must produce ZERO alerts (the PSI small-sample bias
    over raw mapper bins would false-positive without the
    equal-mass bin grouping); a mean-shifted stream must fire."""
    fp = booster._gbdt.drift_fingerprint()
    path = str(tmp_path / "drill.jsonl")
    obs = RunObserver(events_path=path)
    rng = np.random.default_rng(5)
    mon = DriftMonitor(fp, observer=obs, every_rows=256,
                       window_rows=1024, psi_threshold=0.2)
    for _ in range(50):
        Xh = rng.normal(size=(256, N_FEATURES))
        mon.observe_features(Xh)
        mon.observe_scores(booster.predict(Xh), raw=False)
    assert mon.alerts_fired == 0, "i.i.d. false positive: %r" % (
        mon.headline(),)
    for _ in range(4):
        mon.observe_features(rng.normal(loc=2.5, size=(256, N_FEATURES)))
    assert mon.alerting and mon.alerts_fired == 1
    mon.close()
    obs.close()
    evs = read_events(path)                    # schema-validates
    drifts = [e for e in evs if e["ev"] == "drift"]
    assert drifts and drifts[-1]["alert"] == "firing"
    assert [e for e in evs if e["ev"] == "health"
            and e.get("check") == "drift" and e["status"] == "warn"]
    for e in drifts:
        validate_event(e)


def test_input_anomaly_guard_counts_and_warns_once(booster, tmp_path):
    fp = booster._gbdt.drift_fingerprint()
    path = str(tmp_path / "anom.jsonl")
    obs = RunObserver(events_path=path)
    mon = DriftMonitor(fp, observer=obs, every_rows=10_000)
    X = np.zeros((8, N_FEATURES))
    X[0, 0] = np.nan
    X[1, 0] = np.inf
    X[2, 1] = 1e13                            # far outside any bin range
    mon.observe_features(X)
    mon.observe_features(X)           # second block: counts, no new warn
    fs = {f.name: f for f in mon._feats}
    assert fs["Column_0"].non_finite == 4
    assert fs["Column_1"].out_of_range == 2
    snap = REGISTRY.snapshot()
    assert any("lgbm_serve_input_anomalies_total" in k
               and "Column_0" in k and "non_finite" in k
               for k in snap), list(snap)
    obs.close()
    warns = [e for e in read_events(path) if e["ev"] == "health"
             and e.get("check") == "serve_input"]
    assert len(warns) == 2                    # once per affected feature
    flags = {w["detail"]["flag"] for w in warns}
    assert flags == {"non_finite", "out_of_range"}


def test_online_quality_from_delayed_labels(booster, tmp_path):
    fp = booster._gbdt.drift_fingerprint()
    path = str(tmp_path / "oq.jsonl")
    obs = RunObserver(events_path=path)
    mon = DriftMonitor(fp, observer=obs, every_rows=256,
                       window_rows=1024, min_labels=100)
    Xh, yh = _data(n=512, seed=9)
    mon.observe_features(Xh)
    probs = booster.predict(Xh)
    ids = list(range(512))
    mon.note_predictions(ids, probs)
    assert mon.record_outcome(ids, yh) == 512
    assert mon.record_outcome([999999], [1.0]) == 0   # unknown id
    mon.evaluate(force=True)
    mon.close()
    obs.close()
    oq = [e for e in read_events(path) if e["ev"] == "online_quality"]
    assert oq
    rec = oq[-1]
    validate_event(rec)
    assert rec["n"] == 512 and rec["auc"] > 0.9
    assert rec["ref_auc"] > 0.9 and rec["logloss"] > 0


def test_serving_predictor_wiring(booster, tmp_path):
    """submit() feeds the monitor, scores ride the future callback,
    serve_summary carries the drift digest, /statusz flight section
    appears, and record_outcome joins through the predictor."""
    path = str(tmp_path / "serve.jsonl")
    obs = RunObserver(events_path=path)
    with booster.serve(observer=obs, max_batch=256, max_delay_ms=1.0,
                       drift_every=256, drift_window=1024,
                       drift_min_labels=64) as sp:
        assert sp.drift is not None and sp.drift.enabled
        rng = np.random.default_rng(13)
        futs = []
        for i in range(4):
            Xb = rng.normal(loc=2.0, size=(256, N_FEATURES))
            futs.append(sp.submit(Xb, ids=list(range(i * 256,
                                                     (i + 1) * 256))))
        for f in futs:
            f.result(timeout=30)
        import time
        time.sleep(0.2)
        assert sp.record_outcome(list(range(100)),
                                 np.ones(100)) == 100
        from lightgbm_tpu.obs.live import WatchRenderer
        snap = obs.flight_context()
        assert "drift" in snap, list(snap)
        sbuf = io.StringIO()
        WatchRenderer(out=sbuf).render_status({"flight": snap})
        assert "drift psi" in sbuf.getvalue()
        stats = sp.stats()
    obs.close()
    assert stats["drift"]["alerts_fired"] >= 1
    evs = read_events(path)
    summ = [e for e in evs if e["ev"] == "serve_summary"][-1]
    assert summ["drift"]["alerts_fired"] >= 1
    assert [e for e in evs if e["ev"] == "drift"]


def test_booster_predict_hook(tmp_path):
    """Booster.predict on a fingerprinted model with obs_drift_every
    set monitors without a ServingPredictor in the loop."""
    X, y = _data(n=800, seed=3)
    path = str(tmp_path / "predict.jsonl")
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1, "obs_events_path": path,
                     "obs_drift_every": 256, "obs_drift_window": 1024},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    rng = np.random.default_rng(4)
    for _ in range(4):
        bst.predict(rng.normal(loc=3.0, size=(256, N_FEATURES)))
    mon = bst._drift_monitor
    assert mon is not None and mon.alerts_fired >= 1


# -------------------------------------------------------- reader & gates

def _drift_timeline(tmp_path, name, shifted):
    X, y = _data(n=800, seed=7)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    fp = bst._gbdt.drift_fingerprint()
    path = str(tmp_path / name)
    obs = RunObserver(events_path=path)
    mon = DriftMonitor(fp, observer=obs, every_rows=256,
                       window_rows=1024)
    rng = np.random.default_rng(8)
    for _ in range(4):
        mon.observe_features(
            rng.normal(loc=3.0 if shifted else 0.0,
                       size=(256, N_FEATURES)))
    mon.close()
    obs.close()
    return path


def test_obs_drift_cli_check_exit_codes(tmp_path):
    from lightgbm_tpu.obs.query import main as obs_main
    hot = _drift_timeline(tmp_path, "hot.jsonl", shifted=True)
    assert obs_main(["drift", hot, "--check"]) == 1
    cold = _drift_timeline(tmp_path, "cold.jsonl", shifted=False)
    assert obs_main(["drift", cold, "--check"]) in (0, None)
    # a timeline that never monitored must NOT pass as "no drift"
    empty = str(tmp_path / "empty.jsonl")
    obs = RunObserver(events_path=empty)
    obs.run_header(backend="cpu", devices=[], params={}, context={})
    obs.close()
    assert obs_main(["drift", empty, "--check"]) == 1


def test_drift_report_renders_diff_table(tmp_path):
    evs = read_events(_drift_timeline(tmp_path, "r.jsonl", shifted=True))
    m = drift_metrics(evs)
    assert m["present"] and m["psi_max"] > 0.2
    assert m["alerts"]["fired"] >= 1 and m["alerts"]["active"]
    buf = io.StringIO()
    problems = render_drift_report(evs, out=buf, check=True)
    txt = buf.getvalue()
    assert problems
    assert "features by divergence" in txt and "->" in txt
    assert "verdict: FAIL" in txt


def test_ledger_folds_drift_cells(tmp_path):
    from lightgbm_tpu.obs.ledger import METRIC_DIRECTIONS, \
        metrics_from_events
    evs = read_events(_drift_timeline(tmp_path, "l.jsonl", shifted=True))
    m = metrics_from_events(evs)
    assert m.get("drift_psi_max", 0) > 0.2
    assert METRIC_DIRECTIONS["drift_psi_max"] == -1


def test_watch_renders_drift_lines(tmp_path):
    from lightgbm_tpu.obs.live import WatchRenderer
    evs = read_events(_drift_timeline(tmp_path, "w.jsonl", shifted=True))
    buf = io.StringIO()
    r = WatchRenderer(out=buf)
    for e in evs:
        r.feed(e)
    assert "DRIFT[warn]" in buf.getvalue()
