"""LIVE cross-framework model compatibility, both directions.

tests/test_model_compat.py pins against COMMITTED reference model/pred
files; this suite goes further when a reference binary exists
($REF_LGBM or /tmp/refbuild/lightgbm, built unmodified from
/root/reference): models trained HERE are loaded and predicted by the
reference CLI, and models trained by the reference are loaded and
predicted here — predictions must agree.  The text model format is the
compatibility surface (GBDT::SaveModelToString, gbdt.cpp:817-861).

Skipped automatically when no binary is present (the CI image builds one
in round tooling; any user can `cmake && make` the reference).
"""
import os
import subprocess
import tempfile

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
GOLDEN = os.path.join(HERE, "data", "golden")
REF_BIN = os.environ.get("REF_LGBM", "/tmp/refbuild/lightgbm")

pytestmark = pytest.mark.skipif(
    not os.path.exists(REF_BIN),
    reason="no reference binary (set REF_LGBM or build /tmp/refbuild)")


def _ref(args, cwd):
    proc = subprocess.run([REF_BIN] + args, cwd=cwd,
                          capture_output=True, text=True)
    assert proc.returncode == 0, (
        "reference CLI failed (rc=%d):\n%s\n%s"
        % (proc.returncode, proc.stdout[-2000:], proc.stderr[-2000:]))


def _ours(args):
    from lightgbm_tpu import cli
    cli.main(args)


CONFIGS = {
    "binary": ["objective=binary", "num_trees=25", "num_leaves=15",
               "max_bin=63", "min_data_in_leaf=5"],
    "regression": ["objective=regression", "num_trees=25",
                   "num_leaves=15", "max_bin=63", "min_data_in_leaf=5"],
    "multiclass": ["objective=multiclass", "num_class=3", "num_trees=15",
                   "num_leaves=15", "max_bin=63", "min_data_in_leaf=5"],
}


@pytest.mark.parametrize("task", sorted(CONFIGS))
def test_our_model_predicts_identically_in_reference(task):
    train = os.path.join(GOLDEN, "%s.train" % task)
    test = os.path.join(GOLDEN, "%s.test" % task)
    with tempfile.TemporaryDirectory() as tmp:
        model = os.path.join(tmp, "m.txt")
        ours_pred = os.path.join(tmp, "ours.pred")
        ref_pred = os.path.join(tmp, "ref.pred")
        _ours(["task=train", "data=%s" % train, "output_model=%s" % model,
               "verbosity=-1"] + CONFIGS[task])
        _ours(["task=predict", "data=%s" % test, "input_model=%s" % model,
               "output_result=%s" % ours_pred, "verbosity=-1"])
        _ref(["task=predict", "data=%s" % test, "input_model=%s" % model,
              "output_result=%s" % ref_pred, "verbosity=-1"], tmp)
        a = np.loadtxt(ours_pred)
        b = np.loadtxt(ref_pred)
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-9)


@pytest.mark.parametrize("task", sorted(CONFIGS))
def test_reference_model_predicts_identically_here(task):
    train = os.path.join(GOLDEN, "%s.train" % task)
    test = os.path.join(GOLDEN, "%s.test" % task)
    with tempfile.TemporaryDirectory() as tmp:
        model = os.path.join(tmp, "m.txt")
        ours_pred = os.path.join(tmp, "ours.pred")
        ref_pred = os.path.join(tmp, "ref.pred")
        _ref(["task=train", "data=%s" % train, "output_model=%s" % model,
              "verbosity=-1"] + CONFIGS[task], tmp)
        _ref(["task=predict", "data=%s" % test, "input_model=%s" % model,
              "output_result=%s" % ref_pred, "verbosity=-1"], tmp)
        _ours(["task=predict", "data=%s" % test, "input_model=%s" % model,
               "output_result=%s" % ours_pred, "verbosity=-1"])
        a = np.loadtxt(ours_pred)
        b = np.loadtxt(ref_pred)
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-9)


def test_continued_training_across_frameworks():
    """Reference trains 10 trees -> we continue 10 more from its model
    file -> the combined model still loads and predicts in the
    reference (input_model continuation, boosting.cpp:43-62 /
    engine.py:92-98)."""
    train = os.path.join(GOLDEN, "binary.train")
    test = os.path.join(GOLDEN, "binary.test")
    base = [p for p in CONFIGS["binary"]
            if not p.startswith(("objective=", "num_trees="))]
    with tempfile.TemporaryDirectory() as tmp:
        m1 = os.path.join(tmp, "m1.txt")
        m2 = os.path.join(tmp, "m2.txt")
        ours_pred = os.path.join(tmp, "ours.pred")
        ref_pred = os.path.join(tmp, "ref.pred")
        _ref(["task=train", "data=%s" % train, "output_model=%s" % m1,
              "objective=binary", "num_trees=10", "verbosity=-1"] + base,
             tmp)
        _ours(["task=train", "data=%s" % train, "input_model=%s" % m1,
               "output_model=%s" % m2, "objective=binary", "num_trees=10",
               "verbosity=-1"] + base)
        _ours(["task=predict", "data=%s" % test, "input_model=%s" % m2,
               "output_result=%s" % ours_pred, "verbosity=-1"])
        _ref(["task=predict", "data=%s" % test, "input_model=%s" % m2,
              "output_result=%s" % ref_pred, "verbosity=-1"], tmp)
        a = np.loadtxt(ours_pred)
        b = np.loadtxt(ref_pred)
        assert len(a) == len(b)
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-9)
