"""R package validation (VERDICT r2 missing #3).

The build image has no R interpreter (and installs are prohibited), so
the heavyweight behavior tests live in the Python suite that the R
package delegates to via reticulate.  What executes HERE:

* structural validation of every R source file (delimiter balance with
  strings/comments stripped — a cheap parse-ish check),
* NAMESPACE <-> source consistency (every export is defined, every
  declared S3 method exists),
* coverage of the reference R API surface (R-package/NAMESPACE at the
  reference): each export must exist here by name,
* the end-to-end script (tests/smoke.R) must exercise the full surface
  and source every R file.

When an R interpreter with reticulate IS present (any user machine),
test_r_smoke_script_runs executes the real end-to-end flow — the same
gating the reference used for GPU tests on machines without OpenCL.
"""
import re
import shutil
import subprocess
from pathlib import Path

import pytest

R_DIR = Path(__file__).resolve().parent.parent / "R-package"
R_SOURCES = sorted((R_DIR / "R").glob("*.R"))

# the reference's exports (R-package/NAMESPACE at the reference); the
# agaricus.* entries there are datasets, not functions
REFERENCE_EXPORTS = [
    "getinfo", "lgb.Dataset", "lgb.Dataset.construct",
    "lgb.Dataset.create.valid", "lgb.Dataset.save",
    "lgb.Dataset.set.categorical", "lgb.Dataset.set.reference",
    "lgb.cv", "lgb.dump", "lgb.get.eval.result", "lgb.importance",
    "lgb.interprete", "lgb.load", "lgb.model.dt.tree",
    "lgb.plot.importance", "lgb.plot.interpretation", "lgb.prepare",
    "lgb.prepare2", "lgb.prepare_rules", "lgb.prepare_rules2",
    "lgb.save", "lgb.train", "lgb.unloader", "lightgbm",
    "readRDS.lgb.Booster", "saveRDS.lgb.Booster", "setinfo", "slice",
]
REFERENCE_S3 = [
    ("dim", "lgb.Dataset"), ("dimnames", "lgb.Dataset"),
    ("dimnames<-", "lgb.Dataset"), ("getinfo", "lgb.Dataset"),
    ("setinfo", "lgb.Dataset"), ("slice", "lgb.Dataset"),
    ("predict", "lgb.Booster"),
]


def _strip_r(text: str) -> str:
    """Remove comments and string literals so delimiter counts mean
    something."""
    out = []
    for line in text.splitlines():
        line = re.sub(r"'(\\.|[^'\\])*'", "''", line)
        line = re.sub(r'"(\\.|[^"\\])*"', '""', line)
        line = re.sub(r"#.*$", "", line)
        out.append(line)
    return "\n".join(out)


def _all_source_text() -> str:
    return "\n".join(p.read_text() for p in R_SOURCES)


def _defined_functions(text: str):
    names = set(re.findall(r"^\s*([A-Za-z][\w.]*)\s*<-\s*function\s*\(",
                           text, re.M))
    names |= set(re.findall(r"^\s*`([^`]+)`\s*<-\s*function\s*\(",
                            text, re.M))
    return names


def test_r_sources_exist_and_balanced():
    assert len(R_SOURCES) >= 12, [p.name for p in R_SOURCES]
    for f in R_SOURCES + [R_DIR / "tests" / "smoke.R"]:
        text = _strip_r(f.read_text())
        for op, cl in (("(", ")"), ("{", "}"), ("[", "]")):
            assert text.count(op) == text.count(cl), (
                "unbalanced %r in %s" % (op, f.name))


def test_namespace_matches_sources():
    ns = (R_DIR / "NAMESPACE").read_text()
    exports = re.findall(r"^export\(([^)]+)\)", ns, re.M)
    s3 = re.findall(r"^S3method\(([^)]+)\)", ns, re.M)
    defined = _defined_functions(_all_source_text())
    for e in exports:
        assert e in defined, "NAMESPACE exports undefined %s" % e
    for m in s3:
        generic, cls = [part.strip().strip('"') for part in m.split(",", 1)]
        assert ("%s.%s" % (generic, cls)) in defined, (
            "NAMESPACE S3method %s.%s undefined" % (generic, cls))


def test_reference_surface_covered():
    """Every function the reference R API exports must exist here."""
    ns = (R_DIR / "NAMESPACE").read_text()
    exports = set(re.findall(r"^export\(([^)]+)\)", ns, re.M))
    defined = _defined_functions(_all_source_text())
    for fn in REFERENCE_EXPORTS:
        assert fn in defined, "missing reference API function %s" % fn
        assert fn in exports, "reference API %s defined but not exported" % fn
    for generic, cls in REFERENCE_S3:
        assert ("%s.%s" % (generic, cls)) in defined, (
            "missing reference S3 method %s.%s" % (generic, cls))


def test_smoke_script_covers_surface():
    smoke = (R_DIR / "tests" / "smoke.R").read_text()
    for p in R_SOURCES:
        assert p.name in smoke, "smoke.R does not source %s" % p.name
    for fn in ("lgb.train", "lgb.cv", "lgb.save", "lgb.load",
               "saveRDS.lgb.Booster", "readRDS.lgb.Booster",
               "lgb.importance", "lgb.model.dt.tree", "lgb.interprete",
               "lgb.plot.importance", "slice", "getinfo", "setinfo",
               "lightgbm", "lgb.prepare_rules"):
        assert fn in smoke, "smoke.R does not exercise %s" % fn


def test_description_metadata():
    desc = (R_DIR / "DESCRIPTION").read_text()
    assert "Package: lightgbm.tpu" in desc
    assert "reticulate" in desc


@pytest.mark.skipif(shutil.which("Rscript") is None,
                    reason="no R interpreter in this image")
def test_r_smoke_script_runs():
    proc = subprocess.run(
        ["Rscript", str(R_DIR / "tests" / "smoke.R")],
        capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr
    assert "R smoke test OK" in proc.stdout


def test_python_call_surface_r_package_uses():
    """Exercise, from Python, the exact call patterns the R sources make
    through reticulate (kwargs, list-typed indices, evals_result dict,
    folds tuples) — so a kwarg rename or behavior change on the Python
    side fails THIS suite even without an R interpreter."""
    import numpy as np
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(0)
    n = 400
    x = rng.normal(size=(n, 4))
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float64)

    # lgb.Dataset(...) kwargs incl. list-typed categorical
    ds = lgb.Dataset(data=x, label=y, weight=None, group=None,
                     init_score=None, categorical_feature="auto",
                     reference=None, free_raw_data=True, params={})
    ds.construct()
    assert ds.num_data() == n and ds.num_feature() == 4
    # getinfo/setinfo field surface
    ds.set_field("weight", np.ones(n))
    assert len(ds.get_field("label")) == n
    # slice: 0-based list
    sub = ds.subset(list(range(100)))
    assert sub.construct().num_data() == 100
    # dimnames<-
    ds.set_feature_name(["f1", "f2", "f3", "f4"])

    # lgb.train(...) with evals_result dict + named valids
    xv = rng.normal(size=(120, 4))
    yv = (xv[:, 0] + 0.5 * xv[:, 1] > 0).astype(np.float64)
    valid = lgb.Dataset(xv, label=yv, reference=ds)
    evals = {}
    bst = lgb.train(params={"objective": "binary", "num_leaves": 7,
                            "metric": "binary_logloss", "verbose": -1},
                    train_set=ds, num_boost_round=5, valid_sets=[valid],
                    valid_names=["valid_0"], early_stopping_rounds=None,
                    init_model=None, evals_result=evals,
                    verbose_eval=False)
    assert len(evals["valid_0"]["binary_logloss"]) == 5
    assert isinstance(bst.best_iteration, int)

    # predict kwargs the R method uses
    p = bst.predict(x, num_iteration=-1, raw_score=False, pred_leaf=False)
    assert len(p) == n
    leaves = bst.predict(x, num_iteration=-1, raw_score=False,
                         pred_leaf=True)
    assert np.asarray(leaves).shape[0] == n

    # model io surface
    s = bst.model_to_string(num_iteration=-1)
    assert "Tree=" in s
    b2 = lgb.Booster(model_str=s)
    np.testing.assert_allclose(b2.predict(x), p, rtol=1e-12)
    d = bst.dump_model(num_iteration=-1)
    assert d["tree_info"] and "tree_structure" in d["tree_info"][0]

    # importance surface (gain + split by name)
    g = bst.feature_importance("gain")
    f = bst.feature_importance("split")
    assert len(g) == len(f) == len(bst.feature_name())

    # lgb.cv(...) folds as explicit (train, test) 0-based tuples
    folds = [(list(range(0, 300)), list(range(300, 400))),
             (list(range(100, 400)), list(range(0, 100)))]
    out = lgb.cv(params={"objective": "binary", "verbose": -1,
                         "metric": "binary_logloss"},
                 train_set=lgb.Dataset(x, label=y), num_boost_round=3,
                 nfold=2, stratified=False, folds=folds, metrics=None,
                 early_stopping_rounds=None, verbose_eval=False, seed=0)
    assert len(out["binary_logloss-mean"]) == 3


def test_interprete_walk_algorithm_matches_predict():
    """lgb.interprete.R walks the JSON dump root->leaf and attributes
    value deltas to features. This test runs the SAME algorithm (same
    decision strings, missing-range default_value redirect, NaN->right)
    in Python and checks the contributions reconstruct the booster's raw
    prediction exactly — validating the R logic without an interpreter."""
    import math

    import numpy as np
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(3)
    n = 500
    x = rng.normal(size=(n, 5))
    x[:, 2] = rng.integers(0, 4, size=n)      # categorical
    x[rng.random(n) < 0.2, 0] = 0.0           # zeros exercise the redirect
    y = ((x[:, 0] > 0.3) | (x[:, 2] == 2)).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1, "categorical_feature": [2],
                     "min_data_in_leaf": 5},
                    lgb.Dataset(x, label=y, categorical_feature=[2]),
                    num_boost_round=6, verbose_eval=False)
    dump = bst.dump_model()

    def walk_row(row):
        total = 0.0
        for t in dump["tree_info"]:
            node = t["tree_structure"]
            while "split_feature" in node:
                v = row[int(node["split_feature"])]
                if not math.isnan(v) and -1e-20 < v <= 1e-20:
                    v = float(node["default_value"])
                if node["decision_type"] == "is":
                    go_left = (not math.isnan(v)
                               and int(v) == int(node["threshold"]))
                else:
                    go_left = not math.isnan(v) and v <= node["threshold"]
                node = node["left_child"] if go_left else node["right_child"]
            total += float(node["leaf_value"])
        return total

    raw = bst.predict(x, raw_score=True)
    walked = np.array([walk_row(x[i]) for i in range(60)])
    np.testing.assert_allclose(walked, raw[:60], rtol=1e-9, atol=1e-9)


def test_dataset_get_feature_name_public():
    import numpy as np
    import lightgbm_tpu as lgb
    ds = lgb.Dataset(np.zeros((10, 3)) + np.arange(3),
                     label=np.zeros(10),
                     feature_name=["a", "b", "c"])
    assert ds.get_feature_name() == ["a", "b", "c"]
    ds2 = lgb.Dataset(np.random.default_rng(0).normal(size=(10, 2)),
                      label=np.zeros(10))
    assert ds2.get_feature_name() == ["Column_0", "Column_1"]


def test_interprete_multiclass_per_class_walks():
    """Multiclass models interleave num_class trees per iteration; the R
    interprete attributes deltas PER CLASS (tree_index %% num_class).
    Validate that algorithm reconstructs each class's raw score."""
    import numpy as np
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(1)
    n = 600
    x = rng.normal(size=(n, 4))
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(int) + (x[:, 2] > 0.8)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "verbose": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(x, label=y.astype(float)),
                    num_boost_round=4, verbose_eval=False)
    dump = bst.dump_model()
    k = dump["num_tree_per_iteration"]
    assert k == 3

    def walk_row_class(row, cls):
        total = 0.0
        for t in dump["tree_info"]:
            if t["tree_index"] % k != cls:
                continue
            node = t["tree_structure"]
            while "split_feature" in node:
                v = row[int(node["split_feature"])]
                if -1e-20 < v <= 1e-20:
                    v = float(node["default_value"])
                go_left = v <= node["threshold"]
                node = node["left_child"] if go_left else node["right_child"]
            total += float(node["leaf_value"])
        return total

    raw = bst.predict(x, raw_score=True)
    raw = np.asarray(raw).reshape(n, 3)
    for i in range(20):
        for cls in range(3):
            assert abs(walk_row_class(x[i], cls) - raw[i, cls]) < 1e-9
