"""Entry-chunk MXU sparse store (ops/sparse_mxu.py): build, histogram
kernel (interpret mode), partition column extraction, and the
tpu_sparse_kernel training plumbing.

Reference semantics matched: OrderedSparseBin's nonzero-only histogram
iteration (src/io/ordered_sparse_bin.hpp:26-209) with FixHistogram
fill-slot reconstruction (src/treelearner/feature_histogram.hpp:904-941).
"""
import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.sparse_mxu import (build_chunked_store,
                                         chunked_child_hists_ref,
                                         chunked_split_column,
                                         sparse_wave_histogram_mxu)


def _sparse_data(n=5000, f=13, b=14, L=12, seed=0, dense_col=3,
                 empty_col=7):
    rng = np.random.default_rng(seed)
    fill = rng.integers(0, b, size=f)
    X = np.tile(fill, (n, 1)).astype(np.uint8)
    nz = rng.random((n, f)) < 0.15
    X[nz] = rng.integers(0, b, size=int(nz.sum())).astype(np.uint8)
    if dense_col is not None:       # per-column skew: one dense column
        X[:, dense_col] = rng.integers(0, b, size=n).astype(np.uint8)
    if empty_col is not None:       # and one all-fill column
        X[:, empty_col] = fill[empty_col]
    leaf_id = rng.integers(0, L, size=n).astype(np.int32)
    w3 = rng.normal(size=(n, 3)).astype(np.float32)
    return X, fill, leaf_id, w3


def _dense_oracle(X, fill, leaf_id, w3, cid, b):
    """(K, F, B, 3) histogram with fill slots zeroed (the store never
    materializes fill entries; the view reconstructs them)."""
    k, f = len(cid), X.shape[1]
    out = np.zeros((k, f, b, 3))
    oh = np.stack([(X == bb) for bb in range(b)], axis=-1)  # (N, F, B)
    for kk, c in enumerate(cid):
        if c < 0:
            continue
        m = (leaf_id == c).astype(np.float64)
        out[kk] = np.einsum("nfb,nc->fbc", oh, w3 * m[:, None])
    for j in range(f):
        out[:, j, fill[j], :] = 0.0
    return out


def test_store_roundtrip():
    X, fill, _, _ = _sparse_data()
    store, cap, nbytes = build_chunked_store(X, fill, 14, entry_chunk=128,
                                             chunk_block=4)
    n, f = X.shape
    # reconstruct the dense matrix from the store
    dense = np.tile(fill, (n, 1)).astype(np.int64)
    rows = np.asarray(store.ent_row).reshape(-1)
    bins = np.asarray(store.ent_bin).reshape(-1)
    cols = np.repeat(np.asarray(store.chunk_col)[:, 0], 128)
    ok = rows < n
    dense[rows[ok], cols[ok]] = bins[ok]
    np.testing.assert_array_equal(dense, X.astype(np.int64))
    assert store.ent_bin.shape[0] % 4 == 0
    assert cap >= 1 and nbytes > 0


@pytest.mark.parametrize("entry_chunk", [128, 256])
def test_segment_oracle_matches_dense(entry_chunk):
    b, L = 14, 12
    X, fill, leaf_id, w3 = _sparse_data(b=b, L=L)
    store, cap, _ = build_chunked_store(X, fill, b,
                                        entry_chunk=entry_chunk)
    cid = np.array([0, 2, 4, -1, 7], np.int32)
    got = chunked_child_hists_ref(store, jnp.asarray(leaf_id),
                                  jnp.asarray(w3), jnp.asarray(cid), b,
                                  X.shape[1], L)
    want = _dense_oracle(X, fill, leaf_id, w3, cid, b)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                               atol=1e-5)


def test_kernel_interpret_matches_dense():
    b, L = 14, 12
    X, fill, leaf_id, w3 = _sparse_data(b=b, L=L)
    store, cap, _ = build_chunked_store(X, fill, b, entry_chunk=128,
                                        chunk_block=4)
    cid = np.array([0, 2, 4, -1, 7], np.int32)
    got = sparse_wave_histogram_mxu(store, jnp.asarray(leaf_id),
                                    jnp.asarray(w3), jnp.asarray(cid), b,
                                    X.shape[1], interpret=True)
    want = _dense_oracle(X, fill, leaf_id, w3, cid, b)
    np.testing.assert_allclose(np.asarray(got), want, rtol=5e-4,
                               atol=5e-4)


def test_auto_uniform_layout_matches_dense():
    """auto_uniform widens the entry chunk so low-skew columns become
    ONE chunk each (one MXU dot per column, r5); same store contract,
    same histograms, and the skewed case must refuse the layout."""
    b, L = 14, 12
    # low-skew data (no dense column): uniform layout engages
    X, fill, leaf_id, w3 = _sparse_data(b=b, L=L, dense_col=None)
    store, cap, _ = build_chunked_store(X, fill, b, entry_chunk=128,
                                        auto_uniform=True)
    assert cap == 1                      # every column fits one chunk
    cid = np.array([0, 2, 4, -1, 7], np.int32)
    got = sparse_wave_histogram_mxu(store, jnp.asarray(leaf_id),
                                    jnp.asarray(w3), jnp.asarray(cid),
                                    b, X.shape[1], interpret=True)
    want = _dense_oracle(X, fill, leaf_id, w3, cid, b)
    np.testing.assert_allclose(np.asarray(got), want, rtol=5e-4,
                               atol=5e-4)
    # the split-column window still reconstructs every column exactly
    for j in (0, 5, X.shape[1] - 1):
        col = chunked_split_column(store, j, X.shape[0], cap)
        np.testing.assert_array_equal(np.asarray(col),
                                      X[:, j].astype(np.int64))
    # skew gate: one dense column would blow the uniform layout up ->
    # the narrow-chunk layout must be kept
    Xs, fills, _, _ = _sparse_data(b=b, L=L, dense_col=3)
    s2, cap2, _ = build_chunked_store(Xs, fills, b, entry_chunk=128,
                                      auto_uniform=True)
    assert cap2 > 1
    assert s2.ent_bin.shape[1] == 128    # base chunk width kept
    # all-fill columns cost zero chunks in either layout and must not
    # be charged against the uniform gate: 1 busy + many constant
    # columns still widens
    Xc = np.tile(fills, (2000, 1)).astype(np.uint8)
    rng = np.random.default_rng(5)
    busy = rng.integers(0, b, size=2000).astype(np.uint8)
    Xc[:, 1] = busy
    s3, cap3, _ = build_chunked_store(Xc, fills, b, entry_chunk=128,
                                      auto_uniform=True)
    assert cap3 == 1
    assert s3.ent_bin.shape[1] >= 1920   # widened to the busy column
    # absolute VMEM ceiling: a low-skew store whose columns exceed
    # 16384 entries must keep the narrow chunks
    Xb = rng.integers(0, b - 1, size=(20_000, 2)).astype(np.uint8)
    fb = np.full(2, b - 1)
    s4, cap4, _ = build_chunked_store(Xb, fb, b, entry_chunk=128,
                                      auto_uniform=True)
    assert s4.ent_bin.shape[1] == 128
    assert cap4 > 1


def test_kernel_pregathered_weights_identical():
    """entry_weights (the per-tree hoisted gathers, r5) must be exactly
    the in-call gather — same kernel inputs, bit-identical output."""
    from lightgbm_tpu.ops.sparse_mxu import gather_entry_weights
    b, L = 14, 12
    X, fill, leaf_id, w3 = _sparse_data(b=b, L=L)
    store, cap, _ = build_chunked_store(X, fill, b, entry_chunk=128,
                                        chunk_block=4)
    cid = np.array([0, 2, 4, -1, 7], np.int32)
    base = sparse_wave_histogram_mxu(store, jnp.asarray(leaf_id),
                                     jnp.asarray(w3), jnp.asarray(cid),
                                     b, X.shape[1], interpret=True)
    ew = gather_entry_weights(store, jnp.asarray(w3))
    got = sparse_wave_histogram_mxu(store, jnp.asarray(leaf_id),
                                    jnp.asarray(w3), jnp.asarray(cid),
                                    b, X.shape[1], interpret=True,
                                    entry_weights=ew)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(base))
    # the narrow leaf-id gather (uint8 at <=256 leaves) is exact too
    got8 = sparse_wave_histogram_mxu(store, jnp.asarray(leaf_id),
                                     jnp.asarray(w3), jnp.asarray(cid),
                                     b, X.shape[1], interpret=True,
                                     entry_weights=ew, num_leaves=L)
    np.testing.assert_array_equal(np.asarray(got8), np.asarray(base))
    # and the uint16 branch (257..65536 leaves), with ids above 255 so
    # a uint8-wrap bug could not hide
    lid_hi = leaf_id.astype(np.int32) + 300
    cid_hi = np.where(cid >= 0, cid + 300, cid).astype(np.int32)
    base16 = sparse_wave_histogram_mxu(store, jnp.asarray(lid_hi),
                                       jnp.asarray(w3),
                                       jnp.asarray(cid_hi), b,
                                       X.shape[1], interpret=True)
    got16 = sparse_wave_histogram_mxu(store, jnp.asarray(lid_hi),
                                      jnp.asarray(w3),
                                      jnp.asarray(cid_hi), b,
                                      X.shape[1], interpret=True,
                                      entry_weights=ew, num_leaves=512)
    np.testing.assert_array_equal(np.asarray(got16), np.asarray(base16))


def test_kernel_nondefault_chunk_block():
    """A store padded to a chunk_block that is NOT a multiple of the
    kernel's CHUNK_BLOCK still runs (the grid step divides nc exactly)."""
    b, L = 14, 12
    X, fill, leaf_id, w3 = _sparse_data(n=1200, f=9, b=b, L=L, seed=3)
    store, cap, _ = build_chunked_store(X, fill, b, entry_chunk=128,
                                        chunk_block=1)
    nc = store.ent_bin.shape[0]
    cid = np.array([1, 3, -1], np.int32)
    got = sparse_wave_histogram_mxu(store, jnp.asarray(leaf_id),
                                    jnp.asarray(w3), jnp.asarray(cid), b,
                                    X.shape[1], interpret=True)
    want = _dense_oracle(X, fill, leaf_id, w3, cid, b)
    np.testing.assert_allclose(np.asarray(got), want, rtol=5e-4,
                               atol=5e-4)


def test_kernel_root_slot_call():
    """The wave engine's root call: slot 0 = leaf 0, other slots -1."""
    b, L = 10, 8
    X, fill, leaf_id, w3 = _sparse_data(n=2000, f=5, b=b, L=1,
                                        dense_col=None, empty_col=None)
    leaf_id[:] = 0
    store, cap, _ = build_chunked_store(X, fill, b, entry_chunk=128)
    cid = np.full(4, -1, np.int32)
    cid[0] = 0
    got = sparse_wave_histogram_mxu(store, jnp.asarray(leaf_id),
                                    jnp.asarray(w3), jnp.asarray(cid), b,
                                    X.shape[1], interpret=True)
    want = _dense_oracle(X, fill, leaf_id, w3, cid, b)
    np.testing.assert_allclose(np.asarray(got), want, rtol=5e-4,
                               atol=5e-4)
    assert np.all(np.asarray(got)[1:] == 0.0)


def test_split_column_extraction():
    b = 14
    X, fill, _, _ = _sparse_data(b=b)
    store, cap, _ = build_chunked_store(X, fill, b, entry_chunk=128)
    n, f = X.shape
    for j in [0, 3, 7, f - 1]:
        col = chunked_split_column(store, jnp.asarray(j), n, cap)
        np.testing.assert_array_equal(np.asarray(col),
                                      X[:, j].astype(np.int32))


def test_train_sparse_kernel_matches_sparse():
    """tpu_sparse_kernel=true trains through the chunked store (CPU
    fallback = the segment oracle) and matches plain tpu_sparse wave
    growth tree for tree."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(5)
    n = 3000
    X = np.where(rng.random((n, 12)) < 0.1,
                 rng.normal(size=(n, 12)), 0.0)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
    base = {"objective": "binary", "num_leaves": 15, "verbose": -1,
            "min_data_in_leaf": 5, "tpu_sparse": True}
    pk = dict(base, tpu_sparse_kernel=True)
    pw = dict(base, tpu_growth="wave")
    bk = lgb.train(pk, lgb.Dataset(X, label=y, params=pk),
                   num_boost_round=5)
    bw = lgb.train(pw, lgb.Dataset(X, label=y, params=pw),
                   num_boost_round=5)
    assert bk._gbdt.learner.growth == "wave"
    assert bk._gbdt.learner.hist_mode == "sparse_mxu"
    np.testing.assert_allclose(bk.predict(X), bw.predict(X), rtol=1e-5,
                               atol=1e-6)


def test_sparse_kernel_reset_parameters():
    """reset_config under the chunked store: the reuse path must accept
    a ChunkedSparseStore (gbdt.reset_config) and keep training."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(7)
    n = 1500
    X = np.where(rng.random((n, 8)) < 0.12, rng.normal(size=(n, 8)), 0.0)
    y = (X[:, 0] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              "tpu_sparse": True, "tpu_sparse_kernel": True}
    # a non-learning_rate key: learning_rate-only resets take the
    # shrinkage fast path and never reach gbdt.reset_config
    bst = lgb.train(
        params, lgb.Dataset(X, label=y, params=params),
        num_boost_round=4,
        callbacks=[lgb.reset_parameter(
            lambda_l2=lambda i: 0.01 * (i + 1))])
    assert bst._gbdt.learner.hist_mode == "sparse_mxu"
    assert bst.predict(X).shape == (n,)


def test_sparse_kernel_dart_tree_ops():
    """DART's drop/rescale path calls _apply_tree_to_train, which must
    take the raw-data fallback for the chunked store (not slice the
    NamedTuple as a dense matrix)."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(9)
    n = 1200
    X = np.where(rng.random((n, 8)) < 0.12, rng.normal(size=(n, 8)), 0.0)
    y = (X[:, 0] > 0).astype(np.float64)
    params = {"objective": "binary", "boosting": "dart", "num_leaves": 7,
              "verbose": -1, "drop_rate": 0.9, "tpu_sparse": True,
              "tpu_sparse_kernel": True}
    bst = lgb.train(params, lgb.Dataset(X, label=y, params=params),
                    num_boost_round=5)
    assert bst.predict(X).shape == (n,)


def test_sparse_kernel_exact_growth_rejected():
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.log import LightGBMError

    rng = np.random.default_rng(6)
    X = rng.normal(size=(500, 5))
    y = (X[:, 0] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              "tpu_sparse": True, "tpu_sparse_kernel": True,
              "tpu_growth": "exact"}
    with pytest.raises(LightGBMError):
        lgb.train(params, lgb.Dataset(X, label=y, params=params),
                  num_boost_round=1)
