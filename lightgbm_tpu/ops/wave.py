"""Wave growth — best-first tree construction batched for the MXU.

The reference grows leaf-wise, one split at a time, histogramming only the
smaller child's rows (serial_tree_learner.cpp:168-223).  That economics
relies on cheap random access; on TPU, random gather/scatter runs orders of
magnitude below the streaming/matmul roofline (measured ~μs/row via XLA
gather on v5e), so per-leaf row gathers lose to full passes.

The TPU-native schedule instead splits the top-W pending leaves per WAVE:

* ONE streaming partition pass moves every affected row (each row looks up
  its leaf's chosen split in an (L,K) table via a one-hot contraction — no
  gathers);
* ONE batched histogram pass computes ALL W smaller-child histograms:
  per row chunk, the bin one-hot (C, F*B) is contracted against per-child
  masked weights (C, 3W) on the MXU.  The one-hot construction (the VPU
  cost) is paid once per wave instead of once per split, and the
  contraction rides the MXU at ~25-50x the VPU rate — this is where a
  255-leaf tree's 254 histogram scans collapse.
* larger children come from parent subtraction (feature_histogram.hpp:63)
  against the same per-leaf cache the leaf-wise grower uses, and the packed
  best-split search (split_finder.py) vmaps over all 2W children.

wave_width=1 reproduces the reference's leaf-wise order EXACTLY (top-1 ==
argmax, identical node numbering to ops/grow.py).  Larger waves split the
top-W by gain simultaneously — the same greedy frontier, batched; tree
quality matches leaf-wise to benchmark noise (see tests/test_wave.py) while
training time per tree drops from O(num_leaves) full passes to
O(num_leaves / W) passes plus MXU time.

Under a data mesh the two passes are shard-local and the wave's histogram
block is psum'd ONCE per wave — W× less collective latency than per-split
reductions (data_parallel_tree_learner.cpp:148-222 analog).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .grow import TreeArrays, feature_hist_view, pvary_for
from .histogram import leaf_histogram_onehot, leaf_histogram_scatter
from .split_finder import (DEFAULT_BIN_FOR_ZERO, FEATURE, GAIN, IS_CAT,
                           LEFT_COUNT, LEFT_OUTPUT, LEFT_SUM_G, LEFT_SUM_H,
                           RIGHT_COUNT, RIGHT_OUTPUT, RIGHT_SUM_G,
                           RIGHT_SUM_H, SECOND_FEATURE, SECOND_GAIN,
                           SPLIT_VEC_SIZE, THRESHOLD, FeatureMeta,
                           SplitParams, best_splits_vmapped)

# modes implemented only as wave-schedule Pallas kernels; every
# engine/learner gate imports THIS tuple so adding a kernel variant is a
# one-line change.  Lives here (not pallas_wave.py) so CPU-only installs
# never import jax.experimental.pallas just to validate a config.
WAVE_ONLY_MODES = ("pallas_t", "pallas_ct")


def _bin_pad(num_bins: int) -> int:
    """Padded per-feature bin width so F*Bp stays lane-friendly (shared
    policy of the Pallas wave kernels and the auto-mode VMEM gate)."""
    if num_bins <= 64:
        return 64
    return ((num_bins + 127) // 128) * 128


def hist_block_bytes(ncols: int, bin_pad: int, width: int) -> int:
    """Bytes of the (ncols*bin_pad, 3W) f32 accumulator block the wave
    kernels keep resident in VMEM — the single geometry fact behind the
    auto-mode VMEM gate, the accumulator-aware tile planner
    (ops/pallas_wave.py _tile_plan), and the autotuner's cell
    enumeration (ops/autotune.py)."""
    return ncols * bin_pad * 12 * width


def _slot_hist(ohf, match, wc, W, hist_dtype, exact_order):
    """One wave chunk's histogram contraction: (C, q) one-hot x per-child
    masked weights -> (q, 3W).  Under exact order the contraction runs
    per candidate slot in tpu_wave_width=1's operand shapes — one wide
    GEMM's reduction order varies with the (C, 3W) width and would drift
    from the pinned leaf-wise baseline by ulps.  ONE copy shared by
    wave_pass and rehist so the bit-equality-critical layout cannot
    diverge."""
    c = ohf.shape[0]
    if exact_order:
        parts = [jnp.einsum("cq,cw->qw", ohf, match[:, w:w + 1] * wc,
                            preferred_element_type=hist_dtype)
                 for w in range(W)]
        return jnp.concatenate(parts, axis=1)
    wmat = (match[:, :, None] * wc[:, None, :]).reshape(c, 3 * W)
    return jnp.einsum("cq,cw->qw", ohf, wmat,
                      preferred_element_type=hist_dtype)


def pallas_wave_active(hist_mode: str, hist_dtype=jnp.float32) -> bool:
    """True when a Pallas wave kernel will ACTUALLY run: TPU backend, f32
    accumulation (the kernels are single-dtype), and a pallas mode.  The
    single copy of this predicate — the engine gate, the serial learner's
    Xt precompute, and the mesh learner's Xt precompute all import it."""
    return (jax.default_backend() == "tpu"
            and hist_dtype == jnp.float32
            and hist_mode in ("pallas",) + WAVE_ONLY_MODES)


def transposed_wave_active(hist_mode: str, hist_dtype=jnp.float32) -> bool:
    """True when the running kernel is one of the TRANSPOSED layouts —
    i.e. a per-booster (F, N) Xt is worth materializing."""
    return (hist_mode in ("pallas_t", "pallas_ct")
            and pallas_wave_active(hist_mode, hist_dtype))



def make_wave_grow_fn(num_leaves: int, num_bins: int, meta: FeatureMeta,
                      params: SplitParams, max_depth: int,
                      wave_width: int = 16, hist_dtype=jnp.float32,
                      psum_axis: str = None, bundle=None,
                      group_bins: int = 0, cache_hists: bool = True,
                      hist_mode: str = "onehot", chunk: int = 16384,
                      packed_cols: int = 0, sparse_col_cap: int = 0,
                      with_xt: bool = False, exact_order: bool = False,
                      lookup: str = "onehot", hist_hilo: bool = True,
                      compact: bool = False,
                      pallas_interpret: bool = False):
    """Bind meta/bundle onto the cached wave-grow program (same contract as
    ops/grow.make_grow_fn: grow(X, grad, hess, row_mult, feature_mask) ->
    (TreeArrays, leaf_id)).

    with_xt=True: the returned grow takes a SIXTH positional arg — the
    precomputed transposed bin matrix for the transposed Pallas kernels —
    so shard_map callers can pass a per-booster Xt instead of paying one
    (F, N) materialization per tree dispatch (the serial learner's
    keyword path, learner.py)."""
    core = make_wave_core(num_leaves, num_bins, params, max_depth,
                          wave_width, hist_dtype, psum_axis,
                          bundle is not None, group_bins, cache_hists,
                          hist_mode, chunk, packed_cols, sparse_col_cap,
                          exact_order, lookup, hist_hilo, compact,
                          pallas_interpret)

    if with_xt:
        def grow(X, grad, hess, row_mult, feature_mask, Xt):
            return core(X, grad, hess, row_mult, feature_mask, meta,
                        bundle, Xt=Xt)
    else:
        def grow(X, grad, hess, row_mult, feature_mask):
            return core(X, grad, hess, row_mult, feature_mask, meta, bundle)

    grow.core = core
    return grow


@functools.lru_cache(maxsize=64)
def make_wave_jit(*static_args):
    """jit(make_wave_core(...)) cached on the static key so repeated
    boosters / cv folds reuse one compiled executable (the wave analog of
    grow.make_grow_jit)."""
    return jax.jit(make_wave_core(*static_args))


@functools.lru_cache(maxsize=64)
def make_wave_core(num_leaves: int, num_bins: int, params: SplitParams,
                   max_depth: int, wave_width: int, hist_dtype,
                   psum_axis: str, has_bundle: bool, group_bins: int,
                   cache_hists: bool, hist_mode: str, chunk: int,
                   packed_cols: int = 0, sparse_col_cap: int = 0,
                   exact_order: bool = False, lookup: str = "onehot",
                   hist_hilo: bool = True, compact: bool = False,
                   pallas_interpret: bool = False):
    """packed_cols > 0: X is 4-bit packed (ops/pack.py, two columns per
    byte) and packed_cols is the LOGICAL column count; every chunk is
    unpacked in-scan so the full-width matrix never hits HBM (the
    dense_nbits_bin.hpp:37 bandwidth halving, TPU form).

    hist_mode == 'sparse': X is a SparseDeviceStore (ops/sparse_store.py)
    and sparse_col_cap its per-column entry bound.  The wave then pays
    O(nnz) per W splits instead of per split: the partition reads only
    the W chosen split columns (materialized from the store), and ALL W
    smaller-child histograms come from ONE segment_sum over the nonzero
    entries with segment id ``slot*(F*B) + col*B + bin``."""
    L = num_leaves
    W = max(1, min(wave_width, L - 1))
    chunk = max(int(chunk), 256)      # guard tpu_wave_chunk<=0 etc.
    hist_bins = group_bins if has_bundle else num_bins
    sparse_mode = hist_mode in ("sparse", "sparse_mxu")
    # 'sparse_mxu': X is a ChunkedSparseStore (ops/sparse_mxu.py) and
    # sparse_col_cap its per-column CHUNK bound; child histograms come
    # from the entry-chunk MXU kernel on TPU (segment_sum oracle form
    # elsewhere) instead of a segment_sum over the coordinate store
    mxu_sparse = hist_mode == "sparse_mxu"
    if sparse_mode and packed_cols:
        raise ValueError("tpu_sparse and 4-bit packing are exclusive")
    # the bin one-hot holds only 0/1 — exact in bf16 — and is the dominant
    # HBM traffic of the wave pass; on TPU the MXU also multiplies bf16
    # natively.  Weights and the accumulator stay in hist_dtype.
    oh_dtype = (jnp.bfloat16
                if jax.default_backend() == "tpu"
                and hist_dtype == jnp.float32 else hist_dtype)
    # fused Pallas kernels (ops/pallas_wave.py): generate the one-hot in
    # VMEM instead of materializing (chunk, F*B) blocks through HBM.
    # Opt-in (hist_mode='pallas' row-major / 'pallas_t' transposed) while
    # their end-to-end win is validated; precision is handled by the bf16
    # hi/lo weight split (manual rounding — Mosaic's cast truncates).
    # pallas_interpret=True (tests only) runs the Pallas kernels in
    # interpret mode on any backend, so the ct engine path — including
    # spectator-row compaction — is CPU-testable end-to-end
    use_pallas_hist = pallas_wave_active(hist_mode, hist_dtype) or (
        pallas_interpret and hist_dtype == jnp.float32
        and hist_mode in ("pallas",) + WAVE_ONLY_MODES)
    # 'pallas_ct' (v5) is fused (partition + histogram in one kernel,
    # ONE read of Xt per wave) and transposed; the earlier fused
    # variants pallas_f/pallas_ft were deleted after losing every
    # on-chip A/B to pallas_t (tools/AB_RESULTS.md, BENCH_NOTES.md r4)
    pallas_transposed = hist_mode in ("pallas_t", "pallas_ct")
    pallas_fused = hist_mode == "pallas_ct"
    # spectator-row compaction rides the transposed kernels (the fused
    # ct tier calls the fused kernel; the t tier runs a vectorized
    # partition over the gathered slab then the t kernel), and only
    # under serial execution (per-shard divergent tier choices inside
    # shard_map would be legal — no collectives in the branches — but
    # have no measurement yet)
    compact = bool(compact and pallas_transposed and use_pallas_hist
                   and psum_axis is None)

    def maybe_psum(x):
        if psum_axis is not None:
            return lax.psum(x, psum_axis)
        return x

    def to_feature_hist(ghist, sums, meta, bundle):
        return feature_hist_view(ghist, sums, meta, bundle, has_bundle,
                                 fix_default=sparse_mode)

    # scatter-add serializes on TPU (~226ms vs onehot's 7.2ms at 1Mx28,
    # B=63) — only the explicit 'scatter' mode should pay it; the pallas
    # modes keep the fast one-hot root (once per tree, before the kernel
    # takes over the per-wave work)
    root_hist_fn = (leaf_histogram_scatter if hist_mode == "scatter"
                    else leaf_histogram_onehot)

    def grow(X, grad, hess, row_mult, feature_mask, meta, bundle, Xt=None):
        n = grad.shape[0]       # X may be a SparseDeviceStore pytree
        if sparse_mode:
            Fc = Fdev = X.fill.shape[0]
        else:
            Fc = packed_cols or X.shape[1]    # LOGICAL group columns
            Fdev = X.shape[1]                 # stored (packed: half)
        if packed_cols:
            from .pack import unpack4
            unpack = lambda xc: unpack4(xc, Fc)  # noqa: E731
        else:
            unpack = lambda xc: xc               # noqa: E731
        grad = grad.astype(hist_dtype)
        hess = hess.astype(hist_dtype)
        row_mult = row_mult.astype(hist_dtype)
        w3 = jnp.stack([grad * row_mult, hess * row_mult, row_mult],
                       axis=-1)           # (N, 3) per-row weight channels
        leaf_id = jnp.zeros(n, dtype=jnp.int32)
        if psum_axis is not None:
            leaf_id = pvary_for(leaf_id, psum_axis)

        c = min(chunk, max(n, 1))
        pad = (-n) % c
        nch = (n + pad) // c
        if not sparse_mode:
            Xp = jnp.pad(X, ((0, pad), (0, 0))) if pad else X
            xb = Xp.reshape(nch, c, Fdev)
        # transposed matrix for the v2 kernel (MXU-native dot orientation):
        # callers that hold X for many trees pass a precomputed Xt (the
        # learner materializes it once per booster); otherwise fall back to
        # one (F, N) materialization per tree dispatch
        if use_pallas_hist and pallas_transposed and Xt is None:
            Xt = jnp.transpose(X)

        # ---- sparse (coordinate-store) variants: partition reads ONLY
        # the W chosen split columns; all W child histograms are ONE
        # segment_sum over the nonzeros
        # the three O(nnz) weight-channel gathers are tree-constant —
        # hoisted to ONE gather per tree (gather_entry_weights); only
        # the leaf-id gather stays per-wave
        if mxu_sparse and (jax.default_backend() == "tpu"
                           and hist_dtype == jnp.float32):
            from .sparse_mxu import gather_entry_weights
            mxu_entry_w = gather_entry_weights(X, w3)
        else:
            mxu_entry_w = None

        def sparse_child_hists(lid, ids, valid):
            if mxu_sparse:
                from .sparse_mxu import (chunked_child_hists_ref,
                                         sparse_wave_histogram_mxu)
                cid = jnp.where(valid, ids, -1)
                if (jax.default_backend() == "tpu"
                        and hist_dtype == jnp.float32):
                    return sparse_wave_histogram_mxu(
                        X, lid, w3, cid, hist_bins, Fc, hilo=hist_hilo,
                        entry_weights=mxu_entry_w, num_leaves=L)
                return chunked_child_hists_ref(
                    X, lid, w3, cid, hist_bins, Fc, L)
            slot_tbl = jnp.full(L, -1, jnp.int32).at[
                jnp.where(valid, ids, L)].set(
                    jnp.arange(W, dtype=jnp.int32), mode="drop")
            leaf_nz = jnp.take(lid, X.nz_row)
            slot = jnp.take(slot_tbl, leaf_nz)             # (nnz,)
            wnz = jnp.take(w3, X.nz_row, axis=0)           # (nnz, 3)
            # the sharded store pads sections with nz_seg == Fc*B (one
            # past the histogram); the slot offset must not relocate
            # those pads into the NEXT slot's valid range
            real = (slot >= 0) & (X.nz_seg < Fc * hist_bins)
            seg = jnp.where(real,
                            slot * (Fc * hist_bins) + X.nz_seg,
                            W * Fc * hist_bins)            # drop
            flat = jax.ops.segment_sum(
                wnz, seg, num_segments=W * Fc * hist_bins)
            return flat.reshape(W, Fc, hist_bins, 3)

        def route_rows(r, colv, lc):
            """Split routing shared by the dense chunk scan and the
            sparse pass: bundle remap, threshold compare, default-bin
            redirect, right-child move (dense_bin.hpp:190-222)."""
            if has_bundle:
                goff = r[:, 7].astype(jnp.int32)
                in_range = ((colv >= goff)
                            & (colv < goff + r[:, 9].astype(jnp.int32)))
                colv = jnp.where(in_range,
                                 colv - goff + r[:, 8].astype(jnp.int32),
                                 r[:, 4].astype(jnp.int32))
            thr_r = r[:, 2].astype(jnp.int32)
            gl = jnp.where(r[:, 3] > 0.5, colv == thr_r, colv <= thr_r)
            gl = jnp.where(colv == r[:, 4].astype(jnp.int32),
                           r[:, 5] > 0.5, gl)
            active = r[:, 0] > 0.5
            return jnp.where(active & ~gl, r[:, 6].astype(jnp.int32), lc)

        def sparse_wave_pass(lid, tbl, small_id, valid, col_ids):
            if mxu_sparse:
                from .sparse_mxu import chunked_split_column as _colfn
            else:
                from .sparse_store import sparse_split_column as _colfn
            r = jnp.take(tbl, lid, axis=0)                 # (N, 10)
            cj = r[:, 1].astype(jnp.int32)
            colv = jnp.zeros(n, jnp.int32)
            for w in range(W):                             # static W
                vals = _colfn(X, col_ids[w], n, sparse_col_cap)
                colv = jnp.where(cj == col_ids[w], vals, colv)
            new_lid = route_rows(r, colv, lid)
            return new_lid, sparse_child_hists(new_lid, small_id, valid)

        def pallas_hist(lid, cid):
            """Dispatch to the fused kernel in the configured layout —
            the single call site for both wave_pass and rehist."""
            if pallas_transposed:
                from .pallas_wave import wave_histogram_pallas_t
                return wave_histogram_pallas_t(Xt, lid, w3, cid, hist_bins,
                                               logical_cols=packed_cols,
                                               hilo=hist_hilo,
                                               interpret=pallas_interpret)
            from .pallas_wave import wave_histogram_pallas
            return wave_histogram_pallas(X, lid, w3, cid, hist_bins,
                                         logical_cols=packed_cols,
                                         hilo=hist_hilo,
                                         interpret=pallas_interpret)

        def wave_pass(leaf_id, tbl, cols, psrc, small_id, valid):
            """Partition + child histograms, fused into ONE chunked sweep.

            Per chunk: rows look up their leaf's split row in the split
            table (`lookup` strategy below), route left/right (the
            partition), then the chunk's bin one-hot (C, Fc*B) is contracted
            against per-child masked weights (C, 3W) on the MXU.  Nothing
            N x L or N x W is ever materialized.  Shard-local; callers psum
            the histogram block.

            Lookup strategies for the per-row split row `r` (C, 10):
            - 'onehot': (C, L) leaf one-hot @ (L, 10) table on the MXU —
              exact f32, but the one-hot costs L*4 bytes/row of traffic.
            - 'compact': each row matches at most ONE of the W wave
              parents (splits are disjoint), so r is a masked sum over
              the (W, 10) rows — W/L of the one-hot footprint and the
              sum has <=1 nonzero term (exact in any order).
            - 'gather': r = tbl[leaf_id] — the form the sparse pass
              already uses; XLA's TPU gather economics decide.

            On TPU the histogram half runs as the Pallas kernel (one-hot
            generated in VMEM, ops/pallas_wave.py) and the scan below
            only partitions; 'pallas_ct' fuses BOTH halves into one
            kernel — a single read of Xt per wave.
            """
            if use_pallas_hist and pallas_fused:
                from .pallas_wave import wave_partition_hist_pallas_ct
                return wave_partition_hist_pallas_ct(
                    Xt, leaf_id, w3,
                    jnp.where(valid, small_id, -1), cols, psrc,
                    hist_bins, bundled=has_bundle,
                    logical_cols=packed_cols, hilo=hist_hilo,
                    interpret=pallas_interpret)
            lb = jnp.pad(leaf_id, (0, pad)).reshape(nch, c) if pad \
                else leaf_id.reshape(nch, c)
            wpad = jnp.pad(w3, ((0, pad), (0, 0))) if pad else w3
            wb3 = wpad.reshape(nch, c, 3)
            l_iota = jnp.arange(L, dtype=jnp.int32)
            f_iota = jnp.arange(Fc, dtype=jnp.int32)

            def step(acc, args):
                xc, lc, wc = args                   # (C,Fdev) (C,) (C,3)
                xc = unpack(xc)                     # (C, Fc) logical bins
                if lookup == "compact":
                    # <=1 match per row, so the sum is exact and XLA can
                    # fuse the (C, W, 10) broadcast into the reduction —
                    # no (C, L) one-hot ever exists
                    pm = lc[:, None] == psrc[None, :]          # (C, W)
                    r = jnp.sum(
                        jnp.where(pm[:, :, None], cols[None, :, :], 0.0),
                        axis=1)                     # (C, 10)
                elif lookup == "gather":
                    r = jnp.take(tbl, jnp.clip(lc, 0, L - 1), axis=0)
                else:
                    leaf_oh = (lc[:, None] == l_iota[None, :]).astype(
                        jnp.float32)                # (C, L)
                    # HIGHEST: TPU's default matmul precision is bf16,
                    # which rounds integer table entries above 256 (column
                    # ids, thresholds, leaf ids) — the lookup must be
                    # exact f32
                    r = jnp.matmul(leaf_oh, tbl,
                                   precision=lax.Precision.HIGHEST)
                cj = r[:, 1].astype(jnp.int32)
                colv = jnp.sum(
                    jnp.where(cj[:, None] == f_iota[None, :], xc, 0)
                    .astype(jnp.int32), axis=1)     # (C,) split-column bin
                lc2 = route_rows(r, colv, lc)
                if not use_pallas_hist:
                    # child-masked weights: (C, W) match x (C, 3) channels
                    match = ((lc2[:, None] == small_id[None, :])
                             & valid[None, :]).astype(hist_dtype)
                    oh = jax.nn.one_hot(xc.astype(jnp.int32), hist_bins,
                                        dtype=oh_dtype)      # (C, Fc, B)
                    acc = acc + _slot_hist(
                        oh.reshape(c, Fc * hist_bins), match, wc, W,
                        hist_dtype, exact_order)
                return acc, lc2

            acc_shape = ((Fc * hist_bins, 3 * W) if not use_pallas_hist
                         else (1, 1))
            init = jnp.zeros(acc_shape, dtype=hist_dtype)
            if nch == 1:
                flat, lid2 = step(init, (xb[0], lb[0], wb3[0]))
                new_leaf_id = lid2[:n]
            else:
                flat, lid2 = lax.scan(step, init, (xb, lb, wb3))
                new_leaf_id = lid2.reshape(-1)[:n]
            if use_pallas_hist:
                hist = pallas_hist(new_leaf_id,
                                   jnp.where(valid, small_id, -1))
            else:
                # (Fc*B, W*3) -> (W, Fc, B, 3)
                hist = flat.reshape(Fc, hist_bins, W, 3).transpose(2, 0, 1,
                                                                   3)
            return new_leaf_id, hist

        # ---- spectator-row compaction (tpu_wave_compact): capacity
        # tiers at 1/2, 1/4, 1/8 of N, 512-aligned, ascending.  Late
        # waves split leaves holding a shrinking fraction of rows
        # (measured frontier occupancy at 300k x 28/255 leaves: waves 7+
        # touch 17-49% of rows — ~35% of ALL kernel row work is rows
        # whose leaf is final, ROADMAP r4), the same economics as the
        # reference's leaf-ordered bin iteration
        # (ordered_sparse_bin.hpp:26-209): touch only the rows of the
        # leaves being split.
        compact_caps = []
        if compact and not sparse_mode:
            for frac in (2, 4, 8):
                cap = -(-min(n, max(1024, -(-n // frac))) // 512) * 512
                if cap < n and cap not in compact_caps:
                    compact_caps.append(cap)
            compact_caps.sort()

        def compact_wave_pass(leaf_id, tbl, cols, psrc, small_id, valid):
            """Fused wave pass over the ACTIVE rows only (leaf in the
            wave's parent set), gathered into the smallest tier that
            holds them; full-N fallback when none does.

            Exactness: a spectator row matches no parent (routes
            nowhere) and no child (zero histogram weight), so routing
            and SPLIT STRUCTURE are identical to the full-N pass.
            Histogram sums are identical under strictly sequential f32
            accumulation (adding 0.0 anywhere is the identity) — but
            compaction shifts active rows across kernel row-tile
            boundaries, so reductions that pair per-tile partial sums
            non-sequentially reassociate and float fields (gains, leaf
            values) can drift by f32 ulps.  Pinned in
            tests/test_wave_compact.py: bit-equal trees at single-tile
            N, equal structure + ~1e-5-close floats at multi-tile N.
            Cost per wave: one (L,)-table membership gather, a
            stable-compact index build (cumsum), and the row gathers —
            against kernel row work shrinking from N to the tier."""
            from .pallas_wave import wave_partition_hist_pallas_ct
            act_tbl = jnp.zeros(L, bool).at[
                jnp.where(valid, psrc, L)].set(True, mode="drop")
            mask = jnp.take(act_tbl, leaf_id)            # (N,)
            active_n = jnp.sum(mask.astype(jnp.int32))   # TRUE row count
            cid = jnp.where(valid, small_id, -1)

            def tier(cap):
                def run():
                    idx = jnp.nonzero(mask, size=cap, fill_value=n)[0]
                    # fill semantics mirror the kernel's own padding:
                    # leaf -2 matches nothing, weight 0 adds nothing
                    xt_c = jnp.take(Xt, idx, axis=1, mode="fill",
                                    fill_value=0)
                    lid_c = jnp.take(leaf_id, idx, mode="fill",
                                     fill_value=-2)
                    w3_c = jnp.take(w3, idx, axis=0, mode="fill",
                                    fill_value=0.0)
                    if pallas_fused:
                        new_c, hist = wave_partition_hist_pallas_ct(
                            xt_c, lid_c, w3_c, cid, cols, psrc,
                            hist_bins, bundled=has_bundle,
                            logical_cols=packed_cols, hilo=hist_hilo,
                            interpret=pallas_interpret)
                    else:
                        # pallas_t tier: the partition over the
                        # gathered slab is ONE masked reduction — the
                        # compact (W, 10) lookup per row, the split
                        # column from a (Fc, cap) masked sum over Xt_c
                        # (unpacked in place when 4-bit), then the
                        # shared routing algebra — followed by the t
                        # histogram kernel on the updated ids
                        from .pallas_wave import (_unpack4_t,
                                                  wave_histogram_pallas_t)
                        pm = lid_c[None, :] == psrc[:, None]   # (W,cap)
                        r = jnp.sum(
                            jnp.where(pm[:, :, None], cols[:, None, :],
                                      0.0), axis=0)            # (cap,10)
                        xi = xt_c.astype(jnp.int32)
                        if packed_cols:
                            xi = _unpack4_t(xi, Fc)
                        cj = r[:, 1].astype(jnp.int32)
                        f_io = jnp.arange(Fc, dtype=jnp.int32)
                        colv = jnp.sum(
                            jnp.where(cj[None, :] == f_io[:, None],
                                      xi, 0), axis=0)          # (cap,)
                        new_c = route_rows(r, colv, lid_c)
                        hist = wave_histogram_pallas_t(
                            xt_c, new_c, w3_c, cid, hist_bins,
                            logical_cols=packed_cols, hilo=hist_hilo,
                            interpret=pallas_interpret)
                    return (leaf_id.at[idx].set(new_c, mode="drop"),
                            hist)
                return run

            def ladder(caps):
                if not caps:
                    return wave_pass(leaf_id, tbl, cols, psrc, small_id,
                                     valid)
                return lax.cond(active_n <= caps[0], tier(caps[0]),
                                lambda: ladder(caps[1:]))
            return ladder(compact_caps)

        def rehist(leaf_id, ids, valid):
            """Histograms of `ids` children only (no partition) — the
            no-cache larger-child pass."""
            if use_pallas_hist:
                return pallas_hist(leaf_id, jnp.where(valid, ids, -1))
            lb = jnp.pad(leaf_id, (0, pad)).reshape(nch, c) if pad \
                else leaf_id.reshape(nch, c)
            wpad = jnp.pad(w3, ((0, pad), (0, 0))) if pad else w3
            wb3 = wpad.reshape(nch, c, 3)

            def step(acc, args):
                xc, lc, wc = args
                xc = unpack(xc)
                match = ((lc[:, None] == ids[None, :])
                         & valid[None, :]).astype(hist_dtype)
                oh = jax.nn.one_hot(xc.astype(jnp.int32), hist_bins,
                                    dtype=oh_dtype)
                acc = acc + _slot_hist(
                    oh.reshape(c, Fc * hist_bins), match, wc, W,
                    hist_dtype, exact_order)
                return acc, None

            init = jnp.zeros((Fc * hist_bins, 3 * W), dtype=hist_dtype)
            if nch == 1:
                flat, _ = step(init, (xb[0], lb[0], wb3[0]))
            else:
                flat, _ = lax.scan(step, init, (xb, lb, wb3))
            return flat.reshape(Fc, hist_bins, W, 3).transpose(2, 0, 1, 3)

        def best_of_many(hists_k, sums_k, depths_k, feature_mask, meta,
                         bundle):
            """vmapped packed best-split search over K children — the
            shared split_finder helper with the EFB/default-bin view
            applied inside the vmap."""
            return best_splits_vmapped(
                hists_k, sums_k, depths_k, meta, feature_mask, params,
                max_depth,
                hist_view=lambda h, s: to_feature_hist(h, s, meta, bundle))

        # ---- root
        root_sums = maybe_psum(jnp.sum(w3, axis=0))
        if mxu_sparse:
            # root histogram through the same kernel call shape as the
            # wave passes (one compiled executable): slot 0 targets the
            # root, the other W-1 slots are inactive
            hist0 = maybe_psum(sparse_child_hists(
                leaf_id, jnp.zeros(W, jnp.int32),
                jnp.arange(W) == 0)[0])
        elif sparse_mode:
            from .sparse_store import leaf_histogram_sparse
            hist0 = maybe_psum(leaf_histogram_sparse(
                X, grad, hess, leaf_id, 0, row_mult, hist_bins, Fc))
        else:
            root_kw = ({"chunk": chunk}
                       if root_hist_fn is leaf_histogram_onehot else {})
            hist0 = maybe_psum(root_hist_fn(
                X, grad, hess, leaf_id, 0, row_mult, num_bins=hist_bins,
                logical_cols=packed_cols, **root_kw))
        Fh, B = hist0.shape[0], hist0.shape[1]
        if cache_hists:
            hists = jnp.zeros((L, Fh, B, 3), hist_dtype).at[0].set(hist0)
        else:
            hists = jnp.zeros((0,), hist_dtype)
        bests = jnp.full((L, SPLIT_VEC_SIZE), -jnp.inf, dtype=hist_dtype)
        bests = bests.at[0].set(best_of_many(
            hist0[None], root_sums[None], jnp.zeros(1, jnp.int32),
            feature_mask, meta, bundle)[0])
        sums = jnp.zeros((L, 3), hist_dtype).at[0].set(root_sums)
        tree = TreeArrays(
            num_leaves=jnp.asarray(1, jnp.int32),
            split_feature=jnp.zeros(L - 1, jnp.int32),
            threshold_bin=jnp.zeros(L - 1, jnp.int32),
            default_bin_for_zero=jnp.zeros(L - 1, jnp.int32),
            default_bin=jnp.zeros(L - 1, jnp.int32),
            is_cat=jnp.zeros(L - 1, jnp.int32),
            left_child=jnp.zeros(L - 1, jnp.int32),
            right_child=jnp.zeros(L - 1, jnp.int32),
            split_gain=jnp.zeros(L - 1, hist_dtype),
            internal_value=jnp.zeros(L - 1, hist_dtype),
            internal_count=jnp.zeros(L - 1, jnp.int32),
            leaf_parent=jnp.full(L, -1, jnp.int32),
            leaf_value=jnp.zeros(L, hist_dtype),
            leaf_count=jnp.zeros(L, jnp.int32).at[0].set(
                root_sums[2].astype(jnp.int32)),
            leaf_depth=jnp.zeros(L, jnp.int32),
            second_feature=jnp.full(L - 1, -1, jnp.int32),
            second_gain=jnp.zeros(L - 1, hist_dtype),
        )

        def cond(carry):
            nn, done = carry[0], carry[1]
            return (nn < L - 1) & ~done

        def body(carry):
            nn, done, leaf_id, hists, bests, sums, tree = carry
            gains = bests[:, GAIN]
            budget = (L - 1) - nn
            gw, lw = lax.top_k(gains, W)
            rank = jnp.arange(W, dtype=jnp.int32)
            valid = (gw > 0.0) & (rank < budget)
            k = jnp.sum(valid.astype(jnp.int32))
            parent = lw.astype(jnp.int32)          # distinct leaf ids
            info = bests[parent]                   # (W, V)
            node = nn + rank                       # internal node ids
            newleaf = node + 1                     # right-child leaf ids

            f_w = info[:, FEATURE].astype(jnp.int32)
            thr_w = info[:, THRESHOLD].astype(jnp.int32)
            dbz_w = info[:, DEFAULT_BIN_FOR_ZERO].astype(jnp.int32)
            cat_w = info[:, IS_CAT] > 0.5
            fdef_w = meta.default_bin[f_w]
            dleft_w = jnp.where(cat_w, dbz_w == thr_w, dbz_w <= thr_w)

            # ---- per-leaf split tables, fused into one (L, K) f32 matrix
            # (all entries < 2^24, exact in f32) looked up per row by a
            # one-hot contraction — no row gathers anywhere
            src = jnp.where(valid, parent, L)      # L -> dropped
            if has_bundle:
                col_w = bundle.group_of[f_w]
                goff_w = bundle.bin_off[f_w]
                adj_w = bundle.bin_adj[f_w]
                span_w = bundle.bin_span[f_w]
            else:
                col_w = f_w
                goff_w = jnp.zeros(W, jnp.int32)
                adj_w = jnp.zeros(W, jnp.int32)
                span_w = jnp.full(W, num_bins, jnp.int32)
            cols = jnp.stack([
                jnp.ones(W, jnp.float32),                  # 0: active
                col_w.astype(jnp.float32),                 # 1: device column
                thr_w.astype(jnp.float32),                 # 2: threshold bin
                cat_w.astype(jnp.float32),                 # 3: categorical
                fdef_w.astype(jnp.float32),                # 4: default bin
                dleft_w.astype(jnp.float32),               # 5: default left
                newleaf.astype(jnp.float32),               # 6: right leaf id
                goff_w.astype(jnp.float32),                # 7: group offset
                adj_w.astype(jnp.float32),                 # 8: bin adjust
                span_w.astype(jnp.float32),                # 9: bin span
            ], axis=-1)                                    # (W, 10)
            tbl = jnp.zeros((L, 10), jnp.float32).at[src].set(
                cols, mode="drop")
            # compact-lookup operands: the W parent ids (invalid slots
            # get -3, which no real/padded leaf id ever equals) and the
            # raw (W, 10) rows — invalid rows can hold garbage, they
            # never match
            psrc = jnp.where(valid, parent, -3)

            # ---- fused partition + children histograms (one sweep)
            left_small = info[:, LEFT_COUNT] < info[:, RIGHT_COUNT]
            small_id = jnp.where(left_small, parent, newleaf)
            large_id = jnp.where(left_small, newleaf, parent)
            if sparse_mode:
                leaf_id, hist_small = sparse_wave_pass(
                    leaf_id, tbl, small_id, valid, col_w)
            elif compact_caps:
                leaf_id, hist_small = compact_wave_pass(
                    leaf_id, tbl, cols, psrc, small_id, valid)
            else:
                leaf_id, hist_small = wave_pass(leaf_id, tbl, cols, psrc,
                                                small_id, valid)
            hist_small = maybe_psum(hist_small)             # (W, F, B, 3)
            if cache_hists:
                hist_large = hists[parent] - hist_small
            else:
                hist_large = maybe_psum(
                    sparse_child_hists(leaf_id, large_id, valid)
                    if sparse_mode else rehist(leaf_id, large_id, valid))

            left_sums = jnp.stack([info[:, LEFT_SUM_G], info[:, LEFT_SUM_H],
                                   info[:, LEFT_COUNT]], axis=-1)
            right_sums = jnp.stack([info[:, RIGHT_SUM_G],
                                    info[:, RIGHT_SUM_H],
                                    info[:, RIGHT_COUNT]], axis=-1)
            small_sums = jnp.where(left_small[:, None], left_sums,
                                   right_sums)
            large_sums = jnp.where(left_small[:, None], right_sums,
                                   left_sums)

            # ---- vectorized split search for all 2W children
            depth = tree.leaf_depth[parent] + 1             # (W,)
            hists_k = jnp.concatenate([hist_small, hist_large])
            sums_k = jnp.concatenate([small_sums, large_sums])
            depths_k = jnp.concatenate([depth, depth])
            bests_k = best_of_many(hists_k, sums_k, depths_k, feature_mask,
                                   meta, bundle)            # (2W, V)

            if exact_order:
                # ---- EXACT leaf-wise order: the candidates were ranked
                # by pre-wave gain, so leaf-wise would commit them in rank
                # order UNTIL a child created earlier in the wave outranks
                # the next candidate (the reference would split that child
                # next, serial_tree_learner.cpp:203).  Commit exactly that
                # prefix; roll the rest back below.  Histograms are
                # reduction-order-identical across wave widths, so trees
                # match tpu_wave_width=1 (the pinned leaf-wise order)
                # bit-for-bit (the per-candidate contractions below
                # keep reductions W=1-shaped) — tests/test_wave_exact_order.py.
                sg, lg = bests_k[:W, GAIN], bests_k[W:, GAIN]
                cg = jnp.maximum(sg, lg)
                cg = jnp.where(valid, cg, -jnp.inf)
                # leaf id attaining each candidate's child max (ties ->
                # smaller id, matching top_k's first-occurrence pick)
                cid = jnp.where(
                    (sg > lg) | ((sg == lg) & (small_id <= large_id)),
                    small_id, large_id)
                # running (max gain, smallest id attaining it) over the
                # committed prefix — W=1's top_k breaks exact gain ties
                # by LOWEST LEAF ID, so the stop rule must too
                def pairmax(a, b):
                    ga, ia = a
                    gb, ib = b
                    take_a = (ga > gb) | ((ga == gb) & (ia <= ib))
                    return (jnp.where(take_a, ga, gb),
                            jnp.where(take_a, ia, ib))
                run, rid = lax.associative_scan(pairmax, (cg, cid))
                mx = jnp.concatenate([jnp.full((1,), -jnp.inf, cg.dtype),
                                      run[:-1]])              # before t
                mid = jnp.concatenate([jnp.zeros((1,), cid.dtype),
                                       rid[:-1]])
                stop = (mx > gw) | ((mx == gw) & (mid < parent))  # (W,)
                t_idx = jnp.where(jnp.any(stop),
                                  jnp.argmax(stop).astype(jnp.int32),
                                  jnp.asarray(W, jnp.int32))
                kc = jnp.minimum(t_idx, k)
                commit = rank < kc
                # rollback: rows provisionally routed to an uncommitted
                # right child return to the parent — ONE (L,)-table gather
                # over leaf ids, no pass over X
                undo = valid & ~commit
                remap = jnp.arange(L, dtype=jnp.int32).at[
                    jnp.where(undo, newleaf, L)].set(parent, mode="drop")
                leaf_id = jnp.take(remap, leaf_id)
            else:
                commit, kc = valid, k

            if cache_hists:
                hsrc = jnp.where(commit, small_id, L)
                hists = hists.at[hsrc].set(hist_small, mode="drop")
                lsrc = jnp.where(commit, large_id, L)
                hists = hists.at[lsrc].set(hist_large, mode="drop")
            ssrc = jnp.where(commit, small_id, L)
            lsrc2 = jnp.where(commit, large_id, L)
            bests = bests.at[ssrc].set(bests_k[:W], mode="drop")
            bests = bests.at[lsrc2].set(bests_k[W:], mode="drop")
            sums = sums.at[ssrc].set(small_sums, mode="drop")
            sums = sums.at[lsrc2].set(large_sums, mode="drop")

            # ---- tree bookkeeping, vectorized over the wave
            nsrc = jnp.where(commit, node, L - 1 + 64)      # drop sentinel
            tparent = tree.leaf_parent[parent]              # (W,)
            # grandparent child-pointer fix: each split's (parent node,
            # side) slot is unique, so the W scatters cannot collide
            gp = jnp.maximum(tparent, 0)
            was_left = tree.left_child[gp] == ~parent
            fix = commit & (tparent >= 0)
            lc = tree.left_child.at[jnp.where(fix & was_left, gp, L + 63)
                                    ].set(node, mode="drop")
            rc = tree.right_child.at[jnp.where(fix & ~was_left, gp, L + 63)
                                     ].set(node, mode="drop")
            lc = lc.at[nsrc].set(~parent, mode="drop")
            rc = rc.at[nsrc].set(~newleaf, mode="drop")
            lsrc3 = jnp.where(commit, parent, L)
            rsrc3 = jnp.where(commit, newleaf, L)
            tree = tree._replace(
                num_leaves=tree.num_leaves + kc,
                split_feature=tree.split_feature.at[nsrc].set(
                    f_w, mode="drop"),
                threshold_bin=tree.threshold_bin.at[nsrc].set(
                    thr_w, mode="drop"),
                default_bin_for_zero=tree.default_bin_for_zero.at[nsrc].set(
                    dbz_w, mode="drop"),
                default_bin=tree.default_bin.at[nsrc].set(
                    fdef_w, mode="drop"),
                is_cat=tree.is_cat.at[nsrc].set(
                    cat_w.astype(jnp.int32), mode="drop"),
                left_child=lc,
                right_child=rc,
                split_gain=tree.split_gain.at[nsrc].set(
                    info[:, GAIN], mode="drop"),
                internal_value=tree.internal_value.at[nsrc].set(
                    tree.leaf_value[parent], mode="drop"),
                internal_count=tree.internal_count.at[nsrc].set(
                    (info[:, LEFT_COUNT]
                     + info[:, RIGHT_COUNT]).astype(jnp.int32),
                    mode="drop"),
                leaf_parent=tree.leaf_parent.at[lsrc3].set(
                    node, mode="drop").at[rsrc3].set(node, mode="drop"),
                leaf_value=tree.leaf_value.at[lsrc3].set(
                    info[:, LEFT_OUTPUT], mode="drop").at[rsrc3].set(
                        info[:, RIGHT_OUTPUT], mode="drop"),
                leaf_count=tree.leaf_count.at[lsrc3].set(
                    info[:, LEFT_COUNT].astype(jnp.int32),
                    mode="drop").at[rsrc3].set(
                        info[:, RIGHT_COUNT].astype(jnp.int32), mode="drop"),
                leaf_depth=tree.leaf_depth.at[lsrc3].set(
                    depth, mode="drop").at[rsrc3].set(depth, mode="drop"),
                second_feature=tree.second_feature.at[nsrc].set(
                    info[:, SECOND_FEATURE].astype(jnp.int32), mode="drop"),
                second_gain=tree.second_gain.at[nsrc].set(
                    jnp.where(jnp.isfinite(info[:, SECOND_GAIN]),
                              info[:, SECOND_GAIN], 0.0), mode="drop"),
            )
            return (nn + kc, kc == 0, leaf_id, hists, bests, sums, tree)

        carry = (jnp.asarray(0, jnp.int32), jnp.asarray(False), leaf_id,
                 hists, bests, sums, tree)
        carry = lax.while_loop(cond, body, carry)
        return carry[-1], carry[2]

    return grow
