"""Streaming two-round text ingest == in-memory ingest, bit for bit.

The streaming loader (io/streaming.py) must reproduce the in-memory
path's dataset exactly — same Random sample indices drive BinMapper
construction, and GreedyFindBin is row-order independent — while touching
only one chunk of text at a time (dataset_loader.cpp:554-660 semantics).
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.io.streaming import count_rows, stream_supported
from lightgbm_tpu.utils.config import Config


@pytest.fixture(scope="module")
def tsv_file(tmp_path_factory):
    rng = np.random.default_rng(13)
    n, f = 5000, 7
    X = rng.normal(size=(n, f))
    X[rng.random((n, f)) > 0.9] = 0.0            # some zeros
    y = (X[:, 0] + X[:, 1] > 0).astype(np.int64)
    path = tmp_path_factory.mktemp("stream") / "data.tsv"
    with open(path, "w") as fh:
        for i in range(n):
            fh.write("\t".join([str(y[i])] + ["%.17g" % v for v in X[i]]))
            fh.write("\n")
    return str(path), X, y


def test_count_and_detect(tsv_file):
    path, X, y = tsv_file
    assert count_rows(path, skip_header=False) == len(y)
    assert stream_supported(path, has_header=False)


@pytest.mark.parametrize("efb", [False, True])
def test_streaming_matches_in_memory(tsv_file, efb):
    path, X, y = tsv_file
    cfg_mem = Config({"max_bin": 63, "verbose": -1, "enable_bundle": efb})
    cfg_str = Config({"max_bin": 63, "verbose": -1, "enable_bundle": efb,
                      "use_two_round_loading": True})
    td_mem = TrainingData.from_file(path, cfg_mem)
    td_str = TrainingData.from_file(path, cfg_str)
    assert td_str.num_data == td_mem.num_data
    assert td_str.used_feature_idx == td_mem.used_feature_idx
    np.testing.assert_array_equal(td_str.num_bin_arr, td_mem.num_bin_arr)
    assert (td_str.bundle is None) == (td_mem.bundle is None)
    np.testing.assert_array_equal(td_str.binned, td_mem.binned)
    np.testing.assert_array_equal(np.asarray(td_str.metadata.label),
                                  np.asarray(td_mem.metadata.label))


def test_streaming_valid_alignment(tsv_file):
    path, X, y = tsv_file
    cfg = Config({"max_bin": 63, "verbose": -1,
                  "use_two_round_loading": True})
    train = TrainingData.from_file(path, cfg)
    valid = TrainingData.from_file(path, cfg, reference=train)
    np.testing.assert_array_equal(valid.binned, train.binned)


def test_streaming_train_end_to_end(tsv_file):
    path, X, y = tsv_file
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
              "verbose": -1, "use_two_round_loading": True}
    ds = lgb.Dataset(path, params=params)
    bst = lgb.train(params, ds, num_boost_round=8)
    p = bst.predict(X)
    order = np.argsort(p)
    ranks = np.empty(len(p)); ranks[order] = np.arange(1, len(p) + 1)
    npos = y.sum(); auc = (ranks[y > 0].sum() - npos * (npos + 1) / 2) / (
        npos * (len(y) - npos))
    assert auc > 0.9


def test_streaming_with_header_and_ignore(tmp_path):
    rng = np.random.default_rng(3)
    n = 2000
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] > 0).astype(np.int64)
    path = tmp_path / "h.csv"
    with open(path, "w") as fh:
        fh.write("target,a,b,junk,c\n")
        for i in range(n):
            fh.write("%d,%.17g,%.17g,%.17g,%.17g\n"
                     % (y[i], X[i, 0], X[i, 1], X[i, 2], X[i, 3]))
    cfg = dict(max_bin=63, verbose=-1, header=True,
               label_column="name:target", ignore_column="name:junk",
               use_two_round_loading=True)
    td_str = TrainingData.from_file(str(path), Config(dict(cfg)))
    cfg.pop("use_two_round_loading")
    td_mem = TrainingData.from_file(str(path), Config(cfg))
    np.testing.assert_array_equal(td_str.binned, td_mem.binned)
    assert td_str.feature_names == td_mem.feature_names


def test_streaming_blank_lines(tmp_path):
    path = tmp_path / "blanks.csv"
    with open(path, "w") as fh:
        fh.write("1,0.5,1.5\n\n0,2.5,0.25\n   \n1,0.75,3.5\n\n")
    assert count_rows(str(path), skip_header=False) == 3
    cfg = Config({"max_bin": 15, "verbose": -1, "min_data_in_leaf": 1,
                  "use_two_round_loading": True, "min_data_in_bin": 1})
    td = TrainingData.from_file(str(path), cfg)
    assert td.num_data == 3
    cfg2 = Config({"max_bin": 15, "verbose": -1, "min_data_in_leaf": 1,
                   "min_data_in_bin": 1})
    td2 = TrainingData.from_file(str(path), cfg2)
    np.testing.assert_array_equal(td.binned, td2.binned)
    np.testing.assert_array_equal(np.asarray(td.metadata.label),
                                  np.asarray(td2.metadata.label))

# --- out-of-core two-pass pipeline (PR 9): sketch merge, parallel
# --- workers, chunk boundaries, missing values, sparse sources


def test_sketch_merge_order_independent():
    """Shuffled chunk order + split/merged sketches reassemble the exact
    bytes of ``data[sample_idx]`` — the invariant that makes streamed
    BinMapper fitting bit-identical to the one-shot path."""
    import random as pyrandom

    from lightgbm_tpu.io.streaming import SampleSketch

    rng = np.random.default_rng(5)
    data = rng.normal(size=(1000, 5))
    idx = sorted(pyrandom.Random(1).sample(range(1000), 200))
    bounds = [0, 137, 400, 401, 999, 1000]       # odd, incl. 1-row chunk
    chunks = []
    for s, e in zip(bounds[:-1], bounds[1:]):
        sel = [i - s for i in idx if s <= i < e]
        chunks.append((s, data[s:e][sel]))
    sk_a, sk_b = SampleSketch(5), SampleSketch(5)
    for j in (3, 0, 4):
        sk_a.add_chunk(*chunks[j])
    for j in (1, 2):
        sk_b.add_chunk(*chunks[j])
    sk_a.merge(sk_b)
    np.testing.assert_array_equal(sk_a.sample_matrix(), data[idx])


@pytest.mark.parametrize("workers", [1, 2])
def test_from_streamed_matrix_parity(workers):
    """Streamed matrix construction (serial and through the fork pool)
    == from_matrix bit for bit, NaNs included."""
    rng = np.random.default_rng(11)
    X = rng.normal(size=(3000, 6))
    X[rng.random(X.shape) > 0.95] = np.nan       # missing values
    y = (np.nan_to_num(X[:, 0]) > 0).astype(np.float64)
    td_mem = TrainingData.from_matrix(
        X, y, Config({"max_bin": 31, "verbose": -1}))
    td_str = TrainingData.from_streamed(
        X, y, Config({"max_bin": 31, "verbose": -1,
                      "ooc_workers": workers}), chunk_rows=777)
    np.testing.assert_array_equal(td_str.num_bin_arr, td_mem.num_bin_arr)
    np.testing.assert_array_equal(td_str.binned, td_mem.binned)
    np.testing.assert_array_equal(np.asarray(td_str.metadata.label),
                                  np.asarray(td_mem.metadata.label))
    st = td_str._construct_stats
    assert st["source"] == "stream:matrix" and st["rows"] == 3000
    assert st["chunks"] == 4 and st["workers"] >= 1


@pytest.mark.parametrize("chunk_rows", [1, 3, 199, 200, 500])
def test_streamed_chunk_boundaries(chunk_rows):
    """Chunk size spanning the degenerate edges — 1-row chunks, a chunk
    boundary exactly at n, and a single chunk bigger than the data."""
    rng = np.random.default_rng(17)
    X = rng.normal(size=(200, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    cfg = {"max_bin": 15, "verbose": -1, "min_data_in_bin": 1,
           "min_data_in_leaf": 1}
    td_mem = TrainingData.from_matrix(X, y, Config(dict(cfg)))
    td_str = TrainingData.from_streamed(X, y, Config(dict(cfg)),
                                        chunk_rows=chunk_rows)
    np.testing.assert_array_equal(td_str.binned, td_mem.binned)
    assert td_str._construct_stats["chunks"] == -(-200 // chunk_rows)


def test_streamed_missing_token_text(tmp_path):
    """'na' tokens in a text file take the streamed and in-memory loaders
    through the same missing-value handling."""
    rng = np.random.default_rng(29)
    n = 1500
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] > 0).astype(np.int64)
    miss = rng.random((n, 3)) > 0.9
    path = tmp_path / "miss.csv"
    with open(path, "w") as fh:
        for i in range(n):
            cells = ["na" if miss[i, j] else "%.17g" % X[i, j]
                     for j in range(3)]
            fh.write("%d,%s\n" % (y[i], ",".join(cells)))
    cfg = {"max_bin": 31, "verbose": -1, "use_missing": True}
    td_mem = TrainingData.from_file(str(path), Config(dict(cfg)))
    td_str = TrainingData.from_file(
        str(path), Config(dict(cfg, use_two_round_loading=True,
                               ooc_chunk_rows=256)))
    np.testing.assert_array_equal(td_str.binned, td_mem.binned)
    np.testing.assert_array_equal(np.asarray(td_str.metadata.label),
                                  np.asarray(td_mem.metadata.label))


@pytest.mark.parametrize("workers", [1, 2])
def test_streamed_sparse_parity(workers):
    """SparseSource densifies one chunk at a time; result must match the
    all-at-once CSC ingest bit for bit."""
    from lightgbm_tpu.io.sparse import SparseColumns

    rng = np.random.default_rng(23)
    n, f = 2500, 9
    dense = rng.normal(size=(n, f))
    dense[rng.random((n, f)) > 0.2] = 0.0
    colptr, indices, values = [0], [], []
    for j in range(f):
        rows = np.nonzero(dense[:, j])[0]
        indices.extend(rows.tolist())
        values.extend(dense[rows, j].tolist())
        colptr.append(len(indices))
    sp = SparseColumns(np.asarray(colptr, dtype=np.int64),
                       np.asarray(indices, dtype=np.int64),
                       np.asarray(values, dtype=np.float64), n, f)
    y = (dense[:, 0] > 0).astype(np.float64)
    cfg = {"max_bin": 31, "verbose": -1}
    td_csc = TrainingData.from_csc(sp, y, Config(dict(cfg)))
    td_str = TrainingData.from_streamed(
        sp, y, Config(dict(cfg, ooc_workers=workers)), chunk_rows=611)
    np.testing.assert_array_equal(td_str.binned, td_csc.binned)
    st = td_str._construct_stats
    assert st["source"] == "stream:sparse" and st["chunks"] == 5
